"""Standing-query control plane tests: registry lifecycle (admit/update/
retire, drain semantics), the Q-axis size-bucket padding contract
(padded-slot identity vs a fixed fleet, zero XLA recompiles on
churn-within-a-bucket), both admission surfaces (Kafka control topic and
POST /queries) including under ``--chaos``, the ``queries`` coordinated-
checkpoint component across a crash/resume that straddles an admission,
per-query Prometheus labels, and the live ``--kafka-follow`` acceptance
run with per-query window-table identity vs dedicated static runs."""

import glob
import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest
import yaml

from spatialflink_tpu.index import UniformGrid
from spatialflink_tpu.models import Point
from spatialflink_tpu.operators import (PointPointKNNQuery,
                                        PointPointRangeQuery,
                                        QueryConfiguration, QueryType)
from spatialflink_tpu.runtime.opserver import active_server
from spatialflink_tpu.runtime.queryplane import (ControlTopicConsumer,
                                                 QueryRegistry, QuerySpec,
                                                 QuerySpecError, QueryState,
                                                 bucket_size,
                                                 load_queries_file)
from spatialflink_tpu.streams import reset_memory_brokers, resolve_broker
from spatialflink_tpu.streams.formats import serialize_spatial
from spatialflink_tpu.utils import metrics as _metrics
from spatialflink_tpu.utils.metrics import scoped_registry
from spatialflink_tpu.utils.telemetry import (prometheus_text,
                                              telemetry_session)

pytestmark = pytest.mark.queryplane

CONF = "conf/spatialflink-conf.yml"
IN1, OUT = "points.geojson", "output"
GRID = UniformGrid(115.5, 117.6, 39.6, 41.1, num_grid_partitions=100)
CONTROL = json.dumps({"geometry": {"type": "control", "coordinates": []}})


@pytest.fixture(autouse=True)
def _fresh_brokers():
    reset_memory_brokers()
    yield
    reset_memory_brokers()


def _recs(n=3000, seed=0, dt_ms=20):
    rng = np.random.default_rng(seed)
    t0 = 1_700_000_000_000
    return [Point.create(float(115.5 + rng.random() * 2),
                         float(39.6 + rng.random() * 1.5), GRID,
                         obj_id=f"v{i % 13}", timestamp=int(t0 + i * dt_ms))
            for i in range(n)]


def _conf(**kw):
    kw.setdefault("window_size_ms", 10_000)
    kw.setdefault("slide_ms", 5_000)
    return QueryConfiguration(QueryType.WindowBased, **kw)


def _reg(points, family="range", radius=0.5, k=None):
    reg = QueryRegistry(family, radius=radius, k=k)
    for i, (x, y) in enumerate(points):
        reg.admit({"id": f"q{i}", "family": family, "x": x, "y": y})
    reg.apply()
    return reg


QPTS = [(116.5, 40.3), (116.0, 40.0), (117.0, 40.9)]


def _oid_table(results, qid):
    """{window_start: [obj ids]} for one query across dynamic results."""
    out = {}
    for w in results:
        ids = w.extras.get("query_ids", [])
        if qid in ids:
            out[w.window_start] = [r.obj_id
                                   for r in w.records[ids.index(qid)]]
    return out


class TestSpecValidation:
    def test_schema_errors_name_the_field(self):
        for bad, frag in [
            ({"x": 1, "y": 2}, "'id'"),
            ({"id": "", "x": 1, "y": 2}, "'id'"),
            ({"id": "a", "x": 1, "y": 2, "family": "join"}, "'family'"),
            ({"id": "a", "y": 2}, "'x' and 'y'"),
            ({"id": "a", "x": "wat", "y": 2}, "'x' and 'y'"),
            ({"id": "a", "x": 1, "y": 2, "route": "smoke:sig"}, "'route'"),
            ({"id": "a", "x": 1, "y": 2, "route": "file:"}, "'route'"),
            ({"id": "a", "x": 1, "y": 2, "slo": {"wat": 1}}, "'slo'"),
            ({"id": "a", "x": 1, "y": 2, "k": "many"}, "'k'"),
            ({"id": "a", "x": 1, "y": 2, "wobble": 3}, "wobble"),
            ("not-a-dict", "object"),
        ]:
            with pytest.raises(QuerySpecError, match=frag):
                QuerySpec.from_dict(bad, default_family="range")

    def test_fleet_shared_radius_and_k_enforced(self):
        reg = QueryRegistry("range", radius=0.5)
        with pytest.raises(QuerySpecError, match="radius"):
            reg.admit({"id": "a", "x": 1, "y": 2, "radius": 0.7})
        reg.admit({"id": "a", "x": 1, "y": 2, "radius": 0.5})  # restate ok
        regk = QueryRegistry("knn", radius=0.5, k=10)
        with pytest.raises(QuerySpecError, match="k="):
            regk.admit({"id": "b", "family": "knn", "x": 1, "y": 2, "k": 3})
        with pytest.raises(QuerySpecError, match="family"):
            regk.admit({"id": "c", "family": "range", "x": 1, "y": 2})

    def test_queries_file_names_the_offending_entry(self, tmp_path):
        p = tmp_path / "q.json"
        p.write_text(json.dumps({"queries": [
            {"id": "ok", "x": 1, "y": 2}, {"id": "bad", "x": 1}]}))
        with pytest.raises(QuerySpecError, match=r"query\[1\]"):
            load_queries_file(str(p), "range")
        p.write_text(json.dumps([{"id": "ok", "x": 1, "y": 2}]))
        assert [s.id for s in load_queries_file(str(p), "range")] == ["ok"]


class TestLifecycle:
    def test_admit_apply_update_retire_state_machine(self):
        with scoped_registry():
            reg = QueryRegistry("range", radius=0.5)
            e = reg.admit({"id": "a", "x": 1, "y": 2})
            assert e.state is QueryState.PENDING
            assert reg.fleet_version == 0 and not reg.active_entries()
            assert reg.apply() and reg.fleet_version == 1
            assert e.state is QueryState.ACTIVE
            assert [x.id for x in reg.active_entries()] == ["a"]
            # re-admit by id = staged update; lands at the next apply
            reg.admit({"id": "a", "x": 9, "y": 9, "route": "file:/tmp/x"})
            assert e.spec.x == 1 and e.pending_spec is not None
            assert reg.apply() and e.spec.x == 9 and reg.fleet_version == 2
            # retire: active -> draining (still serving) -> retired at apply
            reg.retire("a")
            assert e.state is QueryState.DRAINING and e.serving
            assert [x.id for x in reg.active_entries()] == ["a"]
            assert reg.apply() and e.state is QueryState.RETIRED
            assert not reg.active_entries() and reg.fleet_version == 3
            # idempotence/edges
            with pytest.raises(KeyError):
                reg.retire("a")
            with pytest.raises(KeyError):
                reg.update("nope", {})
            # a pending admission retires immediately, never joins
            p = reg.admit({"id": "b", "x": 1, "y": 2})
            reg.retire("b")
            assert p.state is QueryState.RETIRED
            assert not reg.apply() or "b" not in [
                x.id for x in reg.active_entries()]

    def test_no_change_no_version_bump(self):
        reg = _reg(QPTS[:2])
        v = reg.fleet_version
        assert not reg.apply()  # nothing staged
        assert reg.fleet_version == v

    def test_bucket_padding_contract(self):
        assert [bucket_size(n) for n in (0, 1, 2, 3, 4, 5, 9)] == \
            [1, 1, 2, 4, 4, 8, 16]
        reg = _reg(QPTS)  # 3 live
        entries, pts, valid = reg.padded_fleet(GRID)
        assert len(entries) == 3 and len(pts) == 4
        assert valid.tolist() == [True, True, True, False]
        # pad slots are shape filler copies of the last live point
        assert pts[3].x == pts[2].x and pts[3].y == pts[2].y

    def test_lifecycle_events_on_the_ring(self):
        with scoped_registry(), telemetry_session() as tel:
            reg = _reg(QPTS[:1])
            reg.retire("q0")
            reg.apply()
            kinds = [e["kind"] for e in tel.events.list()]
            for k in ("query-admitted", "query-active", "query-draining",
                      "query-retired"):
                assert k in kinds, kinds

    def test_status_payload_and_slo_verdict(self):
        with scoped_registry():
            reg = QueryRegistry("range", radius=0.5)
            reg.admit({"id": "a", "x": 1, "y": 2,
                       "slo": {"min_window_records": 2}})
            reg.apply()
            entry = reg.active_entries()[0]
            reg.note_window(entry, 5)
            assert entry.slo_ok is True and entry.slo_breaches == 0
            reg.note_window(entry, 1)  # breach
            reg.note_window(entry, 0)  # sustained: still ONE transition
            assert entry.slo_ok is False and entry.slo_breaches == 1
            reg.note_window(entry, 4)  # recovered
            assert entry.slo_ok is True
            st = reg.status()
            assert st["live"] == 1 and st["bucket"] == 1
            row = st["queries"][0]
            assert row["windows_emitted"] == 4 and row["records_out"] == 10
            assert row["slo"] == {"ok": True, "breaches": 1}


class TestDynamicIdentity:
    """The padding/demux contract: a dynamic fleet must be indistinguishable
    per query from the frozen-fleet run_multi path and from dedicated
    single-query runs."""

    def test_padded_fleet_matches_fixed_run_multi_range(self):
        recs = _recs()
        out = list(PointPointRangeQuery(_conf(), GRID).run_dynamic(
            iter(recs), _reg(QPTS), 0.5))  # 3 live in a bucket of 4
        qs = [Point.create(x, y, GRID) for x, y in QPTS]
        ref = list(PointPointRangeQuery(_conf(), GRID).run_multi(
            iter(recs), qs, 0.5))
        assert len(out) == len(ref) and out
        for a, b in zip(out, ref):
            assert (a.window_start, a.window_end) == \
                (b.window_start, b.window_end)
            assert a.extras["query_ids"] == ["q0", "q1", "q2"]
            assert [[r.obj_id for r in q] for q in a.records] == \
                [[r.obj_id for r in q] for q in b.records]

    def test_padded_fleet_matches_fixed_run_multi_knn(self):
        recs = _recs()
        reg = _reg(QPTS, family="knn", k=7)
        out = list(PointPointKNNQuery(_conf(k=7), GRID).run_dynamic(
            iter(recs), reg, 0.5, 7))
        qs = [Point.create(x, y, GRID) for x, y in QPTS]
        ref = list(PointPointKNNQuery(_conf(k=7), GRID).run_multi(
            iter(recs), qs, 0.5, 7))
        assert out and len(out) == len(ref)
        for a, b in zip(out, ref):
            assert a.records == b.records
            assert a.extras["k"] == 7 and a.extras["queries"] == 3

    def test_churn_within_bucket_never_recompiles(self):
        """Admissions/retirements that stay inside one power-of-two size
        bucket REPAD the fleet arrays; the jitted multi kernels must be
        cache hits — the ISSUE's zero-XLA-recompiles acceptance bar,
        asserted on the jit compile counters."""
        from spatialflink_tpu.ops.range import range_filter_point_multi_masks

        recs = _recs(4000)
        reg = _reg(QPTS)  # 3 live, bucket 4

        class Churn:
            def __iter__(self):
                for i, r in enumerate(recs):
                    if i == 1200:  # 3 -> 4 live: still bucket 4
                        reg.admit({"id": "late", "x": 116.8, "y": 40.6})
                    if i == 2400:  # retire one: 3 live, still bucket 4
                        reg.retire("q1")
                    yield r

        # warm the bucket's kernel shape, then churn inside it
        list(PointPointRangeQuery(_conf(), GRID).run_dynamic(
            iter(recs[:600]), _reg(QPTS), 0.5))
        before = range_filter_point_multi_masks._cache_size()
        out = list(PointPointRangeQuery(_conf(), GRID).run_dynamic(
            Churn(), reg, 0.5))
        assert range_filter_point_multi_masks._cache_size() == before, \
            "fleet churn within a size bucket recompiled the multi kernel"
        # the fleet actually changed mid-run
        fleets = [tuple(w.extras["query_ids"]) for w in out]
        assert ("q0", "q1", "q2") in fleets
        assert ("q0", "q2", "late") in fleets
        assert _metrics.REGISTRY.counter("fleet-repads").count >= 2

    def test_admitted_and_retired_match_dedicated_runs(self):
        recs = _recs(4000)
        reg = _reg(QPTS[:2])

        class Churn:
            def __iter__(self):
                for i, r in enumerate(recs):
                    if i == 1500:
                        reg.admit({"id": "late", "x": 116.8, "y": 40.6})
                    if i == 2600:
                        reg.retire("q0")
                    yield r

        out = list(PointPointRangeQuery(_conf(), GRID).run_dynamic(
            Churn(), reg, 0.5))
        # each query's windows match a dedicated single-query run of the
        # SAME case over the full stream, restricted to the windows the
        # query was live for
        for qid, (x, y) in [("q0", QPTS[0]), ("q1", QPTS[1]),
                            ("late", (116.8, 40.6))]:
            ded = {w.window_start: [r.obj_id for r in w.records]
                   for w in PointPointRangeQuery(_conf(), GRID).run(
                       iter(recs), Point.create(x, y, GRID), 0.5)}
            got = _oid_table(out, qid)
            assert got, qid
            for ws, ids in got.items():
                assert ids == ded[ws], (qid, ws)
        # q0 retired mid-run, late admitted mid-run
        assert len(_oid_table(out, "q0")) < len(out)
        assert 0 < len(_oid_table(out, "late")) < len(out)

    def test_empty_fleet_emits_empty_windows(self):
        recs = _recs(1200)
        reg = QueryRegistry("range", radius=0.5)
        out = list(PointPointRangeQuery(_conf(), GRID).run_dynamic(
            iter(recs), reg, 0.5))
        assert out and all(w.records == [] and w.extras["queries"] == 0
                           for w in out)

    def test_per_query_prometheus_labels(self):
        """Satellite: '<base>@<qid>' counters/histograms render as proper
        query=\"<id>\" labels, not flattened names."""
        with scoped_registry(), telemetry_session() as tel:
            reg = _reg(QPTS[:1])
            reg.note_window(reg.active_entries()[0], 3)
            text = prometheus_text(tel)
            assert ('spatialflink_counter{name="windows-emitted",'
                    'query="q0"} 1') in text
            assert ('spatialflink_histogram_count{name="window-records",'
                    'query="q0"} 1') in text
            assert "@" not in "".join(
                ln for ln in text.splitlines() if "{" in ln)
        # registry-only (no session) rendering takes the same path
        with scoped_registry() as r2:
            r2.counter("records-out@fleet-1").inc(5)
            text = prometheus_text(None, registry=r2)
            assert ('spatialflink_counter{name="records-out",'
                    'query="fleet-1"} 5') in text


class TestControlTopic:
    def test_consumer_applies_and_rejects(self):
        with scoped_registry() as reg_counters:
            broker = resolve_broker("memory://ctl-unit")
            reg = _reg(QPTS[:1])
            cons = ControlTopicConsumer(broker, "ctl", "g")
            reg.attach_control(cons)
            broker.produce("ctl", json.dumps(
                {"action": "admit",
                 "query": {"id": "n1", "x": 116.2, "y": 40.2}}))
            broker.produce("ctl", "not json {")
            broker.produce("ctl", json.dumps({"action": "wat"}))
            broker.produce("ctl", json.dumps(
                {"action": "retire", "id": "ghost"}))
            broker.produce("ctl", json.dumps(
                {"action": "update", "id": "q0",
                 "query": {"route": "kafka:routed"}}))
            broker.produce("ctl", json.dumps({"action": "retire",
                                              "id": "n1"}))
            assert reg.apply()
            ids = [e.id for e in reg.active_entries()]
            assert ids == ["q0"]  # n1 admitted then retired pre-apply
            assert reg.active_entries()[0].spec.route == "kafka:routed"
            assert reg_counters.counter(
                "control-records-rejected").count == 3
            # position committed; a second consumer resumes past history
            assert cons.position == 6
            assert ControlTopicConsumer(broker, "ctl", "g").position == 6

    def test_driver_control_admission_under_chaos(self, tmp_path):
        """Control-topic admissions under transport faults: the admitted
        query's routed window table is byte-identical to a fault-free
        dedicated single-query run."""
        from spatialflink_tpu.driver import main

        recs = _recs(1600, dt_ms=60)
        with open(CONF) as f:
            d = yaml.safe_load(f)
        d["kafkaBootStrapServers"] = "memory://qp-chaos"
        d["query"]["radius"] = 0.5
        d["window"].update(interval=10, step=5)
        cfg = tmp_path / "c.yml"
        cfg.write_text(yaml.safe_dump(d))
        route = tmp_path / "late.jsonl"
        qfile = tmp_path / "q.json"
        qfile.write_text(json.dumps(
            [{"id": f"q{i}", "x": x, "y": y}
             for i, (x, y) in enumerate(QPTS[:2])]))
        broker = resolve_broker("memory://qp-chaos")
        for r in recs:
            broker.produce(IN1, serialize_spatial(r, "GeoJSON"))
        broker.produce("ctl", json.dumps(
            {"action": "admit",
             "query": {"id": "late", "x": 116.8, "y": 40.6,
                       "route": f"file:{route}"}}))
        assert main(["--config", str(cfg), "--kafka", "--option", "1",
                     "--queries-file", str(qfile), "--control-topic", "ctl",
                     "--chaos", "seed=11,fetch_fail=0.3,duplicate=0.3,"
                                "reorder=0.5",
                     "--retry", "attempts=12,base_ms=1,max_ms=20"]) == 0
        got = {tuple(d["window"]): d["records"] for d in
               map(json.loads, route.read_text().splitlines())}
        assert got
        conf = QueryConfiguration(
            QueryType.WindowBased, 10_000, 5_000,
            allowed_lateness_ms=d["query"]["thresholds"][
                "outOfOrderTuples"] * 1000)
        ded = {}
        for w in PointPointRangeQuery(conf, GRID).run(
                iter(recs), Point.create(116.8, 40.6, GRID), 0.5):
            ded[(w.window_start, w.window_end)] = [
                serialize_spatial(r, "GeoJSON") for r in w.records]
        for win, docs in got.items():
            assert docs == ded[win], win


class TestPostAdmission:
    def test_post_admission_under_chaos(self, tmp_path):
        """POST /queries mid-run under --chaos: the admitted query serves
        from its admission window on and its table matches the dedicated
        run."""
        from spatialflink_tpu.driver import main

        recs = _recs(2400, dt_ms=60)
        with open(CONF) as f:
            d = yaml.safe_load(f)
        d["kafkaBootStrapServers"] = "memory://qp-post"
        d["query"]["radius"] = 0.5
        d["window"].update(interval=10, step=5)
        cfg = tmp_path / "c.yml"
        cfg.write_text(yaml.safe_dump(d))
        route = tmp_path / "posted.jsonl"
        qfile = tmp_path / "q.json"
        qfile.write_text(json.dumps([{"id": "q0", "x": QPTS[0][0],
                                      "y": QPTS[0][1]}]))
        broker = resolve_broker("memory://qp-post")
        for r in recs:
            broker.produce(IN1, serialize_spatial(r, "GeoJSON"))

        posted = {}

        def post_when_up():
            deadline = time.monotonic() + 20
            srv = None
            while time.monotonic() < deadline and srv is None:
                srv = active_server()
                if srv is None or srv.port is None:
                    srv = None
                    time.sleep(0.005)
            if srv is None:
                posted["error"] = "server never came up"
                return
            body = json.dumps({"id": "posted", "x": 116.8, "y": 40.6,
                               "route": f"file:{route}"}).encode()
            req = urllib.request.Request(srv.url + "/queries", data=body,
                                         method="POST")
            with urllib.request.urlopen(req, timeout=5) as resp:
                posted["code"] = resp.status
                posted["body"] = json.loads(resp.read())

        t = threading.Thread(target=post_when_up, daemon=True)
        t.start()
        assert main(["--config", str(cfg), "--kafka", "--option", "1",
                     "--queries-file", str(qfile), "--status-port", "0",
                     "--chaos", "seed=5,fetch_fail=0.2,latency=0.2,"
                                "latency_ms=4",
                     "--retry", "attempts=12,base_ms=1,max_ms=20"]) == 0
        t.join(timeout=10)
        assert posted.get("code") == 200, posted
        assert posted["body"]["query"]["state"] == "pending"
        got = {tuple(d["window"]): d["records"] for d in
               map(json.loads, route.read_text().splitlines())}
        assert got, "the POSTed query never produced a routed window"
        conf = QueryConfiguration(
            QueryType.WindowBased, 10_000, 5_000,
            allowed_lateness_ms=d["query"]["thresholds"][
                "outOfOrderTuples"] * 1000)
        ded = {}
        for w in PointPointRangeQuery(conf, GRID).run(
                iter(recs), Point.create(116.8, 40.6, GRID), 0.5):
            ded[(w.window_start, w.window_end)] = [
                serialize_spatial(r, "GeoJSON") for r in w.records]
        for win, docs in got.items():
            assert docs == ded[win], win


class TestCheckpointResume:
    def test_resume_straddles_an_admission_with_mid_drain(self, tmp_path,
                                                          monkeypatch):
        """Crash AFTER an admission and a retirement-in-progress were
        checkpointed: the manifest's ``queries`` component must carry the
        admitted query AND the mid-drain one; the resumed run restores the
        fleet (the drain completes at the first window) and the surviving
        queries' tables equal the uninterrupted run's."""
        import contextlib
        import io

        from spatialflink_tpu.driver import main
        from spatialflink_tpu.runtime import queryplane
        from spatialflink_tpu.runtime.state import CheckpointableState

        monkeypatch.setenv("SPATIALFLINK_DECODE_CHUNK", "256")
        recs = _recs(4000, dt_ms=30)
        inp = tmp_path / "in.geojson"
        inp.write_text("".join(serialize_spatial(r, "GeoJSON") + "\n"
                               for r in recs))
        with open(CONF) as f:
            d = yaml.safe_load(f)
        d["query"]["radius"] = 0.5
        d["window"].update(interval=10, step=5)
        cfg = tmp_path / "c.yml"
        cfg.write_text(yaml.safe_dump(d))
        qfile = tmp_path / "q.json"
        qfile.write_text(json.dumps(
            [{"id": f"q{i}", "x": x, "y": y}
             for i, (x, y) in enumerate(QPTS[:2])]))
        base = ["--config", str(cfg), "--input1", str(inp), "--option", "1",
                "--queries-file", str(qfile)]

        class Crash(Exception):
            pass

        def run(argv, hook=None, crash_at=None):
            """Drive main() with a per-emitted-window hook on the router;
            returns the emitted stdout rows (raises Crash mid-run when
            crash_at is hit — the checkpoint-straddle shape)."""
            out = io.StringIO()
            orig = queryplane.QueryRouter.route
            n = {"w": 0}

            def route(self, result):
                orig(self, result)
                n["w"] += 1
                if hook is not None:
                    hook(self.registry, n["w"])
                if crash_at is not None and n["w"] == crash_at:
                    raise Crash()
            queryplane.QueryRouter.route = route
            try:
                with contextlib.redirect_stdout(out):
                    if crash_at is not None:
                        with pytest.raises(Crash):
                            main(argv)
                    else:
                        assert main(argv) == 0
            finally:
                queryplane.QueryRouter.route = orig
            return [eval(ln) for ln in out.getvalue().splitlines()]

        def churn(reg, w):
            if w == 6:
                reg.admit({"id": "late", "x": 116.8, "y": 40.6})
            if w == 8:
                reg.retire("q1")

        # uninterrupted reference: same admission/retirement windows
        ref = run(base, hook=churn)

        # crash at window 9: the every-4 checkpoint at window 8 saw the
        # admission applied and (typically) q1 mid-drain
        ckpt = tmp_path / "ckpt"
        got1 = run(base + ["--checkpoint-dir", str(ckpt),
                           "--checkpoint-every", "4"],
                   hook=churn, crash_at=9)

        # the newest manifest carries the queries component: the admitted
        # query live, q1 possibly mid-drain (state depends on which barrier
        # last fired) — assert presence + states are legal fleet states
        newest = sorted(glob.glob(str(ckpt / "ckpt-*.npz")))[-1]
        comp = CheckpointableState.load(newest).meta["components"]["queries"]
        by_id = {e["spec"]["id"]: e["state"] for e in comp["entries"]}
        assert "late" in by_id and by_id["late"] in ("pending", "active")
        assert by_id.get("q1") in ("active", "draining", None)
        assert comp["fleet_version"] >= 1

        # resume completes the run; the resumed fleet finishes q1's drain
        def finish_retire(reg, w):
            # the crashed run staged q1's retirement at window 8; if the
            # restored manifest predates it, re-stage (idempotent surface:
            # at-least-once control delivery is the documented contract)
            if w == 1:
                try:
                    reg.retire("q1")
                except KeyError:
                    pass
        got = got1 + run(base + ["--checkpoint-dir", str(ckpt), "--resume"],
                         hook=finish_retire)

        def table(rows, qid):
            return {tuple(r["window"]):
                    r["per_query_counts"][r["query_ids"].index(qid)]
                    for r in rows if qid in r["query_ids"]}

        # windows may re-emit across the crash (journal suppresses dupes in
        # the driver's sinks; stdout capture sees each once per process) —
        # compare as maps
        for qid in ("q0", "late"):
            r, g = table(ref, qid), table(got, qid)
            assert set(r) <= set(g)
            assert all(g[w] == c for w, c in r.items()), qid
        # q1 drained in both runs: its live windows match while present
        r1, g1 = table(ref, "q1"), table(got, "q1")
        assert all(g1[w] == c for w, c in r1.items() if w in g1)
        assert len(g1) < len(table(got, "q0"))


class TestFollowAcceptance:
    """The ISSUE acceptance run: ``--kafka-follow --status-port 0`` with a
    query POSTed in and another DELETEd mid-run; per-query window tables
    identical to dedicated static runs; GET /queries shows the live
    ledger; per-query labels visible in /metrics."""

    def test_follow_admit_retire_mid_run(self, tmp_path):
        from spatialflink_tpu.driver import main

        with open(CONF) as f:
            d = yaml.safe_load(f)
        d["kafkaBootStrapServers"] = "memory://qp-follow"
        d["query"]["radius"] = 0.5
        d["query"]["thresholds"]["outOfOrderTuples"] = 0
        d["window"].update(interval=2, step=1)
        cfg = tmp_path / "c.yml"
        cfg.write_text(yaml.safe_dump(d))
        route_a = tmp_path / "qa.jsonl"
        route_p = tmp_path / "posted.jsonl"
        qfile = tmp_path / "q.json"
        qfile.write_text(json.dumps([
            {"id": "qa", "x": 116.5, "y": 40.5, "route": f"file:{route_a}"},
            {"id": "qb", "x": 116.0, "y": 40.0}]))
        broker = resolve_broker("memory://qp-follow")
        recs = []

        def produce():
            t0 = int(time.time() * 1000)
            for i in range(400):
                p = Point.create(116.4 + 0.002 * (i % 60), 40.5, GRID,
                                 obj_id=f"veh{i % 7}",
                                 timestamp=t0 + i * 40)
                recs.append(p)
                broker.produce(IN1, serialize_spatial(p, "GeoJSON"))
                time.sleep(0.004)
            broker.produce(IN1, CONTROL)

        ops = {}

        def drive_plane():
            deadline = time.monotonic() + 25
            srv = None
            while time.monotonic() < deadline and srv is None:
                srv = active_server()
                if srv is None or srv.port is None:
                    srv = None
                    time.sleep(0.005)
            if srv is None:
                ops["error"] = "no server"
                return

            def get(p):
                with urllib.request.urlopen(srv.url + p, timeout=3) as r:
                    return r.status, (json.loads(r.read())
                                      if "json" in r.headers.get(
                                          "Content-Type", "")
                                      else r.read().decode())
            # wait for some windows, then admit + retire mid-run
            while time.monotonic() < deadline:
                if _metrics.REGISTRY.counter("windows-emitted@qa").count >= 3:
                    break
                time.sleep(0.02)
            body = json.dumps({"id": "posted", "x": 116.45, "y": 40.5,
                               "route": f"file:{route_p}"}).encode()
            req = urllib.request.Request(srv.url + "/queries", data=body,
                                         method="POST")
            with urllib.request.urlopen(req, timeout=5) as r:
                ops["post"] = r.status
            req = urllib.request.Request(srv.url + "/queries/qb",
                                         method="DELETE")
            with urllib.request.urlopen(req, timeout=5) as r:
                ops["delete"] = r.status
            time.sleep(0.4)  # a few windows under the new fleet
            ops["queries"] = get("/queries")[1]
            ops["metrics"] = get("/metrics")[1]

        prod = threading.Thread(target=produce, daemon=True)
        plane = threading.Thread(target=drive_plane, daemon=True)
        with scoped_registry():
            prod.start()
            plane.start()
            rc = main(["--config", str(cfg), "--kafka", "--kafka-follow",
                       "--option", "1", "--status-port", "0",
                       "--queries-file", str(qfile), "--live-stats",
                       "--telemetry-interval", "0.3"])
            prod.join(timeout=30)
            plane.join(timeout=30)
        assert rc == 0
        assert "error" not in ops, ops
        assert ops["post"] == 200 and ops["delete"] == 200
        # the live ledger saw the whole lifecycle
        states = {q["id"]: q["state"] for q in ops["queries"]["queries"]}
        assert states.get("posted") in ("pending", "active")
        assert states.get("qb") in ("draining", "retired")
        assert states.get("qa") == "active"
        # per-query labels on the live /metrics
        assert 'query="qa"' in ops["metrics"]
        # identity: each routed query's windows == the dedicated run over
        # the records actually produced (same event times -> same windows)
        conf = QueryConfiguration(QueryType.WindowBased, 2_000, 1_000)
        for route, (x, y) in [(route_a, (116.5, 40.5)),
                              (route_p, (116.45, 40.5))]:
            got = {tuple(doc["window"]): doc["records"] for doc in
                   map(json.loads, route.read_text().splitlines())}
            assert got, route
            ded = {}
            for w in PointPointRangeQuery(conf, GRID).run(
                    iter(list(recs)), Point.create(x, y, GRID), 0.5):
                ded[(w.window_start, w.window_end)] = [
                    serialize_spatial(r, "GeoJSON") for r in w.records]
            for win, docs in got.items():
                assert docs == ded.get(win, []), (route, win)
