"""Kafka transport: in-memory broker shim + source/sink + delivery semantics.

The reference's entire I/O backbone is Kafka: consumers feed every pipeline
(``StreamingJob.java:473``), producers ship results with EXACTLY_ONCE
semantics (``StreamingJob.java:512``), per-type output schemas serialize each
geometry family (``spatialStreams/Serialization.java:17-774``), and latency
values go to their own topic (``utils/HelperClass.java:455-529``).

This environment has no broker and no Kafka client library, so the transport
is built against a minimal broker *interface* with two implementations:

- :class:`InMemoryBroker` — a faithful shim (topics, partitions-as-one-log,
  offsets, consumer groups, commit) used by tests and local replays.
- a real client adapter via :func:`connect_kafka`, gated on kafka-python
  being installed (it is not, in this image).

Delivery semantics re-design (SURVEY §7): Flink's EXACTLY_ONCE producer rides
checkpoint-coordinated transactions; without Flink's checkpoint machinery the
rebuild ships **at-least-once + idempotent writes**: the consumer commits
offsets only AFTER results are produced (re-delivery on crash), and
:class:`IdempotentWindowSink` keys every result by (window_start, window_end,
key) so re-delivered duplicates overwrite instead of double-count — the
effective semantics match exactly-once for windowed results.
"""

from __future__ import annotations

import json
import threading
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

import numpy as np

from spatialflink_tpu.streams.formats import serialize_spatial
from spatialflink_tpu.utils import telemetry as _telemetry


@dataclass(**({"slots": True} if __import__("sys").version_info >= (3, 10) else {}))
class BrokerRecord:
    """One record in a topic log."""

    offset: int
    key: Optional[str]
    value: Any
    timestamp_ms: int = 0


class InMemoryBroker:
    """Topics as append-only logs with consumer-group offset tracking.

    Threadsafe so a producer thread can feed a consuming pipeline, mirroring
    the reference's Kafka-decoupled source/sink topology.
    """

    def __init__(self):
        self._topics: Dict[str, List[BrokerRecord]] = {}
        self._commits: Dict[Tuple[str, str], int] = {}  # (group, topic) -> next offset
        self._lock = threading.Lock()

    # ------------------------------ producer ------------------------- #

    def produce(self, topic: str, value, key: Optional[str] = None,
                timestamp_ms: Optional[int] = None) -> int:
        """Append; returns the record's offset."""
        with self._lock:
            log = self._topics.setdefault(topic, [])
            rec = BrokerRecord(
                offset=len(log), key=key, value=value,
                timestamp_ms=timestamp_ms if timestamp_ms is not None
                else int(time.time() * 1000))
            log.append(rec)
            return rec.offset

    def produce_many(self, topic: str, values, key: Optional[str] = None
                     ) -> int:
        """Batched :meth:`produce` under ONE lock/timestamp — the window
        sink's per-record production amortized (only the plain in-memory
        broker offers this; fault-injecting/supervised wrappers keep the
        per-record path so chaos semantics cover every record). Returns the
        first offset."""
        with self._lock:
            log = self._topics.setdefault(topic, [])
            base = len(log)
            now = int(time.time() * 1000)
            log.extend(BrokerRecord(offset=base + i, key=key, value=v,
                                    timestamp_ms=now)
                       for i, v in enumerate(values))
            return base

    # ------------------------------ consumer ------------------------- #

    def fetch(self, topic: str, offset: int, max_records: int = 500
              ) -> List[BrokerRecord]:
        """Records from ``offset`` onward. Consumers track their own
        position (like a real Kafka consumer); the committed offset only
        decides where a RESTARTED group member resumes."""
        with self._lock:
            log = self._topics.get(topic, [])
            return log[offset:offset + max_records]

    def commit(self, topic: str, group: str, next_offset: int) -> None:
        with self._lock:
            cur = self._commits.get((group, topic), 0)
            self._commits[(group, topic)] = max(cur, next_offset)

    def committed(self, topic: str, group: str) -> int:
        with self._lock:
            return self._commits.get((group, topic), 0)

    def end_offset(self, topic: str) -> int:
        with self._lock:
            return len(self._topics.get(topic, []))

    def topic_values(self, topic: str) -> List[Any]:
        with self._lock:
            return [r.value for r in self._topics.get(topic, [])]


def resequence_batch(batch: List[BrokerRecord], next_offset: int
                     ) -> List[BrokerRecord]:
    """Restore single-log order over a degraded transport: sort a fetched
    batch by offset and drop records already delivered (offset below
    ``next_offset``) or re-delivered within the batch. What a real
    consumer's fetch-session dedup does; a no-op on clean transports.
    Shared by :class:`KafkaSource` and the driver's ``--bulk`` topic drain
    — both assume offset-ordered, exactly-once-per-position hand-off."""
    # fast path: a clean transport delivers the batch already contiguous
    # from next_offset — one scan, no sort, no copy (the common case on
    # every poll of an undegraded broker)
    if batch and batch[0].offset == next_offset:
        expected = next_offset
        for rec in batch:
            if rec.offset != expected:
                break
            expected += 1
        else:
            return batch
    cleaned: List[BrokerRecord] = []
    last = next_offset - 1
    for rec in sorted(batch, key=lambda r: r.offset):
        if rec.offset > last:
            cleaned.append(rec)
            last = rec.offset
    return cleaned


#: yielded by a KafkaSource constructed with ``starvation_sentinel=True``
#: whenever a live-mode poll comes up empty — a batching consumer (the
#: commit tap's chunked decode) flushes on it so buffered records never
#: wait out a quiet topic; it is NOT a record and never commits offsets
STARVED = object()


class KafkaSource:
    """Consumer-group iterator over a topic (reference:
    ``FlinkKafkaConsumer`` at ``StreamingJob.java:473``).

    Yields record values; offsets commit every ``commit_every`` records
    *after* the records were handed downstream, so a crash between hand-off
    and commit re-delivers (at-least-once — pair with
    :class:`IdempotentWindowSink` downstream).

    With ``auto_commit=False`` the source never commits on its own: the
    caller owns commit placement via :meth:`commit_to` and the live
    ``position`` attribute (next offset to read). The driver's Kafka mode
    uses this to align commits with WINDOW emission instead of record
    hand-off — a record handed to a window assembler is not yet reflected
    in any produced result (see :class:`WindowCommitTap`).
    """

    def __init__(self, broker: InMemoryBroker, topic: str, group: str,
                 poll_batch: int = 500, commit_every: int = 1,
                 stop_at_end: bool = True, auto_commit: bool = True,
                 limit: Optional[int] = None,
                 starvation_sentinel: bool = False,
                 commit_lag: Optional[int] = None):
        self.broker = broker
        self.topic = topic
        self.group = group
        self.poll_batch = poll_batch
        self.commit_every = max(1, commit_every)
        self.stop_at_end = stop_at_end
        self.auto_commit = auto_commit
        #: when set (and auto_commit is off), commit ``position - lag``
        #: after every consumed poll batch — progress-driven commits from
        #: the CONSUMPTION side, so an unbounded sparse-match stream (a
        #: --kafka-follow run whose micro-batches rarely emit) still bounds
        #: restart reprocessing. The lag must cover every record that can
        #: be in flight (batcher + device pipeline); the driver computes it
        #: as (pipeline_depth + 1) * realtime_batch_size.
        self.commit_lag = commit_lag
        #: live mode only: yield :data:`STARVED` before sleeping on an empty
        #: poll (opt-in — only consumers that understand the marker set it)
        self.starvation_sentinel = starvation_sentinel
        #: max records to hand out per iteration (None = unbounded) — the
        #: driver's --limit for broker-fed runs; counts THIS run's records,
        #: from the group's resume point
        self.limit = limit
        #: next offset to read; live while iterating (restart resume point)
        self.position = broker.committed(topic, group)

    def commit_to(self, next_offset: int) -> None:
        """Commit the group's resume point (monotone in the broker)."""
        self.broker.commit(self.topic, self.group, next_offset)

    def iter_batches(self) -> Iterator:
        """Batched consumption for chunk-aware consumers (the commit tap's
        native decode): yields ``(values, next_positions)`` lists per poll —
        one Python-level iteration per POLL instead of per record, same
        resequencing/limit/lagged-commit semantics as :meth:`__iter__` (and
        :data:`STARVED` on empty live polls when the sentinel is on).
        Requires ``auto_commit=False`` (the tap owns commit placement);
        control tuples are NOT checked here — the consumer scans the batch
        (the tap does)."""
        if self.auto_commit:
            raise ValueError("iter_batches requires auto_commit=False "
                             "(the consumer owns commit placement)")
        pos = self.position = self.broker.committed(self.topic, self.group)
        yielded = 0
        tel = _telemetry.active()
        while True:
            if self.limit is not None and yielded >= self.limit:
                return
            if tel is not None:
                with tel.span("fetch", query="kafka"):
                    batch = self.broker.fetch(self.topic, pos,
                                              self.poll_batch)
            else:
                batch = self.broker.fetch(self.topic, pos, self.poll_batch)
            if not batch:
                if self.stop_at_end:
                    return
                if self.starvation_sentinel:
                    yield STARVED
                time.sleep(0.01)
                continue
            cleaned = resequence_batch(batch, pos)
            if not cleaned:
                continue  # all duplicates of already-delivered records
            if self.limit is not None:
                cleaned = cleaned[:self.limit - yielded]
            vals = [r.value for r in cleaned]
            poss = [r.offset + 1 for r in cleaned]
            pos = self.position = poss[-1]
            yielded += len(vals)
            if self.commit_lag is not None:
                self.broker.commit(self.topic, self.group,
                                   max(0, pos - self.commit_lag))
            yield vals, poss

    def __iter__(self) -> Iterator[Any]:
        # position starts at the group's committed offset (restart resume)
        # and advances in-memory as records are read, like a real consumer
        pos = self.position = self.broker.committed(self.topic, self.group)
        uncommitted = 0
        yielded = 0
        # telemetry is per-poll (never per record): one span around each
        # fetch when a session is active, the bare call otherwise
        tel = _telemetry.active()
        while True:
            if self.limit is not None and yielded >= self.limit:
                break
            if tel is not None:
                with tel.span("fetch", query="kafka"):
                    batch = self.broker.fetch(self.topic, pos,
                                              self.poll_batch)
            else:
                batch = self.broker.fetch(self.topic, pos, self.poll_batch)
            if not batch:
                if self.stop_at_end:
                    break
                if self.starvation_sentinel:
                    yield STARVED
                time.sleep(0.01)
                continue
            # a degraded transport (retried fetch sessions — see
            # runtime/faults.py) may deliver a batch permuted or with
            # records re-delivered, including from before ``pos``; the
            # window-aligned commit tap's prefix bookkeeping is unsound
            # under reordered positions, so disorder stops here
            cleaned = resequence_batch(batch, pos)
            if not cleaned:
                continue  # all duplicates of already-delivered records
            if self.limit is not None:
                cleaned = cleaned[:self.limit - yielded]
            for rec in cleaned:
                # position advances BEFORE the hand-off so a tap reading it
                # right after receiving the record sees "offset past me"
                pos = self.position = rec.offset + 1
                yield rec.value
                yielded += 1
                uncommitted += 1
                if self.auto_commit and uncommitted >= self.commit_every:
                    self.broker.commit(self.topic, self.group, pos)
                    uncommitted = 0
            if self.commit_lag is not None and not self.auto_commit:
                # consumption-driven lagged commit, once per poll batch: a
                # stream that consumes without emitting (sparse realtime
                # matches) still advances the group offset (commit is
                # monotone, so the emit-time lagged commit composes)
                self.broker.commit(self.topic, self.group,
                                   max(0, pos - self.commit_lag))
        if self.auto_commit and uncommitted:
            self.broker.commit(self.topic, self.group, pos)


class KafkaSink:
    """Producer shipping spatial objects/results with a per-type output
    schema (reference: ``Serialization.java``'s ``*OutputSchema`` classes —
    here one serializer covers every geometry family × format via
    ``serialize_spatial``)."""

    def __init__(self, broker: InMemoryBroker, topic: str,
                 fmt: Optional[str] = None,
                 date_format: Optional[str] = None,
                 delimiter: str = ","):
        self.broker = broker
        self.topic = topic
        self.fmt = fmt
        self.date_format = date_format
        self.delimiter = delimiter

    def _encode(self, record):
        if self.fmt and hasattr(record, "obj_id"):
            return serialize_spatial(record, self.fmt,
                                     delimiter=self.delimiter,
                                     date_format=self.date_format)
        return record

    def emit(self, record) -> None:
        key = getattr(record, "obj_id", None)
        self.broker.produce(self.topic, self._encode(record), key=key)

    def close(self) -> None:
        pass


def _values_equal(a, b) -> bool:
    """Structural equality that tolerates ndarray-valued extras (a
    tAggregate heatmap WindowResult would make plain ``==`` raise
    "truth value of an array is ambiguous")."""
    import dataclasses

    import numpy as _np

    if isinstance(a, _np.ndarray) or isinstance(b, _np.ndarray):
        return _np.array_equal(a, b)
    if dataclasses.is_dataclass(a) and not isinstance(a, type):
        if type(a) is not type(b):
            return False
        return all(_values_equal(getattr(a, f.name), getattr(b, f.name))
                   for f in dataclasses.fields(a))
    if isinstance(a, dict) and isinstance(b, dict):
        return a.keys() == b.keys() and all(
            _values_equal(v, b[k]) for k, v in a.items())
    if isinstance(a, (list, tuple)) and isinstance(b, (list, tuple)):
        return len(a) == len(b) and all(
            _values_equal(x, y) for x, y in zip(a, b))
    try:
        return bool(a == b)
    except Exception:
        return False


class IdempotentWindowSink:
    """At-least-once → effective exactly-once for windowed results.

    Results are keyed by (window_start, window_end, key); re-deliveries of a
    key are dropped entirely — first delivery wins in BOTH the snapshot
    table and the inner sink, so the two exposed outputs can never disagree.
    A re-delivery whose value differs from the recorded one (a recomputed
    window producing a different result — a determinism bug upstream, not
    normal retry noise) is counted separately in
    ``duplicates_value_differing`` so divergence is observable.
    ``key_fn`` extracts the idempotency key from a result (default: the
    window bounds plus a ``cell`` extra when present — SURVEY §7's
    "(window, cell)" plan).
    """

    def __init__(self, inner_sink=None,
                 key_fn: Optional[Callable[[Any], Tuple]] = None):
        self.inner = inner_sink
        self.key_fn = key_fn or self._default_key
        self._delivered: Dict[Tuple, Any] = {}
        self.duplicates_suppressed = 0
        self.duplicates_value_differing = 0

    @staticmethod
    def _default_key(result) -> Tuple:
        ws = getattr(result, "window_start", None)
        we = getattr(result, "window_end", None)
        cell = getattr(result, "extras", {}).get("cell") \
            if hasattr(result, "extras") else None
        return (ws, we, cell)

    def emit(self, result) -> None:
        key = self.key_fn(result)
        if key not in self._delivered:
            self._delivered[key] = result
            if self.inner is not None:
                self.inner.emit(result)
        else:
            self.duplicates_suppressed += 1
            if not _values_equal(self._delivered[key], result):
                self.duplicates_value_differing += 1

    @property
    def delivered_count(self) -> int:
        """Distinct (window, key) results delivered so far."""
        return len(self._delivered)

    def snapshot(self) -> Dict[Tuple, Any]:
        return dict(self._delivered)

    def close(self) -> None:
        if self.inner is not None:
            self.inner.close()


class WindowCommitTap:
    """Window-aligned offset commits for a :class:`KafkaSource` feeding an
    event-time windowed pipeline (the driver's ``--kafka`` mode).

    Sits between the source and the operator: parses each raw record,
    appends ``(source position after it, last-window-end)`` in arrival
    order, and hands the parsed object downstream. A record with event time
    ``ts`` is fully reflected in produced output once the window ending at
    ``lwe = ts - ts % slide + size`` has been EMITTED (windows fire in
    end order, and every window containing the record ends at or before
    ``lwe``). So on each emitted window ``[s, e)`` the longest PREFIX of
    pending records with ``lwe <= e`` commits — prefix-only, so an
    early-arriving record destined for a later window conservatively blocks
    commits behind it. Crash ⇒ re-delivery of exactly the records some
    unfired window still needed (at-least-once, never missing); the
    downstream :class:`KafkaWindowSink` suppresses the re-emitted windows.

    Control tuples are checked BEFORE parse (they are raw sentinel records,
    ``HelperClass.checkExitControlTuple``), so the remote-stop hook fires
    here rather than crashing the parser.

    ``bulk_decode`` (optional) batches the per-record parse through the
    native ingest: raw string records accumulate into chunks and decode in
    ONE native call (the bulk replay path's parser, applied to broker
    records) — per-record positions are snapshotted at pull time, so the
    window-aligned commit bookkeeping is identical. In live mode the source
    must be constructed with ``starvation_sentinel=True``: the tap flushes
    its buffer on every :data:`STARVED` marker, bounding the added latency
    to one poll cycle instead of one chunk fill.
    """

    def __init__(self, source: KafkaSource, size_ms: int, slide_ms: int,
                 parse: Optional[Callable[[Any], Any]] = None,
                 bulk_decode: Optional[Callable[[List[str]], List[Any]]]
                 = None, bulk_chunk: int = 2048,
                 dlq=None, checkpointer=None):
        from collections import deque

        if bulk_decode is not None and parse is None:
            # the fallback branches (embedded newline, count mismatch)
            # reparse the chunk per record — without a parser they would
            # crash exactly when resilience is needed
            raise ValueError("bulk_decode requires a per-record parse "
                             "fallback")
        self.source = source
        self.size_ms = int(size_ms)
        self.slide_ms = max(1, int(slide_ms))
        self.parse = parse
        self.bulk_decode = bulk_decode
        #: int or a zero-arg size callback (the chunk governor's actuator)
        #: — read through the :attr:`bulk_chunk` property, which resolves
        #: a callback per take so a live resize lands between chunks
        self._bulk_chunk = bulk_chunk
        #: the chunked decoder's obj-id space (set by the driver when the
        #: decoder interns); downstream ChunkedStream consumers read it
        self.interner = getattr(bulk_decode, "interner", None)
        #: optional runtime.checkpoint.CheckpointCoordinator: the tap
        #: reports per-record source positions AT HAND-OFF time (not pull
        #: time — the chunked decode buffers raws past the source's read
        #: head, and a checkpoint must never record a position covering
        #: records still sitting in that buffer)
        self.checkpointer = checkpointer
        self._ckpt_key = f"kafka:{source.topic}"
        #: optional runtime.supervisor.DeadLetterQueue: parse failures are
        #: retried against FRESH fetches of the same offset (transport
        #: corruption heals on redelivery) and quarantined — with failure
        #: metadata, before any commit can pass them — when they persist.
        #: Without a DLQ a parse failure propagates, as it always did.
        self.dlq = dlq
        self._pending = deque()
        # telemetry gauges (watermark lag = wall clock minus newest event
        # time; commit backlog = records awaiting a covering window), set
        # per tracked record — cheap float stores, and only when a session
        # was active when the driver wired the tap
        tel = _telemetry.active()
        self._tel = tel
        self._lag_gauge = (tel.gauge("kafka.watermark-lag-ms")
                           if tel is not None else None)
        self._backlog_gauge = (tel.gauge("kafka.commit-backlog")
                               if tel is not None else None)

    @property
    def bulk_chunk(self) -> int:
        """The decode-chunk size RIGHT NOW (every read site resolves the
        governor callback afresh, so a resize applies at the next take)."""
        c = self._bulk_chunk
        return max(1, int(c() if callable(c) else c))

    def _parse_or_dlq(self, raw, position: int):
        """Parse one record; on failure, redeliver-and-retry, then
        quarantine to the DLQ and return None (caller skips the record).
        A quarantined record does not enter the commit bookkeeping: its
        dead-letter entry IS its reflection in produced output, so commits
        may pass it."""
        if self.parse is None:
            return raw
        try:
            return self.parse(raw)
        except Exception as e:
            if self.dlq is None:
                raise
            from spatialflink_tpu.utils.metrics import (
                REGISTRY, check_exit_control_tuple)

            offset = position - 1
            attempts = 1
            last: BaseException = e
            for _ in range(self.dlq.redelivery_limit):
                try:
                    fresh = self.source.broker.fetch(
                        self.source.topic, offset, 1)
                except Exception as fe:  # transport down past retry budget
                    last = fe
                    break
                rec = next((r for r in fresh if r.offset == offset), None)
                if rec is None:
                    break
                attempts += 1
                # a STOP tuple torn in transport parses as garbage; its
                # healed redelivery must honor the remote-stop contract,
                # not be quarantined as poison (ControlTupleExit
                # propagates — it is a control-flow signal, not a parse
                # failure)
                check_exit_control_tuple(rec.value)
                try:
                    obj = self.parse(rec.value)
                except Exception as e2:
                    last = e2
                    continue
                REGISTRY.counter("dlq-redelivery-healed").inc()
                _telemetry.emit_event("dlq-redelivery-healed",
                                      topic=self.source.topic, offset=offset,
                                      attempts=attempts)
                return obj
            self.dlq.quarantine(source_topic=self.source.topic,
                                offset=offset, raw=raw, error=last,
                                attempts=attempts)
            return None

    def _track(self, obj, position: int):
        if self.checkpointer is not None:
            self.checkpointer.note_position(self._ckpt_key, position)
        ts = getattr(obj, "timestamp", None)
        if isinstance(ts, (int, float)):
            lwe = int(ts) - int(ts) % self.slide_ms + self.size_ms
            if self._lag_gauge is not None:
                self._lag_gauge.set(time.time() * 1000 - ts)
        else:
            # unknown event time: block commits behind it until the
            # end-of-stream commit_all (conservative, never unsafe)
            lwe = float("inf")
        self._pending.append((position, lwe))
        if self._backlog_gauge is not None:
            self._backlog_gauge.set(len(self._pending))
        return obj

    def _track_chunk(self, chunk):
        """Vectorized :meth:`_track` for one columnar chunk: commit
        bookkeeping per record (the prefix-commit sweep needs per-record
        positions), checkpoint position + gauges once per chunk."""
        if self.checkpointer is not None:
            self.checkpointer.note_position(
                self._ckpt_key, int(chunk.positions[-1]))
            coord, key = self.checkpointer, self._ckpt_key
            # per-record re-note hook for flatten consumers (see
            # PointChunk.note); chunk-aware assemblers never need it
            chunk.note = lambda p: coord.note_position(key, p)
        ts = np.asarray(chunk.parsed.ts, np.int64)
        lwe = ts - ts % self.slide_ms + self.size_ms
        self._pending.extend(zip(chunk.positions.tolist(), lwe.tolist()))
        if self._lag_gauge is not None:
            self._lag_gauge.set(time.time() * 1000 - int(ts[-1]))
        if self._backlog_gauge is not None:
            self._backlog_gauge.set(len(self._pending))
        return chunk

    def chunks(self) -> Iterator[Any]:
        """Chunked hand-off for the batched decode path
        (``driver.decode_chunks``): yields columnar
        :class:`~spatialflink_tpu.streams.bulk.PointChunk` chunks (native
        decode, per-record positions snapshotted for the commit sweep) or
        plain record lists (per-record fallback / record-mode parse), one
        chunk per flush — at most one poll cycle of buffering in live mode
        (the starvation sentinel flushes)."""
        if self.bulk_decode is not None:
            yield from self._bulk_chunks()
            return
        yield from self._record_chunks()

    def __iter__(self) -> Iterator[Any]:
        from spatialflink_tpu.utils.metrics import check_exit_control_tuple

        if self.bulk_decode is not None:
            # flatten the chunked decode (same buffering the chunked
            # per-record hand-off always had); per-record position re-note
            # keeps checkpoint barriers sound while records dribble out
            for ch in self._bulk_chunks():
                if hasattr(ch, "records"):
                    recs = ch.records()
                    if ch.note is not None and ch.positions is not None:
                        for rec, p in zip(recs, ch.positions.tolist()):
                            ch.note(int(p))
                            yield rec
                    else:
                        yield from recs
                else:
                    yield from ch
            return
        for raw in self.source:
            if raw is STARVED:  # only batching consumers need the marker
                continue
            check_exit_control_tuple(raw)
            obj = self._parse_or_dlq(raw, self.source.position)
            if obj is None:  # quarantined poison record
                continue
            yield self._track(obj, self.source.position)

    def _record_chunks(self) -> Iterator[Any]:
        """Record-mode chunk hand-off (no native decoder — e.g. geometry
        streams): the per-record parse is unchanged, but records batch into
        chunks so downstream bookkeeping amortizes; STARVED flushes."""
        from spatialflink_tpu.utils.metrics import (ControlTupleExit,
                                                    check_exit_control_tuple)

        buf: List = []
        tel = self._tel
        for raw in self.source:
            if raw is STARVED:
                if buf:
                    yield buf
                    buf = []
                continue
            try:
                check_exit_control_tuple(raw)
            except ControlTupleExit:
                if buf:
                    yield buf
                raise
            t0 = time.perf_counter() if tel is not None else 0.0
            obj = self._parse_or_dlq(raw, self.source.position)
            if tel is not None:
                tel.observe("ingest", time.perf_counter() - t0)
            if obj is None:
                continue
            buf.append(self._track(obj, self.source.position))
            if len(buf) >= self.bulk_chunk:
                yield buf
                buf = []
        if buf:
            yield buf

    def _bulk_chunks(self) -> Iterator[Any]:
        from spatialflink_tpu.utils.metrics import (ControlTupleExit,
                                                    check_exit_control_tuple)

        raws: List[str] = []
        poss: List[int] = []

        def flush():
            if not raws:
                return
            t0 = time.perf_counter() if self._tel is not None else 0.0
            # a record with an embedded newline would shift the native
            # parser's line<->record mapping; so would any count mismatch;
            # and a record the POINT bulk parser rejects outright (e.g. a
            # polygon feature in a point topic) raises ValueError — all
            # three fall back to the exact per-record parse, which handles
            # them the way the streaming path always did (never silently
            # drop, mis-attribute, or crash on a record)
            chunk = None
            if not any("\n" in r for r in raws):
                try:
                    chunk = self.bulk_decode(raws)
                except ValueError:
                    chunk = None
                if chunk is not None and len(chunk) != len(raws):
                    chunk = None
            stop = None
            if chunk is None:
                # a torn STOP tuple healing mid-fallback raises
                # ControlTupleExit; records parsed BEFORE it in the chunk
                # must still reach the pipeline (same contract as the
                # intact-control path below), so defer the stop until the
                # parsed prefix has been yielded
                objs = []
                for r, p in zip(raws, poss):
                    try:
                        obj = self._parse_or_dlq(r, p)
                    except ControlTupleExit as e:
                        stop = e
                        break
                    if obj is not None:  # None = quarantined poison record
                        objs.append(self._track(obj, p))
                out = objs if objs else None
            elif hasattr(chunk, "parsed"):
                # columnar chunk: attach the per-record source positions the
                # pull loop snapshotted and track in one vectorized pass
                if chunk.positions is None:
                    chunk.positions = np.asarray(poss, np.int64)
                out = self._track_chunk(chunk)
            else:
                # legacy decoder contract: a plain list of parsed records
                out = [self._track(obj, p)
                       for obj, p in zip(chunk, poss) if obj is not None]
            raws.clear()
            poss.clear()
            if self._tel is not None:
                # ONE ingest observe per decoded chunk — the parse cost
                # amortized per batch (the scalar tap observed per record)
                self._tel.observe("ingest", time.perf_counter() - t0)
            if out is not None and len(out):
                yield out
            if stop is not None:
                raise stop

        # one Python-level iteration per POLL: the source hands whole
        # resequenced batches with per-record positions; only batches that
        # carry a control marker or non-string records drop to the
        # per-record slow path
        for item in self.source.iter_batches():
            if item is STARVED:
                # quiet topic: hand everything buffered downstream so a
                # chunk never waits out dead air (live-mode latency bound =
                # one poll cycle, not one chunk fill)
                yield from flush()
                continue
            vals, positions = item
            fast = True
            for v in vals:
                if not isinstance(v, str) or '"control"' in v:
                    fast = False
                    break
            if fast:
                # append in chunk-sized slices so the decode-chunk bound
                # holds even when a poll batch exceeds it
                i = 0
                while i < len(vals):
                    take = max(self.bulk_chunk - len(raws), 1)
                    raws.extend(vals[i:i + take])
                    poss.extend(positions[i:i + take])
                    i += take
                    if len(raws) >= self.bulk_chunk:
                        yield from flush()
            else:
                for raw, position in zip(vals, positions):
                    if isinstance(raw, str) and '"control"' not in raw:
                        raws.append(raw)
                        poss.append(position)
                        continue
                    # control candidate or pre-parsed object: flush the
                    # buffered prefix FIRST (arrival order — the commit
                    # sweep's pending deque must stay position-sorted;
                    # records before a stop tuple must reach the pipeline)
                    yield from flush()
                    check_exit_control_tuple(raw)
                    if isinstance(raw, str):
                        # had the marker substring but is not an actual
                        # control tuple — a normal record
                        raws.append(raw)
                        poss.append(position)
                        continue
                    obj = self._parse_or_dlq(raw, position)
                    if obj is not None:
                        yield [self._track(obj, position)]
            if len(raws) >= self.bulk_chunk:
                yield from flush()
        yield from flush()

    def on_window_emitted(self, window_end: int) -> None:
        """Commit the prefix of records fully covered by windows ending at
        or before ``window_end`` (call AFTER the result was produced)."""
        pos = None
        while self._pending and self._pending[0][1] <= window_end:
            pos = self._pending.popleft()[0]
        if pos is not None:
            self.source.commit_to(pos)

    def commit_all(self) -> None:
        """Bounded stream fully drained and flushed: everything consumed is
        reflected in output; commit the source's full position."""
        self._pending.clear()
        self.source.commit_to(self.source.position)


def _jsonable(v):
    """Best-effort JSON projection for WindowResult extras (heatmap ndarrays,
    numpy scalars, query objects): arrays → nested lists, unknowns → str."""
    import numpy as _np

    if isinstance(v, _np.ndarray) or hasattr(v, "__array__"):
        return _np.asarray(v).tolist()
    if isinstance(v, _np.generic):
        return v.item()
    if isinstance(v, (set, frozenset)):
        return sorted(str(x) for x in v)
    if isinstance(v, (list, tuple)):
        return [_jsonable(x) for x in v]
    if isinstance(v, dict):
        return {str(k): _jsonable(x) for k, x in v.items()}
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    return str(v)


class KafkaWindowSink:
    """Windowed results → output topic with effective exactly-once ACROSS
    process restarts: the output log itself is the recovery state.

    Every record of a window is produced keyed by the window's idempotency
    key ``"start:end:cell"``, followed by ONE commit-marker record
    (key ``__window_commit__:<key>``, value = record count). At startup the
    sink replays the topic's existing MARKER keys to seed its delivered-set,
    so windows re-delivered by the at-least-once source after a crash are
    suppressed even in a fresh process — the in-memory
    :class:`IdempotentWindowSink` upgraded with log-based recovery
    (reference: Flink's checkpoint-coordinated EXACTLY_ONCE producer,
    ``StreamingJob.java:512``). A window interrupted mid-production leaves
    records without a marker and is re-produced in full on restart:
    record-level duplicates are possible for exactly that window, but
    marker-delimited window reads never see a duplicate or partial window.
    """

    MARKER = "__window_commit__:"

    def __init__(self, broker, topic: str, fmt: Optional[str] = None,
                 date_format: Optional[str] = None, delimiter: str = ",",
                 job_id: Optional[str] = None,
                 seed_scan_limit: Optional[int] = None,
                 seed_scan_warn: int = 100_000):
        self.broker = broker
        self.topic = topic
        #: job/query fingerprint folded into every window key: without it,
        #: re-running a DIFFERENT query/config against the same output
        #: topic would find the old run's markers and silently suppress
        #: every window of the new run (an output topic is otherwise bound
        #: to one job configuration forever). None keeps the legacy
        #: un-prefixed keys for single-job topics.
        self.job_id = job_id
        #: bound/flag the startup scan (see _seed_from_log): scan at most
        #: the last ``seed_scan_limit`` records (None = full scan), and
        #: warn once past ``seed_scan_warn`` scanned records — the
        #: uncompacted-topic signal.
        self.seed_scan_limit = seed_scan_limit
        self.seed_scan_warn = seed_scan_warn
        self._enc = KafkaSink(broker, topic, fmt, date_format, delimiter)
        self._tel = _telemetry.active()
        self.delivered = self._seed_from_log()
        self.duplicates_suppressed = 0
        self.windows_produced = 0

    def _seed_from_log(self) -> set:
        """Marker keys already in the topic. NOTE: a full-topic scan — O(1)
        against the shim, but on a real long-lived cluster topic this is a
        full read per driver start. The marker records are keyed, so running
        the output topic log-COMPACTED keeps the scan bounded by the live
        window count; that is the intended production configuration (the
        alternative — trusting only recent markers — could re-produce an
        old window after an unusually long outage). A scan past
        ``seed_scan_warn`` records warns about the compaction risk;
        ``seed_scan_limit`` hard-bounds the scan to the topic TAIL for
        operators who accept the old-window re-produce risk explicitly."""
        import sys as _sys

        seen: set = set()
        end = self.broker.end_offset(self.topic)
        off = 0
        if self.seed_scan_limit is not None and end > self.seed_scan_limit:
            off = end - self.seed_scan_limit
            print(f"warning: output topic '{self.topic}' holds {end} "
                  f"records; seeding the dedup set from the last "
                  f"{self.seed_scan_limit} only — windows committed before "
                  f"offset {off} can be re-produced on re-delivery",
                  file=_sys.stderr)
        scanned = 0
        warned = False
        while True:
            batch = self.broker.fetch(self.topic, off)
            if not batch:
                from spatialflink_tpu.utils.metrics import REGISTRY

                REGISTRY.counter("sink-seed-scan-records").inc(scanned)
                return seen
            for r in batch:
                if isinstance(r.key, str) and r.key.startswith(self.MARKER):
                    seen.add(r.key[len(self.MARKER):])
                # max(): a degraded transport can deliver the batch
                # permuted — never let the scan cursor move backward
                off = max(off, r.offset + 1)
                scanned += 1
            if scanned > self.seed_scan_warn and not warned:
                warned = True
                print(f"warning: dedup seed scan of output topic "
                      f"'{self.topic}' passed {self.seed_scan_warn} records "
                      "and is still going — the topic looks uncompacted; "
                      "run it log-compacted (marker records are keyed) or "
                      "bound the scan with seed_scan_limit",
                      file=_sys.stderr)

    def window_key(self, result) -> str:
        cell = result.extras.get("cell") if hasattr(result, "extras") else None
        base = (f"{getattr(result, 'window_start', None)}:"
                f"{getattr(result, 'window_end', None)}:{cell}")
        return f"{self.job_id}:{base}" if self.job_id else base

    def emit(self, result) -> None:
        if self._tel is not None:
            # per-window producing time under the sink stage (the span also
            # covers the dedup check — both are the sink's cost)
            t0 = time.time()
            with self._tel.span("sink", query="kafka"):
                self._emit(result)
            t1 = time.time()
            if hasattr(result, "window_start"):
                # the window's downstream sink-commit budget (latency
                # plane), plus — with tracing on — the lineage note that
                # closes the trace: records + marker are on the output
                # topic (suppressed duplicates included — their dedup
                # check IS the commit-path cost they paid)
                self._tel.latency.note_downstream(
                    "sink-commit", result.window_start, t0, t1)
                if self._tel.traces is not None:
                    self._tel.traces.note_any(result.window_start,
                                              "sink-commit", t0, t1)
        else:
            self._emit(result)

    def _emit(self, result) -> None:
        wk = self.window_key(result)
        if wk in self.delivered:
            self.duplicates_suppressed += 1
            return
        if self.job_id and wk.split(":", 1)[1] in self.delivered:
            # upgrade continuity: a PRE-fingerprint marker (bare
            # start:end:cell, written before job prefixes existed) still
            # covers this window — without this, the first restart after
            # an upgrade would re-produce every window already in the
            # topic. New markers are always written prefixed, so the
            # legacy cross-job ambiguity dies out with the old markers.
            self.duplicates_suppressed += 1
            return
        # flatten across the multi-query axis (one list per query)
        recs = (result.flat_records() if hasattr(result, "flat_records")
                else result.records)
        n = 0
        if recs and type(self.broker) is InMemoryBroker:
            # batched production (one lock/timestamp for the window's
            # records); wrapped brokers — chaos, supervised, real cluster —
            # keep the per-record path so their per-produce semantics
            # (fault injection, retries, acks) cover every record.
            # Columnar selections (PointRows) serialize straight from their
            # arrays — no per-record Python objects on the sink path.
            vals = None
            sb = getattr(recs, "serialize_batch", None)
            if sb is not None and self._enc.fmt:
                vals = sb(self._enc.fmt, delimiter=self._enc.delimiter,
                          date_format=self._enc.date_format)
            if vals is None:
                enc = self._enc._encode
                vals = [enc(r) for r in recs]
            n = len(vals)
            self.broker.produce_many(self.topic, vals, key=wk)
        else:
            for rec in recs:
                self.broker.produce(self.topic, self._enc._encode(rec),
                                    key=wk)
                n += 1
        extras = {k: v for k, v in getattr(result, "extras", {}).items()
                  if k != "latency_ms"}
        if extras:
            # aggregate-style windows carry their payload in extras
            # (tAggregate heatmaps, tStats rows, multi-query metadata); ship
            # it as ONE JSON summary record under the window key so the
            # topic — not just stdout — holds the full result
            self.broker.produce(self.topic, json.dumps({
                "window": [result.window_start, result.window_end],
                **{k: _jsonable(v) for k, v in extras.items()}}), key=wk)
            n += 1
        # marker value = how many records were produced under this key
        self.broker.produce(self.topic, str(n), key=self.MARKER + wk)
        self.delivered.add(wk)
        self.windows_produced += 1

    def close(self) -> None:
        pass


class KafkaLatencySink:
    """Per-record latency millis to a topic (reference:
    ``HelperClass.LatencySinkPoint``/``LatencySinkLong``,
    ``utils/HelperClass.java:455-529``): value = now - ingestion_time (or
    event time)."""

    def __init__(self, broker: InMemoryBroker, topic: str,
                 use_event_time: bool = False):
        self.broker = broker
        self.topic = topic
        self.use_event_time = use_event_time

    def emit(self, record) -> None:
        now = time.time() * 1000
        base = record.timestamp if self.use_event_time else getattr(
            record, "ingestion_time", record.timestamp)
        self.broker.produce(self.topic, now - base,
                            key=getattr(record, "obj_id", None))

    def close(self) -> None:
        pass


class RealKafkaBroker:
    """kafka-python-backed implementation of the broker surface
    (produce/fetch/commit/committed/end_offset) consumed by
    :class:`KafkaSource`/:class:`KafkaSink` — the adapter that swaps a real
    cluster in for :class:`InMemoryBroker` without touching the pipelines
    (reference consumers at ``StreamingJob.java:473``, producer at ``:512``).

    Topic-as-one-log mapping: the shim models a topic as a single ordered
    log, so the adapter pins every topic to **partition 0** (the reference's
    driver likewise treats each topic as one stream; scale-out happens in the
    operator mesh, not the partition count). Offsets commit through the
    consumer-group API, so a restarted group resumes where
    :class:`KafkaSource` committed — the same at-least-once contract the shim
    provides, with :class:`IdempotentWindowSink` upgrading it to effective
    exactly-once downstream.

    VERIFICATION BOUNDARY (permanent, environmental): this adapter is
    exercised against an injected fake of the kafka-python client API (see
    ``connect_kafka``'s ``kafka_module`` seam and tests/test_kafka.py) —
    the wire path has never run here, because the build environment has
    neither the kafka-python package nor any broker process to speak the
    Kafka protocol to (zero egress; vendoring a wire client would still
    leave nothing real on the other end of the socket). First use against
    a real cluster should smoke-test produce→fetch→commit→committed on a
    scratch topic before trusting a pipeline to it.
    """

    def __init__(self, kafka_module, bootstrap_servers: str, *,
                 produce_timeout_s: float = 30.0, poll_timeout_ms: int = 500,
                 fetch_retries: int = 20):
        self._kafka = kafka_module
        self.bootstrap = bootstrap_servers
        self.produce_timeout_s = produce_timeout_s
        self.poll_timeout_ms = poll_timeout_ms
        self.fetch_retries = fetch_retries
        self._producer = None
        self._fetch_c = None                      # group-less, for fetch/end
        self._group_c: Dict[str, Any] = {}        # group id -> consumer
        self._commit_hwm: Dict[Tuple[str, str], int] = {}  # (topic, group)

    # ------------------------------ helpers -------------------------- #

    @staticmethod
    def _to_bytes(v) -> Optional[bytes]:
        if v is None:
            return None
        if isinstance(v, bytes):
            return v
        return str(v).encode("utf-8")

    @staticmethod
    def _to_str(v):
        return v.decode("utf-8", errors="replace") if isinstance(v, bytes) else v

    def _tp(self, topic: str):
        return self._kafka.TopicPartition(topic, 0)

    def _oam(self, offset: int):
        cls = getattr(self._kafka, "OffsetAndMetadata", None)
        if cls is None:
            cls = self._kafka.structs.OffsetAndMetadata
        try:
            return cls(offset, "")
        except TypeError:  # newer kafka-python adds leader_epoch
            return cls(offset, "", -1)

    def _get_producer(self):
        if self._producer is None:
            self._producer = self._kafka.KafkaProducer(
                bootstrap_servers=self.bootstrap)
        return self._producer

    def _fetch_consumer(self):
        if self._fetch_c is None:
            self._fetch_c = self._kafka.KafkaConsumer(
                bootstrap_servers=self.bootstrap, enable_auto_commit=False)
        return self._fetch_c

    def _group_consumer(self, group: str):
        if group not in self._group_c:
            self._group_c[group] = self._kafka.KafkaConsumer(
                bootstrap_servers=self.bootstrap, group_id=group,
                enable_auto_commit=False)
        return self._group_c[group]

    # ------------------------------ broker surface ------------------- #

    def produce(self, topic: str, value, key: Optional[str] = None,
                timestamp_ms: Optional[int] = None) -> int:
        # partition=0 pins the producer to the same partition the consumer
        # side reads — without it a multi-partition topic would scatter
        # records where fetch()/end_offset() never look
        fut = self._get_producer().send(
            topic, value=self._to_bytes(value), key=self._to_bytes(key),
            partition=0, timestamp_ms=timestamp_ms)
        # blocking .get() = acknowledged write, the adapter's at-least-once
        # half (re-raise on broker error instead of dropping silently)
        return fut.get(timeout=self.produce_timeout_s).offset

    def fetch(self, topic: str, offset: int, max_records: int = 500
              ) -> List[BrokerRecord]:
        """An empty return means END OF TOPIC (``offset >= end_offset``),
        matching the shim contract KafkaSource relies on for ``stop_at_end``.
        A real consumer's poll() legitimately returns nothing while fetch
        sessions warm up or the broker hiccups, so empty polls are retried
        (up to ``fetch_retries``) as long as records exist past ``offset`` —
        otherwise a cold first poll would masquerade as stream end and the
        source would silently drop the topic's tail."""
        c = self._fetch_consumer()
        tp = self._tp(topic)
        c.assign([tp])
        c.seek(tp, offset)
        out: List[BrokerRecord] = []
        for _ in range(max(1, self.fetch_retries)):
            polled = c.poll(timeout_ms=self.poll_timeout_ms,
                            max_records=max_records)
            for recs in polled.values():
                for r in recs:
                    out.append(BrokerRecord(
                        offset=r.offset, key=self._to_str(r.key),
                        value=self._to_str(r.value),
                        timestamp_ms=getattr(r, "timestamp", 0) or 0))
            if out or offset >= self.end_offset(topic):
                return out
        raise TimeoutError(
            f"kafka fetch: {topic}@{offset} < end_offset but "
            f"{self.fetch_retries} polls returned no records")

    def commit(self, topic: str, group: str, next_offset: int) -> None:
        # monotonic like the shim: a slow replica must not rewind the group.
        # The high-water mark is cached locally (seeded from the broker on
        # first touch) — this adapter owns its group consumers, so one
        # committed() RPC per (topic, group) suffices instead of one per
        # commit on the hot path
        if next_offset <= self.committed(topic, group):
            return
        self._group_consumer(group).commit(
            {self._tp(topic): self._oam(next_offset)})
        self._commit_hwm[(topic, group)] = next_offset

    def committed(self, topic: str, group: str) -> int:
        hwm = self._commit_hwm.get((topic, group))
        if hwm is not None:
            return hwm
        off = self._group_consumer(group).committed(self._tp(topic))
        hwm = 0 if off is None else int(getattr(off, "offset", off))
        self._commit_hwm[(topic, group)] = hwm
        return hwm

    def end_offset(self, topic: str) -> int:
        c = self._fetch_consumer()
        tp = self._tp(topic)
        return int(c.end_offsets([tp])[tp])

    def close(self) -> None:
        if self._producer is not None:
            self._producer.flush()
            self._producer.close()
        for c in ([self._fetch_c] if self._fetch_c else []) + list(
                self._group_c.values()):
            c.close()


#: process-shared in-memory brokers, keyed by their ``memory://name`` URL —
#: a producer thread, a test, and a driver ``main()`` call in the same
#: process all reach the same log (and a re-run of ``main()`` after a
#: simulated crash finds its committed offsets again)
_MEMORY_BROKERS: Dict[str, InMemoryBroker] = {}
_MEMORY_BROKERS_LOCK = threading.Lock()


def resolve_broker(bootstrap_servers: str, kafka_module=None):
    """Broker by bootstrap string: ``memory://<name>`` → the process-shared
    :class:`InMemoryBroker` registered under that URL (created on first
    use); anything else → the real-cluster adapter via
    :func:`connect_kafka`. This is how the driver's ``--kafka`` mode picks
    its transport from ``kafkaBootStrapServers``."""
    if bootstrap_servers.startswith("memory://"):
        with _MEMORY_BROKERS_LOCK:
            return _MEMORY_BROKERS.setdefault(bootstrap_servers,
                                              InMemoryBroker())
    return connect_kafka(bootstrap_servers, kafka_module)


def reset_memory_brokers() -> None:
    """Drop every registered ``memory://`` broker (test isolation)."""
    with _MEMORY_BROKERS_LOCK:
        _MEMORY_BROKERS.clear()


def connect_kafka(bootstrap_servers: str, kafka_module=None) -> RealKafkaBroker:
    """Real-broker adapter against the kafka-python client API.

    ``kafka_module`` is the injection seam: tests pass a fake implementing
    the same surface (KafkaProducer/KafkaConsumer/TopicPartition/
    OffsetAndMetadata); production leaves it None to import kafka-python,
    raising RuntimeError when the package is absent (it is not installed in
    this image — use :class:`InMemoryBroker` for local pipelines).
    """
    if kafka_module is None:
        try:
            import kafka as kafka_module  # type: ignore
        except ImportError as e:
            raise RuntimeError(
                "connect_kafka requires the kafka-python package, which is "
                "not installed in this environment; use InMemoryBroker for "
                "local pipelines and tests.") from e
    return RealKafkaBroker(kafka_module, bootstrap_servers)
