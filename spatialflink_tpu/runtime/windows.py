"""Sliding / tumbling window assignment and buffering.

Flink-compatible assignment: a sliding window of (size, slide) covers
[start, start + size) for starts aligned to ``slide``; each record with
event time ``ts`` belongs to the ``size // slide`` windows whose interval
contains ts. Tumbling = sliding with slide == size.

Windows seal when the watermark passes window_end; sealed windows emit their
buffered records in one shot — this is the host-side half of the
"window batch" execution unit, the rebuild's replacement for Flink's
per-cell window operators (the device half is in spatialflink_tpu.ops).
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Dict, Iterator, List, Optional, Tuple

from spatialflink_tpu.runtime.watermarks import BoundedOutOfOrderness


class _ColumnarSeg(tuple):
    """Marker for a ``(PointChunk, idx_array)`` columnar slice sitting in a
    window/pane buffer alongside plain records — the batched decode path
    buffers slices of the decoded SoA chunks instead of per-record Python
    objects. A tuple subclass so LazyRecords consumes it as-is."""

    __slots__ = ()


def _finalize_buffer(buf: List):
    """A sealed buffer's record list: plain lists pass through unchanged
    (the scalar-record contract); buffers holding columnar segments wrap in
    a LazyRecords view (records materialize on demand; the device batch
    builds straight from the slices)."""
    if not any(isinstance(x, _ColumnarSeg) for x in buf):
        return buf
    from spatialflink_tpu.streams.bulk import LazyRecords

    segs: List = []
    run: List = []
    for x in buf:
        if isinstance(x, _ColumnarSeg):
            if run:
                segs.append(run)
                run = []
            segs.append(tuple(x))
        else:
            run.append(x)
    if run:
        segs.append(run)
    return LazyRecords(segs)


def _materialize_buffer(buf: List) -> Iterator:
    """Per-record view of a buffer for the checkpoint codec (columnar
    segments materialize; the snapshot format stays record-shaped, so old
    and new layouts round-trip through the same codec)."""
    for x in buf:
        if isinstance(x, _ColumnarSeg):
            chunk, idx = x
            for j in idx.tolist():
                yield chunk.record(j)
        else:
            yield x


def _note_seals(starts) -> None:
    """Stamp the TRUE seal wall clock of every window a watermark sweep
    made ready, BEFORE any of them yields (the assembly chain is
    generator-lazy, so a window's own yield executes only when the
    consumer pulls it — windows behind earlier windows' eval/drain would
    otherwise read their wait as part of assembly). The latency plane's
    drive loop pops these to split buffer residency from seal→dispatch
    queueing. One ``active()`` check per sweep, never per record; a
    session-less run executes nothing."""
    from spatialflink_tpu.utils import telemetry as _telemetry

    tel = _telemetry.active()
    if tel is None or not starts:
        return
    now = time.time()
    for s in starts:
        tel.latency.note_seal(s, now)


def _keep_mask(watermarker, ts):
    """Vectorized per-record lateness decisions for one chunk: the keep/drop
    mask against the per-record PREFIX watermark — identical to feeding the
    chunk one record at a time (shared by WindowAssembler.add_chunk /
    add_parsed_chunk and PaneBuffer.add_parsed_chunk)."""
    import numpy as np

    prior = max(watermarker._max_ts, -(2 ** 62))
    run_max = np.maximum.accumulate(ts)
    wm_before = np.empty_like(ts)
    wm_before[0] = prior
    np.maximum(run_max[:-1], prior, out=wm_before[1:])
    return ts >= wm_before - watermarker.allowed_lateness_ms


@dataclass(frozen=True)
class WindowSpec:
    size_ms: int
    slide_ms: int

    @staticmethod
    def tumbling(size_ms: int) -> "WindowSpec":
        return WindowSpec(size_ms, size_ms)

    @staticmethod
    def sliding(size_ms: int, slide_ms: int) -> "WindowSpec":
        return WindowSpec(size_ms, slide_ms)

    def assign(self, ts_ms: int) -> List[int]:
        """Window start times containing ``ts_ms`` (Flink semantics)."""
        last_start = ts_ms - (ts_ms % self.slide_ms)
        starts = []
        start = last_start
        while start > ts_ms - self.size_ms:
            starts.append(start)
            start -= self.slide_ms
        return starts

    @property
    def overlap(self) -> int:
        """Windows each record belongs to (``size // slide`` when slide
        divides size — the pane count per window)."""
        return -(-self.size_ms // self.slide_ms)

    def pane_of(self, ts_ms: int) -> int:
        """Start of the slide-aligned pane containing ``ts_ms``: the
        non-overlapping [p, p + slide) interval every sliding window
        decomposes into when ``slide`` divides ``size``."""
        return ts_ms - (ts_ms % self.slide_ms)

    def pane_decomposable(self) -> bool:
        """True when every window [s, s + size) is exactly a union of
        slide-aligned panes — the precondition of the pane-incremental
        execution mode (slide must divide size, and slide < size: tumbling
        windows have overlap 1, so there is nothing to share)."""
        return (self.slide_ms < self.size_ms
                and self.size_ms % self.slide_ms == 0)

    def pane_starts(self, window_start: int) -> List[int]:
        """The pane starts covering window ``[window_start, +size)``."""
        return list(range(window_start, window_start + self.size_ms,
                          self.slide_ms))

    def earliest_end(self, ts_ms: int) -> int:
        """End of the EARLIEST window containing ``ts_ms`` (O(1)) — the
        first moment a watermark passing it could seal one of the record's
        windows. Lets the chunked assembler flush exactly when a per-record
        ``add`` would have sealed something."""
        last_start = ts_ms - (ts_ms % self.slide_ms)
        k_max = (last_start - ts_ms + self.size_ms - 1) // self.slide_ms
        return last_start - k_max * self.slide_ms + self.size_ms

    def assign_bulk(self, ts_ms) -> "Tuple[object, object]":
        """Vectorized :meth:`assign` over an array of event times.

        Returns ``(win_start, rec_idx)`` sorted by (window, original record
        order): every (window, record) membership pair, grouped by window.
        This is the replay/bulk-ingest fast path — no per-record Python loop,
        no watermark bookkeeping (a bounded replay has complete data, so no
        record is ever late). Assignment runs in record chunks so the dense
        (chunk, size/slide) intermediates stay bounded even for huge replays
        with high window overlap; the final global sort merges the chunks.
        """
        import numpy as np

        ts = np.asarray(ts_ms, np.int64)
        n_max = -(-self.size_ms // self.slide_ms)  # ceil
        offs = np.arange(n_max, dtype=np.int64) * self.slide_ms
        # chunk size targets ~64M int64 intermediate elements max
        chunk = max(1, (1 << 26) // max(1, n_max))
        ws_parts, ri_parts = [], []
        for lo in range(0, ts.shape[0], chunk):
            t = ts[lo:lo + chunk]
            last = t - (t % self.slide_ms)
            starts = last[:, None] - offs[None, :]     # (chunk, n_max)
            valid = starts > (t[:, None] - self.size_ms)
            rec = np.broadcast_to(
                np.arange(lo, lo + t.shape[0], dtype=np.int64)[:, None],
                starts.shape)
            ws_parts.append(starts[valid])
            ri_parts.append(rec[valid])
        win_start = np.concatenate(ws_parts) if ws_parts else \
            np.empty(0, np.int64)
        rec_idx = np.concatenate(ri_parts) if ri_parts else \
            np.empty(0, np.int64)
        order = np.lexsort((rec_idx, win_start))
        return win_start[order], rec_idx[order]


class WindowAssembler:
    """Buffers records into event-time windows; yields sealed windows.

    Usage::

        wa = WindowAssembler(WindowSpec.sliding(10_000, 5_000),
                             allowed_lateness_ms=2_000)
        for rec in stream:
            for (start, end, records) in wa.add(rec.timestamp, rec):
                ...process sealed window...
        for (start, end, records) in wa.flush():
            ...end of stream...

    Late records (event time below the watermark) are dropped and counted,
    mirroring the effective behavior of the reference's bounded
    out-of-orderness extractor feeding already-fired windows.
    """

    def __init__(self, spec: WindowSpec, allowed_lateness_ms: int = 0):
        self.spec = spec
        self.watermarker = BoundedOutOfOrderness(allowed_lateness_ms)
        self._buffers: Dict[int, List] = {}
        self.late_dropped = 0

    def add(self, ts_ms: int, record) -> Iterator[Tuple[int, int, List]]:
        if self.watermarker.is_late(ts_ms):
            self.late_dropped += 1
        else:
            for start in self.spec.assign(ts_ms):
                self._buffers.setdefault(start, []).append(record)
        wm = self.watermarker.on_event(ts_ms)
        yield from self._seal_until(wm)

    def add_chunk(self, ts_list, records
                  ) -> Iterator[Tuple[int, int, List]]:
        """Vectorized :meth:`add` for a chunk of records: the lateness check
        runs against the per-record prefix watermark (identical keep/drop
        decisions to feeding the chunk one record at a time) and window
        assignment rides :meth:`WindowSpec.assign_bulk` — no per-record
        Python assign loop, one watermark update, one seal sweep."""
        import numpy as np

        if not records:
            return
        ts = np.asarray(ts_list, np.int64)
        # watermark BEFORE each record = max of prior state and the chunk
        # prefix (clamped: the uninitialized int64-min state would wrap
        # under the lateness subtraction)
        keep = _keep_mask(self.watermarker, ts)
        self.late_dropped += int((~keep).sum())
        kept_idx = np.nonzero(keep)[0]
        if kept_idx.size:
            kept = [records[int(i)] for i in kept_idx]
            win, rec = self.spec.assign_bulk(ts[kept_idx])
            bounds = np.flatnonzero(np.r_[True, win[1:] != win[:-1], True])
            for i in range(len(bounds) - 1):
                lo, hi = int(bounds[i]), int(bounds[i + 1])
                buf = self._buffers.setdefault(int(win[lo]), [])
                buf.extend(kept[j] for j in rec[lo:hi].tolist())
        wm = self.watermarker.on_event(int(ts.max()))
        yield from self._seal_until(wm)

    def add_parsed_chunk(self, chunk) -> Iterator[Tuple[int, int, List]]:
        """Columnar :meth:`add_chunk`: one decoded :class:`PointChunk`
        buffers as SoA SLICES (``_ColumnarSeg``) instead of per-record
        objects — same vectorized prefix-watermark late drops, same
        ``assign_bulk`` window set, one seal sweep per chunk. Sealed
        windows carry :class:`LazyRecords` views, so the operator layer
        builds device batches straight from the slices and materializes
        Point objects only for records a window actually emits."""
        import numpy as np

        ts = np.asarray(chunk.parsed.ts, np.int64)
        if not ts.size:
            return
        keep = _keep_mask(self.watermarker, ts)
        self.late_dropped += int((~keep).sum())
        kept_idx = np.nonzero(keep)[0]
        if kept_idx.size:
            win, rec = self.spec.assign_bulk(ts[kept_idx])
            bounds = np.flatnonzero(np.r_[True, win[1:] != win[:-1], True])
            for i in range(len(bounds) - 1):
                lo, hi = int(bounds[i]), int(bounds[i + 1])
                self._buffers.setdefault(int(win[lo]), []).append(
                    _ColumnarSeg((chunk, kept_idx[rec[lo:hi]])))
        wm = self.watermarker.on_event(int(ts.max()))
        yield from self._seal_until(wm)

    def assemble_chunks(self, chunks) -> Iterator[Tuple[int, int, List]]:
        """Drive a chunked decode stream (``driver.decode_chunks``): each
        decoded chunk — columnar :class:`PointChunk` or a plain record list
        (bulk-ineligible formats / mixed streams) — buffers whole and then
        seals, so emission granularity is ONE DECODE CHUNK. In live mode a
        chunk is at most one poll cycle (the source's starvation sentinel
        flushes the decoder), bounding the added emission latency to the
        same one-poll-cycle window the chunked Kafka decode always had; a
        checkpoint barrier can therefore never observe records sitting in a
        half-assembled chunk (every pulled record is in the buffers before
        any seal yields)."""
        for ch in chunks:
            if hasattr(ch, "parsed"):
                yield from self.add_parsed_chunk(ch)
            elif ch:
                yield from self.add_chunk([r.timestamp for r in ch], ch)
        yield from self.flush()

    def assemble(self, stream, ts_of=None, chunk: int = 4096
                 ) -> Iterator[Tuple[int, int, List]]:
        """Drive a whole record stream through chunk-vectorized assignment
        (:meth:`add_chunk`) + the end-of-stream :meth:`flush`.

        A chunked decode stream (one exposing ``.chunks`` — see
        ``driver.decode_stream``) short-circuits to
        :meth:`assemble_chunks`, consuming the decoder's columnar chunks
        directly with no per-record materialization.

        Emission timing matches the per-record :meth:`add` loop exactly: a
        chunk flushes the moment its running watermark reaches the earliest
        pending window end (``WindowSpec.earliest_end`` per record, O(1)),
        so sealed windows are never held back behind a fill count — live
        sources emit mid-stream just like before. ``chunk`` only bounds
        memory between seal points."""
        chunks_fn = getattr(stream, "chunks", None)
        if chunks_fn is not None:
            yield from self.assemble_chunks(chunks_fn())
            return
        ts_of = ts_of if ts_of is not None else (lambda r: r.timestamp)
        lateness = self.watermarker.allowed_lateness_ms
        buf_r: List = []
        buf_t: List[int] = []
        chunk_max = -(2 ** 62)
        min_end: Optional[int] = None  # earliest end among chunk records
        base_end: Optional[int] = (
            min(self._buffers) + self.spec.size_ms if self._buffers else None)
        for rec in stream:
            ts = ts_of(rec)
            buf_r.append(rec)
            buf_t.append(ts)
            if ts > chunk_max:
                chunk_max = ts
            e = self.spec.earliest_end(ts)
            if min_end is None or e < min_end:
                min_end = e
            cur_min = min_end if base_end is None else min(min_end, base_end)
            wm = max(chunk_max, self.watermarker._max_ts) - lateness
            if len(buf_r) >= chunk or wm >= cur_min:
                yield from self.add_chunk(buf_t, buf_r)
                buf_r, buf_t = [], []
                chunk_max = -(2 ** 62)
                min_end = None
                base_end = (min(self._buffers) + self.spec.size_ms
                            if self._buffers else None)
        if buf_r:
            yield from self.add_chunk(buf_t, buf_r)
        yield from self.flush()

    def _seal_until(self, watermark: int) -> Iterator[Tuple[int, int, List]]:
        ready = sorted(
            s for s in self._buffers if s + self.spec.size_ms <= watermark
        )
        _note_seals(ready)
        for start in ready:
            records = _finalize_buffer(self._buffers.pop(start))
            yield (start, start + self.spec.size_ms, records)

    def flush(self) -> Iterator[Tuple[int, int, List]]:
        """Seal every remaining window (end of bounded stream)."""
        ready = sorted(self._buffers)
        _note_seals(ready)
        for start in ready:
            records = _finalize_buffer(self._buffers.pop(start))
            yield (start, start + self.spec.size_ms, records)

    def snapshot(self, encode) -> dict:
        """JSON-able open-window state for the checkpoint coordinator:
        watermark, late-drop count, and every open window's buffered records
        (``encode(record) -> str``; columnar segments materialize here, so
        the snapshot format is identical to the record-path layout and old
        checkpoints restore into either). Taken at a barrier where every
        SEALED window has already been emitted downstream, this is exactly
        the state a resumed run needs alongside the source position."""
        return {
            "watermark_max_ts": self.watermarker._max_ts,
            "late_dropped": self.late_dropped,
            "buffers": {str(s): [encode(r)
                                 for r in _materialize_buffer(recs)]
                        for s, recs in self._buffers.items()},
        }

    def restore(self, state: dict, decode) -> None:
        """Inverse of :meth:`snapshot` (``decode(str) -> record``)."""
        self.watermarker._max_ts = int(state["watermark_max_ts"])
        self.late_dropped = int(state.get("late_dropped", 0))
        self._buffers = {int(s): [decode(r) for r in recs]
                         for s, recs in state["buffers"].items()}


class MicroBatcher:
    """Tumbling COUNT micro-windows for the realtime mode, on the
    vectorized decode path.

    The reference's realtime trigger fires per element
    (``QueryType.java`` RealTime); the rebuild batches ``batch_size``
    arrivals per device dispatch. The OLD implementation was a scalar
    sibling outside every runtime plane: a plain list fed record-by-record
    (``_micro_batches``), bypassing the columnar decode, the checkpoint
    coordinator, and the latency plane. This class makes realtime a
    DEGENERATE CASE of the batched window machinery instead:

    - chunked decode streams (``.chunks``) buffer SoA SLICES
      (:class:`_ColumnarSeg`) and sealed batches carry
      :class:`~spatialflink_tpu.streams.bulk.LazyRecords` — the operator
      layer builds device batches straight from the slices, exactly like
      the window assemblers (the old path re-materialized every record);
    - batches cut STRICTLY every ``batch_size`` records in arrival order,
      so batch boundaries — and therefore emitted results — are identical
      to the scalar path REGARDLESS of decode-chunk size (the chunk
      governor may resize mid-run without moving a boundary);
    - ``snapshot``/``restore`` expose the same record-shaped codec
      contract as :class:`WindowAssembler`, so the drive loop registers
      the open micro-batch as a coordinated-checkpoint component: records
      buffered past the noted source position at a barrier are IN the
      manifest, and a resume restores them instead of losing them (the
      old path relied on decode-chunk / batch-size alignment for this —
      an invariant the governor deliberately breaks).
    """

    def __init__(self, batch_size: int):
        self.batch_size = max(1, int(batch_size))
        self._buf: List = []
        self._count = 0

    def add_chunk(self, chunk) -> Iterator[Tuple[int, int, List]]:
        """Buffer one decoded :class:`PointChunk` as columnar slices,
        yielding every micro-batch the chunk completes (a chunk larger
        than the batch size cuts mid-chunk; a smaller one accumulates)."""
        import numpy as np

        n = len(chunk)
        pos = 0
        while pos < n:
            take = min(self.batch_size - self._count, n - pos)
            self._buf.append(
                _ColumnarSeg((chunk, np.arange(pos, pos + take))))
            self._count += take
            pos += take
            if self._count >= self.batch_size:
                yield self._cut()

    def add_records(self, records) -> Iterator[Tuple[int, int, List]]:
        """Per-record buffering for plain (non-columnar) streams."""
        for rec in records:
            self._buf.append(rec)
            self._count += 1
            if self._count >= self.batch_size:
                yield self._cut()

    def batches(self, stream) -> Iterator[Tuple[int, int, List]]:
        """Drive a whole stream: a chunked decode stream consumes columnar
        chunks directly; plain record streams keep a per-record loop. The
        final partial batch flushes at end of stream (bounded sources),
        matching the scalar path's trailing fire."""
        chunks_fn = getattr(stream, "chunks", None)
        if chunks_fn is not None:
            for ch in chunks_fn():
                if hasattr(ch, "parsed"):
                    yield from self.add_chunk(ch)
                elif ch:
                    yield from self.add_records(ch)
        else:
            yield from self.add_records(stream)
        yield from self.flush()

    def flush(self) -> Iterator[Tuple[int, int, List]]:
        if self._buf:
            yield self._cut()

    def _cut(self) -> Tuple[int, int, List]:
        buf = self._buf
        self._buf = []
        self._count = 0
        return (self._edge_ts(buf[0], 0), self._edge_ts(buf[-1], -1),
                _finalize_buffer(buf))

    @staticmethod
    def _edge_ts(item, j: int) -> int:
        """First/last record event time of a buffer entry (the micro-
        batch's start/end — the same ``r[0].timestamp``/``r[-1].timestamp``
        bounds the scalar path reported)."""
        if isinstance(item, _ColumnarSeg):
            chunk, idx = item
            return int(chunk.parsed.ts[int(idx[j])])
        return int(item.timestamp)

    def snapshot(self, encode) -> dict:
        """The open micro-batch for the checkpoint coordinator (columnar
        segments materialize — the record-shaped layout every assembler
        snapshot shares)."""
        return {"batch_size": self.batch_size,
                "records": [encode(r)
                            for r in _materialize_buffer(self._buf)]}

    def restore(self, state: dict, decode) -> None:
        """Inverse of :meth:`snapshot` (restored records re-buffer as
        plain objects; the next chunk appends columnar slices after them
        — :func:`_finalize_buffer` handles the mix)."""
        self._buf = [decode(r) for r in state.get("records", [])]
        self._count = len(self._buf)


class PaneBuffer:
    """Pane-sliced window assembly: each record is buffered ONCE into its
    slide-aligned pane; sealed windows are yielded as *pane lists* instead
    of flat record lists, so the operator layer can kernel-process each pane
    once and share the partial across every window containing it.

    Yields ``(start, end, [(pane_start, records), ...])`` with the exact
    same window set, sealing times, and late-drop decisions as
    :class:`WindowAssembler` (same watermarker): a window exists iff at
    least one of its panes is non-empty, and seals when the watermark passes
    its end. Panes are evicted once the watermark passes ``pane + size``
    (their last covering window has sealed; any record that could still
    land in the pane would be late, because sealing and the late check share
    one watermark — see the eviction proof in ARCHITECTURE.md).

    Requires ``spec.pane_decomposable()``: slide must divide size (a window
    must be exactly a union of panes) and slide < size (tumbling windows
    have nothing to share — callers bypass panes there).
    """

    def __init__(self, spec: WindowSpec, allowed_lateness_ms: int = 0):
        if not spec.pane_decomposable():
            raise ValueError(
                f"PaneBuffer needs slide | size and slide < size, got "
                f"size={spec.size_ms} slide={spec.slide_ms}")
        self.spec = spec
        self.watermarker = BoundedOutOfOrderness(allowed_lateness_ms)
        self._panes: Dict[int, List] = {}
        self.late_dropped = 0
        #: every window start below this has been emitted or is final-empty
        self._next: Optional[int] = None

    def add(self, ts_ms: int, record) -> Iterator[Tuple[int, int, List]]:
        if self.watermarker.is_late(ts_ms):
            self.late_dropped += 1
        else:
            self._panes.setdefault(self.spec.pane_of(ts_ms), []).append(record)
        wm = self.watermarker.on_event(ts_ms)
        yield from self._seal_until(wm)

    def add_parsed_chunk(self, chunk) -> Iterator[Tuple[int, int, List]]:
        """Columnar :meth:`add` over one decoded :class:`PointChunk`: the
        same vectorized prefix-watermark late drops as
        ``WindowAssembler.add_parsed_chunk``, pane assignment in one
        ``ts - ts % slide`` pass, SoA slices buffered per pane, one seal
        sweep per chunk."""
        import numpy as np

        ts = np.asarray(chunk.parsed.ts, np.int64)
        if not ts.size:
            return
        keep = _keep_mask(self.watermarker, ts)
        self.late_dropped += int((~keep).sum())
        kept_idx = np.nonzero(keep)[0]
        if kept_idx.size:
            kts = ts[kept_idx]
            pane = kts - kts % self.spec.slide_ms
            order = np.argsort(pane, kind="stable")
            pane_s = pane[order]
            bounds = np.flatnonzero(
                np.r_[True, pane_s[1:] != pane_s[:-1], True])
            for i in range(len(bounds) - 1):
                lo, hi = int(bounds[i]), int(bounds[i + 1])
                self._panes.setdefault(int(pane_s[lo]), []).append(
                    _ColumnarSeg((chunk, kept_idx[order[lo:hi]])))
        wm = self.watermarker.on_event(int(ts.max()))
        yield from self._seal_until(wm)

    def assemble(self, stream) -> Iterator[Tuple[int, int, List]]:
        """Drive a whole stream: a chunked decode stream (``.chunks``)
        consumes columnar chunks directly (emission granularity = one
        decode chunk, exactly like ``WindowAssembler.assemble_chunks``);
        plain record streams keep the per-record :meth:`add` loop."""
        chunks_fn = getattr(stream, "chunks", None)
        if chunks_fn is not None:
            for ch in chunks_fn():
                if hasattr(ch, "parsed"):
                    yield from self.add_parsed_chunk(ch)
                else:
                    for rec in ch:
                        yield from self.add(rec.timestamp, rec)
        else:
            for rec in stream:
                yield from self.add(rec.timestamp, rec)
        yield from self.flush()

    def _seal_until(self, watermark: int) -> Iterator[Tuple[int, int, List]]:
        if not self._panes:
            return
        limit = watermark - self.spec.size_ms  # starts <= limit seal
        lo = min(self._panes) - self.spec.size_ms + self.spec.slide_ms
        if self._next is not None:
            lo = max(lo, self._next)
        if lo > limit:
            return  # O(1) common case: nothing sealable yet
        yield from self._emit_range(lo, limit)
        # every start <= limit is now emitted or final-empty (a kept record
        # always has ts >= watermark, so its windows end past the watermark
        # and start past `limit`); record that and drop dead panes
        slide = self.spec.slide_ms
        self._next = limit - (limit % slide) + slide
        for p in [p for p in self._panes if p < self._next]:
            del self._panes[p]

    def _emit_range(self, lo: int, limit) -> Iterator[Tuple[int, int, List]]:
        size, slide = self.spec.size_ms, self.spec.slide_ms
        starts = set()
        for p in self._panes:
            s = max(p - size + slide, lo)
            s1 = p if limit is None else min(p, limit)
            while s <= s1:
                starts.add(s)
                s += slide
        _note_seals(sorted(starts))
        for s in sorted(starts):
            panes = [(p, _finalize_buffer(self._panes[p]))
                     for p in range(s, s + size, slide) if p in self._panes]
            yield (s, s + size, panes)

    def flush(self) -> Iterator[Tuple[int, int, List]]:
        """Seal every remaining window (end of bounded stream)."""
        if not self._panes:
            return
        lo = min(self._panes) - self.spec.size_ms + self.spec.slide_ms
        if self._next is not None:
            lo = max(lo, self._next)
        yield from self._emit_range(lo, None)
        self._panes.clear()

    def snapshot(self, encode) -> dict:
        """JSON-able pane state for the checkpoint coordinator: watermark,
        late-drop count, the emitted-frontier ``_next``, and every live
        pane's records. A snapshot taken mid-seal-sweep (``_next`` not yet
        advanced) may re-emit an already-delivered window on resume — the
        idempotent window sink suppresses it; nothing is ever lost."""
        return {
            "watermark_max_ts": self.watermarker._max_ts,
            "late_dropped": self.late_dropped,
            "next": self._next,
            "panes": {str(p): [encode(r) for r in _materialize_buffer(recs)]
                      for p, recs in self._panes.items()},
        }

    def restore(self, state: dict, decode) -> None:
        """Inverse of :meth:`snapshot`."""
        self.watermarker._max_ts = int(state["watermark_max_ts"])
        self.late_dropped = int(state.get("late_dropped", 0))
        nxt = state.get("next")
        self._next = None if nxt is None else int(nxt)
        self._panes = {int(p): [decode(r) for r in recs]
                       for p, recs in state["panes"].items()}
