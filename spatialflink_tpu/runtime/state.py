"""Keyed operator state with explicit snapshot/restore.

The reference leans on Flink managed state (``ValueState``/``MapState``/
``ListState``) and would get checkpointing from Flink if it were configured
(SURVEY §5: it never is). Here host-side operator state is explicit and
snapshot-able: device state pytrees hop to host numpy for serialization, and
:meth:`CheckpointableState.save` / :meth:`load` round-trip through a single
``.npz`` file — the rebuild's checkpoint/resume story.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional

import numpy as np


class CheckpointableState:
    """A named bag of numpy/jax arrays + JSON-able metadata."""

    def __init__(self):
        self.arrays: Dict[str, Any] = {}
        self.meta: Dict[str, Any] = {}

    def save(self, path: str) -> None:
        host = {k: np.asarray(v) for k, v in self.arrays.items()}
        np.savez(path, __meta__=json.dumps(self.meta), **host)

    @classmethod
    def load(cls, path: str) -> "CheckpointableState":
        out = cls()
        with np.load(path, allow_pickle=False) as z:
            for k in z.files:
                if k == "__meta__":
                    out.meta = json.loads(str(z[k]))
                else:
                    out.arrays[k] = z[k]
        return out


class TrajStateStore:
    """Host wrapper around a device :class:`TrajStatsState` that grows with
    the interner and snapshots to disk."""

    def __init__(self, capacity: int = 256):
        from spatialflink_tpu.ops.trajectory import TrajStatsState

        self.capacity = capacity
        self.state = TrajStatsState.zeros(capacity)

    def ensure(self, min_capacity: int) -> None:
        """Grow (power-of-two) so new interned object ids fit."""
        if min_capacity <= self.capacity:
            return
        from spatialflink_tpu.ops.trajectory import TrajStatsState
        from spatialflink_tpu.utils import bucket_size

        new_cap = bucket_size(min_capacity, self.capacity * 2)
        old = self.state
        grown = TrajStatsState.zeros(new_cap)
        import jax.numpy as jnp

        self.state = TrajStatsState(
            *(g.at[: self.capacity].set(o) for g, o in zip(grown, old))
        )
        self.capacity = new_cap

    def snapshot(self) -> CheckpointableState:
        cp = CheckpointableState()
        cp.meta["capacity"] = self.capacity
        for name, arr in self.state._asdict().items():
            cp.arrays[name] = arr
        return cp

    @classmethod
    def restore(cls, cp: CheckpointableState) -> "TrajStateStore":
        from spatialflink_tpu.ops.trajectory import TrajStatsState
        import jax.numpy as jnp

        store = cls(capacity=int(cp.meta["capacity"]))
        store.state = TrajStatsState(
            **{k: jnp.asarray(v) for k, v in cp.arrays.items()}
        )
        return store
