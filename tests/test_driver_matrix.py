"""Full option-space wiring smoke: EVERY range/kNN/join case in the CASES
registry (the reference's StreamingJob cases 1-142 incl. the latency
variants) must run end-to-end through ``run_option`` — not just the
representative pairs the per-family tests use. Catches registry/operator
wiring regressions across the whole matrix; semantic correctness is pinned
elsewhere (tests/test_operator_matrix.py oracles)."""

import os

import numpy as np
import pytest

from spatialflink_tpu.config import Params
from spatialflink_tpu.driver import CASES, run_option
from spatialflink_tpu.models import LineString, Point, Polygon

CONF = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                    "conf", "spatialflink-conf.yml")

_OPERATOR_OPTIONS = sorted(
    o for o, s in CASES.items() if s.family in ("range", "knn", "join"))


def _params(option: int) -> Params:
    p = Params.from_yaml(CONF)
    p.query.option = option
    p.query.radius = 0.5
    p.query.k = 3
    return p


def _stream(kind: str, grid, n=30, seed=0):
    rng = np.random.default_rng(seed)
    t0 = 1_700_000_000_000
    out = []
    for i in range(n):
        cx = float(rng.uniform(grid.min_x + 0.2, grid.max_x - 0.2))
        cy = float(rng.uniform(grid.min_y + 0.2, grid.max_y - 0.2))
        t = t0 + i * 400
        if kind == "Point":
            out.append(Point.create(cx, cy, grid, obj_id=f"o{i % 13}",
                                    timestamp=t))
        elif kind == "Polygon":
            w = 0.05
            out.append(Polygon.create(
                [[(cx, cy), (cx + w, cy), (cx + w, cy + w), (cx, cy + w)]],
                grid, obj_id=f"p{i % 13}", timestamp=t))
        else:
            out.append(LineString.create(
                [(cx, cy), (cx + 0.05, cy + 0.05), (cx + 0.1, cy)],
                grid, obj_id=f"l{i % 13}", timestamp=t))
    return out


def test_matrix_covers_reference_option_space():
    # 9 pairs x {window, realtime} x {range, knn, join} + 6 latency variants
    assert len(_OPERATOR_OPTIONS) == 9 * 2 * 3 + 6


@pytest.mark.parametrize("option", _OPERATOR_OPTIONS)
def test_option_wires_end_to_end(option):
    spec = CASES[option]
    p = _params(option)
    grid, _ = p.grids()
    s1 = _stream(spec.stream, grid, seed=option)
    s2 = (_stream(spec.query, grid, seed=option + 1)
          if spec.family == "join" else None)
    out = list(run_option(p, s1, s2))
    assert out, f"option {option} produced no windows"
    if spec.latency:
        assert all("latency_ms" in w.extras for w in out), option


_TRAJECTORY_OPTIONS = sorted(
    o for o, s in CASES.items()
    if s.family in ("tfilter", "trange", "tstats", "taggregate", "tjoin",
                    "tknn"))


def test_trajectory_matrix_covers_reference_option_space():
    # 6 families x {realtime, window} + the three naive twins
    assert len(_TRAJECTORY_OPTIONS) == 6 * 2 + 3


@pytest.mark.parametrize("option", _TRAJECTORY_OPTIONS)
def test_trajectory_option_wires_end_to_end(option):
    spec = CASES[option]
    p = _params(option)
    grid, _ = p.grids()
    # trajectories: several points per objID so stats/joins have segments
    s1 = _stream("Point", grid, n=60, seed=option)
    s2 = (_stream("Point", grid, n=60, seed=option + 1)
          if spec.family == "tjoin" else None)
    out = list(run_option(p, s1, s2))
    assert isinstance(out, list), option  # wiring ran; some families emit
    #                                       nothing on sparse synthetic data
    if spec.family in ("tstats", "taggregate", "tfilter"):
        assert out, f"option {option} produced no results"
