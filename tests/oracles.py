"""Pure-NumPy float64 reference implementations used as correctness oracles.

These intentionally re-derive the reference's semantics independently of the
device kernels (no jax imports) — the rebuild's analogue of GeoFlink's naive
exhaustive-scan twins (SURVEY.md §4)."""

from __future__ import annotations

import numpy as np


def pp_dist(x1, y1, x2, y2):
    return np.hypot(np.asarray(x2) - x1, np.asarray(y2) - y1)


def point_segment_dist(px, py, x1, y1, x2, y2):
    px, py = float(px), float(py)
    cx, cy = x2 - x1, y2 - y1
    len_sq = cx * cx + cy * cy
    if len_sq == 0:
        return np.hypot(px - x1, py - y1)
    t = max(0.0, min(1.0, ((px - x1) * cx + (py - y1) * cy) / len_sq))
    return np.hypot(px - (x1 + t * cx), py - (y1 + t * cy))


def point_bbox_dist(px, py, bx1, by1, bx2, by2):
    dx = max(bx1 - px, px - bx2, 0.0)
    dy = max(by1 - py, py - by2, 0.0)
    return np.hypot(dx, dy)


def bbox_bbox_dist(a, b):
    dx = max(a[0] - b[2], b[0] - a[2], 0.0)
    dy = max(a[1] - b[3], b[1] - a[3], 0.0)
    return np.hypot(dx, dy)


def point_in_rings(px, py, rings) -> bool:
    """Even-odd rule over a list of rings (each a closed (k,2) array)."""
    inside = False
    for ring in rings:
        r = np.asarray(ring, np.float64)
        x1, y1 = r[:-1, 0], r[:-1, 1]
        x2, y2 = r[1:, 0], r[1:, 1]
        straddle = (y1 > py) != (y2 > py)
        with np.errstate(divide="ignore", invalid="ignore"):
            x_at = x1 + (py - y1) / (y2 - y1) * (x2 - x1)
        crossings = straddle & (px < x_at)
        inside ^= bool(np.sum(crossings) % 2)
    return inside


def point_rings_boundary_dist(px, py, rings) -> float:
    d = np.inf
    for ring in rings:
        r = np.asarray(ring, np.float64)
        for i in range(len(r) - 1):
            d = min(d, point_segment_dist(px, py, r[i, 0], r[i, 1], r[i + 1, 0], r[i + 1, 1]))
    return d


def point_polygon_dist(px, py, rings) -> float:
    """JTS Point.distance(Polygon): 0 inside the areal geometry."""
    if point_in_rings(px, py, rings):
        return 0.0
    return point_rings_boundary_dist(px, py, rings)


def _orient(ax, ay, bx, by, cx, cy):
    return (bx - ax) * (cy - ay) - (by - ay) * (cx - ax)


def segments_intersect(a, b) -> bool:
    d1 = _orient(b[0], b[1], b[2], b[3], a[0], a[1])
    d2 = _orient(b[0], b[1], b[2], b[3], a[2], a[3])
    d3 = _orient(a[0], a[1], a[2], a[3], b[0], b[1])
    d4 = _orient(a[0], a[1], a[2], a[3], b[2], b[3])
    return ((d1 > 0) != (d2 > 0)) and ((d3 > 0) != (d4 > 0))


def seg_seg_dist(a, b) -> float:
    if segments_intersect(a, b):
        return 0.0
    return min(
        point_segment_dist(a[0], a[1], b[0], b[1], b[2], b[3]),
        point_segment_dist(a[2], a[3], b[0], b[1], b[2], b[3]),
        point_segment_dist(b[0], b[1], a[0], a[1], a[2], a[3]),
        point_segment_dist(b[2], b[3], a[0], a[1], a[2], a[3]),
    )


def rings_to_segments(rings):
    segs = []
    for ring in rings:
        r = np.asarray(ring, np.float64)
        for i in range(len(r) - 1):
            segs.append((r[i, 0], r[i, 1], r[i + 1, 0], r[i + 1, 1]))
    return segs


def polygon_polygon_dist(rings_a, rings_b) -> float:
    """JTS Polygon.distance(Polygon): 0 if they intersect/contain."""
    a0 = np.asarray(rings_a[0], np.float64)[0]
    b0 = np.asarray(rings_b[0], np.float64)[0]
    if point_in_rings(a0[0], a0[1], rings_b) or point_in_rings(b0[0], b0[1], rings_a):
        return 0.0
    d = np.inf
    for sa in rings_to_segments(rings_a):
        for sb in rings_to_segments(rings_b):
            d = min(d, seg_seg_dist(sa, sb))
    return d


def scalar_decode_stream(records, cfg, grid, geometry="Point"):
    """THE SEED SCALAR DECODER, kept verbatim as a test-only oracle: raw
    lines/dicts -> spatial objects via one ``parse_spatial`` call per
    record, off-type records dropped — the per-record loop
    ``driver.decode_stream`` replaced with the chunk-vectorized
    ``decode_chunks`` seam. The batched path must emit byte-identical
    window contents when driven by either decoder."""
    from spatialflink_tpu.models import SpatialObject
    from spatialflink_tpu.streams.formats import parse_spatial

    needs_edges = geometry in ("Polygon", "LineString")
    for rec in records:
        obj = rec if isinstance(rec, SpatialObject) else parse_spatial(
            rec, cfg.format, grid, delimiter=cfg.delimiter,
            schema=cfg.csv_tsv_schema, geometry=geometry,
            **cfg.geojson_kwargs())
        if ((needs_edges and not hasattr(obj, "edge_array"))
                or (geometry == "Point" and not hasattr(obj, "x"))):
            continue  # off-type (the scalar path's drop rule)
        yield obj


def scalar_window_tables(records, cfg, grid, size_ms, slide_ms,
                         lateness_ms=0, geometry="Point"):
    """Seed scalar pipeline head: per-record decode + per-record
    ``WindowAssembler.add`` — yields ``(start, end, [records])`` with the
    emission ORDER the scalar loop produced (the timing oracle live tests
    compare consumption positions against)."""
    from spatialflink_tpu.runtime.windows import WindowAssembler, WindowSpec

    wa = WindowAssembler(WindowSpec.sliding(size_ms, slide_ms), lateness_ms)
    for obj in scalar_decode_stream(records, cfg, grid, geometry):
        yield from wa.add(obj.timestamp, obj)
    yield from wa.flush()


def sliding_window_table(ts_list, size, slide, lateness=0):
    """Independent re-derivation of the event-time sliding-window tables
    (Flink semantics + bounded out-of-orderness late drops): feeds the
    timestamps in order, drops records older than the running watermark
    (max seen - lateness), and assigns survivors to every aligned window
    containing them. Returns {window_start: [record_index, ...]} — the
    oracle the pane-incremental engine's window sets are checked against
    (it must match BOTH the per-record assembler and the pane buffer)."""
    out = {}
    max_ts = None
    for i, ts in enumerate(ts_list):
        ts = int(ts)
        if max_ts is not None and ts < max_ts - lateness:
            continue  # late
        if max_ts is None or ts > max_ts:
            max_ts = ts
        start = ts - (ts % slide)
        while start > ts - size:
            out.setdefault(start, []).append(i)
            start -= slide
    return out


def canon_windows(results, canon_record=None):
    """Canonical, order-insensitive window table from an iterator of
    WindowResults: [(start, end, sorted records)] — the shared shape every
    pane-equivalence assertion compares (pane merges may reorder records
    within a window; the SET per window is the contract)."""
    canon_record = canon_record or (lambda r: r)
    return [(r.window_start, r.window_end,
             sorted(canon_record(rec) for rec in r.records))
            for r in results]


def canon_point(p):
    """(obj_id, timestamp, rounded coords) — Point canonicalizer."""
    return (p.obj_id, p.timestamp, round(p.x, 9), round(p.y, 9))


def canon_knn_pair(t):
    """(obj_id, rounded distance) — kNN result-record canonicalizer."""
    return (t[0], round(float(t[1]), 6))


def knn(qx, qy, xs, ys, obj_ids, k, radius=None):
    """Top-k nearest objects with per-object dedup (keep min distance),
    mirroring KNNQuery's PQ + objID-dedup merge (knn/KNNQuery.java:204-300).
    Returns (obj_ids, dists) sorted ascending, at most k entries."""
    d = pp_dist(qx, qy, np.asarray(xs), np.asarray(ys))
    best = {}
    for oid, dist in zip(np.asarray(obj_ids), d):
        if radius is not None and dist > radius:
            continue
        if oid not in best or dist < best[oid]:
            best[oid] = dist
    items = sorted(best.items(), key=lambda kv: kv[1])[:k]
    return [o for o, _ in items], [float(v) for _, v in items]
