"""Generic real-bug lint rules (the in-tree subset of the ruff gate).

``ruff`` is the third-party half of the lint gate (``ruff.toml`` scopes
it to real-bug classes: undefined names, unused imports, f-string and
``is``-literal bugs). The container running tier-1 may not ship ruff, so
the three classes that are cheap to prove from a single module's AST are
implemented here and always run; ``tests/test_analysis.py`` runs ruff on
top whenever the binary exists.

- ``unused-import`` (F401): an imported binding never referenced and not
  re-exported via ``__all__`` or an ``import x as x`` alias.
- ``fstring-placeholder`` (F541): an f-string with no ``{}`` placeholder
  — almost always a formatting bug (a brace that never happened).
- ``is-literal`` (F632): ``is``/``is not`` against a str/bytes/num/tuple
  literal compares identity, not equality — interpreter-dependent.
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Set, Tuple

from spatialflink_tpu.analysis.core import (Finding, ModuleSource, Rule,
                                            register)


def _used_names(tree: ast.AST) -> Set[str]:
    used: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Name):
            used.add(node.id)
        elif isinstance(node, ast.Assign):
            # names exported through __all__ count as used
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == "__all__" \
                        and isinstance(node.value, (ast.List, ast.Tuple)):
                    used.update(
                        e.value for e in node.value.elts
                        if isinstance(e, ast.Constant)
                        and isinstance(e.value, str))
    return used


def _imports(tree: ast.AST) -> List[Tuple[str, bool, ast.AST]]:
    """(bound_name, explicit_reexport, node) for every import binding."""
    out: List[Tuple[str, bool, ast.AST]] = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                bound = a.asname or a.name.split(".")[0]
                out.append((bound, a.asname == a.name, node))
        elif isinstance(node, ast.ImportFrom):
            if node.module == "__future__":
                continue
            for a in node.names:
                if a.name == "*":
                    continue
                bound = a.asname or a.name
                out.append((bound, a.asname == a.name, node))
    return out


@register
class UnusedImportRule(Rule):
    id = "unused-import"
    contract = "no dead imports (they hide real dependencies and typos)"
    runtime_twin = "ruff F401 (when installed)"
    severity = "warning"
    scope = ("spatialflink_tpu/**",)

    def check(self, mod: ModuleSource,
              project=None) -> Iterator[Finding]:
        # __init__.py re-exports by convention (ruff per-file-ignore)
        if mod.relpath.endswith("__init__.py"):
            return
        used = _used_names(mod.tree)
        lines = mod.source.splitlines()
        for bound, reexport, node in _imports(mod.tree):
            if reexport or bound in used or bound == "_":
                continue
            line = lines[node.lineno - 1] if node.lineno <= len(lines) \
                else ""
            if "noqa" in line:
                continue
            yield self.finding(mod, node,
                               f"import {bound!r} is never used")


@register
class FStringPlaceholderRule(Rule):
    id = "fstring-placeholder"
    contract = "f-strings contain at least one placeholder"
    runtime_twin = "ruff F541 (when installed)"
    severity = "warning"
    scope = ("spatialflink_tpu/**",)

    def check(self, mod: ModuleSource,
              project=None) -> Iterator[Finding]:
        for node in ast.walk(mod.tree):
            # a FormattedValue's format_spec is itself a JoinedStr — only
            # real f-string literals count
            if isinstance(node, ast.JoinedStr) and not any(
                    isinstance(v, ast.FormattedValue)
                    for v in node.values) \
                    and not isinstance(mod.parent(node),
                                       ast.FormattedValue):
                yield self.finding(
                    mod, node,
                    "f-string without placeholders — either a missing "
                    "brace or a stray f prefix")


@register
class IsLiteralRule(Rule):
    id = "is-literal"
    contract = "`is` never compares against str/bytes/num/tuple literals"
    runtime_twin = "ruff F632 (when installed)"
    severity = "error"
    scope = ("spatialflink_tpu/**",)

    def check(self, mod: ModuleSource,
              project=None) -> Iterator[Finding]:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Compare):
                continue
            for op, comp in zip(node.ops, node.comparators):
                if not isinstance(op, (ast.Is, ast.IsNot)):
                    continue
                for side in (node.left, comp):
                    if isinstance(side, ast.Tuple) or (
                            isinstance(side, ast.Constant)
                            and isinstance(side.value,
                                           (str, bytes, int, float,
                                            complex))
                            and not isinstance(side.value, bool)):
                        yield self.finding(
                            mod, node,
                            "`is` against a literal tests identity, not "
                            "equality — use == / !=")
                        break
