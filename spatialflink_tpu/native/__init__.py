"""Build + ctypes bindings for the native ingest library.

The shared object is compiled on first use with the system g++ (cached next
to the source, keyed by source mtime) — no build system, no install step.
Everything degrades gracefully: ``lib()`` returns None when no compiler is
available and callers fall back to the pure-Python parsers.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import sys
import threading
from typing import Optional

import platform as _platform

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "ingest.cpp")
# cache key includes OS + arch so a binary from a foreign machine is never
# picked up (the .so files are gitignored, this guards stale copies)
_SO = os.path.join(
    _DIR,
    f"_ingest_{sys.platform}_{_platform.machine()}"
    f"_py{sys.version_info[0]}{sys.version_info[1]}.so",
)

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_failed = False

_c_double_p = ctypes.POINTER(ctypes.c_double)
_c_int64_p = ctypes.POINTER(ctypes.c_int64)
_c_uint64_p = ctypes.POINTER(ctypes.c_uint64)
_c_int32_p = ctypes.POINTER(ctypes.c_int32)
_c_long_p = ctypes.POINTER(ctypes.c_long)


def _build() -> bool:
    if os.path.exists(_SO) and os.path.getmtime(_SO) >= os.path.getmtime(_SRC):
        return True
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-o", _SO + ".tmp", _SRC]
    try:
        r = subprocess.run(cmd, capture_output=True, timeout=120)
    except (OSError, subprocess.TimeoutExpired):
        return False
    if r.returncode != 0:
        sys.stderr.write(f"native ingest build failed:\n{r.stderr.decode()[-2000:]}\n")
        return False
    os.replace(_SO + ".tmp", _SO)
    return True


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    lib.sf_parse_points_csv.restype = ctypes.c_long
    lib.sf_parse_points_csv.argtypes = [
        ctypes.c_char_p, ctypes.c_long, ctypes.c_char,
        ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
        _c_double_p, _c_double_p, _c_int64_p,
        _c_uint64_p, _c_int64_p, _c_int32_p,
        _c_int64_p, _c_long_p,
    ]
    lib.sf_parse_points_geojson.restype = ctypes.c_long
    lib.sf_parse_points_geojson.argtypes = [
        ctypes.c_char_p, ctypes.c_long,
        ctypes.c_char_p, ctypes.c_char_p,
        _c_double_p, _c_double_p, _c_int64_p,
        _c_uint64_p, _c_int64_p, _c_int32_p,
        _c_int64_p, _c_long_p,
    ]
    lib.sf_parse_wkt_geoms.restype = ctypes.c_long
    lib.sf_parse_wkt_geoms.argtypes = [
        ctypes.c_char_p, ctypes.c_long, ctypes.c_char,
        _c_int64_p, _c_uint64_p, _c_int64_p, _c_int32_p,
        ctypes.POINTER(ctypes.c_int8),
        _c_int64_p, _c_int32_p, _c_double_p,
        _c_int64_p, _c_int32_p,
        _c_double_p, _c_double_p,
        _c_int64_p, _c_long_p,
    ]
    lib.sf_parse_geojson_geoms.restype = ctypes.c_long
    lib.sf_parse_geojson_geoms.argtypes = [
        ctypes.c_char_p, ctypes.c_long,
        ctypes.c_char_p, ctypes.c_char_p,
        _c_int64_p, _c_uint64_p, _c_int64_p, _c_int32_p,
        ctypes.POINTER(ctypes.c_int8),
        _c_int64_p, _c_int32_p, _c_double_p,
        _c_int64_p, _c_int32_p,
        _c_double_p, _c_double_p,
        _c_int64_p, _c_long_p,
    ]
    return lib


def lib() -> Optional[ctypes.CDLL]:
    """The loaded native library, building it if needed; None if unavailable
    (or disabled with SPATIALFLINK_NATIVE=0)."""
    global _lib, _failed
    if os.environ.get("SPATIALFLINK_NATIVE", "1") in ("0", "off", "no"):
        return None
    if _lib is not None or _failed:
        return _lib
    with _lock:
        if _lib is not None or _failed:
            return _lib
        if not _build():
            _failed = True
            return None
        try:
            _lib = _bind(ctypes.CDLL(_SO))
        except OSError:
            # stale/corrupt binary: drop it and rebuild once from source
            try:
                os.remove(_SO)
            except OSError:
                pass
            if not _build():
                _failed = True
                return None
            try:
                _lib = _bind(ctypes.CDLL(_SO))
            except OSError:
                _failed = True
                return None
    return _lib


def available() -> bool:
    return lib() is not None
