"""Shapefile reader tests (reference: ShapeFileInputFormat.java).

The test synthesizes well-formed .shp bytes directly from the ESRI spec:
big-endian file/record headers, little-endian shape payloads.
"""

import struct

import numpy as np
import pytest

from spatialflink_tpu.index import UniformGrid
from spatialflink_tpu.models import MultiLineString, Point, Polygon
from spatialflink_tpu.streams.shapefile import (
    FILE_CODE,
    ShapefileError,
    read_shapefile,
)

GRID = UniformGrid(0.0, 10.0, 0.0, 10.0, num_grid_partitions=10)


def _point_payload(x, y):
    return struct.pack("<i", 1) + struct.pack("<dd", x, y)


def _poly_payload(shape_type, parts):
    """Polygon(5)/PolyLine(3) payload from a list of coord-lists."""
    num_points = sum(len(p) for p in parts)
    out = struct.pack("<i", shape_type)
    out += struct.pack("<dddd", 0, 0, 0, 0)  # bbox (unused by reader)
    out += struct.pack("<ii", len(parts), num_points)
    start = 0
    for p in parts:
        out += struct.pack("<i", start)
        start += len(p)
    for p in parts:
        for x, y in p:
            out += struct.pack("<dd", x, y)
    return out


def _build_shp(payloads, file_code=FILE_CODE):
    records = b""
    for i, payload in enumerate(payloads, start=1):
        records += struct.pack(">ii", i, len(payload) // 2) + payload
    total = 100 + len(records)
    header = struct.pack(">i", file_code) + b"\x00" * 20
    header += struct.pack(">i", total // 2)
    header += b"\x00" * (100 - len(header))
    return header + records


@pytest.fixture()
def shp_path(tmp_path):
    ring = [(1.0, 1.0), (4.0, 1.0), (4.0, 4.0), (1.0, 4.0), (1.0, 1.0)]
    hole = [(2.0, 2.0), (3.0, 2.0), (3.0, 3.0), (2.0, 3.0), (2.0, 2.0)]
    line_a = [(0.0, 0.0), (5.0, 5.0), (9.0, 5.0)]
    line_b = [(6.0, 6.0), (7.0, 8.0)]
    payloads = [
        _point_payload(2.5, 7.5),
        _poly_payload(5, [ring, hole]),
        _poly_payload(3, [line_a, line_b]),
        struct.pack("<i", 0),               # null shape: skipped silently
        struct.pack("<i", 8) + b"\x00" * 40,  # multipoint: unsupported
    ]
    p = tmp_path / "test.shp"
    p.write_bytes(_build_shp(payloads))
    return str(p)


def test_reads_all_supported_types(shp_path, capsys):
    objs = read_shapefile(shp_path, GRID)
    assert len(objs) == 3
    pt, poly, mls = objs
    assert isinstance(pt, Point) and (pt.x, pt.y) == (2.5, 7.5)
    assert pt.cell >= 0  # grid assignment happened
    assert isinstance(poly, Polygon)
    assert len(poly.rings) == 2  # shell + hole, split via Parts array
    assert poly.bbox == (1.0, 1.0, 4.0, 4.0)
    assert isinstance(mls, MultiLineString)
    assert [len(l.coords_list) for l in mls.lines] == [3, 2]
    assert "Unsupported shape type [8]" in capsys.readouterr().err


def test_record_ids_are_record_numbers(shp_path):
    objs = read_shapefile(shp_path, GRID)
    assert [o.obj_id for o in objs] == ["1", "2", "3"]


def test_rejects_non_shapefile(tmp_path):
    p = tmp_path / "bad.shp"
    p.write_bytes(_build_shp([], file_code=1234))
    with pytest.raises(ShapefileError, match="not a shapefile"):
        read_shapefile(str(p))


def test_truncated_header(tmp_path):
    p = tmp_path / "trunc.shp"
    p.write_bytes(b"\x00" * 50)
    with pytest.raises(ShapefileError, match="truncated header"):
        read_shapefile(str(p))


def test_driver_option_1001(shp_path):
    from spatialflink_tpu.config import Params
    from spatialflink_tpu.driver import run_option

    params = Params.from_yaml("conf/spatialflink-conf.yml")
    params.input1.grid_bbox = (0.0, 0.0, 10.0, 10.0)
    params.query.option = 1001
    objs = list(run_option(params, shp_path))
    assert len(objs) == 3
