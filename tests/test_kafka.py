"""Kafka transport shim: broker round-trips, consumer groups, delivery
semantics (at-least-once + idempotent windows ≙ the reference's EXACTLY_ONCE
producer, StreamingJob.java:512)."""

import numpy as np

from spatialflink_tpu.index import UniformGrid
from spatialflink_tpu.models import Point
from spatialflink_tpu.operators import (
    PointPointRangeQuery,
    QueryConfiguration,
    QueryType,
)
from spatialflink_tpu.streams import (
    IdempotentWindowSink,
    InMemoryBroker,
    KafkaLatencySink,
    KafkaSink,
    KafkaSource,
    parse_spatial,
)

GRID = UniformGrid(115.50, 117.60, 39.60, 41.10, num_grid_partitions=100)
BASE = 1_700_000_000_000


def _points(n, seed=0):
    rng = np.random.default_rng(seed)
    return [
        Point.create(float(rng.uniform(115.6, 117.5)),
                     float(rng.uniform(39.7, 41.0)), GRID,
                     obj_id=f"o{i % 7}", timestamp=BASE + i * 100)
        for i in range(n)
    ]


class TestBrokerRoundTrip:
    def test_produce_consume(self):
        b = InMemoryBroker()
        for i in range(10):
            b.produce("t", f"v{i}", key=f"k{i % 3}")
        got = list(KafkaSource(b, "t", "g1"))
        assert got == [f"v{i}" for i in range(10)]

    def test_serialized_spatial_round_trip(self):
        """Object -> KafkaSink (GeoJSON schema) -> topic -> KafkaSource ->
        parse: the reference's produce/consume conformance loop
        (Serialization.java <-> Deserialization.java)."""
        b = InMemoryBroker()
        sink = KafkaSink(b, "out", fmt="GeoJSON")
        pts = _points(5)
        for p in pts:
            sink.emit(p)
        parsed = [parse_spatial(v, "GeoJSON", GRID)
                  for v in KafkaSource(b, "out", "g")]
        assert [p.obj_id for p in parsed] == [p.obj_id for p in pts]
        np.testing.assert_allclose([p.x for p in parsed], [p.x for p in pts],
                                   rtol=1e-6)

    def test_consumer_groups_are_independent(self):
        b = InMemoryBroker()
        for i in range(4):
            b.produce("t", i)
        assert list(KafkaSource(b, "t", "a")) == [0, 1, 2, 3]
        assert list(KafkaSource(b, "t", "b")) == [0, 1, 2, 3]

    def test_committed_offset_resumes(self):
        """A second consumer in the same group continues where the first
        committed — the Kafka-consumer-group seek the checkpoint story
        defers to."""
        b = InMemoryBroker()
        for i in range(6):
            b.produce("t", i)
        first = []
        for v in KafkaSource(b, "t", "g", commit_every=1):
            first.append(v)
            if len(first) == 3:
                break  # "crash" mid-processing of the third record
        rest = list(KafkaSource(b, "t", "g"))
        # commit happens AFTER a record's processing completes, so the
        # in-flight third record (processing interrupted) is re-delivered —
        # at-least-once, never lost
        assert first == [0, 1, 2] and rest == [2, 3, 4, 5]

    def test_uncommitted_records_are_redelivered(self):
        """commit_every > consumed count means no commit happened: the next
        consumer sees everything again (at-least-once, never at-most-once)."""
        b = InMemoryBroker()
        for i in range(4):
            b.produce("t", i)
        got = []
        for v in KafkaSource(b, "t", "g", commit_every=100):
            got.append(v)
            if len(got) == 2:
                break  # crash before any commit
        assert list(KafkaSource(b, "t", "g")) == [0, 1, 2, 3]


class TestIdempotentDelivery:
    def test_duplicate_windows_collapse(self):
        from spatialflink_tpu.operators import WindowResult

        inner = []

        class L:
            def emit(self, r):
                inner.append(r)

            def close(self):
                pass

        sink = IdempotentWindowSink(L())
        w1 = WindowResult(0, 10, ["a"])
        w1_dup = WindowResult(0, 10, ["a"])
        w2 = WindowResult(10, 20, ["b"])
        for w in (w1, w1_dup, w2, w1_dup):
            sink.emit(w)
        assert len(inner) == 2
        assert sink.duplicates_suppressed == 2
        assert len(sink.snapshot()) == 2

    def test_value_differing_duplicates_observable(self):
        """First delivery wins in BOTH the table and the inner sink (they can
        never disagree); a re-delivery with a different value — upstream
        nondeterminism, not retry noise — is counted separately."""
        from spatialflink_tpu.operators import WindowResult

        inner = []

        class L:
            def emit(self, r):
                inner.append(r)

            def close(self):
                pass

        sink = IdempotentWindowSink(L())
        w = WindowResult(0, 10, ["a"])
        w_same = WindowResult(0, 10, ["a"])
        w_diff = WindowResult(0, 10, ["b"])
        for r in (w, w_same, w_diff):
            sink.emit(r)
        assert inner == [w]
        assert sink.snapshot() == {(0, 10, None): w}
        assert sink.duplicates_suppressed == 2
        assert sink.duplicates_value_differing == 1

    def test_ndarray_extras_compare_structurally(self):
        """A byte-identical heatmap re-delivery is NOT value-differing —
        plain == on ndarray-valued extras would raise and false-positive."""
        import numpy as np

        from spatialflink_tpu.operators import WindowResult

        hm = np.arange(6).reshape(2, 3)
        sink = IdempotentWindowSink()
        sink.emit(WindowResult(0, 10, [], extras={"heatmap": hm.copy()}))
        sink.emit(WindowResult(0, 10, [], extras={"heatmap": hm.copy()}))
        assert sink.duplicates_suppressed == 1
        assert sink.duplicates_value_differing == 0
        sink.emit(WindowResult(0, 10, [], extras={"heatmap": hm + 1}))
        assert sink.duplicates_value_differing == 1

    def test_replayed_pipeline_is_effectively_exactly_once(self):
        """Crash-and-replay: the consumer re-delivers uncommitted input, the
        pipeline recomputes the same windows, and the idempotent sink keyed
        by (window, cell) suppresses the duplicates — final output equals a
        single clean run."""
        b = InMemoryBroker()
        import json

        for p in _points(200, seed=3):
            b.produce("in", json.dumps({
                "geometry": {"type": "Point", "coordinates": [p.x, p.y]},
                "properties": {"oID": p.obj_id, "timestamp": p.timestamp},
            }))
        q = Point.create(116.5, 40.5, GRID)
        conf = QueryConfiguration(QueryType.WindowBased, window_size_ms=5_000,
                                  slide_ms=5_000)

        def run_pipeline(values, sink):
            stream = (parse_spatial(v, "GeoJSON", GRID) for v in values)
            for res in PointPointRangeQuery(conf, GRID).run(stream, q, 0.4):
                sink.emit(res)

        sink = IdempotentWindowSink()
        # attempt 1: processed every record but "crashed" before the offset
        # commit (raw fetch, no group bookkeeping touched)
        run_pipeline([r.value for r in b.fetch("in", 0, 10**9)], sink)
        # attempt 2: restart — committed offset is still 0, so the whole
        # topic re-delivers and every window recomputes
        run_pipeline(KafkaSource(b, "in", "g"), sink)
        assert sink.duplicates_suppressed > 0
        clean = IdempotentWindowSink()
        run_pipeline(KafkaSource(b, "in", "g2"), clean)  # fresh single run
        got = {k: len(v.records) for k, v in sink.snapshot().items()}
        want = {k: len(v.records) for k, v in clean.snapshot().items()}
        assert got == want


class TestLatencyTopic:
    def test_latency_values_produced(self):
        b = InMemoryBroker()
        sink = KafkaLatencySink(b, "latency", use_event_time=True)
        for p in _points(5):
            sink.emit(p)
        vals = b.topic_values("latency")
        assert len(vals) == 5 and all(isinstance(v, float) for v in vals)
