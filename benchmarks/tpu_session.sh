#!/bin/bash
# One-shot TPU measurement session: run when the axon tunnel answers.
# Captures every pending TPU row in priority order, banking each result as
# it lands so a mid-session wedge keeps whatever completed.
#
#   bash benchmarks/tpu_session.sh [outdir]
#
# Probe first (cheap):  timeout 50 python -c "import jax; jax.devices()"
# Priority order: headline bench (BENCH contract) -> canonical configs
# ledger -> multi-query scaling -> e2e pipeline. Each step has its own
# timeout; a hang moves on rather than killing the session.
set -u
cd "$(dirname "$0")/.."
OUT="${1:-benchmarks}"
mkdir -p "$OUT"
STAMP=$(date -u +%Y%m%dT%H%MZ)
echo "# TPU session $STAMP — each step banks to $OUT" >&2

run() {  # run <name> <timeout_s> <cmd...>
  local name=$1 t=$2; shift 2
  echo "== $name (timeout ${t}s)" >&2
  timeout "$t" "$@" 2> >(tail -5 >&2)
  local rc=$?
  [ $rc -ne 0 ] && echo "!! $name rc=$rc (continuing)" >&2
  return 0
}

# 1. headline (writes one JSON line; keep a copy for banking)
run headline 900 python bench.py | tee "$OUT/BENCH_tpu_${STAMP}.json"
# auto-bank: a valid TPU headline refreshes the banked row bench.py
# attaches to CPU-fallback runs (provenance stamped; invalid/CPU lines
# leave the existing banked row untouched)
python - "$OUT/BENCH_tpu_${STAMP}.json" <<'PY'
import json, sys, datetime

row = None
try:
    for line in open(sys.argv[1]):
        if not line.strip().startswith("{"):
            continue
        try:  # tolerate truncated/stray lines around the valid one
            r = json.loads(line)
        except ValueError:
            continue
        if r.get("valid_for_target"):
            row = r
except OSError:
    pass
if row is None:
    print("# no valid TPU headline; banked row unchanged", file=sys.stderr)
    raise SystemExit(0)
# ALWAYS record the latest valid run separately so a genuine TPU
# regression is visible (the best-row bank below is a max statistic)
latest = dict(row)
latest.pop("banked_tpu_run", None)
latest["measured_utc"] = datetime.datetime.now(
    datetime.timezone.utc).strftime("%Y-%m-%dT%H:%MZ")
with open("benchmarks/BENCH_tpu_latest.json", "w") as f:
    json.dump(latest, f)
print("# latest TPU headline -> benchmarks/BENCH_tpu_latest.json",
      file=sys.stderr)
# bench.py reads THIS fixed path (the script cd's to the repo root); only a
# better number may replace the banked best
path = "benchmarks/BENCH_tpu_r04_interactive.json"
try:
    best = json.load(open(path)).get("value", 0)
except (OSError, ValueError):
    best = 0
if row.get("value", 0) <= best:
    print(f"# headline {row.get('value')} does not beat banked {best}; "
          "banked row unchanged", file=sys.stderr)
    raise SystemExit(0)
row.pop("banked_tpu_run", None)
row["measured_utc"] = datetime.datetime.now(
    datetime.timezone.utc).strftime("%Y-%m-%dT%H:%MZ")
row["provenance"] = "tpu_session.sh auto-bank; see benchmarks/TPU_NOTES.md"
with open(path, "w") as f:
    json.dump(row, f)
print(f"# banked fresh TPU headline -> {path}", file=sys.stderr)
PY

# 2. canonical configs 1/3/4/5
run configs 1200 python benchmarks/bench_configs.py --scale full \
    --out "$OUT/RESULTS_tpu.json"

# 3. multi-query scaling
run multiquery 900 python benchmarks/bench_multi_query.py \
    --out "$OUT/RESULTS_multiquery_tpu.json"

# 4. e2e pipeline (+ multi-vs-jobs)
run e2e 1200 python benchmarks/bench_e2e.py \
    --out "$OUT/RESULTS_e2e_tpu.json"

# 5. bf16-vs-f32 join lattice A/B (TPU_NOTES §7 experiment; if bf16 wins,
#    flip the SPATIALFLINK_JOIN_LATTICE default and record the rows)
run bf16join 600 python benchmarks/exp_bf16_join.py \
    | tee "$OUT/RESULTS_bf16join_${STAMP}.json"

echo "# session done; update BASELINE.md from the fresh RESULTS_*.json," >&2
echo "# refresh benchmarks/BENCH_tpu_r04_interactive.json from the" >&2
echo "# headline line if it improved, and commit." >&2
