"""Window-batch kernels (range / knn / join / geom) vs NumPy oracles.

The oracle for every pruned kernel is an exhaustive scan — the same
methodology the reference implies with its naive-twin operators (SURVEY §4).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from spatialflink_tpu.index import UniformGrid
from spatialflink_tpu.models import EdgeGeomBatch, Point, PointBatch, Polygon, LineString
from spatialflink_tpu.models.batches import single_query_edges
from spatialflink_tpu.ops import geom as G
from spatialflink_tpu.ops import join as J
from spatialflink_tpu.ops import knn as K
from spatialflink_tpu.ops import range as R
from tests import oracles as O

RNG = np.random.default_rng(7)
GRID = UniformGrid(115.50, 117.60, 39.60, 41.10, num_grid_partitions=100)


def random_batch(n, n_objects=None, seed=0):
    rng = np.random.default_rng(seed)
    xs = rng.uniform(115.4, 117.7, n)  # a few points fall outside the grid
    ys = rng.uniform(39.5, 41.2, n)
    oid = rng.integers(0, n_objects or n, n).astype(np.int32)
    b = PointBatch.from_arrays(xs, ys, grid=GRID, obj_id=oid)
    return b, xs, ys, oid


class TestRangeFilter:
    QX, QY = 116.5, 40.5

    def _reference_mask(self, xs, ys, r):
        """Oracle: GN points always pass; CN points pass iff dist <= r;
        everything else fails."""
        q_cell, _ = GRID.assign_cell(self.QX, self.QY)
        gn = GRID.guaranteed_cells_mask(r, int(q_cell))
        cn = GRID.candidate_cells_mask(r, int(q_cell), gn)
        out = np.zeros(len(xs), bool)
        for i, (x, y) in enumerate(zip(xs, ys)):
            c, valid = GRID.assign_cell(x, y)
            if not valid:
                continue
            if gn[c]:
                out[i] = True
            elif cn[c]:
                out[i] = O.pp_dist(x, y, self.QX, self.QY) <= r
        return out

    @pytest.mark.parametrize("r", [0.05, 0.3, 0.5])
    def test_point_query_matches_oracle(self, r):
        b, xs, ys, _ = random_batch(800)
        q_cell, _ = GRID.assign_cell(self.QX, self.QY)
        mask, dists = R.range_filter_point(
            b, self.QX, self.QY, jnp.int32(q_cell), r,
            GRID.guaranteed_layers(r), GRID.candidate_layers(r), n=GRID.n,
        )
        want = self._reference_mask(xs, ys, r)
        got = np.asarray(mask)[: len(xs)]
        # tolerate f32-vs-f64 boundary flips: only exact-boundary points may differ
        diff = np.nonzero(got != want)[0]
        for i in diff:
            d = O.pp_dist(xs[i], ys[i], self.QX, self.QY)
            assert abs(d - r) < 1e-4, f"non-boundary disagreement at {i} (d={d})"

    def test_gn_bypasses_distance(self):
        # a GN point farther than r must still be selected (reference behavior)
        r = 0.5
        q_cell, _ = GRID.assign_cell(self.QX, self.QY)
        gn_layers = GRID.guaranteed_layers(r)
        assert gn_layers >= 0
        b, xs, ys, _ = random_batch(400)
        mask, dists = R.range_filter_point(
            b, self.QX, self.QY, jnp.int32(q_cell), r,
            gn_layers, GRID.candidate_layers(r), n=GRID.n,
        )
        # find any GN point with dist > r: it must be in the mask with inf dist
        gn_mask_np = GRID.guaranteed_cells_mask(r, int(q_cell))
        for i in range(len(xs)):
            c, valid = GRID.assign_cell(xs[i], ys[i])
            if valid and gn_mask_np[c] and O.pp_dist(xs[i], ys[i], self.QX, self.QY) > r:
                assert bool(mask[i])
                assert np.isinf(float(dists[i]))
                break
        else:
            pytest.skip("no far GN point in sample")

    def test_approximate_mode_skips_distance(self):
        r = 0.3
        b, xs, ys, _ = random_batch(400)
        q_cell, _ = GRID.assign_cell(self.QX, self.QY)
        mask, _ = R.range_filter_point(
            b, self.QX, self.QY, jnp.int32(q_cell), r,
            GRID.guaranteed_layers(r), GRID.candidate_layers(r),
            n=GRID.n, approximate=True,
        )
        nb = GRID.neighboring_cells_mask(r, int(q_cell))
        for i in range(len(xs)):
            c, valid = GRID.assign_cell(xs[i], ys[i])
            assert bool(mask[i]) == (bool(valid) and bool(nb[c]))

    def test_masks_variant_matches_point_variant(self):
        r = 0.3
        b, *_ = random_batch(500)
        q_cell, _ = GRID.assign_cell(self.QX, self.QY)
        gn = GRID.guaranteed_cells_mask(r, int(q_cell))
        cn = GRID.candidate_cells_mask(r, int(q_cell), gn)
        from spatialflink_tpu.ops.distances import pp_dist

        dists = pp_dist(b.x, b.y, self.QX, self.QY)
        got = R.range_filter_masks(b, jnp.asarray(gn), jnp.asarray(cn), dists, r)
        want, _ = R.range_filter_point(
            b, self.QX, self.QY, jnp.int32(q_cell), r,
            GRID.guaranteed_layers(r), GRID.candidate_layers(r), n=GRID.n,
        )
        assert (np.asarray(got) == np.asarray(want)).all()


class TestKnn:
    QX, QY = 116.5, 40.5

    @pytest.mark.parametrize("k", [1, 10, 50])
    def test_matches_oracle_no_pruning(self, k):
        b, xs, ys, oid = random_batch(700, n_objects=120)
        res = K.knn_point(
            b, self.QX, self.QY, jnp.int32(0), 0.0, GRID.n, n=GRID.n, k=k
        )
        want_ids, want_d = O.knn(self.QX, self.QY, xs, ys, oid, k)
        got_d = np.asarray(res.dist)[np.asarray(res.valid)]
        np.testing.assert_allclose(got_d, want_d[: len(got_d)], atol=1e-4)
        # ids must match wherever distances are not tied
        got_ids = np.asarray(res.obj_id)[np.asarray(res.valid)]
        for i, (gi, wi) in enumerate(zip(got_ids, want_ids)):
            if gi != wi:
                assert abs(want_d[i] - got_d[i]) < 1e-4  # tie or f32 flip

    def test_dedup_keeps_min_distance(self):
        # same object appears twice; result must carry the nearer distance
        xs = np.array([116.51, 117.0])
        ys = np.array([40.5, 40.5])
        b = PointBatch.from_arrays(xs, ys, grid=GRID, obj_id=np.array([5, 5], np.int32))
        res = K.knn_point(b, self.QX, self.QY, jnp.int32(0), 0.0, GRID.n, n=GRID.n, k=10)
        assert int(res.valid.sum()) == 1
        assert int(res.obj_id[0]) == 5
        assert float(res.dist[0]) == pytest.approx(0.01, abs=1e-4)

    def test_cell_pruning_limits_candidates(self):
        r = 0.1
        b, xs, ys, oid = random_batch(700, n_objects=500)
        q_cell, _ = GRID.assign_cell(self.QX, self.QY)
        res = K.knn_point(
            b, self.QX, self.QY, jnp.int32(q_cell), r,
            GRID.candidate_layers(r), n=GRID.n, k=20,
        )
        nb = GRID.neighboring_cells_mask(r, int(q_cell))
        # oracle restricted to neighboring cells
        keep = []
        for i in range(len(xs)):
            c, valid = GRID.assign_cell(xs[i], ys[i])
            if valid and nb[c]:
                keep.append(i)
        want_ids, want_d = O.knn(self.QX, self.QY, xs[keep], ys[keep], oid[keep], 20)
        got_d = np.asarray(res.dist)[np.asarray(res.valid)]
        np.testing.assert_allclose(got_d, want_d, atol=1e-4)

    def test_enforce_radius(self):
        b, xs, ys, oid = random_batch(500, n_objects=400)
        r = 0.2
        res = K.knn_point(
            b, self.QX, self.QY, jnp.int32(0), r, GRID.n,
            n=GRID.n, k=50, enforce_radius=True,
        )
        got_d = np.asarray(res.dist)[np.asarray(res.valid)]
        assert (got_d <= r + 1e-4).all()
        want_ids, want_d = O.knn(self.QX, self.QY, xs, ys, oid, 50, radius=r)
        assert len(got_d) == len(want_d)

    def test_merge_partials(self):
        b1, x1, y1, o1 = random_batch(300, n_objects=80, seed=1)
        b2, x2, y2, o2 = random_batch(300, n_objects=80, seed=2)
        r1 = K.knn_point(b1, self.QX, self.QY, jnp.int32(0), 0.0, GRID.n, n=GRID.n, k=10)
        r2 = K.knn_point(b2, self.QX, self.QY, jnp.int32(0), 0.0, GRID.n, n=GRID.n, k=10)
        merged = K.merge_knn([r1, r2], 10)
        want_ids, want_d = O.knn(
            self.QX, self.QY,
            np.concatenate([x1, x2]), np.concatenate([y1, y2]),
            np.concatenate([o1, o2]), 10,
        )
        got_d = np.asarray(merged.dist)[np.asarray(merged.valid)]
        np.testing.assert_allclose(got_d, want_d[: len(got_d)], atol=1e-4)


class TestJoin:
    def test_matches_oracle(self):
        r = 0.1
        a, ax, ay, _ = random_batch(300, seed=3)
        b, bx, by, _ = random_batch(100, seed=4)
        L = GRID.candidate_layers(r)
        cx = (GRID.min_x + GRID.max_x) / 2
        cy = (GRID.min_y + GRID.max_y) / 2
        m = np.asarray(J.join_mask(a, b, r, L, cx, cy, n=GRID.n))
        nb_masks = {}
        for j in range(len(bx)):
            c, valid = GRID.assign_cell(bx[j], by[j])
            nb_masks[j] = GRID.neighboring_cells_mask(r, int(c)) if valid else None
        for i in range(len(ax)):
            ca, va = GRID.assign_cell(ax[i], ay[i])
            for j in range(len(bx)):
                want = False
                if va and nb_masks[j] is not None and nb_masks[j][ca]:
                    d = O.pp_dist(ax[i], ay[i], bx[j], by[j])
                    want = d <= r
                if m[i, j] != want:
                    d = O.pp_dist(ax[i], ay[i], bx[j], by[j])
                    assert abs(d - r) < 1e-3, f"non-boundary join mismatch {i},{j}"

    def test_counts_match_mask(self):
        r = 0.15
        a, *_ = random_batch(512, seed=5)
        b, *_ = random_batch(256, seed=6)
        L = GRID.candidate_layers(r)
        cx = (GRID.min_x + GRID.max_x) / 2
        cy = (GRID.min_y + GRID.max_y) / 2
        m = np.asarray(J.join_mask(a, b, r, L, cx, cy, n=GRID.n))
        per_a, total = J.join_counts(a, b, r, L, cx, cy, n=GRID.n, tile=256)
        assert (np.asarray(per_a) == m.sum(axis=1)).all()
        assert int(total) == m.sum()

    def test_pairs_host_extraction(self):
        r = 0.1
        a, ax, ay, _ = random_batch(300, seed=8)
        b, bx, by, _ = random_batch(300, seed=9)
        pairs = set()
        for ai, bi in J.join_pairs_host(a, b, r, GRID, tile=128):
            pairs.update(zip(ai.tolist(), bi.tolist()))
        # every pair satisfies the distance predicate
        for i, j in list(pairs)[:200]:
            assert O.pp_dist(ax[i], ay[i], bx[j], by[j]) <= r + 1e-3

    def test_bf16_superset_contains_f32_mask(self):
        """Every pair the f32 lattice keeps survives the bf16 superset (the
        margin guarantee), across radii incl. small ones."""
        for r, seeds in ((0.1, (3, 4)), (0.02, (5, 6)), (0.5, (7, 8))):
            a, *_ = random_batch(300, seed=seeds[0])
            b, *_ = random_batch(200, seed=seeds[1])
            L = GRID.candidate_layers(r)
            cx = (GRID.min_x + GRID.max_x) / 2
            cy = (GRID.min_y + GRID.max_y) / 2
            exact = np.asarray(J.join_mask(a, b, r, L, cx, cy, n=GRID.n))
            sup = np.asarray(J.join_mask_bf16_superset(
                a, b, r, L, cx, cy, n=GRID.n))
            assert (sup | ~exact).all(), f"superset violated at r={r}"

    @pytest.mark.parametrize("extent", (1.0, 60.0))
    def test_bf16_margin_bounds_error(self, extent):
        """The published (margin, slack_sq) pair really covers the bf16
        lattice error — squared-space guarantee d2_bf16 <= (d+m)^2 + s2
        against an f64 oracle, at Beijing extent AND a wide-extent grid
        (where the f32 accumulation term scales with X^2 and a fixed
        distance-space slack would fail)."""
        from spatialflink_tpu.models import PointBatch

        rng = np.random.default_rng(11)
        g = UniformGrid(0.0, 2 * extent, 0.0, 2 * extent,
                        num_grid_partitions=50)
        ax = rng.uniform(0, 2 * extent, 256)
        ay = rng.uniform(0, 2 * extent, 256)
        bx = rng.uniform(0, 2 * extent, 256)
        by = rng.uniform(0, 2 * extent, 256)
        a = PointBatch.from_arrays(ax, ay, grid=g)
        b = PointBatch.from_arrays(bx, by, grid=g)
        cx = cy = extent
        d2_b = np.asarray(
            J.pairwise_dist2_bf16(a.x, a.y, b.x, b.y, cx, cy))
        m, s2 = J.bf16_distance_margin(a.x, a.y, b.x, b.y, a.valid,
                                       b.valid, cx, cy)
        m, s2 = float(m), float(s2)
        # f64 oracle distances over the stored (f32) batch coordinates
        axd = np.asarray(a.x, np.float64) - cx
        ayd = np.asarray(a.y, np.float64) - cy
        bxd = np.asarray(b.x, np.float64) - cx
        byd = np.asarray(b.y, np.float64) - cy
        d_true = np.sqrt((axd[:, None] - bxd[None, :]) ** 2
                         + (ayd[:, None] - byd[None, :]) ** 2)
        valid = np.asarray(a.valid)[:, None] & np.asarray(b.valid)[None, :]
        bound = (d_true + m) ** 2 + s2
        assert (d2_b[valid] <= bound[valid]).all(), extent

    def test_lattice_strategy_env_validation(self, monkeypatch):
        monkeypatch.setenv("SPATIALFLINK_JOIN_LATTICE", "bfloat16")
        with pytest.raises(ValueError, match="SPATIALFLINK_JOIN_LATTICE"):
            J._lattice_strategy()
        monkeypatch.setenv("SPATIALFLINK_JOIN_LATTICE", " BF16 ")
        assert J._lattice_strategy() == "bf16"

    def test_bf16_pairs_match_f32_pairs(self, monkeypatch):
        """SPATIALFLINK_JOIN_LATTICE=bf16 yields the same pair sets as the
        f32 lattice (superset + exact re-check), incl. through the
        over-budget prefilter path."""
        r = 0.1
        a, *_ = random_batch(300, seed=8)
        b, *_ = random_batch(300, seed=9)

        def pairs(budget=None):
            out = set()
            kw = {} if budget is None else {"lattice_budget": budget}
            for ai, bi in J.join_pairs_host(a, b, r, GRID, tile=128, **kw):
                out.update(zip(ai.tolist(), bi.tolist()))
            return out

        monkeypatch.delenv("SPATIALFLINK_JOIN_LATTICE", raising=False)
        want = pairs()
        want_budget = pairs(budget=1)
        monkeypatch.setenv("SPATIALFLINK_JOIN_LATTICE", "bf16")
        assert pairs() == want
        assert pairs(budget=1) == want_budget == want

    def test_pairwise_dist2_precision_with_centering(self):
        # Close points at degree magnitude. The error floor is the f32
        # *storage* quantization of the inputs (~7.6e-6 deg at |x|~116, i.e.
        # <1 m); the centered matmul itself adds nothing beyond it.
        ax = np.array([116.5000, 116.5001], np.float64)
        ay = np.array([40.5000, 40.5000], np.float64)
        d2 = np.asarray(J.pairwise_dist2(
            jnp.asarray(ax, jnp.float32), jnp.asarray(ay, jnp.float32),
            jnp.asarray(ax, jnp.float32), jnp.asarray(ay, jnp.float32),
            116.55, 40.35,
        ))
        assert np.sqrt(d2[0, 1]) == pytest.approx(1e-4, abs=1.6e-5)
        # without centering the cancellation would be ~2e-3 — catastrophically
        # larger than the 1e-4 separation; verify centering keeps us at the floor
        d2_raw = np.asarray(J.pairwise_dist2(
            jnp.asarray(ax, jnp.float32), jnp.asarray(ay, jnp.float32),
            jnp.asarray(ax, jnp.float32), jnp.asarray(ay, jnp.float32),
        ))
        assert abs(np.sqrt(d2[0, 1]) - 1e-4) <= abs(np.sqrt(d2_raw[0, 1]) - 1e-4)


class TestGeomKernels:
    POLY = Polygon.create(
        [[(116.0, 40.0), (116.4, 40.0), (116.4, 40.4), (116.0, 40.4)],
         [(116.1, 40.1), (116.3, 40.1), (116.3, 40.3), (116.1, 40.3)]],
        GRID, obj_id="donut",
    )
    TRI = Polygon.create([[(117.0, 40.0), (117.2, 40.0), (117.1, 40.2)]], GRID, obj_id="tri")
    LINE = LineString.create([(116.6, 40.6), (116.8, 40.8), (117.0, 40.6)], GRID, obj_id="ls")

    def batch(self):
        return EdgeGeomBatch.from_objects([self.POLY, self.TRI, self.LINE], GRID)

    def test_points_to_geoms_dist(self):
        gb = self.batch()
        pts = PointBatch.from_arrays(
            np.array([116.2, 116.05, 117.1, 116.8]),
            np.array([40.2, 40.2, 40.05, 40.9]),
            grid=GRID,
        )
        d = np.asarray(G.points_to_geoms_dist(pts, gb))
        # point in donut hole -> boundary dist 0.1 ; point in donut body -> 0
        assert d[0, 0] == pytest.approx(0.1, abs=1e-3)
        assert d[1, 0] == 0.0
        # point inside triangle -> 0
        assert d[2, 1] == 0.0
        # point above the linestring apex
        want = O.point_segment_dist(116.8, 40.9, 116.6, 40.6, 116.8, 40.8)
        assert d[3, 2] == pytest.approx(want, abs=1e-3)

    def test_single_geom_variant(self):
        gb = self.batch()
        pts = PointBatch.from_arrays(
            np.array([116.2, 116.5]), np.array([40.2, 40.5]), grid=GRID
        )
        e, m = single_query_edges(self.POLY)
        d = np.asarray(G.points_to_single_geom_dist(pts, jnp.asarray(e), jnp.asarray(m), True))
        full = np.asarray(G.points_to_geoms_dist(pts, gb))[:, 0]
        np.testing.assert_allclose(d, full, atol=1e-5)

    def test_geoms_to_single_geom(self):
        gb = self.batch()
        q = Polygon.create([[(116.35, 40.35), (116.6, 40.35), (116.6, 40.6), (116.35, 40.6)]],
                           GRID, obj_id="q")
        e, m = single_query_edges(q)
        d = np.asarray(G.geoms_to_single_geom_dist(gb, jnp.asarray(e), jnp.asarray(m), True))
        # query overlaps the donut shell corner -> 0
        assert d[0] == 0.0
        want = O.polygon_polygon_dist([np.asarray(self.TRI.rings[0])], [np.asarray(q.rings[0])])
        assert d[1] == pytest.approx(want, abs=1e-3)

    def test_containment_both_ways(self):
        inner = Polygon.create([[(116.45, 40.45), (116.5, 40.45), (116.5, 40.5), (116.45, 40.5)]],
                               GRID, obj_id="inner")
        outer = Polygon.create([[(116.4, 40.4), (116.6, 40.4), (116.6, 40.6), (116.4, 40.6)]],
                               GRID, obj_id="outer")
        gb = EdgeGeomBatch.from_objects([inner], GRID)
        e, m = single_query_edges(outer)
        d = np.asarray(G.geoms_to_single_geom_dist(gb, jnp.asarray(e), jnp.asarray(m), True))
        assert d[0] == 0.0  # inner fully inside query
        gb2 = EdgeGeomBatch.from_objects([outer], GRID)
        e2, m2 = single_query_edges(inner)
        d2 = np.asarray(G.geoms_to_single_geom_dist(gb2, jnp.asarray(e2), jnp.asarray(m2), True))
        assert d2[0] == 0.0  # query fully inside batch geometry

    def test_gn_subset_rule(self):
        gb = self.batch()
        # target mask covering ALL cells -> every geometry passes the all-rule
        all_mask = jnp.ones(GRID.num_cells, bool)
        allw = np.asarray(G.geom_cells_all_within(gb.cells, gb.cells_mask, all_mask))
        assert allw[: 3].all()
        # empty mask -> nothing passes
        none = np.asarray(G.geom_cells_all_within(gb.cells, gb.cells_mask,
                                                  jnp.zeros(GRID.num_cells, bool)))
        assert not none.any()

    def test_bbox_prefilter(self):
        gb = self.batch()
        q_bbox = jnp.asarray(np.array([116.45, 40.0, 116.55, 40.1], np.float32))
        d = np.asarray(G.geoms_bbox_dist(gb, q_bbox))
        want0 = O.bbox_bbox_dist(np.asarray(self.POLY.bbox), [116.45, 40.0, 116.55, 40.1])
        assert d[0] == pytest.approx(want0, abs=1e-3)


class TestReviewRegressions:
    """Regressions for code-review findings on the phase-2 kernels."""

    def test_join_counts_small_batch_default_tile(self):
        # batches smaller than the default tile must not crash (tile clamps)
        a, *_ = random_batch(100, seed=11)
        b, *_ = random_batch(100, seed=12)
        cx = (GRID.min_x + GRID.max_x) / 2
        cy = (GRID.min_y + GRID.max_y) / 2
        per_a, total = J.join_counts(a, b, 0.1, GRID.candidate_layers(0.1), cx, cy, n=GRID.n)
        m = np.asarray(J.join_mask(a, b, 0.1, GRID.candidate_layers(0.1), cx, cy, n=GRID.n))
        assert int(total) == m.sum()

    def test_multipolygon_component_containment(self):
        # one component far away, the other strictly inside the query:
        # JTS distance is 0; the vertex test must scan all components
        from spatialflink_tpu.models import MultiPolygon

        mp = MultiPolygon.create(
            [[[(117.0, 41.0), (117.05, 41.0), (117.05, 41.05), (117.0, 41.05)]],
             [[(116.45, 40.45), (116.5, 40.45), (116.5, 40.5), (116.45, 40.5)]]],
            GRID, obj_id="mp",
        )
        outer = Polygon.create(
            [[(116.4, 40.4), (116.6, 40.4), (116.6, 40.6), (116.4, 40.6)]], GRID
        )
        gb = EdgeGeomBatch.from_objects([mp], GRID)
        e, m = single_query_edges(outer)
        d = np.asarray(G.geoms_to_single_geom_dist(gb, jnp.asarray(e), jnp.asarray(m), True))
        assert d[0] == 0.0

    def test_padded_slot_not_zero_when_query_contains_origin(self):
        # padded geometry slots have all-zero edges; a query polygon covering
        # (0,0) must NOT produce distance 0 for them
        tri = Polygon.create([[(117.0, 40.0), (117.2, 40.0), (117.1, 40.2)]], GRID)
        gb = EdgeGeomBatch.from_objects([tri], GRID, pad=8)
        origin_poly_edges = np.array(
            [[-1, -1, 1, -1], [1, -1, 1, 1], [1, 1, -1, 1], [-1, 1, -1, -1]], np.float32
        )
        d = np.asarray(G.geoms_to_single_geom_dist(
            gb, jnp.asarray(origin_poly_edges), jnp.ones(4, bool), True
        ))
        assert (d[1:] > 1e18).all()  # padded slots stay at the +inf sentinel


class TestTopkStrategies:
    """The three exact selection strategies (full sort / grouped / prefilter)
    must agree with each other and the oracle on any input — including
    adversarial duplicate-heavy streams that force the prefilter fallback."""

    def _check(self, obj_id, dist, eligible, k):
        # exhaustive per-object min oracle
        best = {}
        for o, d, e in zip(obj_id, dist, eligible):
            if e and (int(o) not in best or d < best[int(o)]):
                best[int(o)] = float(np.float32(d))
        want_d = sorted(best.values())[:k]
        for strat in ("sort", "grouped", "prefilter", "approx_verified",
                      "auto"):
            got = K.topk_by_distance(
                jnp.asarray(obj_id), jnp.asarray(dist), jnp.asarray(eligible),
                k, strategy=strat)
            gi = np.asarray(got.obj_id)[np.asarray(got.valid)]
            gd = np.asarray(got.dist)[np.asarray(got.valid)]
            np.testing.assert_allclose(gd, want_d, atol=0, err_msg=strat)
            assert len(set(gi)) == len(gi), strat  # ids distinct
            for a, d in zip(gi, gd):
                assert best[int(a)] == d, strat  # each id carries its true min

    @pytest.mark.parametrize("k", [1, 10, 50])
    @pytest.mark.parametrize("n", [100, 1000, 70000])
    def test_random(self, n, k):
        rng = np.random.default_rng(n + k)
        oid = rng.integers(0, max(4, n // 4), n).astype(np.int32)
        d = rng.uniform(0, 1, n).astype(np.float32)
        elig = rng.uniform(0, 1, n) < 0.7
        self._check(oid, d, elig, k)

    def test_one_object_dominates_forces_fallback(self):
        # one object owns the 5000 nearest points -> top-m prefilter holds
        # < k distinct ids -> exactness check fails -> full-sort fallback
        n, k = 8192, 50
        rng = np.random.default_rng(0)
        d = np.concatenate([
            np.linspace(0.0, 0.1, 5000, dtype=np.float32),
            rng.uniform(0.5, 1.0, n - 5000).astype(np.float32)])
        oid = np.concatenate([
            np.zeros(5000, np.int32),
            rng.integers(1, 200, n - 5000).astype(np.int32)])
        self._check(oid, d, np.ones(n, bool), k)

    def test_fewer_eligible_than_k(self):
        n = 4096
        oid = np.arange(n, dtype=np.int32)
        d = np.linspace(0, 1, n, dtype=np.float32)
        elig = np.zeros(n, bool)
        elig[[5, 17, 99]] = True
        self._check(oid, d, elig, 50)

    def test_none_eligible(self):
        n = 1024
        self._check(np.arange(n, dtype=np.int32),
                    np.linspace(0, 1, n, dtype=np.float32),
                    np.zeros(n, bool), 10)

    def test_all_same_distance_ties(self):
        n = 2048
        oid = np.arange(n, dtype=np.int32) % 500
        d = np.full(n, 0.25, np.float32)
        self._check(oid, d, np.ones(n, bool), 20)

    def test_approx_strategy_high_recall_on_random(self):
        # approx is allowed recall < 1 but must be near-exact on
        # well-spread random data (and exact on CPU's fallback impl)
        n, k = 50_000, 50
        rng = np.random.default_rng(5)
        oid = rng.integers(0, n // 4, n).astype(np.int32)
        d = rng.uniform(0, 1, n).astype(np.float32)
        elig = np.ones(n, bool)
        want = K.topk_by_distance(jnp.asarray(oid), jnp.asarray(d),
                                  jnp.asarray(elig), k, strategy="sort")
        got = K.topk_by_distance(jnp.asarray(oid), jnp.asarray(d),
                                 jnp.asarray(elig), k, strategy="approx")
        wd = np.asarray(want.dist)[np.asarray(want.valid)]
        gd = np.asarray(got.dist)[np.asarray(got.valid)]
        overlap = len(np.intersect1d(np.asarray(want.obj_id)[np.asarray(want.valid)],
                                     np.asarray(got.obj_id)[np.asarray(got.valid)]))
        if jax.default_backend() == "cpu":
            # CPU lowers approx_min_k to the exact reduction, so the strict
            # bounds hold; on TPU PartialReduce's recall target (<1) makes
            # them legitimately violable — only sanity-check shape there
            assert overlap >= int(0.9 * k), overlap
            assert gd[0] == wd[0]
        else:
            assert overlap >= int(0.5 * k), overlap
        assert len(gd) <= k and (np.diff(gd) >= 0).all()

    def test_approx_verified_small_m_falls_back_exact(self):
        # m smaller than the duplicate-heavy head -> certificate fails ->
        # full-sort fallback -> still exact (recall misses cost a recompute,
        # never a wrong answer)
        n, k = 8192, 50
        rng = np.random.default_rng(9)
        d = np.concatenate([
            np.linspace(0.0, 0.1, 4000, dtype=np.float32),
            rng.uniform(0.5, 1.0, n - 4000).astype(np.float32)])
        oid = np.concatenate([
            np.zeros(4000, np.int32),
            rng.integers(1, 300, n - 4000).astype(np.int32)])
        want = K.topk_by_distance(jnp.asarray(oid), jnp.asarray(d),
                                  jnp.ones(n, bool), k, strategy="sort")
        got = K._topk_approx_verified(jnp.asarray(oid), jnp.asarray(d),
                                      jnp.ones(n, bool), k, m=64)
        np.testing.assert_array_equal(np.asarray(got.obj_id),
                                      np.asarray(want.obj_id))
        np.testing.assert_array_equal(np.asarray(got.dist),
                                      np.asarray(want.dist))

    def test_auto_dispatches_partialreduce_path_on_tpu(self, monkeypatch):
        # "auto" on TPU must route large windows to the approx_verified
        # (PartialReduce) path — the sweep-measured winner (TPU_NOTES.md) —
        # and the result must stay exact. Backend is monkeypatched; CPU's
        # approx_min_k fallback keeps the kernel runnable here.
        calls = []
        orig = K._topk_approx_verified

        def spy(*a, **kw):
            calls.append(1)
            return orig(*a, **kw)

        monkeypatch.setattr(K, "_topk_approx_verified", spy)
        monkeypatch.setattr(K.jax, "default_backend", lambda: "tpu")
        n, k = K._GROUPED_MIN_N + 512, 50
        rng = np.random.default_rng(11)
        oid = rng.integers(0, n // 4, n).astype(np.int32)
        d = rng.uniform(0, 1, n).astype(np.float32)
        got = K.topk_by_distance(jnp.asarray(oid), jnp.asarray(d),
                                 jnp.ones(n, bool), k, strategy="auto")
        assert calls, "auto on TPU did not dispatch approx_verified"
        want = K.topk_by_distance(jnp.asarray(oid), jnp.asarray(d),
                                  jnp.ones(n, bool), k, strategy="sort")
        np.testing.assert_array_equal(np.asarray(got.obj_id),
                                      np.asarray(want.obj_id))
        np.testing.assert_array_equal(np.asarray(got.dist),
                                      np.asarray(want.dist))

    def test_unknown_strategy_raises(self):
        with pytest.raises(ValueError):
            K.topk_by_distance(jnp.zeros(8, jnp.int32), jnp.zeros(8),
                               jnp.ones(8, bool), 2, strategy="bogus")
