"""Shared benchmark-harness plumbing.

Importers reach this as ``benchmarks._common``, which already requires the
repo root on sys.path (each harness script inserts it before importing); the
``from bench import ...`` below resolves through that same root entry."""

from __future__ import annotations

import os
import sys


def bench_telemetry():
    """An in-memory telemetry session (no reporter thread) for harness
    scripts: ``with bench_telemetry() as tel: ...; attach_telemetry(row,
    tel)``. Spans recorded by the pipeline under test (ingest / window /
    kernel / merge / sink) land in the session automatically."""
    from spatialflink_tpu.utils.telemetry import telemetry_session

    return telemetry_session()


def attach_telemetry(row: dict, tel) -> dict:
    """Attach the final telemetry snapshot to a bench result row, so
    BENCH_*/RESULTS_* files carry per-stage breakdowns next to the
    end-to-end numbers."""
    row["telemetry"] = tel.snapshot()
    return row


def settle_backend() -> None:
    """The axon sitecustomize force-sets jax_platforms='axon,cpu' in every
    interpreter, so the JAX_PLATFORMS env var alone cannot keep a process
    off a wedged accelerator tunnel — honor it at the config level, and
    when no platform was requested, probe the default backend the way
    bench.py does so a wedged tunnel downgrades to CPU instead of hanging
    the harness."""
    req = os.environ.get("JAX_PLATFORMS", "")
    from bench import _force_cpu, _probe_default_backend_ok

    if req and "axon" not in req:
        import jax

        jax.config.update("jax_platforms", req)
    elif not _probe_default_backend_ok(attempts=2):
        print("warning: backend probe failed; falling back to CPU",
              file=sys.stderr)
        _force_cpu()
