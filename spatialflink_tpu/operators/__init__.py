"""Spatial query operators (reference: ``spatialOperators/``).

Operator classes mirror the reference API surface: construct with a
:class:`QueryConfiguration` + grid(s), then ``run(stream, query, radius,...)``.
``run`` consumes an iterator of spatial objects and yields result events —
per sealed window in window mode, per micro-batch in real-time mode.

The execution model differs deliberately (SURVEY §7): instead of Flink's
per-cell keyed window operators + shuffles, each window is one padded device
batch evaluated by a masked kernel (spatialflink_tpu.ops), optionally
sharded over a device mesh (spatialflink_tpu.parallel).
"""

from spatialflink_tpu.operators.base import (
    QueryConfiguration,
    QueryType,
    WindowResult,
)
from spatialflink_tpu.operators.range_query import PointPointRangeQuery
from spatialflink_tpu.operators.knn_query import PointPointKNNQuery
from spatialflink_tpu.operators.join_query import PointPointJoinQuery

__all__ = [
    "QueryConfiguration",
    "QueryType",
    "WindowResult",
    "PointPointRangeQuery",
    "PointPointKNNQuery",
    "PointPointJoinQuery",
]
