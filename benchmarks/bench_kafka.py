"""Broker-path throughput: sustained records/s for the SAME bounded query
through the three ``--kafka`` execution paths the driver offers, plus the
file-replay reference point — quantifying what each decode/replay tier buys
(the reference's pipelines are all Kafka-fed, ``StreamingJob.java:473``):

- ``record``:  per-record ``parse_spatial`` in the commit tap (the
  fallback when a chunk cannot ride the native parser; live follow mode
  also uses chunked decode, with starvation-sentinel flushes bounding the
  buffering latency to one poll cycle)
- ``chunked``: the default bounded drain — raw records batch through the
  native bulk parser in ``WindowCommitTap`` chunks
- ``bulk``:    ``--kafka --bulk`` — one lazy topic drain through the
  native ingest + columnar windowing (``run_option_bulk``)
- ``file``:    ``--bulk`` file replay of the same records (no broker)

All four produce identical windows (asserted). Usage:

    python benchmarks/bench_kafka.py [--n N] [--out PATH]

Emits one JSON line per path and writes the table to
``benchmarks/RESULTS_kafka_<backend>.json``.
"""

from __future__ import annotations

import argparse
import contextlib
import io
import json
import os
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from benchmarks._common import settle_backend  # noqa: E402


def _rows(n: int):
    rng = np.random.default_rng(7)
    t0 = 1_700_000_000_000
    xs = rng.uniform(115.6, 117.5, n)
    ys = rng.uniform(39.7, 41.0, n)
    return [f"o{i % 512},{t0 + i * 5},{xs[i]:.6f},{ys[i]:.6f}"
            for i in range(n)]


def _conf_file(tmp: str, url: str) -> str:
    import yaml

    with open(os.path.join(os.path.dirname(__file__), "..", "conf",
                           "spatialflink-conf.yml")) as f:
        d = yaml.safe_load(f)
    d["kafkaBootStrapServers"] = url
    d["inputStream1"]["format"] = "CSV"
    path = os.path.join(tmp, url.rsplit("/", 1)[-1] + ".yml")
    with open(path, "w") as f:
        yaml.safe_dump(d, f)
    return path


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=200_000)
    ap.add_argument("--out", default=None)
    args = ap.parse_args()

    settle_backend()
    import jax

    from spatialflink_tpu import driver as drv
    from spatialflink_tpu.streams import resolve_broker

    backend = jax.default_backend()
    rows = _rows(args.n)
    results = []
    windows_by_path = {}

    with tempfile.TemporaryDirectory() as tmp:
        def run(name: str, extra, disable_chunked: bool = False,
                use_file: bool = False):
            url = f"memory://bench-kafka-{name}"
            cfg = _conf_file(tmp, url)
            argv = ["--config", cfg, "--option", "1"]
            if use_file:
                path = os.path.join(tmp, "rows.csv")
                with open(path, "w") as f:
                    f.write("\n".join(rows) + "\n")
                argv += ["--input1", path, "--format", "CSV"]
            else:
                broker = resolve_broker(url)
                for r in rows:
                    broker.produce("points.geojson", r)
                argv += ["--kafka"]
            argv += extra
            orig = drv._kafka_bulk_decode
            if disable_chunked:
                drv._kafka_bulk_decode = lambda *a, **k: None
            t = time.perf_counter()
            try:
                with contextlib.redirect_stdout(io.StringIO()) as out:
                    rc = drv.main(argv)
            finally:
                drv._kafka_bulk_decode = orig
            dt = time.perf_counter() - t
            assert rc == 0, name
            wins = [l for l in out.getvalue().splitlines()
                    if l.startswith("{")]
            windows_by_path[name] = wins
            row = {"path": name, "records": args.n,
                   "records_per_sec": round(args.n / dt),
                   "wall_s": round(dt, 3), "windows": len(wins),
                   "backend": backend}
            print(json.dumps(row))
            results.append(row)

        run("record", [], disable_chunked=True)
        run("chunked", [])
        run("bulk", ["--bulk"])
        run("file", ["--bulk"], use_file=True)

    base = windows_by_path["record"]
    for name, wins in windows_by_path.items():
        assert wins == base, f"{name} diverged from the record path windows"

    out = args.out or os.path.join(os.path.dirname(__file__),
                                   f"RESULTS_kafka_{backend}.json")
    with open(out, "w") as f:
        json.dump({"n": args.n, "backend": backend, "rows": results}, f,
                  indent=1)
    print(f"# wrote {out}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
