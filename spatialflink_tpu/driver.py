"""Driver layer (reference: ``GeoFlink/StreamingJob.java:68-1704``).

The reference's ``main`` is a ~1700-line ``switch(queryOption)`` wiring Kafka
sources through deserializers into one of ~120 query pipelines. Here the same
option space is a declarative registry: ``CASES[option]`` describes the
family (range/knn/join/trajectory/deser), the stream/query geometry types,
window vs real-time mode, and the latency/naive variants; :func:`run_option`
builds the pipeline and returns the result iterator.

Option numbering parity (``StreamingJob.java:470-1704``):

- range:     1/2 + 5*i   (window/realtime) over the 9 ordered type pairs
- kNN:       51/52 + 5*i
- join:      101/102 + 5*i
- latency variants: 8/9 (range), 58/59 (kNN), 108/109 (join) — point-polygon
- trajectory: 201..212 (+ naive twins 2030/2090/2011)
- ser/de round-trips: 401..906
- shapefile: 1001..1003; synthetic harness: 99
- apps: 1010..1012 (StayTime), 2000 (CheckIn)
"""

from __future__ import annotations

import argparse
import os
import sys
import time
from dataclasses import dataclass
from typing import Iterable, Iterator, List, Optional

import numpy as np

from spatialflink_tpu import operators as ops
from spatialflink_tpu.config import Params, StreamConfig
from spatialflink_tpu.index import UniformGrid
from spatialflink_tpu.models import SpatialObject
from spatialflink_tpu.operators import QueryConfiguration, QueryType, WindowResult
from spatialflink_tpu.streams.formats import parse_spatial, serialize_spatial

_PAIRS = [
    ("Point", "Point"), ("Point", "Polygon"), ("Point", "LineString"),
    ("Polygon", "Point"), ("Polygon", "Polygon"), ("Polygon", "LineString"),
    ("LineString", "Point"), ("LineString", "Polygon"),
    ("LineString", "LineString"),
]


@dataclass(frozen=True)
class CaseSpec:
    family: str                # range|knn|join|tfilter|trange|tstats|taggregate|tjoin|tknn|deser|shapefile|synthetic|staytime|checkin
    stream: str = "Point"      # geometry type of input stream 1
    query: str = "Point"       # geometry type of the query side
    mode: str = "window"       # window|realtime
    latency: bool = False
    naive: bool = False
    fmt: Optional[str] = None         # deser cases force a format
    timestamped: bool = False         # deser trajectory variants
    delim: Optional[str] = None       # deser cases force a delimiter (TSV)


def _build_cases() -> dict:
    c: dict = {}
    for i, (s, q) in enumerate(_PAIRS):
        c[1 + 5 * i] = CaseSpec("range", s, q, "window")
        c[2 + 5 * i] = CaseSpec("range", s, q, "realtime")
        c[51 + 5 * i] = CaseSpec("knn", s, q, "window")
        c[52 + 5 * i] = CaseSpec("knn", s, q, "realtime")
        c[101 + 5 * i] = CaseSpec("join", s, q, "window")
        c[102 + 5 * i] = CaseSpec("join", s, q, "realtime")
    # latency variants (StreamingJob.java:506-522, 685-700, 863-886)
    c[8] = CaseSpec("range", "Point", "Polygon", "window", latency=True)
    c[9] = CaseSpec("range", "Point", "Polygon", "realtime", latency=True)
    c[58] = CaseSpec("knn", "Point", "Polygon", "window", latency=True)
    c[59] = CaseSpec("knn", "Point", "Polygon", "realtime", latency=True)
    c[108] = CaseSpec("join", "Point", "Polygon", "window", latency=True)
    c[109] = CaseSpec("join", "Point", "Polygon", "realtime", latency=True)
    # trajectory queries (StreamingJob.java:1163-1287)
    c[201] = CaseSpec("tfilter", mode="realtime")
    c[202] = CaseSpec("tfilter", mode="window")
    c[203] = CaseSpec("trange", mode="realtime")
    c[2030] = CaseSpec("trange", mode="realtime", naive=True)
    c[204] = CaseSpec("trange", mode="window")
    c[205] = CaseSpec("tstats", mode="realtime")
    c[206] = CaseSpec("tstats", mode="window")
    c[207] = CaseSpec("taggregate", mode="realtime")
    c[208] = CaseSpec("taggregate", mode="window")
    c[209] = CaseSpec("tjoin", mode="realtime")
    c[2090] = CaseSpec("tjoin", mode="realtime", naive=True)
    c[210] = CaseSpec("tjoin", mode="window")
    c[211] = CaseSpec("tknn", mode="realtime")
    c[2011] = CaseSpec("tknn", mode="realtime", naive=True)
    c[212] = CaseSpec("tknn", mode="window")
    # ser/de conformance pipelines (StreamingJob.java:1289-1545)
    _types = ["Point", "Polygon", "LineString", "GeometryCollection",
              "MultiPoint"]
    for base, fmt, ts in ((400, "GeoJSON", False), (500, "WKT", False),
                          (600, "WKT", False), (700, "GeoJSON", True),
                          (800, "WKT", True), (900, "WKT", True)):
        # 600/900 families are the TAB-separated (TSV) variants
        delim = "\t" if base in (600, 900) else None
        for j, t in enumerate(_types, start=1):
            c[base + j] = CaseSpec("deser", t, fmt=fmt, timestamped=ts,
                                   delim=delim)
        # x06: plain (non-WKT) CSV/TSV point rows
        c[base + 6] = CaseSpec("deser", "Point",
                               fmt="TSV" if delim else "CSV",
                               timestamped=ts, delim=delim)
    # shapefile batch inputs (StreamingJob.java:1546-1569)
    c[1001] = CaseSpec("shapefile", "Point")
    c[1002] = CaseSpec("shapefile", "Polygon")
    c[1003] = CaseSpec("shapefile", "LineString")
    c[99] = CaseSpec("synthetic")
    # apps (StreamingJob.java:1619-1700): 1010 = CellStayTime over a point
    # stream, 1011 = CellSensorRangeIntersection over a polygon stream,
    # 1012 = normalizedCellStayTime over both
    c[1010] = CaseSpec("staytime", "Point")
    c[1011] = CaseSpec("staytime", "Polygon")
    c[1012] = CaseSpec("staytime", "Point", "Polygon")
    c[2000] = CaseSpec("checkin")
    return c


CASES = _build_cases()


# --------------------------------------------------------------------- #
# stream decoding


class ChunkedStream:
    """The decoded stream as the operators consume it: iterating yields
    spatial objects (the legacy record contract — joins, trajectory,
    realtime and the apps flatten through here), while chunk-aware window
    drivers (``WindowAssembler.assemble`` / ``PaneBuffer.assemble``) pull
    :meth:`chunks` and never materialize per-record objects at all.
    ``interner`` is the stream's one obj-id space (kNN resolution and
    pane-merge tie order read through it)."""

    __slots__ = ("_chunks", "interner")

    def __init__(self, chunks: Iterator, interner):
        self._chunks = chunks
        self.interner = interner

    def chunks(self) -> Iterator:
        """Single-use chunk iterator (columnar PointChunk or record list)."""
        return self._chunks

    def __iter__(self):
        for ch in self._chunks:
            if hasattr(ch, "parsed"):
                recs = ch.records()
                if ch.note is not None and ch.positions is not None:
                    # flatten consumers (joins, trajectory state machines)
                    # pull one record at a time: re-note checkpoint
                    # positions per record so a barrier can never cover
                    # records still buffered in this loop
                    for rec, p in zip(recs, ch.positions.tolist()):
                        ch.note(int(p))
                        yield rec
                else:
                    yield from recs
            else:
                yield from ch


def _off_type_warner(geometry: str, dropped):
    """Counter-keyed off-type warning: warns when the ``off-type-dropped``
    counter first moves and again at each decade (1, 10, 100, ...), always
    printing the running count — the batched decoder's replacement for the
    old one-shot boolean (which went silent forever after one record)."""
    state = {"next": 1}

    def warn(typename: str) -> None:
        c = dropped.count
        if c >= state["next"]:
            print(f"warning: dropping off-type {typename} record(s) from "
                  f"declared {geometry} stream (off-type-dropped={c})",
                  file=sys.stderr)
            while state["next"] <= c:
                state["next"] *= 10
    return warn


def decode_chunks(records: Iterable, cfg: StreamConfig, grid: UniformGrid,
                  geometry: str = "Point", chunk: int = 4096,
                  interner=None, max_buffer_s: float = 0.2) -> Iterator:
    """Chunk-vectorized decode — THE ingest path for every mode (file
    replay, kafka chunked drain, ``--kafka-follow`` live). Raw lines buffer
    into chunks and parse through ``streams.bulk``'s columnar parsers (one
    native call per chunk for CSV/TSV/GeoJSON point streams, yielding a
    columnar :class:`~spatialflink_tpu.streams.bulk.PointChunk`); geometry
    streams and pre-parsed objects batch per chunk with the same amortized
    bookkeeping. Telemetry observes, the ingest meter, and the off-type
    filter all run ONCE PER CHUNK instead of once per record.

    Semantics preserved from the scalar decoder: the control-tuple stop
    hook fires at the record that carries it (buffered records before it
    still reach the pipeline), off-type rows — e.g. a stray polygon
    feature in a declared point stream — are dropped per-chunk with the
    same ``off-type-dropped`` counter (a chunk the columnar parser rejects
    falls back to the exact per-record parse rather than crashing), and
    live sources' starvation sentinel flushes the buffer so chunking adds
    at most one poll cycle of latency.

    ``chunk`` is an int OR a zero-arg size callback (the chunk governor's
    actuator, ``runtime/control.py``): a callback resolves ONCE at each
    buffer start, so a live resize lands between flushes — never inside
    one — and the flush threshold stays constant while a chunk fills."""
    from spatialflink_tpu.streams import bulk as B
    from spatialflink_tpu.streams.kafka import STARVED
    from spatialflink_tpu.utils import IdInterner
    from spatialflink_tpu.utils import metrics as _metrics
    from spatialflink_tpu.utils import telemetry as _telemetry
    from spatialflink_tpu.utils.metrics import (REGISTRY, ControlTupleExit,
                                                check_exit_control_tuple)

    meter = REGISTRY.meter("ingest-throughput")
    dropped = REGISTRY.counter("off-type-dropped")
    warn = _off_type_warner(geometry, dropped)
    needs_edges = geometry in ("Polygon", "LineString")
    is_point = geometry == "Point"
    fmt = cfg.format.lower()
    bulk_ok = is_point and fmt in ("csv", "tsv", "geojson")
    interner = interner if interner is not None else IdInterner()
    tel = _telemetry.active()
    # decode-chunk buffer depth (backpressure timeline): the fill level at
    # each flush — one gauge set per CHUNK, nothing per record
    depth_gauge = (tel.gauge("decode.buffer-depth")
                   if tel is not None else None)

    def off_type_filter(objs: List) -> List:
        kept = []
        for o in objs:
            if ((needs_edges and not hasattr(o, "edge_array"))
                    or (is_point and not hasattr(o, "x"))):
                dropped.inc()
                warn(type(o).__name__)
            else:
                kept.append(o)
        return kept

    def parse_one(rec):
        return parse_spatial(
            rec, cfg.format, grid,
            delimiter=cfg.delimiter,
            schema=cfg.csv_tsv_schema,
            # only CSV/TSV needs the hint (coordinate-string rows,
            # CSVTSVToSpatialPolygon); GeoJSON/WKT are self-describing
            geometry=geometry,
            **cfg.geojson_kwargs(),
        )

    def parse_raws(raws: List[str]):
        # the columnar parse rides only when the chunk maps 1:1 onto parser
        # lines (no INTERIOR newlines — a trailing newline from an
        # unstripped file iterator is normalized away) and every row is a
        # point the native/reject machinery accepts; anything else —
        # including off-type rows, which the point parsers reject with
        # ValueError — falls back to the exact per-record parse + the
        # off-type drop counter
        if bulk_ok:
            raws = [r[:-1] if r.endswith("\n") else r for r in raws]
        if bulk_ok and not any("\n" in r for r in raws):
            data = "\n".join(raws).encode()
            try:
                if fmt == "geojson":
                    parsed = B.bulk_parse_geojson(data, interner=interner,
                                                  **cfg.geojson_kwargs())
                else:
                    parsed = B.bulk_parse_csv(
                        data, delimiter="\t" if fmt == "tsv" else cfg.delimiter,
                        schema=_schema4(cfg), date_format=cfg.date_format,
                        interner=interner)
            except ValueError:
                parsed = None
            if parsed is not None and len(parsed) == len(raws):
                return B.PointChunk.build(parsed, grid)
        return off_type_filter([parse_one(r) for r in raws])

    src_chunks = getattr(records, "chunks", None)
    if src_chunks is not None:
        # an upstream chunked decoder (the Kafka commit tap) already parsed;
        # apply only the meter + off-type bookkeeping per chunk
        for ch in src_chunks():
            if hasattr(ch, "parsed"):
                meter.mark(len(ch))
                if len(ch):
                    yield ch
            else:
                meter.mark(len(ch))
                kept = off_type_filter(list(ch))
                if kept:
                    yield kept
        return

    buf: List = []
    kind = None  # "str" (columnar-parseable) | "obj" (parsed) | "raw"
    chunk_fn = chunk if callable(chunk) else None
    chunk_n = max(1, int(chunk_fn() if chunk_fn is not None else chunk))

    def flush():
        nonlocal buf, kind
        if not buf:
            return None
        t0 = time.perf_counter() if tel is not None else 0.0
        if depth_gauge is not None:
            depth_gauge.set(len(buf))
        if kind == "str":
            out = parse_raws(buf)
        elif kind == "obj":
            out = off_type_filter(buf)
        else:
            out = off_type_filter([parse_one(r) for r in buf])
        if tel is not None:
            # ONE ingest observe per chunk — the parse cost amortized over
            # the chunk (the scalar path observed per record)
            tel.observe("ingest", time.perf_counter() - t0)
        meter.mark(len(buf))
        buf = []
        kind = None
        return out if len(out) else None

    src = iter(records)
    shutdown_requested = _metrics.shutdown_requested  # hoisted: per-record
    while True:
        try:
            rec = next(src)
        except StopIteration:
            break
        except ControlTupleExit:
            # a source-raised stop (a tailing fleet source seeing the
            # shutdown flag while idle): drain the buffer downstream
            # first — every record already read must reach its window
            # before the stop propagates (positions were tap-counted)
            out = flush()
            if out is not None:
                yield out
            raise
        if rec is STARVED:
            # quiet live topic: hand everything buffered downstream so a
            # chunk never waits out dead air (latency bound = one poll)
            out = flush()
            if out is not None:
                yield out
            continue
        try:
            check_exit_control_tuple(rec)
        except ControlTupleExit:
            out = flush()
            if out is not None:
                yield out
            raise
        k = ("str" if isinstance(rec, str)
             else "obj" if isinstance(rec, SpatialObject) else "raw")
        if buf and k != kind:
            out = flush()
            if out is not None:
                yield out
        if not buf:
            t_first = time.perf_counter()
            if chunk_fn is not None:
                chunk_n = max(1, int(chunk_fn()))
        buf.append(rec)
        kind = k
        if shutdown_requested():
            # SIGTERM landed between records: the current record is
            # already buffered (tap-counted — dropping it would lose it
            # from the final checkpoint), so drain the chunk and stop
            out = flush()
            if out is not None:
                yield out
            raise _metrics.GracefulShutdown(
                "shutdown requested (SIGTERM): buffered records drained")
        # size OR age flush: a slow live source without a starvation
        # sentinel (direct KafkaSource feeds) must not hold records hostage
        # to a chunk fill — `max_buffer_s` bounds the added decode latency
        # (replay sources fill chunks in microseconds and never hit it)
        if (len(buf) >= chunk_n
                or time.perf_counter() - t_first >= max_buffer_s):
            out = flush()
            if out is not None:
                yield out
    out = flush()
    if out is not None:
        yield out


def decode_stream(records: Iterable, cfg: StreamConfig, grid: UniformGrid,
                  geometry: str = "Point",
                  chunk: int = 4096) -> "ChunkedStream":
    """Raw lines/dicts → spatial objects (the reference's per-case
    ``Deserialization.*Stream`` stage), rebuilt on the batched
    :func:`decode_chunks` seam: the scalar per-record parse loop is gone —
    every mode decodes chunk-vectorized, and the returned
    :class:`ChunkedStream` serves both per-record consumers (iteration)
    and the chunk-aware window assemblers (``.chunks``). The seed scalar
    decoder survives only as a test oracle (``tests/oracles.py``)."""
    from spatialflink_tpu.utils import IdInterner

    interner = getattr(records, "interner", None)
    if interner is None and geometry == "Point" \
            and cfg.format.lower() in ("csv", "tsv", "geojson"):
        interner = IdInterner()
    return ChunkedStream(
        decode_chunks(records, cfg, grid, geometry, chunk, interner=interner),
        interner)


#: (family, mode) combinations the coordinated checkpointer covers: their
#: drive loops register every piece of cross-record state with the
#: coordinator and barrier between processing units. Families with
#: unregistered cross-batch state (realtime join's rolling buffers, tJoin/
#: tKnn's bespoke loops, the apps) are refused — a checkpoint that misses
#: live state would LOSE records on resume, which is worse than no
#: checkpoint.
_CKPT_WINDOW_FAMILIES = ("range", "knn", "join", "tfilter", "trange",
                         "tstats", "taggregate")
_CKPT_REALTIME_FAMILIES = ("range", "knn", "tstats", "taggregate")


def _checkpoint_dir_unsupported(params: Params,
                                spec: CaseSpec) -> Optional[str]:
    """None when --checkpoint-dir covers this case; else the reason it
    doesn't (the driver warns and runs without the coordinator)."""
    if spec.naive:
        return "naive-twin oracles keep the plain path"
    if spec.mode == "window":
        if params.window.type == "COUNT":
            return ("count windows buffer by arrival order outside the "
                    "checkpointable assemblers")
        if spec.family not in _CKPT_WINDOW_FAMILIES:
            return (f"windowed {spec.family} has no registered "
                    "checkpoint state")
        return None
    if spec.family not in _CKPT_REALTIME_FAMILIES:
        return (f"realtime {spec.family} keeps cross-batch state outside "
                "the checkpointable participants")
    return None


def _query_conf(params: Params, spec: CaseSpec) -> QueryConfiguration:
    size_ms, step_ms = params.window_ms()
    if spec.mode == "realtime":
        qt = QueryType.RealTime
    elif params.window.type == "COUNT":
        # sliding count windows for every single-stream windowed operator
        # (the reference declares CountBased and throws "Not yet support"
        # everywhere except tAggregate's per-cell variant, QueryType.java:6;
        # here the mode is implemented — see operators/base.py
        # _count_windows); joins/apps with bespoke window logic still raise
        qt = QueryType.CountBased
        # count windows interpret interval/step as raw element COUNTS — the
        # reference hands the same config values to countWindow un-scaled
        size_ms, step_ms = int(params.window.interval_s), int(params.window.step_s)
    else:
        qt = QueryType.WindowBased
    return QueryConfiguration(
        query_type=qt,
        window_size_ms=size_ms,
        slide_ms=step_ms,
        allowed_lateness_ms=params.query.allowed_lateness_s * 1000,
        approximate=params.query.approximate,
        # pane-incremental sliding windows (--panes / query.panes): kernel
        # partials once per slide, merged across overlapping windows; only
        # engages for pane-decomposable event-time windows (operators gate)
        panes=params.query.panes,
        # --pane-merge device|host: where pane partials live and merge
        pane_device_merge=params.query.pane_device_merge,
        k=params.query.k,
        # query.parallelism ≙ env.setParallelism(30) (StreamingJob.java:221):
        # shard window batches across a device mesh; query.hosts > 1 makes
        # it the 2-D multi-host (DCN x ICI) shape
        devices=params.query.parallelism or None,
        hosts=params.query.hosts or None,
        # coordinated checkpointing (--checkpoint-dir): operators register
        # their window/pane/trajectory state and barrier through this
        checkpointer=getattr(params, "checkpointer", None),
        # skew-adaptive refinement layer (--adaptive-grid): the shared
        # AdaptiveGrid whose leaf masks drive the pre-kernel prefilter
        adaptive_grid=getattr(params, "adaptive_grid", None),
        # mesh shard placement (--shard-order)
        shard_order=getattr(params, "shard_order", "arrival"),
    )


def _operator_class(spec: CaseSpec):
    """The stream x query operator class for a range/kNN/join CaseSpec."""
    fam = {"range": "Range", "knn": "KNN", "join": "Join"}[spec.family]
    return getattr(ops, f"{spec.stream}{spec.query}{fam}Query")


def _query_object(params: Params, grid: UniformGrid, kind: str):
    if kind == "Point":
        pts = params.query_point_objects(grid)
        if not pts:
            raise ValueError("query.queryPoints is empty")
        return pts[0]
    if kind == "Polygon":
        polys = params.query_polygon_objects(grid)
        if not polys:
            raise ValueError("query.queryPolygons is empty")
        return polys[0]
    lss = params.query_linestring_objects(grid)
    if not lss:
        raise ValueError("query.queryLineStrings is empty")
    return lss[0]


def _run_multi_case(params: Params, spec: CaseSpec, op, s1,
                    u_grid: UniformGrid, radius: float) -> Iterator:
    """``query.multiQuery`` dispatch: answer ALL configured query objects in
    one dispatch per window via run_multi (TPU-native extension; without the
    flag the driver keeps reference parity and uses only the first query
    object). Supported: ALL NINE range and kNN pairs here, plus trajectory
    kNN (211/212) routed through its own branch in ``_run_trajectory`` —
    keep the three in sync: this dispatch, the tknn branch, and
    run_option's family gate. Other families error rather than silently
    falling back to first-query semantics (run_option rejects them before
    dispatch reaches here)."""
    if spec.latency:
        raise ValueError(
            "multiQuery does not combine with the latency variants "
            "(per-record latency assumes single-query record lists)")
    getter, name = {
        "Point": (params.query_point_objects, "queryPoints"),
        "Polygon": (params.query_polygon_objects, "queryPolygons"),
        "LineString": (params.query_linestring_objects, "queryLineStrings"),
    }[spec.query]
    qs = getter(u_grid)
    if not qs:
        raise ValueError(f"query.{name} is empty")
    if spec.family == "range":
        return op.run_multi(s1, qs, radius)
    return op.run_multi(s1, qs, radius, params.query.k)


def _with_latency(results: Iterator[WindowResult]) -> Iterator[WindowResult]:
    """Annotate each result with per-record latency millis (reference:
    ``now - ingestionTime`` shipped to a Kafka topic,
    ``utils/HelperClass.java:455-529``). With telemetry active the same
    values feed the session's ``record-latency-ms`` streaming histogram so
    the snapshots carry p50/p95/p99."""
    from spatialflink_tpu.utils import telemetry as _telemetry

    tel = _telemetry.active()
    hist = tel.histogram("record-latency-ms") if tel is not None else None
    for r in results:
        now = int(time.time() * 1000)
        lats = []
        for rec in r.records:
            obj = rec[0] if isinstance(rec, tuple) else rec
            base = getattr(obj, "ingestion_time", None)
            if isinstance(base, (int, float)) and base > 0:
                lats.append(now - int(base))
        if hist is not None:
            for v in lats:
                hist.record(v)
        r.extras["latency_ms"] = lats
        yield r


# --------------------------------------------------------------------- #


def run_option(params: Params, stream1: Iterable, stream2: Optional[Iterable]
               = None) -> Iterator:
    """Wire and run the pipeline for ``params.query.option``.

    ``stream1``/``stream2`` are iterables of raw records (str/dict) or parsed
    spatial objects — the host-side stand-ins for the reference's two Kafka
    consumers."""
    opt = params.query.option
    if opt not in CASES:
        raise ValueError(f"unknown queryOption {opt}")
    spec = CASES[opt]
    if params.query.multi_query and spec.family not in ("range", "knn",
                                                        "tknn"):
        # every ineligible family errors — silently answering only the
        # first query under the flag would be worse than failing
        raise ValueError(
            f"multiQuery is not supported for queryOption {opt} "
            f"({spec.family}); supported: all nine range and kNN "
            "pairs, plus trajectory kNN (211/212)")
    u_grid, q_grid = params.grids()
    conf = _query_conf(params, spec)
    radius = params.query.radius

    # decode chunk sizing: realtime chunks at the micro-batch size (chunk
    # fill and batch fire coincide — no added latency vs the scalar path);
    # count windows chunk at the slide COUNT (fires stay step-aligned);
    # windowed modes use the default throughput chunk (live sources bound
    # the buffering to one poll cycle via the starvation sentinel)
    if spec.mode == "realtime":
        # the vectorized micro-batcher cuts strictly every
        # realtime_batch_size records regardless of decode-chunk size, so
        # the governor may drive realtime chunks without moving a single
        # batch boundary (tests/test_control.py pins the identity)
        dchunk = _governed_chunk(max(1, conf.realtime_batch_size))
    elif params.window.type == "COUNT":
        dchunk = max(1, min(4096, int(params.window.step_s)))
    else:
        dchunk = _governed_chunk(_decode_chunk_env(4096))

    if spec.family in ("range", "knn", "join"):
        cls = _operator_class(spec)
        s1 = decode_stream(stream1, params.input1, u_grid, spec.stream,
                           chunk=dchunk)
        if spec.family == "join":
            op = cls(conf, u_grid, q_grid)
            if stream2 is None:
                raise ValueError(f"queryOption {opt} (join) needs stream2")
            s2 = decode_stream(stream2, params.input2, q_grid, spec.query,
                               chunk=dchunk)
            out = op.run(s1, s2, radius)
        else:
            op = cls(conf, u_grid)
            registry = getattr(params, "query_registry", None)
            if registry is not None:
                # dynamic standing-query plane: the live registry — not the
                # static config — says what runs (admissions/retirements
                # land at window boundaries, padded to Q-axis size buckets)
                if spec.family == "knn":
                    out = op.run_dynamic(s1, registry, radius,
                                         params.query.k)
                else:
                    out = op.run_dynamic(s1, registry, radius)
            elif params.query.multi_query:
                out = _run_multi_case(params, spec, op, s1, u_grid, radius)
            else:
                q = _query_object(params, u_grid, spec.query)
                if spec.family == "knn":
                    out = op.run(s1, q, radius, params.query.k)
                else:
                    out = op.run(s1, q, radius)
        return _with_latency(out) if spec.latency else out

    if spec.family in ("tfilter", "trange", "tstats", "taggregate", "tjoin",
                       "tknn"):
        return _run_trajectory(params, spec, conf, u_grid, q_grid,
                               stream1, stream2)

    if spec.family == "deser":
        return _run_deser(params, spec, u_grid, stream1)

    if spec.family == "shapefile":
        from spatialflink_tpu.streams.shapefile import read_shapefile

        # stream1 is a path (or iterable of paths) to .shp files
        paths = [stream1] if isinstance(stream1, (str, bytes)) else list(stream1)
        return iter([obj for p in paths for obj in read_shapefile(p, u_grid)])

    if spec.family == "synthetic":
        return _run_synthetic(params, conf, u_grid)

    if spec.family == "staytime":
        from spatialflink_tpu.apps.stay_time import StayTime

        app = StayTime(conf, u_grid)
        traj_ids = set(params.query.traj_ids) or None
        if spec.query == "Polygon":  # 1012: point stream + polygon stream
            if stream2 is None:
                raise ValueError("queryOption 1012 needs a polygon stream2")
            s1 = decode_stream(stream1, params.input1, u_grid)
            # both sides must live in the app's grid (the reference passes
            # ONE uGrid to normalizedCellStayTime, StreamingJob.java:1667)
            s2 = decode_stream(stream2, params.input2, u_grid, "Polygon")
            # query.trajIDs names moving-object trajectories; sensor polygon
            # IDs live in a different namespace, so the sensor side is never
            # filtered by it (StayTime.java keys sensors by poly id only)
            return app.normalized_cell_stay_time(
                s1, s2, traj_ids_points=traj_ids, traj_ids_sensors=None)
        s1 = decode_stream(stream1, params.input1, u_grid, spec.stream)
        if spec.stream == "Polygon":  # 1011: sensor-range intersection
            return app.cell_sensor_range_intersection(s1, traj_ids)
        return app.cell_stay_time(s1, traj_ids)

    if spec.family == "checkin":
        from spatialflink_tpu.apps.check_in import CheckIn

        # raw DEIM CSV lines (eventID,deviceID,userID,ts,x,y) are parsed by
        # the app itself; parsed Points pass through
        return CheckIn(conf).run(stream1)

    raise AssertionError(f"unhandled family {spec.family}")


def _run_trajectory(params, spec, conf, u_grid, q_grid, stream1, stream2):
    dchunk = _governed_chunk(
        max(1, conf.realtime_batch_size) if spec.mode == "realtime"
        else 4096)
    s1 = decode_stream(stream1, params.input1, u_grid, chunk=dchunk)
    q = params.query
    if spec.family == "tfilter":
        return ops.PointTFilterQuery(conf, u_grid).run(s1, set(q.traj_ids))
    if spec.family == "trange":
        polys = params.query_polygon_objects(u_grid)
        op = ops.PointPolygonTRangeQuery(conf, u_grid)
        return op.run_naive(s1, polys) if spec.naive else op.run(s1, polys)
    if spec.family == "tstats":
        return ops.PointTStatsQuery(conf, u_grid).run(
            s1, set(q.traj_ids) or None,
            checkpoint_path=params.checkpoint_path,
            checkpoint_every=params.checkpoint_every,
            checkpoint_job=params.checkpoint_job)
    if spec.family == "taggregate":
        return ops.PointTAggregateQuery(conf, u_grid).run(
            s1, q.aggregate_function,
            traj_deletion_threshold_ms=q.traj_deletion_threshold_s * 1000,
            checkpoint_path=params.checkpoint_path,
            checkpoint_every=params.checkpoint_every,
            checkpoint_job=params.checkpoint_job)
    if spec.family == "tjoin":
        if stream2 is None:
            raise ValueError("trajectory join needs stream2")
        s2 = decode_stream(stream2, params.input2, q_grid, chunk=dchunk)
        op = ops.PointPointTJoinQuery(conf, u_grid, q_grid)
        run = op.run_naive if spec.naive else op.run
        return run(s1, s2, params.query.radius)
    if spec.family == "tknn":
        op = ops.PointPointTKNNQuery(conf, u_grid)
        if params.query.multi_query:
            if spec.naive:
                raise ValueError(
                    "multiQuery does not combine with the naive-twin tKnn "
                    "(the oracle exists to check the pruned single path)")
            qps = params.query_point_objects(u_grid)
            if not qps:
                raise ValueError("query.queryPoints is empty")
            return op.run_multi(s1, qps, params.query.radius, q.k)
        qp = _query_object(params, u_grid, "Point")
        run = op.run_naive if spec.naive else op.run
        return run(s1, qp, params.query.radius, q.k)
    raise AssertionError(spec.family)


def _run_deser(params, spec, grid, stream1) -> Iterator:
    """Parse each record with the case's forced format and immediately
    re-serialize — the reference's parse→print→produce conformance path
    (``StreamingJob.java:1289-1545``)."""
    fmt = spec.fmt
    delim = spec.delim or ("\t" if fmt == "TSV" else params.input1.delimiter or ",")
    for rec in stream1:
        obj = rec if isinstance(rec, SpatialObject) else parse_spatial(
            rec, fmt, grid,
            delimiter=delim,
            schema=params.input1.csv_tsv_schema,
            date_format=params.input1.date_format,
        )
        yield obj, serialize_spatial(
            obj, fmt, delimiter=delim,
            date_format=params.input1.date_format if spec.timestamped else None)


def _run_synthetic(params: Params, conf, grid) -> Iterator[WindowResult]:
    """queryOption 99: run ALL SIX trajectory query families over
    deterministic synthetic trajectories — the reference harness sketched
    every one against ``env.fromCollection`` (``StreamingJob.java:1571-1618``).
    Results are tagged with the family via ``extras['family']`` so a smoke
    run can assert each family actually fired."""
    from spatialflink_tpu.models import Polygon
    from spatialflink_tpu.streams.sources import (SyntheticPointSource,
                                                  generate_query_polygons)

    def src():
        return SyntheticPointSource(grid, num_trajectories=16, steps=8, seed=7)

    def tagged(family, it):
        for r in it:
            if hasattr(r, "extras"):
                r.extras.setdefault("family", family)
            yield r

    first = list(src())
    traj_ids = {p.obj_id for p in first[:4]}
    qp = first[0]
    # a query polygon covering the middle of the grid (guarantees matches)
    # plus cell-sized tiles from the HelperClass.generateQueryPolygons
    # rebuild (streams.sources.generate_query_polygons) — the polygon-SET
    # shape the reference harness fed tRange
    cx = (grid.min_x + grid.max_x) / 2
    cy = (grid.min_y + grid.max_y) / 2
    dx = (grid.max_x - grid.min_x) / 4
    dy = (grid.max_y - grid.min_y) / 4
    qpoly = Polygon.create(
        [[(cx - dx, cy - dy), (cx + dx, cy - dy), (cx + dx, cy + dy),
          (cx - dx, cy + dy)]], grid)
    qpolys = [qpoly] + generate_query_polygons(8, grid)

    yield from tagged("tfilter",
                      ops.PointTFilterQuery(conf, grid).run(src(), traj_ids))
    yield from tagged("trange",
                      ops.PointPolygonTRangeQuery(conf, grid).run(src(), qpolys))
    yield from tagged("tstats", ops.PointTStatsQuery(conf, grid).run(src()))
    yield from tagged("taggregate", ops.PointTAggregateQuery(conf, grid).run(
        src(), params.query.aggregate_function))
    # query.radius defaults to 0.0 in the config schema (= unset); the
    # harness needs a working radius — tJoin's proximity test and tKnn's
    # enforced radius filter both emit nothing at 0 — so 0 falls back to a
    # half-degree probe. A deliberately tiny radius still passes through.
    radius = params.query.radius if params.query.radius > 0 else 0.5
    yield from tagged("tjoin", ops.PointPointTJoinQuery(conf, grid, grid).run(
        src(), src(), radius))
    yield from tagged("tknn", ops.PointPointTKNNQuery(conf, grid).run(
        src(), qp, radius, params.query.k))


# --------------------------------------------------------------------- #
# CLI


def _read_src(src) -> Optional[bytes]:
    """Bulk-input source to bytes: a replay file path, a ``bytes`` block, or
    a zero-arg callable (the LAZY ``--kafka --bulk`` topic drain — called
    only after the cheap case/format gates passed, so an ineligible
    invocation never pays a full topic read). A callable returning None
    means the source cannot ride the bulk path (caller falls back)."""
    if callable(src):
        return src()
    if isinstance(src, bytes):
        return src
    with open(src, "rb") as f:
        return f.read()


def _bulk_parse_stream(cfg: StreamConfig, src,
                       allowed_lateness_s: int):
    """Native-ingest one POINT stream (see :func:`_read_src` for accepted
    sources) + vectorized watermark dropping; None when the format/content
    cannot ride the bulk path (e.g. a geometry feature in a declared point
    stream — the record path dead-letters it instead)."""
    import dataclasses

    from spatialflink_tpu.runtime.watermarks import BoundedOutOfOrderness
    from spatialflink_tpu.streams.bulk import bulk_parse_csv, bulk_parse_geojson
    from spatialflink_tpu.utils.telemetry import span as _tel_span

    fmt = cfg.format.lower()
    if fmt not in ("csv", "tsv", "geojson"):
        return None
    data = _read_src(src)
    if data is None:
        return None
    try:
        # one span covers the whole native parse (the bulk path's "ingest"
        # stage — a single call, so the module-level nullcontext-when-off
        # helper is fine here)
        with _tel_span("ingest", query="bulk"):
            if fmt in ("csv", "tsv"):
                delim = "\t" if fmt == "tsv" else cfg.delimiter
                parsed = bulk_parse_csv(
                    data, delimiter=delim, schema=_schema4(cfg),
                    date_format=cfg.date_format)
            else:
                parsed = bulk_parse_geojson(data, **cfg.geojson_kwargs())
    except ValueError as e:
        print(f"# --bulk: point stream not bulk-ingestible ({e}); "
              "using the record path", file=sys.stderr)
        return None
    # reproduce the record path's watermark dropping (same keep/late rule,
    # computed in one vectorized pass over the timestamp array)
    keep = BoundedOutOfOrderness.bulk_keep_mask(
        parsed.ts, allowed_lateness_s * 1000)
    if not keep.all():
        parsed = dataclasses.replace(
            parsed, x=parsed.x[keep], y=parsed.y[keep], ts=parsed.ts[keep],
            obj_id=parsed.obj_id[keep])
    return parsed


def run_option_bulk(params: Params, input_path: str,
                    input_path2: Optional[str] = None) -> Optional[Iterator]:
    """Vectorized replay fast path for windowed Point/Point range, kNN and
    join cases over CSV/TSV/GeoJSON point files: native ingest -> bulk window
    batches -> pipelined kernels, no per-record Python objects. Lateness
    semantics match the record path exactly. Returns None when the
    case/format cannot ride it (caller falls back to the record path)."""
    spec = CASES.get(params.query.option)
    if spec is None or spec.mode != "window" or spec.latency:
        return None
    if params.window.type == "COUNT":
        # count windows trigger on arrival ORDER; the bulk assemblers build
        # event-time windows — the record path implements the mode
        return None
    if params.query.multi_query:
        # every range/kNN pair has a bulk multi-query evaluator (point
        # streams over CSV/TSV/GeoJSON, geometry streams over WKT/GeoJSON);
        # anything else falls back to the record path (run_option), which
        # dispatches or errors per the multiQuery eligibility rules —
        # silently answering only the first configured query would be
        # worse than the slower path
        if spec.family not in ("range", "knn"):
            return None
        u_grid, _ = params.grids()
        getter, qname = {
            "Point": (params.query_point_objects, "queryPoints"),
            "Polygon": (params.query_polygon_objects, "queryPolygons"),
            "LineString": (params.query_linestring_objects,
                           "queryLineStrings"),
        }[spec.query]
        qs = getter(u_grid)
        if not qs:
            # validate BEFORE the full-file native ingest, like the record
            # path's _non_empty guard
            raise ValueError(f"query.{qname} is empty")
        if spec.stream in ("Polygon", "LineString"):
            if params.input1.format.lower() not in ("wkt", "geojson"):
                return None
            parsed = _bulk_parse_geom_stream(params, input_path)
        else:
            parsed = _bulk_parse_stream(params.input1, input_path,
                                        params.query.allowed_lateness_s)
        if parsed is None:
            return None
        conf = _query_conf(params, spec)
        cls = _operator_class(spec)
        if spec.family == "range":
            return cls(conf, u_grid).run_multi_bulk(
                parsed, qs, params.query.radius)
        return cls(conf, u_grid).run_multi_bulk(
            parsed, qs, params.query.radius, params.query.k)
    geom_stream = spec.stream in ("Polygon", "LineString")
    if geom_stream:
        # geometry STREAMS ride the bulk path for range/kNN over WKT or
        # GeoJSON files
        if (spec.family not in ("range", "knn")
                or params.input1.format.lower() not in ("wkt", "geojson")):
            return None
        parsed = _bulk_parse_geom_stream(params, input_path)
    else:
        if (spec.family not in ("range", "knn", "join")
                or spec.stream != "Point"):
            return None
        if spec.family == "join":
            if spec.query != "Point":
                return None
            # cheap format gate on BOTH sides before any ingest work, so an
            # ineligible side-2 format doesn't waste a full side-1 parse
            if (input_path2 is None or params.input2.format.lower()
                    not in ("csv", "tsv", "geojson")):
                return None
        parsed = _bulk_parse_stream(params.input1, input_path,
                                    params.query.allowed_lateness_s)
    if parsed is None:
        return None
    u_grid, _ = params.grids()
    conf = _query_conf(params, spec)
    if spec.family == "join":
        parsed2 = _bulk_parse_stream(params.input2, input_path2,
                                     params.query.allowed_lateness_s)
        if parsed2 is None:
            return None
        return ops.PointPointJoinQuery(conf, u_grid, u_grid).run_bulk(
            parsed, parsed2, params.query.radius)
    q = _query_object(params, u_grid, spec.query)
    cls = _operator_class(spec)
    if spec.family == "range":
        return cls(conf, u_grid).run_bulk(parsed, q, params.query.radius)
    return cls(conf, u_grid).run_bulk(
        parsed, q, params.query.radius, params.query.k)


def _bulk_parse_geom_stream(params: Params, src):
    """Native WKT/GeoJSON geometry ingest (file path or pre-drained bytes)
    + the same vectorized watermark dropping as the point path (ParsedGeoms
    carries its own subset machinery). Returns None — honoring
    run_option_bulk's fall-back-to-record-path contract — when the input
    holds geometry the bulk path can't ride (e.g. a stray POINT or
    GEOMETRYCOLLECTION row in a polygon stream)."""
    from spatialflink_tpu.runtime.watermarks import BoundedOutOfOrderness
    from spatialflink_tpu.streams.bulk import (bulk_parse_geojson_geoms,
                                               bulk_parse_wkt)
    from spatialflink_tpu.utils.telemetry import span as _tel_span

    cfg = params.input1
    if cfg.format.lower() == "wkt":
        kw = dict(delimiter=cfg.delimiter, date_format=cfg.date_format)
    else:
        kw = cfg.geojson_kwargs()
    try:
        data = _read_src(src)
        if data is None:
            return None
        with _tel_span("ingest", query="bulk"):
            # format pre-gated to WKT/GeoJSON by run_option_bulk
            if cfg.format.lower() == "wkt":
                parsed = bulk_parse_wkt(data, **kw)
            else:
                parsed = bulk_parse_geojson_geoms(data, **kw)
    except ValueError as e:
        print(f"# --bulk: geometry file not bulk-ingestible ({e}); "
              "using the record path", file=sys.stderr)
        return None
    keep = BoundedOutOfOrderness.bulk_keep_mask(
        parsed.ts, params.query.allowed_lateness_s * 1000)
    if not keep.all():
        parsed = parsed.subset(np.nonzero(keep)[0])
    return parsed


def _emit(result, sink) -> None:
    if isinstance(result, WindowResult):
        if "queries" in result.extras:
            # multi-query windows: records is a list of Q per-query lists
            counts = {"count": sum(len(r) for r in result.records),
                      "per_query_counts": [len(r) for r in result.records]}
        else:
            counts = {"count": len(result.records)}
        sink.emit({
            "window": [result.window_start, result.window_end],
            **counts,
            **{k: v for k, v in result.extras.items() if k != "latency_ms"},
        })
    else:
        sink.emit(result)


def _enable_compilation_cache() -> None:
    """Persist XLA compilations across CLI invocations.

    A pipeline's kernels are identical run to run, but every fresh process
    pays the compiles again — ~0.4 s on CPU and tens of seconds on TPU
    (where the first jit is 20-40 s). Defaults to a user cache dir; an
    explicit ``JAX_COMPILATION_CACHE_DIR`` (or pre-set jax config) wins.
    Failure is non-fatal: the cache is an optimization, not a dependency.
    """
    import jax

    try:
        if os.environ.get("JAX_COMPILATION_CACHE_DIR"):
            cache = os.environ["JAX_COMPILATION_CACHE_DIR"]
        elif jax.config.jax_compilation_cache_dir:
            return  # user already configured it in-process
        else:
            cache = os.path.join(
                os.environ.get("XDG_CACHE_HOME",
                               os.path.expanduser("~/.cache")),
                "spatialflink_tpu", "jax_cache")
        os.makedirs(cache, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache)
        if "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS" not in os.environ:
            jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
    except Exception as e:  # pragma: no cover - depends on fs/env
        print(f"note: compilation cache disabled ({e})", file=sys.stderr)


def _parse_fn(cfg: StreamConfig, grid: UniformGrid, geometry: str):
    """The per-record parse :func:`decode_stream` applies, as a plain
    callable (the Kafka commit tap parses BEFORE the pipeline so it can read
    event times; decode_stream then passes the parsed objects through)."""
    def parse(rec):
        if isinstance(rec, SpatialObject):
            return rec
        return parse_spatial(rec, cfg.format, grid, delimiter=cfg.delimiter,
                             schema=cfg.csv_tsv_schema, geometry=geometry,
                             **cfg.geojson_kwargs())
    return parse


def _decode_chunk_env(default: int) -> int:
    """Decode chunk size with the ``SPATIALFLINK_DECODE_CHUNK`` override —
    the knob tests/benches use to exercise chunk-boundary behavior (e.g.
    record-granular checkpoint positions on tiny topics)."""
    v = os.environ.get("SPATIALFLINK_DECODE_CHUNK")
    return max(1, int(v)) if v else default


def _governed_chunk(dchunk: int, pinned: bool = False):
    """The decode-chunk actuator seam: a per-flush size callback that
    reads the installed chunk governor (``--controller``) LATE — at each
    buffer start, not at wiring time — so stream construction order vs.
    governor install order does not matter, and a governor installed
    mid-run takes effect at the next flush. Without one the callback
    returns the fixed size (same values as the pre-governor int).
    ``pinned`` sizes — an explicit ``SPATIALFLINK_DECODE_CHUNK`` env
    override, or count-window step alignment — stay fixed ints: the
    operator asked for THAT chunk."""
    if pinned or os.environ.get("SPATIALFLINK_DECODE_CHUNK"):
        return dchunk
    from spatialflink_tpu.runtime.control import active_governor

    def _resolve() -> int:
        gov = active_governor()
        return gov.chunk() if gov is not None else dchunk
    return _resolve


def _schema4(cfg: StreamConfig) -> list:
    """csvTsvSchemaAttr padded to the 4 [oID, ts, x, y] slots (None =
    absent) — shared by the bulk file path and the kafka chunked decode."""
    return (list(cfg.csv_tsv_schema) + [None] * 4)[:4]


def _kafka_bulk_decode(cfg: StreamConfig, grid: UniformGrid):
    """Chunked native decode for broker-fed POINT streams (CSV/TSV/GeoJSON):
    the bulk replay parser applied to poll batches, returning a COLUMNAR
    :class:`~spatialflink_tpu.streams.bulk.PointChunk` (vectorized cell
    assignment; per-record Point objects materialize only if a non-columnar
    consumer flattens). None when the format cannot ride it (the tap then
    parses per record)."""
    from spatialflink_tpu.streams import bulk as B
    from spatialflink_tpu.utils import IdInterner

    fmt = cfg.format.lower()
    if fmt not in ("csv", "tsv", "geojson"):
        return None
    interner = IdInterner()
    schema = _schema4(cfg)

    def decode(raws: List[str]):
        data = "\n".join(raws).encode()
        if fmt == "geojson":
            parsed = B.bulk_parse_geojson(data, interner=interner,
                                          **cfg.geojson_kwargs())
        else:
            parsed = B.bulk_parse_csv(
                data, delimiter="\t" if fmt == "tsv" else cfg.delimiter,
                schema=schema, date_format=cfg.date_format,
                interner=interner)
        return B.PointChunk.build(parsed, grid)

    decode.interner = interner
    return decode


def _preproduce(broker, topic: str, path: str, limit: Optional[int]) -> None:
    """Produce the file to the topic EXACTLY ONCE across restarts: records
    already in the topic count as the file's prefix (this mode assumes the
    topic is fed only by this file), so a re-run of the same command after a
    crash — even a crash mid-preproduce — resumes producing at the first
    missing record instead of appending a duplicate copy (which would
    corrupt every window still covered by uncommitted offsets) or silently
    truncating the dataset."""
    from spatialflink_tpu.streams.sources import FileReplaySource

    have = broker.end_offset(topic)
    lim = None if limit is None else max(0, limit - have)
    n = 0
    for line in FileReplaySource(path, limit=lim, skip=have):
        broker.produce(topic, line)
        n += 1
    if have and n:
        print(f"# topic '{topic}' already held {have} records (interrupted "
              f"produce?); resumed {path} from record {have} (+{n})",
              file=sys.stderr)
    elif have:
        print(f"# topic '{topic}' already holds {have} records; NOT "
              f"re-producing {path} (restart detected — consumption resumes "
              "from the group's committed offset)", file=sys.stderr)
    else:
        print(f"# produced {n} records from {path} -> topic '{topic}'",
              file=sys.stderr)


# the operator families whose window-mode pipelines run records through the
# shared event-time WindowAssembler — eligible for window-aligned offset
# commits and the marker-keyed output sink (apps/deser have bespoke result
# shapes and commit only on full drain)
_KAFKA_WINDOWED_FAMILIES = ("range", "knn", "join", "tfilter", "trange",
                            "tstats", "taggregate", "tjoin", "tknn")


@dataclass
class _KafkaWiring:
    """The driver's broker-backed I/O: sources (+ commit taps), the
    marker-keyed window sink, the plain record sink, and the latency topic
    (reference topology: ``StreamingJob.java:473,512`` +
    ``HelperClass.java:455-529``)."""

    broker: object
    stream1: Iterable
    stream2: Optional[Iterable]
    sources: List
    taps: List
    win_sink: Optional[object]
    plain_sink: object
    latency_topic: str
    group: str
    #: for realtime single-stream cases: commit position minus this lag on
    #: every emitted result — any record more than pipeline_depth+1
    #: micro-batches behind the read head is in a long-emitted batch, so a
    #: restart reprocesses a bounded tail instead of the whole topic
    commit_lag: Optional[int] = None
    #: set by the --kafka --bulk drain: (topic, next_offset) pairs covering
    #: the drained range; finish() commits exactly these (the sources were
    #: never iterated, so their positions are meaningless)
    bulk_offsets: Optional[List] = None
    #: degradation counters at wiring time: the summary reports the DELTA,
    #: so a later in-process run doesn't inherit an earlier run's chaos/
    #: retry/dlq counts (the registry is process-global)
    deg_baseline: Optional[dict] = None

    def emit(self, result) -> None:
        """Produce one pipeline result, then advance window-aligned commits
        (produce-before-commit is the at-least-once ordering)."""
        suppressed = False
        if isinstance(result, WindowResult) and self.win_sink is not None:
            before = self.win_sink.duplicates_suppressed
            self.win_sink.emit(result)
            suppressed = self.win_sink.duplicates_suppressed > before
            for tap in self.taps:
                tap.on_window_emitted(result.window_end)
        elif isinstance(result, WindowResult):
            for rec in result.flat_records():
                self.plain_sink.emit(rec)
        elif (isinstance(result, tuple) and len(result) == 2
                and isinstance(result[0], SpatialObject)):
            # deser-family (obj, serialized) conformance pairs
            self.plain_sink.emit(result[0])
        else:
            self.plain_sink.emit(result)
        lats = (result.extras.get("latency_ms")
                if isinstance(result, WindowResult) else None)
        if lats and not suppressed:
            # a window the sink suppressed as a re-delivered duplicate must
            # not double its latency samples either (and restart-time
            # re-deliveries would skew the distribution upward)
            for v in lats:
                self.broker.produce(self.latency_topic, v)
        if self.commit_lag is not None:
            for src in self.sources:
                src.commit_to(max(0, src.position - self.commit_lag))

    def finish(self) -> None:
        """Bounded input fully drained + flushed: every consumed record is
        reflected in produced output, so the full positions commit. NOT
        called on a control-tuple stop or crash — the conservative
        window-aligned commits stand, and restart re-delivers."""
        if self.bulk_offsets is not None:
            for topic, off in self.bulk_offsets:
                self.broker.commit(topic, self.group, off)
            return
        tapped = {id(t.source) for t in self.taps}
        for tap in self.taps:
            tap.commit_all()
        for src in self.sources:
            if id(src) not in tapped:
                src.commit_to(src.position)

    def summary(self) -> str:
        from spatialflink_tpu.utils.metrics import degradation_snapshot

        parts = []
        if self.win_sink is not None:
            parts.append(f"{self.win_sink.windows_produced} windows produced"
                         f" (+{self.win_sink.duplicates_suppressed} "
                         "re-delivered suppressed)")
        parts.append("committed " + ", ".join(
            f"{s.topic}@{s.broker.committed(s.topic, s.group)}"
            for s in self.sources))
        base = self.deg_baseline or {}
        deg = {k: v - base.get(k, 0) for k, v in
               degradation_snapshot().items() if v > base.get(k, 0)}
        if deg:
            # injected faults + recovery activity (retries, breaker trips,
            # verified produces, dead-lettered records) THIS run — the
            # "how rough was the transport" digest
            parts.append("degraded: " + ", ".join(
                f"{k}={v}" for k, v in sorted(deg.items())))
        return "# kafka: " + "; ".join(parts)


def _topic_reader(kafka: _KafkaWiring, topic: str, limit: Optional[int],
                  offsets_out: List):
    """Zero-arg LAZY drain of one topic for run_option_bulk (called only
    after the cheap bulk gates pass): committed offset -> current end
    (bounded by --limit) as newline-joined bytes, recording the drained
    range in ``offsets_out`` for the post-run commit. Returns None — the
    fall-back-to-streaming signal — when any record cannot ride the bulk
    path: non-string values, embedded newlines (they would shift the
    line<->record mapping), or a control tuple (the streaming path honors
    its stop semantics)."""
    def drain() -> Optional[bytes]:
        b = kafka.broker
        off = b.committed(topic, kafka.group)
        end = b.end_offset(topic)
        if limit is not None:
            end = min(end, off + limit)
        from spatialflink_tpu.streams.kafka import resequence_batch

        vals: List[str] = []
        while off < end:
            batch = b.fetch(topic, off, min(65536, end - off))
            if not batch:
                break
            for r in resequence_batch(batch, off):
                v = r.value
                if not isinstance(v, str) or "\n" in v or '"control"' in v:
                    print(f"# --kafka --bulk: topic '{topic}' not "
                          "bulk-drainable (non-string/multiline/control "
                          "records); using the streaming path",
                          file=sys.stderr)
                    return None
                vals.append(v)
                off = r.offset + 1
        offsets_out.append((topic, off))
        return "\n".join(vals).encode()

    def read() -> Optional[bytes]:
        from spatialflink_tpu.utils.telemetry import span as _tel_span

        # the drain is the --kafka --bulk path's ingest stage (one call)
        with _tel_span("ingest", query="kafka-drain"):
            return drain()

    return read


def _wire_kafka(params: Params, spec: CaseSpec, args, skip1: int
                ) -> _KafkaWiring:
    from spatialflink_tpu.streams.kafka import (KafkaSink, KafkaSource,
                                                KafkaWindowSink,
                                                WindowCommitTap,
                                                resolve_broker)

    from spatialflink_tpu.utils.metrics import degradation_snapshot

    bootstrap = args.kafka_bootstrap or params.kafka_bootstrap_servers
    group = args.kafka_group
    chaos_spec = getattr(args, "chaos", None)
    retry_spec = getattr(args, "retry", None)
    use_dlq = bool(getattr(args, "dlq", False))
    deg_baseline = degradation_snapshot()
    t1, t2 = params.input1.topic_name, params.input2.topic_name
    windowed = (spec.mode == "window" and params.window.type != "COUNT"
                and spec.family in _KAFKA_WINDOWED_FAMILIES)
    commit_lag = None
    if spec.mode == "realtime" and spec.family in ("range", "knn"):
        # stateless single-stream micro-batches: a lagged commit bounds
        # restart reprocessing (join's rolling buffer and the stateful
        # trajectory/app cases keep end-only commits — their records stay
        # live past their own batch)
        qc = _query_conf(params, spec)
        commit_lag = (max(1, qc.pipeline_depth) + 1) * qc.realtime_batch_size
    # validate BEFORE any broker side effect (a rejected command must not
    # leave records on a shared cluster's input topic)
    if args.kafka_follow and not windowed and commit_lag is None and not (
            args.checkpoint and spec.family in ("tstats", "taggregate")):
        raise ValueError(
            "--kafka-follow needs a case with incremental commit support "
            "(event-time windowed families, realtime range/kNN, or "
            "checkpointed tStats/tAggregate with --checkpoint): an "
            "unbounded run of this case would never advance the group "
            "offset and a restart would reprocess the entire topic")

    broker = resolve_broker(bootstrap)
    if chaos_spec is not None:
        # fault injection UNDER the supervisor, so the recovery machinery
        # (not the pipeline) eats the injected faults — the layering a real
        # flaky cluster imposes
        from spatialflink_tpu.runtime.faults import ChaosBroker, FaultPlan

        broker = ChaosBroker(broker, FaultPlan.from_spec(chaos_spec))
    if retry_spec is not None:
        from spatialflink_tpu.runtime.supervisor import SupervisedBroker

        broker = SupervisedBroker.from_spec(broker, retry_spec)
    # bounded replay THROUGH the broker: file records become topic records
    if args.input1:
        _preproduce(broker, t1, args.input1, args.limit)
    if args.input2:
        _preproduce(broker, t2, args.input2, args.limit)
    # a checkpointed resume seeks the group past the records the saved state
    # already reflects — the file path's skip, as an offset commit (commit
    # is monotone, so an older checkpoint can never rewind the group)
    if skip1:
        broker.commit(t1, group, skip1)
    coord = getattr(params, "checkpointer", None)
    if coord is not None and retry_spec is not None:
        # carry the circuit breaker across restarts: a resume into a still-
        # degraded transport starts with the checkpointed failure history
        # instead of re-learning the outage from scratch
        coord.register("supervisor", lambda: ({}, broker.snapshot()),
                       lambda _arrays, meta: broker.restore(meta))
    if coord is not None and coord.restored:
        from spatialflink_tpu.utils import telemetry as _telemetry

        depth = 0
        for topic in dict.fromkeys([t1, t2]):
            pos = coord.position(f"kafka:{topic}", 0)
            if pos:
                broker.commit(topic, group, pos)
                depth += max(0, broker.end_offset(topic) - pos)
        print(f"# resume: consumer group sought to checkpointed offsets; "
              f"{depth} records past the checkpoint to (re)process",
              file=sys.stderr)
        tel = _telemetry.active()
        if tel is not None:
            tel.gauge("recovery.replay-depth").set(depth)
    follow = bool(args.kafka_follow)
    u_grid, q_grid = params.grids()
    size_ms, step_ms = params.window_ms()
    geom1 = spec.stream if spec.family in ("range", "knn", "join") \
        else "Point"
    geom2 = spec.query if spec.family == "join" else "Point"
    # point streams batch the decode through the native bulk parser; in
    # live (follow) mode the source's starvation sentinel bounds the chunk
    # buffering latency to one poll cycle, and a smaller chunk keeps the
    # per-flush work short
    two_stream = (spec.family in ("join", "tjoin")
                  or (spec.family == "staytime" and spec.query == "Polygon"))
    bulk1 = (_kafka_bulk_decode(params.input1, u_grid)
             if windowed and geom1 == "Point" else None)
    bulk2 = (_kafka_bulk_decode(params.input2, q_grid)
             if windowed and two_stream and geom2 == "Point" else None)
    # both modes seed at the measured 2048-4096 throughput/latency knee
    # (the old follow default of 512 sat on the wrong side of it — 20-50%
    # p99 on the table); the chunk governor, when installed, owns the
    # size from that starting point via its per-flush callback
    chunk = _governed_chunk(_decode_chunk_env(2048))
    # --limit bounds THIS run's consumption per stream (from the group's
    # resume point), mirroring the file path's record bound. Follow mode
    # ALWAYS sets the starvation sentinel on windowed sources: the commit
    # tap's chunk hand-off (native decode or record-mode batching) flushes
    # on it, so chunking never adds more than one poll cycle of latency.
    src1 = KafkaSource(broker, t1, group, auto_commit=False,
                       stop_at_end=not follow, limit=args.limit,
                       starvation_sentinel=follow and windowed,
                       commit_lag=commit_lag)
    sources = [src1]
    src2 = None
    if two_stream:
        src2 = KafkaSource(broker, t2, group, auto_commit=False,
                           stop_at_end=not follow, limit=args.limit,
                           starvation_sentinel=follow and windowed,
                           commit_lag=commit_lag)
        sources.append(src2)

    out = params.output.topic_name
    dlq = None
    if use_dlq and not windowed:
        # the quarantine hook lives in the windowed commit tap's parse
        # stage; realtime/app/deser cases parse inside their pipelines and
        # a poison record still raises — say so instead of silently
        # accepting a flag that protects nothing
        print("warning: --dlq applies to event-time windowed --kafka "
              "cases only; this case parses in-pipeline and poison "
              "records will still fail the run", file=sys.stderr)
    elif use_dlq:
        from spatialflink_tpu.runtime.supervisor import DeadLetterQueue

        dlq = DeadLetterQueue(broker, out + "-dlq")
    taps: List = []
    stream1: Iterable = src1
    stream2: Optional[Iterable] = src2
    if windowed:
        stream1 = WindowCommitTap(src1, size_ms, step_ms,
                                  parse=_parse_fn(params.input1, u_grid,
                                                  geom1),
                                  bulk_decode=bulk1, bulk_chunk=chunk,
                                  dlq=dlq, checkpointer=coord)
        taps.append(stream1)
        if src2 is not None:
            stream2 = WindowCommitTap(src2, size_ms, step_ms,
                                      parse=_parse_fn(params.input2, q_grid,
                                                      geom2),
                                      bulk_decode=bulk2, bulk_chunk=chunk,
                                      dlq=dlq, checkpointer=coord)
            taps.append(stream2)
    elif coord is not None:
        # non-windowed (realtime) supported cases: a pass-through tap
        # reports the live source position at each record hand-off, so
        # coordinated checkpoints can seek the group on resume
        from spatialflink_tpu.runtime.checkpoint import CheckpointTap

        stream1 = CheckpointTap(src1, coord, f"kafka:{t1}",
                                position_fn=lambda: src1.position)
        if src2 is not None:
            stream2 = CheckpointTap(src2, coord, f"kafka:{t2}",
                                    position_fn=lambda: src2.position)

    sink_kw = dict(fmt=args.output_format,
                   date_format=params.input1.date_format,
                   delimiter=params.output.delimiter)
    win_sink = KafkaWindowSink(broker, out,
                               job_id=params.job_fingerprint(group),
                               seed_scan_limit=getattr(
                                   args, "seed_scan_limit", None),
                               **sink_kw) if windowed else None
    return _KafkaWiring(
        broker=broker, stream1=stream1, stream2=stream2, sources=sources,
        taps=taps, win_sink=win_sink,
        plain_sink=KafkaSink(broker, out, **sink_kw),
        latency_topic=out + "-latency", group=group, commit_lag=commit_lag,
        deg_baseline=deg_baseline)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="spatialflink-tpu",
        description="TPU-native spatial stream query driver "
                    "(StreamingJob equivalent)")
    ap.add_argument("--config", required=True, help="YAML config path")
    ap.add_argument("--input1", help="newline-delimited input file for stream 1")
    ap.add_argument("--input2", help="newline-delimited input file for stream 2")
    ap.add_argument("--limit", type=int, default=None,
                    help="max records to read per stream")
    ap.add_argument("--option", type=int, default=None,
                    help="override query.option")
    ap.add_argument("--format", default=None,
                    help="override inputStream1.format (GeoJSON/WKT/CSV/TSV)")
    ap.add_argument("--format2", default=None,
                    help="override inputStream2.format (two-stream cases)")
    ap.add_argument("--checkpoint", default=None,
                    help="state checkpoint file for stateful realtime queries "
                         "(tStats): saved periodically, restored at startup")
    ap.add_argument("--checkpoint-every", type=int, default=16,
                    help="micro-batches between checkpoints (default 16); "
                         "with --checkpoint-dir, processing units (windows/"
                         "micro-batches) between coordinated checkpoints")
    ap.add_argument("--checkpoint-dir", metavar="DIR", default=None,
                    help="coordinated pipeline checkpointing: periodically "
                         "snapshot source positions, watermarks, open "
                         "window/pane buffers, pane-kernel partials, "
                         "trajectory state, and circuit-breaker state into "
                         "one atomic checksummed manifest under DIR "
                         "(retaining the last --checkpoint-retain, falling "
                         "back past corrupt ones). Resume with --resume: "
                         "sources seek to the checkpointed offsets and "
                         "re-emitted windows are suppressed (--kafka: the "
                         "marker-seeded window sink; stdout/--output: a "
                         "durable emitted-window journal in DIR) — bounded "
                         "replay, exactly-once windowed output. Realtime "
                         "results on the plain sink stay at-least-once "
                         "across a resume. Windowed + realtime range/kNN, "
                         "windowed join/trajectory, realtime tStats/"
                         "tAggregate; record path only (not --bulk)")
    ap.add_argument("--resume", action="store_true",
                    help="restore the newest valid checkpoint from "
                         "--checkpoint-dir before running (refuses a "
                         "checkpoint written by a different query/window "
                         "config or consumer group)")
    ap.add_argument("--checkpoint-interval", type=float, default=None,
                    metavar="SECONDS",
                    help="also checkpoint when this much wall time passed "
                         "since the last one (default: batch cadence only)")
    ap.add_argument("--checkpoint-retain", type=int, default=3,
                    help="retained checkpoint manifests in --checkpoint-dir "
                         "(default 3); older ones are pruned, corrupt newest "
                         "falls back to the previous")
    ap.add_argument("--devices", type=int, default=None,
                    help="shard window batches across this many devices "
                         "(power of two; overrides query.parallelism)")
    ap.add_argument("--hosts", type=int, default=None,
                    help="outer DCN axis width: > 1 builds the 2-D "
                         "multi-host mesh (hosts x devices/hosts; overrides "
                         "query.hosts)")
    ap.add_argument("--output", default=None,
                    help="also write every result RECORD to this file, one "
                         "per line, serialized in --output-format — the "
                         "reference's output Kafka topic "
                         "(StreamingJob.java:512, Serialization.java output "
                         "schemas), as a file")
    ap.add_argument("--output-format", default="GeoJSON",
                    choices=["GeoJSON", "WKT", "CSV", "TSV"],
                    help="serialization for --output (spatial records; "
                         "non-spatial result tuples are written as JSON "
                         "lines)")
    ap.add_argument("--metrics", action="store_true",
                    help="print a sorted-JSON metrics snapshot (counters, "
                         "meters, degradation digest) to stderr at exit")
    ap.add_argument("--telemetry-dir", metavar="DIR", default=None,
                    help="enable structured telemetry: per-stage spans "
                         "(ingest/window/kernel/merge/sink), latency "
                         "histograms, watermark-lag/backlog/grid-skew "
                         "gauges, and the degradation counters, emitted as "
                         "JSONL snapshots to DIR/telemetry.jsonl (one "
                         "immediately, one per --telemetry-interval, one at "
                         "exit) plus a final Prometheus text dump "
                         "DIR/metrics.prom. Off by default — the record "
                         "loop runs uninstrumented")
    ap.add_argument("--telemetry-interval", type=float, default=5.0,
                    metavar="SECONDS",
                    help="seconds between periodic telemetry snapshots "
                         "(default 5.0); also the --live-stats digest "
                         "cadence")
    ap.add_argument("--trace-dir", metavar="DIR", default=None,
                    help="record per-window TRACE LINEAGE (first-record "
                         "ingest, assembly, pane seals, kernel dispatch, "
                         "merge/readback, emit, sink, Kafka sink commit — "
                         "stable trace ids derived from (query, "
                         "window_start), bounded ring of the last 256 "
                         "windows) and export it at exit as Chrome "
                         "trace-event JSON to DIR/trace.json — load it in "
                         "Perfetto (ui.perfetto.dev) or chrome://tracing "
                         "to scrub the run's timeline. Activates a "
                         "telemetry session; live access via the status "
                         "server's /trace/<id> and /trace/recent")
    ap.add_argument("--status-port", type=int, default=None, metavar="PORT",
                    help="serve a live in-run status plane on "
                         "127.0.0.1:PORT (0 = ephemeral, bound port "
                         "printed): GET /healthz (SLO verdict, 200/503), "
                         "/status (full JSON snapshot: throughput, latency "
                         "percentiles, watermark lag, backlogs, pane-cache "
                         "hit rate, checkpoint age/seq, breaker/DLQ state, "
                         "hottest cells), /metrics (live Prometheus text), "
                         "/events (lifecycle event ring). Snapshots are "
                         "built per request only; without a telemetry "
                         "session (--telemetry-dir/--live-stats) the "
                         "record loop stays byte-identical and the plane "
                         "serves the always-on registry counters")
    ap.add_argument("--live-stats", action="store_true",
                    help="print a one-line pipeline digest (throughput, "
                         "windows, latency p99, watermark lag, backlog, "
                         "checkpoint age, breaker/DLQ/degradation, health) "
                         "to stderr every --telemetry-interval seconds; "
                         "activates a telemetry session. Automatic in "
                         "--kafka-follow runs that already have one")
    ap.add_argument("--slo", metavar="SPEC", default=None,
                    help="health/SLO thresholds as comma-joined key=value "
                         "pairs, e.g. 'watermark_lag_ms=5000,"
                         "p99_window_ms=250,commit_backlog=10000,"
                         "checkpoint_age_s=60,recompiles=0,"
                         "device_mem_bytes=8e9'. Drives /healthz (503 on "
                         "breach), stamps a 'health' verdict into every "
                         "telemetry snapshot and digest line, counts "
                         "breach transitions in the slo-breaches counter, "
                         "and emits slo-breach/slo-recovered (and "
                         "watermark-stall) lifecycle events")
    ap.add_argument("--postmortem-dir", metavar="DIR", default=None,
                    help="arm the flight recorder: a bounded ring of run "
                         "lifecycle notes that dumps a post-mortem bundle "
                         "directory (status snapshot, event ring, compile "
                         "registry, recent window traces, device memory "
                         "profile, config fingerprint) to DIR on crash, "
                         "first SLO breach, strict-recompile abort, or "
                         "SIGUSR1 — read it with 'python -m "
                         "spatialflink_tpu.doctor summarize/diff'. "
                         "Activates a telemetry session")
    ap.add_argument("--strict-recompile", action="store_true",
                    help="abort the run (exit 3, post-mortem bundle if "
                         "--postmortem-dir) when any XLA kernel compiles "
                         "AFTER the declared warmup — the PR 8/9 "
                         "zero-recompile contracts as a hard production "
                         "invariant instead of a test-time assert. "
                         "Observational without this flag: post-warmup "
                         "compiles still count ('device-recompiles', "
                         "'recompile' events, GET /compile)")
    ap.add_argument("--sentinel-warmup", type=int, default=1,
                    metavar="WINDOWS",
                    help="recompile-sentinel warmup: compiles stop being "
                         "expected after this many emitted windows "
                         "(default 1). Streams whose batch sizes keep "
                         "growing into fresh padding buckets late in the "
                         "run may need a larger value before "
                         "--strict-recompile is safe")
    ap.add_argument("--profile", metavar="DIR", default=None,
                    help="capture a jax.profiler trace of the run to DIR "
                         "(TensorBoard/XProf format) with per-operator "
                         "dispatch/readback annotations — the reference's "
                         "Flink web UI observability as a trace "
                         "(StreamingJob.java:70-72)")
    ap.add_argument("--bulk", action="store_true",
                    help="DEPRECATED alias: the chunk-vectorized decode + "
                         "bulk window assignment is now the only execution "
                         "path (every mode), so the flag no longer selects "
                         "a faster engine — it keeps only its whole-replay "
                         "semantics (no watermark-paced emission, no "
                         "control-tuple stop hook) for bounded files/topics")
    ap.add_argument("--pane-merge", choices=["auto", "device", "host"],
                    default=None,
                    help="where --panes partials live and merge: 'device' "
                         "keeps pane kernel partials resident in device "
                         "memory across slides and merges each sealed "
                         "window ON DEVICE (one merged readback per window "
                         "— kNN families; filter families keep their "
                         "already-optimal host union), 'host' resolves "
                         "each partial to host and merges there, 'auto' "
                         "(default) picks device on accelerator backends "
                         "(a per-pane host sync is a full dispatch RTT "
                         "there) and host on CPU (measured faster — the "
                         "pane-state bench rows are the A/B)")
    ap.add_argument("--panes", action="store_true",
                    help="pane-incremental sliding windows: buffer records "
                         "into non-overlapping slide-aligned panes, run the "
                         "device kernel once per sealed pane, and assemble "
                         "each window by merging its size/slide cached pane "
                         "partials — at overlap o the per-slide kernel work "
                         "drops ~o-fold. Results are identical to "
                         "full-window evaluation; tumbling windows and "
                         "specs whose slide does not divide the size bypass "
                         "the cache (pane-cache-hits/-misses counters show "
                         "the reuse rate)")
    ap.add_argument("--queries-file", metavar="PATH", default=None,
                    help="activate the DYNAMIC standing-query plane seeded "
                         "from a JSON file of query specs ([{'id', 'x', "
                         "'y', optional 'radius'/'k'/'route'/'slo'}, ...] "
                         "or {'queries': [...]}): the fleet batches onto "
                         "the device Q-axis (padded to power-of-two size "
                         "buckets so admissions repad instead of "
                         "recompiling) and queries are admitted/updated/"
                         "retired MID-RUN via POST/DELETE /queries on "
                         "--status-port and/or a --control-topic, with "
                         "per-query counters, routes (stdout/file:/"
                         "kafka:), SLO verdicts, GET /queries, and a "
                         "'queries' slot in coordinated checkpoints so "
                         "--resume restores the live fleet. Windowed "
                         "point-query range (all stream types) and "
                         "Point/Point kNN")
    ap.add_argument("--control-topic", metavar="TOPIC", default=None,
                    help="with --kafka: also consume JSON admit/update/"
                         "retire control records for the standing-query "
                         "plane from TOPIC ({'action': 'admit', 'query': "
                         "{...}} / {'action': 'retire', 'id': ...}), "
                         "applied at window boundaries (activates the "
                         "dynamic plane like --queries-file; both may be "
                         "used together)")
    ap.add_argument("--controller", metavar="SPEC", nargs="?", const="",
                    default=None,
                    help="closed-loop decode-chunk governor: tick on the "
                         "telemetry-reporter cadence, read the live stage "
                         "budget, and resize the decode chunk one "
                         "power-of-two bucket at a time between flushes — "
                         "shrink when queue/buffer wait dominates and the "
                         "record→emit p99 breaches, grow when dispatch-"
                         "bound or idle; never recompiles. SPEC tunes the "
                         "policy as comma key=value pairs over "
                         "target_p99_ms/min_chunk/max_chunk/"
                         "interactive_max_chunk/fast_lane_depth/"
                         "confirm_ticks/cooldown_ticks/shed_after_stalls/"
                         "unshed_after_clean/idle_headroom (bare "
                         "--controller = defaults). Needs a telemetry "
                         "session (--telemetry-dir/--live-stats/"
                         "--status-port/...) for the tick source; live "
                         "state in the controller block of GET /latency "
                         "and the stderr digest")
    ap.add_argument("--latency-class", choices=["interactive", "batch"],
                    default="batch", dest="latency_class",
                    help="latency class for this run's standing queries "
                         "(default batch; also the default class for "
                         "--queries-file/--control-topic admissions that "
                         "omit 'latency_class'). While any interactive "
                         "query serves, the --controller fast lane caps "
                         "the decode chunk at interactive_max_chunk and "
                         "bounds the pipeline queue depth to "
                         "fast_lane_depth so interactive emits never park "
                         "behind throughput amortization")
    ap.add_argument("--tenant-default", metavar="NAME", default="default",
                    dest="tenant_default",
                    help="tenant charged for queries that omit 'tenant' "
                         "(and for dispatch cost no standing query claims, "
                         "e.g. static single-query runs). Per-tenant "
                         "attributed kernel-ms/bytes, records, windows, "
                         "SLO/shed/quota counters and the fairness summary "
                         "serve at GET /tenants (+ /tenants/<id>, "
                         "tenant=\"T\" Prometheus labels, /fleet/tenants "
                         "on the supervisor); attribution splits each "
                         "measured dispatch across live fleet slots by "
                         "candidate work and sums to the measured span by "
                         "construction")
    ap.add_argument("--tenant-quota", metavar="SPEC", action="append",
                    default=None, dest="tenant_quota",
                    help="admission quota per tenant as "
                         "'T:max_active[,kernel_ms_s=X]' (repeatable, or "
                         "';'-separated). max_active caps the tenant's "
                         "held query slots (pending+active+draining+shed); "
                         "kernel_ms_s caps its recent attributed kernel-ms "
                         "per second. A breach answers POST /queries with "
                         "429 quota-exceeded and creates NO entry — unlike "
                         "backpressure shedding, which parks the spec and "
                         "auto-admits when pressure clears")
    ap.add_argument("--multi-query", action="store_true",
                    help="answer ALL configured query points/geometries in "
                         "one dispatch per window (run_multi; default keeps "
                         "reference parity: first query object only). "
                         "All nine range and kNN pairs, plus trajectory kNN")
    ap.add_argument("--adaptive-grid", nargs="?", const=4, type=int,
                    default=None, metavar="K", dest="adaptive_grid",
                    help="skew-adaptive grid: refine hot cells KxK (default "
                         "K=4) and coarsen cold neighborhoods, with "
                         "epoch-based split/merge decisions driven by the "
                         "live occupancy gauges (and per-cell attributed "
                         "cost when telemetry is on). Records keep their "
                         "base cells and device kernels are untouched; the "
                         "refined GN∪CN leaf masks gate window-batch "
                         "membership host-side before the kernel, so "
                         "exact-mode results are identical to the uniform "
                         "grid and the win is the smaller batch on skewed "
                         "streams (single-query range family; layout "
                         "served at /partition, carried in coordinated "
                         "checkpoints)")
    ap.add_argument("--repartition-interval", type=int, default=50_000,
                    metavar="N",
                    help="records per repartition epoch for "
                         "--adaptive-grid (default 50000): each epoch "
                         "re-evaluates split/merge thresholds with "
                         "hysteresis (split at 5%% epoch share, merge "
                         "back below 1.25%% for 2 consecutive epochs)")
    ap.add_argument("--shard-order", choices=["arrival", "cell"],
                    default="arrival",
                    help="mesh shard placement for distributed window "
                         "batches: 'arrival' (default) shards contiguously; "
                         "'cell' pre-permutes each batch so whole grid "
                         "cells co-locate per shard (keyBy(gridID) parity, "
                         "parallel.mesh.cell_hash_order) — results are "
                         "identical; BASELINE.md records the measured "
                         "verdict (the host permute usually costs more "
                         "than the kernel saving)")
    ap.add_argument("--kafka", action="store_true",
                    help="consume inputStream{1,2}.topicName and produce "
                         "results to outputStream.topicName through the "
                         "broker named by kafkaBootStrapServers "
                         "('memory://<name>' = the in-process shim; anything "
                         "else = a real cluster via kafka-python) — the "
                         "reference's FlinkKafkaConsumer/Producer topology "
                         "(StreamingJob.java:473,512). --input1/--input2 "
                         "files, when given, are pre-produced to the input "
                         "topics first (bounded replay through the broker)")
    ap.add_argument("--kafka-group", default="spatialflink",
                    help="consumer group id (restart resumes from the "
                         "group's committed offsets; default 'spatialflink')")
    ap.add_argument("--kafka-bootstrap", default=None,
                    help="override kafkaBootStrapServers from the config")
    ap.add_argument("--kafka-follow", action="store_true",
                    help="live mode: keep polling past the current end of "
                         "the input topic instead of stopping (a producer "
                         "feeds the topic concurrently; stop with the "
                         "control tuple)")
    ap.add_argument("--chaos", metavar="SPEC", default=None,
                    help="fault-inject the broker transport from a seeded "
                         "deterministic plan: comma-joined key=value pairs "
                         "over seed / produce_fail / ack_lost / fetch_fail "
                         "/ duplicate / reorder / torn / latency(+_ms) / "
                         "fail_next_produces / fail_next_fetches, e.g. "
                         "'seed=7,fetch_fail=0.2,torn=0.1'. Pair with "
                         "--retry (and --dlq for torn payloads) or the "
                         "injected faults will crash the run — that "
                         "contrast is the point")
    ap.add_argument("--retry", metavar="SPEC", nargs="?", const="",
                    default=None,
                    help="supervise broker produce/fetch with retry + "
                         "backoff + a circuit breaker (idempotent produce "
                         "retries: ambiguous failures re-check the log "
                         "before re-sending). Optional SPEC tunes it: "
                         "attempts / base_ms / max_ms / multiplier / "
                         "jitter / attempt_timeout_ms / deadline_ms / "
                         "seed / breaker_threshold / cooldown_ms")
    ap.add_argument("--dlq", action="store_true",
                    help="quarantine poison records (parse failures that "
                         "survive redelivery) to '<outputTopic>-dlq' with "
                         "failure metadata instead of crashing the "
                         "pipeline (windowed --kafka cases)")
    ap.add_argument("--seed-scan-limit", type=int, default=None,
                    metavar="N",
                    help="bound the output-topic dedup seed scan to the "
                         "last N records (default: full scan; the scan "
                         "warns when an uncompacted topic makes it large) "
                         "— accepts that windows committed before the "
                         "scanned tail can be re-produced on re-delivery")
    ap.add_argument("--fleet", type=int, default=None, metavar="N",
                    help="supervised multi-worker fleet: spawn N full "
                         "worker pipelines (each with its own checkpoint "
                         "manifest and opserver), partition --input1 by "
                         "grid leaf, restart dead workers from their "
                         "latest checkpoint, and merge the windowAll "
                         "results exactly-once (windowed range/kNN file "
                         "replays; aggregated view at GET /fleet)")
    ap.add_argument("--fleet-dir", metavar="DIR", default=None,
                    help="fleet working directory: per-worker partitions, "
                         "outboxes, logs, checkpoints, the fleet manifest, "
                         "and the merged result (required with --fleet; "
                         "inspect with python -m spatialflink_tpu.doctor "
                         "fleet DIR)")
    ap.add_argument("--fleet-role", choices=["supervisor", "worker"],
                    default=None,
                    help="process role under --fleet (workers are spawned "
                         "by the supervisor with this set; not for direct "
                         "use)")
    ap.add_argument("--fleet-worker-id", type=int, default=0,
                    metavar="ID", help="this worker's id (supervisor-set)")
    ap.add_argument("--fleet-heartbeat", type=float, default=1.0,
                    metavar="SECONDS",
                    help="worker heartbeat interval; the supervisor "
                         "declares a worker dead after ~5 missed beats "
                         "(default: 1.0)")
    ap.add_argument("--fleet-epoch-records", type=int, default=20000,
                    metavar="N",
                    help="repartition epoch length in routed records: at "
                         "each boundary the supervisor compares worker "
                         "backpressure and may move leaves off the hottest "
                         "worker (default: 20000)")
    ap.add_argument("--fleet-restart-cap", type=int, default=3,
                    metavar="N",
                    help="max restarts per worker before the fleet aborts "
                         "(default: 3)")
    ap.add_argument("--fleet-slo-p99-ms", type=float, default=None,
                    metavar="MS",
                    help="optional SLO supervision: restart a worker whose "
                         "record->emit p99 stays above MS for 3 "
                         "consecutive polls (default: off)")
    ap.add_argument("--fleet-chaos-kill", metavar="WID:N", default=None,
                    help="fault-injection hook: SIGKILL worker WID once "
                         "its outbox holds N windows (recovery tests and "
                         "the fault bench row)")
    ap.add_argument("--fleet-plane", choices=("on", "off"), default="on",
                    help="fleet observability plane: end-to-end "
                         "record->merged-emit lineage, the merged event "
                         "timeline, /fleet/latency|timeline|events|metrics "
                         "federation, and fleet post-mortem snapshots; "
                         "'off' disables retention and the outbox lineage "
                         "sidecar — the merged digest is identical either "
                         "way (default: on)")
    ap.add_argument("--fleet-rescale", metavar="AT:N[,AT:N...]",
                    default=None,
                    help="live rescale: once AT records have been routed, "
                         "scale the fleet to N workers at the next epoch "
                         "boundary (coordinated flush barrier, leaf "
                         "reassignment, fenced worker ids; e.g. "
                         "'10000:3,20000:2' runs 2->3->2); the merged "
                         "digest is identical to a fixed-N run")
    ap.add_argument("--fleet-chaos-stall", metavar="WID:SECONDS",
                    default=None,
                    help="fault-injection hook: worker WID's first "
                         "incarnation wedges heartbeat+checkpoints for "
                         "SECONDS after its first window while continuing "
                         "to write (gray-failure drill: the supervisor "
                         "fences+respawns it WITHOUT a kill; the zombie's "
                         "stale rows must be dropped at merge)")
    ap.add_argument("--fleet-quarantine-s", type=float, default=10.0,
                    metavar="S",
                    help="gray-failure quarantine deadline: a worker whose "
                         "suspicion score stays high is first drained of "
                         "new leaf routes (still merging its output), then "
                         "fenced+respawned after S seconds without "
                         "recovery (default: 10)")
    ap.add_argument("--fleet-fence", type=int, default=0,
                    metavar="TOKEN",
                    help=argparse.SUPPRESS)  # supervisor-issued fence
    ap.add_argument("--fleet-stall-s", type=float, default=0.0,
                    metavar="S",
                    help=argparse.SUPPRESS)  # chaos glue, supervisor-set
    args = ap.parse_args(argv)

    _enable_compilation_cache()
    params = Params.from_yaml(args.config)
    if args.option is not None:
        params.query.option = args.option
    if args.multi_query:
        params.query.multi_query = True
    if args.panes:
        params.query.panes = True
    if args.pane_merge is not None and args.pane_merge != "auto":
        params.query.pane_device_merge = args.pane_merge == "device"
    if args.bulk:
        print("note: --bulk is deprecated — the batched columnar path is "
              "now the default for every mode; the flag keeps only its "
              "whole-replay semantics (see README)", file=sys.stderr)
    if args.devices is not None:
        params.query.parallelism = args.devices
    if args.hosts is not None:
        params.query.hosts = args.hosts
    try:
        params.validate_mesh()
    except Exception as e:
        ap.error(str(e))
    if args.format is not None or args.format2 is not None:
        import dataclasses

        i1 = (dataclasses.replace(params.input1, format=args.format)
              if args.format is not None else params.input1)
        i2 = (dataclasses.replace(params.input2, format=args.format2)
              if args.format2 is not None else params.input2)
        params = dataclasses.replace(params, input1=i1, input2=i2)
    if args.checkpoint:
        params.checkpoint_path = args.checkpoint
        params.checkpoint_every = args.checkpoint_every
        # the job fingerprint rides the checkpoint meta so a resume under a
        # DIFFERENT query/window config is refused instead of silently
        # producing wrong state (the old silent-footgun UX)
        params.checkpoint_job = params.job_fingerprint(args.kafka_group)
        cp_spec = CASES.get(params.query.option)
        if cp_spec and not (cp_spec.family in ("tstats", "taggregate")
                            and cp_spec.mode == "realtime"):
            print("--checkpoint only applies to stateful realtime queries "
                  "(tStats 205 / tAggregate 207); ignored for this case",
                  file=sys.stderr)
        elif os.path.exists(args.checkpoint):
            # pre-flight: fail at arg-parse time with the SAME shared guard
            # the restore path enforces (fast, before any broker/source
            # side effect)
            from spatialflink_tpu.runtime.checkpoint import (
                CheckpointMismatch, check_job_fingerprint)
            from spatialflink_tpu.runtime.state import (CheckpointCorrupt,
                                                        checkpoint_meta)

            try:
                check_job_fingerprint(
                    checkpoint_meta(args.checkpoint).get("job"),
                    params.checkpoint_job, args.checkpoint)
            except CheckpointCorrupt as e:
                ap.error(f"--checkpoint: {e} (delete the file, or restore "
                         "a retained copy, to start over)")
            except CheckpointMismatch as e:
                ap.error(str(e))

    spec = CASES.get(params.query.option)
    if spec is None:
        print(f"unknown queryOption {params.query.option}", file=sys.stderr)
        return 2
    if args.fleet is not None and args.fleet_role != "worker":
        # supervised multi-worker fleet: validate here (argparse-grade
        # errors), then hand the whole run to the supervisor — workers
        # re-enter main() as plain single-process pipelines
        if args.fleet < 1:
            ap.error("--fleet needs N >= 1 workers")
        if not args.fleet_dir:
            ap.error("--fleet requires --fleet-dir (worker partitions, "
                     "outboxes, and the fleet manifest live there)")
        if args.kafka or not args.input1:
            ap.error("--fleet partitions a file replay and needs "
                     "--input1 (kafka transport stays single-process)")
        if spec.mode != "window" or spec.family not in ("range", "knn"):
            ap.error("--fleet supports windowed range/kNN cases (the "
                     "windowAll merge families); option "
                     f"{params.query.option} is {spec.family}/{spec.mode}")
        if args.bulk or params.query.multi_query:
            ap.error("--fleet does not compose with --bulk or "
                     "--multi-query")
        if args.queries_file or args.control_topic:
            ap.error("--fleet does not compose with the dynamic query "
                     "plane (each worker runs the static configured "
                     "query)")
        if args.adaptive_grid is not None:
            ap.error("--fleet owns the leaf placement layout; "
                     "--adaptive-grid inside workers does not compose")
        from spatialflink_tpu.runtime import fleetsup

        base_argv = list(sys.argv[1:] if argv is None else argv)
        return fleetsup.run_supervisor(args, params, spec, base_argv)
    if args.fleet_role == "worker" and not (
            args.fleet_dir and args.input1 and args.checkpoint_dir):
        ap.error("--fleet-role worker needs --fleet-dir, --input1 and "
                 "--checkpoint-dir (workers are spawned by the "
                 "supervisor, not launched directly)")
    if args.kafka and args.bulk and args.kafka_follow:
        ap.error("--kafka-follow and --bulk are mutually exclusive "
                 "(bulk is a bounded vectorized drain, not a live stream)")
    # the dynamic standing-query plane (validated/constructed below, after
    # the checkpointer exists); the flag participates in the checkpoint
    # LAYOUT tag — a dynamic run's manifest carries a 'queries' component a
    # static run could never restore
    dynamic_queries = bool(args.queries_file or args.control_topic)
    if args.resume and not args.checkpoint_dir:
        ap.error("--resume requires --checkpoint-dir")
    if args.checkpoint_dir:
        if args.checkpoint:
            ap.error("--checkpoint-dir and --checkpoint are mutually "
                     "exclusive (the directory coordinator subsumes the "
                     "single-file tStats/tAggregate checkpoint)")
        if args.bulk:
            ap.error("--checkpoint-dir does not compose with --bulk "
                     "(bulk is a whole-replay; coordinated checkpoints "
                     "apply to the record path)")
        reason = _checkpoint_dir_unsupported(params, spec)
        if reason:
            print(f"--checkpoint-dir ignored: {reason}", file=sys.stderr)
        else:
            from spatialflink_tpu.runtime.checkpoint import (
                CheckpointCoordinator, CheckpointMismatch)

            # source identity: the sink-dedup job fingerprint deliberately
            # excludes transport/source (a sharded or re-encoded re-run must
            # dedup against the original's markers), but a CHECKPOINT is
            # bound to the exact source its positions index into — resuming
            # against a different file/topic/broker would seek into records
            # that were never processed
            if args.kafka:
                src_id = ("kafka:" + (args.kafka_bootstrap
                                      or params.kafka_bootstrap_servers)
                          + f"/{params.input1.topic_name}"
                          + f",{params.input2.topic_name}")
            else:
                src_id = f"file:{args.input1},{args.input2}"
            coord = CheckpointCoordinator(
                args.checkpoint_dir,
                every_batches=args.checkpoint_every,
                every_seconds=args.checkpoint_interval,
                retain=args.checkpoint_retain,
                job=params.job_fingerprint(args.kafka_group),
                # execution knobs the job fingerprint deliberately excludes
                # but the manifest's component layout + positions depend on
                layout=(f"{spec.family}:{spec.mode}"
                        f":panes={int(bool(params.query.panes))}"
                        f":multi={int(bool(params.query.multi_query))}"
                        f":dyn={int(dynamic_queries)}"
                        f":{src_id}"))
            if args.resume:
                try:
                    restored = coord.load()
                except CheckpointMismatch as e:
                    ap.error(str(e))
                if restored:
                    print(f"# resuming from checkpoint seq {coord.seq} "
                          f"(source positions: {coord.positions() or '{}'})",
                          file=sys.stderr)
                else:
                    print("# --resume: no valid checkpoint in "
                          f"{args.checkpoint_dir}; starting fresh",
                          file=sys.stderr)
            # dynamic attribute (not a dataclass field): the coordinator
            # must not leak into Params.to_dict()/fingerprints
            params.checkpointer = coord
    if args.shard_order != "arrival":
        params.shard_order = args.shard_order
    if args.adaptive_grid is not None:
        if args.bulk:
            # the whole-replay alias builds its batches straight from the
            # parsed file before any window-time refinement could gate them
            print("--adaptive-grid ignored with --bulk (whole-replay "
                  "batches bypass the window-time prefilter); the default "
                  "batched path supports it", file=sys.stderr)
        else:
            from spatialflink_tpu.index import AdaptiveGrid
            from spatialflink_tpu.runtime.repartition import (
                RepartitionController)

            try:
                agrid = AdaptiveGrid(params.grids()[0],
                                     refine=args.adaptive_grid)
            except ValueError as e:
                ap.error(f"--adaptive-grid: {e}")
            ctl = RepartitionController(
                agrid, interval_records=args.repartition_interval)
            coord = getattr(params, "checkpointer", None)
            if coord is not None:
                # grid layout rides the coordinated manifest: --resume
                # restores the adapted partitioning (auto-applied here if
                # the coordinator already loaded one)
                ctl.register_checkpoint(coord)
            # dynamic attributes (not dataclass fields), like checkpointer:
            # must not leak into Params.to_dict()/fingerprints
            params.adaptive_grid = agrid
            params.repartitioner = ctl
            print(f"# adaptive grid: hot cells split "
                  f"{args.adaptive_grid}x{args.adaptive_grid}, repartition "
                  f"epoch every {args.repartition_interval} records "
                  "(layout at /partition)", file=sys.stderr)
    # tenant quotas parse up front: a malformed SPEC is a flag error, not a
    # mid-run surprise at first admission
    tenant_quotas = {}
    if getattr(args, "tenant_quota", None):
        from spatialflink_tpu.utils.accounting import parse_tenant_quotas

        try:
            tenant_quotas = parse_tenant_quotas(";".join(args.tenant_quota))
        except ValueError as e:
            ap.error(f"--tenant-quota: {e}")
    if dynamic_queries:
        from spatialflink_tpu.runtime.queryplane import (QueryRegistry,
                                                         QuerySpec,
                                                         QuerySpecError,
                                                         load_queries_file)

        if args.control_topic and not args.kafka:
            ap.error("--control-topic consumes admissions from the broker "
                     "and needs --kafka")
        if (spec.family not in ("range", "knn") or spec.query != "Point"
                or (spec.family == "knn" and spec.stream != "Point")):
            ap.error("--queries-file/--control-topic (the dynamic "
                     "standing-query plane) serve point-query fleets: "
                     "windowed range over any stream type, and Point/Point "
                     f"kNN — not queryOption {params.query.option} "
                     f"({spec.family}, {spec.stream}x{spec.query})")
        if spec.mode != "window" or params.window.type == "COUNT":
            ap.error("the dynamic standing-query plane runs event-time "
                     "windowed cases only (the fleet changes at window "
                     "boundaries)")
        if spec.latency:
            ap.error("the dynamic standing-query plane does not combine "
                     "with the latency variants (per-record latency "
                     "assumes single-query record lists)")
        if args.bulk:
            ap.error("the dynamic standing-query plane does not compose "
                     "with --bulk (a whole-replay has no admission "
                     "boundaries)")
        if params.query.multi_query:
            ap.error("--multi-query is subsumed by the query registry "
                     "(the live fleet IS the multi-query set); drop the "
                     "flag")
        if params.query.panes:
            print("note: --panes is bypassed on the dynamic standing-query "
                  "path (pane partials are fleet-shaped; a fleet change "
                  "would serve stale partials) — full-window evaluation",
                  file=sys.stderr)
        registry = QueryRegistry(spec.family, radius=params.query.radius,
                                 k=params.query.k,
                                 default_latency_class=args.latency_class,
                                 default_tenant=args.tenant_default,
                                 tenant_quotas=tenant_quotas)
        coord = getattr(params, "checkpointer", None)
        restored = bool(coord is not None
                        and registry.register_checkpoint(coord))
        if restored:
            print(f"# resume: restored standing-query fleet "
                  f"(version {registry.fleet_version}, "
                  f"{len(registry.active_entries())} live)", file=sys.stderr)
        else:
            seeds = []
            try:
                if args.queries_file:
                    seeds = load_queries_file(
                        args.queries_file, spec.family,
                        default_latency_class=args.latency_class,
                        default_tenant=args.tenant_default)
            except (OSError, ValueError) as e:
                ap.error(f"--queries-file: {e}")
            if not seeds and params.query.query_points:
                # the config's queryPoints seed the fleet (the registry is
                # the source of truth for what runs; the static config is
                # just its time-zero admission batch)
                seeds = [QuerySpec(id=f"q{i}", family=spec.family, x=x, y=y,
                                   latency_class=args.latency_class)
                         for i, (x, y) in
                         enumerate(params.query.query_points)]
            try:
                for s in seeds:
                    registry.admit(s)
            except QuerySpecError as e:
                ap.error(f"--queries-file: {e}")
            # seeds serve from window one — dedicated-static-run parity
            registry.apply()
        # dynamic attribute, like checkpointer: must not leak into
        # Params.to_dict()/fingerprints
        params.query_registry = registry
        surfaces = ["POST/DELETE /queries (--status-port)"]
        if args.control_topic:
            surfaces.append(f"control topic '{args.control_topic}'")
        print(f"# query plane: dynamic {spec.family} fleet, "
              f"{len(registry.active_entries())} live "
              f"(admission via {' + '.join(surfaces)})", file=sys.stderr)
    if not args.kafka and (args.chaos is not None or args.retry is not None
                           or args.dlq or args.seed_scan_limit is not None):
        ap.error("--chaos/--retry/--dlq/--seed-scan-limit wrap the broker "
                 "transport and need --kafka")
    if args.kafka and spec.family in ("shapefile", "synthetic"):
        ap.error(f"--kafka does not apply to the {spec.family} cases "
                 "(no input topic)")
    if not args.input1 and not args.kafka and spec.family not in ("synthetic",):
        print("--input1 is required for this queryOption", file=sys.stderr)
        return 2
    # a resumed checkpointed run must not re-apply records the saved state
    # already reflects: the checkpoint records a consumed-record offset and
    # the file replay skips that many (a Kafka consumer group would seek)
    skip1 = 0
    if (args.checkpoint and spec.family in ("tstats", "taggregate")
            and spec.mode == "realtime"):
        from spatialflink_tpu.runtime.state import checkpoint_consumed

        skip1 = checkpoint_consumed(args.checkpoint)
        if skip1:
            print(f"# resuming from checkpoint: skipping {skip1} "
                  "already-consumed records", file=sys.stderr)

    # --limit bounds the *original* record range: a resumed run covers the
    # remainder of that range, not N additional records past the checkpoint
    limit1 = args.limit
    if skip1 and limit1 is not None:
        limit1 = max(0, limit1 - skip1)

    health = None
    if args.slo is not None:
        from spatialflink_tpu.runtime.health import HealthEvaluator

        try:
            health = HealthEvaluator.from_spec(args.slo)
        except ValueError as e:
            ap.error(str(e))
        if (args.status_port is None and not args.telemetry_dir
                and not args.live_stats):
            print("warning: --slo has no consumer without --status-port, "
                  "--telemetry-dir, or --live-stats (nothing evaluates "
                  "the thresholds)", file=sys.stderr)

    if args.controller is not None:
        from spatialflink_tpu.runtime.control import (ChunkGovernor,
                                                      GovernorPolicy)

        try:
            policy = GovernorPolicy.from_spec(args.controller)
        except ValueError as e:
            ap.error(str(e))
        # dynamic attribute, like checkpointer/query_registry: must not
        # leak into Params.to_dict()/fingerprints
        params.chunk_governor = ChunkGovernor(policy=policy)
        if not (args.telemetry_dir or args.live_stats or args.trace_dir
                or args.postmortem_dir):
            print("warning: --controller has no tick source without a "
                  "telemetry session (--telemetry-dir/--live-stats/"
                  "--trace-dir/--postmortem-dir): the latency plane's "
                  "bucket close drives the control law, so the chunk "
                  "stays at its seed", file=sys.stderr)

    if (args.telemetry_dir or args.live_stats or args.trace_dir
            or args.postmortem_dir):
        from spatialflink_tpu.utils.telemetry import telemetry_session

        # the session must wrap the KAFKA WIRING too (taps/sinks capture
        # their gauges at construction), not just the result loop.
        # --live-stats/--trace-dir/--postmortem-dir without
        # --telemetry-dir run a reporterless session (instrumentation on;
        # the digest / trace book / flight-recorder bundle are fed from
        # it)
        with telemetry_session(args.telemetry_dir or None,
                               args.telemetry_interval, health=health,
                               trace_dir=args.trace_dir):
            if args.telemetry_dir:
                print(f"# telemetry: JSONL snapshots every "
                      f"{args.telemetry_interval:g}s -> "
                      f"{os.path.join(args.telemetry_dir, 'telemetry.jsonl')}",
                      file=sys.stderr)
            if args.trace_dir:
                print("# tracing: per-window lineage -> "
                      f"{os.path.join(args.trace_dir, 'trace.json')} "
                      "(Chrome trace-event JSON; open in Perfetto)",
                      file=sys.stderr)
            return _run_cli(ap, args, params, spec, skip1, limit1, health)
    return _run_cli(ap, args, params, spec, skip1, limit1, health)


def _run_cli(ap, args, params: Params, spec: CaseSpec, skip1: int,
             limit1: Optional[int], health=None) -> int:
    """The post-validation half of :func:`main`: wire transport, run the
    pipeline, drain results into the sinks, print summaries. Split out so
    the telemetry session can scope the whole run."""
    from spatialflink_tpu.streams.sinks import StdoutSink
    from spatialflink_tpu.streams.sources import FileReplaySource
    from spatialflink_tpu.utils import telemetry as _telemetry

    coord = getattr(params, "checkpointer", None)
    tel = _telemetry.active()
    if tel is not None:
        # the ledger's catch-all tenant follows the flag; on resume the
        # 'tenants' checkpoint component restores cumulative attribution
        tel.tenants.default_tenant = getattr(args, "tenant_default",
                                             "default") or "default"
        if coord is not None:
            tel.tenants.register_checkpoint(coord)
    wctx = None
    if getattr(args, "fleet_role", None) == "worker":
        from spatialflink_tpu.runtime.fleet import WorkerContext

        # fleet worker glue: heartbeat + canonical outbox + tailing
        # partition source; everything else is the normal pipeline
        wctx = WorkerContext.from_args(args, spec).start()
    kafka = None
    if args.kafka:
        try:
            kafka = _wire_kafka(params, spec, args, skip1)
        except ValueError as e:
            ap.error(str(e))
        stream1, stream2 = kafka.stream1, kafka.stream2
    elif spec.family == "shapefile":
        stream1 = args.input1
    elif spec.family == "synthetic":
        stream1 = []
    elif coord is not None:
        # coordinated checkpointing over file replay: resume skips the
        # records the checkpoint already reflects (bounded replay, like a
        # consumer-group seek), and the tap reports the live position so
        # later checkpoints carry it. --limit keeps bounding the ORIGINAL
        # record range across the resume.
        from spatialflink_tpu.runtime.checkpoint import CheckpointTap

        skip_a = coord.position("file:1", 0)
        lim_a = (max(0, args.limit - skip_a)
                 if args.limit is not None else None)
        src_a = (wctx.tailing_source(limit=lim_a, skip=skip_a)
                 if wctx is not None else
                 FileReplaySource(args.input1, limit=lim_a, skip=skip_a))
        stream1 = CheckpointTap(src_a, coord, "file:1", base=skip_a)
        if skip_a:
            print(f"# resume: skipping {skip_a} already-reflected records "
                  "of --input1", file=sys.stderr)
    else:
        stream1 = (wctx.tailing_source(limit=limit1, skip=skip1)
                   if wctx is not None else
                   FileReplaySource(args.input1, limit=limit1, skip=skip1))
    if not args.kafka:
        stream2 = None
        if args.input2 and coord is not None:
            from spatialflink_tpu.runtime.checkpoint import CheckpointTap

            skip_b = coord.position("file:2", 0)
            lim_b = (max(0, args.limit - skip_b)
                     if args.limit is not None else None)
            stream2 = CheckpointTap(
                FileReplaySource(args.input2, limit=lim_b, skip=skip_b),
                coord, "file:2", base=skip_b)
        elif args.input2:
            stream2 = FileReplaySource(args.input2, limit=args.limit)

    from spatialflink_tpu.utils.metrics import ControlTupleExit

    results = None
    if args.bulk and kafka is not None:
        # vectorized TOPIC replay: the readers drain committed-offset..end
        # LAZILY (only once run_option_bulk's cheap case/format gates
        # pass); the drained offsets commit after the full run produced
        offs: List = []
        results = run_option_bulk(
            params,
            _topic_reader(kafka, params.input1.topic_name, args.limit, offs),
            _topic_reader(kafka, params.input2.topic_name, args.limit, offs))
        if results is None:
            print("# --kafka --bulk not applicable to this case/format/"
                  "topic content; using the streaming path", file=sys.stderr)
        else:
            kafka.bulk_offsets = offs
    elif args.bulk:
        results = run_option_bulk(params, args.input1, args.input2)
        if results is None:
            print("--bulk not applicable to this case/format; "
                  "using the record path", file=sys.stderr)
        elif args.limit is not None:
            print("--bulk ignores --limit (whole-file replay)", file=sys.stderr)
    if results is None:
        results = run_option(params, stream1, stream2)

    sink = StdoutSink()
    out_sink = None
    if args.output:
        from spatialflink_tpu.streams.sinks import FileSink

        out_sink = FileSink(args.output, args.output_format,
                            delimiter=params.output.delimiter,
                            date_format=params.input1.date_format)
    import contextlib

    stack = contextlib.ExitStack()
    if wctx is not None:
        stack.callback(wctx.close)
    import signal as _signal
    import threading as _threading

    from spatialflink_tpu.utils import metrics as _metrics_mod

    if _threading.current_thread() is _threading.main_thread():
        # SIGTERM = graceful drain: the decode loop sees the flag at the
        # next record boundary, flushes its buffer into the pipeline, and
        # raises GracefulShutdown — which exits 0 below after a final
        # checkpoint. Cleared at run start so an earlier run's late signal
        # can't stop this one; handler restored by the stack.
        _metrics_mod.clear_shutdown()
        _prev_term = _signal.signal(
            _signal.SIGTERM,
            lambda signum, frame: _metrics_mod.request_shutdown())
        stack.callback(_signal.signal, _signal.SIGTERM, _prev_term)
    from spatialflink_tpu.utils import deviceplane

    # recompile sentinel: warmup re-opens for this run; after the declared
    # warmup (--sentinel-warmup emitted windows) every fresh XLA compile is
    # a 'recompile' event + counter, and an abort under --strict-recompile.
    # end_run on the stack so an in-process rerun (tests) starts cold.
    sentinel = deviceplane.registry()
    sentinel.begin_run(strict=args.strict_recompile)
    stack.callback(sentinel.end_run)
    recorder = None
    if args.postmortem_dir:
        recorder = deviceplane.FlightRecorder(
            args.postmortem_dir,
            config={
                "job_fingerprint": params.job_fingerprint(),
                "option": params.query.option,
                "family": spec.family,
                "mode": spec.mode,
                "backend": deviceplane.backend_provenance(),
                "flags": {
                    "kafka": bool(args.kafka),
                    "chaos": args.chaos is not None,
                    "panes": bool(getattr(args, "panes", False)),
                    "strict_recompile": args.strict_recompile,
                    "sentinel_warmup": args.sentinel_warmup,
                    "slo": args.slo,
                },
            })
        recorder.install_signal()
        if health is not None:
            recorder.attach_health(health)
        stack.callback(recorder.close)
        recorder.note("run-start", option=params.query.option,
                      family=spec.family)
        print(f"# flight recorder armed: post-mortem bundles -> "
              f"{args.postmortem_dir} (crash / SLO breach / SIGUSR1; "
              "read with python -m spatialflink_tpu.doctor)",
              file=sys.stderr)
    repartitioner = getattr(params, "repartitioner", None)
    if repartitioner is not None:
        # chain onto the grid-cell observer hook (decode-time base-cell
        # assignments feed the epoch counters) and become the /partition
        # endpoint's controller; restored on exit so repeated in-process
        # runs (tests) never leak the chain
        repartitioner.install()
        stack.callback(repartitioner.uninstall)
    governor = getattr(params, "chunk_governor", None)
    if governor is not None:
        # the decode streams resolve the governor per flush (late-bound
        # through _governed_chunk), so installing here — inside the stack,
        # uninstalled on every exit path — is safe regardless of wiring
        # order; checkpointed runs carry the control state as the
        # 'controller' manifest component
        governor.install()
        stack.callback(governor.uninstall)
        if coord is not None:
            governor.register_checkpoint(coord)
        pol = governor.policy
        print(f"# controller: decode-chunk governor on "
              f"(seed {governor.chunk()}, bounds "
              f"[{pol.min_chunk}, {pol.max_chunk}], target p99 "
              f"{pol.target_p99_ms:g}ms; live state at GET /latency)",
              file=sys.stderr)
    registry = getattr(params, "query_registry", None)
    router = None
    if registry is not None:
        from spatialflink_tpu.runtime.queryplane import (ControlTopicConsumer,
                                                         QueryRouter)

        # install BEFORE the opserver starts: POST/DELETE/GET /queries
        # discover the registry through queryplane.active_registry()
        registry.install()
        stack.callback(registry.uninstall)
        if getattr(args, "control_topic", None) and kafka is not None:
            registry.attach_control(ControlTopicConsumer(
                kafka.broker, args.control_topic, args.kafka_group))
        router = QueryRouter(registry, broker=kafka.broker
                             if kafka is not None else None)
        stack.callback(router.close)
    if args.profile:
        from spatialflink_tpu.utils.metrics import profile_to

        stack.enter_context(profile_to(args.profile))
        print(f"# profiling to {args.profile} (view with TensorBoard/xprof)",
              file=sys.stderr)
    from spatialflink_tpu.utils import telemetry as _telemetry

    tel = _telemetry.active()
    if args.status_port is not None:
        from spatialflink_tpu.runtime.opserver import OpServer

        # reads the active session (or the registry fallback) per request;
        # closed by the stack on pipeline exit — including a control-tuple
        # stop or a crash — so the port never outlives the run
        opserver = OpServer(port=args.status_port, health=health).start()
        stack.callback(opserver.close)
        if wctx is not None:
            # the supervisor discovers the ephemeral port through this
            # drop file and aggregates /status + /latency into /fleet
            wctx.write_url(opserver.url)
            # a harvestable first event per incarnation: the supervisor's
            # timeline shows each (re)spawn coming up before any window
            _telemetry.emit_event("worker-online", worker=wctx.worker_id,
                                  url=opserver.url)
        print(f"# status server: {opserver.url} "
              "(/healthz /status /metrics /events)", file=sys.stderr)
    if args.live_stats or (args.kafka_follow and tel is not None):
        from spatialflink_tpu.runtime.opserver import LiveStats

        # --kafka-follow runs with a telemetry session get the digest
        # automatically: a live run is exactly where a terminal operator
        # needs throughput/lag/health without the HTTP server
        live = LiveStats(interval_s=args.telemetry_interval,
                         health=health).start()
        stack.callback(live.close)
    # per-window pipeline latency: wall clock from asking the pipeline for
    # the next result to receiving it (assembly + kernel + readback for
    # that window — the end-to-end number per emitted window)
    win_hist = (tel.histogram("window-latency-ms")
                if tel is not None else None)

    def emit_result(result) -> None:
        _emit(result, sink)
        if kafka is not None:
            kafka.emit(result)
        if (router is not None and isinstance(result, WindowResult)
                and "query_ids" in result.extras):
            # per-query demux: counters/SLO verdicts always; non-stdout
            # routes (file:/kafka:) get one JSON doc per (window, query)
            router.route(result)
        if out_sink is not None:
            if isinstance(result, WindowResult):
                for rec in result.flat_records():
                    out_sink.emit(rec)
            elif (isinstance(result, tuple) and len(result) == 2
                    and isinstance(result[0], SpatialObject)):
                # deser-family results are (obj, serialized) pairs —
                # the reference produces exactly these to the output
                # topic (StreamingJob.java:1289-1545)
                out_sink.emit(result[0])
            else:
                out_sink.emit(result)

    journal = None
    if coord is not None and kafka is None and spec.mode == "window":
        # the Kafka window sink recovers its delivered-set from the topic's
        # commit markers; stdout/--output have no such log, so a durable
        # emitted-window journal in the checkpoint dir suppresses the
        # windows a resumed run would otherwise re-print — exactly-once on
        # the file path too
        from spatialflink_tpu.runtime.checkpoint import EmittedWindowJournal

        # a fresh run — including --resume that found no valid manifest —
        # must not inherit a previous run's emitted history; a fenced
        # fleet worker additionally drops journal lines its superseded
        # predecessor wrote past the fence cutoff (those windows were
        # never merged, so the successor must re-emit them)
        journal = EmittedWindowJournal(
            coord.dir,
            fresh=not (args.resume and coord.restored),
            fence=(wctx.fence if wctx is not None else 0),
            fence_cutoffs=(wctx.journal_fence_cutoffs()
                           if wctx is not None else None))

    n = 0
    stopped = False
    graceful_stop = False
    strict_abort = False
    it = iter(results)
    try:
        while True:
            t0 = time.perf_counter() if tel is not None else 0.0
            try:
                result = next(it)
            except StopIteration:
                break
            if win_hist is not None:
                win_hist.record((time.perf_counter() - t0) * 1e3)
            if (journal is not None and isinstance(result, WindowResult)
                    and journal.seen(result)):
                continue  # delivered by the pre-crash process
            if wctx is not None and isinstance(result, WindowResult):
                # canonical outbox line BEFORE the emit and the journal
                # record: a kill between outbox and journal re-appends an
                # identical line on resume, which the merge dedups — the
                # exactly-once ordering the fleet merge relies on. The
                # window's stage budget rides along as a lineage sidecar
                # OUTSIDE the fingerprint (--fleet-plane), so the merged
                # digest cannot depend on it
                budget = None
                if (tel is not None
                        and getattr(args, "fleet_plane", "on") != "off"):
                    budget = tel.latency.budget_row(result.window_start)
                wctx.note_window(result, budget=budget)
            if tel is not None:
                s0 = time.time()
                with tel.span("sink"):
                    emit_result(result)
                s1 = time.time()
                if isinstance(result, WindowResult):
                    # the driver's emission stage, appended by
                    # window_start (the result no longer carries its
                    # family label): the latency plane's downstream
                    # "sink" budget, plus the trace-lineage note when
                    # tracing is on
                    tel.latency.note_downstream(
                        "sink", result.window_start, s0, s1)
                    if tel.traces is not None:
                        tel.traces.note_any(result.window_start, "sink",
                                            s0, s1)
            else:
                emit_result(result)
            if journal is not None and isinstance(result, WindowResult):
                journal.record(result)
            n += 1
            if (not sentinel.warm and isinstance(result, WindowResult)
                    and n >= args.sentinel_warmup):
                # declared warmup done: the run's steady-state shapes have
                # been seen; any later compile is a sentinel event
                sentinel.mark_warm(
                    f"{n} window(s) emitted (--sentinel-warmup "
                    f"{args.sentinel_warmup})")
            if recorder is not None and isinstance(result, WindowResult):
                recorder.note("window", start=result.window_start,
                              records=len(result.records))
    except ControlTupleExit as e:
        # the remote-stop hook (HelperClass.checkExitControlTuple:441-453) is
        # a graceful shutdown, not an error: finish the summary and exit 0.
        # A SIGTERM-raised stop additionally writes a final checkpoint
        # below — buffered records were drained into the pipeline first.
        stopped = True
        graceful_stop = isinstance(e, _metrics_mod.GracefulShutdown)
    except deviceplane.RecompileError as e:
        # --strict-recompile abort: the zero-recompile contract was
        # violated; capture the moment and exit distinctly (3)
        if recorder is not None:
            recorder.dump("strict-recompile", error=e)
        print(f"# STRICT-RECOMPILE ABORT: {e}", file=sys.stderr)
        strict_abort = True
    except BaseException as e:
        # any other crash: dump the post-mortem bundle (state at the
        # moment of death — the whole point of the recorder), then
        # propagate unchanged
        if recorder is not None:
            recorder.dump("crash", error=e)
        raise
    finally:
        stack.close()  # stop the profiler trace before the summary prints
        if out_sink is not None:
            out_sink.close()
        if journal is not None:
            journal.close()
    if graceful_stop and coord is not None:
        # a signal-driven stop writes one FINAL coordinated checkpoint:
        # the decode buffer drained into the pipeline before the stop
        # propagated, so operator state + source positions cover every
        # record read — a later --resume completes the stream with
        # nothing lost and nothing re-emitted
        final_path = coord.commit()
        print(f"# graceful shutdown: final checkpoint seq {coord.seq} "
              f"({final_path})", file=sys.stderr)
    if wctx is not None:
        wctx.write_run_summary(
            rc=3 if strict_abort else 0,
            stopped=stopped,
            graceful=graceful_stop,
            resumed=bool(coord is not None and coord.restored),
            emitted=n,
            suppressed=journal.suppressed if journal is not None else 0,
            post_warmup_compiles=sentinel.run_recompiles,
            checkpoint_seq=(coord.seq if coord is not None else None))
    if kafka is not None:
        if not stopped:
            # fully drained bounded topic: full positions are safe to commit.
            # A control-tuple stop keeps the conservative window-aligned
            # commits instead (buffered-but-unfired windows re-deliver).
            kafka.finish()
        print(kafka.summary(), file=sys.stderr)
    print(f"# emitted {n} results" + (" (control-tuple stop)" if stopped else ""),
          file=sys.stderr)
    if journal is not None and journal.suppressed:
        print(f"# resume: suppressed {journal.suppressed} window(s) the "
              "crashed run already emitted (journal "
              f"{journal.path})", file=sys.stderr)
    if out_sink is not None:
        print(f"# wrote {out_sink.records_written} records to {args.output} "
              f"({args.output_format})", file=sys.stderr)
    if args.metrics:
        import json

        from spatialflink_tpu.utils.metrics import (REGISTRY,
                                                    degradation_snapshot)

        # machine-readable: ONE sorted-JSON object on stderr (the old
        # Python-dict repr was neither parseable nor stable), with the
        # degradation digest alongside the raw counters
        print(json.dumps({"metrics": REGISTRY.snapshot(),
                          "degradation": degradation_snapshot()},
                         sort_keys=True), file=sys.stderr)
    return 3 if strict_abort else 0


if __name__ == "__main__":
    raise SystemExit(main())
