"""String object-id <-> int32 interning.

Device kernels key dedup/top-k by integer object ids; the host keeps the
string mapping (the reference keys state by the raw ``objID`` string)."""

from __future__ import annotations

from typing import Dict, List


class IdInterner:
    def __init__(self) -> None:
        self._to_int: Dict[str, int] = {}
        self._to_str: List[str] = []

    def intern(self, obj_id: str) -> int:
        idx = self._to_int.get(obj_id)
        if idx is None:
            idx = len(self._to_str)
            self._to_int[obj_id] = idx
            self._to_str.append(obj_id)
        return idx

    def lookup(self, idx: int) -> str:
        return self._to_str[idx]

    def __len__(self) -> int:
        return len(self._to_str)

    def to_list(self) -> List[str]:
        """Id-ordered strings for checkpointing (index == interned id)."""
        return list(self._to_str)

    @classmethod
    def from_list(cls, ids: List[str]) -> "IdInterner":
        out = cls()
        for s in ids:
            out.intern(str(s))
        return out
