"""Closed-loop control tests: governor convergence units (dominant-stage
direction, hysteresis/cooldown, bounds, pow-two buckets), the
zero-recompile property across live chunk resizes (compile-registry
sentinel), fast-lane vs batch isolation, the shed/un-shed admission
lifecycle end to end (governor -> registry -> 429 on POST /queries),
realtime-on-vectorized identity vs the old scalar micro-batch path, and
checkpoint/resume mid-governor-adjustment (controller manifest component
+ the open micro-batch as checkpointed state)."""

import numpy as np
import pytest

from spatialflink_tpu import driver
from spatialflink_tpu.config import StreamConfig
from spatialflink_tpu.index import UniformGrid
from spatialflink_tpu.models import Point
from spatialflink_tpu.operators import (PointPointKNNQuery,
                                        PointPointRangeQuery,
                                        QueryConfiguration, QueryType)
from spatialflink_tpu.runtime.checkpoint import (CheckpointCoordinator,
                                                 record_codec)
from spatialflink_tpu.runtime.control import (KNEE_CHUNK, ChunkGovernor,
                                              GovernorPolicy, active_governor,
                                              chunk_bucket)
from spatialflink_tpu.runtime.opserver import OpServer
from spatialflink_tpu.runtime.queryplane import QueryRegistry, QueryState
from spatialflink_tpu.runtime.windows import MicroBatcher
from spatialflink_tpu.utils import deviceplane
from spatialflink_tpu.utils.telemetry import telemetry_session

pytestmark = pytest.mark.control

GRID = UniformGrid(115.5, 117.6, 39.6, 41.1, num_grid_partitions=100)
CFG = StreamConfig(format="CSV", date_format=None, csv_tsv_schema=[0, 1, 2, 3])


def _lines(n, span_ms=100_000, seed=0):
    rng = np.random.default_rng(seed)
    t0 = 1_700_000_000_000
    return [f"v{i % 97},{t0 + i * span_ms // n},"
            f"{115.5 + rng.random() * 2:.6f},"
            f"{39.6 + rng.random() * 1.5:.6f}" for i in range(n)]


def _bucket(dominant=None, stall=False, depth=0):
    deltas = {} if dominant is None else {dominant: 1.0, "emit": 0.1}
    return {"stage_delta_s": deltas, "stall": stall,
            "decode_buffer_depth": depth}


def _tick_n(gov, n, **kw):
    for _ in range(n):
        gov.on_tick(_bucket(**kw.pop("bucket_kw", {}) or kw), kw.get("p99"))


# ------------------------------------------------------------------ units


class TestChunkBucket:
    def test_snaps_to_nearest_power_of_two(self):
        assert chunk_bucket(1000) == 1024
        assert chunk_bucket(1536) == 1024   # exact tie keeps the floor
        assert chunk_bucket(1537) == 2048
        assert chunk_bucket(4096) == 4096

    def test_clamps_to_bounds(self):
        assert chunk_bucket(100000, 256, 8192) == 8192
        assert chunk_bucket(3, 256, 8192) == 256


class TestPolicy:
    def test_from_spec_roundtrip_and_defaults(self):
        p = GovernorPolicy.from_spec("")
        assert p.target_p99_ms == GovernorPolicy().target_p99_ms
        p = GovernorPolicy.from_spec(
            "target_p99_ms=150,min_chunk=512,confirm_ticks=1")
        assert (p.target_p99_ms, p.min_chunk, p.confirm_ticks) == (
            150.0, 512, 1)

    @pytest.mark.parametrize("spec", [
        "min_chunk=1000",                 # not a power of two
        "min_chunk=8192,max_chunk=256",   # inverted bounds
        "target_p99_ms=0",
        "nonsense=5",
        "confirm_ticks=oops",
        "confirm_ticks",                  # not key=value
    ])
    def test_bad_specs_raise(self, spec):
        with pytest.raises(ValueError):
            GovernorPolicy.from_spec(spec)


class TestGovernorConvergence:
    def _gov(self, **kw):
        kw.setdefault("confirm_ticks", 2)
        kw.setdefault("cooldown_ticks", 0)
        return ChunkGovernor(policy=GovernorPolicy(**kw))

    def test_wait_dominant_with_breach_shrinks(self):
        gov = self._gov()
        for _ in range(2):
            gov.on_tick(_bucket(dominant="queue"), 400.0)
        assert gov.chunk() == KNEE_CHUNK // 2
        assert gov.shrinks == 1

    def test_buffer_dominant_with_breach_shrinks(self):
        gov = self._gov()
        for _ in range(2):
            gov.on_tick(_bucket(dominant="buffer"), 400.0)
        assert gov.chunk() == KNEE_CHUNK // 2

    def test_stall_always_shrinks_even_under_target(self):
        gov = self._gov()
        for _ in range(2):
            gov.on_tick(_bucket(dominant="dispatch", stall=True), 10.0)
        assert gov.chunk() == KNEE_CHUNK // 2

    def test_dispatch_dominant_no_breach_grows(self):
        gov = self._gov()
        for _ in range(2):
            gov.on_tick(_bucket(dominant="dispatch"), 100.0)
        assert gov.chunk() == KNEE_CHUNK * 2
        assert gov.grows == 1

    def test_idle_headroom_grows(self):
        gov = self._gov()
        for _ in range(2):
            gov.on_tick(_bucket(), 10.0)  # no dominant stage, tiny p99
        assert gov.chunk() == KNEE_CHUNK * 2

    def test_breach_without_wait_dominance_holds(self):
        # dispatch-bound AND breaching: neither law fires — growing would
        # add latency, shrinking would not cut a wait that is not there
        gov = self._gov()
        for _ in range(6):
            gov.on_tick(_bucket(dominant="dispatch"), 400.0)
        assert gov.chunk() == KNEE_CHUNK

    def test_hysteresis_single_tick_never_steps(self):
        gov = self._gov(confirm_ticks=2)
        gov.on_tick(_bucket(dominant="queue"), 400.0)
        assert gov.chunk() == KNEE_CHUNK

    def test_hysteresis_alternating_directions_never_step(self):
        gov = self._gov(confirm_ticks=2)
        for _ in range(4):
            gov.on_tick(_bucket(dominant="queue"), 400.0)   # shrink vote
            gov.on_tick(_bucket(dominant="dispatch"), 10.0)  # grow vote
        assert gov.chunk() == KNEE_CHUNK

    def test_cooldown_quiets_after_step(self):
        gov = self._gov(confirm_ticks=1, cooldown_ticks=2)
        gov.on_tick(_bucket(dominant="queue"), 400.0)
        assert gov.chunk() == KNEE_CHUNK // 2
        # two cooldown ticks absorb further confirmed votes
        gov.on_tick(_bucket(dominant="queue"), 400.0)
        gov.on_tick(_bucket(dominant="queue"), 400.0)
        assert gov.chunk() == KNEE_CHUNK // 2
        gov.on_tick(_bucket(dominant="queue"), 400.0)
        assert gov.chunk() == KNEE_CHUNK // 4

    def test_bounds_clamp_and_count_no_phantom_steps(self):
        gov = self._gov(confirm_ticks=1, min_chunk=1024, max_chunk=4096)
        for _ in range(10):
            gov.on_tick(_bucket(dominant="queue"), 400.0)
        assert gov.chunk() == 1024
        assert gov.shrinks == 1  # at the bound nothing counts as a step
        for _ in range(10):
            gov.on_tick(_bucket(dominant="dispatch"), 10.0)
        assert gov.chunk() == 4096
        assert gov.grows == 2

    def test_decisions_ring_bounded_with_schema(self):
        gov = self._gov(confirm_ticks=1)
        for i in range(80):
            gov.on_tick(_bucket(dominant="queue" if i % 2 else "dispatch"),
                        400.0 if i % 2 else 10.0)
        st = gov.status()
        assert len(st["decisions"]) <= 32
        d = st["decisions"][-1]
        assert {"ts_ms", "tick", "action", "chunk",
                "p99_emit_ms"} <= set(d)
        assert st["ticks"] == 80

    def test_status_schema(self):
        st = ChunkGovernor().status()
        assert {"chunk", "base_chunk", "seed_chunk", "fast_lane",
                "shedding", "ticks", "grows", "shrinks", "sheds",
                "streak", "policy", "decisions"} <= set(st)
        assert st["chunk"] == KNEE_CHUNK


# --------------------------------------------------- zero-recompile proof


class TestZeroRecompileAcrossResizes:
    def test_live_resizes_never_recompile(self):
        """Drive the SAME windowed pipeline at every chunk bucket the
        governor can visit, warm up at the first, and assert the compile
        registry sees zero post-warmup compiles — a decode-chunk resize
        sizes host buffers only (the recompile-surface rule's runtime
        half)."""
        lines = _lines(1200)
        qp = Point.create(116.5, 40.3, GRID, obj_id="q")

        def run(chunk):
            op = PointPointRangeQuery(
                QueryConfiguration(QueryType.WindowBased, 10_000, 5_000),
                GRID)
            s = driver.decode_stream(iter(lines), CFG, GRID, chunk=chunk)
            return [(r.window_start, sorted(p.obj_id for p in r.records))
                    for r in op.run(s, qp, 0.5)]

        reg = deviceplane.registry()
        reg.begin_run(strict=False)
        try:
            base = run(256)
            reg.mark_warm("chunk-resize test (first bucket warmed)")
            for chunk in (512, 1024, 2048, 4096, 8192):
                assert run(chunk) == base, f"chunk {chunk} changed results"
            assert reg.run_recompiles == 0
        finally:
            reg.end_run()

    def test_governed_callback_resolves_per_flush(self):
        gov = ChunkGovernor(policy=GovernorPolicy(confirm_ticks=1,
                                                  cooldown_ticks=0))
        gov.install()
        try:
            cb = driver._governed_chunk(4096)
            assert callable(cb) and cb() == KNEE_CHUNK
            for _ in range(1):
                gov.on_tick(_bucket(dominant="queue"), 999.0)
            assert cb() == KNEE_CHUNK // 2  # same callback, new size
        finally:
            gov.uninstall()
        assert cb() == 4096  # no governor -> the fixed size

    def test_env_pin_wins_over_governor(self, monkeypatch):
        monkeypatch.setenv("SPATIALFLINK_DECODE_CHUNK", "64")
        gov = ChunkGovernor().install()
        try:
            assert driver._governed_chunk(driver._decode_chunk_env(4096)) \
                == 64
        finally:
            gov.uninstall()


# ------------------------------------------------------ fast lane / shed


def _registry_with(*classes):
    reg = QueryRegistry("range", radius=0.5)
    for i, lclass in enumerate(classes):
        reg.admit({"id": f"q{i}", "x": 116.5, "y": 40.3,
                   "latency_class": lclass})
    reg.apply()
    return reg


class TestFastLane:
    def test_engages_with_interactive_fleet_only(self):
        gov = ChunkGovernor(policy=GovernorPolicy(interactive_max_chunk=512,
                                                  fast_lane_depth=1))
        reg = _registry_with("batch", "batch").install()
        try:
            gov.on_tick(_bucket(), None)
            assert not gov.fast_lane
            assert gov.chunk() == KNEE_CHUNK
            assert gov.drain_depth(4) == 4
        finally:
            reg.uninstall()
        reg = _registry_with("batch", "interactive").install()
        try:
            gov.on_tick(_bucket(), None)
            assert gov.fast_lane
            assert gov.chunk() == 512        # capped, no streak needed
            assert gov.drain_depth(4) == 1   # bounded in-flight queue
        finally:
            reg.uninstall()

    def test_disengages_when_interactive_retires(self):
        gov = ChunkGovernor(policy=GovernorPolicy(interactive_max_chunk=512))
        reg = _registry_with("interactive").install()
        try:
            # breach while dispatch-dominant = a direction-0 tick: the
            # fast lane refreshes without the chunk moving
            gov.on_tick(_bucket(dominant="dispatch"), 400.0)
            assert gov.fast_lane
            reg.retire("q0")
            reg.apply()
            gov.on_tick(_bucket(dominant="dispatch"), 400.0)
            assert not gov.fast_lane and gov.chunk() == KNEE_CHUNK
        finally:
            reg.uninstall()

    def test_fast_lane_depth_bound_keeps_results_identical(self):
        """The drive loop's fast-lane drain bound changes scheduling only:
        a deep-pipeline run under an engaged fast lane emits the same
        window table as the un-governed run."""
        lines = _lines(800)
        qp = Point.create(116.5, 40.3, GRID, obj_id="q")

        def run():
            op = PointPointRangeQuery(
                QueryConfiguration(QueryType.WindowBased, 10_000, 5_000,
                                   pipeline_depth=4), GRID)
            s = driver.decode_stream(iter(lines), CFG, GRID)
            return [(r.window_start, sorted(p.obj_id for p in r.records))
                    for r in op.run(s, qp, 0.5)]

        base = run()
        gov = ChunkGovernor().install()
        reg = _registry_with("interactive").install()
        try:
            gov.on_tick(_bucket(), None)
            assert gov.fast_lane
            assert run() == base
        finally:
            reg.uninstall()
            gov.uninstall()

    def test_latency_class_validation_and_serialization(self):
        reg = QueryRegistry("range", radius=0.5)
        with pytest.raises(ValueError):
            reg.admit({"id": "bad", "x": 116.5, "y": 40.3,
                       "latency_class": "urgent"})
        e = reg.admit({"id": "q", "x": 116.5, "y": 40.3,
                       "latency_class": "interactive"})
        assert e.spec.to_dict()["latency_class"] == "interactive"
        # batch is the default and stays off the wire
        e2 = reg.admit({"id": "q2", "x": 116.5, "y": 40.3})
        assert "latency_class" not in e2.spec.to_dict()
        assert e2.spec.latency_class == "batch"

    def test_default_latency_class_applies_to_admissions(self):
        reg = QueryRegistry("range", radius=0.5,
                            default_latency_class="interactive")
        e = reg.admit({"id": "q", "x": 116.5, "y": 40.3})
        assert e.spec.latency_class == "interactive"
        assert not reg.has_interactive()  # PENDING: not serving yet
        reg.apply()
        assert reg.has_interactive()


class TestShedLifecycle:
    def _gov(self):
        return ChunkGovernor(policy=GovernorPolicy(shed_after_stalls=2,
                                                   unshed_after_clean=2))

    def test_shed_and_unshed_transitions(self):
        gov = self._gov()
        reg = _registry_with("batch").install()
        try:
            gov.on_tick(_bucket(stall=True), None)
            assert not reg.shedding
            gov.on_tick(_bucket(stall=True), None)
            assert reg.shedding and gov.shedding
            # admissions while shedding park in SHED, uncounted in staged
            e = reg.admit({"id": "late", "x": 116.5, "y": 40.3})
            assert e.state is QueryState.SHED
            assert reg.staged_count() == 0
            # one clean bucket is not enough; two release
            gov.on_tick(_bucket(), None)
            assert reg.shedding
            gov.on_tick(_bucket(), None)
            assert not reg.shedding
            assert reg._entries["late"].state is QueryState.PENDING
            reg.apply()
            assert reg._entries["late"].state is QueryState.ACTIVE
        finally:
            reg.uninstall()

    def test_post_queries_returns_429_while_shedding(self):
        reg = _registry_with("batch").install()
        try:
            srv = OpServer(port=0)
            reg.set_shedding(True)
            code, payload = srv.admit_query_payload(
                {"id": "nope", "x": 116.5, "y": 40.3})
            assert code == 429
            assert payload["query"]["state"] == "shed"
            assert "shed" in payload["error"]
            # the parked spec admits normally after release
            reg.set_shedding(False)
            code, payload = srv.admit_query_payload(
                {"id": "nope", "x": 116.5, "y": 40.3})
            assert code == 200
        finally:
            reg.uninstall()

    def test_retire_while_shed_is_immediate(self):
        reg = _registry_with("batch")
        reg.set_shedding(True)
        reg.admit({"id": "parked", "x": 116.5, "y": 40.3})
        e = reg.retire("parked")
        assert e.state is QueryState.RETIRED

    def test_shed_state_rides_registry_snapshot(self):
        reg = _registry_with("batch")
        reg.set_shedding(True)
        reg.admit({"id": "parked", "x": 116.5, "y": 40.3})
        meta = reg.snapshot()
        reg2 = QueryRegistry("range", radius=0.5)
        reg2.restore(meta)
        assert reg2.shedding
        assert reg2._entries["parked"].state is QueryState.SHED


# ------------------------------------- realtime on the vectorized path


class TestRealtimeVectorizedIdentity:
    def _conf(self, batch=64, depth=2):
        return QueryConfiguration(QueryType.RealTime,
                                  realtime_batch_size=batch,
                                  pipeline_depth=depth)

    def test_microbatcher_cuts_match_scalar_micro_batches(self):
        lines = _lines(1000)
        op = PointPointRangeQuery(self._conf(), GRID)
        s = driver.decode_stream(iter(lines), CFG, GRID, chunk=176)
        mb = MicroBatcher(64)
        got = [(a, b, len(recs)) for a, b, recs in mb.batches(s)]
        # the oracle: the pre-rebuild scalar path's strict count cuts
        oracle_stream = driver.decode_stream(iter(lines), CFG, GRID)
        want = [(r[0].timestamp, r[-1].timestamp, len(r))
                for r in op._micro_batches(iter(oracle_stream)) if r]
        assert got == want

    @pytest.mark.parametrize("chunk", [32, 176, 512, 4096])
    def test_realtime_results_identical_across_decode_chunks(self, chunk):
        """Batch boundaries are count-strict: the decode chunk (what the
        governor resizes) never moves a micro-window, so realtime output
        is chunk-invariant — and equal to the scalar path's."""
        lines = _lines(900)
        qp = Point.create(116.5, 40.3, GRID, obj_id="q")

        def run(c):
            op = PointPointRangeQuery(self._conf(), GRID)
            s = driver.decode_stream(iter(lines), CFG, GRID, chunk=c)
            return [(r.window_start, r.window_end,
                     sorted(p.obj_id for p in r.records))
                    for r in op.run(s, qp, 0.5)]

        assert run(chunk) == run(64)

    def test_realtime_vs_scalar_oracle_full_results(self):
        lines = _lines(700)
        qp = Point.create(116.5, 40.3, GRID, obj_id="q")
        op = PointPointRangeQuery(self._conf(), GRID)
        s = driver.decode_stream(iter(lines), CFG, GRID, chunk=200)
        got = [(r.window_start, r.window_end,
                sorted(p.obj_id for p in r.records))
               for r in op.run(s, qp, 0.5)]
        # oracle: drive the batched loop with the scalar generator the old
        # realtime branch used verbatim
        op2 = PointPointRangeQuery(self._conf(), GRID)
        oracle_stream = driver.decode_stream(iter(lines), CFG, GRID)
        batched = ((r[0].timestamp, r[-1].timestamp, r)
                   for r in op2._micro_batches(iter(oracle_stream)) if r)
        mask_cache = op2._leaf_mask_cache(
            lambda: op2.conf.adaptive_grid.neighboring_leaf_mask(
                0.5, qp.cell, point=(qp.x, qp.y)))
        want = [(r.window_start, r.window_end,
                 sorted(p.obj_id for p in r.records))
                for r in op2._drive_batched(
                    batched,
                    lambda recs, ts: op2._eval(recs, qp, 0.5, ts,
                                               mask_cache),
                    realtime=True)]
        assert got == want

    def test_realtime_knn_rides_the_vectorized_path_too(self):
        lines = _lines(600)
        qp = Point.create(116.5, 40.3, GRID, obj_id="q")

        def run(c):
            op = PointPointKNNQuery(self._conf(), GRID)
            s = driver.decode_stream(iter(lines), CFG, GRID, chunk=c)
            return [(r.window_start,
                     sorted((oid, round(float(d), 9))
                            for oid, d in r.records))
                    for r in op.run(s, qp, 0.5)]

        assert run(100) == run(64)

    def test_trailing_partial_batch_fires(self):
        lines = _lines(130)  # 130 = 2 * 64 + 2 -> three fires
        op = PointPointRangeQuery(self._conf(), GRID)
        s = driver.decode_stream(iter(lines), CFG, GRID)
        mb = MicroBatcher(64)
        sizes = [len(recs) for _, _, recs in mb.batches(s)]
        assert sizes == [64, 64, 2]

    def test_realtime_never_emits_empty_selections(self):
        # a query point far from every record: realtime stays silent (the
        # reference's fire-per-element trigger never emits empties)
        lines = _lines(300)
        qp = Point.create(115.6, 39.7, GRID, obj_id="far")
        op = PointPointRangeQuery(self._conf(), GRID)
        s = driver.decode_stream(iter(lines), CFG, GRID)
        assert [r for r in op.run(s, qp, 0.0001)] == []

    def test_realtime_feeds_latency_plane(self):
        """The rebuild's point: realtime inherits the telemetry planes.
        The old scalar path never budgeted a stage; now record->emit
        histograms and stage budgets populate."""
        lines = _lines(400)
        qp = Point.create(116.5, 40.3, GRID, obj_id="q")
        with telemetry_session(None) as tel:
            op = PointPointRangeQuery(self._conf(), GRID)
            s = driver.decode_stream(iter(lines), CFG, GRID)
            out = list(op.run(s, qp, 0.5))
            assert out
            snap = tel.latency.to_dict()
        assert snap["record_emit"]["count"] >= len(out)


# --------------------------------------------- checkpoint / resume


class TestCheckpointMidAdjustment:
    def test_controller_component_roundtrips(self, tmp_path):
        coord = CheckpointCoordinator(str(tmp_path), job="j")
        gov = ChunkGovernor(policy=GovernorPolicy(confirm_ticks=2,
                                                  cooldown_ticks=1,
                                                  shed_after_stalls=3))
        gov.register_checkpoint(coord)
        # mid-adjustment: one confirmed step taken, a streak in progress,
        # one stall tick banked
        for _ in range(2):
            gov.on_tick(_bucket(dominant="queue"), 999.0)
        gov.on_tick(_bucket(dominant="queue", stall=True), 999.0)
        st = gov.status()
        assert st["base_chunk"] == KNEE_CHUNK // 2
        coord.commit()

        coord2 = CheckpointCoordinator(str(tmp_path), job="j")
        assert coord2.load()
        gov2 = ChunkGovernor()
        gov2.register_checkpoint(coord2)  # restores on registration
        st2 = gov2.status()
        assert st2["base_chunk"] == st["base_chunk"]
        assert st2["streak"] == st["streak"]
        assert st2["shedding"] == st["shedding"]

    def test_restored_chunk_clamps_to_new_policy_bounds(self, tmp_path):
        coord = CheckpointCoordinator(str(tmp_path), job="j")
        gov = ChunkGovernor(seed_chunk=8192)
        gov.register_checkpoint(coord)
        coord.commit()
        coord2 = CheckpointCoordinator(str(tmp_path), job="j")
        assert coord2.load()
        gov2 = ChunkGovernor(policy=GovernorPolicy(max_chunk=1024))
        gov2.register_checkpoint(coord2)
        assert gov2.chunk() == 1024

    def test_open_micro_batch_snapshot_restore_identity(self):
        """Cut a stream mid-batch, snapshot the open buffer (columnar
        segments and all), restore into a fresh batcher, continue with the
        remaining records: the batch sequence equals the uninterrupted
        run — no record lost, none duplicated, no boundary moved."""
        lines = _lines(500)
        s = driver.decode_stream(iter(lines), CFG, GRID, chunk=96)
        enc, dec = record_codec(GRID)

        uninterrupted = MicroBatcher(64)
        want = [(a, b, [p.obj_id for p in recs])
                for a, b, recs in uninterrupted.batches(
                    driver.decode_stream(iter(lines), CFG, GRID, chunk=96))]

        mb = MicroBatcher(64)
        got = []
        chunks = s.chunks()
        for i, ch in enumerate(chunks):
            got.extend((a, b, [p.obj_id for p in recs])
                       for a, b, recs in mb.add_chunk(ch))
            if i == 2:
                break
        state = mb.snapshot(enc)
        assert state["records"], "crash point holds an open micro-batch"

        mb2 = MicroBatcher(64)
        mb2.restore(state, dec)
        for ch in chunks:
            got.extend((a, b, [p.obj_id for p in recs])
                       for a, b, recs in mb2.add_chunk(ch))
        got.extend((a, b, [p.obj_id for p in recs])
                   for a, b, recs in mb2.flush())
        assert got == want

    def test_realtime_drive_registers_batcher_with_coordinator(self,
                                                               tmp_path):
        coord = CheckpointCoordinator(str(tmp_path), job="j")
        conf = QueryConfiguration(QueryType.RealTime, realtime_batch_size=64,
                                  checkpointer=coord)
        op = PointPointRangeQuery(conf, GRID)
        qp = Point.create(116.5, 40.3, GRID, obj_id="q")
        s = driver.decode_stream(iter(_lines(300)), CFG, GRID)
        list(op.run(s, qp, 0.5))
        assert "realtime-batcher" in coord._snapshots
