"""Shared example bootstrap: make the demo run on whatever works.

The default accelerator backend can hang at init (e.g. a wedged remote-TPU
tunnel — and on this image a sitecustomize force-selects it, overriding the
``JAX_PLATFORMS`` env var). Probe it in a subprocess with a timeout and fall
back to CPU so the examples always complete; force a platform explicitly
with ``SPATIALFLINK_EXAMPLE_PLATFORM=cpu|tpu``.

Call :func:`ensure_backend` BEFORE any jax-touching import.
"""

import os
import subprocess
import sys


def ensure_backend(min_devices: int = 1, timeout: int = 45) -> None:
    plat = os.environ.get("SPATIALFLINK_EXAMPLE_PLATFORM")
    if not plat:
        try:
            r = subprocess.run(
                [sys.executable, "-c", "import jax; jax.devices()"],
                timeout=timeout, capture_output=True)
            plat = None if r.returncode == 0 else "cpu"
        except subprocess.TimeoutExpired:
            plat = "cpu"
        if plat == "cpu":
            print("# default backend unreachable; falling back to CPU",
                  file=sys.stderr)
    if plat == "cpu" and min_devices > 1:
        # XLA_FLAGS is read at backend init — set it before first device use
        flags = os.environ.get("XLA_FLAGS", "")
        if "host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags +
                f" --xla_force_host_platform_device_count={min_devices}"
            ).strip()
    if plat:
        os.environ["JAX_PLATFORMS"] = plat
        import jax  # the env var alone loses to sitecustomize's config set

        jax.config.update("jax_platforms", plat)
