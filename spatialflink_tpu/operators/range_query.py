"""Point-stream x point-query continuous range query.

Reference: ``spatialOperators/range/PointPointRangeQuery.java`` — realtime
(:43-83), window (:85-141), incremental (:144-245). Semantics preserved:
guaranteed-cell points are emitted without distance computation; candidate
points pass iff exact distance <= r; approximate mode emits all GN∪CN points.
"""

from __future__ import annotations

from typing import Iterable, Iterator, List, Optional

import jax.numpy as jnp
import numpy as np

from spatialflink_tpu.models import Point
from spatialflink_tpu.operators.base import (
    QueryConfiguration,
    QueryType,
    SpatialOperator,
    WindowResult,
)
from spatialflink_tpu.ops.range import range_filter_point


class PointPointRangeQuery(SpatialOperator):
    def run(self, stream: Iterable[Point], query_point: Point, radius: float
            ) -> Iterator[WindowResult]:
        if self.conf.query_type is QueryType.RealTime:
            return self._run_realtime(stream, query_point, radius)
        return self._run_window(stream, query_point, radius)

    # ---------------------------------------------------------------- #

    def _eval(self, records: List[Point], query_point: Point, radius: float,
              ts_base: int) -> List[Point]:
        if not records:
            return []
        batch = self._point_batch(records, ts_base)
        mask, _ = range_filter_point(
            batch,
            query_point.x,
            query_point.y,
            jnp.int32(query_point.cell),
            radius,
            self.grid.guaranteed_layers(radius),
            self.grid.candidate_layers(radius),
            n=self.grid.n,
            approximate=self.conf.approximate,
        )
        idx = np.nonzero(np.asarray(mask))[0]
        return [records[i] for i in idx if i < len(records)]

    def _run_window(self, stream, query_point, radius) -> Iterator[WindowResult]:
        for start, end, records in self._windows(stream):
            selected = self._eval(records, query_point, radius, start)
            yield WindowResult(start, end, selected)

    def _run_realtime(self, stream, query_point, radius) -> Iterator[WindowResult]:
        for records in self._micro_batches(stream):
            selected = self._eval(records, query_point, radius,
                                  records[0].timestamp if records else 0)
            if selected:
                yield WindowResult(selected[0].timestamp, selected[-1].timestamp, selected)

    # ---------------------------------------------------------------- #

    def run_incremental(self, stream: Iterable[Point], query_point: Point,
                        radius: float) -> Iterator[WindowResult]:
        """Incremental sliding windows: carry the previous window's survivors
        and only evaluate records newer than the previous slide
        (``PointPointRangeQuery.queryIncremental``, ``:144-245``)."""
        prev: dict = {}  # id(record) -> record surviving from previous window
        prev_window_start = None
        for start, end, records in self._windows(stream):
            if prev_window_start is None:
                fresh = records
            else:
                cutoff = start + self.conf.window_size_ms - self.conf.slide_ms
                # records at/after the previous window's end are new
                fresh = [r for r in records if r.timestamp >= cutoff]
            selected_new = self._eval(fresh, query_point, radius, start)
            carried = [
                r for r in prev.values() if r.timestamp >= start
            ]
            out = {id(r): r for r in carried}
            out.update({id(r): r for r in selected_new})
            prev = out
            prev_window_start = start
            yield WindowResult(start, end, list(out.values()))
