"""Closed-loop controller Pareto bench — ISSUE 18's bench bar.

The chunk governor (``runtime/control.py``) claims it meets or beats
EVERY fixed decode-chunk size on the record→emit p99 vs throughput
frontier, per latency class, including under ``--chaos``. This harness
measures exactly that claim:

- ``frontier`` rows: for each (mode ∈ clean|chaos) × (latency class ∈
  batch|interactive), a fixed-chunk sweep of the windowed range pipeline
  plus ONE governed run (ChunkGovernor installed, ticking on the latency
  plane's bucket cadence like the production reporter thread does). Each
  governed row carries the Pareto composite

      score = min over fixed chunks c of max(gov_rps/rps_c, p99_c/gov_p99)

  — >= 1 means no fixed chunk dominates the governor on both axes. The
  harness asserts score >= 0.75 (the same 25% robustness margin the
  tier-1 ``bench_guard`` gate uses on its ``controller_pareto`` row).
- Window-table identity is asserted across every fixed chunk and the
  governed run of a sweep — and the chaos sweeps assert identity against
  the CLEAN reference table (the exactly-once resequencing contract:
  duplicates/reorder under ``FaultPlan`` must not change one window).
- The governed run of every sweep runs under the compile-registry
  recompile sentinel: live chunk resizes must cause 0 post-warmup XLA
  compiles (the recompile-surface rule's runtime half).
- ``realtime`` row: the rebuilt vectorized realtime mode vs the
  pre-rebuild scalar ``_micro_batches`` branch (fire-table identity
  asserted) — the ISSUE 18 realtime acceptance number.

The interactive class installs a QueryRegistry holding one ``interactive``
standing query, which engages the governor's fast lane (chunk capped at
``interactive_max_chunk``, drive-loop queue depth bounded) — the fixed
rows of that sweep run WITHOUT the cap, so the frontier shows what the
lane trades (throughput) for what it buys (tail latency).

Usage:
    python benchmarks/bench_control.py [--n N] [--chunks 512,...]
        [--out benchmarks/RESULTS_control.json] [--require-backend cpu]
"""

from __future__ import annotations

import argparse
import contextlib
import json
import os
import sys
import threading
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

#: governed score floor: the guard gate's 25% robustness margin
SCORE_FLOOR = 0.75
CHAOS_SPEC = "seed=11,duplicate=0.08,reorder=0.25"


def _lines(n: int):
    rng = np.random.default_rng(0)
    t0 = 1_700_000_000_000
    ts = t0 + (np.arange(n) * 100_000 // max(n, 1))
    return [f"v{int(i) % 97},{int(t)},"
            f"{115.5 + rng.random() * 2:.6f},{39.6 + rng.random() * 1.5:.6f}"
            for i, t in enumerate(ts)]


def _cfg_grid():
    from spatialflink_tpu.config import StreamConfig
    from spatialflink_tpu.index import UniformGrid

    return (StreamConfig(format="CSV", date_format=None,
                         csv_tsv_schema=[0, 1, 2, 3]),
            UniformGrid(115.5, 117.6, 39.6, 41.1, num_grid_partitions=100))


@contextlib.contextmanager
def _ticker(tel, interval_s: float = 0.02):
    """A reporter-cadence stand-in: close latency-plane buckets (= feed
    the governor) from a side thread, like the production telemetry
    reporter does — the bench must not tick from the hot loop it times."""
    tel.latency.tick_interval_s = interval_s
    stop = threading.Event()

    def loop():
        while not stop.wait(interval_s):
            try:
                tel.latency.maybe_tick(tel)
            except Exception:
                pass

    t = threading.Thread(target=loop, name="bench-ctl-ticker", daemon=True)
    t.start()
    try:
        yield
    finally:
        stop.set()
        t.join(timeout=2.0)


@contextlib.contextmanager
def _latency_class(lclass: str):
    """Installed-registry context: ``interactive`` admits one interactive
    standing query (the governor's fast-lane signal); ``batch`` installs
    a batch-only fleet so the lane provably stays off."""
    from spatialflink_tpu.runtime.queryplane import QueryRegistry

    reg = QueryRegistry("range", radius=0.5)
    reg.admit({"id": "probe", "x": 116.5, "y": 40.3,
               "latency_class": lclass})
    reg.apply()
    reg.install()
    try:
        yield reg
    finally:
        reg.uninstall()


def _run_replay(lines, cfg, grid, chunk, gov=None, lclass="batch"):
    """(window_table, rps, p99_ms) for one clean-replay configuration."""
    from spatialflink_tpu import driver
    from spatialflink_tpu.models import Point
    from spatialflink_tpu.operators import (PointPointRangeQuery,
                                            QueryConfiguration, QueryType)
    from spatialflink_tpu.utils.telemetry import telemetry_session

    conf = QueryConfiguration(QueryType.WindowBased, 10_000, 5_000)
    qp = Point.create(116.5, 40.3, grid, obj_id="q")
    with telemetry_session() as tel, _latency_class(lclass), _ticker(tel):
        if gov is not None:
            gov.install()
        try:
            op = PointPointRangeQuery(conf, grid)
            s = driver.decode_stream(iter(lines), cfg, grid, chunk=chunk)
            t0 = time.perf_counter()
            table = [(r.window_start, len(r.records))
                     for r in op.run(s, qp, 0.5)]
            wall = time.perf_counter() - t0
            p99 = tel.latency.record_emit.percentile(99)
        finally:
            if gov is not None:
                gov.uninstall()
    return table, len(lines) / wall, p99


def _run_chaos(lines, cfg, grid, chunk, gov=None, lclass="batch", tag="0"):
    """Same measurement through the degraded transport: InMemoryBroker
    wrapped in a seeded ChaosBroker (duplicates + reordering), consumed
    via KafkaSource -> WindowCommitTap -> the chunked decode. The
    resequencing consumer must hand the SAME records downstream, so the
    window table is asserted (by the caller) against the clean run's."""
    from spatialflink_tpu import driver
    from spatialflink_tpu.models import Point
    from spatialflink_tpu.operators import (PointPointRangeQuery,
                                            QueryConfiguration, QueryType)
    from spatialflink_tpu.runtime.faults import ChaosBroker, FaultPlan
    from spatialflink_tpu.streams.kafka import (InMemoryBroker, KafkaSource,
                                                WindowCommitTap)
    from spatialflink_tpu.utils.telemetry import telemetry_session

    inner = InMemoryBroker()
    for ln in lines:
        inner.produce("t", ln)
    broker = ChaosBroker(inner, FaultPlan.from_spec(CHAOS_SPEC))
    conf = QueryConfiguration(QueryType.WindowBased, 10_000, 5_000)
    qp = Point.create(116.5, 40.3, grid, obj_id="q")
    with telemetry_session() as tel, _latency_class(lclass), _ticker(tel):
        if gov is not None:
            gov.install()
        try:
            src = KafkaSource(broker, "t", f"g-{tag}", poll_batch=500,
                              auto_commit=False, stop_at_end=True)
            tap = WindowCommitTap(
                src, 10_000, 5_000, parse=lambda r: None,
                bulk_decode=driver._kafka_bulk_decode(cfg, grid),
                bulk_chunk=chunk)
            op = PointPointRangeQuery(conf, grid)
            s = driver.decode_stream(tap, cfg, grid, chunk=chunk)
            t0 = time.perf_counter()
            table = [(r.window_start, len(r.records))
                     for r in op.run(s, qp, 0.5)]
            wall = time.perf_counter() - t0
            p99 = tel.latency.record_emit.percentile(99)
        finally:
            if gov is not None:
                gov.uninstall()
    return table, len(lines) / wall, p99


def frontier(lines, cfg, grid, chunks, mode: str, lclass: str,
             clean_ref=None, reps: int = 3):
    """One sweep: fixed chunks + the governed run, identity + sentinel
    asserted; returns (rows, governed_score, reference_table).

    Every configuration (each fixed chunk AND the governed run) is
    measured ``reps`` times and reported at its best p99 / best rps:
    single-shot p99 over ~20 windows through a chaos transport is
    scheduling-noise-dominated (the same fixed config varies up to 3x
    run to run), and best-of-R is the stable estimator of what a config
    can do — applied uniformly, so neither side of the comparison gets
    the optimism the other didn't."""
    from spatialflink_tpu.runtime.control import ChunkGovernor
    from spatialflink_tpu.utils import deviceplane

    runner = _run_chaos if mode == "chaos" else _run_replay
    rows = []
    ref = clean_ref
    fixed = {}
    for c in chunks:
        rps, p99 = 0.0, float("inf")
        for rep in range(reps):
            kw = (dict(tag=f"{mode}-{lclass}-{c}-{rep}")
                  if mode == "chaos" else {})
            table, r_, p_ = runner(lines, cfg, grid, c, lclass=lclass, **kw)
            if ref is None:
                ref = table
            assert table == ref, (
                f"{mode}/{lclass}: window table diverged at fixed "
                f"chunk {c}")
            rps, p99 = max(rps, r_), min(p99, p_)
        fixed[c] = (rps, p99)
        rows.append(dict(path="frontier", mode=mode, latency_class=lclass,
                         chunk=c, governed=False, records=len(lines),
                         reps=reps, records_per_sec=int(rps),
                         emit_p99_ms=round(p99, 3)))
        print(json.dumps(rows[-1]), flush=True)
    # the governed runs, under the recompile sentinel: a live resize must
    # never cost an XLA compile (shapes pre-warmed by the fixed sweep)
    dp = deviceplane.registry()
    dp.begin_run()
    dp.mark_warm("bench_control governed run (fixed sweep pre-warmed)")
    try:
        rps, p99 = 0.0, float("inf")
        for rep in range(reps):
            gov = ChunkGovernor()  # fresh trajectory per rep
            kw = (dict(tag=f"{mode}-{lclass}-gov-{rep}")
                  if mode == "chaos" else {})
            table, r_, p_ = runner(lines, cfg, grid, gov.chunk_callback(),
                                   gov=gov, lclass=lclass, **kw)
            assert table == ref, (
                f"{mode}/{lclass}: governed run changed results")
            rps, p99 = max(rps, r_), min(p99, p_)
        post_warm = dp.run_recompiles
    finally:
        dp.end_run()
    assert post_warm == 0, (
        f"{mode}/{lclass}: recompile sentinel fired {post_warm}x across "
        "governed chunk resizes — the decode chunk must only size host "
        "buffers")
    score = min(max(rps / frps, fp99 / p99)
                for frps, fp99 in fixed.values())
    st = gov.status()
    rows.append(dict(path="frontier", mode=mode, latency_class=lclass,
                     chunk="governed", governed=True, records=len(lines),
                     records_per_sec=int(rps), emit_p99_ms=round(p99, 3),
                     pareto_score=round(score, 2),
                     final_chunk=st["chunk"], fast_lane=st["fast_lane"],
                     ticks=st["ticks"],
                     steps=st["grows"] + st["shrinks"],
                     post_warmup_compiles=post_warm))
    print(json.dumps(rows[-1]), flush=True)
    assert score >= SCORE_FLOOR, (
        f"{mode}/{lclass}: governed run dominated by a fixed chunk "
        f"(score {score:.2f} < {SCORE_FLOOR}) — the governor must meet "
        "or beat every fixed size on the frontier")
    return rows, score, ref


def bench_realtime(lines, cfg, grid) -> dict:
    """Vectorized realtime vs the scalar oracle (same shape as the
    ``realtime_vectorized`` tier-1 guard row, kept here so the ISSUE 18
    results file is self-contained)."""
    from spatialflink_tpu import driver
    from spatialflink_tpu.models import Point
    from spatialflink_tpu.operators import (PointPointRangeQuery,
                                            QueryConfiguration, QueryType)

    conf = QueryConfiguration(QueryType.RealTime, realtime_batch_size=512)
    qp = Point.create(116.5, 40.3, grid, obj_id="q")

    def run_new():
        op = PointPointRangeQuery(conf, grid)
        s = driver.decode_stream(iter(lines), cfg, grid)
        return [(r.window_start, r.window_end, len(r.records))
                for r in op.run(s, qp, 0.5)]

    def run_scalar():
        op = PointPointRangeQuery(conf, grid)
        stream = iter(driver.decode_stream(iter(lines), cfg, grid))
        batched = ((r[0].timestamp, r[-1].timestamp, r)
                   for r in op._micro_batches(stream) if r)
        mask_cache = op._leaf_mask_cache(
            lambda: op.conf.adaptive_grid.neighboring_leaf_mask(
                0.5, qp.cell, point=(qp.x, qp.y)))
        return [(r.window_start, r.window_end, len(r.records))
                for r in op._drive_batched(
                    batched,
                    lambda recs, tsb: op._eval(recs, qp, 0.5, tsb,
                                               mask_cache),
                    realtime=True)]

    run_new(), run_scalar()  # warm
    t0 = time.perf_counter()
    new = run_new()
    dt_new = time.perf_counter() - t0
    t0 = time.perf_counter()
    old = run_scalar()
    dt_old = time.perf_counter() - t0
    assert new == old, "vectorized realtime diverged from the scalar oracle"
    row = dict(path="realtime", records=len(lines), fires=len(new),
               wall_vectorized_s=round(dt_new, 3),
               wall_scalar_s=round(dt_old, 3),
               speedup=round(dt_old / dt_new, 2))
    print(json.dumps(row), flush=True)
    return row


def measure(n: int, chunks):
    cfg, grid = _cfg_grid()
    lines = _lines(n)
    rows = []
    _run_replay(lines, cfg, grid, 4096)  # jit warm
    clean_ref = None
    scores = {}
    for mode in ("clean", "chaos"):
        for lclass in ("batch", "interactive"):
            sweep, score, ref = frontier(
                lines, cfg, grid, chunks, mode, lclass,
                # chaos sweeps must reproduce the CLEAN table: the
                # exactly-once resequencing contract, asserted per row
                clean_ref=clean_ref if mode == "chaos" else None)
            if clean_ref is None:
                clean_ref = ref
            rows.extend(sweep)
            scores[f"{mode}/{lclass}"] = score
    rows.append(bench_realtime(lines, cfg, grid))
    return rows, scores


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=60_000)
    ap.add_argument("--chunks", default="512,1024,2048,4096,8192")
    ap.add_argument("--out", default=None)
    ap.add_argument("--require-backend", default=None)
    args = ap.parse_args()

    from benchmarks._common import settle_backend

    settle_backend()
    import jax

    backend = jax.default_backend()
    if args.require_backend and backend != args.require_backend:
        print(f"# backend {backend} != required {args.require_backend}",
              file=sys.stderr)
        return 2
    chunks = [int(c) for c in args.chunks.split(",") if c]
    rows, scores = measure(args.n, chunks)
    for r in rows:
        r["backend"] = backend
    if args.out:
        with open(args.out, "w") as f:
            json.dump({"backend": backend, "chaos_spec": CHAOS_SPEC,
                       "score_floor": SCORE_FLOOR,
                       "pareto_scores": scores, "rows": rows}, f, indent=1)
        print(f"# wrote {args.out}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
