"""The batched-hot-path regression gate is itself tier-1: a regression back
to per-record decode/assignment cost must fail the suite, not wait for the
next manual bench run (ISSUE 8's wins rot silently otherwise)."""

import json
import os
import subprocess
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_bench_guard_passes_thresholds():
    """bench_guard --check against the checked-in GUARD_baseline.json
    floors: the measured batched-vs-scalar speedup ratios must stay within
    25% of the conservative floors (ratios, not absolute rec/s, so the
    gate is machine-robust). Also pins the row contract bench_diff pairs
    on."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    r = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "benchmarks", "bench_guard.py"),
         "--check", "--n", "60000"],
        capture_output=True, text=True, timeout=480, env=env, cwd=_ROOT)
    rows = [json.loads(ln) for ln in r.stdout.splitlines()
            if ln.startswith("{")]
    assert [x["path"] for x in rows] == [
        "window_assign", "decode_columnar", "windowed_pipeline",
        "skew_adaptive", "query_plane", "controller_pareto",
        "realtime_vectorized", "latency_record_emit",
        "fleet_scaling", "fleet_rescale", "tenant_plane"], r.stdout
    assert all(x["speedup"] > 0 for x in rows if "speedup" in x)
    # the governor's Pareto composite row carries its convergence trace
    # (final chunk, tick/step counts) so a never-ticking controller is
    # visible even while the composite holds
    ctl = [x for x in rows if x["path"] == "controller_pareto"]
    assert len(ctl) == 1 and ctl[0]["gov_ticks"] > 0
    assert ctl[0]["gov_final_chunk"] > 0 and ctl[0]["gov_p99_ms"] > 0
    rt = [x for x in rows if x["path"] == "realtime_vectorized"]
    assert len(rt) == 1 and rt[0]["fires"] > 0
    # the lower-is-better latency row (record→emit p99 through the
    # latency-decomposition plane, gated against its baseline ceiling)
    lat = [x for x in rows if x["path"] == "latency_record_emit"]
    assert len(lat) == 1 and lat[0]["p99_ms"] > 0
    # the lower-is-better fleet row (absolute single-worker supervised-
    # fleet wall at the pinned record count, gated against its ceiling;
    # the bench asserts merged-digest identity across N=1/N=2 in-run)
    fl = [x for x in rows if x["path"] == "fleet_scaling"]
    assert len(fl) == 1 and fl[0]["wall_fleet1_s"] > 0
    assert fl[0]["scaling_n2"] > 0 and fl[0]["overhead_x"] > 0
    assert fl[0]["merged_windows"] > 0
    # the live-rescale row (N=2->4 mid-run at an epoch boundary, digest
    # asserted vs a fixed-N=2 oracle in-run; gated under the shared fleet
    # metric key)
    rs = [x for x in rows if x["path"] == "fleet_rescale"]
    assert len(rs) == 1 and rs[0]["wall_fleet1_s"] > 0
    assert rs[0]["workers_final"] == 4 and rs[0]["rescale_x"] > 0
    assert rs[0]["merged_windows"] > 0
    # the lower-is-better tenant-ledger row (session-on/off wall ratio
    # over the two-tenant dynamic fleet, gated against its ceiling; the
    # bench asserts window-table identity and attribution conservation
    # — every dispatch resolved, zero residual — in-run)
    tp = [x for x in rows if x["path"] == "tenant_plane"]
    assert len(tp) == 1 and tp[0]["overhead_vs_off_x"] > 0
    assert tp[0]["dispatches_resolved"] > 0
    assert tp[0]["max_residual_ms"] < 1e-6
    assert r.returncode == 0, (
        f"bench_guard regression:\n{r.stdout}\n{r.stderr[-1000:]}")


def test_guard_baseline_rows_exist():
    base = json.load(open(os.path.join(_ROOT, "benchmarks",
                                       "GUARD_baseline.json")))
    assert base["metric"] == "speedup"
    assert {r["path"] for r in base["rows"]} == {
        "window_assign", "decode_columnar", "windowed_pipeline",
        "skew_adaptive", "query_plane", "controller_pareto",
        "realtime_vectorized"}
    # the floors assert the batched path (and the skew-adaptive grid on
    # the clustered stream) is actually FASTER than its baseline
    assert all(r["speedup"] >= 1.0 for r in base["rows"])
    # the latency ceilings (lower-is-better second diff pass)
    assert {r["path"] for r in base["latency_rows"]} == {
        "latency_record_emit"}
    assert all(r["p99_ms"] > 0 for r in base["latency_rows"])
    # the fleet supervision-cost + live-rescale ceilings (lower-is-better
    # third pass, both paired on the shared wall_fleet1_s key)
    assert {r["path"] for r in base["fleet_rows"]} == {
        "fleet_scaling", "fleet_rescale"}
    assert all(r["wall_fleet1_s"] > 0 for r in base["fleet_rows"])
    # the tenant-ledger overhead ceiling (lower-is-better fourth pass):
    # a ratio ceiling >= 1 — the ledger may cost something, never 1.5x+
    assert {r["path"] for r in base["tenant_rows"]} == {"tenant_plane"}
    assert all(r["overhead_vs_off_x"] >= 1.0 for r in base["tenant_rows"])
