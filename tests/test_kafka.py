"""Kafka transport shim: broker round-trips, consumer groups, delivery
semantics (at-least-once + idempotent windows ≙ the reference's EXACTLY_ONCE
producer, StreamingJob.java:512)."""

import numpy as np

from spatialflink_tpu.index import UniformGrid
from spatialflink_tpu.models import Point
from spatialflink_tpu.operators import (
    PointPointRangeQuery,
    QueryConfiguration,
    QueryType,
)
from spatialflink_tpu.streams import (
    IdempotentWindowSink,
    InMemoryBroker,
    KafkaLatencySink,
    KafkaSink,
    KafkaSource,
    parse_spatial,
)

GRID = UniformGrid(115.50, 117.60, 39.60, 41.10, num_grid_partitions=100)
BASE = 1_700_000_000_000


def _points(n, seed=0):
    rng = np.random.default_rng(seed)
    return [
        Point.create(float(rng.uniform(115.6, 117.5)),
                     float(rng.uniform(39.7, 41.0)), GRID,
                     obj_id=f"o{i % 7}", timestamp=BASE + i * 100)
        for i in range(n)
    ]


class TestBrokerRoundTrip:
    def test_produce_consume(self):
        b = InMemoryBroker()
        for i in range(10):
            b.produce("t", f"v{i}", key=f"k{i % 3}")
        got = list(KafkaSource(b, "t", "g1"))
        assert got == [f"v{i}" for i in range(10)]

    def test_serialized_spatial_round_trip(self):
        """Object -> KafkaSink (GeoJSON schema) -> topic -> KafkaSource ->
        parse: the reference's produce/consume conformance loop
        (Serialization.java <-> Deserialization.java)."""
        b = InMemoryBroker()
        sink = KafkaSink(b, "out", fmt="GeoJSON")
        pts = _points(5)
        for p in pts:
            sink.emit(p)
        parsed = [parse_spatial(v, "GeoJSON", GRID)
                  for v in KafkaSource(b, "out", "g")]
        assert [p.obj_id for p in parsed] == [p.obj_id for p in pts]
        np.testing.assert_allclose([p.x for p in parsed], [p.x for p in pts],
                                   rtol=1e-6)

    def test_consumer_groups_are_independent(self):
        b = InMemoryBroker()
        for i in range(4):
            b.produce("t", i)
        assert list(KafkaSource(b, "t", "a")) == [0, 1, 2, 3]
        assert list(KafkaSource(b, "t", "b")) == [0, 1, 2, 3]

    def test_committed_offset_resumes(self):
        """A second consumer in the same group continues where the first
        committed — the Kafka-consumer-group seek the checkpoint story
        defers to."""
        b = InMemoryBroker()
        for i in range(6):
            b.produce("t", i)
        first = []
        for v in KafkaSource(b, "t", "g", commit_every=1):
            first.append(v)
            if len(first) == 3:
                break  # "crash" mid-processing of the third record
        rest = list(KafkaSource(b, "t", "g"))
        # commit happens AFTER a record's processing completes, so the
        # in-flight third record (processing interrupted) is re-delivered —
        # at-least-once, never lost
        assert first == [0, 1, 2] and rest == [2, 3, 4, 5]

    def test_uncommitted_records_are_redelivered(self):
        """commit_every > consumed count means no commit happened: the next
        consumer sees everything again (at-least-once, never at-most-once)."""
        b = InMemoryBroker()
        for i in range(4):
            b.produce("t", i)
        got = []
        for v in KafkaSource(b, "t", "g", commit_every=100):
            got.append(v)
            if len(got) == 2:
                break  # crash before any commit
        assert list(KafkaSource(b, "t", "g")) == [0, 1, 2, 3]


class TestIdempotentDelivery:
    def test_duplicate_windows_collapse(self):
        from spatialflink_tpu.operators import WindowResult

        inner = []

        class L:
            def emit(self, r):
                inner.append(r)

            def close(self):
                pass

        sink = IdempotentWindowSink(L())
        w1 = WindowResult(0, 10, ["a"])
        w1_dup = WindowResult(0, 10, ["a"])
        w2 = WindowResult(10, 20, ["b"])
        for w in (w1, w1_dup, w2, w1_dup):
            sink.emit(w)
        assert len(inner) == 2
        assert sink.duplicates_suppressed == 2
        assert len(sink.snapshot()) == 2

    def test_value_differing_duplicates_observable(self):
        """First delivery wins in BOTH the table and the inner sink (they can
        never disagree); a re-delivery with a different value — upstream
        nondeterminism, not retry noise — is counted separately."""
        from spatialflink_tpu.operators import WindowResult

        inner = []

        class L:
            def emit(self, r):
                inner.append(r)

            def close(self):
                pass

        sink = IdempotentWindowSink(L())
        w = WindowResult(0, 10, ["a"])
        w_same = WindowResult(0, 10, ["a"])
        w_diff = WindowResult(0, 10, ["b"])
        for r in (w, w_same, w_diff):
            sink.emit(r)
        assert inner == [w]
        assert sink.snapshot() == {(0, 10, None): w}
        assert sink.duplicates_suppressed == 2
        assert sink.duplicates_value_differing == 1

    def test_ndarray_extras_compare_structurally(self):
        """A byte-identical heatmap re-delivery is NOT value-differing —
        plain == on ndarray-valued extras would raise and false-positive."""
        import numpy as np

        from spatialflink_tpu.operators import WindowResult

        hm = np.arange(6).reshape(2, 3)
        sink = IdempotentWindowSink()
        sink.emit(WindowResult(0, 10, [], extras={"heatmap": hm.copy()}))
        sink.emit(WindowResult(0, 10, [], extras={"heatmap": hm.copy()}))
        assert sink.duplicates_suppressed == 1
        assert sink.duplicates_value_differing == 0
        sink.emit(WindowResult(0, 10, [], extras={"heatmap": hm + 1}))
        assert sink.duplicates_value_differing == 1

    def test_replayed_pipeline_is_effectively_exactly_once(self):
        """Crash-and-replay: the consumer re-delivers uncommitted input, the
        pipeline recomputes the same windows, and the idempotent sink keyed
        by (window, cell) suppresses the duplicates — final output equals a
        single clean run."""
        b = InMemoryBroker()
        import json

        for p in _points(200, seed=3):
            b.produce("in", json.dumps({
                "geometry": {"type": "Point", "coordinates": [p.x, p.y]},
                "properties": {"oID": p.obj_id, "timestamp": p.timestamp},
            }))
        q = Point.create(116.5, 40.5, GRID)
        conf = QueryConfiguration(QueryType.WindowBased, window_size_ms=5_000,
                                  slide_ms=5_000)

        def run_pipeline(values, sink):
            stream = (parse_spatial(v, "GeoJSON", GRID) for v in values)
            for res in PointPointRangeQuery(conf, GRID).run(stream, q, 0.4):
                sink.emit(res)

        sink = IdempotentWindowSink()
        # attempt 1: processed every record but "crashed" before the offset
        # commit (raw fetch, no group bookkeeping touched)
        run_pipeline([r.value for r in b.fetch("in", 0, 10**9)], sink)
        # attempt 2: restart — committed offset is still 0, so the whole
        # topic re-delivers and every window recomputes
        run_pipeline(KafkaSource(b, "in", "g"), sink)
        assert sink.duplicates_suppressed > 0
        clean = IdempotentWindowSink()
        run_pipeline(KafkaSource(b, "in", "g2"), clean)  # fresh single run
        got = {k: len(v.records) for k, v in sink.snapshot().items()}
        want = {k: len(v.records) for k, v in clean.snapshot().items()}
        assert got == want


class TestLatencyTopic:
    def test_latency_values_produced(self):
        b = InMemoryBroker()
        sink = KafkaLatencySink(b, "latency", use_event_time=True)
        for p in _points(5):
            sink.emit(p)
        vals = b.topic_values("latency")
        assert len(vals) == 5 and all(isinstance(v, float) for v in vals)


# --------------------------------------------------------------------- #
# Real-client adapter (connect_kafka / RealKafkaBroker) against a fake
# kafka-python module — the client-API-level coverage the reference gets
# from its live cluster (StreamingJob.java:473 consumers, :512 producer).

from collections import namedtuple

TopicPartition = namedtuple("TopicPartition", ["topic", "partition"])
OffsetAndMetadata = namedtuple("OffsetAndMetadata", ["offset", "metadata"])
_ConsumerRecord = namedtuple(
    "ConsumerRecord", ["topic", "partition", "offset", "key", "value", "timestamp"])
_RecordMetadata = namedtuple("RecordMetadata", ["topic", "partition", "offset"])


class _FakeCluster:
    """Shared backing store for one fake-module instance: topic logs plus
    per-group committed offsets, all keyed by (topic, partition)."""

    def __init__(self):
        self.logs = {}      # (topic, partition) -> [ (key, value, ts) ]
        self.commits = {}   # (group, topic, partition) -> offset


class _FakeFuture:
    def __init__(self, value, error=None):
        self._value, self._error = value, error

    def get(self, timeout=None):
        if self._error is not None:
            raise self._error
        return self._value


class _FakeProducer:
    def __init__(self, cluster, bootstrap_servers=None, **_):
        self._cluster = cluster
        self.closed = False

    def send(self, topic, value=None, key=None, partition=None,
             timestamp_ms=None):
        assert value is None or isinstance(value, bytes), "values must be bytes"
        assert key is None or isinstance(key, bytes), "keys must be bytes"
        # a real producer key-hashes/round-robins across ALL partitions when
        # partition is unset — the adapter must pin partition 0 explicitly or
        # records land where the partition-0 consumer never looks
        assert partition == 0, "adapter must pin partition 0 on send"
        log = self._cluster.logs.setdefault((topic, partition), [])
        log.append((key, value, timestamp_ms or 0))
        return _FakeFuture(_RecordMetadata(topic, partition, len(log) - 1))

    def flush(self):
        pass

    def close(self):
        self.closed = True


class _FakeConsumer:
    def __init__(self, cluster, bootstrap_servers=None, group_id=None,
                 enable_auto_commit=True, **_):
        assert not enable_auto_commit, \
            "adapter must manage commits itself (at-least-once)"
        self._cluster = cluster
        self.group_id = group_id
        self._assigned = []
        self._positions = {}
        self.closed = False

    def assign(self, tps):
        self._assigned = list(tps)

    def seek(self, tp, offset):
        self._positions[tp] = offset

    def poll(self, timeout_ms=0, max_records=500):
        out = {}
        for tp in self._assigned:
            log = self._cluster.logs.get((tp.topic, tp.partition), [])
            pos = self._positions.get(tp, 0)
            recs = [
                _ConsumerRecord(tp.topic, tp.partition, i, k, v, ts)
                for i, (k, v, ts) in enumerate(log[pos:pos + max_records], pos)
            ]
            if recs:
                out[tp] = recs
                self._positions[tp] = recs[-1].offset + 1
        return out

    def commit(self, offsets):
        assert self.group_id is not None, "commit needs a consumer group"
        for tp, oam in offsets.items():
            self._cluster.commits[(self.group_id, tp.topic, tp.partition)] = \
                oam.offset

    def committed(self, tp):
        return self._cluster.commits.get(
            (self.group_id, tp.topic, tp.partition))

    def end_offsets(self, tps):
        return {tp: len(self._cluster.logs.get((tp.topic, tp.partition), []))
                for tp in tps}

    def close(self):
        self.closed = True


class FakeKafkaModule:
    """Injectable stand-in for the kafka-python package surface the adapter
    touches: KafkaProducer, KafkaConsumer, TopicPartition, OffsetAndMetadata."""

    TopicPartition = TopicPartition
    OffsetAndMetadata = OffsetAndMetadata

    def __init__(self):
        self.cluster = _FakeCluster()
        mod = self

        class KafkaProducer(_FakeProducer):
            def __init__(self, **kw):
                super().__init__(mod.cluster, **kw)

        class KafkaConsumer(_FakeConsumer):
            def __init__(self, **kw):
                super().__init__(mod.cluster, **kw)

        self.KafkaProducer = KafkaProducer
        self.KafkaConsumer = KafkaConsumer


class TestRealKafkaAdapter:
    def _broker(self):
        from spatialflink_tpu.streams.kafka import connect_kafka

        return connect_kafka("fake:9092", kafka_module=FakeKafkaModule())

    def test_produce_fetch_round_trip_with_utf8(self):
        b = self._broker()
        for i in range(5):
            off = b.produce("t", f"v{i}", key=f"k{i}", timestamp_ms=100 + i)
            assert off == i
        recs = b.fetch("t", 2, max_records=10)
        assert [r.value for r in recs] == ["v2", "v3", "v4"]
        assert recs[0].key == "k2" and recs[0].offset == 2
        assert b.end_offset("t") == 5

    def test_commit_committed_and_monotonicity(self):
        b = self._broker()
        for i in range(10):
            b.produce("t", str(i))
        assert b.committed("t", "g") == 0
        b.commit("t", "g", 7)
        assert b.committed("t", "g") == 7
        b.commit("t", "g", 3)  # must not rewind the group
        assert b.committed("t", "g") == 7
        assert b.committed("t", "other-group") == 0

    def test_kafka_source_at_least_once_over_adapter(self):
        # the SAME KafkaSource drives the real adapter and the shim: consume
        # part of the topic committing as we go, "crash", restart, and verify
        # redelivery starts at the committed offset
        b = self._broker()
        for i in range(8):
            b.produce("in", f"r{i}")
        src = iter(KafkaSource(b, "in", "g", commit_every=2))
        got = [next(src) for _ in range(5)]
        assert got == [f"r{i}" for i in range(5)]
        del src  # crash before the 5th record's commit (commit_every=2 -> 4)
        assert b.committed("in", "g") == 4
        replay = list(KafkaSource(b, "in", "g", commit_every=2))
        assert replay == [f"r{i}" for i in range(4, 8)]  # r4 redelivered
        assert b.committed("in", "g") == 8

    def test_idempotent_sink_dedups_adapter_redelivery(self):
        # at-least-once + idempotent sink = effective exactly-once, through
        # the real-client adapter end to end
        from spatialflink_tpu.streams.formats import serialize_spatial

        b = self._broker()
        for p in _points(6):
            b.produce("in", serialize_spatial(p, "GeoJSON"))
        sink = IdempotentWindowSink()
        conf = QueryConfiguration(QueryType.WindowBased, 10_000, 5_000)
        q = Point.create(116.5, 40.5, GRID)

        def run(group_records):
            pts = [parse_spatial(v, "GeoJSON", GRID, date_format=None)
                   for v in group_records]
            for w in PointPointRangeQuery(conf, GRID).run(iter(pts), q, 5.0):
                sink.emit(w)

        run(list(KafkaSource(b, "in", "g")))           # first full pass
        n_windows = len(sink.snapshot())
        run([r.value for r in b.fetch("in", 0, 100)])  # full redelivery
        assert sink.duplicates_suppressed > 0
        # redelivery added no new windows: first delivery won every key
        assert len(sink.snapshot()) == n_windows

    def test_missing_kafka_package_raises_runtime_error(self, monkeypatch):
        import sys

        import pytest as _pytest

        from spatialflink_tpu.streams.kafka import connect_kafka

        # force the import failure regardless of whether kafka-python is
        # installed in the running environment
        monkeypatch.setitem(sys.modules, "kafka", None)
        with _pytest.raises(RuntimeError, match="kafka-python"):
            connect_kafka("real:9092")  # no injected module, package absent

    def test_close_flushes_and_closes_clients(self):
        b = self._broker()
        b.produce("t", "x")
        b.fetch("t", 0)
        b.commit("t", "g", 1)
        b.close()
        assert b._producer.closed and b._fetch_c.closed
        assert all(c.closed for c in b._group_c.values())
