"""Fleet supervisor: N supervised worker pipelines, leaf-partitioned
input, crash-recovering restarts, exactly-once global merge.

The reference deploys GeoFlink at parallelism 30: Flink's JobManager
places keyed subtasks on TaskManagers, restarts dead ones from the last
checkpoint, and windowAll stages merge the keyed partials into one global
result. The rebuild's supervisor is that control plane shrunk to one
process:

- **Placement** — the stream partitions by grid LEAF (PR 8's adaptive
  layout as the placement unit; the default layout is one leaf per base
  cell). A seed scan of the input head feeds
  :func:`~spatialflink_tpu.runtime.repartition.balance_leaves` (greedy
  LPT) for the initial leaf→worker assignment; unseen leaves route by
  ``leaf % N``.
- **Workers** — each is the FULL existing single-process driver
  (``--fleet-role worker``): own PaneCache, own checkpoint manifest, own
  emitted-window journal, own opserver on an ephemeral port. The
  supervisor only routes lines into per-worker partition files and reads
  canonical outboxes back — no shared mutable state between pipelines.
- **Supervision** — a monitor thread watches exit codes, heartbeat-file
  age, and (optionally) record→emit p99 SLO breaches from the worker's
  ``/latency`` payload. A dead worker restarts from its latest
  checkpoint manifest with ``--resume``; the per-incarnation run summary
  carries the recompile sentinel's post-warmup count, so the respawn
  PROVES it never silently recompiled instead of asserting it by hope.
- **Rebalance** — at repartition epochs the supervisor compares worker
  loads (backpressure/latency signals when present, routed-record counts
  otherwise) and :func:`~spatialflink_tpu.runtime.repartition
  .pick_rebalance` moves leaves off the most loaded worker (with
  hysteresis) — the fleet analogue of PR 8's in-process repartitioner.
- **Exactly-once merge** — workers append canonical fingerprinted window
  docs to their outboxes BEFORE journaling them; the supervisor dedups
  by window key, merges per-family through
  :func:`~spatialflink_tpu.operators.base.merge_window_records`, and the
  merged table's digest is byte-stable against a fault-free
  single-worker run — the property the tier-1 kill test pins.
- **Drain** — SIGTERM stops routing, forwards the signal to every
  worker (each drains open windows and writes a final checkpoint via the
  driver's graceful-shutdown path), then merges whatever was emitted and
  exits 0.

``GET /fleet`` on the supervisor's own opserver serves the aggregated
view (:meth:`FleetSupervisor.fleet_view` via :func:`active_fleet`, the
same module-global hook pattern as ``repartition.active_controller``).
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.request
from typing import Dict, List, Optional, Tuple

from spatialflink_tpu.runtime import fleet as F
from spatialflink_tpu.runtime.checkpoint import atomic_write_json
from spatialflink_tpu.runtime.repartition import (balance_leaves,
                                                  pick_rebalance)
from spatialflink_tpu.utils import metrics as _metrics

_ACTIVE_FLEET: Optional["FleetSupervisor"] = None


def active_fleet() -> Optional["FleetSupervisor"]:
    """The running supervisor, if any (the ``/fleet`` endpoint's data
    source — same pattern as ``repartition.active_controller``)."""
    return _ACTIVE_FLEET


def _set_active(sup: Optional["FleetSupervisor"]) -> None:
    global _ACTIVE_FLEET
    _ACTIVE_FLEET = sup


# --------------------------------------------------------------------- #
# worker argv


#: flags the supervisor OWNS per worker (stripped from the inherited argv
#: and re-issued with worker-specific values) or that must not recurse
#: into a worker process; value = number of value tokens the flag takes.
_WORKER_STRIP = {
    "--fleet": 1, "--fleet-role": 1, "--fleet-dir": 1,
    "--fleet-worker-id": 1, "--fleet-heartbeat": 1,
    "--fleet-epoch-records": 1, "--fleet-restart-cap": 1,
    "--fleet-chaos-kill": 1, "--fleet-slo-p99-ms": 1,
    "--input1": 1, "--checkpoint-dir": 1, "--status-port": 1,
    "--output": 1, "--postmortem-dir": 1, "--resume": 0,
    "--limit": 1, "--telemetry-dir": 1, "--trace-dir": 1, "--profile": 1,
}


def _strip_flags(argv: List[str], spec: Dict[str, int]) -> List[str]:
    out: List[str] = []
    i = 0
    while i < len(argv):
        tok = argv[i]
        name = tok.split("=", 1)[0]
        if name in spec:
            i += 1
            if spec[name] and "=" not in tok:
                i += spec[name]
            continue
        out.append(tok)
        i += 1
    return out


def worker_argv(base_argv: List[str], *, fleet_dir: str, worker_id: int,
                heartbeat_s: float, resume: bool) -> List[str]:
    """A worker's driver argv: the supervisor's own argv minus the
    fleet/placement flags, plus the worker-role glue. Everything else
    (config, query option, panes, strict-recompile, SLO, metrics…)
    inherits unchanged — a worker IS the single-process pipeline."""
    wd = F.worker_dir(fleet_dir, worker_id)
    argv = _strip_flags(list(base_argv), _WORKER_STRIP)
    argv += [
        "--fleet-role", "worker",
        "--fleet-dir", fleet_dir,
        "--fleet-worker-id", str(worker_id),
        "--fleet-heartbeat", f"{heartbeat_s:g}",
        "--input1", os.path.join(wd, F.PARTITION_FILE),
        "--checkpoint-dir", os.path.join(wd, "ckpt"),
        "--postmortem-dir", os.path.join(wd, "postmortem"),
        "--status-port", "0",
    ]
    if resume:
        argv.append("--resume")
    return argv


def _parse_chaos(spec: Optional[str]) -> Optional[Tuple[int, int]]:
    """``WID:NWINDOWS`` — SIGKILL worker WID once its outbox holds
    NWINDOWS lines (the deterministic kill hook the recovery tests and
    the bench fault row use)."""
    if not spec:
        return None
    wid, _, n = str(spec).partition(":")
    return int(wid), max(1, int(n or 1))


def _http_json(url: str, timeout: float = 1.0) -> Optional[dict]:
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return json.loads(r.read().decode())
    except Exception:
        return None


def _worker_load(poll: dict) -> Optional[float]:
    """A comparable load scalar from a worker's polled ops payloads:
    prefer the backpressure/latency plane (record→emit p99), fall back to
    None (caller then uses routed-record counts)."""
    lat = (poll or {}).get("latency") or {}
    re_h = lat.get("record_emit") or {}
    for key in ("p99_ms", "p99"):
        v = re_h.get(key)
        if isinstance(v, (int, float)):
            return float(v)
    return None


# --------------------------------------------------------------------- #
# supervisor


class FleetSupervisor:
    """One supervisor process: spawns/monitors/restarts N worker drivers,
    routes the input stream into per-worker partition files by grid leaf,
    and merges the workers' canonical outboxes into the global window
    table.

    Cross-thread discipline: the monitor thread and the main routing loop
    share process/poll state, so EVERY instance-attribute write outside
    ``__init__`` holds ``self._lock`` (the invariant linter's
    thread-shared-state rule proves this at the AST level). Durable state
    (assignment, epoch, restart counts) lives in
    :class:`~spatialflink_tpu.runtime.fleet.FleetManifest`, whose
    snapshot/restore pair the checkpoint-coverage rule proves
    field-by-field."""

    def __init__(self, args, params, spec, base_argv: List[str]):
        self._lock = threading.RLock()
        self.n_workers = int(args.fleet)
        self.root = args.fleet_dir
        self.args = args
        self.params = params
        self.case = spec
        self.base_argv = list(base_argv)
        self.heartbeat_s = float(getattr(args, "fleet_heartbeat", 1.0))
        self.hb_timeout_s = max(5.0, 5.0 * self.heartbeat_s)
        self.boot_timeout_s = 120.0
        self.epoch_records = max(1, int(getattr(args, "fleet_epoch_records",
                                                20000) or 20000))
        self.restart_cap = int(getattr(args, "fleet_restart_cap", 3))
        self.slo_p99_ms = getattr(args, "fleet_slo_p99_ms", None)
        self.manifest = F.FleetManifest(
            os.path.join(self.root, F.MANIFEST_FILE))
        self._chaos = _parse_chaos(getattr(args, "fleet_chaos_kill", None))
        self._chaos_fired = False
        self._procs: Dict[int, subprocess.Popen] = {}
        self._logs: Dict[int, object] = {}
        self._spawned_at: Dict[int, float] = {}
        self._incarnations: Dict[int, int] = {}
        self._urls: Dict[int, str] = {}
        self._polls: Dict[int, dict] = {}
        self._slo_strikes: Dict[int, int] = {}
        self._kill_reason: Dict[int, str] = {}
        self._rcs: Dict[int, int] = {}
        self._restart_log: List[dict] = []
        self._routed = 0
        self._routed_by_worker: Dict[int, int] = {}
        self._done_feeding = False
        self._draining = False
        self._stopping = False
        self._failed: Optional[Tuple[int, int]] = None
        self._monitor_thread: Optional[threading.Thread] = None

    # -------------------------------------------------------------- #
    # placement

    def _leaf_fn(self):
        """Vectorized line→leaf router over PR 8's leaf layout (default
        layout = one leaf per base cell of the configured uniform grid)."""
        from spatialflink_tpu.index.adaptive_grid import AdaptiveGrid
        from spatialflink_tpu.streams.formats import parse_spatial

        cfg = self.params.input1
        grid = self.params.grids()[0]
        refine = getattr(self.args, "adaptive_grid", None) or 4
        leaves = AdaptiveGrid(grid, refine=refine)
        geometry = self.case.stream
        kw = cfg.geojson_kwargs()

        def leaf_of(line: str) -> Optional[int]:
            try:
                obj = parse_spatial(line, cfg.format, grid,
                                    delimiter=cfg.delimiter,
                                    schema=cfg.csv_tsv_schema,
                                    geometry=geometry, **kw)
                if hasattr(obj, "x"):
                    xs, ys = obj.x, obj.y
                else:  # edge geometries place by bbox centroid
                    b = obj.bbox
                    xs, ys = (b[0] + b[2]) / 2, (b[1] + b[3]) / 2
                leaf = leaves.assign_leaf(xs, ys)
            except Exception:
                return None
            v = int(leaf if getattr(leaf, "ndim", 0) == 0 else leaf.flat[0])
            return v if v >= 0 else None

        return leaf_of

    def _seed_assignment(self, leaf_of) -> None:
        """Occupancy-seeded LPT packing from the input head (bounded by
        one epoch of records, capped — seeding is a sample-based estimate
        and must not re-parse a huge replay before routing starts); a
        resumed supervisor keeps its manifest's assignment so worker
        checkpoints stay aligned with their leaves."""
        if self.manifest.fleet_assignment:
            return
        occ: Dict[int, int] = {}
        scanned = 0
        head = min(self.epoch_records, 10_000)
        with open(self.args.input1) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                leaf = leaf_of(line)
                if leaf is not None:
                    occ[leaf] = occ.get(leaf, 0) + 1
                scanned += 1
                if scanned >= head:
                    break
        assignment = balance_leaves(occ, self.n_workers)
        self.manifest.assign_all(assignment)
        self.manifest.save()

    # -------------------------------------------------------------- #
    # worker lifecycle

    def _spawn_locked(self, wid: int, *, resume: bool, reason: str) -> None:
        wd = F.worker_dir(self.root, wid)
        os.makedirs(wd, exist_ok=True)
        inc = self._incarnations.get(wid, 0) + 1
        self._incarnations[wid] = inc
        argv = worker_argv(self.base_argv, fleet_dir=self.root,
                           worker_id=wid, heartbeat_s=self.heartbeat_s,
                           resume=resume)
        log = self._logs.get(wid)
        if log is None:
            log = open(os.path.join(wd, "worker.log"), "a")
            self._logs[wid] = log
        log.write(f"--- incarnation {inc} ({reason}) ---\n")
        log.flush()
        self._procs[wid] = subprocess.Popen(
            [sys.executable, "-m", "spatialflink_tpu.driver"] + argv,
            stdout=log, stderr=subprocess.STDOUT,
            start_new_session=True)  # controlled drain: WE forward signals
        self._spawned_at[wid] = time.monotonic()
        self._urls.pop(wid, None)
        self._slo_strikes[wid] = 0

    def _restart_locked(self, wid: int, rc: Optional[int],
                        reason: str) -> None:
        n = self.manifest.note_restart(wid)
        self.manifest.save()
        self._restart_log.append({"ts_ms": int(time.time() * 1000),
                                  "worker": wid, "rc": rc,
                                  "reason": reason, "restart": n})
        if n > self.restart_cap:
            self._failed = (wid, rc if rc is not None else -1)
            return
        self._spawn_locked(wid, resume=True, reason=reason)

    def _monitor_loop(self) -> None:
        next_poll = 0.0
        while True:
            with self._lock:
                if self._stopping or self._failed:
                    return
                procs = dict(self._procs)
            now = time.monotonic()
            poll_ops = now >= next_poll
            if poll_ops:
                next_poll = now + max(1.0, self.heartbeat_s)
            for wid, proc in procs.items():
                rc = proc.poll()
                if rc is not None:
                    self._on_exit(wid, proc, rc)
                    continue
                self._check_liveness(wid, proc)
                if poll_ops:
                    self._poll_ops(wid)
            self._check_chaos()
            time.sleep(0.2)

    def _on_exit(self, wid: int, proc: subprocess.Popen, rc: int) -> None:
        with self._lock:
            if self._procs.get(wid) is not proc:
                return
            del self._procs[wid]
            self._rcs[wid] = rc
            done = os.path.exists(
                os.path.join(F.worker_dir(self.root, wid), F.DONE_MARKER))
            if self._draining or self._stopping or (rc == 0 and done):
                return  # clean finish after EOF, or drain in progress
            reason = self._kill_reason.pop(wid, None) or (
                f"exit rc={rc}" if rc != 0
                else "exited before partition EOF")
            self._restart_locked(wid, rc, reason)

    def _check_liveness(self, wid: int, proc: subprocess.Popen) -> None:
        hb = os.path.join(F.worker_dir(self.root, wid), F.HEARTBEAT_FILE)
        age = F.heartbeat_age_s(hb)
        with self._lock:
            booted_s = time.monotonic() - self._spawned_at.get(wid, 0.0)
        if age is None:
            if booted_s > self.boot_timeout_s:
                self._kill(wid, proc, "no heartbeat after boot timeout")
        elif age > self.hb_timeout_s and booted_s > self.hb_timeout_s:
            self._kill(wid, proc, f"heartbeat stale {age:.1f}s")

    def _kill(self, wid: int, proc: subprocess.Popen, reason: str) -> None:
        with self._lock:
            self._kill_reason[wid] = reason
        try:
            proc.kill()
        except OSError:
            pass

    def _poll_ops(self, wid: int) -> None:
        url = self._resolve_url(wid)
        if not url:
            return
        status = _http_json(f"{url}/status")
        latency = _http_json(f"{url}/latency")
        if status is None and latency is None:
            return
        with self._lock:
            self._polls[wid] = {"status": status, "latency": latency,
                                "ts_ms": int(time.time() * 1000)}
        if self.slo_p99_ms:
            p99 = _worker_load({"latency": latency})
            with self._lock:
                if p99 is not None and p99 > float(self.slo_p99_ms):
                    self._slo_strikes[wid] = self._slo_strikes.get(wid,
                                                                   0) + 1
                    strikes = self._slo_strikes[wid]
                else:
                    self._slo_strikes[wid] = 0
                    strikes = 0
                proc = self._procs.get(wid)
            if strikes >= 3 and proc is not None:
                self._kill(wid, proc,
                           f"slo breach: record_emit p99 {p99:.1f}ms > "
                           f"{float(self.slo_p99_ms):g}ms x{strikes}")

    def _resolve_url(self, wid: int) -> Optional[str]:
        with self._lock:
            url = self._urls.get(wid)
        if url:
            return url
        doc = F.read_json(os.path.join(F.worker_dir(self.root, wid),
                                       F.URL_FILE))
        url = (doc or {}).get("url")
        if url:
            with self._lock:
                self._urls[wid] = url
        return url

    def _check_chaos(self) -> None:
        if self._chaos is None:
            return
        with self._lock:
            if self._chaos_fired:
                return
            wid, n = self._chaos
            proc = self._procs.get(wid)
        if proc is None:
            return
        outbox = os.path.join(F.worker_dir(self.root, wid), F.OUTBOX_FILE)
        try:
            with open(outbox) as f:
                lines = sum(1 for ln in f if ln.strip())
        except OSError:
            return
        if lines >= n:
            with self._lock:
                self._chaos_fired = True
            self._kill(wid, proc, f"chaos kill at {lines} windows")

    # -------------------------------------------------------------- #
    # routing

    def _route(self, leaf_of) -> int:
        """Feed the input file into per-worker partition files, one epoch
        at a time; at each epoch boundary, flush, rebalance if a worker
        is hot, and persist the manifest. Returns routed-record count."""
        outs = {}
        for wid in range(self.n_workers):
            wd = F.worker_dir(self.root, wid)
            os.makedirs(wd, exist_ok=True)
            outs[wid] = open(os.path.join(wd, F.PARTITION_FILE), "a")
        assignment = dict(self.manifest.fleet_assignment)
        occ: Dict[int, int] = {}
        routed = 0
        epoch_n = 0
        epoch_by_worker = {wid: 0 for wid in outs}
        try:
            with open(self.args.input1) as f:
                for line in f:
                    if _metrics.shutdown_requested():
                        break
                    with self._lock:
                        if self._failed:
                            break
                    line = line.rstrip("\n")
                    if not line.strip():
                        continue
                    if '"control"' in line:
                        # stop tuples fan out: every worker must see one
                        for w, out in outs.items():
                            out.write(line + "\n")
                            out.flush()
                        routed += 1
                        continue
                    leaf = leaf_of(line)
                    wid = (assignment.get(leaf, leaf % self.n_workers)
                           if leaf is not None else routed % self.n_workers)
                    outs[wid].write(line + "\n")
                    routed += 1
                    epoch_n += 1
                    epoch_by_worker[wid] += 1
                    if leaf is not None:
                        occ[leaf] = occ.get(leaf, 0) + 1
                    if epoch_n % 512 == 0:
                        outs[wid].flush()
                    if epoch_n >= self.epoch_records:
                        for out in outs.values():
                            out.flush()
                        assignment = self._epoch_boundary(
                            assignment, occ, epoch_by_worker)
                        epoch_n = 0
                        epoch_by_worker = {w: 0 for w in outs}
                    if (self.args.limit is not None
                            and routed >= self.args.limit):
                        break
            for out in outs.values():
                out.flush()
                os.fsync(out.fileno())
        finally:
            for out in outs.values():
                out.close()
        with self._lock:
            self._routed = routed
            for w, n in epoch_by_worker.items():
                self._routed_by_worker[w] = (
                    self._routed_by_worker.get(w, 0) + n)
        return routed

    def _epoch_boundary(self, assignment: Dict[int, int],
                        occ: Dict[int, int],
                        epoch_by_worker: Dict[int, int]) -> Dict[int, int]:
        """Rebalance decision at an epoch boundary: worker loads come from
        the polled backpressure/latency plane when available (record→emit
        p99), else from this epoch's routed-record counts; leaves move
        smallest-first from donor to receiver until roughly half the
        spread is covered."""
        with self._lock:
            for w, n in epoch_by_worker.items():
                self._routed_by_worker[w] = (
                    self._routed_by_worker.get(w, 0) + n)
            polls = dict(self._polls)
        loads: Dict[int, float] = {}
        for wid in range(self.n_workers):
            sig = _worker_load(polls.get(wid, {}))
            loads[wid] = (sig if sig is not None
                          else float(epoch_by_worker.get(wid, 0)))
        pair = pick_rebalance(loads)
        if pair is not None:
            donor, receiver = pair
            donor_leaves = sorted(
                (leaf for leaf, w in assignment.items() if w == donor),
                key=lambda leaf: occ.get(leaf, 0))
            budget = sum(occ.get(l, 0) for l in donor_leaves) // 4
            moved = []
            for leaf in donor_leaves[:-1]:  # never strip the last leaf
                if budget <= 0:
                    break
                assignment[leaf] = receiver
                budget -= occ.get(leaf, 0)
                moved.append(leaf)
            if moved:
                self.manifest.assign_all({l: receiver for l in moved})
                print(f"# fleet epoch {self.manifest.fleet_epoch + 1}: "
                      f"moved {len(moved)} leaves worker{donor} -> "
                      f"worker{receiver}", flush=True)
        self.manifest.advance_epoch()
        self.manifest.save()
        return assignment

    def _write_done_markers(self, routed: int) -> None:
        for wid in range(self.n_workers):
            atomic_write_json(
                os.path.join(F.worker_dir(self.root, wid), F.DONE_MARKER),
                {"routed_total": routed,
                 "epoch": self.manifest.fleet_epoch})

    # -------------------------------------------------------------- #
    # fleet view

    def fleet_view(self) -> dict:
        """The ``/fleet`` payload: one aggregated snapshot of every
        worker's liveness, restarts, and polled ops-plane state."""
        from spatialflink_tpu.utils.telemetry import fleet_snapshot

        with self._lock:
            procs = dict(self._procs)
            rcs = dict(self._rcs)
            polls = dict(self._polls)
            urls = dict(self._urls)
            incs = dict(self._incarnations)
            routed = self._routed
            routed_by = dict(self._routed_by_worker)
            restart_log = list(self._restart_log)
        per_leaf: Dict[int, int] = {}
        for leaf, wid in self.manifest.fleet_assignment.items():
            per_leaf[wid] = per_leaf.get(wid, 0) + 1
        workers = []
        for wid in range(self.n_workers):
            hb = os.path.join(F.worker_dir(self.root, wid),
                              F.HEARTBEAT_FILE)
            workers.append({
                "worker": wid,
                "alive": wid in procs,
                "rc": rcs.get(wid),
                "incarnations": incs.get(wid, 0),
                "restarts": self.manifest.fleet_restarts.get(wid, 0),
                "heartbeat_age_s": F.heartbeat_age_s(hb),
                "url": urls.get(wid),
                "leaves": per_leaf.get(wid, 0),
                "routed": routed_by.get(wid, 0),
                "status": (polls.get(wid) or {}).get("status"),
                "latency": (polls.get(wid) or {}).get("latency"),
            })
        return fleet_snapshot(workers, epoch=self.manifest.fleet_epoch,
                              routed=routed, restart_log=restart_log)

    # -------------------------------------------------------------- #
    # run

    def run(self) -> int:
        os.makedirs(self.root, exist_ok=True)
        leaf_of = self._leaf_fn()
        self._seed_assignment(leaf_of)
        graceful = False
        with self._lock:
            for wid in range(self.n_workers):
                ckpt = os.path.join(F.worker_dir(self.root, wid), "ckpt")
                resume = bool(os.path.isdir(ckpt) and os.listdir(ckpt))
                self._spawn_locked(wid, resume=resume, reason="start")
            self._monitor_thread = threading.Thread(
                target=self._monitor_loop, name="fleet-monitor",
                daemon=True)
            self._monitor_thread.start()
        try:
            routed = self._route(leaf_of)
            graceful = _metrics.shutdown_requested()
            if graceful:
                self._forward_sigterm()
            else:
                self._write_done_markers(routed)
            with self._lock:
                self._done_feeding = True
            rc = self._await_workers()
            if rc != 0:
                return rc
            # a SIGTERM landing after EOF (while workers drain their
            # already-complete partitions) is still a graceful stop
            graceful = graceful or _metrics.shutdown_requested()
            return self._finish(routed, graceful)
        finally:
            with self._lock:
                self._stopping = True
                procs = dict(self._procs)
            for proc in procs.values():
                if proc.poll() is None:
                    proc.terminate()
            mon = self._monitor_thread
            if mon is not None:
                mon.join(timeout=5.0)
            for log in self._logs.values():
                try:
                    log.close()
                except OSError:
                    pass

    def _forward_sigterm(self) -> None:
        with self._lock:
            if self._draining:
                return
            self._draining = True
            procs = dict(self._procs)
        print("# fleet: draining workers (SIGTERM)", flush=True)
        for proc in procs.values():
            if proc.poll() is None:
                try:
                    proc.terminate()
                except OSError:
                    pass

    def _await_workers(self) -> int:
        """Wait for every worker to reach a clean exit; the monitor keeps
        restarting crashed ones until the restart cap trips."""
        while True:
            if _metrics.shutdown_requested():
                self._forward_sigterm()  # SIGTERM after EOF: drain anyway
            with self._lock:
                failed = self._failed
                procs = dict(self._procs)
            if failed:
                wid, rc = failed
                print(f"# fleet: worker{wid} failed permanently "
                      f"(rc={rc}, restart cap {self.restart_cap})",
                      file=sys.stderr, flush=True)
                return 1
            if not procs:
                return 0
            time.sleep(0.1)

    def _finish(self, routed: int, graceful: bool) -> int:
        per_worker = {}
        runs = {}
        compiles = 0
        for wid in range(self.n_workers):
            wd = F.worker_dir(self.root, wid)
            per_worker[wid] = F.read_outbox(
                os.path.join(wd, F.OUTBOX_FILE))
            runs[wid] = F.read_runs(wd)
            compiles += sum(int(r.get("post_warmup_compiles") or 0)
                            for r in runs[wid])
        merged = F.merge_outboxes(per_worker, self.case.family,
                                  k=self.params.query.k)
        tmp = os.path.join(self.root, F.MERGED_FILE + ".tmp")
        with open(tmp, "w") as f:
            for doc in merged:
                f.write(json.dumps(doc, sort_keys=True) + "\n")
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, os.path.join(self.root, F.MERGED_FILE))
        digest = F.merged_table_digest(merged)
        with self._lock:
            restart_log = list(self._restart_log)
        result = {
            "digest": digest,
            "workers": self.n_workers,
            "routed": routed,
            "merged_windows": len(merged),
            "epochs": self.manifest.fleet_epoch,
            "restarts": {str(k): v for k, v in
                         self.manifest.fleet_restarts.items()},
            "restart_log": restart_log,
            "post_warmup_compiles": compiles,
            "graceful": graceful,
            "runs": {str(k): v for k, v in runs.items()},
        }
        atomic_write_json(os.path.join(self.root, F.RESULT_FILE), result)
        print(f"# fleet merged {len(merged)} windows from "
              f"{self.n_workers} workers (routed {routed}, "
              f"restarts {sum(self.manifest.fleet_restarts.values())}, "
              f"post-warmup compiles {compiles}, digest {digest[:16]})",
              flush=True)
        return 0


# --------------------------------------------------------------------- #
# driver entry


def run_supervisor(args, params, spec, base_argv: List[str]) -> int:
    """``--fleet N``: run the supervisor role. Owns its own opserver
    (serving ``/fleet``) and the SIGTERM drain handler; returns the
    process exit code."""
    from spatialflink_tpu.runtime.opserver import OpServer

    sup = FleetSupervisor(args, params, spec, base_argv)
    _set_active(sup)
    _metrics.clear_shutdown()
    prev_term = None
    on_main = threading.current_thread() is threading.main_thread()
    if on_main:
        prev_term = signal.signal(
            signal.SIGTERM, lambda s, f: _metrics.request_shutdown())
    server = None
    if args.status_port is not None:
        server = OpServer(port=args.status_port).start()
        print(f"# fleet opserver: {server.url}/fleet", flush=True)
    try:
        return sup.run()
    finally:
        if server is not None:
            server.close()
        if on_main and prev_term is not None:
            signal.signal(signal.SIGTERM, prev_term)
        _set_active(None)
