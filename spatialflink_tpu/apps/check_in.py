"""CheckIn app: room-occupancy counting from in/out sensor events (the DEIM
demo; reference: ``apps/CheckIn.java:26-346``).

Events are :class:`Point` records carrying the DEIM fields
(``event_id``/``device_id``/``user_id``; ``Point.java:44-46``). A device id
is ``"<room>-<in|out>"``.

Two stages, mirroring the reference:

1. **Missing-event insertion** (``ProcessWinForInsertingMissingValues``,
   ``CheckIn.java:251-317``): per user, consecutive events from the SAME
   device id (two "in"s or two "out"s in a row) imply a lost opposite event;
   a synthetic one is inserted at the midpoint timestamp.
2. **Occupancy counting** (``ProcessForCountingObjects``,
   ``CheckIn.java:208-250``): per room (device-id prefix), a running counter
   +1 on "-in" / -1 on "-out", emitted per event with the room capacity.
"""

from __future__ import annotations

import time
from typing import Dict, Iterable, Iterator, Optional, Tuple

from spatialflink_tpu.models import Point
from spatialflink_tpu.operators.base import QueryConfiguration, SpatialOperator


def parse_checkin_csv(line: str, delimiter: str = ",") -> Point:
    """``eventID,deviceID,userID,timestamp,x,y`` → DEIM point (the ctor form
    ``Point(eventID, deviceID, userID, ts, x, y)``, ``Point.java:114-125``)."""
    f = [s.strip() for s in line.strip().split(delimiter)]
    return Point(
        obj_id=f[2], timestamp=int(f[3]),
        x=float(f[4]) if len(f) > 4 else 0.0,
        y=float(f[5]) if len(f) > 5 else 0.0,
        event_id=f[0], device_id=f[1], user_id=f[2],
    )


def _room(device_id: str) -> str:
    return device_id.split("-", 1)[0]


def _symbol(device_id: str) -> str:
    return device_id.split("-", 1)[1] if "-" in device_id else ""


class CheckIn(SpatialOperator):
    """Occupancy pipeline. Grid-free: pass ``grid=None``."""
    # interner-keyed cross-window state: windows must carry
    # materialized records in the OPERATOR's id space (the
    # chunked decode still batches the parse)
    columnar_windows = False

    # CheckIn owns its fixed countWindow(2,1)/countWindow(1) pipeline
    # (apps/CheckIn.java); the generic count mode does not apply
    supports_count_windows = False

    def __init__(self, conf: QueryConfiguration, grid=None,
                 room_capacities: Optional[Dict[str, int]] = None):
        # grid is unused by CheckIn but the base init keeps the shared
        # config checks (e.g. CountBased rejection) consistent
        super().__init__(conf, grid)
        self.room_capacities = dict(room_capacities or {})

    # ------------------------------------------------------------------ #

    def insert_missing_events(self, stream: Iterable[Point]
                              ) -> Iterator[Point]:
        """Per user, repair lost in/out events: two consecutive events with
        the same device id get the opposite event synthesized at the midpoint
        timestamp (``CheckIn.java:283-307``)."""
        last: Dict[str, Point] = {}
        for p in stream:
            prev = last.get(p.user_id)
            last[p.user_id] = p
            if prev is None:
                yield p
                continue
            if prev.device_id == p.device_id and _symbol(p.device_id):
                mid = (prev.timestamp + p.timestamp) // 2
                sym = _symbol(prev.device_id)
                flipped = _room(prev.device_id) + ("-out" if sym == "in"
                                                   else "-in")
                yield Point(
                    obj_id=p.user_id, timestamp=mid, x=p.x, y=p.y,
                    event_id=p.event_id, device_id=flipped, user_id=p.user_id,
                )
            yield p

    def run(self, stream: Iterable) -> Iterator[Tuple[str, Optional[int], int, int]]:
        """-> (room, capacity, occupancy, emit_ts) per event, after missing-
        event repair. Raw CSV lines are parsed with :func:`parse_checkin_csv`."""
        points = (p if isinstance(p, Point) else parse_checkin_csv(p)
                  for p in stream)
        counters: Dict[str, int] = {}
        for p in self.insert_missing_events(points):
            room = _room(p.device_id)
            delta = {"in": 1, "out": -1}.get(_symbol(p.device_id), 0)
            counters[room] = counters.get(room, 0) + delta
            yield (room, self.room_capacities.get(room), counters[room],
                   int(time.time() * 1000))
