"""runtime/state.py unit coverage: TrajStateStore growth/rebase, the
CheckpointableState save/load round trip (including the mid-save-crash
leftover-.tmp path), and the checksum/schema hardening of the envelope."""

import json
import os
import zipfile

import numpy as np
import pytest

from spatialflink_tpu.runtime.state import (CheckpointableState,
                                            CheckpointCorrupt,
                                            STATE_SCHEMA_VERSION,
                                            TrajStateStore,
                                            checkpoint_consumed,
                                            checkpoint_meta)


# ------------------------------------------------------------ round trip


def test_save_load_round_trip(tmp_path):
    cp = CheckpointableState()
    cp.arrays["a"] = np.arange(12, dtype=np.int32).reshape(3, 4)
    cp.arrays["b"] = np.linspace(0.0, 1.0, 5, dtype=np.float32)
    cp.meta = {"consumed": 7, "names": ["x", "y"], "nested": {"k": 1}}
    path = str(tmp_path / "state.npz")
    cp.save(path)

    out = CheckpointableState.load(path)
    assert out.meta == cp.meta
    assert sorted(out.arrays) == ["a", "b"]
    np.testing.assert_array_equal(out.arrays["a"], cp.arrays["a"])
    np.testing.assert_array_equal(out.arrays["b"], cp.arrays["b"])
    assert checkpoint_consumed(path) == 7
    assert checkpoint_meta(path)["names"] == ["x", "y"]


def test_mid_save_crash_leaves_previous_checkpoint_intact(tmp_path,
                                                          monkeypatch):
    """A crash between the tmp write and the rename (simulated by a failing
    os.replace) must leave the PREVIOUS checkpoint loadable and the .tmp
    behind — the atomicity contract the coordinator's retention builds on."""
    path = str(tmp_path / "state.npz")
    cp = CheckpointableState()
    cp.arrays["v"] = np.array([1, 2, 3])
    cp.meta = {"consumed": 3}
    cp.save(path)

    cp2 = CheckpointableState()
    cp2.arrays["v"] = np.array([9, 9, 9, 9])
    cp2.meta = {"consumed": 99}
    real_replace = os.replace

    def torn_replace(src, dst, *a, **kw):
        if str(dst) == path:
            raise OSError("simulated crash mid-rename")
        return real_replace(src, dst, *a, **kw)

    monkeypatch.setattr(os, "replace", torn_replace)
    with pytest.raises(OSError, match="mid-rename"):
        cp2.save(path)
    monkeypatch.undo()

    assert os.path.exists(path + ".tmp"), "tmp file should be left behind"
    out = CheckpointableState.load(path)  # previous checkpoint still valid
    assert out.meta["consumed"] == 3
    np.testing.assert_array_equal(out.arrays["v"], [1, 2, 3])


# ------------------------------------------------------------ corruption


def test_truncated_file_raises_checkpoint_corrupt(tmp_path):
    path = str(tmp_path / "state.npz")
    cp = CheckpointableState()
    cp.arrays["v"] = np.arange(1000)
    cp.save(path)
    data = open(path, "rb").read()
    open(path, "wb").write(data[: len(data) // 2])
    with pytest.raises(CheckpointCorrupt, match="unreadable|checksum"):
        CheckpointableState.load(path)
    with pytest.raises(CheckpointCorrupt):
        checkpoint_consumed(path)


def test_garbage_file_raises_checkpoint_corrupt(tmp_path):
    path = str(tmp_path / "state.npz")
    open(path, "wb").write(b"not a zip at all")
    with pytest.raises(CheckpointCorrupt):
        CheckpointableState.load(path)


def test_checksum_mismatch_detected(tmp_path):
    """Bit-flip an array payload inside the zip: the envelope checksum must
    catch it (np.load alone would happily return the flipped values)."""
    path = str(tmp_path / "state.npz")
    cp = CheckpointableState()
    cp.arrays["v"] = np.zeros(64, np.int64)
    cp.meta = {"consumed": 5}
    cp.save(path)

    tampered = str(tmp_path / "tampered.npz")
    with zipfile.ZipFile(path) as zin, \
            zipfile.ZipFile(tampered, "w", zipfile.ZIP_STORED) as zout:
        for item in zin.infolist():
            data = zin.read(item.filename)
            if item.filename == "v.npy":
                data = data[:-8] + b"\x01" * 8
            zout.writestr(item.filename, data)
    with pytest.raises(CheckpointCorrupt, match="checksum"):
        CheckpointableState.load(tampered)


def test_newer_schema_version_refused(tmp_path):
    path = str(tmp_path / "state.npz")
    envelope = {"schema": STATE_SCHEMA_VERSION + 1, "checksum": "0" * 64,
                "meta": {"consumed": 1}}
    np.savez(path, __meta__=json.dumps(envelope))
    with pytest.raises(CheckpointCorrupt, match="schema version"):
        CheckpointableState.load(path)


def test_legacy_unversioned_checkpoint_still_loads(tmp_path):
    """Pre-envelope checkpoints (bare meta JSON, no checksum) must keep
    loading — they predate the hardening."""
    path = str(tmp_path / "legacy.npz")
    np.savez(path, __meta__=json.dumps({"consumed": 4, "capacity": 8}),
             v=np.arange(3))
    out = CheckpointableState.load(path)
    assert out.meta == {"consumed": 4, "capacity": 8}
    assert checkpoint_consumed(path) == 4


def test_checkpoint_consumed_missing_file_is_zero(tmp_path):
    assert checkpoint_consumed(str(tmp_path / "nope.npz")) == 0


# ------------------------------------------------------------ TrajStateStore


def test_traj_state_store_ensure_growth_preserves_state():
    import jax.numpy as jnp

    store = TrajStateStore(capacity=4)
    marked = store.state._replace(
        last_ts=store.state.last_ts.at[:4].set(jnp.int32([1, 2, 3, 4])))
    store.state = marked
    store.ensure(3)  # no-op below capacity
    assert store.capacity == 4
    store.ensure(5)  # power-of-two growth
    assert store.capacity >= 8 and store.capacity & (store.capacity - 1) == 0
    np.testing.assert_array_equal(np.asarray(store.state.last_ts[:4]),
                                  [1, 2, 3, 4])
    store.ensure(100)
    assert store.capacity >= 100
    np.testing.assert_array_equal(np.asarray(store.state.last_ts[:4]),
                                  [1, 2, 3, 4])


def test_traj_state_store_rebase_ts():
    import jax.numpy as jnp

    from spatialflink_tpu.ops.trajectory import INT32_MIN

    store = TrajStateStore(capacity=4)
    store.state = store.state._replace(
        last_ts=jnp.int32([INT32_MIN, 1000, -(2**30) + 5, 2**20]))
    store.rebase_ts(0)  # no-op
    np.testing.assert_array_equal(
        np.asarray(store.state.last_ts),
        [INT32_MIN, 1000, -(2**30) + 5, 2**20])
    store.rebase_ts(500)
    got = np.asarray(store.state.last_ts)
    assert got[0] == INT32_MIN          # uninitialized sentinel kept
    assert got[1] == 500                # shifted
    assert got[2] == -(2**30) + 1       # clamped to the "very old" floor
    assert got[3] == 2**20 - 500
    # a huge forward jump clamps everything initialized to the floor
    store.rebase_ts(2**31)
    got = np.asarray(store.state.last_ts)
    assert got[0] == INT32_MIN
    assert (got[1:] == -(2**30) + 1).all()


def test_traj_state_store_snapshot_restore_round_trip(tmp_path):
    import jax.numpy as jnp

    store = TrajStateStore(capacity=8)
    store.state = store.state._replace(
        last_ts=store.state.last_ts.at[0].set(jnp.int32(42)))
    cp = store.snapshot()
    path = str(tmp_path / "traj.npz")
    cp.save(path)
    restored = TrajStateStore.restore(CheckpointableState.load(path))
    assert restored.capacity == 8
    for a, b in zip(restored.state, store.state):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
