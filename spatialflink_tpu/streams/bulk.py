"""Bulk (vectorized) point ingestion: text blocks -> structure-of-arrays.

The per-tuple path (``streams.formats.parse_spatial``) mirrors the
reference's per-record deserializer; this module is the high-throughput
twin used when a whole file/window of records is available at once — the
common replay/benchmark case, and what a Kafka poll returns. The parse runs
in native C++ (:mod:`spatialflink_tpu.native`), obj-id interning is
vectorized over unique hashes, and only rejected lines (ISO dates,
non-point GeoJSON, malformed rows) fall back to the Python parser.

Output is a :class:`ParsedPoints` SoA — exactly what
:meth:`PointBatch.from_arrays` wants — plus the per-record Python
:class:`Point` view for code that needs objects.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, List, Optional, Sequence, Tuple

import numpy as np

from spatialflink_tpu import native
from spatialflink_tpu.index import UniformGrid
from spatialflink_tpu.models import Point, PointBatch
from spatialflink_tpu.streams import formats
from spatialflink_tpu.utils import IdInterner

import ctypes


@dataclass
class ParsedPoints:
    """Structure-of-arrays result of a bulk parse (record order preserved)."""

    x: np.ndarray       # (N,) f64
    y: np.ndarray       # (N,) f64
    ts: np.ndarray      # (N,) i64 epoch millis
    obj_id: np.ndarray  # (N,) i32 interned ids
    interner: IdInterner

    def __len__(self) -> int:
        return self.x.shape[0]

    def to_batch(self, grid: Optional[UniformGrid] = None, *,
                 ts_base: Optional[int] = None,
                 pad: Optional[int] = None) -> PointBatch:
        base = int(self.ts[0]) if ts_base is None and len(self) else (ts_base or 0)
        return PointBatch.from_arrays(
            self.x, self.y, grid=grid, obj_id=self.obj_id, ts=self.ts,
            ts_base=base, pad=pad,
        )

    def to_points(self, grid: Optional[UniformGrid] = None) -> List[Point]:
        """Per-record Point objects (the ONE ParsedPoints->records
        conversion — the kafka chunked decode and tests share it); cell
        assignment is vectorized over the whole batch (Point.create's
        per-point assign would dominate the loop)."""
        if grid is not None:
            cells, _ = grid.assign_cell(self.x, self.y)
        else:
            cells = np.full(len(self), -1, np.int32)
        lk = self.interner.lookup
        return [
            Point(obj_id=lk(int(o)), timestamp=int(t), x=float(x),
                  y=float(y), cell=int(c))
            for o, t, x, y, c in zip(self.obj_id, self.ts, self.x, self.y,
                                     cells)
        ]


@dataclass
class PointChunk:
    """One decoded chunk riding the batched record path: the columnar parse
    result plus the vectorized per-record cell assignment, so nothing
    downstream re-derives either per record. ``positions`` (optional) carries
    the per-record source offsets a Kafka commit tap snapshotted at pull
    time; ``ingest_ms`` is the wall clock the chunk was decoded at — the
    stamp lazily-materialized Points inherit as ``ingestion_time`` (the
    scalar path stamped each record at parse; per-chunk is the batched
    equivalent)."""

    parsed: ParsedPoints
    cells: np.ndarray                       # (N,) i32, -1 = outside grid
    positions: Optional[np.ndarray] = None  # (N,) i64 source offsets
    ingest_ms: int = 0
    #: checkpoint-position callback (set by the Kafka commit tap): chunk
    #: consumers that dribble records out one at a time (the flatten path
    #: feeding joins/trajectory) re-note per record so a checkpoint barrier
    #: never covers records still sitting in a half-consumed chunk; the
    #: chunk-aware assemblers buffer whole chunks before any barrier can
    #: run, so the tap's chunk-level note is already safe there
    note: Optional[Callable[[int], None]] = None

    def __len__(self) -> int:
        return len(self.parsed)

    @staticmethod
    def build(parsed: ParsedPoints, grid: Optional[UniformGrid],
              positions=None) -> "PointChunk":
        import time as _time

        if grid is not None and len(parsed):
            cells, _ = grid.assign_cell(parsed.x, parsed.y)
            cells = np.asarray(cells, np.int32)
        else:
            cells = np.full(len(parsed), -1, np.int32)
        return PointChunk(parsed=parsed, cells=cells,
                          positions=None if positions is None
                          else np.asarray(positions, np.int64),
                          ingest_ms=int(_time.time() * 1000))

    def record(self, i: int) -> Point:
        """Materialize record ``i`` (the lazy per-record view)."""
        p = self.parsed
        return Point(obj_id=p.interner.lookup(int(p.obj_id[i])),
                     timestamp=int(p.ts[i]), x=float(p.x[i]),
                     y=float(p.y[i]), cell=int(self.cells[i]),
                     ingestion_time=self.ingest_ms)

    def records(self) -> List[Point]:
        """Materialize every record (the flatten path for consumers without
        a columnar window driver — joins, trajectory, realtime)."""
        lk = self.parsed.interner.lookup
        ing = self.ingest_ms
        return [
            Point(obj_id=lk(int(o)), timestamp=int(t), x=float(x),
                  y=float(y), cell=int(c), ingestion_time=ing)
            for o, t, x, y, c in zip(self.parsed.obj_id, self.parsed.ts,
                                     self.parsed.x, self.parsed.y,
                                     self.cells)
        ]


class LazyRecords:
    """A window's (or pane's) record list as columnar chunk slices,
    materializing per-record :class:`Point` objects only on demand.

    This is what the batched record path buffers instead of Python objects:
    segments are either ``(PointChunk, idx_array)`` columnar slices or plain
    record lists (mixed streams — a bulk-ineligible chunk falls back to
    objects). ``point_batch`` builds the window's device batch straight from
    the SoA slices (no per-record objects anywhere on the selected path);
    ``__getitem__`` materializes single records so sparse selections (range
    survivors, join pairs) only ever pay for what they emit. Object ids
    across every segment live in ONE id space — the stream's decode
    ``interner`` — which kNN result resolution and pane-merge tie-breaking
    read through."""

    __slots__ = ("_segs", "_offsets", "_len", "interner", "_cache")

    def __init__(self, segs):
        self._segs = segs
        self._offsets = []
        self._len = 0
        self.interner = None
        for seg in segs:
            self._offsets.append(self._len)
            if isinstance(seg, tuple):
                chunk, idx = seg
                self._len += int(idx.size)
                if self.interner is None:
                    self.interner = chunk.parsed.interner
            else:
                self._len += len(seg)
        self._cache: dict = {}

    def __len__(self) -> int:
        return self._len

    def __getitem__(self, i):
        if isinstance(i, slice):
            return [self[j] for j in range(*i.indices(self._len))]
        if i < 0:
            i += self._len
        if not 0 <= i < self._len:
            raise IndexError(i)
        hit = self._cache.get(i)
        if hit is not None:
            return hit
        # segment lookup (few segments per window; linear scan is fine)
        for seg, off in zip(reversed(self._segs), reversed(self._offsets)):
            if i >= off:
                rec = (seg[0].record(int(seg[1][i - off]))
                       if isinstance(seg, tuple) else seg[i - off])
                self._cache[i] = rec
                return rec
        raise IndexError(i)

    def __iter__(self):
        for seg in self._segs:
            if isinstance(seg, tuple):
                chunk, idx = seg
                for j in idx.tolist():
                    yield chunk.record(j)
            else:
                yield from seg

    def _flat(self):
        """Memoized concatenated per-record arrays (x, y, ts, oid, cell,
        ingest_ms) for vectorized selection; None when an object segment
        makes the columnar gather inapplicable (mixed streams take the
        per-item path)."""
        flat = self._cache.get("_flat_", False)
        if flat is not False:
            return flat
        xs, ys, tss, oids, cells, ings = [], [], [], [], [], []
        for seg in self._segs:
            if not isinstance(seg, tuple):
                self._cache["_flat_"] = None
                return None
            chunk, idx = seg
            p = chunk.parsed
            xs.append(p.x[idx])
            ys.append(p.y[idx])
            tss.append(p.ts[idx])
            oids.append(p.obj_id[idx])
            cells.append(chunk.cells[idx])
            ings.append(np.full(idx.size, chunk.ingest_ms, np.int64))
        flat = tuple(np.concatenate(a) for a in (xs, ys, tss, oids, cells,
                                                 ings))
        self._cache["_flat_"] = flat
        return flat

    def point_batch(self, grid, ts_base: int,
                    pad: Optional[int] = None) -> PointBatch:
        """The window's device batch from the columnar slices — cells were
        assigned once per chunk, obj ids stay in the decode interner's id
        space. Object segments (mixed streams) intern into the same space."""
        xs, ys, tss, oids, cells = [], [], [], [], []
        interner = self.interner if self.interner is not None else IdInterner()
        for seg in self._segs:
            if isinstance(seg, tuple):
                chunk, idx = seg
                p = chunk.parsed
                xs.append(p.x[idx])
                ys.append(p.y[idx])
                tss.append(p.ts[idx])
                oids.append(p.obj_id[idx])
                cells.append(chunk.cells[idx])
            elif seg:
                xs.append(np.array([r.x for r in seg], np.float64))
                ys.append(np.array([r.y for r in seg], np.float64))
                tss.append(np.array([r.timestamp for r in seg], np.int64))
                oids.append(np.array([interner.intern(r.obj_id)
                                      for r in seg], np.int32))
                cells.append(np.array([r.cell for r in seg], np.int32))
        if not xs:
            return PointBatch.from_arrays(np.empty(0), np.empty(0),
                                          grid=grid, ts_base=ts_base, pad=pad)
        return PointBatch.from_arrays(
            np.concatenate(xs), np.concatenate(ys), grid=grid,
            obj_id=np.concatenate(oids), ts=np.concatenate(tss),
            ts_base=ts_base, pad=pad, cell=np.concatenate(cells))

    def take(self, idx):
        """The records at ``idx`` as a :class:`PointRows` view — one
        vectorized gather instead of N ``__getitem__`` segment lookups, and
        Point objects materialize only if a consumer actually reads them
        (result sinks serialize straight from the arrays)."""
        flat = self._flat()
        if flat is None:
            return [self[int(i)] for i in idx]
        idx = np.asarray(idx, np.int64)
        return PointRows(tuple(a[idx] for a in flat), self.interner)


class PointRows:
    """A window's SELECTED records as columnar arrays — list-shaped (len /
    index / iterate / slice materialize real :class:`Point` objects,
    cached), but sinks that only need serialized output read
    :meth:`serialize_batch` and never build a Python object per record.
    This is what keeps the batched path's per-selected-record cost at
    string-format level instead of dataclass-construction level."""

    __slots__ = ("_cols", "interner", "_mat")

    def __init__(self, cols, interner):
        self._cols = cols  # (x, y, ts, oid, cell, ingest_ms) gathered
        self.interner = interner
        self._mat = None

    def __len__(self) -> int:
        return int(self._cols[0].shape[0])

    def _materialize(self) -> List[Point]:
        if self._mat is None:
            fx, fy, ft, fo, fc, fi = self._cols
            lk = self.interner.lookup
            self._mat = [
                Point(obj_id=lk(int(o)), timestamp=int(t), x=float(x),
                      y=float(y), cell=int(c), ingestion_time=int(g))
                for o, t, x, y, c, g in zip(fo, ft, fx, fy, fc, fi)
            ]
        return self._mat

    def __getitem__(self, i):
        return self._materialize()[i]

    def __iter__(self):
        return iter(self._materialize())

    def __eq__(self, other):
        if isinstance(other, PointRows):
            other = other._materialize()
        return self._materialize() == other

    def __repr__(self):
        return f"PointRows({len(self)} records)"

    def __add__(self, other):
        return self._materialize() + list(other)

    def __radd__(self, other):
        return list(other) + self._materialize()

    def serialize_batch(self, fmt, *, delimiter: str = ",",
                        date_format=None) -> Optional[List[str]]:
        """Serialized output records straight from the columnar arrays —
        GeoJSON rides the exact fast template ``formats.serialize_geojson``
        uses (byte-identical, equivalence-tested); other formats return
        None and the caller serializes materialized records."""
        if not fmt or fmt.lower() != "geojson":
            return None
        import json as _json

        from spatialflink_tpu.streams.formats import (_JSON_SAFE_RE,
                                                      format_timestamp)

        fx, fy, ft, fo, _fc, _fi = self._cols
        lk = self.interner.lookup
        tmpl = ('{"geometry": {"type": "Point", "coordinates": [%r, %r]}, '
                '"properties": {"oID": %s, "timestamp": %s}, '
                '"type": "Feature"}')
        safe = _JSON_SAFE_RE.match
        # ids: one quote/escape per DISTINCT object, gathered vectorized
        uniq, inv = np.unique(fo, return_inverse=True)
        qid = np.array(
            [('"%s"' % s if safe(s) else _json.dumps(s))
             for s in (lk(int(u)) for u in uniq)], dtype=object)[inv]
        if date_format and "%f" not in date_format:
            # timestamps quote-memoized per second (format_timestamp is
            # already second-memoized; this also amortizes the escape —
            # sound only without a sub-second token, like that memo)
            memo: dict = {}

            def jts(t):
                k = int(t) // 1000
                s = memo.get(k)
                if s is None:
                    raw = format_timestamp(int(t), date_format)
                    s = '"%s"' % raw if safe(raw) else _json.dumps(raw)
                    memo[k] = s
                return s
        elif date_format:
            def jts(t):
                raw = format_timestamp(int(t), date_format)
                return '"%s"' % raw if safe(raw) else _json.dumps(raw)
        else:
            jts = int
        return [tmpl % (float(x), float(y), o, jts(t))
                for x, y, o, t in zip(fx, fy, qid, ft)]

def _ptr(a: np.ndarray, ctype):
    return a.ctypes.data_as(ctypes.POINTER(ctype))


def _intern_hashes(data: bytes, oid_hash, oid_start, oid_len,
                   interner: IdInterner, normalize) -> np.ndarray:
    """Vectorized obj-id interning: one string materialization per UNIQUE
    hash, everything else is numpy. ``normalize`` applies the same id
    normalization the native hash used (format-specific)."""
    uniq, first, inv = np.unique(oid_hash, return_index=True, return_inverse=True)
    ids = np.empty(uniq.shape[0], np.int32)
    for u, j in enumerate(first):
        s = data[oid_start[j]: oid_start[j] + oid_len[j]].decode("utf-8", "replace")
        ids[u] = interner.intern(normalize(s))
    return ids[inv]


# CSV ids: parse_csv removes every '"' then field-trims whitespace; GeoJSON
# ids: the native span is already the exact decoded value
_NORM_CSV = lambda s: s.replace('"', "").strip()  # noqa: E731
_NORM_RAW = lambda s: s  # noqa: E731


def _nonblank_lines(data: bytes):
    """The C parser's blank-line rule exactly: a line is blank iff it contains
    only ' ', '\t', '\r' — NOT the wider bytes.strip() whitespace set, so
    reject indices stay aligned."""
    return [ln for ln in data.split(b"\n") if ln.strip(b" \t\r")]


def _merge_rejects(n: int, accepted: dict, reparsed: List[Tuple[int, Point]],
                   interner: IdInterner) -> ParsedPoints:
    """Stitch native-accepted arrays and Python-reparsed records back into
    original line order."""
    if not reparsed:  # fast path: nothing rejected, arrays are already ordered
        return ParsedPoints(
            x=np.ascontiguousarray(accepted["x"]),
            y=np.ascontiguousarray(accepted["y"]),
            ts=np.ascontiguousarray(accepted["ts"]),
            obj_id=accepted["oid"], interner=interner,
        )
    total = n + len(reparsed)
    x = np.empty(total, np.float64)
    y = np.empty(total, np.float64)
    ts = np.empty(total, np.int64)
    oid = np.empty(total, np.int32)
    reject_lines = {line for line, _ in reparsed}
    # accepted records occupy the non-rejected line slots in order
    order = [i for i in range(total) if i not in reject_lines]
    x[order] = accepted["x"]
    y[order] = accepted["y"]
    ts[order] = accepted["ts"]
    oid[order] = accepted["oid"]
    for line, p in reparsed:
        x[line], y[line], ts[line] = p.x, p.y, p.timestamp
        oid[line] = interner.intern(p.obj_id)
    return ParsedPoints(x=x, y=y, ts=ts, obj_id=oid, interner=interner)


def _require_point(obj, line: str) -> Point:
    if not isinstance(obj, Point):
        raise ValueError(
            "bulk point ingestion got a non-Point record "
            f"({type(obj).__name__}); use streams.formats.parse_spatial for "
            f"mixed-geometry streams: {line[:120]!r}"
        )
    return obj


def _python_fallback(data: bytes, fmt: str, interner: IdInterner,
                     **kw) -> ParsedPoints:
    pts = []
    for ln in data.decode("utf-8", "replace").split("\n"):
        if not ln.strip():
            continue
        pts.append(_require_point(formats.parse_spatial(ln, fmt, None, **kw), ln))
    return ParsedPoints(
        x=np.array([p.x for p in pts], np.float64),
        y=np.array([p.y for p in pts], np.float64),
        ts=np.array([p.timestamp for p in pts], np.int64),
        obj_id=np.array([interner.intern(p.obj_id) for p in pts], np.int32),
        interner=interner,
    )


def bulk_parse_csv(
    data: bytes,
    *,
    delimiter: str = ",",
    schema: Sequence[Optional[int]] = (0, 1, 2, 3),
    date_format: Optional[str] = formats.DEFAULT_DATE_FORMAT,
    interner: Optional[IdInterner] = None,
) -> ParsedPoints:
    """Parse a newline-separated CSV/TSV block of points.

    ``schema`` = column indices of [oID, timestamp, x, y] (None = absent),
    matching :func:`formats.parse_csv` / ``Deserialization.java:288-330``.
    """
    interner = interner if interner is not None else IdInterner()
    nlib = native.lib()
    if nlib is None:
        return _python_fallback(data, "csv", interner, delimiter=delimiter,
                                schema=schema, date_format=date_format)
    cap = data.count(b"\n") + 1
    buf = data if data.endswith(b"\0") else data + b"\0"
    xs = np.empty(cap, np.float64)
    ys = np.empty(cap, np.float64)
    ts = np.empty(cap, np.int64)
    oh = np.empty(cap, np.uint64)
    os_ = np.empty(cap, np.int64)
    ol = np.empty(cap, np.int32)
    rej = np.empty(cap, np.int64)
    nrej = ctypes.c_long(0)
    oi = -1 if schema[0] is None else int(schema[0])
    ti = -1 if schema[1] is None else int(schema[1])
    n = nlib.sf_parse_points_csv(
        buf, len(data), delimiter.encode()[:1] or b",",
        oi, ti, int(schema[2]), int(schema[3]),
        _ptr(xs, ctypes.c_double), _ptr(ys, ctypes.c_double),
        _ptr(ts, ctypes.c_int64),
        _ptr(oh, ctypes.c_uint64), _ptr(os_, ctypes.c_int64),
        _ptr(ol, ctypes.c_int32),
        _ptr(rej, ctypes.c_int64), ctypes.byref(nrej),
    )
    oid = _intern_hashes(data, oh[:n], os_[:n], ol[:n], interner, _NORM_CSV)
    accepted = {"x": xs[:n], "y": ys[:n], "ts": ts[:n], "oid": oid}
    reparsed = []
    if nrej.value:  # line-splitting is only paid when something was rejected
        lines = _nonblank_lines(data)
        for i in rej[: nrej.value]:
            ln = lines[int(i)].decode("utf-8", "replace")
            p = formats.parse_csv(ln, None, delimiter=delimiter, schema=schema,
                                  date_format=date_format)
            reparsed.append((int(i), _require_point(p, ln)))
    return _merge_rejects(n, accepted, reparsed, interner)


def bulk_parse_geojson(
    data: bytes,
    *,
    property_obj_id: str = "oID",
    property_timestamp: str = "timestamp",
    date_format: Optional[str] = None,
    interner: Optional[IdInterner] = None,
) -> ParsedPoints:
    """Parse a newline-separated block of GeoJSON Point features.

    Non-point features and date-formatted timestamps are re-parsed by the
    Python parser (full fidelity), so this accepts exactly what
    :func:`formats.parse_geojson` accepts.
    """
    interner = interner if interner is not None else IdInterner()
    nlib = native.lib()
    kw = dict(property_obj_id=property_obj_id,
              property_timestamp=property_timestamp,
              date_format=date_format)
    if nlib is None:
        return _python_fallback(data, "geojson", interner, **kw)
    cap = data.count(b"\n") + 1
    buf = data if data.endswith(b"\0") else data + b"\0"
    xs = np.empty(cap, np.float64)
    ys = np.empty(cap, np.float64)
    ts = np.empty(cap, np.int64)
    oh = np.empty(cap, np.uint64)
    os_ = np.empty(cap, np.int64)
    ol = np.empty(cap, np.int32)
    rej = np.empty(cap, np.int64)
    nrej = ctypes.c_long(0)
    n = nlib.sf_parse_points_geojson(
        buf, len(data),
        property_obj_id.encode(), property_timestamp.encode(),
        _ptr(xs, ctypes.c_double), _ptr(ys, ctypes.c_double),
        _ptr(ts, ctypes.c_int64),
        _ptr(oh, ctypes.c_uint64), _ptr(os_, ctypes.c_int64),
        _ptr(ol, ctypes.c_int32),
        _ptr(rej, ctypes.c_int64), ctypes.byref(nrej),
    )
    oid = _intern_hashes(data, oh[:n], os_[:n], ol[:n], interner, _NORM_RAW)
    accepted = {"x": xs[:n], "y": ys[:n], "ts": ts[:n], "oid": oid}
    reparsed = []
    if nrej.value:
        lines = _nonblank_lines(data)
        for i in rej[: nrej.value]:
            ln = lines[int(i)].decode("utf-8", "replace")
            p = formats.parse_geojson(ln, None, **kw)
            reparsed.append((int(i), _require_point(p, ln)))
    return _merge_rejects(n, accepted, reparsed, interner)


def bulk_window_batches(parsed: ParsedPoints, spec, grid=None, *,
                        pad: Optional[int] = None):
    """Vectorized window assembly: ParsedPoints -> per-window device batches.

    Yields ``(start, end, idx, PointBatch)`` in window order, where ``idx``
    is the original-record index array for the window. The whole assignment
    is numpy (``WindowSpec.assign_bulk``); batches are built straight from
    the SoA slices, so no per-record Python objects exist anywhere on this
    path — the high-throughput twin of ``WindowAssembler`` for bounded
    replays, mirroring how ``bulk_parse_*`` twins ``formats.parse_spatial``.
    """
    if not len(parsed):
        return
    win, rec = spec.assign_bulk(parsed.ts)
    if not len(win):  # sampling specs (slide > size) can assign nothing
        return
    # cells once per record, not once per window membership (sliding windows
    # revisit each record size/slide times)
    if grid is not None:
        cells, _ = grid.assign_cell(parsed.x, parsed.y)
        cells = np.asarray(cells, np.int32)
    else:
        cells = np.full(len(parsed), -1, np.int32)
    bounds = np.flatnonzero(np.r_[True, win[1:] != win[:-1], True])
    for i in range(len(bounds) - 1):
        lo, hi = int(bounds[i]), int(bounds[i + 1])
        start = int(win[lo])
        idx = rec[lo:hi]
        batch = PointBatch.from_arrays(
            parsed.x[idx], parsed.y[idx], grid=grid,
            obj_id=parsed.obj_id[idx], ts=parsed.ts[idx],
            ts_base=start, pad=pad, cell=cells[idx],
        )
        yield start, start + spec.size_ms, idx, batch


def bulk_pane_window_batches(parsed: ParsedPoints, spec, grid=None, *,
                             pad: Optional[int] = None):
    """Pane-sliced twin of :func:`bulk_window_batches` for the
    ``--panes`` execution mode: each record lands in exactly ONE
    slide-aligned pane batch (built once — not ``size/slide`` times), and
    windows are yielded as ``(start, end, [(pane_start, (idx, batch)),
    ...])`` pane lists covering the same window set ``assign_bulk`` would
    produce. Requires ``spec.pane_decomposable()`` (callers gate)."""
    if not len(parsed):
        return
    size, slide = spec.size_ms, spec.slide_ms
    ts = np.asarray(parsed.ts, np.int64)
    pane = ts - ts % slide
    order = np.argsort(pane, kind="stable")  # record order kept within pane
    pane_s = pane[order]
    if grid is not None:
        cells, _ = grid.assign_cell(parsed.x, parsed.y)
        cells = np.asarray(cells, np.int32)
    else:
        cells = np.full(len(parsed), -1, np.int32)
    bounds = np.flatnonzero(np.r_[True, pane_s[1:] != pane_s[:-1], True])
    # index slices now (cheap views of `order`); pane BATCHES build lazily
    # on first use and evict once no later window can cover them, so peak
    # host memory is O(overlap panes), not a second full copy of the replay
    slices = {int(pane_s[int(bounds[i])]):
              order[int(bounds[i]): int(bounds[i + 1])]
              for i in range(len(bounds) - 1)}
    built: dict = {}
    # window set: every aligned start covered by >= 1 non-empty pane — the
    # same set assign_bulk derives record-by-record
    starts = sorted({int(s)
                     for p in slices
                     for s in range(p - size + slide, p + slide, slide)})
    for s in starts:
        panes = []
        for p in range(s, s + size, slide):
            idx = slices.get(p)
            if idx is None:
                continue
            batch = built.get(p)
            if batch is None:
                batch = built[p] = PointBatch.from_arrays(
                    parsed.x[idx], parsed.y[idx], grid=grid,
                    obj_id=parsed.obj_id[idx], ts=parsed.ts[idx],
                    ts_base=p, pad=pad, cell=cells[idx],
                )
            panes.append((p, (idx, batch)))
        for dead in [p for p in built if p < s + slide]:
            del built[dead]
        yield s, s + size, panes


def bulk_parse_file(path: str, fmt: str, **kw) -> ParsedPoints:
    """Bulk-parse a whole replay file of points."""
    with open(path, "rb") as f:
        data = f.read()
    if fmt.lower() in ("csv", "tsv"):
        if fmt.lower() == "tsv":
            kw.setdefault("delimiter", "\t")
        return bulk_parse_csv(data, **kw)
    if fmt.lower() == "geojson":
        return bulk_parse_geojson(data, **kw)
    raise ValueError(f"bulk ingestion supports csv/tsv/geojson, not {fmt!r}")


# --------------------------------------------------------------------------- #
# Bulk WKT geometry ingestion (polygon / linestring streams)

@dataclass
class ParsedGeoms:
    """Structure-of-arrays result of a bulk WKT geometry parse.

    Flattened ragged layout: geometry g owns rings
    ``ring_off[g] : ring_off[g] + ring_cnt[g]``; ring r owns raw vertices
    ``ring_voff[r] : ring_voff[r] + ring_size[r]`` in (``vx``, ``vy``).
    Lines the native parser rejected (MULTI* geometries, date-formatted
    timestamps, malformed WKT) are re-parsed in Python and flattened into
    the SAME arrays in original line order, so downstream assembly never
    sees two representations.
    """

    ts: np.ndarray        # (N,) i64 epoch millis
    obj_id: np.ndarray    # (N,) i32 interned
    is_areal: np.ndarray  # (N,) bool
    bbox: np.ndarray      # (N, 4) f64
    ring_off: np.ndarray  # (N,) i64
    ring_cnt: np.ndarray  # (N,) i32
    ring_voff: np.ndarray  # (R,) i64
    ring_size: np.ndarray  # (R,) i32
    vx: np.ndarray        # (V,) f64
    vy: np.ndarray        # (V,) f64
    interner: IdInterner

    def __len__(self) -> int:
        return self.ts.shape[0]

    def subset(self, idx: np.ndarray) -> "ParsedGeoms":
        """Geometry subset with re-based ring/vertex offsets (window
        assembly slices the stream dim; pure numpy)."""
        idx = np.asarray(idx)
        rcnt = self.ring_cnt[idx]
        # ring indices of the selected geometries, in selection order
        rrep = np.repeat(np.arange(idx.size), rcnt)
        cum = np.concatenate([[0], np.cumsum(rcnt)])
        rpos = np.arange(int(cum[-1])) - np.repeat(cum[:-1], rcnt)
        rings = self.ring_off[idx][rrep] + rpos
        sizes = self.ring_size[rings].astype(np.int64)
        # vertex gather per selected ring
        vrep = np.repeat(np.arange(rings.size), sizes)
        vcum = np.concatenate([[0], np.cumsum(sizes)])
        vpos = np.arange(int(vcum[-1])) - np.repeat(vcum[:-1], sizes)
        verts = self.ring_voff[rings][vrep] + vpos
        return ParsedGeoms(
            ts=self.ts[idx], obj_id=self.obj_id[idx],
            is_areal=self.is_areal[idx], bbox=self.bbox[idx],
            ring_off=cum[:-1].astype(np.int64),
            ring_cnt=rcnt,
            ring_voff=vcum[:-1].astype(np.int64),
            ring_size=sizes.astype(np.int32),
            vx=self.vx[verts], vy=self.vy[verts],
            interner=self.interner,
        )


def _object_rings(obj) -> Tuple[List[np.ndarray], bool]:
    """A parsed geometry object's rings as coordinate arrays + is_areal —
    how reject objects flatten into the ParsedGeoms layout. Multi-part
    geometries flatten to all their parts' rings (the edge/cells semantics
    EdgeGeomBatch.from_objects derives via obj.edge_array())."""
    from spatialflink_tpu.models import objects as sobj

    if isinstance(obj, sobj.MultiPolygon):
        return [np.asarray(r, np.float64) for p in obj.polygons
                for r in p.rings], True
    if isinstance(obj, sobj.Polygon):
        return [np.asarray(r, np.float64) for r in obj.rings], True
    if isinstance(obj, sobj.MultiLineString):
        return [np.asarray(l.coords_list, np.float64) for l in obj.lines], False
    if isinstance(obj, sobj.LineString):
        return [np.asarray(obj.coords_list, np.float64)], False
    raise ValueError(
        f"bulk WKT geometry ingestion got {type(obj).__name__}; use "
        "streams.formats.parse_spatial for mixed-geometry streams")


def bulk_parse_wkt(
    data: bytes,
    *,
    delimiter: str = ",",
    date_format: Optional[str] = formats.DEFAULT_DATE_FORMAT,
    interner: Optional[IdInterner] = None,
) -> ParsedGeoms:
    """Parse a newline-separated block of WKT polygon/linestring records
    with optional ``oid<delim>ts<delim>`` prefix fields — the bulk twin of
    ``parse_spatial(..., "WKT")`` for geometry streams
    (``Deserialization.java:516-628`` WKT polygon/linestring parsers).
    """
    interner = interner if interner is not None else IdInterner()

    def parse_line(ln):
        return formats.parse_spatial(ln, "WKT", None, delimiter=delimiter,
                                     date_format=date_format)

    nlib = native.lib()
    if nlib is None:
        return _geoms_python_fallback(data, parse_line, interner)
    capr = max(1, data.count(b"("))
    capv = data.count(b",") + capr + 2

    def invoke(buf, *arrs):
        return nlib.sf_parse_wkt_geoms(
            buf, len(data), delimiter.encode()[:1] or b",", *arrs)

    return _native_geoms_parse(data, invoke, parse_line, interner,
                               _NORM_CSV, capr, capv)


def bulk_parse_geojson_geoms(
    data: bytes,
    *,
    property_obj_id: str = "oID",
    property_timestamp: str = "timestamp",
    date_format: Optional[str] = None,
    interner: Optional[IdInterner] = None,
) -> ParsedGeoms:
    """Parse a newline-separated block of GeoJSON Polygon/LineString
    features — the bulk twin of ``parse_spatial(..., "GeoJSON")`` for
    geometry streams (``Deserialization.java:236-334``
    GeoJSONToSpatialPolygon/LineString). Point/Multi*/GeometryCollection
    features, escaped strings and date-formatted timestamps are re-parsed
    by the Python parser, so this accepts exactly what the record path
    accepts."""
    interner = interner if interner is not None else IdInterner()
    kw = dict(property_obj_id=property_obj_id,
              property_timestamp=property_timestamp,
              date_format=date_format)

    def parse_line(ln):
        return formats.parse_spatial(ln, "GeoJSON", None, **kw)

    nlib = native.lib()
    if nlib is None:
        return _geoms_python_fallback(data, parse_line, interner)
    # every point/ring/coords level opens one '[' -> safe upper bounds
    capr = max(1, data.count(b"["))
    capv = capr + 2

    def invoke(buf, *arrs):
        return nlib.sf_parse_geojson_geoms(
            buf, len(data), property_obj_id.encode(),
            property_timestamp.encode(), *arrs)

    return _native_geoms_parse(data, invoke, parse_line, interner,
                               _NORM_RAW, capr, capv)


def _native_geoms_parse(data: bytes, invoke, parse_line, interner, norm,
                        capr: int, capv: int) -> ParsedGeoms:
    """Shared buffers + assembly for the native geometry parsers
    (sf_parse_wkt_geoms / sf_parse_geojson_geoms — identical output
    contract). ``invoke(buf, *array_ptrs)`` calls the symbol with its
    format-specific leading arguments; rejects reparse via ``parse_line``."""
    cap = data.count(b"\n") + 1
    buf = data if data.endswith(b"\0") else data + b"\0"
    ts = np.empty(cap, np.int64)
    oh = np.empty(cap, np.uint64)
    os_ = np.empty(cap, np.int64)
    ol = np.empty(cap, np.int32)
    ispoly = np.empty(cap, np.int8)
    roff = np.empty(cap, np.int64)
    rcnt = np.empty(cap, np.int32)
    bbox = np.empty((cap, 4), np.float64)
    rvoff = np.empty(capr, np.int64)
    rsize = np.empty(capr, np.int32)
    vx = np.empty(capv, np.float64)
    vy = np.empty(capv, np.float64)
    rej = np.empty(cap, np.int64)
    nrej = ctypes.c_long(0)
    n = invoke(
        buf,
        _ptr(ts, ctypes.c_int64), _ptr(oh, ctypes.c_uint64),
        _ptr(os_, ctypes.c_int64), _ptr(ol, ctypes.c_int32),
        _ptr(ispoly, ctypes.c_int8),
        _ptr(roff, ctypes.c_int64), _ptr(rcnt, ctypes.c_int32),
        _ptr(bbox, ctypes.c_double),
        _ptr(rvoff, ctypes.c_int64), _ptr(rsize, ctypes.c_int32),
        _ptr(vx, ctypes.c_double), _ptr(vy, ctypes.c_double),
        _ptr(rej, ctypes.c_int64), ctypes.byref(nrej),
    )
    oid = _intern_hashes(data, oh[:n], os_[:n], ol[:n], interner, norm)
    n_rings = int(rcnt[:n].sum())
    n_verts = int(rsize[:n_rings].sum()) if n_rings else 0
    accepted = ParsedGeoms(
        ts=np.ascontiguousarray(ts[:n]), obj_id=oid,
        is_areal=ispoly[:n].astype(bool),
        bbox=np.ascontiguousarray(bbox[:n]),
        ring_off=np.ascontiguousarray(roff[:n]),
        ring_cnt=np.ascontiguousarray(rcnt[:n]),
        ring_voff=np.ascontiguousarray(rvoff[:n_rings]),
        ring_size=np.ascontiguousarray(rsize[:n_rings]),
        vx=np.ascontiguousarray(vx[:n_verts]),
        vy=np.ascontiguousarray(vy[:n_verts]),
        interner=interner,
    )
    if not nrej.value:
        return accepted
    lines = _nonblank_lines(data)
    reparsed = []
    for i in rej[: nrej.value]:
        ln = lines[int(i)].decode("utf-8", "replace")
        reparsed.append((int(i), parse_line(ln)))
    return _merge_geom_rejects(accepted, reparsed, interner)


def _geoms_python_fallback(data: bytes, parse_line, interner) -> ParsedGeoms:
    """No native library: parse every line in Python, same output layout."""
    reparsed = []
    i = 0
    for ln in data.decode("utf-8", "replace").split("\n"):
        if not ln.strip(" \t\r"):
            continue
        reparsed.append((i, parse_line(ln)))
        i += 1
    empty = ParsedGeoms(
        ts=np.empty(0, np.int64), obj_id=np.empty(0, np.int32),
        is_areal=np.empty(0, bool), bbox=np.empty((0, 4)),
        ring_off=np.empty(0, np.int64), ring_cnt=np.empty(0, np.int32),
        ring_voff=np.empty(0, np.int64), ring_size=np.empty(0, np.int32),
        vx=np.empty(0), vy=np.empty(0), interner=interner,
    )
    return _merge_geom_rejects(empty, reparsed, interner)


def _merge_geom_rejects(accepted: ParsedGeoms, reparsed, interner
                        ) -> ParsedGeoms:
    """Flatten Python-reparsed geometry objects into the SoA layout and
    stitch them back into original line order with the accepted records.

    Python loops touch only the REJECTED objects (their rings); the accepted
    block's flattened arrays are appended as-is and the line-order permute
    rides :meth:`ParsedGeoms.subset` (offset re-basing is exactly the
    subset gather)."""
    n_acc = len(accepted)
    # flatten reject objects -> a small SoA block (O(reject rings) Python)
    rej_rings: List[np.ndarray] = []
    rej_cnt = np.empty(len(reparsed), np.int32)
    rej_ts = np.empty(len(reparsed), np.int64)
    rej_oid = np.empty(len(reparsed), np.int32)
    rej_areal = np.empty(len(reparsed), bool)
    rej_bbox = np.empty((len(reparsed), 4), np.float64)
    for j, (_line, obj) in enumerate(reparsed):
        rl, is_areal = _object_rings(obj)
        rej_rings.extend(rl)
        rej_cnt[j] = len(rl)
        rej_ts[j] = obj.timestamp
        rej_oid[j] = interner.intern(obj.obj_id)
        rej_areal[j] = is_areal
        rej_bbox[j] = np.asarray(obj.bbox, np.float64)
    rej_size = np.array([r.shape[0] for r in rej_rings], np.int32)
    rej_coords = (np.concatenate(rej_rings, axis=0) if rej_rings
                  else np.empty((0, 2)))
    # combined = [accepted block | reject block], offsets shifted
    n_rings_acc = accepted.ring_size.shape[0]
    n_verts_acc = accepted.vx.shape[0]
    combined = ParsedGeoms(
        ts=np.concatenate([accepted.ts, rej_ts]),
        obj_id=np.concatenate([accepted.obj_id, rej_oid]),
        is_areal=np.concatenate([accepted.is_areal, rej_areal]),
        bbox=np.concatenate([accepted.bbox.reshape(n_acc, 4), rej_bbox]),
        ring_off=np.concatenate([
            accepted.ring_off,
            n_rings_acc + np.concatenate(
                [[0], np.cumsum(rej_cnt)])[:-1].astype(np.int64)]),
        ring_cnt=np.concatenate([accepted.ring_cnt, rej_cnt]),
        ring_voff=np.concatenate([
            accepted.ring_voff,
            n_verts_acc + np.concatenate(
                [[0], np.cumsum(rej_size)])[:-1].astype(np.int64)]),
        ring_size=np.concatenate([accepted.ring_size, rej_size]),
        vx=np.concatenate([accepted.vx, rej_coords[:, 0]]),
        vy=np.concatenate([accepted.vy, rej_coords[:, 1]]),
        interner=interner,
    )
    # permutation back to original line order: accepted rows occupy the
    # non-rejected line slots in order, rejects their recorded lines
    total = n_acc + len(reparsed)
    line_of = np.empty(total, np.int64)
    reject_lines = np.array([line for line, _ in reparsed], np.int64)
    is_rej = np.zeros(total, bool)
    is_rej[reject_lines] = True
    line_of[:n_acc] = np.nonzero(~is_rej)[0]
    line_of[n_acc:] = reject_lines
    perm = np.argsort(line_of, kind="stable")
    return combined.subset(perm)


def geoms_to_edge_batch(parsed: ParsedGeoms, grid=None, *,
                        ts_base: int = 0, pad: Optional[int] = None,
                        edge_pad: Optional[int] = None,
                        cell_pad: Optional[int] = None):
    """ParsedGeoms -> :class:`EdgeGeomBatch`, fully vectorized.

    Edge construction matches the object path (``Polygon.create`` +
    ``edge_array``): polygon rings are auto-closed (closure edge appended
    when the raw first and last vertices differ), linestrings are open
    chains; cells are the grid cells overlapped by the bbox with the
    centroid cell as representative (``_EdgeGeom._assign_cells`` rule).
    """
    from spatialflink_tpu.models.batches import EdgeGeomBatch
    from spatialflink_tpu.utils.padding import bucket_size, pad_to

    n = len(parsed)
    if n == 0:
        return EdgeGeomBatch.from_objects([], grid, parsed.interner,
                                          ts_base=ts_base, pad=pad)

    # --- per-ring edge construction --------------------------------------- #
    sizes = parsed.ring_size.astype(np.int64)
    voff = parsed.ring_voff
    R = sizes.shape[0]
    ring_geom = np.repeat(np.arange(n), parsed.ring_cnt)
    if R:
        closure = parsed.is_areal[ring_geom] & (
            (parsed.vx[voff] != parsed.vx[voff + sizes - 1])
            | (parsed.vy[voff] != parsed.vy[voff + sizes - 1]))
        e_r = sizes - 1 + closure
        eoff = np.concatenate([[0], np.cumsum(e_r)])
        total_e = int(eoff[-1])
        base_cnt = sizes - 1
        brep = np.repeat(np.arange(R), base_cnt)
        bcum = np.concatenate([[0], np.cumsum(base_cnt)])
        bpos = np.arange(int(bcum[-1])) - np.repeat(bcum[:-1], base_cnt)
        src = voff[brep] + bpos
        e_flat = np.empty((total_e, 4), np.float32)
        dest = eoff[brep] + bpos
        e_flat[dest, 0] = parsed.vx[src]
        e_flat[dest, 1] = parsed.vy[src]
        e_flat[dest, 2] = parsed.vx[src + 1]
        e_flat[dest, 3] = parsed.vy[src + 1]
        cr = np.nonzero(closure)[0]
        cdest = eoff[cr] + sizes[cr] - 1
        e_flat[cdest, 0] = parsed.vx[voff[cr] + sizes[cr] - 1]
        e_flat[cdest, 1] = parsed.vy[voff[cr] + sizes[cr] - 1]
        e_flat[cdest, 2] = parsed.vx[voff[cr]]
        e_flat[cdest, 3] = parsed.vy[voff[cr]]
        ge = np.bincount(ring_geom, weights=e_r, minlength=n).astype(np.int64)
    else:
        e_flat = np.empty((0, 4), np.float32)
        ge = np.zeros(n, np.int64)

    E = (bucket_size(max(int(ge.max()) if n else 1, 1), 8)
         if edge_pad is None else edge_pad)
    edges = np.zeros((n, E, 4), np.float32)
    emask = np.zeros((n, E), bool)
    if R:
        goff = np.concatenate([[0], np.cumsum(ge)])
        edge_geom = np.repeat(np.arange(n), ge)
        pos_in_geom = np.arange(int(goff[-1])) - np.repeat(goff[:-1], ge)
        edges[edge_geom, pos_in_geom] = e_flat
        emask[edge_geom, pos_in_geom] = True

    # --- cells from bbox --------------------------------------------------- #
    cell_rep = np.full(n, -1, np.int32)
    if grid is not None:
        ix1, iy1 = grid.cell_indices(parsed.bbox[:, 0], parsed.bbox[:, 1])
        ix2, iy2 = grid.cell_indices(parsed.bbox[:, 2], parsed.bbox[:, 3])
        ix1, iy1 = np.asarray(ix1, np.int64), np.asarray(iy1, np.int64)
        ix2, iy2 = np.asarray(ix2, np.int64), np.asarray(iy2, np.int64)
        inside = (ix2 >= 0) & (iy2 >= 0) & (ix1 < grid.n) & (iy1 < grid.n)
        ix1c = np.clip(ix1, 0, grid.n - 1)
        iy1c = np.clip(iy1, 0, grid.n - 1)
        ix2c = np.clip(ix2, 0, grid.n - 1)
        iy2c = np.clip(iy2, 0, grid.n - 1)
        nx = np.where(inside, ix2c - ix1c + 1, 0)
        ny = np.where(inside, iy2c - iy1c + 1, 0)
        counts = nx * ny
        C = (bucket_size(max(int(counts.max()), 1), 8)
             if cell_pad is None else cell_pad)
        cells = np.full((n, C), -1, np.int32)
        cmask = np.zeros((n, C), bool)
        total_c = int(counts.sum())
        if total_c:
            grep = np.repeat(np.arange(n), counts)
            gcum = np.concatenate([[0], np.cumsum(counts)])
            gpos = np.arange(total_c) - np.repeat(gcum[:-1], counts)
            ny_r = np.repeat(ny, counts)
            cxs = np.repeat(ix1c, counts) + gpos // np.maximum(ny_r, 1)
            cys = np.repeat(iy1c, counts) + gpos % np.maximum(ny_r, 1)
            cells[grep, gpos] = (cxs * grid.n + cys).astype(np.int32)
            cmask[grep, gpos] = True
        # representative: centroid cell when valid (always inside the bbox
        # range), else the minimum overlapped cell (= (ix1c, iy1c))
        cx = (parsed.bbox[:, 0] + parsed.bbox[:, 2]) / 2
        cy = (parsed.bbox[:, 1] + parsed.bbox[:, 3]) / 2
        c, valid = grid.assign_cell(cx, cy)
        rep = np.where(np.asarray(valid), np.asarray(c, np.int64),
                       ix1c * grid.n + iy1c)
        cell_rep = np.where(counts > 0, rep, -1).astype(np.int32)
    else:
        C = cell_pad or 8
        cells = np.full((n, C), -1, np.int32)
        cmask = np.zeros((n, C), bool)

    size = bucket_size(n, 8) if pad is None else pad
    ts32 = (parsed.ts - int(ts_base)).astype(np.int32)
    return EdgeGeomBatch(
        edges=pad_to(edges, size),
        edge_mask=pad_to(emask, size),
        bbox=pad_to(parsed.bbox.astype(np.float32), size),
        obj_id=pad_to(parsed.obj_id, size),
        ts=pad_to(ts32, size),
        cell=pad_to(cell_rep, size, fill=-1),
        cells=pad_to(cells, size, fill=-1),
        cells_mask=pad_to(cmask, size),
        is_areal=pad_to(parsed.is_areal, size),
        valid=pad_to(np.ones(n, bool), size),
    )


def bulk_geom_window_batches(parsed: ParsedGeoms, spec, grid=None, *,
                             pad: Optional[int] = None,
                             min_bucket: int = 8):
    """Vectorized window assembly for geometry streams:
    ParsedGeoms -> per-window (start, end, idx, EdgeGeomBatch) — the
    geometry twin of :func:`bulk_window_batches`. ``min_bucket`` raises the
    per-window capacity floor (mesh runs need the geometry dim divisible by
    the device count)."""
    from spatialflink_tpu.utils.padding import bucket_size

    if not len(parsed):
        return
    win, rec = spec.assign_bulk(parsed.ts)
    if not len(win):
        return
    bounds = np.flatnonzero(np.r_[True, win[1:] != win[:-1], True])
    for i in range(len(bounds) - 1):
        lo, hi = int(bounds[i]), int(bounds[i + 1])
        start = int(win[lo])
        idx = rec[lo:hi]
        wpad = pad if pad is not None else bucket_size(idx.size, min_bucket)
        batch = geoms_to_edge_batch(parsed.subset(idx), grid,
                                    ts_base=start, pad=wpad)
        yield start, start + spec.size_ms, idx, batch


def bulk_parse_geom_file(path: str, fmt: str = "WKT", **kw) -> ParsedGeoms:
    """Bulk-parse a whole replay file of WKT or GeoJSON polygon/linestring
    records (kwargs are format-specific: delimiter/date_format for WKT,
    property_obj_id/property_timestamp/date_format for GeoJSON)."""
    f = fmt.lower()
    if f not in ("wkt", "geojson"):
        raise ValueError(
            f"bulk geometry ingestion supports WKT/GeoJSON, not {fmt!r}")
    with open(path, "rb") as fh:
        data = fh.read()
    if f == "wkt":
        return bulk_parse_wkt(data, **kw)
    return bulk_parse_geojson_geoms(data, **kw)
