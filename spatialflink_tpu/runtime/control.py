"""Closed-loop decode-chunk governor: the actuator on the PR 11 sensors.

PR 11 built the sensor plane — exact per-window stage-residency budgets,
the backpressure timeline, per-query ``p99_emit_ms`` SLOs — and located
the CPU throughput/latency knee at decode-chunk 2048-4096, with 20-50%
p99 on the table either side. But every knob stayed statically tuned per
run. CheetahGIS (arxiv 2511.09262) makes backpressure a first-class
control input for streaming spatial query processing; this module closes
the loop the same way the PR 7 join-block coalescer extended the
calibrate-then-choose pattern of Adaptive Geospatial Joins
(arxiv 1802.09488) — except continuously, at runtime.

Design points:

- The governor ticks on the telemetry-reporter cadence: the latency
  plane's bucket close (:meth:`~spatialflink_tpu.utils.latencyplane
  .LatencyPlane.tick`) hands it the freshly closed backpressure bucket —
  per-stage time deltas, the stall annotation, decode-buffer depth — plus
  the live record→emit p99. No new threads, no new sampling path: the
  controller reads exactly what ``/latency`` serves.
- Decisions move the decode chunk ONE power-of-two bucket per step,
  bounded to ``[min_chunk, max_chunk]``, with HYSTERESIS twice over: a
  direction must persist for ``confirm_ticks`` consecutive buckets before
  a step applies, and every applied step starts a ``cooldown_ticks``
  quiet period — the split/merge discipline of
  :class:`~spatialflink_tpu.runtime.repartition.RepartitionController`,
  transplanted to a scalar knob.
- Shrink when the queue/buffer stages dominate the budget delta AND the
  record→emit p99 breaches the target (records are waiting, smaller
  flushes cut the wait); grow when the dispatch stage dominates or the
  pipe is idle with p99 comfortably under target (per-chunk overheads
  amortize better at the knee). A backpressure stall always votes shrink.
- ZERO RECOMPILES by construction: the decode chunk only sizes host-side
  buffers (the ``decode_chunks`` flush threshold and the Kafka tap's
  ``bulk_chunk``) — no kernel static anywhere keys on it, and window
  batch shapes already ride their own padding buckets. The PR 15
  recompile-surface rule keeps that true statically; the PR 10 runtime
  sentinel asserts 0 post-warmup recompiles across live resizes in the
  Pareto bench (``benchmarks/bench_control.py``).
- Per-query latency classes: ``QuerySpec.latency_class`` marks a query
  ``interactive`` or ``batch``. While any interactive query serves, the
  governor engages the FAST LANE — the effective chunk is capped at
  ``interactive_max_chunk`` and the drive loop bounds its in-flight queue
  depth to ``fast_lane_depth`` — so a hot batch fleet cannot ride the
  chunk (and the pipeline deque) up and park an interactive query's p99
  behind amortization built for throughput.
- Admission shedding: ``shed_after_stalls`` consecutive stalled buckets
  flip the :class:`~spatialflink_tpu.runtime.queryplane.QueryRegistry`
  into shedding — new admissions land in the ``shed`` lifecycle state
  (HTTP 429 on ``POST /queries``) instead of growing an unbounded staged
  backlog; ``unshed_after_clean`` clean buckets release them to PENDING.
- Every decision emits a ``chunk-governor`` ring event (like
  ``repartition`` does), bumps the ``chunk-grow`` / ``chunk-shrink`` /
  ``shed`` counters, and moves the ``decode.chunk`` gauge; ``status()``
  is the ``controller`` block on ``GET /latency``.
- Checkpoint component ``controller``: the current chunk, direction
  streaks and shed state ride the coordinated manifest, so ``--resume``
  continues a mid-adjustment trajectory instead of re-warming from the
  seed (pinned by ``tests/test_control.py``).

OFF by default: nothing constructs a governor unless the driver's
``--controller`` flag (or a test) installs one; with no active governor
every read site keeps its fixed chunk — byte-identical behavior.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Callable, List, Optional

#: the one governor the current process runs (driver-installed) — how the
#: latency plane's tick and the decode/drive loops find it without
#: plumbing (same pattern as repartition.active_controller)
_ACTIVE: Optional["ChunkGovernor"] = None

#: stages whose dominance means records WAIT (shrink pressure) vs the
#: stage that amortizes with bigger flushes (grow pressure)
_WAIT_STAGES = ("buffer", "queue")
_AMORTIZE_STAGE = "dispatch"

#: the measured CPU throughput/latency knee (PR 11 Pareto sweep): the
#: governor's default seed, and the corrected ``--kafka-follow`` default
KNEE_CHUNK = 2048


def active_governor() -> Optional["ChunkGovernor"]:
    """The process's installed :class:`ChunkGovernor`, or None."""
    return _ACTIVE


def chunk_bucket(n: int, lo: int = 1, hi: int = 1 << 20) -> int:
    """Snap ``n`` to the nearest power of two, clamped to ``[lo, hi]``
    (both powers of two). Kernel shapes never key on the decode chunk,
    but the power-of-two lattice keeps every DOWNSTREAM padding bucket
    (fleet Q-axis, window batch pads) stable across a resize — the
    belt-and-suspenders half of the zero-recompile argument."""
    n = max(1, int(n))
    b = 1 << (n.bit_length() - 1)
    if n - b > 2 * b - n:
        b <<= 1
    return max(int(lo), min(int(hi), b))


@dataclass
class GovernorPolicy:
    """The control law's thresholds. Hysteresis = a direction must hold
    for ``confirm_ticks`` buckets before a step AND every step starts a
    ``cooldown_ticks`` quiet period; shed/un-shed carry their own
    consecutive-bucket counters."""

    #: record→emit p99 target (ms); breach = shrink pressure
    target_p99_ms: float = 250.0
    #: chunk bounds, powers of two (the pareto sweep's sane range)
    min_chunk: int = 256
    max_chunk: int = 8192
    #: fast-lane cap while any interactive query serves
    interactive_max_chunk: int = 1024
    #: fast-lane bound on the drive loop's in-flight deque depth
    fast_lane_depth: int = 1
    #: consecutive same-direction buckets before a step applies
    confirm_ticks: int = 2
    #: quiet buckets after an applied step
    cooldown_ticks: int = 1
    #: consecutive stalled buckets before admissions shed
    shed_after_stalls: int = 2
    #: consecutive clean buckets before shed admissions release
    unshed_after_clean: int = 2
    #: grow when idle headroom (p99 under this fraction of target)
    idle_headroom: float = 0.5

    @classmethod
    def from_spec(cls, spec: str) -> "GovernorPolicy":
        """Parse ``--controller target_p99_ms=150,min_chunk=512,...``
        (empty spec = defaults), mirroring ``HealthEvaluator.from_spec``."""
        float_keys = ("target_p99_ms", "idle_headroom")
        kwargs = {}
        for part in spec.split(","):
            part = part.strip()
            if not part:
                continue
            key, sep, val = part.partition("=")
            key = key.strip()
            if not sep:
                raise ValueError(
                    f"--controller entry {part!r} is not key=value")
            if key not in cls.__dataclass_fields__:
                raise ValueError(
                    f"unknown --controller key {key!r}; known: "
                    + ", ".join(sorted(cls.__dataclass_fields__)))
            try:
                kwargs[key] = (float(val) if key in float_keys
                               else int(val))
            except ValueError:
                raise ValueError(
                    f"--controller {key}={val!r} is not numeric")
        return cls(**kwargs).validate()

    def validate(self) -> "GovernorPolicy":
        for name in ("min_chunk", "max_chunk", "interactive_max_chunk"):
            v = getattr(self, name)
            if v < 1 or v & (v - 1):
                raise ValueError(f"{name} must be a power of two, got {v}")
        if not self.min_chunk <= self.max_chunk:
            raise ValueError(
                f"need min_chunk ({self.min_chunk}) <= max_chunk "
                f"({self.max_chunk})")
        if self.target_p99_ms <= 0:
            raise ValueError("target_p99_ms must be positive")
        if min(self.confirm_ticks, self.cooldown_ticks + 1,
               self.shed_after_stalls, self.unshed_after_clean,
               self.fast_lane_depth) < 1:
            raise ValueError("tick/depth counts must be >= 1 "
                             "(cooldown_ticks >= 0)")
        if not 0 < self.idle_headroom <= 1:
            raise ValueError("idle_headroom must be in (0, 1]")
        return self


class ChunkGovernor:
    """Turns latency-plane buckets into decode-chunk steps and admission
    shed/un-shed transitions. Thread-safe enough for its consumers: the
    tick path runs on whichever thread closes the bucket (reporter or a
    scrape); :meth:`chunk` / :meth:`drain_depth` are hot-path reads of a
    single int/bool (mutated only under the lock); ``status()`` reads
    under the same lock the tick mutates under."""

    def __init__(self, seed_chunk: int = KNEE_CHUNK,
                 policy: Optional[GovernorPolicy] = None):
        self.policy = (policy or GovernorPolicy()).validate()
        p = self.policy
        self._lock = threading.Lock()
        self._chunk = chunk_bucket(seed_chunk, p.min_chunk, p.max_chunk)
        self.seed_chunk = self._chunk
        #: pending direction (+1 grow / -1 shrink / 0) and its streak
        self._dir = 0
        self._streak = 0
        self._cooldown = 0
        #: shed bookkeeping
        self._stall_ticks = 0
        self._clean_ticks = 0
        self.shedding = False
        #: fast lane engaged (any interactive query serving)
        self._fast_lane = False
        self.ticks = 0
        self.grows = 0
        self.shrinks = 0
        self.sheds = 0
        #: recent decisions, newest last (the /latency controller tail)
        self.decisions: List[dict] = []

    # ------------------------------ actuators ------------------------- #

    def chunk(self) -> int:
        """The decode chunk RIGHT NOW — what every per-flush callback
        returns. The fast-lane cap applies here, so engaging it never
        waits out a hysteresis streak."""
        c = self._chunk
        if self._fast_lane:
            c = min(c, self.policy.interactive_max_chunk)
        return c

    def chunk_callback(self) -> Callable[[], int]:
        """The per-flush size callback handed to ``decode_chunks`` /
        ``WindowCommitTap`` — resolved once per buffered flush, so a
        resize lands between chunks, never inside one."""
        return self.chunk

    @property
    def fast_lane(self) -> bool:
        return self._fast_lane

    def drain_depth(self, depth: int) -> int:
        """The drive loop's effective in-flight deque bound: the run's
        ``pipeline_depth`` normally, ``fast_lane_depth`` while the fast
        lane is engaged (an interactive query's window must not sit
        behind a deep amortization deque)."""
        if self._fast_lane:
            return max(1, min(int(depth), self.policy.fast_lane_depth))
        return max(1, int(depth))

    # ------------------------------ the loop -------------------------- #

    def on_tick(self, bucket: dict, p99_ms: Optional[float] = None) -> None:
        """One closed backpressure bucket (see ``LatencyPlane.tick``) +
        the live record→emit p99. Evaluates the control law under
        hysteresis and applies at most one chunk step and at most one
        shed transition."""
        p = self.policy
        stall = bool(bucket.get("stall"))
        deltas = bucket.get("stage_delta_s") or {}
        dominant = None
        if deltas:
            dominant = max(deltas, key=lambda s: deltas[s])
            if deltas[dominant] <= 0.0:
                dominant = None
        breach = p99_ms is not None and p99_ms > p.target_p99_ms
        idle = (dominant is None
                or (p99_ms is not None
                    and p99_ms <= p.idle_headroom * p.target_p99_ms))
        if stall or (breach and dominant in _WAIT_STAGES):
            direction = -1
        elif not breach and (dominant == _AMORTIZE_STAGE or idle):
            direction = +1
        else:
            direction = 0
        with self._lock:
            self.ticks += 1
            self._refresh_fast_lane_locked()
            stepped = self._vote_locked(direction)
            shed_flip = self._shed_locked(stall)
            chunk = self.chunk()
        if stepped:
            self._note_step(stepped, chunk, dominant, p99_ms, stall)
        if shed_flip is not None:
            self._note_shed(shed_flip, stall, p99_ms)
        self._export(chunk)

    def _vote_locked(self, direction: int) -> int:
        """Hysteresis + one bounded step; returns the applied direction
        (0 = no step). Caller holds the lock."""
        p = self.policy
        if self._cooldown > 0:
            self._cooldown -= 1
            return 0
        if direction == 0:
            self._dir = 0
            self._streak = 0
            return 0
        if direction == self._dir:
            self._streak += 1
        else:
            self._dir = direction
            self._streak = 1
        if self._streak < p.confirm_ticks:
            return 0
        nxt = self._chunk << 1 if direction > 0 else self._chunk >> 1
        nxt = max(p.min_chunk, min(p.max_chunk, nxt))
        self._streak = 0
        if nxt == self._chunk:
            return 0
        self._chunk = nxt
        self._cooldown = p.cooldown_ticks
        if direction > 0:
            self.grows += 1
        else:
            self.shrinks += 1
        return direction

    def _shed_locked(self, stall: bool) -> Optional[bool]:
        """Shed state machine; returns the new shed state on a flip,
        None otherwise. Caller holds the lock."""
        p = self.policy
        if stall:
            self._stall_ticks += 1
            self._clean_ticks = 0
            if not self.shedding and self._stall_ticks >= p.shed_after_stalls:
                self.shedding = True
                self.sheds += 1
                return True
        else:
            self._clean_ticks += 1
            self._stall_ticks = 0
            if self.shedding and self._clean_ticks >= p.unshed_after_clean:
                self.shedding = False
                return False
        return None

    def _refresh_fast_lane_locked(self) -> None:
        """Fast lane = any serving query declared ``interactive``. Read
        off the installed registry each tick (the registry is the source
        of truth for the fleet — no second subscription path)."""
        try:
            from spatialflink_tpu.runtime.queryplane import active_registry

            reg = active_registry()
            self._fast_lane = bool(
                reg is not None and reg.has_interactive())
        except Exception:
            pass

    # ------------------------------ reporting ------------------------- #

    def _note_step(self, direction: int, chunk: int, dominant, p99_ms,
                   stall: bool) -> None:
        from spatialflink_tpu.utils import telemetry as _telemetry
        from spatialflink_tpu.utils.metrics import REGISTRY

        kind = "chunk-grow" if direction > 0 else "chunk-shrink"
        REGISTRY.counter(kind).inc()
        decision = {
            "ts_ms": int(time.time() * 1000),
            "tick": self.ticks,
            "action": kind,
            "chunk": chunk,
            "dominant_stage": dominant,
            "p99_emit_ms": None if p99_ms is None else round(p99_ms, 3),
            "stall": stall,
            "fast_lane": self._fast_lane,
        }
        with self._lock:
            self.decisions.append(decision)
            del self.decisions[:-32]
        _telemetry.emit_event(
            "chunk-governor", action=kind, chunk=chunk,
            dominant_stage=dominant, stall=stall,
            p99_emit_ms=decision["p99_emit_ms"])

    def _note_shed(self, shedding: bool, stall: bool, p99_ms) -> None:
        from spatialflink_tpu.utils import telemetry as _telemetry
        from spatialflink_tpu.utils.metrics import REGISTRY

        kind = "shed" if shedding else "unshed"
        if shedding:
            REGISTRY.counter("shed").inc()
        decision = {
            "ts_ms": int(time.time() * 1000),
            "tick": self.ticks,
            "action": kind,
            "chunk": self.chunk(),
            "stall": stall,
            "p99_emit_ms": None if p99_ms is None else round(p99_ms, 3),
            "fast_lane": self._fast_lane,
        }
        with self._lock:
            self.decisions.append(decision)
            del self.decisions[:-32]
        _telemetry.emit_event("chunk-governor", action=kind,
                              stall=stall, chunk=decision["chunk"])
        if shedding:
            # stall escalation beyond admission shedding: a sustained
            # stall is a LAYOUT problem as much as a load problem, so ask
            # the partition layer for an early repartition epoch — in-
            # process via the installed adaptive-grid controller, and
            # fleet-wide via a harvestable event the supervisor folds
            # into its next routing boundary (rebalance/rescale epoch)
            _telemetry.emit_event("rebalance-request",
                                  trigger="governor-stall",
                                  chunk=decision["chunk"],
                                  p99_emit_ms=decision["p99_emit_ms"])
            try:
                from spatialflink_tpu.runtime.repartition import (
                    active_controller)

                ctl = active_controller()
                if ctl is not None:
                    ctl.request_epoch()
            except Exception:
                pass
        try:
            from spatialflink_tpu.runtime.queryplane import active_registry

            reg = active_registry()
            if reg is not None:
                reg.set_shedding(shedding)
        except Exception:
            pass

    def _export(self, chunk: int) -> None:
        # gauges, not just live object state: the /status digest (and the
        # fleet federation that merges digests cross-process) derives its
        # controller stanza purely from the snapshot dict
        from spatialflink_tpu.utils import telemetry as _telemetry

        tel = _telemetry.active()
        if tel is not None:
            tel.gauge("decode.chunk").set(float(chunk))
            tel.gauge("decode.fast-lane").set(1.0 if self._fast_lane
                                              else 0.0)
            tel.gauge("controller.shedding").set(1.0 if self.shedding
                                                 else 0.0)

    def status(self) -> dict:
        """The ``controller`` block on ``GET /latency`` (and the bundle):
        the live actuator value, the policy (so the trigger is observable
        BEFORE it fires, next to the budget it reads), streak/cooldown
        progress, shed state, and recent decisions."""
        p = self.policy
        with self._lock:
            decisions = list(self.decisions)
            return {
                "chunk": self.chunk(),
                "base_chunk": self._chunk,
                "seed_chunk": self.seed_chunk,
                "fast_lane": self._fast_lane,
                "shedding": self.shedding,
                "ticks": self.ticks,
                "grows": self.grows,
                "shrinks": self.shrinks,
                "sheds": self.sheds,
                "streak": {"dir": self._dir, "ticks": self._streak,
                           "cooldown": self._cooldown,
                           "stall_ticks": self._stall_ticks,
                           "clean_ticks": self._clean_ticks},
                "policy": {
                    "target_p99_ms": p.target_p99_ms,
                    "min_chunk": p.min_chunk,
                    "max_chunk": p.max_chunk,
                    "interactive_max_chunk": p.interactive_max_chunk,
                    "fast_lane_depth": p.fast_lane_depth,
                    "confirm_ticks": p.confirm_ticks,
                    "cooldown_ticks": p.cooldown_ticks,
                    "shed_after_stalls": p.shed_after_stalls,
                    "unshed_after_clean": p.unshed_after_clean,
                },
                "decisions": decisions,
            }

    # ------------------------------ checkpoint ------------------------ #

    def register_checkpoint(self, coordinator) -> None:
        """Carry the control state in the coordinated-checkpoint manifest
        (component ``controller``) so ``--resume`` continues the
        trajectory — chunk, streaks, shed state — instead of re-warming
        from the seed. Registration auto-restores pending loaded state."""

        def snapshot():
            with self._lock:
                return {}, {
                    "chunk": self._chunk,
                    "dir": self._dir,
                    "streak": self._streak,
                    "cooldown": self._cooldown,
                    "stall_ticks": self._stall_ticks,
                    "clean_ticks": self._clean_ticks,
                    "shedding": self.shedding,
                    "ticks": self.ticks,
                }

        def restore(_arrays, meta) -> None:
            p = self.policy
            with self._lock:
                self._chunk = chunk_bucket(
                    meta.get("chunk", self._chunk), p.min_chunk, p.max_chunk)
                self._dir = int(meta.get("dir", 0))
                self._streak = int(meta.get("streak", 0))
                self._cooldown = int(meta.get("cooldown", 0))
                self._stall_ticks = int(meta.get("stall_ticks", 0))
                self._clean_ticks = int(meta.get("clean_ticks", 0))
                self.shedding = bool(meta.get("shedding", False))
                self.ticks = max(self.ticks, int(meta.get("ticks", 0)))

        coordinator.register("controller", snapshot, restore)

    # ------------------------------ lifecycle ------------------------- #

    def install(self) -> "ChunkGovernor":
        global _ACTIVE
        _ACTIVE = self
        self._export(self.chunk())
        return self

    def uninstall(self) -> None:
        global _ACTIVE
        if _ACTIVE is self:
            _ACTIVE = None
