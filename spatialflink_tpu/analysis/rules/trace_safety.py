"""Rule 2 — trace-safety: jitted kernels cannot hide retrace triggers.

Inside an ``instrumented_jit``-decorated function, Python-level control
flow on a *traced* argument either fails at trace time on some path the
tests never execute, or — the worse case — silently succeeds per
concrete value and triggers the post-warmup recompiles the runtime
sentinel aborts on. The rule flags, per jitted kernel:

- ``if``/``while``/ternary tests and ``for`` iteration over traced
  arguments (error);
- ``int(...)`` / ``bool(...)`` / ``float(...)`` coercion of traced
  values (error — a concretization point);
- tests that branch on ``.shape``/``.ndim``/``.size``/``.dtype`` of a
  traced argument (warning — legal under jit but every distinct shape is
  a fresh compile, which the padding discipline exists to avoid);
- ``static_argnums``/``static_argnames`` parameters with list/dict/set
  defaults or annotations (error — unhashable statics raise at call
  time, but only on the first uncached call signature).

Static parameters (named by the decoration) are exempt everywhere:
branching on ``n``/``k`` statics is the repo's core padding idiom.
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional, Set

from spatialflink_tpu.analysis.core import (Finding, ModuleSource, Rule,
                                            register)
from spatialflink_tpu.analysis.rules.common import (function_params,
                                                    jit_static_names)

_SHAPE_ATTRS = {"shape", "ndim", "size", "dtype"}
_COERCIONS = {"int", "bool", "float"}
_UNHASHABLE_ANNOS = {"list", "List", "dict", "Dict", "set", "Set"}


def _shadowed(mod: ModuleSource, node: ast.AST, name: str,
              stop: ast.FunctionDef) -> bool:
    """Is ``name`` rebound by a nested def/lambda between ``node`` and
    the jitted function ``stop``?"""
    for fn in mod.enclosing_functions(node):
        if fn is stop:
            return False
        if name in function_params(fn):
            return True
    return False


class _TracedUse:
    """Classification of traced-argument references inside one test or
    call-argument expression."""

    def __init__(self, mod: ModuleSource, root: ast.FunctionDef,
                 traced: Set[str]):
        self.mod = mod
        self.root = root
        self.traced = traced

    def classify(self, expr: ast.AST) -> Optional[str]:
        """"value" when the expression reads a traced argument's value,
        "shape" when every traced reference sits under a shape-like
        attribute, None when no traced argument is involved."""
        hits = []
        for node in ast.walk(expr):
            if isinstance(node, ast.Name) and node.id in self.traced \
                    and not _shadowed(self.mod, node, node.id, self.root):
                parent = self.mod.parent(node)
                if isinstance(parent, ast.Attribute) \
                        and parent.attr in _SHAPE_ATTRS:
                    hits.append("shape")
                else:
                    hits.append("value")
        if not hits:
            return None
        return "value" if "value" in hits else "shape"


@register
class TraceSafetyRule(Rule):
    id = "trace-safety"
    contract = ("no Python control flow / concretization on traced "
                "arguments inside instrumented_jit kernels; statics stay "
                "hashable")
    runtime_twin = ("recompile sentinel + --strict-recompile abort "
                    "(utils/deviceplane.py)")
    severity = "error"
    scope = ("spatialflink_tpu/**",)

    def check(self, mod: ModuleSource,
              project=None) -> Iterator[Finding]:
        for fn in ast.walk(mod.tree):
            if not isinstance(fn, ast.FunctionDef):
                continue
            statics = jit_static_names(fn)
            if statics is None:
                continue
            yield from self._check_statics(mod, fn, statics)
            traced = set(function_params(fn)) - statics
            uses = _TracedUse(mod, fn, traced)
            for node in ast.walk(fn):
                yield from self._check_node(mod, node, uses)

    def _check_statics(self, mod, fn, statics) -> Iterator[Finding]:
        args = fn.args
        pos = args.posonlyargs + args.args
        defaults = dict(zip([a.arg for a in pos[len(pos)
                                               - len(args.defaults):]],
                            args.defaults))
        defaults.update({a.arg: d for a, d in zip(args.kwonlyargs,
                                                  args.kw_defaults)
                         if d is not None})
        annos = {a.arg: a.annotation for a in pos + args.kwonlyargs
                 if a.annotation is not None}
        for name in sorted(statics):
            d = defaults.get(name)
            if isinstance(d, (ast.List, ast.Dict, ast.Set, ast.ListComp,
                              ast.DictComp, ast.SetComp)):
                yield self.finding(
                    mod, d,
                    f"static argument {name!r} defaults to an unhashable "
                    "container — jit statics must hash; use a tuple")
            anno = annos.get(name)
            if anno is not None:
                base = anno.value if isinstance(anno, ast.Subscript) \
                    else anno
                aname = base.id if isinstance(base, ast.Name) else \
                    base.attr if isinstance(base, ast.Attribute) else None
                if aname in _UNHASHABLE_ANNOS:
                    yield self.finding(
                        mod, anno,
                        f"static argument {name!r} is annotated as an "
                        "unhashable container — jit statics must hash; "
                        "use a tuple")

    def _check_node(self, mod, node, uses: _TracedUse) -> Iterator[Finding]:
        if isinstance(node, (ast.If, ast.While, ast.IfExp)):
            kind = uses.classify(node.test)
            if kind == "value":
                yield self.finding(
                    mod, node,
                    "Python control flow on a traced argument — this "
                    "either fails at trace time or concretizes and "
                    "retraces per value; use lax.cond/jnp.where or mark "
                    "the argument static")
            elif kind == "shape":
                yield self.finding(
                    mod, node,
                    "branch on a traced argument's shape/dtype — legal, "
                    "but every distinct shape is a fresh XLA compile the "
                    "sentinel will flag post-warmup; pad to bucketed "
                    "shapes or hoist the branch behind a static",
                    severity="warning")
        elif isinstance(node, ast.For):
            if uses.classify(node.iter) == "value":
                yield self.finding(
                    mod, node,
                    "Python iteration over a traced argument unrolls (or "
                    "fails) at trace time — use lax.scan/fori_loop or a "
                    "static length")
        elif isinstance(node, ast.Call) and isinstance(node.func, ast.Name) \
                and node.func.id in _COERCIONS and node.args:
            if uses.classify(node.args[0]) == "value":
                yield self.finding(
                    mod, node,
                    f"{node.func.id}() concretizes a traced value inside "
                    "a jitted kernel — a silent retrace trigger (shape "
                    "reads are fine; values are not)")
