"""Cost-attribution plane tests: window trace lineage (ring, stable ids,
Chrome/Perfetto export), per-cell / per-family cost profiles under
clustered (Zipfian) streams — asserting the hot cell dominates COST, not
just count (groundwork for ROADMAP item 2) — the new /trace/<id>,
/trace/recent, /profile/cells endpoints and the /events?since= cursor, and
the driver acceptance run: a live --kafka-follow --chaos --panes run whose
exported trace.json carries ingest/pane-seal/kernel/merge/emit slices for
emitted windows while /trace and /profile answer schema-valid payloads
mid-run."""

import json
import os
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest
import yaml

from spatialflink_tpu.index import UniformGrid
from spatialflink_tpu.models import Point
from spatialflink_tpu.operators import (PointPointRangeQuery,
                                        QueryConfiguration, QueryType)
from spatialflink_tpu.runtime.opserver import OpServer, active_server
from spatialflink_tpu.streams.formats import serialize_spatial
from spatialflink_tpu.utils.metrics import scoped_registry
from spatialflink_tpu.utils.telemetry import (CellOccupancy, CostProfiles,
                                              WindowTraceBook,
                                              status_snapshot,
                                              telemetry_session)

pytestmark = pytest.mark.costattr

GRID = UniformGrid(115.5, 117.6, 39.6, 41.1, num_grid_partitions=100)

TRACE_KEYS = {"trace_id", "query", "window_start", "window_end",
              "first_record_ms", "emitted_ms", "events"}


def _get(url, timeout=5):
    try:
        resp = urllib.request.urlopen(url, timeout=timeout)
        code, body = resp.status, resp.read()
        ctype = resp.headers.get("Content-Type", "")
    except urllib.error.HTTPError as e:
        code, body = e.code, e.read()
        ctype = e.headers.get("Content-Type", "")
    if "json" in ctype:
        return code, json.loads(body)
    return code, body.decode()


class TestWindowTraceBook:
    def test_lineage_roundtrip_and_stable_id(self):
        book = WindowTraceBook()
        assert book.trace_id("range", 5000) == "range:5000"
        t = time.time()
        book.first_record("range", 5000, 1_700_000_000_000)
        book.note("range", 5000, "window", t, t + 0.001)
        book.note("range", 5000, "pane-seal", t, t + 0.002, pane=4000)
        book.note("range", 5000, "kernel", t, t + 0.003)
        book.note("range", 5000, "merge", t, t + 0.001)
        book.seal("range", 5000, 10_000)
        book.note_any(5000, "sink-commit", t, t + 0.0005)
        tr = book.get("range:5000")
        assert TRACE_KEYS <= set(tr)
        assert tr["window_end"] == 10_000
        assert tr["first_record_ms"] == 1_700_000_000_000
        stages = [e["stage"] for e in tr["events"]]
        # ingest is inserted FIRST (it precedes everything it explains)
        assert stages == ["ingest", "window", "pane-seal", "kernel",
                          "merge", "emit", "sink-commit"]
        assert tr["events"][3]["dur_ms"] == pytest.approx(3.0, abs=0.5)
        assert tr["events"][2]["pane"] == 4000
        json.dumps(tr)  # JSON-safe as served
        # recent() newest-first summary
        rec = book.recent()
        assert rec[0]["trace_id"] == "range:5000"
        assert rec[0]["events"] == 7

    def test_ring_bounds_and_total(self):
        book = WindowTraceBook(capacity=4)
        for i in range(10):
            book.note("q", i, "kernel", time.time())
        assert book.total == 10
        assert len(book.recent(99)) == 4
        assert book.get("q:0") is None  # evicted
        assert book.get("q:9") is not None

    def test_note_any_matches_every_family(self):
        book = WindowTraceBook()
        t = time.time()
        book.note("range", 1000, "kernel", t)
        book.note("knn", 1000, "kernel", t)
        book.note("range", 2000, "kernel", t)
        book.note_any(1000, "sink", t, t + 0.001)
        assert [e["stage"] for e in book.get("range:1000")["events"]] == \
            ["kernel", "sink"]
        assert [e["stage"] for e in book.get("knn:1000")["events"]] == \
            ["kernel", "sink"]
        assert [e["stage"] for e in book.get("range:2000")["events"]] == \
            ["kernel"]

    def test_chrome_trace_perfetto_shape(self, tmp_path):
        book = WindowTraceBook()
        t = time.time()
        book.first_record("range", 0, int(t * 1000))
        book.note("range", 0, "kernel", t, t + 0.005)
        book.seal("range", 0, 5000)
        book.note("knn", 0, "kernel", t, t + 0.002)
        doc = book.chrome_trace()
        assert set(doc) == {"traceEvents", "displayTimeUnit"}
        evs = doc["traceEvents"]
        slices = [e for e in evs if e["ph"] == "X"]
        instants = [e for e in evs if e["ph"] == "i"]
        metas = [e for e in evs if e["ph"] == "M"]
        # every slice carries the Chrome trace-event required fields in
        # microseconds, pinned to a per-family track
        for e in slices:
            assert {"name", "ts", "dur", "pid", "tid", "args"} <= set(e)
            assert e["dur"] >= 1.0
            assert e["args"]["trace_id"]
        assert {e["name"] for e in instants} == {"ingest", "emit"}
        assert {m["args"]["name"] for m in metas} == {"range", "knn"}
        # distinct families get distinct tracks
        assert len({e["tid"] for e in slices}) == 2
        path = book.export_chrome(str(tmp_path / "trace.json"))
        assert json.load(open(path))["traceEvents"]


# the Zipf/clustered generator is SHARED with the adaptive-grid suites and
# benchmarks/bench_skew.py — one definition in streams.synthetic
from spatialflink_tpu.streams.synthetic import ZIPF_HOT, zipf_cells

_zipf_cells = zipf_cells


class TestZipfOccupancy:
    def test_topk_and_skew_on_clustered_stream(self):
        occ = CellOccupancy()
        cells = _zipf_cells()
        # half vectorized, half scalar (the per-record ingest path)
        occ.record(cells[: len(cells) // 2])
        for c in cells[len(cells) // 2:]:
            occ.record(int(c))
        top = occ.top_k(8)
        assert top[0][0] == ZIPF_HOT
        assert top[0][1] >= 0.55 * len(cells)
        # hottest cell dwarfs the runner-up and the skew factor says so
        assert top[0][1] > 3 * top[1][1]
        assert occ.skew() > 5.0
        # the skew-CONCENTRATION gauges (the --adaptive-grid trigger's
        # observable form): the hot cell holds ~60% of the records, and the
        # distribution is far from uniform on the Gini scale
        assert occ.top_share() == pytest.approx(
            top[0][1] / len(cells), abs=1e-9)
        assert occ.top_share() > 0.55
        assert occ.gini() > 0.5
        d = occ.to_dict()
        assert {"top_share", "gini"} <= set(d)
        # a perfectly uniform stream reads as unconcentrated
        flat = CellOccupancy()
        flat.record(np.arange(100, dtype=np.int64))
        assert flat.gini() == pytest.approx(0.0, abs=1e-9)
        assert flat.top_share() == pytest.approx(0.01, abs=1e-9)


class TestCostProfiles:
    def test_proportional_kernel_attribution(self):
        cp = CostProfiles()
        cp.record_cells(np.array([3, 3, 3, 9]))
        cp.attribute_kernel("range", 0.008, records=4, nbytes=64)
        top = cp.top_cost_cells(4)
        assert top[0] == [3, 6.0, 3]  # 3/4 of 8ms
        assert top[1] == [9, 2.0, 1]
        # pending drained: an all-cached window attributes nothing new
        cp.attribute_kernel("range", 0.050, records=0)
        assert cp.top_cost_cells(4)[0][1] == 6.0
        fam = cp.to_dict()["families"]["range"]
        assert fam["windows"] == 2 and fam["records_in"] == 4
        assert fam["kernel_ms"] == pytest.approx(58.0)
        assert fam["bytes_moved"] == 64

    def test_scalar_fast_path_counts_like_vectorized(self):
        a, b = CostProfiles(), CostProfiles()
        cells = _zipf_cells(n=500)
        a.record_cells(cells)
        for c in cells:
            b.record_cells(int(c))
        b.record_cells(-1)  # invalid cells drop
        a.attribute_kernel("q", 0.001)
        b.attribute_kernel("q", 0.001)
        assert a.top_cost_cells(16) == b.top_cost_cells(16)

    def test_hot_cell_dominates_cost_not_just_count(self):
        """The skew-COST signal: windows dominated by the hot cell run a
        LONGER kernel (more candidates in the cell), so the hot cell's
        attributed cost share must exceed even its (already dominant)
        record share — cost is the signal occupancy alone can't give."""
        cp = CostProfiles()
        rng = np.random.default_rng(3)
        hot_records = cold_records = 0
        for w in range(40):
            hot_window = w % 2 == 0
            if hot_window:  # 90% hot-cell records, slow kernel
                cells = np.where(rng.uniform(size=100) < 0.9, ZIPF_HOT,
                                 50 + rng.integers(0, 30, 100))
                hot_records += int((cells == ZIPF_HOT).sum())
                cold_records += int((cells != ZIPF_HOT).sum())
                cp.record_cells(cells)
                cp.attribute_kernel("range", 0.020, records=100)
            else:  # uniform cold window, fast kernel
                cells = 50 + rng.integers(0, 30, 100)
                cold_records += 100
                cp.record_cells(cells)
                cp.attribute_kernel("range", 0.002, records=100)
        top = cp.top_cost_cells(64)
        assert top[0][0] == ZIPF_HOT
        total_cost = sum(c for _, c, _ in top)
        cost_share = top[0][1] / total_cost
        record_share = hot_records / (hot_records + cold_records)
        assert cost_share > 0.5, "hot cell must dominate attributed cost"
        assert cost_share > record_share + 0.2, \
            "cost share must exceed record share (skew COST, not count)"

    def test_tick_series_buckets_deltas(self):
        cp = CostProfiles()
        cp.record_cells(np.array([1, 1]))
        cp.attribute_kernel("q", 0.004)
        b1 = cp.tick()
        assert b1["kernel_ms"] == pytest.approx(4.0)
        assert b1["top_cells"][0][0] == 1
        b2 = cp.tick()  # nothing new since the last bucket
        assert b2["kernel_ms"] == 0.0 and b2["top_cells"] == []
        assert list(cp.series) == [b1, b2]

    def test_scrape_driven_series_in_reporterless_session(self):
        """The /profile/cells read path itself buckets the series (at the
        tick interval), so a --trace-dir/--status-port run WITHOUT the
        JSONL reporter still serves a time series, while back-to-back
        scrapes inside one interval don't double-bucket."""
        cp = CostProfiles(tick_interval_s=3600.0)
        cp.record_cells(np.array([2, 2]))
        cp.attribute_kernel("q", 0.002)
        assert cp.cells_payload()["series"] == []  # interval not elapsed
        cp.tick_interval_s = 0.0
        assert len(cp.cells_payload()["series"]) == 1
        cp.tick_interval_s = 3600.0
        assert len(cp.cells_payload()["series"]) == 1  # no double-bucket

    def test_end_to_end_clustered_pipeline_profiles(self):
        """Full operator drive over a clustered point stream with a
        session: the hot cell tops the cost profile AND the status digest
        surfaces it (top_cost_cells), with the family profile fed from
        the real kernel spans."""
        from spatialflink_tpu.streams.synthetic import clustered_points

        # shared generator (streams.synthetic): 70% of records in a tight
        # cluster spanning a third of one cell, anchored mid-cell so the
        # whole cluster shares ONE grid cell
        hot_x, hot_y = 116.4975, 40.5135
        stream = clustered_points(GRID, 600, 0.7, seed=11,
                                  hot_center=(hot_x, hot_y),
                                  cluster_span_cells=0.33)
        conf = QueryConfiguration(QueryType.WindowBased,
                                  window_size_ms=10_000, slide_ms=5_000)
        q = Point.create(hot_x, hot_y, GRID)
        with scoped_registry(), telemetry_session() as tel:
            n = sum(1 for _ in PointPointRangeQuery(conf, GRID).run(
                iter(stream), q, 0.5))
            assert n >= 2
            payload = tel.costs.cells_payload()
            snap = status_snapshot(tel)
        hot_cell = int(GRID.assign_cell(hot_x, hot_y)[0])
        assert payload["cells"], "pipeline produced no cost profile"
        assert payload["cells"][0]["cell"] == hot_cell
        # dominance, not an exact share: per-dispatch wall-clock weights
        # the attribution, and kernel timings shift with jit cache warmth
        # (cold first-window compiles overweight early arrivals)
        assert payload["cells"][0]["cost_share"] > 0.25
        assert payload["cells"][0]["cost_ms"] > \
            2 * payload["cells"][1]["cost_ms"]
        fam = payload["families"]["range"]
        assert fam["windows"] == n and fam["kernel_ms"] > 0
        assert fam["records_in"] > 600  # windows overlap: records recount
        assert snap["status"]["top_cost_cells"][0][0] == hot_cell


class TestEndpoints:
    def test_trace_profile_and_since_cursor(self):
        with scoped_registry(), telemetry_session(trace=True) as tel:
            t = time.time()
            tel.traces.note("range", 1000, "kernel", t, t + 0.004)
            tel.traces.seal("range", 1000, 2000)
            tel.costs.record_cells(np.array([5, 5, 8]))
            tel.costs.attribute_kernel("range", 0.004, records=3)
            for i in range(5):
                tel.event("e", i=i)
            srv = OpServer(port=0).start()
            try:
                code, recent = _get(srv.url + "/trace/recent")
                assert code == 200 and recent["total"] == 1
                tid = recent["traces"][0]["trace_id"]
                assert tid == "range:1000"
                code, tr = _get(srv.url + "/trace/" + tid)
                assert code == 200 and TRACE_KEYS <= set(tr)
                assert [e["stage"] for e in tr["events"]] == ["kernel",
                                                              "emit"]
                code, missing = _get(srv.url + "/trace/range:999")
                assert code == 404 and "unknown" in missing["error"]
                code, prof = _get(srv.url + "/profile/cells")
                assert code == 200
                assert prof["cells"][0]["cell"] == 5
                assert {"cell", "records", "cost_ms",
                        "cost_share"} <= set(prof["cells"][0])
                assert prof["families"]["range"]["kernel_ms"] > 0
                assert "series" in prof
                # the ?since cursor: resume from latest_seq, see only new
                code, evs = _get(srv.url + "/events")
                assert code == 200 and len(evs["events"]) == 5
                cursor = evs["latest_seq"]
                assert cursor == evs["events"][-1]["seq"], \
                    "latest_seq must not run ahead of the delivered list"
                code, evs2 = _get(srv.url + f"/events?since={cursor}")
                assert code == 200 and evs2["events"] == []
                assert evs2["latest_seq"] == cursor  # cursor never rewinds
                tel.event("fresh")
                code, evs3 = _get(srv.url + f"/events?since={cursor}")
                assert [e["kind"] for e in evs3["events"]] == ["fresh"]
                assert evs3["events"][0]["seq"] == cursor + 1
                assert "mono_ms" in evs3["events"][0]
                code, bad = _get(srv.url + "/events?since=nope")
                assert code == 400
            finally:
                srv.close()

    def test_endpoints_without_session_explain_themselves(self):
        from spatialflink_tpu.utils import telemetry as telemetry_mod

        assert telemetry_mod.active() is None
        srv = OpServer(port=0).start()
        try:
            code, recent = _get(srv.url + "/trace/recent")
            assert code == 200 and recent["traces"] == []
            assert "note" in recent
            code, tr = _get(srv.url + "/trace/range:1")
            assert code == 404
            code, prof = _get(srv.url + "/profile/cells")
            assert code == 200 and prof["cells"] == [] and "note" in prof
        finally:
            srv.close()

    def test_plain_session_has_no_trace_book(self):
        with telemetry_session() as tel:  # no trace=True / trace_dir
            assert tel.traces is None
            srv = OpServer(port=0).start()
            try:
                code, recent = _get(srv.url + "/trace/recent")
                assert code == 200 and "note" in recent
            finally:
                srv.close()


def _full_lineage_traces(trace_doc, required):
    """trace_ids whose event set covers ``required`` stage names."""
    per_trace = {}
    for e in trace_doc["traceEvents"]:
        tid = e.get("args", {}).get("trace_id")
        if tid:
            per_trace.setdefault(tid, set()).add(e["name"])
    return [t for t, s in per_trace.items() if required <= s]


class TestDriverTraceExport:
    def test_file_run_exports_perfetto_lineage(self, tmp_path):
        """--trace-dir on a plain file replay with --panes: trace.json is
        Chrome/Perfetto-loadable and ≥ 1 window's trace carries the full
        ingest → pane-seal → kernel → merge → emit → sink lineage."""
        from spatialflink_tpu.driver import main

        inp = tmp_path / "pts.geojson"
        with open(inp, "w") as f:
            for i in range(120):
                p = Point.create(116.5 + 0.001 * (i % 40), 40.5, GRID,
                                 obj_id=f"o{i}",
                                 timestamp=1_700_000_000_000 + i * 500)
                f.write(serialize_spatial(p, "GeoJSON") + "\n")
        tdir = tmp_path / "trace"
        assert main(["--config", "conf/spatialflink-conf.yml",
                     "--input1", str(inp), "--option", "1", "--panes",
                     "--trace-dir", str(tdir)]) == 0
        doc = json.load(open(tdir / "trace.json"))
        assert doc["traceEvents"], "empty trace export"
        full = _full_lineage_traces(
            doc, {"ingest", "pane-seal", "kernel", "merge", "emit", "sink"})
        assert full, "no window trace carries the full lineage"
        assert all(t.startswith("range:") for t in full)
        # slices are microsecond X events a viewer can actually render
        assert any(e["ph"] == "X" and e["dur"] >= 1 and e["name"] == "kernel"
                   for e in doc["traceEvents"])


CONTROL = json.dumps({"geometry": {"type": "control", "coordinates": []}})


class _TracePoller(threading.Thread):
    """Mid-run client for the acceptance test: waits for the driver's
    ephemeral server, then for a sealed window trace AND a non-empty cost
    profile, then grabs /trace/<id>, /profile/cells, and /events?since."""

    def __init__(self):
        super().__init__(daemon=True)
        self.result: dict = {}

    def run(self):
        deadline = time.monotonic() + 30.0
        srv = None
        while time.monotonic() < deadline and srv is None:
            srv = active_server()
            if srv is None or srv.port is None:
                srv = None
                time.sleep(0.01)
        if srv is None:
            self.result["error"] = "status server never came up"
            return
        while time.monotonic() < deadline:
            try:
                _, recent = _get(srv.url + "/trace/recent", timeout=2)
                _, prof = _get(srv.url + "/profile/cells", timeout=2)
            except Exception:
                time.sleep(0.05)
                continue
            sealed = [t for t in recent.get("traces", [])
                      if t.get("emitted_ms")]
            if sealed and prof.get("cells") and \
                    prof.get("families", {}).get("range", {}).get(
                        "kernel_ms", 0) > 0:
                self.result["recent"] = recent
                self.result["profile"] = prof
                try:
                    self.result["trace"] = _get(
                        srv.url + "/trace/" + sealed[0]["trace_id"],
                        timeout=2)
                    _, evs = _get(srv.url + "/events", timeout=2)
                    self.result["events_since"] = _get(
                        srv.url + f"/events?since={evs['latest_seq']}",
                        timeout=2)
                except Exception as e:  # pragma: no cover - diagnostic
                    self.result["error"] = repr(e)
                return
            time.sleep(0.05)
        self.result["error"] = "no sealed trace + cost profile mid-run"


class TestLiveAcceptance:
    """The ISSUE acceptance run: --kafka-follow --chaos --panes with the
    trace plane on — mid-run /trace/<id> and /profile/cells return
    schema-valid payloads, and the exported trace.json is
    Perfetto-loadable with ingest/pane-seal/kernel/merge/emit slices for
    ≥ 1 window."""

    def test_follow_chaos_panes_trace_plane(self, tmp_path):
        from spatialflink_tpu.driver import main
        from spatialflink_tpu.streams.kafka import (reset_memory_brokers,
                                                    resolve_broker)

        reset_memory_brokers()
        try:
            with open("conf/spatialflink-conf.yml") as f:
                d = yaml.safe_load(f)
            d["kafkaBootStrapServers"] = "memory://costattr-follow"
            d["window"].update(interval=4, step=1)  # overlap 4: pane reuse
            d["query"]["thresholds"]["outOfOrderTuples"] = 0
            cfg = tmp_path / "conf.yml"
            cfg.write_text(yaml.safe_dump(d))
            broker = resolve_broker("memory://costattr-follow")

            def produce():
                # ~7s of wall-clock event time: 4s windows on 1s slides
                # seal from ~5s on, so the poller has a live span with
                # sealed traces and attributed kernel cost
                for i in range(700):
                    p = Point.create(116.5 + 0.001 * (i % 40), 40.5, GRID,
                                     obj_id=f"veh{i % 7}",
                                     timestamp=int(time.time() * 1000))
                    broker.produce("points.geojson",
                                   serialize_spatial(p, "GeoJSON"))
                    time.sleep(0.01)
                broker.produce("points.geojson", CONTROL)

            t = threading.Thread(target=produce, daemon=True)
            poller = _TracePoller()
            t.start()
            poller.start()
            tdir = tmp_path / "trace"
            rc = main(["--config", str(cfg), "--kafka", "--kafka-follow",
                       "--option", "1", "--panes",
                       "--chaos", "seed=3,fail_next_fetches=2",
                       "--retry", "attempts=8,base_ms=1",
                       "--status-port", "0",
                       "--trace-dir", str(tdir),
                       "--telemetry-dir", str(tmp_path / "tel"),
                       "--telemetry-interval", "0.1"])
            t.join(timeout=30)
            poller.join(timeout=30)
            assert rc == 0
            res = poller.result
            assert "error" not in res, res
            # --- /trace/<id> mid-run: schema-valid, real durations ---
            code, tr = res["trace"]
            assert code == 200 and TRACE_KEYS <= set(tr)
            assert tr["query"] == "range" and tr["emitted_ms"]
            stages = {e["stage"] for e in tr["events"]}
            assert {"kernel", "merge", "emit"} <= stages
            assert any("dur_ms" in e for e in tr["events"])
            # --- /profile/cells mid-run: schema-valid, cost attributed ---
            prof = res["profile"]
            assert {"cells", "families", "series",
                    "total_kernel_ms"} <= set(prof)
            assert prof["cells"][0]["cost_ms"] > 0
            assert prof["families"]["range"]["windows"] >= 1
            assert prof["families"]["range"]["pane_misses"] >= 1
            # --- /events?since= cursor drains mid-run ---
            code, evs = res["events_since"]
            assert code == 200 and isinstance(evs["events"], list)
            # --- the exported artifact: Perfetto-loadable full lineage ---
            doc = json.load(open(tdir / "trace.json"))
            full = _full_lineage_traces(
                doc, {"ingest", "pane-seal", "kernel", "merge", "emit"})
            assert full, "trace.json lacks a full-lineage window"
            # downstream sink stages ride the same traces (kafka commit)
            names = {e["name"] for e in doc["traceEvents"]}
            assert "sink-commit" in names
            # telemetry snapshots carry the cost digest alongside
            with open(tmp_path / "tel" / "telemetry.jsonl") as f:
                snaps = [json.loads(line) for line in f]
            assert snaps[-1]["status"]["top_cost_cells"]
            assert snaps[-1]["costs"]["families"]["range"]["kernel_ms"] > 0
            assert snaps[-1]["traces"]["enabled"] is True
        finally:
            reset_memory_brokers()
