"""Trajectory operators vs sequential NumPy oracles and naive twins."""

import numpy as np
import pytest

from spatialflink_tpu.index import UniformGrid
from spatialflink_tpu.models import LineString, Point, Polygon
from spatialflink_tpu.operators import (
    PointPointTJoinQuery,
    PointPointTKNNQuery,
    PointPolygonTRangeQuery,
    PointTAggregateQuery,
    PointTFilterQuery,
    PointTStatsQuery,
    QueryConfiguration,
    QueryType,
)
from spatialflink_tpu.streams import SyntheticPointSource
from tests import oracles as O

GRID = UniformGrid(115.50, 117.60, 39.60, 41.10, num_grid_partitions=100)
BASE = 1_700_000_000_000


def source(**kw):
    defaults = dict(num_trajectories=20, steps=25, dt_ms=1000, seed=9)
    defaults.update(kw)
    return SyntheticPointSource(GRID, **defaults)


def window_conf(**kw):
    return QueryConfiguration(window_size_ms=10_000, slide_ms=10_000, **kw)


def realtime_conf(**kw):
    kw.setdefault("realtime_batch_size", 100)
    return QueryConfiguration(query_type=QueryType.RealTime, **kw)


class TestTFilter:
    def test_realtime_filters_ids(self):
        op = PointTFilterQuery(realtime_conf(), GRID)
        results = list(op.run(source(), {"traj-1", "traj-2"}))
        assert results
        for res in results:
            assert {p.obj_id for p in res.records} <= {"traj-1", "traj-2"}

    def test_empty_set_passes_all(self):
        op = PointTFilterQuery(realtime_conf(), GRID)
        n = sum(len(r.records) for r in op.run(source(), set()))
        assert n == 20 * 25

    def test_windowed_builds_linestrings(self):
        op = PointTFilterQuery(window_conf(), GRID)
        results = list(op.run(source(), {"traj-3"}))
        full = [r for r in results if r.records]
        assert full
        for res in full:
            assert all(isinstance(t, LineString) for t in res.records)
            assert res.records[0].obj_id == "traj-3"
            # coords are time-sorted
            ts = res.records[0].coords_list
            assert len(ts) >= 2


class TestTStats:
    def _oracle(self, points):
        """Sequential reference semantics (TStatsQuery.java:89-148)."""
        state = {}
        out = []
        for p in points:
            st = state.get(p.obj_id)
            if st is None:
                state[p.obj_id] = [p.x, p.y, p.timestamp, 0.0, 0]
                continue
            if p.timestamp > st[2]:
                d = O.pp_dist(st[0], st[1], p.x, p.y)
                st[3] += d
                st[4] += p.timestamp - st[2]
                st[0], st[1], st[2] = p.x, p.y, p.timestamp
                out.append((p.obj_id, st[3], st[4], st[3] / st[4]))
        return out

    def test_realtime_matches_sequential_oracle(self):
        pts = list(source(num_trajectories=5, steps=30))
        op = PointTStatsQuery(realtime_conf(realtime_batch_size=37), GRID)
        got = []
        for res in op.run(iter(pts)):
            got.extend(res.records)
        want = self._oracle(pts)
        assert len(got) == len(want)
        got_by_obj = {}
        for oid, s, t, v in got:
            got_by_obj.setdefault(oid, []).append((s, t, v))
        want_by_obj = {}
        for oid, s, t, v in want:
            want_by_obj.setdefault(oid, []).append((s, t, v))
        for oid in want_by_obj:
            g, w = got_by_obj[oid], want_by_obj[oid]
            # emission order within object follows event time in both;
            # atol covers f32 coordinate-quantization drift (~2e-6/segment,
            # see ops.distances precision model) over the run
            np.testing.assert_allclose([x[0] for x in g], [x[0] for x in w],
                                       atol=1e-4)
            assert [x[1] for x in g] == [x[1] for x in w]

    def test_out_of_order_dropped(self):
        pts = [
            Point.create(116.0, 40.0, GRID, "a", BASE + 1000),
            Point.create(116.1, 40.0, GRID, "a", BASE + 3000),
            Point.create(116.2, 40.0, GRID, "a", BASE + 2000),  # late: dropped
            Point.create(116.3, 40.0, GRID, "a", BASE + 4000),
        ]
        op = PointTStatsQuery(realtime_conf(realtime_batch_size=2), GRID)
        got = []
        for res in op.run(iter(pts)):
            got.extend(res.records)
        want = self._oracle(pts)
        assert len(got) == len(want) == 2
        np.testing.assert_allclose(got[-1][1], want[-1][1], rtol=1e-4)

    def test_long_horizon_no_int32_wrap(self):
        """A run whose event time spans >> 2^31 ms (~24.8 days) must not wrap:
        carried last_ts offsets are rebased per micro-batch and pathological
        batch spans are split host-side."""
        day = 86_400_000
        # continuously active trajectory: one point every 12h for 90 days
        n = 180
        pts = [Point.create(116.0 + 0.001 * (i % 50), 40.0, GRID, "a",
                            BASE + i * (day // 2))
               for i in range(n + 1)]
        op = PointTStatsQuery(realtime_conf(realtime_batch_size=16), GRID)
        got = []
        for res in op.run(iter(pts)):
            got.extend(res.records)
        # every in-order point after the first emits (nothing silently
        # dropped to a wrapped offset), temporal length = the full span
        assert len(got) == n
        assert abs(got[-1][2] - 90 * day) <= 4096  # f32 accumulator rounding

    def test_state_carries_across_micro_batches(self):
        pts = [Point.create(116.0 + 0.01 * i, 40.0, GRID, "a", BASE + i * 1000)
               for i in range(10)]
        op1 = PointTStatsQuery(realtime_conf(realtime_batch_size=3), GRID)
        op2 = PointTStatsQuery(realtime_conf(realtime_batch_size=1000), GRID)
        final1 = [r.records[-1] for r in op1.run(iter(pts))][-1]
        final2 = [r.records[-1] for r in op2.run(iter(pts))][-1]
        np.testing.assert_allclose(final1[1], final2[1], rtol=1e-4)
        assert final1[2] == final2[2]


class TestTAggregate:
    def test_windowed_sum_matches_oracle(self):
        pts = list(source(num_trajectories=8, steps=20))
        op = PointTAggregateQuery(window_conf(), GRID)
        results = [r for r in op.run(iter(pts), "SUM") if "heatmap" in r.extras]
        assert results
        # oracle for the first emitted window
        from spatialflink_tpu.runtime import WindowAssembler, WindowSpec

        wa = WindowAssembler(WindowSpec.sliding(10_000, 10_000))
        windows = {}
        for p in pts:
            for s, e, recs in wa.add(p.timestamp, p):
                windows[s] = recs
        res = results[0]
        recs = windows[res.window_start]
        want = np.zeros(GRID.num_cells)
        groups = {}
        for p in recs:
            if p.cell >= 0:
                g = groups.setdefault((p.cell, p.obj_id), [p.timestamp, p.timestamp])
                g[0] = min(g[0], p.timestamp)
                g[1] = max(g[1], p.timestamp)
        for (cell, _oid), (mn, mx) in groups.items():
            want[cell] += mx - mn
        np.testing.assert_allclose(res.extras["heatmap"], want, rtol=1e-5)

    @pytest.mark.parametrize("agg", ["AVG", "MIN", "MAX", "COUNT"])
    def test_other_aggregates_run(self, agg):
        pts = list(source(num_trajectories=5, steps=12))
        op = PointTAggregateQuery(window_conf(), GRID)
        results = [r for r in op.run(iter(pts), agg) if "heatmap" in r.extras]
        assert results and np.isfinite(results[0].extras["heatmap"]).all()

    def test_all_mode_returns_groups(self):
        pts = list(source(num_trajectories=4, steps=10))
        op = PointTAggregateQuery(window_conf(), GRID)
        results = list(op.run(iter(pts), "ALL"))
        assert any(r.records for r in results)
        cell, oid, length = results[0].records[0]
        assert isinstance(oid, str) and length >= 0

    def test_realtime_eviction(self):
        pts = [Point.create(116.0, 40.0, GRID, "a", BASE),
               Point.create(116.0, 40.0, GRID, "a", BASE + 1000),
               Point.create(116.5, 40.5, GRID, "b", BASE + 60_000)]
        op = PointTAggregateQuery(realtime_conf(realtime_batch_size=2), GRID)
        results = list(op.run(iter(pts), "COUNT", traj_deletion_threshold_ms=10_000))
        hm = results[-1].extras["heatmap"]
        cell_a, _ = GRID.assign_cell(116.0, 40.0)
        assert hm[int(cell_a)] == 0  # trajectory a evicted after 60s gap

    def test_realtime_all_flags_sum_substitution(self):
        # the realtime heatmap form can't carry ALL's per-(cell, objID)
        # records, so ALL is served as SUM — the result must SAY so rather
        # than silently relabeling (windowed ALL returns true records)
        pts = [Point.create(116.0, 40.0, GRID, "a", BASE),
               Point.create(116.0, 40.0, GRID, "a", BASE + 1000)]
        op = PointTAggregateQuery(realtime_conf(realtime_batch_size=2), GRID)
        res = list(op.run(iter(pts), "ALL"))[-1]
        assert res.extras["aggregate"] == "ALL"
        assert res.extras["heatmap_semantics"] == "SUM"
        sum_res = list(PointTAggregateQuery(
            realtime_conf(realtime_batch_size=2), GRID).run(
                iter([Point.create(116.0, 40.0, GRID, "a", BASE),
                      Point.create(116.0, 40.0, GRID, "a", BASE + 1000)]),
                "SUM"))[-1]
        np.testing.assert_array_equal(res.extras["heatmap"],
                                      sum_res.extras["heatmap"])
        assert "heatmap_semantics" not in sum_res.extras


class TestTAggregateCheckpointResume:
    """Kill/resume must preserve the realtime heatmap: the (cell, objID)
    extent map is snapshotted and restored, and the consumed offset lets a
    file replay skip already-applied records."""

    def _stream(self, lo, hi):
        rng = np.random.default_rng(41)
        n = 300
        xs = rng.uniform(115.6, 117.5, n)
        ys = rng.uniform(39.7, 41.0, n)
        pts = [Point.create(float(xs[i]), float(ys[i]), GRID,
                            obj_id=f"t{i % 9}", timestamp=BASE + i * 1000)
               for i in range(n)]
        return pts[lo:hi]

    def test_resume_equals_uninterrupted(self, tmp_path):
        cp = str(tmp_path / "tagg.npz")
        conf = lambda: realtime_conf(realtime_batch_size=32)
        full = list(PointTAggregateQuery(conf(), GRID).run(
            iter(self._stream(0, 300)), "SUM"))
        list(PointTAggregateQuery(conf(), GRID).run(
            iter(self._stream(0, 160)), "SUM",
            checkpoint_path=cp, checkpoint_every=1))
        assert PointTAggregateQuery.checkpoint_consumed(cp) == 160
        out2 = list(PointTAggregateQuery(conf(), GRID).run(
            iter(self._stream(160, 300)), "SUM", checkpoint_path=cp))
        np.testing.assert_array_equal(out2[-1].extras["heatmap"],
                                      full[-1].extras["heatmap"])

    def test_eviction_state_survives_checkpoint(self, tmp_path):
        cp = str(tmp_path / "tagg2.npz")
        pts = [Point.create(116.0, 40.0, GRID, "a", BASE),
               Point.create(116.0, 40.0, GRID, "a", BASE + 1000)]
        list(PointTAggregateQuery(realtime_conf(realtime_batch_size=2), GRID).run(
            iter(pts), "SUM", traj_deletion_threshold_ms=10_000,
            checkpoint_path=cp, checkpoint_every=1))
        # resumed run sees a 60s-later point: the restored extent for "a"
        # must be evicted by last_seen, proving last_seen round-tripped
        late = [Point.create(116.5, 40.5, GRID, "b", BASE + 60_000),
                Point.create(116.5, 40.5, GRID, "b", BASE + 61_000)]
        out = list(PointTAggregateQuery(realtime_conf(realtime_batch_size=2), GRID).run(
            iter(late), "SUM", traj_deletion_threshold_ms=10_000,
            checkpoint_path=cp))
        hm = out[-1].extras["heatmap"]
        cell_a, _ = GRID.assign_cell(116.0, 40.0)
        assert hm[int(cell_a)] == 0


class TestTAggregateCountWindows:
    """Per-cell COUNT windows (TAggregateQuery.java:381-494): keyed by cell,
    fire every `slide` arrivals over the last `size` points of that cell."""

    def _conf(self, size, slide):
        from spatialflink_tpu.operators import QueryConfiguration, QueryType

        return QueryConfiguration(QueryType.CountBased, window_size_ms=size,
                                  slide_ms=slide)

    def test_fires_every_slide_per_cell(self):
        # 6 points in one cell, size=4 slide=2 -> fires at arrivals 2, 4, 6
        pts = [Point.create(116.05, 40.05, GRID, f"t{i % 2}", BASE + i * 1000)
               for i in range(6)]
        op = PointTAggregateQuery(self._conf(4, 2), GRID)
        results = list(op.run(iter(pts), "ALL"))
        assert len(results) == 3
        # third fire sees the LAST 4 points (arrivals 3..6)
        cell, lengths = results[2].records[0]
        # t0 points in window: ts 2000, 4000 -> length 2000; t1: 3000, 5000
        assert lengths == {"t0": 2000, "t1": 2000}

    def test_cells_fire_independently(self):
        a = [Point.create(116.05, 40.05, GRID, "a", BASE + i * 1000)
             for i in range(2)]
        b = [Point.create(117.05, 41.05, GRID, "b", BASE + i * 1000)
             for i in range(2)]
        # interleave: each cell reaches its slide=2 exactly once
        pts = [a[0], b[0], a[1], b[1]]
        op = PointTAggregateQuery(self._conf(2, 2), GRID)
        results = list(op.run(iter(pts), "COUNT"))
        assert len(results) == 2
        cells = {r.extras["cell"] for r in results}
        assert len(cells) == 2

    def test_sum_and_avg(self):
        pts = [Point.create(116.05, 40.05, GRID, "x", BASE),
               Point.create(116.05, 40.05, GRID, "x", BASE + 3000),
               Point.create(116.05, 40.05, GRID, "y", BASE + 1000),
               Point.create(116.05, 40.05, GRID, "y", BASE + 2000)]
        op = PointTAggregateQuery(self._conf(4, 4), GRID)
        (res,) = list(op.run(iter(pts), "SUM"))
        assert res.records == [(pts[0].cell, 4000)]  # 3000 + 1000
        op = PointTAggregateQuery(self._conf(4, 4), GRID)
        (res,) = list(op.run(iter(pts), "AVG"))
        assert res.records == [(pts[0].cell, 2000)]

    def test_min_max_require_multipoint_objects(self):
        """All-singleton window: the reference's min/max trackers only update
        on a re-sighting, so MIN stays Long.MAX_VALUE and nothing is emitted
        (TAggregateQuery.java:476-489 guards)."""
        pts = [Point.create(116.05, 40.05, GRID, f"s{i}", BASE + i * 1000)
               for i in range(4)]
        for agg in ("MIN", "MAX"):
            op = PointTAggregateQuery(self._conf(4, 4), GRID)
            assert list(op.run(iter(pts), agg)) == []

    def test_min_tracks_intermediate_lengths(self):
        """The reference's MIN is the minimum over lengths at each
        re-sighting: B's length at its 2nd point (1000) undercuts every
        FINAL length (A=10000, B=100000) and wins."""
        pts = [
            Point.create(116.05, 40.05, GRID, "A", BASE),
            Point.create(116.05, 40.05, GRID, "B", BASE),
            Point.create(116.05, 40.05, GRID, "B", BASE + 1000),
            Point.create(116.05, 40.05, GRID, "A", BASE + 10_000),
            Point.create(116.05, 40.05, GRID, "B", BASE + 100_000),
        ]
        op = PointTAggregateQuery(self._conf(5, 5), GRID)
        (res,) = list(op.run(iter(pts), "MIN"))
        assert res.records == [(pts[0].cell, "B", 1000)]
        op = PointTAggregateQuery(self._conf(5, 5), GRID)
        (res,) = list(op.run(iter(pts), "MAX"))
        assert res.records == [(pts[0].cell, "B", 100_000)]

    def test_count_mode_rejected_for_joins_and_apps(self):
        """Count windows are now implemented for single-stream operators
        (range/kNN/trajectory); the two-stream joins and the bespoke-window
        apps keep the rejection."""
        import pytest as _pytest

        from spatialflink_tpu.apps.check_in import CheckIn
        from spatialflink_tpu.operators import (
            PointPointJoinQuery,
            PointPointRangeQuery,
        )

        PointPointRangeQuery(self._conf(4, 2), GRID)  # accepted now
        with _pytest.raises(NotImplementedError):
            PointPointJoinQuery(self._conf(4, 2), GRID)
        with _pytest.raises(NotImplementedError):
            CheckIn(self._conf(4, 2))

    def test_driver_count_window_option_208(self):
        """window.type COUNT + option 208 runs count-window tAggregate with
        interval/step as raw counts."""
        from spatialflink_tpu.config import Params
        from spatialflink_tpu.driver import run_option

        d = dict(
            inputStream1=dict(
                topicName="t", format="CSV", csvTsvSchemaAttr=[0, 1, 2, 3],
                dateFormat=None, gridBBox=[115.5, 39.6, 117.6, 41.1],
                numGridCells=100),
            outputStream=dict(topicName="o"),
            query=dict(option=208, radius=0.5, aggregateFunction="ALL"),
            window=dict(type="COUNT", interval=4, step=2),
        )
        lines = [f"t{i % 2},{BASE + i * 1000},116.05,40.05" for i in range(6)]
        results = list(run_option(Params.from_dict(d), iter(lines)))
        assert len(results) == 3


class TestTJoin:
    def test_dedup_keeps_latest(self):
        a = [Point.create(116.5, 40.5, GRID, "A", BASE + i * 1000) for i in range(3)]
        b = [Point.create(116.5001, 40.5, GRID, "B", BASE + i * 1000) for i in range(3)]
        op = PointPointTJoinQuery(window_conf(), GRID)
        results = [r for r in op.run(iter(a), iter(b), 0.05) if r.records]
        assert results
        assert len(results[0].records) == 1  # one output per (A, B)
        la, lb = results[0].records[0]
        assert (la.obj_id, lb.obj_id) == ("A", "B")

    def test_windowed_emits_subtrajectory_linestrings(self):
        """Windowed mode joins deduped pairs back to both sides' windowed
        trajectories (PointPointTJoinQuery.java:183-338): records are
        (LineString, LineString) pairs carrying each trajectory's full
        window points in time order."""
        from spatialflink_tpu.models import LineString

        a = [Point.create(116.5 + i * 1e-4, 40.5, GRID, "A", BASE + i * 1000)
             for i in range(4)]
        b = [Point.create(116.5001, 40.5, GRID, "B", BASE + i * 1000)
             for i in range(4)]
        op = PointPointTJoinQuery(window_conf(), GRID)
        results = [r for r in op.run(iter(a), iter(b), 0.05) if r.records]
        assert results
        la, lb = results[0].records[0]
        assert isinstance(la, LineString) and isinstance(lb, LineString)
        # side a's LineString carries ALL of A's window points, sorted
        first = results[0]
        in_window = [p for p in a
                     if first.window_start <= p.timestamp < first.window_end]
        assert [tuple(np.round(c, 6)) for c in la.coords_list] == \
               [(round(p.x, 6), round(p.y, 6)) for p in in_window]

    def test_windowed_drops_single_point_trajectories(self):
        """A trajectory with < 2 points in the window has no LineString to
        join against (TJoinQuery.java:184) — its pairs are dropped."""
        a = [Point.create(116.5, 40.5, GRID, "A", BASE)]  # one point only
        b = [Point.create(116.5001, 40.5, GRID, "B", BASE + i * 1000)
             for i in range(3)]
        op = PointPointTJoinQuery(window_conf(), GRID)
        results = [r for r in op.run(iter(a), iter(b), 0.05) if r.records]
        assert not results

    def test_realtime_still_emits_point_pairs(self):
        a = [Point.create(116.5, 40.5, GRID, "A", BASE + i * 100) for i in range(4)]
        b = [Point.create(116.5001, 40.5, GRID, "B", BASE + i * 100) for i in range(4)]
        op = PointPointTJoinQuery(realtime_conf(realtime_batch_size=4), GRID)
        results = [r for r in op.run(iter(a), iter(b), 0.05) if r.records]
        assert results
        pa, pb = results[0].records[0]
        assert isinstance(pa, Point) and isinstance(pb, Point)

    def test_self_join_skips_same_object(self):
        pts = [Point.create(116.5 + i * 1e-4, 40.5, GRID, f"t{i % 2}", BASE + i * 500)
               for i in range(8)]
        op = PointPointTJoinQuery(window_conf(), GRID)
        results = [r for r in op.run_single(iter(pts), 0.05) if r.records]
        assert results
        for res in results:
            for x, y in res.records:
                assert x.obj_id != y.obj_id

    def test_pruned_matches_naive(self):
        a = list(source(seed=30, num_trajectories=10, steps=12))
        b = list(source(seed=31, num_trajectories=5, steps=12))
        op1 = PointPointTJoinQuery(window_conf(), GRID)
        op2 = PointPointTJoinQuery(window_conf(), GRID)
        r = 0.08
        pruned = {(res.window_start, x.obj_id, y.obj_id)
                  for res in op1.run(iter(a), iter(b), r) for x, y in res.records}
        naive = {(res.window_start, x.obj_id, y.obj_id)
                 for res in op2.run_naive(iter(a), iter(b), r) for x, y in res.records}
        assert pruned == naive


class TestTKnn:
    def test_nearest_trajectories_with_radius(self):
        pts = list(source(seed=33, num_trajectories=15, steps=12))
        q = Point.create(116.5, 40.5, GRID, obj_id="q")
        op = PointPointTKNNQuery(window_conf(k=5), GRID)
        results = [r for r in op.run(iter(pts), q, 0.5) if r.records]
        assert results
        for res in results:
            dists = [d for _, d, _ in res.records]
            assert all(d <= 0.5 + 1e-3 for d in dists)
            assert dists == sorted(dists)
            for oid, d, sub in res.records:
                assert sub is None or getattr(sub, "obj_id", oid) == oid

    def test_pruned_matches_naive(self):
        pts = list(source(seed=34, num_trajectories=12, steps=10))
        q = Point.create(116.5, 40.5, GRID, obj_id="q")
        op1 = PointPointTKNNQuery(window_conf(k=4), GRID)
        op2 = PointPointTKNNQuery(window_conf(k=4), GRID)
        pruned = [(r.window_start, [(o, round(d, 4)) for o, d, _ in r.records])
                  for r in op1.run(iter(pts), q, 0.3)]
        naive = [(r.window_start, [(o, round(d, 4)) for o, d, _ in r.records])
                 for r in op2.run_naive(iter(pts), q, 0.3)]
        assert pruned == naive


class TestTRange:
    POLYS = [
        Polygon.create([[(116.4, 40.4), (116.6, 40.4), (116.6, 40.6), (116.4, 40.6)]],
                       GRID, obj_id="z1"),
        Polygon.create([[(116.0, 40.0), (116.1, 40.0), (116.1, 40.1), (116.0, 40.1)]],
                       GRID, obj_id="z2"),
    ]

    def test_realtime_matches_naive(self):
        pts = list(source(seed=35, num_trajectories=10, steps=15))
        op1 = PointPolygonTRangeQuery(realtime_conf(), GRID)
        op2 = PointPolygonTRangeQuery(realtime_conf(), GRID)
        got = {(p.obj_id, p.timestamp)
               for r in op1.run(iter(pts), self.POLYS) for p in r.records}
        naive = {(p.obj_id, p.timestamp)
                 for r in op2.run_naive(iter(pts), self.POLYS) for p in r.records}
        assert got == naive

    def test_windowed_returns_full_subtrajectories(self):
        pts = list(source(seed=36, num_trajectories=8, steps=15))
        op = PointPolygonTRangeQuery(window_conf(), GRID)
        results = [r for r in op.run(iter(pts), self.POLYS) if r.records]
        for res in results:
            assert res.extras["matched_ids"]
            ids = {getattr(t, "obj_id") for t in res.records}
            assert ids == res.extras["matched_ids"]


class TestStateCheckpoint:
    def test_snapshot_restore_roundtrip(self, tmp_path):
        from spatialflink_tpu.runtime.state import TrajStateStore
        from spatialflink_tpu.ops.trajectory import tstats_update
        from spatialflink_tpu.models import PointBatch

        store = TrajStateStore(capacity=256)
        b = PointBatch.from_arrays(
            np.array([116.0, 116.1]), np.array([40.0, 40.0]),
            grid=GRID, obj_id=np.array([1, 1], np.int32),
            ts=np.array([BASE, BASE + 1000], np.int64), ts_base=BASE,
        )
        store.state, _ = tstats_update(store.state, b)
        path = str(tmp_path / "state.npz")
        store.snapshot().save(path)
        from spatialflink_tpu.runtime.state import CheckpointableState

        restored = TrajStateStore.restore(CheckpointableState.load(path))
        assert restored.capacity == store.capacity
        np.testing.assert_allclose(np.asarray(restored.state.spatial),
                                   np.asarray(store.state.spatial))
        assert int(np.asarray(restored.state.last_ts)[1]) == 1000


class TestTStatsCheckpointResume:
    """Kill-and-resume must continue accumulating exactly where the previous
    process stopped (state + interner + timestamp base restored)."""

    _N = 400

    def _stream(self, lo, hi):
        rng = np.random.default_rng(17)
        t0 = 1_700_000_000_000
        xs = rng.uniform(115.6, 117.5, self._N)  # full draw: slices of the
        ys = rng.uniform(39.7, 41.0, self._N)    # same stream, not new ones
        pts = [Point.create(float(xs[i]), float(ys[i]), GRID,
                            obj_id=f"t{i % 7}", timestamp=t0 + i * 1000)
               for i in range(self._N)]
        return pts[lo:hi]

    def _conf(self):
        return QueryConfiguration(QueryType.RealTime, realtime_batch_size=32)

    def test_resume_equals_uninterrupted(self, tmp_path):
        cp = str(tmp_path / "tstats.npz")
        full = list(PointTStatsQuery(self._conf(), GRID).run(
            iter(self._stream(0, 400))))
        # process 1: first half, checkpoint every batch, then "crash"
        out1 = list(PointTStatsQuery(self._conf(), GRID).run(
            iter(self._stream(0, 200)), checkpoint_path=cp, checkpoint_every=1))
        # process 2: fresh operator, resumes, consumes the rest
        out2 = list(PointTStatsQuery(self._conf(), GRID).run(
            iter(self._stream(200, 400)), checkpoint_path=cp))
        got = [t for w in out1 + out2 for t in w.records]
        want = [t for w in full for t in w.records]
        assert len(got) == len(want)
        # tuples are grouped per object within each micro-batch, so different
        # batch boundaries reorder the global sequence; per-object tuple
        # sequences must match exactly
        def by_obj(tuples):
            d = {}
            for t in tuples:
                d.setdefault(t[0], []).append(t[1:])
            return d
        g, w = by_obj(got), by_obj(want)
        assert set(g) == set(w)
        for o in w:
            np.testing.assert_allclose(g[o], w[o], rtol=1e-5, atol=1e-3)

    def test_checkpoint_records_consumed_offset(self, tmp_path):
        """The checkpoint stores the number of consumed source records so a
        file-replaying caller can skip them on resume instead of
        double-counting (the ADVICE round-1 driver.py:481 finding)."""
        cp = str(tmp_path / "tstats.npz")
        list(PointTStatsQuery(self._conf(), GRID).run(
            iter(self._stream(0, 200)), checkpoint_path=cp, checkpoint_every=1))
        assert PointTStatsQuery.checkpoint_consumed(cp) == 200
        # resumed run's consumed count continues from the restored offset
        list(PointTStatsQuery(self._conf(), GRID).run(
            iter(self._stream(200, 300)), checkpoint_path=cp))
        assert PointTStatsQuery.checkpoint_consumed(cp) == 300
        assert PointTStatsQuery.checkpoint_consumed(
            str(tmp_path / "missing.npz")) == 0

    def test_cli_resume_skips_consumed_records(self, tmp_path):
        """End-to-end: driver --checkpoint resume over the SAME input file
        must not re-apply already-checkpointed records — the run equals one
        uninterrupted pass, not pass + replayed prefix."""

        from spatialflink_tpu.driver import main as cli_main

        pts = self._stream(0, 200)
        inp = tmp_path / "pts.csv"
        with open(inp, "w") as f:
            for p in pts:
                f.write(f"{p.obj_id},{p.timestamp},{p.x},{p.y}\n")
        conf = tmp_path / "conf.yml"
        import shutil

        shutil.copy("conf/spatialflink-conf.yml", conf)
        import yaml

        with open(conf) as f:
            y = yaml.safe_load(f)
        y["query"]["option"] = 205  # tStats realtime
        y["inputStream1"]["format"] = "CSV"
        y["inputStream1"]["csvTsvSchemaAttr"] = [0, 1, 2, 3]
        y["inputStream1"]["dateFormat"] = None
        with open(conf, "w") as f:
            yaml.safe_dump(y, f)
        cp = str(tmp_path / "cli.npz")
        args = ["--config", str(conf), "--input1", str(inp),
                "--checkpoint", cp, "--checkpoint-every", "1"]
        assert cli_main(args) == 0
        consumed_after_first = PointTStatsQuery.checkpoint_consumed(cp)
        assert consumed_after_first == 200
        # second run over the same file: every record is skipped as consumed
        assert cli_main(args) == 0
        assert PointTStatsQuery.checkpoint_consumed(cp) == 200

    def test_cli_resume_respects_limit(self, tmp_path):
        """--limit N bounds the ORIGINAL record range: resume covers the
        remainder of the first N records, not N more past the checkpoint
        (ADVICE round-2 driver.py:508)."""
        from spatialflink_tpu.driver import main as cli_main

        pts = self._stream(0, 200)
        inp = tmp_path / "pts.csv"
        with open(inp, "w") as f:
            for p in pts:
                f.write(f"{p.obj_id},{p.timestamp},{p.x},{p.y}\n")
        conf = tmp_path / "conf.yml"
        import shutil

        import yaml

        shutil.copy("conf/spatialflink-conf.yml", conf)
        with open(conf) as f:
            y = yaml.safe_load(f)
        y["query"]["option"] = 205
        y["inputStream1"]["format"] = "CSV"
        y["inputStream1"]["csvTsvSchemaAttr"] = [0, 1, 2, 3]
        y["inputStream1"]["dateFormat"] = None
        with open(conf, "w") as f:
            yaml.safe_dump(y, f)
        cp = str(tmp_path / "cli.npz")
        args = ["--config", str(conf), "--input1", str(inp),
                "--checkpoint", cp, "--checkpoint-every", "1",
                "--limit", "100"]
        assert cli_main(args) == 0
        assert PointTStatsQuery.checkpoint_consumed(cp) == 100
        # re-run with identical args: all 100 are consumed; the effective
        # limit shrinks to 0 instead of pulling 100 MORE records
        assert cli_main(args) == 0
        assert PointTStatsQuery.checkpoint_consumed(cp) == 100

    def test_no_resume_without_flag(self, tmp_path):
        cp = str(tmp_path / "tstats.npz")
        list(PointTStatsQuery(self._conf(), GRID).run(
            iter(self._stream(0, 100)), checkpoint_path=cp, checkpoint_every=1))
        # resumed run continues process 1's accumulation (read cp BEFORE the
        # no-resume run below overwrites it with its own final state)
        resumed = list(PointTStatsQuery(self._conf(), GRID).run(
            iter(self._stream(100, 140)), checkpoint_path=cp))
        last_resumed = {t[0]: t for w in resumed for t in w.records}
        # resume=False ignores the existing file and starts from zeroed state
        out = list(PointTStatsQuery(self._conf(), GRID).run(
            iter(self._stream(100, 140)), checkpoint_path=cp, resume=False))
        last_fresh = {t[0]: t for w in out for t in w.records}
        common = set(last_fresh) & set(last_resumed)
        assert common
        # accumulated spatial length must be strictly larger when resumed
        assert all(last_resumed[o][1] > last_fresh[o][1] for o in common)
