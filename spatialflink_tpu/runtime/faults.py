"""Fault injection: a seeded chaos layer over the broker interface.

The delivery-semantics redesign (``streams/kafka.py`` docstring) claims
at-least-once + idempotent window sinks are *exactly-once-equivalent* for
windowed results. Nothing in the happy-path tests proves that claim survives
a degraded transport — the classes of trouble a real cluster serves up:
transient produce/consume errors, lost acks, latency spikes, duplicate
deliveries, fetch-session reordering, and torn/corrupt payloads.

:class:`ChaosBroker` wraps any broker implementing the
:class:`~spatialflink_tpu.streams.kafka.InMemoryBroker` surface
(produce/fetch/commit/committed/end_offset) and injects exactly those faults
under a seeded, deterministic :class:`FaultPlan` — the same plan + the same
call sequence reproduces the same fault schedule, so a chaos run is as
replayable as a clean one. Recovery lives one layer up
(:mod:`spatialflink_tpu.runtime.supervisor`): retry/backoff + circuit
breaking for the transient errors, offset resequencing in
:class:`~spatialflink_tpu.streams.kafka.KafkaSource` for duplicates and
reordering, and redelivery-then-dead-letter for payload corruption.

Fault model boundaries (what each class means here):

- ``produce_fail`` — the produce raises BEFORE the record is appended (the
  record did not land; a blind retry is safe).
- ``ack_lost`` — the record IS appended, then the produce raises (the
  classic ambiguous failure; a blind retry would duplicate the record —
  the supervisor's verified produce re-checks the log instead).
- ``fetch_fail`` — the fetch raises; nothing about the log changed.
- ``duplicate`` — a fetched batch re-delivers a record it (or a previous
  fetch) already carried, possibly one from before the requested offset
  (fetch-session rewind).
- ``reorder`` — a fetched batch arrives permuted (NOT something a real
  single-partition consumer observes from Kafka itself, but exactly what a
  resequencing consumer must tolerate from retried fetch sessions — and the
  adversarial case for the window-aligned commit bookkeeping).
- ``torn`` — a delivered record's VALUE is corrupted in transport; the log
  itself stays intact, so a re-fetch of the same offset can heal it. A
  record that is corrupt IN the log (true poison) fails every redelivery
  and is the dead-letter queue's job.
- ``latency`` — a produce/fetch stalls for ``latency_ms`` before running.
- ``stall`` (:class:`StallFault`, process-level rather than broker-level) —
  the worker's LIVENESS surfaces wedge for a duration while the pipeline
  keeps running slowly: heartbeats stop, checkpoints stop committing, but
  windows keep trickling out. The gray failure / zombie case the fleet's
  fencing layer exists to contain, injectable via ``--fleet-chaos-stall``.

Every injection bumps a ``chaos-*`` counter in the process metrics registry
so a run summary can report how degraded the transport actually was.
"""

from __future__ import annotations

import random
import threading
import time
from dataclasses import dataclass, fields, replace
from typing import List, Optional


class TransientBrokerError(Exception):
    """A broker operation failed in a way a retry may fix (the injected
    stand-in for network timeouts, NotEnoughReplicas, fetch-session drops).
    The supervisor's :class:`~spatialflink_tpu.runtime.supervisor.RetryPolicy`
    treats this as retryable by default."""


def parse_spec(spec: str, known: dict, where: str) -> dict:
    """Parse a comma-joined ``key=value`` CLI spec (``--chaos``/``--retry``)
    against ``known`` (name -> value converter). Unknown keys fail loudly —
    a typoed field silently configuring nothing would defeat the point of
    both spec surfaces."""
    kw = {}
    for part in filter(None, (p.strip() for p in spec.split(","))):
        if "=" not in part:
            raise ValueError(f"{where}: malformed entry {part!r} "
                             "(want key=value)")
        k, v = part.split("=", 1)
        k = k.strip()
        if k not in known:
            raise ValueError(f"{where}: unknown field {k!r} "
                             f"(known: {', '.join(sorted(known))})")
        kw[k] = known[k](v)
    return kw


@dataclass
class FaultPlan:
    """Seeded, deterministic fault schedule for a :class:`ChaosBroker`.

    Rates are per-opportunity probabilities in ``[0, 1]`` drawn from one
    ``random.Random(seed)`` stream in broker-call order — single-threaded
    drivers replay identically. The ``fail_next_*`` fields are scripted
    BURSTS (consume-before-draw): the next N operations of that kind fail
    unconditionally — the deterministic way to drive a circuit breaker to
    its trip threshold in tests.
    """

    seed: int = 0
    produce_fail: float = 0.0     # raise before the record is appended
    ack_lost: float = 0.0         # append the record, then raise
    fetch_fail: float = 0.0       # raise instead of returning a batch
    duplicate: float = 0.0        # per-batch: re-deliver a record
    reorder: float = 0.0          # per-batch: permute delivery order
    torn: float = 0.0             # per-record: corrupt the delivered value
    latency: float = 0.0          # per-call: stall before the operation
    latency_ms: float = 2.0       # stall duration for latency injections
    fail_next_produces: int = 0   # scripted burst of produce failures
    fail_next_fetches: int = 0    # scripted burst of fetch failures

    _RATE_FIELDS = ("produce_fail", "ack_lost", "fetch_fail", "duplicate",
                    "reorder", "torn", "latency")

    def __post_init__(self):
        for name in self._RATE_FIELDS:
            v = getattr(self, name)
            if not 0.0 <= v <= 1.0:
                raise ValueError(f"FaultPlan.{name}: rate {v} not in [0, 1]")

    @classmethod
    def from_spec(cls, spec: str) -> "FaultPlan":
        """Parse the CLI's ``--chaos`` spec: ``key=value`` pairs joined by
        commas, e.g. ``"seed=7,fetch_fail=0.2,torn=0.1,duplicate=0.3"``."""
        known = {f.name: (int if f.name.startswith("fail_next")
                          or f.name == "seed" else float)
                 for f in fields(cls)}
        return cls(**parse_spec(spec, known, "--chaos"))


class StallFault:
    """Injectable gray failure: the worker wedges for ``duration_s``
    WITHOUT exiting, and keeps writing.

    Arms on the first emitted window (``on_window``; a worker that never
    produced anything is indistinguishable from one still booting, which
    is the boot-timeout's job, not this fault's). While wedged:

    - the :class:`~spatialflink_tpu.runtime.fleet.HeartbeatWriter`'s gate
      (``wedged``) suppresses beats — the supervisor sees silence;
    - :meth:`~spatialflink_tpu.runtime.checkpoint.CheckpointCoordinator
      .due` returns False — a zombie must not commit manifests its fenced
      successor would resume from;
    - each subsequent ``on_window`` sleeps ``emit_delay_s`` — the worker
      is SLOW, not dead: it keeps appending outbox rows after the
      supervisor has presumed it dead, which is exactly the stale-fence
      traffic the containment tests need to observe being dropped.

    Installed process-globally (:func:`install_stall`) because the
    checkpoint coordinator has no handle on the worker context."""

    def __init__(self, duration_s: float, *, emit_delay_s: float = 0.1):
        self.duration_s = float(duration_s)
        self.emit_delay_s = float(emit_delay_s)
        self._armed_at: Optional[float] = None

    def on_window(self) -> None:
        if self._armed_at is None:
            self._armed_at = time.monotonic()
            from spatialflink_tpu.utils.metrics import REGISTRY
            REGISTRY.counter("chaos-stall").inc()
        elif self.wedged():
            time.sleep(self.emit_delay_s)

    def wedged(self) -> bool:
        return (self._armed_at is not None
                and time.monotonic() - self._armed_at < self.duration_s)


_STALL: Optional[StallFault] = None


def install_stall(fault: StallFault) -> StallFault:
    """Install the process-wide stall fault (one per worker process; the
    fleet chaos flag is the only writer)."""
    global _STALL
    _STALL = fault
    return fault


def active_stall() -> Optional[StallFault]:
    return _STALL


def _corrupt(value):
    """A torn payload: truncate and splice in bytes no spatial wire format
    accepts, so every parser fails loudly instead of mis-reading it."""
    if isinstance(value, str):
        return value[: max(1, len(value) // 2)] + "\x00TORN\x00"
    if isinstance(value, bytes):
        return value[: max(1, len(value) // 2)] + b"\x00TORN\x00"
    return "\x00TORN\x00"


class ChaosBroker:
    """Fault-injecting wrapper around any broker implementing the
    :class:`~spatialflink_tpu.streams.kafka.InMemoryBroker` surface.

    The wrapped log is never corrupted: torn payloads mutate COPIES of the
    fetched records, duplicates re-deliver existing records, and an
    ``ack_lost`` produce genuinely lands (that is the ambiguity being
    modeled). Offset bookkeeping (commit/committed/end_offset) passes
    through clean — chaos attacks the data path, not the control plane,
    matching where real deployments bleed first.
    """

    def __init__(self, inner, plan: Optional[FaultPlan] = None):
        from spatialflink_tpu.utils.metrics import REGISTRY

        self.inner = inner
        self.plan = plan or FaultPlan()
        self._rng = random.Random(self.plan.seed)
        # one mutable burst state so a shared plan object stays reusable
        self._burst_produce = int(self.plan.fail_next_produces)
        self._burst_fetch = int(self.plan.fail_next_fetches)
        self._lock = threading.Lock()
        self._c = {name: REGISTRY.counter(f"chaos-{name.replace('_', '-')}")
                   for name in ("produce_fail", "ack_lost", "fetch_fail",
                                "duplicate", "reorder", "torn", "latency")}

    # ------------------------------ helpers -------------------------- #

    def _hit(self, rate: float) -> bool:
        return rate > 0.0 and self._rng.random() < rate

    def _stall(self) -> None:
        self._c["latency"].inc()
        import time

        time.sleep(self.plan.latency_ms / 1000.0)

    # ------------------------------ broker surface ------------------- #
    # The lock guards only the RNG/burst draws (draw ORDER is what makes a
    # plan deterministic); injected sleeps and inner-broker I/O run outside
    # it so a latency spike on one call stalls THAT call, not every thread
    # sharing the broker — the per-call fault the model documents.

    def produce(self, topic: str, value, key: Optional[str] = None,
                timestamp_ms: Optional[int] = None) -> int:
        with self._lock:
            stall = self._hit(self.plan.latency)
            if self._burst_produce > 0:
                self._burst_produce -= 1
                fail = True
            else:
                fail = self._hit(self.plan.produce_fail)
            lose_ack = not fail and self._hit(self.plan.ack_lost)
        if stall:
            self._stall()
        if fail:
            self._c["produce_fail"].inc()
            raise TransientBrokerError(
                f"injected produce failure on {topic!r}")
        off = self.inner.produce(topic, value, key=key,
                                 timestamp_ms=timestamp_ms)
        if lose_ack:
            self._c["ack_lost"].inc()
            raise TransientBrokerError(
                f"injected lost ack on {topic!r} (record landed at "
                f"offset {off})")
        return off

    def fetch(self, topic: str, offset: int, max_records: int = 500
              ) -> List:
        with self._lock:
            stall = self._hit(self.plan.latency)
            if self._burst_fetch > 0:
                self._burst_fetch -= 1
                fail = True
            else:
                fail = self._hit(self.plan.fetch_fail)
        if stall:
            self._stall()
        if fail:
            self._c["fetch_fail"].inc()
            raise TransientBrokerError(
                f"injected fetch failure on {topic!r}@{offset}")
        batch = list(self.inner.fetch(topic, offset, max_records))
        if not batch:
            return batch
        with self._lock:
            dup = self._hit(self.plan.duplicate)
            rewind = dup and offset > 0 and self._rng.random() < 0.5
        prev = (self.inner.fetch(topic, offset - 1, 1) if rewind
                else None)  # rewind read is I/O: outside the lock
        with self._lock:
            if dup:
                self._c["duplicate"].inc()
                if rewind:
                    # fetch-session rewind: re-deliver a record from BEFORE
                    # the requested offset
                    if prev:
                        batch.insert(0, prev[0])
                else:
                    i = self._rng.randrange(len(batch))
                    batch.insert(self._rng.randrange(len(batch) + 1),
                                 batch[i])
            if len(batch) > 1 and self._hit(self.plan.reorder):
                self._c["reorder"].inc()
                self._rng.shuffle(batch)
            if self.plan.torn > 0.0:
                for i, rec in enumerate(batch):
                    if self._hit(self.plan.torn):
                        self._c["torn"].inc()
                        # corrupt a COPY; the log record stays intact so a
                        # redelivery of this offset can heal
                        batch[i] = replace(rec, value=_corrupt(rec.value))
        return batch

    def commit(self, topic: str, group: str, next_offset: int) -> None:
        self.inner.commit(topic, group, next_offset)

    def committed(self, topic: str, group: str) -> int:
        return self.inner.committed(topic, group)

    def end_offset(self, topic: str) -> int:
        return self.inner.end_offset(topic)

    def topic_values(self, topic: str) -> List:
        return self.inner.topic_values(topic)

    def close(self) -> None:
        if hasattr(self.inner, "close"):
            self.inner.close()
