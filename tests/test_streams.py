"""Ser/de round-trips per (format x type), sources, watermarks, windows."""

import numpy as np
import pytest

from spatialflink_tpu.index import UniformGrid
from spatialflink_tpu.models import (
    GeometryCollection,
    LineString,
    MultiLineString,
    MultiPoint,
    MultiPolygon,
    Point,
    Polygon,
)
from spatialflink_tpu.runtime import BoundedOutOfOrderness, WindowAssembler, WindowSpec
from spatialflink_tpu.streams import (
    SyntheticPointSource,
    kafka_source,
    parse_spatial,
    serialize_spatial,
)
from spatialflink_tpu.streams.formats import parse_timestamp

GRID = UniformGrid(115.50, 117.60, 39.60, 41.10, num_grid_partitions=100)


class TestGeoJSON:
    KAFKA_RECORD = (
        '{"key":136138,"value":{"geometry":{"coordinates":[116.44412,39.93984],'
        '"type":"Point"},"properties":{"oID":"2560","timestamp":"2008-02-02 20:12:32"},'
        '"type":"Feature"}}'
    )

    def test_kafka_envelope_trajectory_point(self):
        # the exact record format documented at Deserialization.java:119
        p = parse_spatial(self.KAFKA_RECORD, "GeoJSON", GRID)
        assert isinstance(p, Point)
        assert p.obj_id == "2560"
        assert p.x == pytest.approx(116.44412)
        assert p.timestamp == parse_timestamp("2008-02-02 20:12:32")
        assert p.cell >= 0

    def test_bare_geometry(self):
        p = parse_spatial('{"coordinates":[116.5,40.5],"type":"Point"}', "GeoJSON", GRID)
        assert isinstance(p, Point) and p.obj_id == ""

    @pytest.mark.parametrize("obj", [
        Point.create(116.5, 40.5, GRID, "p1", 5000),
        Polygon.create([[(116.0, 40.0), (116.1, 40.0), (116.1, 40.1)]], GRID, "poly", 5000),
        Polygon.create([[(116.0, 40.0), (116.4, 40.0), (116.4, 40.4), (116.0, 40.4)],
                        [(116.1, 40.1), (116.3, 40.1), (116.3, 40.3), (116.1, 40.3)]],
                       GRID, "donut", 5000),
        LineString.create([(116.0, 40.0), (116.2, 40.2), (116.4, 40.1)], GRID, "ls", 5000),
        MultiPoint.create([(116.0, 40.0), (116.2, 40.2)], GRID, "mpt", 5000),
        MultiPolygon.create([[[(116.0, 40.0), (116.1, 40.0), (116.1, 40.1)]],
                             [[(117.0, 41.0), (117.1, 41.0), (117.1, 41.05)]]],
                            GRID, "mp", 5000),
        MultiLineString.create([[(116.0, 40.0), (116.1, 40.1)],
                                [(116.5, 40.5), (116.6, 40.6)]], GRID, "ml", 5000),
    ])
    def test_roundtrip_all_types(self, obj):
        s = serialize_spatial(obj, "GeoJSON")
        back = parse_spatial(s, "GeoJSON", GRID, date_format=None)
        assert type(back) is type(obj)
        assert back.obj_id == obj.obj_id
        assert back.timestamp == obj.timestamp

    def test_geometrycollection_roundtrip(self):
        gc = GeometryCollection.create(
            [Point.create(116.5, 40.5), LineString.create([(116.0, 40.0), (116.1, 40.1)])],
            obj_id="gc", timestamp=99,
        )
        s = serialize_spatial(gc, "GeoJSON")
        back = parse_spatial(s, "GeoJSON", GRID, date_format=None)
        assert isinstance(back, GeometryCollection)
        assert len(back.geometries) == 2
        assert isinstance(back.geometries[0], Point)


class TestWKT:
    @pytest.mark.parametrize("obj", [
        Point.create(116.5, 40.5, GRID, "p1"),
        Polygon.create([[(116.0, 40.0), (116.1, 40.0), (116.1, 40.1)]], GRID, "poly"),
        LineString.create([(116.0, 40.0), (116.2, 40.2)], GRID, "ls"),
        MultiPoint.create([(116.0, 40.0), (116.2, 40.2)], GRID, "mpt"),
        MultiPolygon.create([[[(116.0, 40.0), (116.1, 40.0), (116.1, 40.1)]],
                             [[(117.0, 41.0), (117.1, 41.0), (117.1, 41.05)]]], GRID, "mp"),
        MultiLineString.create([[(116.0, 40.0), (116.1, 40.1)],
                                [(116.5, 40.5), (116.6, 40.6)]], GRID, "ml"),
    ])
    def test_roundtrip(self, obj):
        s = serialize_spatial(obj, "WKT")
        back = parse_spatial(s, "WKT", GRID)
        assert type(back) is type(obj)

    def test_trajectory_fields_before_geometry(self):
        p = parse_spatial("42, 1700000000123, POINT (116.5 40.5)", "WKT", GRID)
        assert p.obj_id == "42"
        assert p.timestamp == 1700000000123
        assert p.x == pytest.approx(116.5)

    def test_polygon_with_hole(self):
        wkt = "POLYGON ((0 0, 4 0, 4 4, 0 4, 0 0), (1 1, 2 1, 2 2, 1 2, 1 1))"
        poly = parse_spatial(wkt, "WKT")
        assert isinstance(poly, Polygon)
        assert len(poly.rings) == 2

    def test_geometrycollection_roundtrip(self):
        # Deserialization.java:836 (parse) / Serialization.java:682-774
        wkt = ("GEOMETRYCOLLECTION (POINT (116.5 40.5), "
               "LINESTRING (116.0 40.0, 116.1 40.1), "
               "POLYGON ((116.0 40.0, 116.1 40.0, 116.1 40.1, 116.0 40.0)))")
        gc = parse_spatial(wkt, "WKT", GRID)
        assert isinstance(gc, GeometryCollection)
        assert len(gc.geometries) == 3
        assert isinstance(gc.geometries[0], Point)
        assert gc.geometries[0].x == pytest.approx(116.5)
        assert isinstance(gc.geometries[1], LineString)
        assert isinstance(gc.geometries[2], Polygon)
        back = parse_spatial(serialize_spatial(gc, "WKT"), "WKT", GRID)
        assert isinstance(back, GeometryCollection)
        assert len(back.geometries) == 3
        assert back.geometries[0].x == pytest.approx(116.5)

    def test_wkt_serialization_preserves_fields(self):
        """serialize->parse keeps objID and timestamp: the reference's WKT
        output schemas carry both (``Serialization.java:53-96``, objID
        prefix + date suffix); we prefix-normalize so our own parser reads
        them back losslessly. Bare geometries (no fields set) stay bare."""
        poly = Polygon.create([[(1, 1), (2, 1), (2, 2), (1, 1)]], GRID,
                              obj_id="g7", timestamp=1700000000123)
        s = serialize_spatial(poly, "WKT", date_format=None)
        assert s.startswith("g7, 1700000000123, POLYGON")
        back = parse_spatial(s, "WKT", GRID, date_format=None)
        assert back.obj_id == "g7" and back.timestamp == 1700000000123
        bare = serialize_spatial(Point.create(1.0, 2.0, GRID), "WKT")
        assert bare == "POINT (1.0 2.0)"
        # empty oid + set timestamp: the oid field is emitted quoted-empty
        # so the parser cannot mis-read the timestamp as the object id
        ts_only = Point.create(1.0, 2.0, GRID, obj_id="", timestamp=12345)
        s = serialize_spatial(ts_only, "WKT", date_format=None)
        back = parse_spatial(s, "WKT", GRID, date_format=None)
        assert back.obj_id == "" and back.timestamp == 12345

    def test_geometrycollection_trajectory_fields(self):
        # trajectory variant (Deserialization.java:854): oID/time prefix fields
        gc = parse_spatial(
            "7, 1700000000123, GEOMETRYCOLLECTION (POINT (116.5 40.5))",
            "WKT", GRID)
        assert isinstance(gc, GeometryCollection)
        assert gc.obj_id == "7" and gc.timestamp == 1700000000123
        assert gc.geometries[0].obj_id == "7"

    def test_unknown_outer_keyword_raises(self):
        # round-3 silent-corruption repro: a misspelled collection keyword
        # must NOT parse its embedded POINT as a record
        with pytest.raises(ValueError):
            parse_spatial("GEOMETRYCOLECTION (POINT (116.5 40.5))", "WKT", GRID)

    def test_nested_geometrycollection(self):
        gc = parse_spatial(
            "GEOMETRYCOLLECTION (GEOMETRYCOLLECTION (POINT (1 2)), POINT (3 4))",
            "WKT")
        assert isinstance(gc, GeometryCollection)
        assert isinstance(gc.geometries[0], GeometryCollection)
        assert gc.geometries[1].x == pytest.approx(3)


class TestCoordinateStrings:
    """CSV/TSV coordinate-string geometry rows (Deserialization.java:1367-1565,
    CSVTSVToSpatialPolygon :487-516) and bracket-style CLI coordinate strings
    (HelperClass.java:145-221)."""

    def test_csv_polygon_no_keyword(self):
        line = "((116.0 40.0, 116.1 40.0, 116.1 40.1, 116.0 40.0))"
        poly = parse_spatial(line, "CSV", GRID, geometry="Polygon")
        assert isinstance(poly, Polygon)
        assert len(poly.rings) == 1 and len(poly.rings[0]) >= 3

    def test_csv_polygon_with_hole_and_prefix_fields(self):
        line = ("p1, 1700000000000, ((0 0, 4 0, 4 4, 0 4, 0 0), "
                "(1 1, 2 1, 2 2, 1 2, 1 1))")
        poly = parse_spatial(line, "CSV", geometry="Polygon")
        assert isinstance(poly, Polygon)
        assert poly.obj_id == "p1" and poly.timestamp == 1700000000000
        assert len(poly.rings) == 2

    def test_csv_multipolygon_keyword_sniff(self):
        # keyword present overrides like str.contains("MULTIPOLYGON")
        line = 'MULTIPOLYGON (((-74.15 40.62, -74.16 40.62, -74.15 40.63, -74.15 40.62)))'
        mp = parse_spatial(line, "CSV", geometry="Polygon")
        assert isinstance(mp, MultiPolygon)
        # keyword-less triple nesting promotes to multi too
        mp2 = parse_spatial("(((1 1, 2 1, 2 2, 1 1)), ((5 5, 6 5, 6 6, 5 5)))",
                            "CSV", geometry="Polygon")
        assert isinstance(mp2, MultiPolygon) and len(mp2.polygons) == 2

    def test_csv_linestring_rows(self):
        ls = parse_spatial("(116.0 40.0, 116.2 40.2)", "CSV", GRID,
                           geometry="LineString")
        assert isinstance(ls, LineString) and len(ls.coords_list) == 2
        ml = parse_spatial("((1 1, 2 2), (3 3, 4 4))", "CSV",
                           geometry="LineString")
        assert isinstance(ml, MultiLineString) and len(ml.lines) == 2

    def test_tsv_polygon_row(self):
        line = "p7\t1700000000000\t((116.0 40.0, 116.1 40.0, 116.1 40.1, 116.0 40.0))"
        poly = parse_spatial(line, "TSV", GRID, geometry="Polygon")
        assert isinstance(poly, Polygon) and poly.obj_id == "p7"

    def test_bracket_coords(self):
        from spatialflink_tpu.streams.formats import parse_bracket_coords
        pts = parse_bracket_coords("[100.0, 0.0], [103.0, 0.0], [103.0, 1.0]")
        assert pts == [(100.0, 0.0), (103.0, 0.0), (103.0, 1.0)]
        assert parse_bracket_coords(None) == []
        # malformed pairs skipped like the reference's swallowed exceptions
        assert parse_bracket_coords("[1.0, 2.0], [oops], [3.0, 4.0]") == \
            [(1.0, 2.0), (3.0, 4.0)]

    def test_bracket_rings(self):
        from spatialflink_tpu.streams.formats import parse_bracket_rings
        rings = parse_bracket_rings(
            "[[116.5, 40.5], [117.6, 40.5], [117.6, 41.4]], "
            "[[117.5, 40.5], [118.6, 40.5], [118.6, 41.4]]")
        assert len(rings) == 2 and rings[0][0] == (116.5, 40.5)

    def test_bracket_polygons(self):
        from spatialflink_tpu.streams.formats import parse_bracket_polygons
        polys = parse_bracket_polygons(
            "[[[116.5, 40.5], [117.6, 40.5], [117.6, 41.4]]] , "
            "[[[117.5, 40.5], [118.6, 40.5], [118.6, 41.4]]]")
        assert len(polys) == 2 and polys[1][0][0] == (117.5, 40.5)


class TestCSV:
    def test_schema_indices(self):
        # schema [oID, time, x, y] at positions 0..3 (Deserialization.java:313-317)
        p = parse_spatial("2560, 1202933552000, 116.44412, 39.93984", "CSV", GRID)
        assert p.obj_id == "2560" and p.timestamp == 1202933552000
        p2 = parse_spatial("116.5\t40.5\tfoo\t7", "TSV", GRID, schema=(2, None, 0, 1))
        assert p2.x == pytest.approx(116.5) and p2.obj_id == "foo"

    def test_roundtrip(self):
        p = Point.create(116.5, 40.5, GRID, "p9", 777)
        s = serialize_spatial(p, "CSV")
        back = parse_spatial(s, "CSV", GRID)
        assert back.obj_id == "p9" and back.timestamp == 777

    def test_date_format_timestamps(self):
        p = parse_spatial("a, 2008-02-02 20:12:32, 116.5, 40.5", "CSV", GRID)
        assert p.timestamp == parse_timestamp("2008-02-02 20:12:32")


class TestSources:
    def test_synthetic_deterministic(self):
        src = SyntheticPointSource(GRID, num_trajectories=5, steps=3, seed=42)
        a = [(p.obj_id, p.x, p.timestamp) for p in src]
        b = [(p.obj_id, p.x, p.timestamp) for p in src]
        assert a == b
        assert len(a) == 15
        assert a[0][0] == "traj-0"

    def test_synthetic_timestamps_advance(self):
        src = SyntheticPointSource(GRID, num_trajectories=2, steps=3, dt_ms=500)
        ts = [p.timestamp for p in src]
        assert ts[0] + 500 == ts[2] and ts[2] + 500 == ts[4]

    def test_kafka_source_clear_error(self):
        with pytest.raises(RuntimeError, match="kafka"):
            next(iter(kafka_source("topic", "localhost:9092")))

    def test_generate_query_polygons(self):
        """HelperClass.generateQueryPolygons rebuild: num cell-sized squares
        tiling from the bbox corner, deterministic, grid-assigned."""
        from spatialflink_tpu.streams.sources import generate_query_polygons

        polys = generate_query_polygons(7, GRID)
        assert len(polys) == 7
        for p in polys:
            xs = [c[0] for c in p.rings[0]]
            ys = [c[1] for c in p.rings[0]]
            # tiles are GRID-cell-sized squares (cells bucket both axes by
            # cell_length), so each covers exactly one cell
            assert max(xs) - min(xs) == pytest.approx(GRID.cell_length)
            assert max(ys) - min(ys) == pytest.approx(GRID.cell_length)
            assert p.cells  # assigned against the passed grid
        # column-major from the bbox corner, reproducible
        again = generate_query_polygons(7, GRID)
        assert [p.rings[0][0] for p in polys] == [p.rings[0][0] for p in again]
        assert polys[0].rings[0][0] == (GRID.min_x, GRID.min_y)

    def test_generate_query_polygons_capped_by_bbox(self):
        from spatialflink_tpu.index import UniformGrid
        from spatialflink_tpu.streams.sources import generate_query_polygons

        small = UniformGrid(0, 10, 0, 10, num_grid_partitions=2)
        assert len(generate_query_polygons(8, small)) == 4  # only 4 tiles fit
        flat = UniformGrid(0, 0, 0, 0, num_grid_partitions=2)
        assert generate_query_polygons(4, flat) == []  # degenerate, no hang


class TestWatermarks:
    def test_monotonic_and_lateness(self):
        wm = BoundedOutOfOrderness(allowed_lateness_ms=100)
        wm.on_event(1000)
        assert wm.watermark == 900
        wm.on_event(500)  # out-of-order does not regress the watermark
        assert wm.watermark == 900
        assert wm.is_late(800)
        assert not wm.is_late(950)


class TestWindows:
    def test_sliding_assignment(self):
        spec = WindowSpec.sliding(10_000, 5_000)
        assert spec.assign(12_000) == [10_000, 5_000]
        assert spec.assign(4_999) == [0, -5_000]

    def test_tumbling_assignment(self):
        spec = WindowSpec.tumbling(5_000)
        assert spec.assign(12_000) == [10_000]

    def test_seal_on_watermark(self):
        wa = WindowAssembler(WindowSpec.tumbling(1_000))
        sealed = list(wa.add(100, "a"))
        assert sealed == []
        sealed = list(wa.add(1_500, "b"))  # watermark 1500 seals [0,1000)
        assert len(sealed) == 1
        start, end, records = sealed[0]
        assert (start, end, records) == (0, 1_000, ["a"])

    def test_lateness_delays_sealing_and_drops(self):
        wa = WindowAssembler(WindowSpec.tumbling(1_000), allowed_lateness_ms=500)
        assert list(wa.add(100, "a")) == []
        assert list(wa.add(1_200, "b")) == []  # wm=700 < 1000: not sealed yet
        sealed = list(wa.add(1_600, "c"))      # wm=1100 seals [0,1000)
        assert len(sealed) == 1 and sealed[0][2] == ["a"]
        # a record at ts=900 is now late (wm=1100) and must be dropped
        assert list(wa.add(900, "late")) == []
        assert wa.late_dropped == 1

    def test_sliding_windows_share_records(self):
        wa = WindowAssembler(WindowSpec.sliding(10_000, 5_000))
        list(wa.add(7_000, "x"))
        out = {s: recs for s, e, recs in wa.flush()}
        assert out == {0: ["x"], 5_000: ["x"]}

    def test_end_to_end_synthetic_window_counts(self):
        src = SyntheticPointSource(GRID, num_trajectories=10, steps=20, dt_ms=1000,
                                  start_ts=1_700_000_000_000)
        wa = WindowAssembler(WindowSpec.sliding(10_000, 5_000))
        sealed = []
        for p in src:
            sealed.extend(wa.add(p.timestamp, p))
        sealed.extend(wa.flush())
        # each full window holds 10 trajectories x 10 steps
        full = [r for s, e, r in sealed if len(r) == 100]
        assert full, "expected at least one full 10s window"


class TestFormatRegressions:
    """Regressions for code-review findings on the streams layer."""

    def test_bare_multicoord_wkt_no_garbage_oid(self):
        ls = parse_spatial("LINESTRING (1 2, 3 4)", "WKT", GRID)
        assert isinstance(ls, LineString)
        assert ls.obj_id == ""
        poly = parse_spatial("POLYGON ((0 0, 1 0, 1 1, 0 0))", "WKT", GRID)
        assert poly.obj_id == ""

    def test_null_geometry_falls_back(self):
        with pytest.raises(ValueError):
            parse_spatial('{"type":"Feature","geometry":null,"properties":{"oID":"a"}}',
                          "GeoJSON", GRID)
