"""Rule 6 — thread-shared-state: lock discipline on cross-thread
classes.

The opserver, reporter, control-topic, and LiveStats threads all read —
and in the query plane's case write — state owned by the pipeline
thread. Two checks:

1. **Write discipline.** Any class that creates an instance lock in
   ``__init__`` (``self._lock = threading.Lock()/RLock()/Condition()``)
   has opted into lock-protected state; every instance-attribute write
   in its other methods must happen under ``with self._lock`` (or in a
   method documented as caller-locked: name ending ``_locked`` or a
   docstring saying the lock is held).
2. **Documented coverage.** The classes the architecture documents as
   cross-thread — ``QueryRegistry``, ``EventRing``, ``MetricsRegistry``,
   ``CheckpointCoordinator`` — must own an instance lock at all; a
   documented-shared class with no lock is a finding even before any
   write is inspected.

Reads are deliberately out of scope (GIL-atomic snapshots of ints are
this codebase's documented idiom); it is unsynchronized *writes* that
corrupt dicts and deques.
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Optional

from spatialflink_tpu.analysis.core import (Finding, ModuleSource, Rule,
                                            register)
from spatialflink_tpu.analysis.rules.common import attr_write_targets, dotted

#: classes the architecture documents as cross-thread (ARCHITECTURE.md
#: "Static invariants"); each must own an instance lock.
DOCUMENTED_CROSS_THREAD = ("QueryRegistry", "EventRing", "MetricsRegistry",
                           "CheckpointCoordinator")

_LOCK_FACTORIES = {"Lock", "RLock", "Condition"}
_HELD_DOC_MARKERS = ("lock held", "lock is held", "caller holds",
                     "holds the lock", "under the lock",
                     "caller-locked")


def _lock_attr(cls: ast.ClassDef) -> Optional[str]:
    """The instance-lock attribute name assigned in ``__init__``."""
    for meth in cls.body:
        if isinstance(meth, ast.FunctionDef) and meth.name == "__init__":
            for stmt in ast.walk(meth):
                if not isinstance(stmt, ast.Assign):
                    continue
                if not isinstance(stmt.value, ast.Call):
                    continue
                name = dotted(stmt.value.func) or ""
                if name.split(".")[-1] not in _LOCK_FACTORIES:
                    continue
                for attr, _ in attr_write_targets(stmt):
                    return attr
    return None


def _caller_locked(meth: ast.FunctionDef) -> bool:
    if meth.name.endswith("_locked"):
        return True
    doc = ast.get_docstring(meth) or ""
    low = doc.lower()
    return any(marker in low for marker in _HELD_DOC_MARKERS)


@register
class ThreadSharedStateRule(Rule):
    id = "thread-shared-state"
    contract = ("cross-thread classes own an instance lock and write "
                "instance state only while holding it")
    runtime_twin = ("liveops/queryplane concurrency tests (mid-run HTTP "
                    "mutation under --chaos)")
    severity = "error"
    scope = ("spatialflink_tpu/**",)

    def check(self, mod: ModuleSource) -> Iterator[Finding]:
        for cls in ast.walk(mod.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            lock = _lock_attr(cls)
            if lock is None:
                if cls.name in DOCUMENTED_CROSS_THREAD:
                    yield self.finding(
                        mod, cls,
                        f"{cls.name} is documented cross-thread but owns "
                        "no instance lock — give it one (writes from the "
                        "opserver/reporter/control threads race the "
                        "pipeline) or allowlist with the reviewed reason")
                continue
            yield from self._check_writes(mod, cls, lock)

    def _check_writes(self, mod: ModuleSource, cls: ast.ClassDef,
                      lock: str) -> Iterator[Finding]:
        for meth in cls.body:
            if not isinstance(meth, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            if meth.name in ("__init__", "__post_init__", "__new__") \
                    or _caller_locked(meth):
                continue
            for stmt in ast.walk(meth):
                if not isinstance(stmt, (ast.Assign, ast.AugAssign,
                                         ast.AnnAssign)):
                    continue
                for attr, node in attr_write_targets(stmt):
                    if attr == lock:
                        continue
                    if self._under_lock(mod, stmt, lock):
                        continue
                    yield self.finding(
                        mod, node,
                        f"write to self.{attr} outside `with self.{lock}` "
                        f"in lock-disciplined class {cls.name} — "
                        "cross-thread writes must hold the instance lock "
                        "(or mark the method caller-locked)")

    def _under_lock(self, mod: ModuleSource, stmt: ast.stmt,
                    lock: str) -> bool:
        for anc in mod.ancestors(stmt):
            if isinstance(anc, ast.With):
                for item in anc.items:
                    expr = item.context_expr
                    # `with self._lock:` or `with self._lock.acquire…`
                    name = dotted(expr) if not isinstance(expr, ast.Call) \
                        else dotted(expr.func)
                    if name in (f"self.{lock}", f"self.{lock}.acquire"):
                        return True
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # stop at the method boundary — a lock taken by a caller
                # is invisible here and must be declared via _locked
                return False
        return False


def documented_classes() -> List[str]:
    """Expose the documented-cross-thread list for docs/tests."""
    return list(DOCUMENTED_CROSS_THREAD)
