"""End-to-end operator pipelines on synthetic streams (the minimum slice)."""

import numpy as np
import pytest

from spatialflink_tpu.index import UniformGrid
from spatialflink_tpu.models import Point
from spatialflink_tpu.operators import (
    PointPointJoinQuery,
    PointPointKNNQuery,
    PointPointRangeQuery,
    QueryConfiguration,
    QueryType,
)
from spatialflink_tpu.streams import SyntheticPointSource
from tests import oracles as O

GRID = UniformGrid(115.50, 117.60, 39.60, 41.10, num_grid_partitions=100)
QUERY = Point.create(116.5, 40.5, GRID, obj_id="q")


def window_conf(**kw):
    return QueryConfiguration(
        query_type=QueryType.WindowBased, window_size_ms=10_000, slide_ms=5_000, **kw
    )


def source(**kw):
    defaults = dict(num_trajectories=50, steps=30, dt_ms=1000, seed=3)
    defaults.update(kw)
    return SyntheticPointSource(GRID, **defaults)


class TestRangePipeline:
    def test_window_results_match_oracle(self):
        r = 0.3
        op = PointPointRangeQuery(window_conf(), GRID)
        results = list(op.run(source(), QUERY, r))
        assert results, "no windows sealed"
        # oracle per window: replay records through the same window assembler
        from spatialflink_tpu.runtime import WindowAssembler, WindowSpec

        wa = WindowAssembler(WindowSpec.sliding(10_000, 5_000))
        windows = {}
        for p in source():
            for s, e, recs in wa.add(p.timestamp, p):
                windows[s] = recs
        for res in results:
            if res.window_start not in windows:
                continue
            recs = windows[res.window_start]
            want = set()
            gn = GRID.guaranteed_cells_mask(r, QUERY.cell)
            cn = GRID.candidate_cells_mask(r, QUERY.cell, gn)
            for p in recs:
                if p.cell >= 0 and (
                    gn[p.cell]
                    or (cn[p.cell] and O.pp_dist(p.x, p.y, QUERY.x, QUERY.y) <= r)
                ):
                    want.add((p.obj_id, p.timestamp))
            got = {(p.obj_id, p.timestamp) for p in res.records}
            boundary = {
                t for t in got ^ want
            }
            for oid, ts in boundary:
                p = next(p for p in recs if (p.obj_id, p.timestamp) == (oid, ts))
                assert abs(O.pp_dist(p.x, p.y, QUERY.x, QUERY.y) - r) < 1e-3

    def test_realtime_mode_emits(self):
        op = PointPointRangeQuery(
            QueryConfiguration(query_type=QueryType.RealTime, realtime_batch_size=128),
            GRID,
        )
        results = list(op.run(source(), QUERY, 0.5))
        assert results
        assert all(len(r.records) > 0 for r in results)

    def test_count_windows_match_deque_oracle(self):
        """CountBased range (implemented here; the reference throws "Not
        yet support", QueryType.java:6): every `slide` arrivals, the last
        `size` records evaluate — oracle is a plain deque replay of the
        same stream through the single-window evaluator semantics."""
        from collections import deque

        size, slide, r = 40, 15, 0.3
        conf = QueryConfiguration(query_type=QueryType.CountBased,
                                  window_size_ms=size, slide_ms=slide)
        recs = list(source())
        got = list(PointPointRangeQuery(conf, GRID).run(iter(recs), QUERY, r))
        # oracle
        import math

        nb_mask = GRID.neighboring_cells_mask(r, QUERY.cell)

        def within(p):
            return bool(nb_mask[p.cell]) and \
                math.hypot(p.x - QUERY.x, p.y - QUERY.y) <= r

        buf, want = deque(maxlen=size), []
        for i, p in enumerate(recs, 1):
            buf.append(p)
            if i % slide == 0:
                want.append({q.obj_id for q in buf if q.cell >= 0
                             and within(q)})
        assert len(got) == len(want) > 0
        for g, w in zip(got, want):
            assert {p.obj_id for p in g.records} == w

    def test_count_based_still_raises_for_joins(self):
        """A count trigger over two independently-arriving streams is
        ambiguous; joins (incl. the trajectory join) keep the reference's
        construction-time rejection."""
        from spatialflink_tpu.operators import (
            PointPointJoinQuery,
            PointPointTJoinQuery,
        )

        for cls in (PointPointJoinQuery, PointPointTJoinQuery):
            with pytest.raises(NotImplementedError):
                cls(QueryConfiguration(query_type=QueryType.CountBased),
                    GRID)

    def test_count_based_bulk_paths_refuse(self):
        """Bulk replay assembles EVENT-TIME windows; under count mode its
        window_spec() raises rather than silently reinterpreting counts as
        milliseconds."""
        conf = QueryConfiguration(query_type=QueryType.CountBased,
                                  window_size_ms=40, slide_ms=15)
        with pytest.raises(NotImplementedError, match="record-path only"):
            conf.window_spec()
        op = PointPointRangeQuery(conf, GRID)
        with pytest.raises(NotImplementedError, match="record-path only"):
            next(iter(op.run_multi_bulk(
                __import__("types").SimpleNamespace(interner=None),
                [QUERY], 0.3)))

    def test_incremental_matches_full(self):
        r = 0.3
        op_full = PointPointRangeQuery(window_conf(), GRID)
        op_inc = PointPointRangeQuery(window_conf(), GRID)
        full = {
            res.window_start: {(p.obj_id, p.timestamp) for p in res.records}
            for res in op_full.run(source(), QUERY, r)
        }
        inc = {
            res.window_start: {(p.obj_id, p.timestamp) for p in res.records}
            for res in op_inc.run_incremental(source(), QUERY, r)
        }
        shared = set(full) & set(inc)
        assert shared
        for s in shared:
            assert full[s] == inc[s], f"window {s} differs"


class TestKnnPipeline:
    def test_window_knn_matches_oracle(self):
        k, r = 10, 0.0  # r=0: no pruning
        op = PointPointKNNQuery(window_conf(k=k), GRID)
        results = list(op.run(source(), QUERY, r))
        assert results
        from spatialflink_tpu.runtime import WindowAssembler, WindowSpec

        wa = WindowAssembler(WindowSpec.sliding(10_000, 5_000))
        windows = {}
        for p in source():
            for s, e, recs in wa.add(p.timestamp, p):
                windows[s] = recs
        checked = 0
        for res in results:
            recs = windows.get(res.window_start)
            if not recs:
                continue
            want_ids, want_d = O.knn(
                QUERY.x, QUERY.y,
                [p.x for p in recs], [p.y for p in recs],
                [p.obj_id for p in recs], k,
            )
            got_d = [d for _, d in res.records]
            np.testing.assert_allclose(got_d, want_d, atol=1e-4)
            checked += 1
        assert checked


class TestJoinPipeline:
    def test_join_pairs_match_oracle(self):
        r = 0.05
        conf = window_conf()
        op = PointPointJoinQuery(conf, GRID)
        ordinary = list(source(seed=10, num_trajectories=40, steps=20))
        queries = list(source(seed=11, num_trajectories=10, steps=20))
        results = list(op.run(iter(ordinary), iter(queries), r))
        assert results
        total_pairs = sum(len(res.records) for res in results)
        assert total_pairs > 0
        for res in results[:3]:
            for pa, pb in res.records:
                assert O.pp_dist(pa.x, pa.y, pb.x, pb.y) <= r + 1e-3


class TestJoinRegressions:
    def test_realtime_join_emits_microbatches(self):
        conf = QueryConfiguration(query_type=QueryType.RealTime, realtime_batch_size=64)
        op = PointPointJoinQuery(conf, GRID)
        ordinary = list(source(seed=20, num_trajectories=20, steps=10))
        queries = list(source(seed=21, num_trajectories=5, steps=10))
        results = list(op.run(iter(ordinary), iter(queries), 0.5))
        assert results, "realtime join must emit per micro-batch"

    def test_realtime_join_finds_cross_batch_pairs(self):
        """A pair whose two points straddle a micro-batch boundary must be
        found: both sides keep a rolling window_size_ms buffer across batches
        (reference realtime joins buffer a full small window per stream,
        tJoin/TJoinQuery.java:216-268)."""
        conf = QueryConfiguration(query_type=QueryType.RealTime,
                                  realtime_batch_size=4, window_size_ms=60_000)
        op = PointPointJoinQuery(conf, GRID)
        t0 = 1_700_000_000_000
        far = [Point.create(115.6 + 0.01 * i, 39.7, GRID, obj_id=f"f{i}",
                            timestamp=t0 + i * 100) for i in range(4)]
        # batch 1 = far[0:3] + a; batch 2 = far[3] + b: the (a, b) pair
        # straddles the boundary
        a = Point.create(116.5, 40.5, GRID, obj_id="a", timestamp=t0 + 150)
        b = Point.create(116.5001, 40.5001, GRID, obj_id="b", timestamp=t0 + 500)
        ordinary = [far[0], far[1], far[2], a, far[3]]
        queries = [b]
        results = list(op.run(iter(ordinary), iter(queries), 0.05))
        pairs = {(pa.obj_id, pb.obj_id) for r in results for pa, pb in r.records}
        assert ("a", "b") in pairs

    def test_realtime_join_eviction_spares_in_window_pairs(self):
        """A later filler in the same micro-batch must not evict a buffered
        point that is still within window_size_ms of a new arrival: eviction
        is horizon-ed on the earliest NEW record, and pair co-residence is
        |ta - tb| <= window_size_ms."""
        conf = QueryConfiguration(query_type=QueryType.RealTime,
                                  realtime_batch_size=2, window_size_ms=1_000)
        op = PointPointJoinQuery(conf, GRID)
        t0 = 1_700_000_000_000
        a = Point.create(116.5, 40.5, GRID, obj_id="a", timestamp=t0)
        f0 = Point.create(115.6, 39.7, GRID, obj_id="x", timestamp=t0 + 50)
        b = Point.create(116.5001, 40.5001, GRID, obj_id="b",
                         timestamp=t0 + 900)
        f1 = Point.create(115.7, 39.7, GRID, obj_id="y", timestamp=t0 + 1_100)
        results = list(op.run(iter([a, f0, f1]), iter([b]), 0.05))
        pairs = {(pa.obj_id, pb.obj_id) for r in results for pa, pb in r.records}
        assert ("a", "b") in pairs

    def test_realtime_join_no_duplicate_pairs(self):
        conf = QueryConfiguration(query_type=QueryType.RealTime,
                                  realtime_batch_size=8, window_size_ms=60_000)
        op = PointPointJoinQuery(conf, GRID)
        ordinary = list(source(seed=24, num_trajectories=10, steps=8))
        queries = list(source(seed=25, num_trajectories=4, steps=8))
        results = list(op.run(iter(ordinary), iter(queries), 0.5))
        emitted = [((pa.obj_id, pa.timestamp), (pb.obj_id, pb.timestamp))
                   for r in results for pa, pb in r.records]
        assert len(emitted) == len(set(emitted)), "pair emitted twice"

    def test_realtime_join_expires_old_buffer(self):
        """Points older than window_size_ms must not pair with new arrivals."""
        conf = QueryConfiguration(query_type=QueryType.RealTime,
                                  realtime_batch_size=2, window_size_ms=1_000)
        op = PointPointJoinQuery(conf, GRID)
        t0 = 1_700_000_000_000
        a_old = Point.create(116.5, 40.5, GRID, obj_id="a", timestamp=t0)
        filler = Point.create(115.6, 39.7, GRID, obj_id="x", timestamp=t0 + 100)
        b_new = Point.create(116.5, 40.5, GRID, obj_id="b", timestamp=t0 + 5_000)
        filler2 = Point.create(115.7, 39.7, GRID, obj_id="y", timestamp=t0 + 5_100)
        results = list(op.run(iter([a_old, filler, filler2]), iter([b_new]), 0.05))
        pairs = {(pa.obj_id, pb.obj_id) for r in results for pa, pb in r.records}
        assert ("a", "b") not in pairs

    def test_one_sided_windows_are_emitted_and_freed(self):
        conf = window_conf()
        op = PointPointJoinQuery(conf, GRID)
        # query side goes quiet after the first 10 seconds
        ordinary = list(source(seed=22, num_trajectories=10, steps=40))
        queries = [p for p in source(seed=23, num_trajectories=5, steps=40)
                   if p.timestamp < ordinary[0].timestamp + 10_000]
        results = list(op.run(iter(ordinary), iter(queries), 0.5))
        starts = [r.window_start for r in results]
        # windows long after the query side stopped must still be emitted
        assert max(starts) > min(starts) + 20_000


class TestPipelinedDispatch:
    """Deferred/pipelined window dispatch must not change results or order
    (operators keep pipeline_depth windows in flight on device)."""

    def _stream(self, n=400, seed=11):
        rng = np.random.default_rng(seed)
        t0 = 1_700_000_000_000
        return [
            Point.create(float(rng.uniform(115.6, 117.5)),
                         float(rng.uniform(39.7, 41.0)), GRID,
                         obj_id=str(i % 60), timestamp=t0 + i * 100)
            for i in range(n)
        ]

    def _run(self, mk_op, depth, pts, *args):
        conf = QueryConfiguration(QueryType.WindowBased, window_size_ms=10_000,
                                  slide_ms=5_000, pipeline_depth=depth)
        op = mk_op(conf)
        return list(op.run(iter(pts), *args))

    def test_range_depth_invariant(self):
        pts = self._stream()
        q = Point.create(116.5, 40.5, GRID)
        mk = lambda conf: PointPointRangeQuery(conf, GRID)
        r1 = self._run(mk, 1, pts, q, 0.4)
        r4 = self._run(mk, 4, pts, q, 0.4)
        assert [w.window_start for w in r1] == [w.window_start for w in r4]
        for a, b in zip(r1, r4):
            assert sorted(p.obj_id for p in a.records) == \
                   sorted(p.obj_id for p in b.records)

    def test_knn_depth_invariant(self):
        pts = self._stream()
        q = Point.create(116.5, 40.5, GRID)
        from spatialflink_tpu.operators.knn_query import PointPointKNNQuery
        mk = lambda conf: PointPointKNNQuery(conf, GRID)
        r1 = self._run(mk, 1, pts, q, 0.0, 7)
        r4 = self._run(mk, 4, pts, q, 0.0, 7)
        assert [(w.window_start, w.records) for w in r1] == \
               [(w.window_start, w.records) for w in r4]

    def test_join_depth_invariant(self):
        pts = self._stream(300, seed=1)
        qs = self._stream(80, seed=2)
        from spatialflink_tpu.operators.join_query import PointPointJoinQuery
        mk = lambda conf: PointPointJoinQuery(conf, GRID, GRID)
        r1 = self._run(mk, 1, pts, iter(qs), 0.25)
        r4 = self._run(mk, 4, pts, iter(qs), 0.25)
        assert [w.window_start for w in r1] == [w.window_start for w in r4]
        key = lambda w: sorted((a.obj_id, b.obj_id) for a, b in w.records)
        for a, b in zip(r1, r4):
            assert key(a) == key(b)
            assert isinstance(a.records, list)

    def test_geom_join_depth_invariant_exercises_deferred(self):
        # _GenericStreamJoin is the path that returns Deferred lattices
        from spatialflink_tpu.models import Polygon
        from spatialflink_tpu.operators.join_query import PointGeomJoinQuery

        pts = self._stream(300, seed=3)
        rng = np.random.default_rng(4)
        t0 = 1_700_000_000_000
        polys = []
        for i in range(40):
            cx = float(rng.uniform(115.8, 117.3))
            cy = float(rng.uniform(39.8, 40.9))
            polys.append(Polygon.create(
                [[(cx, cy), (cx + .05, cy), (cx + .05, cy + .05),
                  (cx, cy + .05), (cx, cy)]], GRID,
                obj_id=f"p{i}", timestamp=t0 + i * 500))
        mk = lambda conf: PointGeomJoinQuery(conf, GRID, GRID)
        r1 = self._run(mk, 1, pts, iter(polys), 0.2)
        r4 = self._run(mk, 4, pts, iter(polys), 0.2)
        assert [w.window_start for w in r1] == [w.window_start for w in r4]
        key = lambda w: sorted((a.obj_id, b.obj_id) for a, b in w.records)
        for a, b in zip(r1, r4):
            assert key(a) == key(b)
            assert isinstance(a.records, list)  # materialized before yield
