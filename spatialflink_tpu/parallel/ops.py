"""Distributed window kernels via shard_map + XLA collectives.

Each op shards the window batch's point dimension across the mesh, runs the
single-device kernel (spatialflink_tpu.ops) per shard, and merges partials
with collectives:

- kNN: per-shard dedup+top-k, then ``all_gather`` of the k-sized partials and
  a final re-top-k — a tree merge on ICI replacing the reference's
  parallelism-1 ``windowAll`` stage (``knn/PointPointKNNQuery.java:188-190``).
  Per-device traffic is O(k * n_devices), independent of window size.
- range: per-shard masked filter + ``psum`` count.
- join: the a-side is sharded, the (smaller) query side replicated — the
  broadcast-join layout, matching the reference's query-stream replication
  (``join/JoinQuery.java:72-90``) without materializing copies.

The shard bodies call the same kernels used single-device (jit-in-jit), so
eligibility/distance semantics cannot fork between the two paths.

All functions are jit-compatible and run under a ``jax.sharding.Mesh`` of any
size; they are exercised on an 8-device virtual CPU mesh in tests and
dry-run-compiled by ``__graft_entry__.dryrun_multichip``.

Device-truth coverage contract: this module deliberately has NO raw
``jax.jit`` sites (enforced by the ``TestJitCoverage`` AST meta-test in
tier-1). The per-shard bodies are closures over the module-level
``instrumented_jit`` kernels imported from ``spatialflink_tpu.ops`` —
their registry hooks live inside the traced bodies, so a fresh shard_map
trace that misses the inner jaxpr cache feeds the compile registry
(``utils.deviceplane``) exactly like a single-device compile, and the
recompile sentinel sees multichip recompiles through the same inner
entries. Wrapping the per-call ``shard_map`` closures themselves in
``instrumented_jit`` would register a fresh entry per invocation
(closure identity churn) and corrupt the per-function compile counters —
don't.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from spatialflink_tpu.models.batches import PointBatch
from spatialflink_tpu.ops.join import join_mask
from spatialflink_tpu.ops.knn import KnnResult, knn_point, topk_by_distance
from spatialflink_tpu.ops.range import range_filter_point
from spatialflink_tpu.parallel.mesh import CELL_AXIS, DCN_AXIS

def _compat_shard_map():
    """jax.shard_map across jax versions: < 0.5 ships it under
    experimental, and the replication-check kwarg was named check_rep
    before the check_vma rename — keyed on the actual signature, not the
    attribute location, so the middle range (top-level fn, old kwarg) works
    too."""
    import functools
    import inspect

    fn = getattr(jax, "shard_map", None)
    if fn is None:
        from jax.experimental.shard_map import shard_map as fn
    try:
        if "check_vma" in inspect.signature(fn).parameters:
            return fn
    except (TypeError, ValueError):  # uninspectable: assume current API
        return fn

    @functools.wraps(fn)
    def renamed(*args, **kwargs):
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        return fn(*args, **kwargs)

    return renamed


shard_map = _compat_shard_map()


def distributed_knn(
    mesh: Mesh,
    points: PointBatch,
    qx,
    qy,
    q_cell,
    radius,
    nb_layers,
    *,
    n: int,
    k: int,
    enforce_radius: bool = False,
    strategy: str = "auto",
) -> KnnResult:
    """kNN over a batch sharded on the point dim; result replicated.

    ``strategy`` is threaded to the per-shard ``knn_point_stats`` so
    approximate mode (``approx``) behaves the same at any parallelism; the
    re-merge is exact top-k over the k-sized partials either way. Thin facade
    over :func:`distributed_stream_knn` (one implementation of the
    gather+re-merge for every stream type)."""
    from spatialflink_tpu.ops.knn import knn_point_stats

    def local(pts: PointBatch):
        return knn_point_stats(
            pts, qx, qy, q_cell, radius, nb_layers,
            n=n, k=k, enforce_radius=enforce_radius, strategy=strategy)

    res, _evals = distributed_stream_knn(
        mesh, points, k=k, strategy=strategy, local_fn=local)
    return res


def distributed_knn_hierarchical(
    mesh: Mesh,
    points: PointBatch,
    qx,
    qy,
    q_cell,
    radius,
    nb_layers,
    *,
    n: int,
    k: int,
    enforce_radius: bool = False,
    strategy: str = "auto",
) -> KnnResult:
    """kNN over a 2-D (DCN_AXIS, CELL_AXIS) mesh with a two-level merge.

    The window's point dim is sharded over both axes. Each chip computes its
    local dedup+top-k; the first merge all-gathers k-sized partials *within*
    a slice (ICI — cheap), the second all-gathers one k-sized partial *per
    slice* across hosts (DCN — k * n_hosts elements total, independent of
    window size). This is the multi-host shape of the reference's two-stage
    local-top-k -> global-merge plan (SURVEY §2.5) without its parallelism-1
    global stage.
    """

    def per_shard(pts: PointBatch) -> KnnResult:
        local = knn_point(
            pts, qx, qy, q_cell, radius, nb_layers,
            n=n, k=k, enforce_radius=enforce_radius, strategy=strategy,
        )
        # level 1 across the slice (ICI), level 2 per-slice partials across
        # hosts (DCN) — ONE merge implementation (_gather_topk) shared with
        # distributed_stream_knn's 2-D path
        return _gather_topk(_gather_topk(local, CELL_AXIS, k), DCN_AXIS, k)

    fn = shard_map(
        per_shard,
        mesh=mesh,
        check_vma=False,
        in_specs=(P((DCN_AXIS, CELL_AXIS)),),
        out_specs=KnnResult(P(), P(), P()),
    )
    return fn(points)


def distributed_range_count(
    mesh: Mesh,
    points: PointBatch,
    qx,
    qy,
    q_cell,
    radius,
    gn_layers,
    cn_layers,
    *,
    n: int,
    approximate: bool = False,
):
    """Range-query match count with a psum merge; (count, mask_sharded)."""

    def per_shard(pts: PointBatch):
        mask, _dists = range_filter_point(
            pts, qx, qy, q_cell, radius, gn_layers, cn_layers,
            n=n, approximate=approximate,
        )
        count = jax.lax.psum(jnp.sum(mask, dtype=jnp.int32), CELL_AXIS)
        return count, mask

    fn = shard_map(
        per_shard,
        mesh=mesh,
        check_vma=False,
        in_specs=(P(CELL_AXIS),),
        out_specs=(P(), P(CELL_AXIS)),
    )
    return fn(points)


def distributed_join_mask(
    mesh: Mesh,
    a: PointBatch,
    b: PointBatch,
    radius,
    nb_layers,
    center_x,
    center_y,
    *,
    n: int,
):
    """Broadcast join returning the full (Na, Nb) boolean pair lattice,
    sharded on the a (point) dim — the record-output form operators need
    (``distributed_join_counts`` is the count-only reduction). The a side is
    sharded, the (smaller) query side replicated; no collective is required
    for the lattice itself, so each device owns its row block."""

    return distributed_stream_join_lattice(
        mesh, a, b,
        lambda a_s, b_r: join_mask(a_s, b_r, radius, nb_layers,
                                   center_x, center_y, n=n))


def _point_axes(mesh: Mesh):
    """The point-dim sharding axes of ``mesh``: ``(CELL_AXIS,)`` for the 1-D
    mesh, ``(DCN_AXIS, CELL_AXIS)`` for the 2-D hybrid — single source for
    the stream ops' specs/collectives so every op accepts either shape."""
    return tuple(mesh.axis_names)


def distributed_stream_filter(mesh: Mesh, batch, mask_stats_fn):
    """Geometry/point STREAM filter over the mesh (the missing mesh dispatch
    for PointGeom/GeomPoint/GeomGeom range — every reference pipeline runs at
    parallelism 30, ``StreamingJob.java:221``).

    ``batch`` (any pytree whose leaves share the sharded leading dim) is
    sharded on that dim; ``mask_stats_fn(shard) -> (mask, gn_bypassed,
    dist_evals)`` runs the SAME single-device kernels per shard (closure over
    replicated query-side arrays), so semantics cannot fork between the two
    paths; the pruning stats are psum-merged. Returns (mask_sharded,
    gn_total, evals_total) — embarrassingly parallel on the mask, one scalar
    collective for the counters. Accepts 1-D and 2-D (hosts x chips) meshes.
    """
    return _stream_filter_impl(mesh, batch, mask_stats_fn,
                               lambda axes: P(axes))


def distributed_stream_filter_multi(mesh: Mesh, batch, multi_mask_stats):
    """Multi-query stream filter over the mesh: ``multi_mask_stats(shard) ->
    (masks (Q, n_shard), gn (Q,), evals (Q,))`` runs the SAME vmapped
    single-device kernels per shard (closures over replicated query-side
    stacks); per-query pruning counters psum-merge. Returns
    (masks (Q, N) sharded on the point dim, gn totals (Q,), evals totals
    (Q,)). Accepts 1-D and 2-D meshes."""
    return _stream_filter_impl(mesh, batch, multi_mask_stats,
                               lambda axes: P(None, axes))


def _stream_filter_impl(mesh: Mesh, batch, stats_fn, mask_spec):
    """Shared shard_map wiring for the single- and multi-query stream
    filters — they differ only in where the sharded point dim sits in the
    mask output (leading vs after the query axis)."""
    axes = _point_axes(mesh)

    def per_shard(b):
        mask, gn, evals = stats_fn(b)
        return (mask, jax.lax.psum(gn, axes), jax.lax.psum(evals, axes))

    fn = shard_map(
        per_shard,
        mesh=mesh,
        check_vma=False,
        in_specs=(P(axes),),
        out_specs=(mask_spec(axes), P(), P()),
    )
    return fn(batch)


def distributed_stream_knn(mesh: Mesh, batch, elig_dist_fn=None, *, k: int,
                           strategy: str = "auto", local_fn=None):
    """Geometry/point STREAM kNN over the mesh: per-shard local dedup+top-k,
    all-gather of the k-sized partials, re-top-k — the generic-stream twin of
    :func:`distributed_knn` (kills the reference's parallelism-1 ``windowAll``
    for the polygon/linestring pairs too). Returns (KnnResult replicated,
    dist_evals total) with the candidate count psum-merged for the pruning
    counter.

    Per-shard compute goes through the SAME module-level jitted kernels the
    single-device paths use — ``local_fn(shard) -> (KnnResult, count)``
    (e.g. a ``knn_point_stats`` closure) or ``elig_dist_fn(shard) ->
    (eligible, dists)`` fed into ``knn_eligible_stats`` — so XLA fuses the
    distance math identically in both paths and the 8-dev ≡ 1-dev parity is
    bit-for-bit, not just approximate. The re-merge is value-preserving
    (top-k selects, never recomputes), so merged distances are exact copies
    of per-shard results.
    """
    from spatialflink_tpu.ops.knn import knn_eligible_stats

    def local(b):
        if local_fn is not None:
            return local_fn(b)
        eligible, dists = elig_dist_fn(b)
        return knn_eligible_stats(b.obj_id, dists, eligible,
                                  k=k, strategy=strategy)

    return _stream_knn_impl(mesh, batch, local, k, _gather_topk)


def distributed_stream_knn_multi(mesh: Mesh, batch, local_fn, *, k: int):
    """Multi-query stream kNN over the mesh: ``local_fn(shard) ->
    (KnnResult (Q, k), evals (Q,))`` is the vmapped single-device kernel
    closure; per-shard (Q, k) partials all-gather and re-top-k per query
    (two-level on a 2-D mesh — DCN traffic is Q * k * hosts, window-size
    independent). Returns (KnnResult (Q, k) replicated, evals totals (Q,))."""
    return _stream_knn_impl(mesh, batch, local_fn, k, _gather_topk_multi)


def _stream_knn_impl(mesh: Mesh, batch, local_fn, k: int, gather):
    """Shared shard_map wiring for the single- and multi-query stream kNN —
    they differ only in the partial shape ((k,) vs (Q, k)) and hence the
    gather-merge helper."""
    axes = _point_axes(mesh)

    def per_shard(b):
        local, n_elig = local_fn(b)
        # level 1: merge k-sized partials across the slice (ICI axis)
        merged = gather(local, CELL_AXIS, k)
        if DCN_AXIS in axes:
            # level 2 (2-D mesh): one k-sized partial per slice across
            # hosts — DCN traffic is k * n_hosts (* Q for multi),
            # window-size independent (the hierarchical merge of
            # distributed_knn_hierarchical, available to every stream type
            # through the operator path)
            merged = gather(merged, DCN_AXIS, k)
        return merged, jax.lax.psum(n_elig, axes)

    fn = shard_map(
        per_shard,
        mesh=mesh,
        check_vma=False,
        in_specs=(P(axes),),
        out_specs=(KnnResult(P(), P(), P()), P()),
    )
    return fn(batch)


def _gather_topk(partial: KnnResult, axis_name: str, k: int) -> KnnResult:
    """all-gather k-sized per-shard partials over one mesh axis and re-top-k
    (value-preserving: selection only, distances are exact copies)."""
    return topk_by_distance(
        jax.lax.all_gather(partial.obj_id, axis_name).reshape(-1),
        jax.lax.all_gather(partial.dist, axis_name).reshape(-1),
        jax.lax.all_gather(partial.valid, axis_name).reshape(-1),
        k)


def _gather_topk_multi(partial: KnnResult, axis_name: str, k: int
                       ) -> KnnResult:
    """:func:`_gather_topk` for (Q, k) partials: all-gather over the mesh
    axis gives (D, Q, k); re-top-k per query over the D*k merged candidates.
    The merge operands are tiny (devices * k), so a vmapped full sort is the
    right selection — no cond, value-preserving."""
    def gather(x):
        g = jax.lax.all_gather(x, axis_name)         # (D, Q, k)
        return jnp.moveaxis(g, 0, 1).reshape(g.shape[1], -1)  # (Q, D*k)

    oid, dist, valid = (gather(partial.obj_id), gather(partial.dist),
                        gather(partial.valid))
    return jax.vmap(
        lambda o, d, v: topk_by_distance(o, d, v, k, strategy="sort")
    )(oid, dist, valid)


def distributed_stream_join_lattice(mesh: Mesh, a, b, lattice_fn):
    """Generic broadcast join for the geometry pairs: the a side (any batch
    pytree) sharded on its leading dim, the query side replicated;
    ``lattice_fn(a_shard, b) -> (rows, Nb) bool`` runs the same pair-lattice
    kernel as single-device (``join_point_geom_mask`` /
    ``join_geom_geom_mask``). No collective — each device owns its row
    block, mirroring :func:`distributed_join_mask` for PointPoint."""

    def per_shard(a_shard, b_rep):
        return lattice_fn(a_shard, b_rep)

    axes = _point_axes(mesh)
    fn = shard_map(
        per_shard,
        mesh=mesh,
        check_vma=False,
        in_specs=(P(axes), P()),
        out_specs=P(axes),
    )
    return fn(a, b)


def distributed_join_counts(
    mesh: Mesh,
    a: PointBatch,
    b: PointBatch,
    radius,
    nb_layers,
    center_x,
    center_y,
    *,
    n: int,
):
    """Broadcast join: a sharded, b replicated; per-a counts + psum total."""

    def per_shard(a_shard: PointBatch, b_rep: PointBatch):
        m = join_mask(a_shard, b_rep, radius, nb_layers, center_x, center_y, n=n)
        per_a = jnp.sum(m, axis=1, dtype=jnp.int32)
        total = jax.lax.psum(jnp.sum(per_a), CELL_AXIS)
        return per_a, total

    fn = shard_map(
        per_shard,
        mesh=mesh,
        check_vma=False,
        in_specs=(P(CELL_AXIS), P()),
        out_specs=(P(CELL_AXIS), P()),
    )
    return fn(a, b)


def _gather_shard_major(x, axes):
    """all_gather a per-shard array over the mesh's point axes into
    shard-major order matching the batch's contiguous sharding: outer (DCN)
    axis major, inner (ICI) axis minor — ``(D, *x.shape)``."""
    g = jax.lax.all_gather(x, CELL_AXIS)              # (n_cell, ...)
    if DCN_AXIS in axes:
        g = jax.lax.all_gather(g, DCN_AXIS)           # (n_dcn, n_cell, ...)
        g = g.reshape((-1,) + g.shape[2:])
    return g


def distributed_taggregate(mesh: Mesh, batch, *, num_cells: int, agg: str):
    """Windowed tAggregate over the mesh (``TAggregateQuery.java:53-377``):
    per-shard (cell, objID) group EXTENTS — the mergeable form; a length is
    not, since a group split at a shard boundary must merge [min_ts, max_ts]
    before measuring — then an all-gather of the shard representatives and
    a replicated extent-merge re-sort. ``agg='ALL'`` returns the merged
    :class:`TAggregateGroups` (size N, replicated — the same shape the
    single-device path extracts records from); other aggregates return the
    dense (num_cells,) heatmap, replicated."""
    from spatialflink_tpu.ops.trajectory import (_OID_SENTINEL, INT32_MIN,
                                                 taggregate_group_extents,
                                                 taggregate_heatmap,
                                                 taggregate_merge_extents)

    axes = _point_axes(mesh)
    int32_max = jnp.iinfo(jnp.int32).max

    def per_shard(b):
        e = taggregate_group_extents(b, num_cells=num_cells)
        # blank non-representatives so only one extent row per local group
        # survives the gather (sentinels sort last in the merge)
        cell = jnp.where(e.first, e.cell, num_cells)
        oid = jnp.where(e.first, e.obj_id, _OID_SENTINEL)
        mn = jnp.where(e.first, e.min_ts, int32_max)
        mx = jnp.where(e.first, e.max_ts, INT32_MIN)
        merged = taggregate_merge_extents(
            _gather_shard_major(cell, axes).reshape(-1),
            _gather_shard_major(oid, axes).reshape(-1),
            _gather_shard_major(mn, axes).reshape(-1),
            _gather_shard_major(mx, axes).reshape(-1),
            num_cells=num_cells)
        if agg == "ALL":
            return merged
        return taggregate_heatmap(merged, num_cells=num_cells, agg=agg)

    from spatialflink_tpu.ops.trajectory import TAggregateGroups

    out_spec = (TAggregateGroups(P(), P(), P(), P())
                if agg == "ALL" else P())
    fn = shard_map(
        per_shard,
        mesh=mesh,
        check_vma=False,
        in_specs=(P(axes),),
        out_specs=out_spec,
    )
    return fn(batch)


def distributed_tstats_window(mesh: Mesh, batch, *, m: int):
    """Windowed tStats over the mesh (``TStatsQuery.java:153-197``): the
    window must be globally (objID, ts)-sorted and deduplicated BEFORE
    contiguous sharding (the operator does this host-side), so each shard
    summarizes a contiguous slice of every trajectory's run and the
    replicated stitch adds exactly the boundary pairs the single-device
    sorted cumsum would have linked. Returns (spatial (M,), temporal (M,)
    i32 ms, count (M,)), replicated; trajectories emit iff count >= 2."""
    from spatialflink_tpu.ops.trajectory import (tstats_stitch_summaries,
                                                 tstats_window_summary)

    axes = _point_axes(mesh)

    def per_shard(b):
        s = tstats_window_summary(b, m=m)
        # tree-map preserves the NamedTuple structure: (D, M) tables
        tabs = jax.tree.map(lambda x: _gather_shard_major(x, axes), s)
        return tstats_stitch_summaries(tabs)

    fn = shard_map(
        per_shard,
        mesh=mesh,
        check_vma=False,
        in_specs=(P(axes),),
        out_specs=(P(), P(), P()),
    )
    return fn(batch)
