"""Attribute the headline kNN window's latency: device time vs dispatch.

Round-3 VERDICT weak #2: the 67.8ms TPU p50 was never attributed (device
compute vs axon-tunnel RTT). This script measures, for one 1M-point kNN
(k=50) window on the current backend:

- per-window DEVICE time via the slope method (index-dependent fori_loop at
  two iteration counts — fixed dispatch overhead cancels);
- single-window WALL time (dispatch -> readback, what a realtime caller
  sees);
- their difference = per-dispatch overhead (tunnel RTT + host sync);

and optionally captures a ``jax.profiler`` trace of one window when
``SPATIALFLINK_PROFILE_DIR`` is set. Prints one JSON line.

Usage: python benchmarks/profile_knn.py [strategy]
"""

from __future__ import annotations

import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    strategy = sys.argv[1] if len(sys.argv) > 1 else "auto"
    n_points, k, radius = 1_000_000, 50, 0.5

    import jax
    import jax.numpy as jnp

    from spatialflink_tpu.index import UniformGrid
    from spatialflink_tpu.models import PointBatch
    from spatialflink_tpu.ops.knn import knn_point

    grid = UniformGrid(115.50, 117.60, 39.60, 41.10, num_grid_partitions=100)
    rng = np.random.default_rng(0)
    batch = jax.device_put(PointBatch.from_arrays(
        rng.uniform(grid.min_x, grid.max_x, n_points),
        rng.uniform(grid.min_y, grid.max_y, n_points),
        grid=grid,
        obj_id=rng.integers(0, n_points // 4, n_points).astype(np.int32)))
    qx, qy = 116.5, 40.5
    qc = jnp.int32(grid.assign_cell(qx, qy)[0])
    layers = grid.candidate_layers(radius)

    @jax.jit
    def run_n(b, iters):
        def body(i, acc):
            r = knn_point(b, qx + i * 1e-7, qy, qc, radius, layers,
                          n=grid.n, k=k, strategy=strategy)
            return acc + r.dist[0]
        return jax.lax.fori_loop(0, iters, body, jnp.float32(0))

    # the escalating slope helper lives in bench_configs (same directory);
    # run_n(b, iters) already matches its dynamic-iters contract
    from bench_configs import _slope_time, _p50_latency_ms

    device_ms = _slope_time(lambda it: run_n(batch, it), lo=2, hi=42) * 1e3

    win = jax.jit(lambda b: knn_point(b, qx, qy, qc, radius, layers,
                                      n=grid.n, k=k, strategy=strategy))
    wall_ms = _p50_latency_ms(lambda: win(batch), n=11)

    prof_dir = os.environ.get("SPATIALFLINK_PROFILE_DIR")
    if prof_dir:
        from spatialflink_tpu.utils.metrics import profile_to

        with profile_to(prof_dir):
            jax.block_until_ready(win(batch))

    print(json.dumps({
        "backend": jax.default_backend(),
        "strategy": strategy,
        "device_ms_per_window": round(device_ms, 3),
        "p50_wall_ms_per_window": round(wall_ms, 3),
        "dispatch_overhead_ms": round(wall_ms - device_ms, 3),
        "note": ("wall - device = per-dispatch overhead (tunnel RTT + host "
                 "sync); a streaming pipeline with pipeline_depth>=2 pays "
                 "device time only"),
        "trace_dir": prof_dir,
    }))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
