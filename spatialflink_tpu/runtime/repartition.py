"""Runtime repartition controller: epoch-based split/merge decisions for the
skew-adaptive grid, driven by the live occupancy signal (and, when a
telemetry session is active, by PR 6's per-cell ATTRIBUTED kernel cost — a
hot cell's records make every window containing them expensive, so cost is
the sharper trigger than raw counts).

Design points:

- The controller observes base-cell assignments through the SAME module
  hook telemetry uses (``index.uniform_grid._CELL_OBSERVER``), CHAINED so
  both consumers see one pass — it costs one extra bincount per decoded
  chunk, nothing per record.
- Decisions are EPOCH-based (every ``interval_records`` observed records)
  with HYSTERESIS: a cell splits when its epoch share crosses
  ``split_share``; a split cell merges back only after its share has
  stayed below ``merge_share`` (< split_share) for ``cooldown_epochs``
  consecutive epochs — the split/merge thresholds are deliberately far
  apart so a cell oscillating around one threshold cannot thrash the
  layout. Cold ``coarsen x coarsen`` neighborhoods coarsen under the same
  cooldown discipline.
- Every applied change bumps the grid's monotonic ``version`` (operators
  key their cached per-query leaf masks on it), emits a ``repartition``
  lifecycle event into the :class:`~spatialflink_tpu.utils.telemetry
  .EventRing`, and bumps the ``repartitions``/``grid-splits``/
  ``grid-merges`` registry counters.
- Correctness does not depend on WHEN (or whether) an epoch fires: the
  adaptive masks are a sound over-approximation for every layout, so a
  repartition can never change a window's result set — only how much work
  the pre-kernel prefilter saves. The mid-run identity tests
  (``tests/test_repartition.py``) pin this, including under ``--chaos``
  and across a checkpoint/resume straddling a repartition.
- The layout is a coordinated-checkpoint participant (component ``grid``):
  ``--resume`` restores the adapted partitioning and version; epoch
  counters deliberately restart (they re-warm within one interval).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Callable, List, Optional

import numpy as np

from spatialflink_tpu.index import AdaptiveGrid
from spatialflink_tpu.index import uniform_grid as _ug

#: the one controller the current process runs (driver-installed); lets the
#: opserver's /partition endpoint and in-process tooling find it without
#: plumbing (same pattern as opserver.active_server)
_ACTIVE: Optional["RepartitionController"] = None


def active_controller() -> Optional["RepartitionController"]:
    """The process's installed :class:`RepartitionController`, or None."""
    return _ACTIVE


@dataclass
class RepartitionPolicy:
    """Split/merge thresholds. Shares are fractions of the records observed
    in ONE epoch; hysteresis = the split and merge thresholds are far apart
    AND merges/coarsens wait out ``cooldown_epochs`` below threshold."""

    #: split a base cell when its epoch record share reaches this
    split_share: float = 0.05
    #: merge a split cell back once its share stays below this (must be
    #: well under split_share — the hysteresis band)
    merge_share: float = 0.0125
    #: consecutive cold epochs before a merge / un-coarsen applies
    cooldown_epochs: int = 2
    #: cap on concurrently split cells (each costs refine^2 leaves)
    max_splits: int = 64
    #: coarsen an aligned block when the whole block's epoch share is below
    #: this (default: a nearly-empty block — ~5 records per 50k-record
    #: epoch); 0 disables coarsening
    coarsen_share: float = 0.0001
    #: un-coarsen when the block's share reaches this (hysteresis twin)
    uncoarsen_share: float = 0.002
    #: ignore epochs with fewer observed records than this (no signal)
    min_epoch_records: int = 256
    #: blend weight of ATTRIBUTED COST share vs record share in the split
    #: score when a telemetry session provides per-cell cost (0 = counts
    #: only, 1 = cost only); cost is the sharper skew signal (PR 6)
    cost_weight: float = 0.5

    def validate(self) -> "RepartitionPolicy":
        if not 0 < self.merge_share < self.split_share <= 1:
            raise ValueError(
                f"need 0 < merge_share ({self.merge_share}) < split_share "
                f"({self.split_share}) <= 1 (the hysteresis band)")
        if self.coarsen_share and not (0 <= self.coarsen_share
                                       < self.uncoarsen_share):
            raise ValueError(
                f"need coarsen_share ({self.coarsen_share}) < "
                f"uncoarsen_share ({self.uncoarsen_share})")
        return self


class RepartitionController:
    """Feeds base-cell observations into epoch split/merge decisions on an
    :class:`AdaptiveGrid`. Thread-safe enough for its consumers: the
    observe path runs on the pipeline thread; ``status()`` (the
    ``/partition`` endpoint) reads under the same lock the epoch mutates
    under."""

    def __init__(self, grid: AdaptiveGrid,
                 interval_records: int = 50_000,
                 policy: Optional[RepartitionPolicy] = None,
                 coarsen: bool = True):
        self.grid = grid
        self.interval_records = max(1, int(interval_records))
        self.policy = (policy or RepartitionPolicy()).validate()
        self.coarsen_enabled = bool(coarsen) and self.policy.coarsen_share > 0
        n2 = grid.n * grid.n
        self._counts = np.zeros(n2, np.int64)
        self._since = 0
        self.epochs = 0
        self.repartitions = 0
        #: consecutive epochs each split cell spent below merge_share
        self._cold_epochs: dict = {}
        #: consecutive epochs each block spent below coarsen_share
        self._block_cold: dict = {}
        #: recent decisions, newest last (the /partition event tail)
        self.decisions: List[dict] = []
        self._lock = threading.Lock()
        self._restore_observer: Optional[Callable[[], None]] = None

    # ------------------------------------------------------------------ #
    # observation

    def install(self) -> "RepartitionController":
        """Chain onto the grid-cell observer hook (shared with telemetry's
        occupancy/cost recorders) and become the process's active
        controller. :meth:`uninstall` restores both."""
        global _ACTIVE
        prev = _ug._CELL_OBSERVER
        note = self.note_cells

        def observe(cells) -> None:
            if prev is not None:
                prev(cells)
            note(cells)

        _ug._CELL_OBSERVER = observe

        def restore() -> None:
            global _ACTIVE
            _ug._CELL_OBSERVER = prev
            if _ACTIVE is self:
                _ACTIVE = None

        with self._lock:
            self._restore_observer = restore
        _ACTIVE = self
        return self

    def uninstall(self) -> None:
        with self._lock:
            restore_fn = self._restore_observer
            self._restore_observer = None
        if restore_fn is not None:
            restore_fn()

    def note_cells(self, cells) -> None:
        """One decoded chunk's base-cell ids (any shape; -1 = outside the
        grid). Accumulates the epoch bincount and fires :meth:`epoch` when
        the interval fills — on the pipeline thread, between chunks, so a
        layout change can never interleave with a window evaluation."""
        c = np.asarray(cells).ravel()
        c = c[(c >= 0) & (c < self._counts.size)]
        if c.size == 0:
            return
        with self._lock:
            self._counts += np.bincount(c, minlength=self._counts.size)
            self._since += int(c.size)
            due = self._since >= self.interval_records
        if due:
            self.epoch()

    def request_epoch(self) -> None:
        """Arm the next observation to close the epoch early — the chunk
        governor's sustained-stall escalation (ROADMAP item 3's residual:
        stall events trigger repartition epochs, not just shedding).
        Only the interval clock is touched: the epoch itself still fires
        from :meth:`note_cells` on the pipeline thread, between chunks —
        the one place a layout change cannot interleave with a window
        evaluation."""
        with self._lock:
            self._since = max(self._since, self.interval_records)

    # ------------------------------------------------------------------ #
    # decisions

    def _cost_shares(self) -> Optional[np.ndarray]:
        """Per-base-cell attributed-cost shares from the active telemetry
        session's :class:`~spatialflink_tpu.utils.telemetry.CostProfiles`,
        or None without a session / without attributed cost yet. Cumulative
        (not per-epoch) — cost ratchets toward persistently hot cells,
        which is the right bias for a split decision."""
        from spatialflink_tpu.utils import telemetry as _telemetry

        tel = _telemetry.active()
        if tel is None:
            return None
        cost = tel.costs.cell_costs(self._counts.size)
        total = float(cost.sum())
        if total <= 0:
            return None
        return cost / total

    def epoch(self) -> bool:
        """Close one epoch: evaluate split/merge/coarsen with hysteresis and
        apply the new layout. Returns True when the layout changed."""
        p = self.policy
        with self._lock:
            counts = self._counts
            total = int(counts.sum())
            self._counts = np.zeros_like(counts)
            self._since = 0
            self.epochs += 1
            epoch_no = self.epochs
        # the no-signal floor clamps to the epoch interval: a deliberately
        # small --repartition-interval must still make decisions
        if total < min(p.min_epoch_records, self.interval_records):
            return False
        shares = counts / total
        cost = self._cost_shares()
        if cost is not None and p.cost_weight > 0:
            score = (1 - p.cost_weight) * shares + p.cost_weight * cost
        else:
            score = shares

        splits = set(self.grid.split_cells())
        # merges first (cooldown): a split cell cold for cooldown epochs
        # merges back to base granularity
        merged = []
        for cell in sorted(splits):
            if shares[cell] < p.merge_share:
                self._cold_epochs[cell] = self._cold_epochs.get(cell, 0) + 1
                if self._cold_epochs[cell] >= p.cooldown_epochs:
                    splits.discard(cell)
                    merged.append(int(cell))
                    self._cold_epochs.pop(cell, None)
            else:
                self._cold_epochs.pop(cell, None)
        # splits: hottest first, capped
        new_splits = []
        for cell in np.argsort(score)[::-1]:
            if len(splits) >= p.max_splits:
                break
            if score[cell] < p.split_share:
                break
            if int(cell) not in splits:
                splits.add(int(cell))
                new_splits.append(int(cell))
                self._cold_epochs.pop(int(cell), None)

        # coarsen/un-coarsen cold neighborhoods (block lattice sums)
        blocks = set(self.grid.coarse_blocks())
        coarsened, uncoarsened = [], []
        if self.coarsen_enabled:
            n, c = self.grid.n, self.grid.coarsen
            nb = -(-n // c)
            grid2d = shares.reshape(n, n)
            pad = nb * c
            padded = np.zeros((pad, pad))
            padded[:n, :n] = grid2d
            block_share = padded.reshape(nb, c, nb, c).sum(axis=(1, 3))
            for bx in range(nb):
                for by in range(nb):
                    key = (bx, by)
                    members = self.grid._block_members(bx, by)
                    if any(m in splits for m in members):
                        blocks.discard(key)
                        self._block_cold.pop(key, None)
                        continue
                    s = float(block_share[bx, by])
                    if key in blocks:
                        if s >= p.uncoarsen_share:
                            blocks.discard(key)
                            uncoarsened.append(list(key))
                            self._block_cold.pop(key, None)
                    elif s < p.coarsen_share:
                        self._block_cold[key] = \
                            self._block_cold.get(key, 0) + 1
                        if self._block_cold[key] >= p.cooldown_epochs:
                            blocks.add(key)
                            coarsened.append(list(key))
                            self._block_cold.pop(key, None)
                    else:
                        self._block_cold.pop(key, None)

        changed = self.grid.apply_layout(splits, blocks)
        if changed:
            self._note_change(epoch_no, total, new_splits, merged,
                              coarsened, uncoarsened)
        return changed

    def _note_change(self, epoch_no: int, total: int, new_splits, merged,
                     coarsened, uncoarsened) -> None:
        from spatialflink_tpu.utils import telemetry as _telemetry
        from spatialflink_tpu.utils.metrics import REGISTRY

        with self._lock:
            self.repartitions += 1
        REGISTRY.counter("repartitions").inc()
        REGISTRY.counter("grid-splits").inc(len(new_splits))
        REGISTRY.counter("grid-merges").inc(len(merged))
        decision = {
            "ts_ms": int(time.time() * 1000),
            "epoch": epoch_no,
            "epoch_records": total,
            "version": self.grid.version,
            "split": new_splits,
            "merged": merged,
            "coarsened": coarsened,
            "uncoarsened": uncoarsened,
            "num_leaves": self.grid.num_leaves,
        }
        with self._lock:
            self.decisions.append(decision)
            del self.decisions[:-32]
        _telemetry.emit_event(
            "repartition", version=self.grid.version, epoch=epoch_no,
            split=new_splits, merged=merged, coarsened=len(coarsened),
            uncoarsened=len(uncoarsened), num_leaves=self.grid.num_leaves)
        tel = _telemetry.active()
        if tel is not None:
            tel.gauge("grid.version").set(float(self.grid.version))
            tel.gauge("grid.leaves").set(float(self.grid.num_leaves))

    # ------------------------------------------------------------------ #
    # serving / checkpointing

    def status(self) -> dict:
        """The ``/partition`` endpoint payload: the live layout, the policy
        thresholds (so the trigger is observable BEFORE it fires, next to
        the skew gauges it reads), epoch progress, and recent decisions."""
        with self._lock:
            since = self._since
            decisions = list(self.decisions)
            counts = self._counts
            total = int(counts.sum())
            top = []
            if total:
                nz = np.nonzero(counts)[0]
                order = nz[np.argsort(counts[nz])[::-1][:8]]
                top = [[int(c), round(float(counts[c]) / total, 4)]
                       for c in order]
        return {
            "grid": self.grid.layout(),
            "policy": {
                "split_share": self.policy.split_share,
                "merge_share": self.policy.merge_share,
                "cooldown_epochs": self.policy.cooldown_epochs,
                "max_splits": self.policy.max_splits,
                "coarsen_share": (self.policy.coarsen_share
                                  if self.coarsen_enabled else 0.0),
                "uncoarsen_share": self.policy.uncoarsen_share,
                "cost_weight": self.policy.cost_weight,
            },
            "interval_records": self.interval_records,
            "epoch": {"number": self.epochs, "records": since,
                      "top_shares": top},
            "repartitions": self.repartitions,
            "decisions": decisions,
        }

    def register_checkpoint(self, coordinator) -> None:
        """Carry the grid layout in the coordinated-checkpoint manifest
        (component ``grid``) so ``--resume`` restores the adapted
        partitioning. Registration auto-restores pending loaded state."""

        def snapshot():
            return {}, self.grid.layout()

        def restore(_arrays, meta) -> None:
            self.grid.apply_layout(
                meta.get("split_cells", ()),
                [tuple(b) for b in meta.get("coarse_blocks", ())])
            # the version is monotonic ACROSS the resume: never rewind
            # below the saved stamp (operators' mask caches key on it)
            self.grid.version = max(self.grid.version,
                                    int(meta.get("version", 0)))

        coordinator.register("grid", snapshot, restore)


# --------------------------------------------------------------------- #
# fleet placement: leaves as the unit of worker assignment


def balance_leaves(occupancy, n_workers: int):
    """Greedy LPT packing of leaves onto ``n_workers`` by observed
    occupancy: heaviest leaf first onto the lightest worker. This is the
    fleet supervisor's initial placement (PR 8's leaf layout as the
    placement unit — under the uniform grid the leaves ARE the base
    cells), and the same routine re-packs after a rebalance decision.

    ``occupancy`` maps leaf id -> observed record count (a seed-scan or a
    full epoch); returns leaf id -> worker index. Leaves never observed
    route by ``leaf % n_workers`` at partition time (see
    ``fleet.Partitioner``) — LPT only places the leaves we have signal
    for."""
    n = max(1, int(n_workers))
    loads = [0] * n
    assignment = {}
    for leaf, count in sorted(occupancy.items(),
                              key=lambda kv: (-kv[1], kv[0])):
        w = min(range(n), key=lambda i: loads[i])
        assignment[int(leaf)] = w
        loads[w] += int(count)
    return assignment


def pick_rebalance(loads):
    """(donor, receiver) worker pair for a repartition epoch, from a
    backpressure-style load signal (worker -> scalar; the fleet feeds the
    aggregated ``/latency`` backpressure share, falling back to record
    throughput). Returns ``None`` when the spread is too small to act on
    (hysteresis: moving leaves for a <25% imbalance would thrash)."""
    if len(loads) < 2:
        return None
    donor = max(loads, key=lambda w: loads[w])
    receiver = min(loads, key=lambda w: loads[w])
    if donor == receiver:
        return None
    hi, lo = float(loads[donor]), float(loads[receiver])
    if hi <= 0 or (hi - lo) / hi < 0.25:
        return None
    return donor, receiver
