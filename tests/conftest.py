"""Test environment: force an 8-device virtual CPU platform.

Note: this image's axon sitecustomize imports jax at interpreter start and
calls ``jax.config.update("jax_platforms", "axon,cpu")``, which overrides the
JAX_PLATFORMS env var. Setting env vars is therefore not enough — we must
write the config value back (and do it before any jax backend initializes,
which conftest import order guarantees)."""

import os

# XLA_FLAGS is read at backend-init time, so the env route works for it.
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

# Persistent compilation cache: the suite is dominated by 8-device shard_map
# compiles (and subprocess tests — examples, CLI, DCN workers — that re-jit
# the same programs in fresh interpreters). Env var rather than config-only
# so child processes inherit it.
_cache = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                      ".jax_cache")
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", _cache)
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0")

import jax  # noqa: E402  (sitecustomize may have imported it already)

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_compilation_cache_dir",
                  os.environ["JAX_COMPILATION_CACHE_DIR"])
jax.config.update("jax_persistent_cache_min_compile_time_secs",
                  float(os.environ["JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS"]))
