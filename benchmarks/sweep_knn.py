"""Sweep kNN top-k selection strategies on the current backend.

Usage: python benchmarks/sweep_knn.py [N_POINTS]

Times each strategy (sort / grouped at several group counts / prefilter at
several m / approx) on the headline window shape with the slope method
(index-dependent on-device fori_loop at two iteration counts), and prints a
table. Use the results to set ops.knn._DEFAULT_GROUPS/_GROUPED_MIN_N and the
prefilter m, and to pick bench.py's strategy on real hardware.
"""

from __future__ import annotations

import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    n_points = int(sys.argv[1]) if len(sys.argv) > 1 else 1_000_000
    k = 50

    from benchmarks._common import settle_backend

    settle_backend()  # a wedged tunnel downgrades to CPU instead of hanging
    import jax
    import jax.numpy as jnp

    from spatialflink_tpu.index import UniformGrid
    from spatialflink_tpu.models import PointBatch
    from spatialflink_tpu.ops import knn as Kn
    from spatialflink_tpu.ops import distances as D
    from spatialflink_tpu.ops.range import cheb_layers

    grid = UniformGrid(115.50, 117.60, 39.60, 41.10, num_grid_partitions=100)
    rng = np.random.default_rng(0)
    xs = rng.uniform(grid.min_x, grid.max_x, n_points)
    ys = rng.uniform(grid.min_y, grid.max_y, n_points)
    oid = rng.integers(0, n_points // 4, n_points).astype(np.int32)
    batch = jax.device_put(PointBatch.from_arrays(xs, ys, grid=grid, obj_id=oid))
    qx, qy = 116.5, 40.5
    qc = jnp.int32(grid.assign_cell(qx, qy)[0])
    layers = grid.candidate_layers(0.5)

    # slope measurement is shared with bench_configs: dynamic loop-count jit
    # arg + ×5 escalation until the gap clears the RTT-jitter floor (a fixed
    # 40-window gap is ~2ms for the approx_min_k path — it produced
    # physically impossible rows on the first round-4 TPU pass). Override
    # the starting window via SPATIALFLINK_SWEEP_ITERS=lo,hi.
    from bench_configs import _slope_time_ex

    lo, hi0 = (int(v) for v in os.environ.get(
        "SPATIALFLINK_SWEEP_ITERS", "2,42").split(","))

    def slope_ms(select):
        """-> (ms/window, ok); ok=False marks a row whose gap never cleared
        the noise floor even at the cap — the table itself carries the flag
        so redirected stdout can't record an impossible number unmarked."""
        @jax.jit
        def run_n(b, iters):
            def body(i, acc):
                lay = cheb_layers(b.cell, qc, grid.n)
                elig = b.valid & (lay <= layers)
                d = D.pp_dist(b.x, b.y, qx + i * 1e-7, qy)
                r = select(b.obj_id, d, elig)
                return acc + r.dist[0]
            return jax.lax.fori_loop(0, iters, body, jnp.float32(0))

        per, ok = _slope_time_ex(lambda it: run_n(batch, it), lo=lo, hi=hi0)
        return per * 1e3, ok

    rows = [("sort", lambda o, d, e: Kn._topk_full_sort(o, d, e, k))]
    for g in (64, 128, 256, 512, 1024):
        rows.append((f"grouped g={g}",
                     lambda o, d, e, g=g: Kn._topk_grouped(o, d, e, k, g)))
    for m in (512, 1024, 2048, 4096):
        rows.append((f"prefilter m={m}",
                     lambda o, d, e, m=m: Kn._topk_prefiltered(o, d, e, k, m)))
    for m in (800, 1600, 3200):
        rows.append((f"approx_ver m={m}",
                     lambda o, d, e, m=m: Kn._topk_approx_verified(o, d, e, k, m)))
    rows.append(("approx m=1600",
                 lambda o, d, e: Kn._topk_approx(o, d, e, k, 1600)))

    print(f"# backend={jax.default_backend()} n={n_points} k={k}")
    print(f"{'strategy':<18}{'ms/window':>12}{'Mpts/s':>12}")
    for name, fn in rows:
        ms, ok = slope_ms(fn)
        flag = "" if ok else "  UNRELIABLE (gap under noise floor at cap)"
        print(f"{name:<18}{ms:>12.3f}{n_points / ms / 1e3:>12.1f}{flag}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
