"""Sliding / tumbling window assignment and buffering.

Flink-compatible assignment: a sliding window of (size, slide) covers
[start, start + size) for starts aligned to ``slide``; each record with
event time ``ts`` belongs to the ``size // slide`` windows whose interval
contains ts. Tumbling = sliding with slide == size.

Windows seal when the watermark passes window_end; sealed windows emit their
buffered records in one shot — this is the host-side half of the
"window batch" execution unit, the rebuild's replacement for Flink's
per-cell window operators (the device half is in spatialflink_tpu.ops).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Iterator, List, Optional, Tuple

from spatialflink_tpu.runtime.watermarks import BoundedOutOfOrderness


@dataclass(frozen=True)
class WindowSpec:
    size_ms: int
    slide_ms: int

    @staticmethod
    def tumbling(size_ms: int) -> "WindowSpec":
        return WindowSpec(size_ms, size_ms)

    @staticmethod
    def sliding(size_ms: int, slide_ms: int) -> "WindowSpec":
        return WindowSpec(size_ms, slide_ms)

    def assign(self, ts_ms: int) -> List[int]:
        """Window start times containing ``ts_ms`` (Flink semantics)."""
        last_start = ts_ms - (ts_ms % self.slide_ms)
        starts = []
        start = last_start
        while start > ts_ms - self.size_ms:
            starts.append(start)
            start -= self.slide_ms
        return starts

    def assign_bulk(self, ts_ms) -> "Tuple[object, object]":
        """Vectorized :meth:`assign` over an array of event times.

        Returns ``(win_start, rec_idx)`` sorted by (window, original record
        order): every (window, record) membership pair, grouped by window.
        This is the replay/bulk-ingest fast path — no per-record Python loop,
        no watermark bookkeeping (a bounded replay has complete data, so no
        record is ever late). Assignment runs in record chunks so the dense
        (chunk, size/slide) intermediates stay bounded even for huge replays
        with high window overlap; the final global sort merges the chunks.
        """
        import numpy as np

        ts = np.asarray(ts_ms, np.int64)
        n_max = -(-self.size_ms // self.slide_ms)  # ceil
        offs = np.arange(n_max, dtype=np.int64) * self.slide_ms
        # chunk size targets ~64M int64 intermediate elements max
        chunk = max(1, (1 << 26) // max(1, n_max))
        ws_parts, ri_parts = [], []
        for lo in range(0, ts.shape[0], chunk):
            t = ts[lo:lo + chunk]
            last = t - (t % self.slide_ms)
            starts = last[:, None] - offs[None, :]     # (chunk, n_max)
            valid = starts > (t[:, None] - self.size_ms)
            rec = np.broadcast_to(
                np.arange(lo, lo + t.shape[0], dtype=np.int64)[:, None],
                starts.shape)
            ws_parts.append(starts[valid])
            ri_parts.append(rec[valid])
        win_start = np.concatenate(ws_parts) if ws_parts else \
            np.empty(0, np.int64)
        rec_idx = np.concatenate(ri_parts) if ri_parts else \
            np.empty(0, np.int64)
        order = np.lexsort((rec_idx, win_start))
        return win_start[order], rec_idx[order]


class WindowAssembler:
    """Buffers records into event-time windows; yields sealed windows.

    Usage::

        wa = WindowAssembler(WindowSpec.sliding(10_000, 5_000),
                             allowed_lateness_ms=2_000)
        for rec in stream:
            for (start, end, records) in wa.add(rec.timestamp, rec):
                ...process sealed window...
        for (start, end, records) in wa.flush():
            ...end of stream...

    Late records (event time below the watermark) are dropped and counted,
    mirroring the effective behavior of the reference's bounded
    out-of-orderness extractor feeding already-fired windows.
    """

    def __init__(self, spec: WindowSpec, allowed_lateness_ms: int = 0):
        self.spec = spec
        self.watermarker = BoundedOutOfOrderness(allowed_lateness_ms)
        self._buffers: Dict[int, List] = {}
        self.late_dropped = 0

    def add(self, ts_ms: int, record) -> Iterator[Tuple[int, int, List]]:
        if self.watermarker.is_late(ts_ms):
            self.late_dropped += 1
        else:
            for start in self.spec.assign(ts_ms):
                self._buffers.setdefault(start, []).append(record)
        wm = self.watermarker.on_event(ts_ms)
        yield from self._seal_until(wm)

    def _seal_until(self, watermark: int) -> Iterator[Tuple[int, int, List]]:
        ready = sorted(
            s for s in self._buffers if s + self.spec.size_ms <= watermark
        )
        for start in ready:
            records = self._buffers.pop(start)
            yield (start, start + self.spec.size_ms, records)

    def flush(self) -> Iterator[Tuple[int, int, List]]:
        """Seal every remaining window (end of bounded stream)."""
        for start in sorted(self._buffers):
            records = self._buffers.pop(start)
            yield (start, start + self.spec.size_ms, records)
