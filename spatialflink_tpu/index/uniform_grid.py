"""Uniform grid spatial index.

TPU-native re-design of the reference's ``UniformGrid``
(``spatialIndices/UniformGrid.java:33-519``):

- Cells are identified by a single int32 ``cell = cx * n + cy`` instead of two
  concatenated zero-padded 5-digit strings (``UniformGrid.java:92``); the
  string form is still available for wire-format parity via :meth:`cell_key`.
- Guaranteed / candidate neighboring-cell *sets* become dense boolean masks of
  shape ``(n*n,)`` so that device kernels test membership with one gather
  (``mask[cell]``) instead of a hash-set probe.
- For point queries the layer geometry is pure index arithmetic: a cell
  ``(px,py)`` is within layer ``L`` of ``(qx,qy)`` iff the Chebyshev distance
  ``max(|px-qx|,|py-qy|) <= L`` — device kernels can use this directly without
  materializing any mask (see :func:`cells_within_layers`).

Layer math mirrors the reference exactly:
- guaranteed layers  = floor(r / (cellLength*sqrt(2))) - 1
  (``UniformGrid.java:427-438``; -1 means "no guaranteed cells", 0 means
  "only the query cell itself").
- candidate layers   = ceil(r / cellLength)   (``UniformGrid.java:440-444``).
- radius == 0 in getNeighboringCells returns *all* grid cells
  (``UniformGrid.java:264-266``).
"""

from __future__ import annotations

import math
from typing import Callable, Iterable, NamedTuple, Optional, Set, Tuple, Union

import numpy as np

#: telemetry hook: when a telemetry session is active
#: (:func:`spatialflink_tpu.utils.telemetry.telemetry_session`), this is the
#: session's cell-occupancy recorder and every :meth:`UniformGrid.assign_cell`
#: result feeds the hottest-cell/skew gauges. None (the default) keeps the
#: assignment path exactly as before — one module-global None check.
_CELL_OBSERVER: Optional[Callable[[np.ndarray], None]] = None


class GridParams(NamedTuple):
    """Static grid geometry, safe to close over in a jitted function.

    All fields are Python scalars, so they are compile-time constants under
    ``jax.jit`` — changing the grid triggers (correctly) a recompile.
    """

    min_x: float
    min_y: float
    cell_length: float
    n: int  # grid is n x n cells

    @property
    def num_cells(self) -> int:
        return self.n * self.n


class UniformGrid:
    """An n x n square grid over a bounding box.

    Two constructors mirror the reference:

    - ``UniformGrid(min_x, max_x, min_y, max_y, num_grid_partitions=n)``
      (cell-count ctor, ``UniformGrid.java:74-85``).
    - ``UniformGrid(min_x, max_x, min_y, max_y, cell_length=L)``
      (cell-length ctor, ``UniformGrid.java:47-72``): first expands the
      shorter bbox axis symmetrically to make the bbox square
      (``adjustCoordinatesForSquareGrid``, ``UniformGrid.java:114-134``),
      then derives the partition count from the *degree-space Euclidean*
      width (the reference feeds lon/lat degrees through the same formula).
    """

    def __init__(
        self,
        min_x: float,
        max_x: float,
        min_y: float,
        max_y: float,
        *,
        num_grid_partitions: int | None = None,
        cell_length: float | None = None,
    ):
        if (num_grid_partitions is None) == (cell_length is None):
            raise ValueError(
                "pass exactly one of num_grid_partitions or cell_length"
            )

        self.min_x, self.max_x = float(min_x), float(max_x)
        self.min_y, self.max_y = float(min_y), float(max_y)

        if cell_length is not None:
            self._adjust_for_square_grid()
            grid_length = math.hypot(0.0, self.max_x - self.min_x)
            rows = grid_length / cell_length
            self.n = 1 if rows < 1 else int(math.ceil(rows))
            self.cell_length = (self.max_x - self.min_x) / self.n
        else:
            self.n = int(num_grid_partitions)
            self.cell_length = (self.max_x - self.min_x) / self.n

    def _adjust_for_square_grid(self) -> None:
        dx = self.max_x - self.min_x
        dy = self.max_y - self.min_y
        if dx > dy:
            d = (dx - dy) / 2
            self.max_y += d
            self.min_y -= d
        elif dy > dx:
            d = (dy - dx) / 2
            self.max_x += d
            self.min_x -= d

    # ------------------------------------------------------------------ #
    # basic geometry

    @property
    def num_cells(self) -> int:
        return self.n * self.n

    @property
    def params(self) -> GridParams:
        return GridParams(self.min_x, self.min_y, self.cell_length, self.n)

    def cell_indices(self, x, y) -> Tuple[np.ndarray, np.ndarray]:
        """(x, y) coordinates -> integer cell indices (cx, cy); vectorized.

        Pure floor-division, as ``HelperClass.assignGridCellID``
        (``utils/HelperClass.java:104-116``). Out-of-bbox coordinates yield
        out-of-range indices (negative or >= n) — they are *not* clamped,
        matching the reference, and will never compare equal to a valid cell.
        """
        cx = np.floor((np.asarray(x, np.float64) - self.min_x) / self.cell_length)
        cy = np.floor((np.asarray(y, np.float64) - self.min_y) / self.cell_length)
        return cx.astype(np.int64), cy.astype(np.int64)

    def valid_indices(self, cx, cy):
        """``UniformGrid.validKey`` (``UniformGrid.java:224-229``)."""
        cx, cy = np.asarray(cx), np.asarray(cy)
        return (cx >= 0) & (cy >= 0) & (cx < self.n) & (cy < self.n)

    def assign_cell(self, x, y) -> Tuple[np.ndarray, np.ndarray]:
        """Coordinates -> (cell id int32, valid bool); cell is -1 if invalid."""
        cx, cy = self.cell_indices(x, y)
        valid = self.valid_indices(cx, cy)
        cell = np.where(valid, cx * self.n + cy, -1).astype(np.int32)
        if _CELL_OBSERVER is not None:
            _CELL_OBSERVER(cell)
        return cell, valid

    def cell_id(self, cx: int, cy: int) -> int:
        return int(cx) * self.n + int(cy)

    def cell_xy(self, cell) -> Tuple[np.ndarray, np.ndarray]:
        cell = np.asarray(cell)
        return cell // self.n, cell % self.n

    def cell_key(self, cell: int) -> str:
        """Reference wire format: two 5-digit zero-padded indices concatenated
        (``CELLINDEXSTRLENGTH = 5``, ``UniformGrid.java:40,92``)."""
        cx, cy = int(cell) // self.n, int(cell) % self.n
        return f"{cx:05d}{cy:05d}"

    def cell_from_key(self, key: str) -> int:
        return self.cell_id(int(key[:5]), int(key[5:]))

    def cell_bounds(self, cell: int) -> Tuple[float, float, float, float]:
        """(min_x, min_y, max_x, max_y) of a cell
        (``UniformGrid.getCellMinMaxBoundary``, ``UniformGrid.java:149-158``)."""
        cx, cy = int(cell) // self.n, int(cell) % self.n
        return (
            self.min_x + cx * self.cell_length,
            self.min_y + cy * self.cell_length,
            self.min_x + (cx + 1) * self.cell_length,
            self.min_y + (cy + 1) * self.cell_length,
        )

    def bbox_cells(self, min_x: float, min_y: float, max_x: float, max_y: float) -> Set[int]:
        """All valid cells overlapped by a bounding box
        (``HelperClass.assignGridCellID(bBox, uGrid)``,
        ``utils/HelperClass.java:123-143``)."""
        cx1, cy1 = self.cell_indices(min_x, min_y)
        cx2, cy2 = self.cell_indices(max_x, max_y)
        out: Set[int] = set()
        for cx in range(int(cx1), int(cx2) + 1):
            for cy in range(int(cy1), int(cy2) + 1):
                if 0 <= cx < self.n and 0 <= cy < self.n:
                    out.add(self.cell_id(cx, cy))
        return out

    # ------------------------------------------------------------------ #
    # layer math (reference parity)

    def guaranteed_layers(self, radius: float) -> int:
        """floor(r / cellDiagonal) - 1; -1 => no guaranteed cells
        (``UniformGrid.java:427-438``)."""
        cell_diagonal = self.cell_length * math.sqrt(2.0)
        return int(math.floor(radius / cell_diagonal - 1))

    def candidate_layers(self, radius: float) -> int:
        """ceil(r / cellLength) (``UniformGrid.java:440-444``)."""
        return int(math.ceil(radius / self.cell_length))

    # ------------------------------------------------------------------ #
    # neighboring-cell masks (dense over the n*n grid)

    def _layer_mask(self, cells: Iterable[int], layers: int) -> np.ndarray:
        """Boolean (n*n,) mask of all valid cells within Chebyshev distance
        ``layers`` of any seed cell."""
        mask = np.zeros((self.n, self.n), dtype=bool)
        if layers < 0:
            return mask.reshape(-1)
        for cell in cells:
            cx, cy = int(cell) // self.n, int(cell) % self.n
            x0, x1 = max(0, cx - layers), min(self.n, cx + layers + 1)
            y0, y1 = max(0, cy - layers), min(self.n, cy + layers + 1)
            mask[x0:x1, y0:y1] = True
        return mask.reshape(-1)

    @staticmethod
    def _as_cells(cells: Union[int, Iterable[int]]) -> Iterable[int]:
        if isinstance(cells, (int, np.integer)):
            return (int(cells),)
        return cells

    def guaranteed_cells_mask(self, radius: float, cells: Union[int, Iterable[int]]) -> np.ndarray:
        """Guaranteed neighboring cells of query cell(s) as a dense mask.

        Mirrors ``getGuaranteedNeighboringCells`` for a point cell
        (``UniformGrid.java:165-190``) and its polygon/linestring overloads
        (union over the geometry's cells, ``:193-222``).
        """
        return self._layer_mask(self._as_cells(cells), self.guaranteed_layers(radius))

    def candidate_cells_mask(
        self,
        radius: float,
        cells: Union[int, Iterable[int]],
        guaranteed_mask: np.ndarray | None = None,
    ) -> np.ndarray:
        """Candidate neighboring cells = within candidate layers, minus the
        guaranteed set (``getCandidateNeighboringCells``,
        ``UniformGrid.java:367-425``). Mutually exclusive with the GN mask."""
        if guaranteed_mask is None:
            guaranteed_mask = self.guaranteed_cells_mask(radius, cells)
        cand = self._layer_mask(self._as_cells(cells), self.candidate_layers(radius))
        return cand & ~guaranteed_mask

    def neighboring_cells_mask(self, radius: float, cells: Union[int, Iterable[int]]) -> np.ndarray:
        """GN ∪ CN. ``radius == 0`` selects *all* cells
        (``getNeighboringCells``, ``UniformGrid.java:261-293``)."""
        if radius == 0:
            return np.ones(self.num_cells, dtype=bool)
        return self._layer_mask(self._as_cells(cells), self.candidate_layers(radius))

    def neighboring_layer_cells_mask(self, cell: int, layer: int) -> np.ndarray:
        """The ring of cells at exactly Chebyshev distance ``layer``
        (``getNeighboringLayerCells``, ``UniformGrid.java:446-479``)."""
        outer = self._layer_mask((cell,), layer)
        inner = self._layer_mask((cell,), layer - 1) if layer > 0 else np.zeros(self.num_cells, bool)
        return outer & ~inner

    def all_neighboring_layers(self, cell: int) -> list:
        """Non-empty rings around a cell, nearest first
        (``getAllNeighboringLayers``, ``UniformGrid.java:482-500``)."""
        out = []
        for layer in range(self.n):
            ring = self.neighboring_layer_cells_mask(cell, layer)
            if not ring.any():
                break
            out.append(ring)
        return out

    def cell_layer_wrt(self, query_cell: int, cell: int) -> int:
        """Chebyshev layer of ``cell`` w.r.t. ``query_cell``
        (``HelperClass.getCellLayerWRTQueryCell``, ``utils/HelperClass.java:278-296``)."""
        qx, qy = query_cell // self.n, query_cell % self.n
        cx, cy = cell // self.n, cell % self.n
        return max(abs(qx - cx), abs(qy - cy))

    def __repr__(self) -> str:
        return (
            f"UniformGrid(n={self.n}, cell_length={self.cell_length:.6g}, "
            f"bbox=[{self.min_x}, {self.min_y}, {self.max_x}, {self.max_y}])"
        )


def cheb_layers(cell_a, cell_b, n: int):
    """Chebyshev layer distance between two cell ids on an n x n grid;
    a huge sentinel if either cell is invalid (-1). jnp-array friendly.

    This is the single arithmetic form of the reference's neighboring-cell
    membership test for point queries: ``cheb_layers(a, b, n) <= L`` is
    "cell a lies within L layers of cell b"."""
    import jax.numpy as jnp

    cell_a, cell_b = jnp.asarray(cell_a), jnp.asarray(cell_b)
    ax, ay = cell_a // n, cell_a % n
    bx, by = cell_b // n, cell_b % n
    layers = jnp.maximum(jnp.abs(ax - bx), jnp.abs(ay - by))
    return jnp.where((cell_a >= 0) & (cell_b >= 0), layers, jnp.int32(2**30))


def cells_within_layers(cell_a, cell_b, layers: int, n: int):
    """Boolean form of :func:`cheb_layers`: invalid cells (-1) never match."""
    return cheb_layers(cell_a, cell_b, n) <= layers
