"""Spatial query operators (reference: ``spatialOperators/``).

Operator classes mirror the reference API surface: construct with a
:class:`QueryConfiguration` + grid(s), then ``run(stream, query, radius,...)``.
``run`` consumes an iterator of spatial objects and yields result events —
per sealed window in window mode, per micro-batch in real-time mode.

The execution model differs deliberately (SURVEY §7): instead of Flink's
per-cell keyed window operators + shuffles, each window is one padded device
batch evaluated by a masked kernel (spatialflink_tpu.ops), optionally
sharded over a device mesh (spatialflink_tpu.parallel).

All 9 stream-type x query-type pairs of SURVEY §2.2 are exported under their
reference names for each of range/kNN/join; pairs sharing a device
representation share an implementation (polygon and linestring streams are
both padded edge-array batches).
"""

from spatialflink_tpu.operators.base import (
    QueryConfiguration,
    QueryType,
    WindowResult,
)
from spatialflink_tpu.operators.range_query import (
    PointPointRangeQuery,
    PointPolygonRangeQuery,
    PointLineStringRangeQuery,
    PolygonPointRangeQuery,
    PolygonPolygonRangeQuery,
    PolygonLineStringRangeQuery,
    LineStringPointRangeQuery,
    LineStringPolygonRangeQuery,
    LineStringLineStringRangeQuery,
)
from spatialflink_tpu.operators.knn_query import (
    PointPointKNNQuery,
    PointPolygonKNNQuery,
    PointLineStringKNNQuery,
    PolygonPointKNNQuery,
    PolygonPolygonKNNQuery,
    PolygonLineStringKNNQuery,
    LineStringPointKNNQuery,
    LineStringPolygonKNNQuery,
    LineStringLineStringKNNQuery,
)
from spatialflink_tpu.operators.trajectory import (
    PointTFilterQuery,
    PointPolygonTRangeQuery,
    PointTStatsQuery,
    PointTAggregateQuery,
    PointPointTJoinQuery,
    PointPointTKNNQuery,
    assemble_subtrajectories,
)
from spatialflink_tpu.operators.join_query import (
    PointPointJoinQuery,
    PointPolygonJoinQuery,
    PointLineStringJoinQuery,
    PolygonPointJoinQuery,
    PolygonPolygonJoinQuery,
    PolygonLineStringJoinQuery,
    LineStringPointJoinQuery,
    LineStringPolygonJoinQuery,
    LineStringLineStringJoinQuery,
)

__all__ = [
    "QueryConfiguration",
    "QueryType",
    "WindowResult",
] + [
    f"{pair}{kind}Query"
    for pair in (
        "PointPoint", "PointPolygon", "PointLineString",
        "PolygonPoint", "PolygonPolygon", "PolygonLineString",
        "LineStringPoint", "LineStringPolygon", "LineStringLineString",
    )
    for kind in ("Range", "KNN", "Join")
] + [
    "PointTFilterQuery",
    "PointPolygonTRangeQuery",
    "PointTStatsQuery",
    "PointTAggregateQuery",
    "PointPointTJoinQuery",
    "PointPointTKNNQuery",
    "assemble_subtrajectories",
]
