"""Point-stream x point-stream join.

Reference: ``spatialOperators/join/PointPointJoinQuery.java`` — query-stream
replication to neighboring cells, gridID equi-join per window, exact-distance
filter (``:110-171``). Here both sides are windowed together and joined with
the MXU pairwise-distance kernel + Chebyshev cell predicate (ops.join); pairs
are extracted sparsely on the host.

Real-time mode micro-batches the *merged* arrival stream and joins each
micro-batch's two sides (the reference's fire-per-element trigger analogue,
``tJoin/TJoinQuery.java:216-268``).
"""

from __future__ import annotations

import heapq
from collections import deque
from typing import Dict, Iterable, Iterator, List, Tuple

from spatialflink_tpu.models import Point
from spatialflink_tpu.operators.base import (
    Deferred,
    QueryType,
    SpatialOperator,
    WindowResult,
)
from spatialflink_tpu.ops.join import join_pairs_host
from spatialflink_tpu.runtime import WindowAssembler


def _merge_by_time(a: Iterable[Point], b: Iterable[Point]) -> Iterator[Tuple[int, int, Point]]:
    """Merge two event-time-ordered streams, tagging side 0/1."""
    return heapq.merge(
        ((p.timestamp, 0, p) for p in a),
        ((p.timestamp, 1, p) for p in b),
        key=lambda t: t[0],
    )


def _combine_windows(r1: WindowResult, r2: WindowResult) -> WindowResult:
    """One WindowResult whose records are r1's followed by r2's; deferred
    inputs stay deferred (both lattices remain in flight on device)."""
    rec1, rec2 = r1.records, r2.records
    if not isinstance(rec1, Deferred) and not isinstance(rec2, Deferred):
        return WindowResult(r1.window_start, r1.window_end, rec1 + rec2)

    def collect(_):
        out = rec1.finish() if isinstance(rec1, Deferred) else list(rec1)
        out += rec2.finish() if isinstance(rec2, Deferred) else list(rec2)
        return out

    return WindowResult(r1.window_start, r1.window_end, Deferred(None, collect))


def _merge_sorted_windows(gen_a, gen_b):
    """Outer-merge two window-start-sorted (start, end, idx, batch) streams
    into (start, end, a_win|None, b_win|None)."""
    a = next(gen_a, None)
    b = next(gen_b, None)
    while a is not None or b is not None:
        if b is None or (a is not None and a[0] < b[0]):
            yield a[0], a[1], (a[2], a[3]), None
            a = next(gen_a, None)
        elif a is None or b[0] < a[0]:
            yield b[0], b[1], None, (b[2], b[3])
            b = next(gen_b, None)
        else:
            yield a[0], a[1], (a[2], a[3]), (b[2], b[3])
            a = next(gen_a, None)
            b = next(gen_b, None)


class PointPointJoinQuery(SpatialOperator):
    telemetry_label = "join"

    # a count trigger over TWO independently-arriving streams is ambiguous
    # (whose arrivals count?); joins keep the reference's rejection
    supports_count_windows = False

    prune_cells = True  # naive twins disable grid pruning (exact filter only)

    def run(self, ordinary: Iterable[Point], query_stream: Iterable[Point],
            radius: float) -> Iterator[WindowResult]:
        if self.conf.query_type is QueryType.RealTime:
            results = self._run_realtime(ordinary, query_stream, radius)
        elif self._panes_active():
            results = self._run_windowed_panes(ordinary, query_stream, radius)
        else:
            results = self._run_windowed(ordinary, query_stream, radius)
        return self._pipeline(results)

    def _pipeline(self, results: Iterator[WindowResult]
                  ) -> Iterator[WindowResult]:
        """Keep up to ``conf.pipeline_depth`` join lattices in flight on
        device (``records`` may arrive as a :class:`Deferred`), materializing
        in window order — the host seals and dispatches the next window while
        the device works on the previous one."""
        depth = max(1, self.conf.pipeline_depth)
        pending: deque = deque()
        coord = self.conf.checkpointer

        def force(r: WindowResult) -> WindowResult:
            if isinstance(r.records, Deferred):
                r.records = r.records.finish()
            return r

        # same knob semantics as base._drive: depth-1 windows stay in flight
        # behind the one being assembled; eager (non-Deferred) results pass
        # straight through once older deferred windows have drained
        for r in results:
            if isinstance(r.records, Deferred):
                pending.append(r)
                while len(pending) > depth - 1:
                    yield force(pending.popleft())
            else:
                while pending:
                    yield force(pending.popleft())
                yield r
            if coord is not None:
                # coordinated-checkpoint barrier (see base._drive_batched):
                # drain the in-flight lattices first — their windows' records
                # are no longer in the snapshotted assemblers/sealed maps,
                # so they must be fully emitted before the manifest writes
                coord.note_batch()
                if coord.due():
                    while pending:
                        yield force(pending.popleft())
                    coord.commit()
        while pending:
            yield force(pending.popleft())

    # ---------------------------------------------------------------- #

    def _register_ckpt_join(self, wa_a, wa_b, sealed_a, sealed_b,
                            panes: bool) -> None:
        """Coordinator participant for the two-stream windowed join: both
        sides' assemblers (or pane buffers) plus the sealed-on-one-side
        maps awaiting the other watermark. ``panes`` switches the sealed
        payload shape: record lists vs ``[(pane_start, records)]`` lists."""
        coord = self.conf.checkpointer
        if coord is None:
            return
        from spatialflink_tpu.runtime.checkpoint import record_codec

        # side b decodes against grid2 — the query-side grid the driver
        # parses stream2 into; decoding both sides with grid would mint
        # wrong cell ids whenever the two grids differ
        enc, dec_a = record_codec(self.grid)
        _, dec_b = record_codec(self.grid2)

        if panes:
            def enc_sealed(sealed):
                return {str(s): [[p, [enc(r) for r in recs]]
                                 for p, recs in pane_list]
                        for s, pane_list in sealed.items()}

            def dec_sealed(state, sealed, dec):
                sealed.update({int(s): [(int(p), [dec(r) for r in recs])
                                        for p, recs in pl]
                               for s, pl in state.items()})
        else:
            def enc_sealed(sealed):
                return {str(s): [enc(r) for r in recs]
                        for s, recs in sealed.items()}

            def dec_sealed(state, sealed, dec):
                sealed.update({int(s): [dec(r) for r in recs]
                               for s, recs in state.items()})

        def snap():
            return ({}, {"a": wa_a.snapshot(enc), "b": wa_b.snapshot(enc),
                         "sealed_a": enc_sealed(sealed_a),
                         "sealed_b": enc_sealed(sealed_b)})

        def restore(_arrays, meta):
            wa_a.restore(meta["a"], dec_a)
            wa_b.restore(meta["b"], dec_b)
            dec_sealed(meta["sealed_a"], sealed_a, dec_a)
            dec_sealed(meta["sealed_b"], sealed_b, dec_b)

        coord.register("join-windows", snap, restore)

    def _run_realtime(self, ordinary, query_stream, radius) -> Iterator[WindowResult]:
        """Micro-batched realtime join over a *rolling* window.

        The reference's realtime joins buffer a full small window per stream
        with fire-per-element triggers (``tJoin/TJoinQuery.java:216-268``), so
        any pair co-resident within the window is found regardless of arrival
        interleaving. Mirroring that: both sides keep a rolling buffer of the
        last ``window_size_ms`` of records across micro-batches; each batch
        joins (old ∪ new) × (old ∪ new) but suppresses old×old pairs (already
        emitted by an earlier fire), so a pair straddling a micro-batch
        boundary is emitted exactly once — when its later point arrives.
        """
        win = self.conf.window_size_ms
        buf_a: List[Point] = []
        buf_b: List[Point] = []
        new_a: List[Point] = []
        new_b: List[Point] = []
        seen = 0
        last_ts = 0

        def fire(end_ts):
            nonlocal buf_a, buf_b, new_a, new_b, seen
            # evict only points that cannot pair with ANY new arrival: the
            # earliest new record sets the horizon (evicting against end_ts
            # would drop a buffered point still within win of a new one);
            # the max_dt filter below enforces |ta - tb| <= win exactly
            first_new = min(p.timestamp for p in new_a + new_b)
            cutoff = first_new - win
            buf_a = [p for p in buf_a if p.timestamp >= cutoff]
            buf_b = [p for p in buf_b if p.timestamp >= cutoff]
            all_b = buf_b + new_b
            # two lattices instead of (old+new)^2: new_a x (old_b + new_b)
            # and old_a x new_b cover every pair with a new member exactly
            # once and never recompute the old x old block an earlier fire
            # already evaluated
            start = end_ts - win
            r1 = self._join_window(start, end_ts, new_a, all_b, radius,
                                   max_dt=win)
            r2 = self._join_window(start, end_ts, buf_a, new_b, radius,
                                   max_dt=win)
            res = _combine_windows(r1, r2)
            if not isinstance(res.records, Deferred) and not res.records:
                res = None  # realtime fires never emit known-empty results
            buf_a, buf_b = buf_a + new_a, all_b
            new_a, new_b, seen = [], [], 0
            return res

        for ts, side, rec in _merge_by_time(ordinary, query_stream):
            (new_a if side == 0 else new_b).append(rec)
            last_ts = ts
            seen += 1
            if seen >= self.conf.realtime_batch_size:
                res = fire(ts)
                if res is not None:
                    yield res
        if new_a or new_b:
            res = fire(last_ts)
            if res is not None:
                yield res

    # ---------------------------------------------------------------- #

    def _run_windowed(self, ordinary, query_stream, radius) -> Iterator[WindowResult]:
        spec = self.conf.window_spec()
        wa_a = WindowAssembler(spec, self.conf.allowed_lateness_ms)
        wa_b = WindowAssembler(spec, self.conf.allowed_lateness_ms)
        # windows sealed on one side, waiting for the other; bounded by the
        # watermark sweep below (a window is emitted -- possibly one-sided --
        # once BOTH sides' watermarks have passed its end)
        sealed_a: Dict[int, List[Point]] = {}
        sealed_b: Dict[int, List[Point]] = {}
        self._register_ckpt_join(wa_a, wa_b, sealed_a, sealed_b, panes=False)

        def sweep() -> Iterator[WindowResult]:
            # Empty windows never appear in an assembler's buffers, so a
            # window sealed on one side may have no counterpart; once both
            # watermarks passed its end the missing side is final-empty.
            wm = min(wa_a.watermarker.watermark, wa_b.watermarker.watermark)
            for start in sorted(set(sealed_a) | set(sealed_b)):
                end = start + spec.size_ms
                both = start in sealed_a and start in sealed_b
                if both or end <= wm:
                    recs_a = sealed_a.pop(start, [])
                    recs_b = sealed_b.pop(start, [])
                    yield self._join_window(start, end, recs_a, recs_b, radius)

        for ts, side, rec in _merge_by_time(ordinary, query_stream):
            wa = wa_a if side == 0 else wa_b
            sealed = sealed_a if side == 0 else sealed_b
            for start, _end, records in wa.add(ts, rec):
                sealed[start] = records
            yield from sweep()
        for start, _end, records in wa_a.flush():
            sealed_a[start] = records
        for start, _end, records in wa_b.flush():
            sealed_b[start] = records
        for start in sorted(set(sealed_a) | set(sealed_b)):
            yield self._join_window(
                start, start + spec.size_ms,
                sealed_a.pop(start, []), sealed_b.pop(start, []), radius,
            )

    def _run_windowed_panes(self, ordinary, query_stream, radius
                            ) -> Iterator[WindowResult]:
        """Pane-incremental windowed join (``--panes``): both sides buffer
        into slide-aligned panes, and each window's pair set is the union of
        its PANE-PAIR BLOCKS ``A_i x B_j`` — each block's lattice kernel
        runs once and is reused by every window containing both panes, so a
        slide adds only the O(overlap) new blocks touching the freshest
        pane instead of recomputing the O(overlap^2) full lattice. Window
        set/sealing/late-drops are identical to :meth:`_run_windowed`
        (same watermark sweep, pane-grouped); pair ORDER within a window is
        block order rather than full-lattice order — the pair SET is
        identical. Block results stay deferred until the window's readback,
        so pane mode composes with ``pipeline_depth``."""
        from spatialflink_tpu.operators.base import PaneCache, PanePartial
        from spatialflink_tpu.runtime.windows import PaneBuffer

        spec = self.conf.window_spec()
        slide = spec.slide_ms
        pb_a = PaneBuffer(spec, self.conf.allowed_lateness_ms)
        pb_b = PaneBuffer(spec, self.conf.allowed_lateness_ms)
        sealed_a: Dict[int, List] = {}  # start -> [(pane_start, records)]
        sealed_b: Dict[int, List] = {}
        self._register_ckpt_join(pb_a, pb_b, sealed_a, sealed_b, panes=True)
        # block cache keyed (pane_a, pane_b); a block is needed only while
        # BOTH its panes can appear in a future window, so eviction hinges
        # on the earlier pane
        cache = PaneCache(slide, key_floor=min)
        self._register_ckpt_pane_cache("pane-cache", cache)
        # per-side pane BATCH memo: a pane's device batch is built once and
        # shared by every block touching it — without this each new pane
        # would rebuild its batch O(overlap) times (once per block) and the
        # host batch-building cost would match full-window recompute
        bcache_a: Dict[int, object] = {}
        bcache_b: Dict[int, object] = {}

        def block(pa: int, ra: List, pb_s: int, rb: List) -> PanePartial:
            def evaluate():
                if pa not in bcache_a:
                    bcache_a[pa] = self._batch_a(ra, pa)
                if pb_s not in bcache_b:
                    bcache_b[pb_s] = self._batch_b(rb, pb_s)
                return PanePartial(self._join_block(
                    bcache_a[pa], ra, bcache_b[pb_s], rb, radius))

            return cache.get((pa, pb_s), evaluate)

        def evict(start: int) -> None:
            cache.evict_before(start)
            for bc in (bcache_a, bcache_b):
                for dead in [p for p in bc if p < start + slide]:
                    del bc[dead]

        def join_panes(start: int, panes_a: List, panes_b: List
                       ) -> WindowResult:
            if self._blocks_dispatch_bound(panes_a, panes_b):
                # ADAPTIVE GRANULARITY: the window's pane-pair blocks are
                # dispatch-bound (mean block lattice below the measured
                # per-dispatch break-even — the 0.56–0.95× dense regime in
                # BASELINE), so evaluate the window as ONE coalesced
                # lattice dispatch instead of overlap² tiny ones. No
                # cross-window reuse for such windows — matching the
                # full-recompute path they now cost — while big-block
                # (compute-bound) windows keep the cached-block path.
                from spatialflink_tpu.utils.metrics import REGISTRY

                REGISTRY.counter("join-blocks-coalesced").inc(
                    len(panes_a) * len(panes_b))
                evict(start)
                return self._join_window(
                    start, start + spec.size_ms,
                    [r for _, rs in panes_a for r in rs],
                    [r for _, rs in panes_b for r in rs], radius)
            blocks = [block(pa, ra, pb_s, rb)
                      for pa, ra in panes_a for pb_s, rb in panes_b]
            evict(start)

            def collect(_):
                return [pair for h in blocks for pair in h.resolve()]

            return WindowResult(start, start + spec.size_ms,
                                Deferred(None, collect))

        def sweep() -> Iterator[WindowResult]:
            wm = min(pb_a.watermarker.watermark, pb_b.watermarker.watermark)
            for start in sorted(set(sealed_a) | set(sealed_b)):
                end = start + spec.size_ms
                both = start in sealed_a and start in sealed_b
                if both or end <= wm:
                    yield join_panes(start, sealed_a.pop(start, []),
                                     sealed_b.pop(start, []))

        for ts, side, rec in _merge_by_time(ordinary, query_stream):
            pb = pb_a if side == 0 else pb_b
            sealed = sealed_a if side == 0 else sealed_b
            for start, _end, panes in pb.add(ts, rec):
                sealed[start] = panes
            yield from sweep()
        for start, _end, panes in pb_a.flush():
            sealed_a[start] = panes
        for start, _end, panes in pb_b.flush():
            sealed_b[start] = panes
        for start in sorted(set(sealed_a) | set(sealed_b)):
            yield join_panes(start, sealed_a.pop(start, []),
                             sealed_b.pop(start, []))

    @staticmethod
    def _blocks_dispatch_bound(panes_a: List, panes_b: List) -> bool:
        """True when this window's pane-pair blocks sit below the measured
        per-dispatch break-even (``ops.join.adaptive_block_min_cells``):
        mean block lattice cells at PADDED capacities — dispatch cost
        scales with the padded shape, not the live record count."""
        if not panes_a or not panes_b or len(panes_a) * len(panes_b) <= 1:
            return False
        from spatialflink_tpu.ops.join import adaptive_block_min_cells
        from spatialflink_tpu.utils.padding import bucket_size

        min_cells = adaptive_block_min_cells()
        if min_cells <= 0:
            return False
        mean_a = sum(bucket_size(max(len(rs), 1))
                     for _, rs in panes_a) / len(panes_a)
        mean_b = sum(bucket_size(max(len(rs), 1))
                     for _, rs in panes_b) / len(panes_b)
        return mean_a * mean_b < min_cells

    def run_bulk(self, parsed_a, parsed_b, radius: float, *,
                 pad: int = None) -> Iterator[WindowResult]:
        """Bulk-replay fast path: both sides go through the vectorized window
        assembler; records are (index_a, index_b) pairs into the two
        ParsedPoints. Windowed mode only."""
        from spatialflink_tpu.streams.bulk import bulk_window_batches

        if self.conf.query_type is QueryType.RealTime:
            raise ValueError("run_bulk supports windowed mode only")
        spec = self.conf.window_spec()
        gen_a = bulk_window_batches(parsed_a, spec, self.grid, pad=pad)
        # both sides must carry cell ids from the SAME grid: join_pairs_host
        # evaluates the Chebyshev cell predicate in self.grid (as _join_window
        # does via _point_batch); windowing side b in grid2 would compare cell
        # ids across different grids and misprune pairs
        gen_b = bulk_window_batches(parsed_b, spec, self.grid, pad=pad)
        for start, end, a_win, b_win in _merge_sorted_windows(gen_a, gen_b):
            pairs: List[Tuple[int, int]] = []
            if a_win is not None and b_win is not None:
                idx_a, batch_a = a_win
                idx_b, batch_b = b_win
                for ai, bi in self._join_pairs(batch_a, batch_b, radius):
                    pairs.extend(
                        (int(idx_a[i]), int(idx_b[j]))
                        for i, j in zip(ai.tolist(), bi.tolist())
                        if i < len(idx_a) and j < len(idx_b)
                    )
            yield WindowResult(start, end, pairs)

    def _join_pairs(self, batch_a, batch_b, radius):
        """(a_index, b_index) survivor arrays for one window's pair lattice.

        Single-device: b-tiled host extraction (``ops.join.join_pairs_host``).
        With ``conf.devices``: the a side is sharded over the mesh and the
        query side replicated — the broadcast-join layout of SURVEY §2.5
        (``join/JoinQuery.java:72-90``'s replication without materialized
        copies) via ``parallel.ops.distributed_join_mask``.
        """
        nb_layers = None if self.prune_cells else self.grid.n
        if self.distributed:
            import numpy as np

            from spatialflink_tpu.parallel.ops import distributed_join_mask

            if nb_layers is None:
                nb_layers = (self.grid.n if radius == 0
                             else self.grid.candidate_layers(radius))
            cx = self.grid.min_x + self.grid.cell_length * self.grid.n / 2
            cy = self.grid.min_y + self.grid.cell_length * self.grid.n / 2
            m = self._eval_degradable(
                lambda: None,  # sentinel: single-device path yields below
                lambda mesh, sa: distributed_join_mask(
                    mesh, sa, batch_b, radius,
                    nb_layers, cx, cy, n=self.grid.n),
                batch_a)
            if m is not None:
                ai, bi = np.nonzero(np.asarray(m))
                if ai.size:
                    yield ai, bi
                return
        yield from join_pairs_host(batch_a, batch_b, radius, self.grid,
                                   nb_layers=nb_layers)

    def _batch_a(self, recs, ts_base):
        return self._point_batch(recs, ts_base)

    _batch_b = _batch_a

    def _join_block(self, batch_a, recs_a: List[Point], batch_b,
                    recs_b: List[Point], radius) -> List[Tuple[Point, Point]]:
        """One pane-pair block from PRE-BUILT pane batches — the pane
        path's :meth:`_join_window` twin (windowed semantics only: no
        realtime rolling-prefix/max_dt filters). Taking batches lets the
        pane driver build each pane's batch once per SIDE instead of once
        per block; the mixed ts bases are harmless (the join predicates
        read positions and cells, never the batch ts offsets)."""
        pairs: List[Tuple[Point, Point]] = []
        for ai, bi in self._join_pairs(batch_a, batch_b, radius):
            pairs.extend(
                (recs_a[i], recs_b[j])
                for i, j in zip(ai.tolist(), bi.tolist())
                if i < len(recs_a) and j < len(recs_b)
            )
        return pairs

    def _join_window(self, start, end, recs_a: List[Point], recs_b: List[Point],
                     radius, *, old_a: int = 0, old_b: int = 0,
                     max_dt: int = None) -> WindowResult:
        # old_a/old_b: realtime rolling-buffer prefix lengths — pairs with
        # BOTH members in the prefix were emitted by an earlier fire.
        # max_dt: realtime co-residence bound — only pairs whose event times
        # lie within one realtime window of each other are emitted
        pairs: List[Tuple[Point, Point]] = []
        if recs_a and recs_b:
            batch_a = self._point_batch(recs_a, start)
            batch_b = self._point_batch(recs_b, start)
            for ai, bi in self._join_pairs(batch_a, batch_b, radius):
                pairs.extend(
                    (recs_a[i], recs_b[j])
                    for i, j in zip(ai.tolist(), bi.tolist())
                    if i < len(recs_a) and j < len(recs_b)
                    and not (i < old_a and j < old_b)
                    and (max_dt is None
                         or abs(recs_a[i].timestamp - recs_b[j].timestamp) <= max_dt)
                )
        return WindowResult(start, end, pairs)


class _GenericStreamJoin(PointPointJoinQuery):
    """Shared two-stream windowed/realtime join driver; subclasses override
    batch construction and the pair-lattice kernel."""

    def _join_window(self, start, end, recs_a, recs_b, radius, *,
                     old_a: int = 0, old_b: int = 0,
                     max_dt: int = None) -> WindowResult:
        import numpy as np

        if not (recs_a and recs_b):
            return WindowResult(start, end, [])
        batch_a = self._batch_a(recs_a, start)
        batch_b = self._batch_b(recs_b, start)
        if self.distributed:
            # broadcast-join layout for the geometry pairs too: a sharded on
            # the mesh, query side replicated, same lattice kernel per shard
            from spatialflink_tpu.parallel.ops import (
                distributed_stream_join_lattice,
            )

            m_dev = self._eval_degradable(
                lambda: self._lattice(batch_a, batch_b, radius),
                lambda mesh, sa: distributed_stream_join_lattice(
                    mesh, sa, batch_b,
                    lambda a_s, b_r: self._lattice(a_s, b_r, radius)),
                batch_a)
        else:
            m_dev = self._lattice(batch_a, batch_b, radius)

        def collect(m):
            ai, bi = np.nonzero(np.asarray(m))
            return [
                (recs_a[i], recs_b[j])
                for i, j in zip(ai.tolist(), bi.tolist())
                if i < len(recs_a) and j < len(recs_b)
                and not (i < old_a and j < old_b)
                and (max_dt is None
                     or abs(recs_a[i].timestamp - recs_b[j].timestamp) <= max_dt)
            ]

        return WindowResult(start, end, Deferred(m_dev, collect))

    def _nb_layers(self, radius):
        # radius 0 => all cells neighbors (UniformGrid.java:264-266)
        return self.grid.n if radius == 0 else self.grid.candidate_layers(radius)

    def _join_block(self, batch_a, recs_a, batch_b, recs_b, radius):
        """Pane-pair block for the geometry pairs: the same lattice kernel
        (single-device or broadcast-sharded) over pre-built pane batches,
        with the pair extraction DEFERRED — blocks stay in flight on device
        until the first covering window's readback."""
        import numpy as np

        if self.distributed:
            from spatialflink_tpu.parallel.ops import (
                distributed_stream_join_lattice,
            )

            m_dev = self._eval_degradable(
                lambda: self._lattice(batch_a, batch_b, radius),
                lambda mesh, sa: distributed_stream_join_lattice(
                    mesh, sa, batch_b,
                    lambda a_s, b_r: self._lattice(a_s, b_r, radius)),
                batch_a)
        else:
            m_dev = self._lattice(batch_a, batch_b, radius)

        def collect(m):
            ai, bi = np.nonzero(np.asarray(m))
            return [
                (recs_a[i], recs_b[j])
                for i, j in zip(ai.tolist(), bi.tolist())
                if i < len(recs_a) and j < len(recs_b)
            ]

        return Deferred(m_dev, collect)


class PointGeomJoinQuery(_GenericStreamJoin):
    """Point stream x polygon/linestring query stream
    (``join/PointPolygonJoinQuery.java``, ``PointLineStringJoinQuery``)."""

    def _batch_a(self, recs, ts_base):
        return self._point_batch(recs, ts_base)

    def _batch_b(self, recs, ts_base):
        return self._geom_batch(recs, ts_base)

    def _lattice(self, a, b, radius):
        from spatialflink_tpu.ops.join import join_point_geom_mask

        return join_point_geom_mask(a, b, radius, self._nb_layers(radius), n=self.grid.n)


class GeomPointJoinQuery(_GenericStreamJoin):
    """Polygon/linestring stream x point query stream
    (``join/PolygonPointJoinQuery.java``, ``LineStringPointJoinQuery``)."""

    def _batch_a(self, recs, ts_base):
        return self._geom_batch(recs, ts_base)

    def _batch_b(self, recs, ts_base):
        return self._point_batch(recs, ts_base)

    def _lattice(self, a, b, radius):
        from spatialflink_tpu.ops.join import join_point_geom_mask

        # reuse the point x geom lattice with sides swapped
        return join_point_geom_mask(b, a, radius, self._nb_layers(radius),
                                    n=self.grid.n).T

    
class GeomGeomJoinQuery(_GenericStreamJoin):
    """Polygon/linestring stream x polygon/linestring query stream
    (``join/PolygonPolygonJoinQuery.java`` + 3 sibling pairs)."""

    def _batch_a(self, recs, ts_base):
        return self._geom_batch(recs, ts_base)

    _batch_b = _batch_a

    def _lattice(self, a, b, radius):
        from spatialflink_tpu.ops.join import join_geom_geom_mask

        return join_geom_geom_mask(a, b, radius, self._nb_layers(radius), n=self.grid.n)


# Reference-named aliases
PointPolygonJoinQuery = PointGeomJoinQuery
PointLineStringJoinQuery = PointGeomJoinQuery
PolygonPointJoinQuery = GeomPointJoinQuery
LineStringPointJoinQuery = GeomPointJoinQuery
PolygonPolygonJoinQuery = GeomGeomJoinQuery
PolygonLineStringJoinQuery = GeomGeomJoinQuery
LineStringPolygonJoinQuery = GeomGeomJoinQuery
LineStringLineStringJoinQuery = GeomGeomJoinQuery
