"""Trajectory (stateful, per-object) kernels.

The reference's trajectory operators are Flink keyed-state machines driven
one tuple at a time (``tStats/TStatsQuery.java:44-150``,
``tAggregate/TAggregateQuery.java:53-377``). The TPU re-design turns each
micro-batch/window into sorted segment computations:

- :func:`tstats_update` — running per-trajectory spatial length / temporal
  length / speed with carried device state. A batch is sorted by
  (objID, ts); per-object runs become segments; the reference's sequential
  ValueState update becomes (gather state) -> (segment prefix sums) ->
  (scatter state), with the out-of-order drop rule (``:118``) expressed as
  "strictly increasing event time within the sorted run and above the
  carried last_ts".
- :func:`taggregate_window` — per-cell heatmap of trajectory lengths
  (max_ts - min_ts per (cell, objID) group) with SUM/AVG/MIN/MAX/COUNT
  aggregation as dense segment reductions over the n*n cell array.

All outputs are in *sorted* order with an ``order`` array mapping back to
the input batch positions.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from spatialflink_tpu.models.batches import PointBatch
from spatialflink_tpu.ops import distances as D
from spatialflink_tpu.utils.deviceplane import instrumented_jit

INT32_MIN = np.int32(-(2**31))
_OID_SENTINEL = np.int32(2**31 - 1)


class TrajStatsState(NamedTuple):
    """Per-object carried state, sized (M,) for M interned object ids."""

    last_x: jnp.ndarray   # f32
    last_y: jnp.ndarray   # f32
    last_ts: jnp.ndarray  # i32; INT32_MIN = uninitialized
    spatial: jnp.ndarray  # f32 running spatial length (degrees)
    temporal: jnp.ndarray # f32 running temporal length (ms); f32 so decade-
                          # scale cumulative spans don't wrap int32 (precision
                          # ~0.5s at year scale — speed is the consumer)

    @staticmethod
    def zeros(m: int) -> "TrajStatsState":
        return TrajStatsState(
            last_x=jnp.zeros(m, jnp.float32),
            last_y=jnp.zeros(m, jnp.float32),
            last_ts=jnp.full(m, INT32_MIN, jnp.int32),
            spatial=jnp.zeros(m, jnp.float32),
            temporal=jnp.zeros(m, jnp.float32),
        )


class TStatsOut(NamedTuple):
    """Per-input-point emissions, in sorted (objID, ts) order."""

    obj_id: jnp.ndarray    # (N,) i32
    spatial: jnp.ndarray   # (N,) f32 running spatial length
    temporal: jnp.ndarray  # (N,) f32 running temporal length (ms)
    speed: jnp.ndarray     # (N,) f32 spatial/temporal
    emit: jnp.ndarray      # (N,) bool — reference emits only in-order,
                           # state-initialized tuples
    order: jnp.ndarray     # (N,) i32 original batch position


def _propagate_run_value(value_at_first, is_first):
    """Broadcast a per-run scalar (defined at run-first positions) across the
    run, relying on the values being nondecreasing across runs (true for
    cumsum offsets, since contributions are non-negative). Dtype-generic:
    uses the dtype's minimum as the seed for non-first positions."""
    if jnp.issubdtype(value_at_first.dtype, jnp.floating):
        lo = -jnp.inf
    else:
        lo = jnp.iinfo(value_at_first.dtype).min
    seeded = jnp.where(is_first, value_at_first, lo)
    return jax.lax.cummax(seeded)


@partial(instrumented_jit, donate_argnums=(0,))
def tstats_update(state: TrajStatsState, batch: PointBatch):
    """-> (new_state, TStatsOut). Batch obj_id must be < state size."""
    n = batch.x.shape[0]
    m = state.last_x.shape[0]

    oid = jnp.where(batch.valid, batch.obj_id, _OID_SENTINEL)
    order0 = jnp.arange(n, dtype=jnp.int32)
    oid_s, ts_s, x_s, y_s, order = jax.lax.sort(
        (oid, batch.ts, batch.x, batch.y, order0), num_keys=2
    )
    valid_s = oid_s != _OID_SENTINEL
    safe_oid = jnp.where(valid_s, oid_s, 0)

    prev_oid = jnp.concatenate([jnp.full((1,), -1, jnp.int32), oid_s[:-1]])
    run_first = oid_s != prev_oid

    st_last_ts = state.last_ts[safe_oid]
    # accepted: strictly newer than the carried state AND first of its exact
    # (oid, ts) group — sorted order makes both checks locally evaluable
    prev_ts = jnp.concatenate([jnp.full((1,), INT32_MIN, jnp.int32), ts_s[:-1]])
    tie = (~run_first) & (ts_s == prev_ts)
    accepted = valid_s & ~tie & (ts_s > st_last_ts)

    # previous *accepted* element of the same object (in-batch link)
    pos = jnp.where(accepted, jnp.arange(n, dtype=jnp.int32), -1)
    prev_acc_pos = jnp.concatenate([jnp.full((1,), -1, jnp.int32),
                                    jax.lax.cummax(pos)[:-1]])
    has_batch_prev = (prev_acc_pos >= 0) & (
        oid_s[jnp.maximum(prev_acc_pos, 0)] == oid_s
    )
    gp = jnp.maximum(prev_acc_pos, 0)
    state_init = st_last_ts != INT32_MIN
    px = jnp.where(has_batch_prev, x_s[gp], state.last_x[safe_oid])
    py = jnp.where(has_batch_prev, y_s[gp], state.last_y[safe_oid])
    pts = jnp.where(has_batch_prev, ts_s[gp], st_last_ts)
    has_prev = has_batch_prev | state_init

    emit = accepted & has_prev
    contrib_d = jnp.where(emit, D.pp_dist(px, py, x_s, y_s), 0.0)
    # time deltas: exact int32 subtraction, then f32 for accumulation. The
    # subtraction cannot wrap because batch offsets are split host-side to
    # |off| <= 2^30 and rebased dormant state clamps at -(2^30)+1 (operator
    # invariants), so |ts_s - pts| < 2^31. The f32 cast is exact below
    # 2^24 ms (~4.6h gaps); beyond that the delta rounds by <= 128 ms —
    # negligible against such gaps.
    contrib_t = jnp.where(emit, (ts_s - pts).astype(jnp.float32), 0.0)

    # running totals: carried base + within-run prefix sums
    cd = jnp.cumsum(contrib_d)
    ct = jnp.cumsum(contrib_t)
    base_d = _propagate_run_value(cd - contrib_d, run_first)
    base_t = _propagate_run_value(ct - contrib_t, run_first)
    run_d = state.spatial[safe_oid] + (cd - base_d).astype(jnp.float32)
    run_t = state.temporal[safe_oid] + (ct - base_t)
    speed = jnp.where(run_t > 0, run_d / run_t, 0.0)

    # ---- state scatter ------------------------------------------------- #
    seg = safe_oid
    upd_d = jax.ops.segment_sum(contrib_d, seg, num_segments=m)
    upd_t = jax.ops.segment_sum(contrib_t, seg, num_segments=m)
    acc_ts = jnp.where(accepted, ts_s, INT32_MIN)
    new_last_ts_seg = jax.ops.segment_max(acc_ts, seg, num_segments=m)
    new_last_ts = jnp.maximum(state.last_ts, new_last_ts_seg)

    # coords of the newest accepted element per object: accepted ts are
    # strictly increasing within a run, so the match below is unique
    is_newest = accepted & (ts_s == new_last_ts_seg[safe_oid])
    scat = jnp.where(is_newest, safe_oid, m)  # m = dropped (out of bounds)
    new_last_x = state.last_x.at[scat].set(x_s, mode="drop")
    new_last_y = state.last_y.at[scat].set(y_s, mode="drop")

    new_state = TrajStatsState(
        last_x=new_last_x,
        last_y=new_last_y,
        last_ts=new_last_ts,
        spatial=state.spatial + upd_d,
        temporal=state.temporal + upd_t,
    )
    out = TStatsOut(obj_id=oid_s, spatial=run_d, temporal=run_t, speed=speed,
                    emit=emit, order=order)
    return new_state, out


class TStatsWindowSummary(NamedTuple):
    """Per-trajectory (M,) shard summary of one WINDOW slice — the mergeable
    form of the windowed tStats reduction: within-shard pair sums plus the
    boundary data (first/last accepted point) a cross-shard stitch needs.
    Requires the window's records to be globally sorted by (objID, ts) and
    (objID, ts)-deduplicated BEFORE contiguous sharding, so each shard holds
    a contiguous slice of every trajectory's global run and the stitch pair
    (last of shard i, first of shard i+1) is exactly the pair the
    single-device sorted cumsum would have linked."""

    spatial: jnp.ndarray   # (M,) f32 within-shard consecutive-pair distance
    count: jnp.ndarray     # (M,) i32 accepted points in this shard
    min_ts: jnp.ndarray    # (M,) i32 (INT32_MAX where absent)
    max_ts: jnp.ndarray    # (M,) i32 (INT32_MIN where absent)
    first_x: jnp.ndarray   # (M,) f32 earliest accepted point
    first_y: jnp.ndarray
    last_x: jnp.ndarray    # (M,) f32 latest accepted point
    last_y: jnp.ndarray


@partial(instrumented_jit, static_argnames=("m",))
def tstats_window_summary(batch: PointBatch, *, m: int) -> TStatsWindowSummary:
    """Fresh-state (windowed) per-trajectory stats of one shard slice."""
    n = batch.x.shape[0]
    oid = jnp.where(batch.valid, batch.obj_id, _OID_SENTINEL)
    order0 = jnp.arange(n, dtype=jnp.int32)
    oid_s, ts_s, x_s, y_s, _ = jax.lax.sort(
        (oid, batch.ts, batch.x, batch.y, order0), num_keys=2)
    valid_s = oid_s != _OID_SENTINEL
    safe_oid = jnp.where(valid_s, oid_s, 0)

    prev_oid = jnp.concatenate([jnp.full((1,), -1, jnp.int32), oid_s[:-1]])
    run_first = oid_s != prev_oid
    prev_ts = jnp.concatenate([jnp.full((1,), INT32_MIN, jnp.int32), ts_s[:-1]])
    # fresh state: drop only exact (oid, ts) duplicates (tstats_update's tie
    # rule with st_last_ts uninitialized)
    accepted = valid_s & ~((~run_first) & (ts_s == prev_ts))

    pos = jnp.where(accepted, jnp.arange(n, dtype=jnp.int32), -1)
    prev_acc_pos = jnp.concatenate([jnp.full((1,), -1, jnp.int32),
                                    jax.lax.cummax(pos)[:-1]])
    has_batch_prev = (prev_acc_pos >= 0) & (
        oid_s[jnp.maximum(prev_acc_pos, 0)] == oid_s)
    gp = jnp.maximum(prev_acc_pos, 0)
    pair = accepted & has_batch_prev
    contrib_d = jnp.where(pair, D.pp_dist(x_s[gp], y_s[gp], x_s, y_s), 0.0)

    seg = safe_oid
    spatial = jax.ops.segment_sum(jnp.where(accepted, contrib_d, 0.0), seg,
                                  num_segments=m)
    count = jax.ops.segment_sum(accepted.astype(jnp.int32), seg,
                                num_segments=m)
    min_ts = jax.ops.segment_min(
        jnp.where(accepted, ts_s, _OID_SENTINEL), seg, num_segments=m)
    max_ts = jax.ops.segment_max(
        jnp.where(accepted, ts_s, INT32_MIN), seg, num_segments=m)

    # boundary coords: the earliest / latest accepted point per trajectory
    # (unique matches — accepted ts are strictly increasing within a run)
    is_first = accepted & (ts_s == min_ts[safe_oid])
    is_last = accepted & (ts_s == max_ts[safe_oid])
    fx = jnp.zeros(m, jnp.float32).at[
        jnp.where(is_first, safe_oid, m)].set(x_s, mode="drop")
    fy = jnp.zeros(m, jnp.float32).at[
        jnp.where(is_first, safe_oid, m)].set(y_s, mode="drop")
    lx = jnp.zeros(m, jnp.float32).at[
        jnp.where(is_last, safe_oid, m)].set(x_s, mode="drop")
    ly = jnp.zeros(m, jnp.float32).at[
        jnp.where(is_last, safe_oid, m)].set(y_s, mode="drop")
    return TStatsWindowSummary(spatial=spatial, count=count, min_ts=min_ts,
                               max_ts=max_ts, first_x=fx, first_y=fy,
                               last_x=lx, last_y=ly)


@instrumented_jit
def tstats_stitch_summaries(tabs: TStatsWindowSummary):
    """Merge (D, M) shard summaries (shard-major, in GLOBAL slice order) into
    final per-trajectory stats: spatial = Σ within-shard sums + the boundary
    links d(last of previous present shard, first of next); temporal =
    global max_ts - min_ts. Returns (spatial (M,), temporal_ms (M,) i32,
    count (M,)) — a trajectory emits iff count >= 2, matching the
    single-device pair rule."""
    m = tabs.spatial.shape[1]

    def step(carry, row):
        has, plx, ply = carry
        present = row.count > 0
        link = has & present
        add = jnp.where(
            link, D.pp_dist(plx, ply, row.first_x, row.first_y), 0.0)
        nlx = jnp.where(present, row.last_x, plx)
        nly = jnp.where(present, row.last_y, ply)
        return (has | present, nlx, nly), add

    init = (jnp.zeros(m, bool), jnp.zeros(m, jnp.float32),
            jnp.zeros(m, jnp.float32))
    _, adds = jax.lax.scan(step, init, tabs)
    spatial = tabs.spatial.sum(0) + adds.sum(0)
    count = tabs.count.sum(0)
    mn = tabs.min_ts.min(0)
    mx = tabs.max_ts.max(0)
    temporal = jnp.where(count > 0, mx - mn, 0)
    return spatial, temporal, count


def tstats_stitch_host(parts):
    """NumPy stitch of per-PANE window summaries (the pane engine's twin of
    :func:`tstats_stitch_summaries`): ``parts`` is a time-ordered list of
    dicts with keys ``spatial``/``count``/``min_ts``/``max_ts`` (absolute
    int64 ms) /``first_x``/``first_y``/``last_x``/``last_y``, each sized to
    its pane's interner bucket — shorter tables are padded with
    absent-trajectory defaults (later panes can only ADD trajectories).
    Returns ``(spatial (M,) f32, temporal_ms (M,) i64, count (M,))``;
    trajectories emit iff count >= 2, like the single-device pair rule.

    Panes partition event time, so each pane's (objID, ts)-sorted run is a
    contiguous slice of the window's global sorted run and the boundary
    link d(last of previous present pane, first of next) is exactly the
    consecutive pair the single-device cumsum would have measured — the
    same argument as the contiguous shard stitch, with panes in place of
    shards. Host numpy because pane extents are ABSOLUTE ms (per-pane
    batches have different int32 offset bases) and overlap-many tiny tables
    don't warrant a dispatch."""
    i64 = np.int64
    M = max(p["count"].shape[0] for p in parts)

    def pad(a, fill, dtype=None):
        if a.shape[0] == M:
            return a
        out = np.full(M, fill, dtype or a.dtype)
        out[: a.shape[0]] = a
        return out

    spatial = np.zeros(M, np.float32)
    count = np.zeros(M, i64)
    min_ts = np.full(M, np.iinfo(i64).max, i64)
    max_ts = np.full(M, np.iinfo(i64).min, i64)
    has = np.zeros(M, bool)
    plx = np.zeros(M, np.float32)
    ply = np.zeros(M, np.float32)
    for p in parts:
        cnt = pad(p["count"], 0)
        present = cnt > 0
        link = has & present
        fx, fy = pad(p["first_x"], 0.0), pad(p["first_y"], 0.0)
        dx = (fx - plx).astype(np.float32)
        dy = (fy - ply).astype(np.float32)
        spatial += pad(p["spatial"], 0.0) + np.where(
            link, np.hypot(dx, dy).astype(np.float32), np.float32(0.0))
        count += cnt
        min_ts = np.minimum(min_ts, pad(p["min_ts"], np.iinfo(i64).max))
        max_ts = np.maximum(max_ts, pad(p["max_ts"], np.iinfo(i64).min))
        lx, ly = pad(p["last_x"], 0.0), pad(p["last_y"], 0.0)
        plx = np.where(present, lx, plx)
        ply = np.where(present, ly, ply)
        has |= present
    temporal = np.where(count > 0, max_ts - min_ts, 0)
    return spatial, temporal, count


def taggregate_merge_extents_host(parts):
    """Merge per-pane (cell, objID, min_ts, max_ts) extent ROWS into the
    window's final per-group extents — the pane twin of
    :func:`taggregate_merge_extents`, on host because pane extents carry
    absolute int64 timestamps. ``parts`` is a list of ``(cells, oids,
    min_ts, max_ts)`` array tuples; returns the merged dict
    ``{(cell, oid): (min_ts, max_ts)}``."""
    merged: dict = {}
    for cells, oids, mns, mxs in parts:
        for c, o, mn, mx in zip(cells.tolist(), oids.tolist(),
                                mns.tolist(), mxs.tolist()):
            key = (c, o)
            cur = merged.get(key)
            if cur is None:
                merged[key] = (mn, mx)
            else:
                merged[key] = (min(cur[0], mn), max(cur[1], mx))
    return merged


# ------------------------------------------------------------------------- #
# TAggregate: per-cell heatmap of trajectory lengths


class TAggregateGroups(NamedTuple):
    """Per-(cell, objID) groups of a window, in sorted order."""

    cell: jnp.ndarray     # (N,) i32 group cell (garbage where ~first)
    obj_id: jnp.ndarray   # (N,) i32 group object
    length: jnp.ndarray   # (N,) i32 max_ts - min_ts of the group
    first: jnp.ndarray    # (N,) bool marks group representatives


class TAggregateExtents(NamedTuple):
    """Per-(cell, objID) group ts-extents of a window, in sorted order — the
    MERGEABLE form of :class:`TAggregateGroups` (min/max compose across
    shards; a length does not, since a group split at a shard boundary must
    merge extents before measuring)."""

    cell: jnp.ndarray     # (N,) i32 group cell (sentinel num_cells where pad)
    obj_id: jnp.ndarray   # (N,) i32 group object
    min_ts: jnp.ndarray   # (N,) i32 group min timestamp
    max_ts: jnp.ndarray   # (N,) i32 group max timestamp
    first: jnp.ndarray    # (N,) bool marks group representatives


@partial(instrumented_jit, static_argnames=("num_cells",))
def taggregate_group_extents(batch: PointBatch, *,
                             num_cells: int) -> TAggregateExtents:
    """Group a window by (cell, objID) with per-group [min_ts, max_ts]
    extents (``tAggregate/TAggregateQuery.java:381-494``)."""
    n = batch.x.shape[0]
    ok = batch.valid & (batch.cell >= 0)
    cell = jnp.where(ok, batch.cell, num_cells)  # sentinel cell sorts last
    oid = jnp.where(ok, batch.obj_id, _OID_SENTINEL)
    cell_s, oid_s, ts_s = jax.lax.sort((cell, oid, batch.ts), num_keys=3)

    prev_cell = jnp.concatenate([jnp.full((1,), -1, jnp.int32), cell_s[:-1]])
    prev_oid = jnp.concatenate([jnp.full((1,), -1, jnp.int32), oid_s[:-1]])
    first = ((cell_s != prev_cell) | (oid_s != prev_oid)) & (cell_s < num_cells)

    gid = jnp.cumsum(first.astype(jnp.int32)) - 1  # dense group ids
    gid = jnp.where(cell_s < num_cells, gid, n - 1)
    min_ts = jax.ops.segment_min(ts_s, gid, num_segments=n)
    max_ts = jax.ops.segment_max(ts_s, gid, num_segments=n)
    return TAggregateExtents(cell=cell_s, obj_id=oid_s, min_ts=min_ts[gid],
                             max_ts=max_ts[gid], first=first)


@partial(instrumented_jit, static_argnames=("num_cells",))
def taggregate_groups(batch: PointBatch, *, num_cells: int) -> TAggregateGroups:
    """Group a window by (cell, objID); per-group trajectory length =
    max - min timestamp (``tAggregate/TAggregateQuery.java:381-494``)."""
    e = taggregate_group_extents(batch, num_cells=num_cells)
    return TAggregateGroups(cell=e.cell, obj_id=e.obj_id,
                            length=e.max_ts - e.min_ts, first=e.first)


@partial(instrumented_jit, static_argnames=("num_cells",))
def taggregate_merge_extents(cell, oid, min_ts, max_ts, *,
                             num_cells: int) -> TAggregateGroups:
    """Merge (cell, objID) group-extent tables into final groups — the
    second stage of the distributed window: per-shard representatives (with
    non-representatives blanked to the sentinel cell) are gathered,
    re-sorted, and extent-merged, so a group split across shards measures
    max-over-shards minus min-over-shards exactly like the single-device
    sort would have."""
    n = cell.shape[0]
    cell_s, oid_s, mn_s, mx_s = jax.lax.sort((cell, oid, min_ts, max_ts),
                                             num_keys=2)
    prev_cell = jnp.concatenate([jnp.full((1,), -1, jnp.int32), cell_s[:-1]])
    prev_oid = jnp.concatenate([jnp.full((1,), -1, jnp.int32), oid_s[:-1]])
    first = ((cell_s != prev_cell) | (oid_s != prev_oid)) & (cell_s < num_cells)
    gid = jnp.cumsum(first.astype(jnp.int32)) - 1
    gid = jnp.where(cell_s < num_cells, gid, n - 1)
    g_min = jax.ops.segment_min(mn_s, gid, num_segments=n)
    g_max = jax.ops.segment_max(mx_s, gid, num_segments=n)
    return TAggregateGroups(cell=cell_s, obj_id=oid_s,
                            length=(g_max - g_min)[gid], first=first)


@partial(instrumented_jit, static_argnames=("num_cells", "agg"))
def taggregate_heatmap(groups: TAggregateGroups, *, num_cells: int, agg: str):
    """Dense (num_cells,) heatmap from (cell, objID) groups.

    agg in {SUM, AVG, MIN, MAX, COUNT} (conf aggregate,
    ``geoflink-conf.yml:53``; ALL is served by the groups themselves)."""
    cell = jnp.where(groups.first, groups.cell, num_cells)
    length = groups.length.astype(jnp.float32)
    if agg in ("SUM", "AVG"):
        total = jax.ops.segment_sum(
            jnp.where(groups.first, length, 0.0), cell, num_segments=num_cells + 1
        )
        if agg == "SUM":
            return total[:num_cells]
        count = jax.ops.segment_sum(
            groups.first.astype(jnp.float32), cell, num_segments=num_cells + 1
        )
        return jnp.where(count[:num_cells] > 0, total[:num_cells] / count[:num_cells], 0.0)
    if agg == "COUNT":
        return jax.ops.segment_sum(
            groups.first.astype(jnp.float32), cell, num_segments=num_cells + 1
        )[:num_cells]
    if agg == "MIN":
        v = jax.ops.segment_min(
            jnp.where(groups.first, length, jnp.inf), cell, num_segments=num_cells + 1
        )[:num_cells]
        return jnp.where(jnp.isfinite(v), v, 0.0)
    if agg == "MAX":
        v = jax.ops.segment_max(
            jnp.where(groups.first, length, -jnp.inf), cell, num_segments=num_cells + 1
        )[:num_cells]
        return jnp.where(jnp.isfinite(v), v, 0.0)
    raise ValueError(f"unknown aggregate {agg!r}")
