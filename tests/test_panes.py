"""Pane-incremental window engine (--panes): equivalence + cache behavior.

Headline invariant: pane-incremental execution is an EXECUTION STRATEGY,
not a semantics change — for every supported family (range, kNN, join,
tRange, tStats, tAggregate) and every arrival pattern (in-order,
out-of-order, late-dropped, chaos-replayed), the pane window tables are
identical to full-recompute tables (exact for selections/ids, tolerance
for float aggregates whose summation order legitimately differs).

Fast tests (default marker set): the PaneBuffer unit contract against the
independent tests/oracles.py window oracle, per-family equivalence on
small streams, and the pane-cache smoke test asserting hit/miss counters +
pane-merge telemetry spans. The broad fuzz sweeps and the --chaos replay
identity are marked ``slow``.
"""

import json

import numpy as np
import pytest
import yaml

from spatialflink_tpu.index import UniformGrid
from spatialflink_tpu.models import Point, Polygon
from spatialflink_tpu.operators import (
    PointPointJoinQuery,
    PointPointKNNQuery,
    PointPointRangeQuery,
    QueryConfiguration,
    QueryType,
)
from spatialflink_tpu.operators.trajectory import (
    PointPolygonTRangeQuery,
    PointTAggregateQuery,
    PointTStatsQuery,
)
from spatialflink_tpu.runtime.windows import PaneBuffer, WindowAssembler, WindowSpec
from spatialflink_tpu.utils.metrics import scoped_registry
from spatialflink_tpu.utils.telemetry import telemetry_session
from tests import oracles as O

GRID = UniformGrid(115.50, 117.60, 39.60, 41.10, num_grid_partitions=100)
QUERY = Point.create(116.5, 40.5, GRID, obj_id="q")
POLY = Polygon.create(
    [[(116.0, 40.0), (117.0, 40.0), (117.0, 40.8), (116.0, 40.8)]], GRID)


def conf(panes, size_ms=20_000, slide_ms=5_000, **kw):
    return QueryConfiguration(query_type=QueryType.WindowBased,
                              window_size_ms=size_ms, slide_ms=slide_ms,
                              panes=panes, **kw)


def stream(n=400, seed=0, jitter_ms=0, span_ms=40_000, n_obj=30):
    """Synthetic point stream; ``jitter_ms`` > 0 makes arrivals
    out-of-order (and, with lateness 0, exercises late drops)."""
    r = np.random.default_rng(seed)
    ts = np.sort(r.integers(0, span_ms, n))
    if jitter_ms:
        ts = ts + r.integers(-jitter_ms, jitter_ms + 1, n)
    return [
        Point.create(float(x), float(y), GRID, obj_id=f"v{int(o)}",
                     timestamp=int(t))
        for x, y, o, t in zip(r.uniform(115.6, 117.5, n),
                              r.uniform(39.7, 41.0, n),
                              r.integers(0, n_obj, n), ts)
    ]


# --------------------------------------------------------------------- #
# PaneBuffer unit contract


class TestPaneBuffer:
    def test_rejects_non_decomposable_specs(self):
        with pytest.raises(ValueError):
            PaneBuffer(WindowSpec.sliding(10_000, 10_000))  # tumbling
        with pytest.raises(ValueError):
            PaneBuffer(WindowSpec.sliding(10_000, 4_000))  # slide !| size

    @pytest.mark.parametrize("jitter,lateness", [(0, 0), (1500, 0),
                                                 (1500, 2000)])
    def test_matches_assembler_and_oracle(self, jitter, lateness):
        spec = WindowSpec.sliding(15_000, 5_000)
        recs = stream(n=300, seed=4, jitter_ms=jitter)
        wa = WindowAssembler(spec, lateness)
        pb = PaneBuffer(spec, lateness)
        ref, pane = [], []
        for r in recs:
            ref += list(wa.add(r.timestamp, r))
            pane += list(pb.add(r.timestamp, r))
        ref += list(wa.flush())
        pane += list(pb.flush())
        flat = [(s, e, sorted(O.canon_point(p) for _, rs in panes
                              for p in rs)) for s, e, panes in pane]
        rf = [(s, e, sorted(O.canon_point(p) for p in rs))
              for s, e, rs in ref]
        assert flat == rf
        assert pb.late_dropped == wa.late_dropped
        # independent oracle: window starts + membership counts
        oracle = O.sliding_window_table([r.timestamp for r in recs],
                                        spec.size_ms, spec.slide_ms,
                                        lateness)
        assert sorted(s for s, _, _ in rf) == sorted(oracle)
        counts = {s: len(idx) for s, idx in oracle.items()}
        assert {s: len(r) for s, _, r in rf} == counts

    def test_each_record_buffered_once(self):
        spec = WindowSpec.sliding(20_000, 5_000)
        pb = PaneBuffer(spec)
        for r in stream(n=100, seed=1):
            list(pb.add(r.timestamp, r))
        assert sum(len(v) for v in pb._panes.values()) <= 100


# --------------------------------------------------------------------- #
# fast per-family equivalence (default marker set)


def canon_range(results):
    return O.canon_windows(results, O.canon_point)


def canon_knn(results):
    return O.canon_windows(results, O.canon_knn_pair)


def canon_join(results):
    return O.canon_windows(
        results, lambda ab: (O.canon_point(ab[0]), O.canon_point(ab[1])))


class TestFamilyEquivalence:
    def test_range(self):
        s = stream(jitter_ms=1200, seed=2)
        off = canon_range(PointPointRangeQuery(conf(False), GRID)
                          .run(iter(s), QUERY, 0.4))
        on = canon_range(PointPointRangeQuery(conf(True), GRID)
                         .run(iter(s), QUERY, 0.4))
        assert off == on and off

    def test_knn(self):
        s = stream(jitter_ms=800, seed=3)
        off = canon_knn(PointPointKNNQuery(conf(False), GRID)
                        .run(iter(s), QUERY, 0.5, 7))
        on = canon_knn(PointPointKNNQuery(conf(True), GRID)
                       .run(iter(s), QUERY, 0.5, 7))
        assert off == on and off

    def test_join(self):
        a, b = stream(n=250, seed=5, jitter_ms=600), stream(n=80, seed=6,
                                                            jitter_ms=600)
        off = canon_join(PointPointJoinQuery(conf(False), GRID, GRID)
                         .run(iter(a), iter(b), 0.2))
        on = canon_join(PointPointJoinQuery(conf(True), GRID, GRID)
                        .run(iter(a), iter(b), 0.2))
        assert off == on and any(r for _, _, r in off)

    def test_trange(self):
        s = stream(seed=7, jitter_ms=500)
        def canon(results):
            return [(r.window_start, sorted(r.extras["matched_ids"]),
                     sorted((getattr(g, "obj_id", ""), type(g).__name__)
                            for g in r.records)) for r in results]
        off = canon(PointPolygonTRangeQuery(conf(False), GRID)
                    .run(iter(s), [POLY]))
        on = canon(PointPolygonTRangeQuery(conf(True), GRID)
                   .run(iter(s), [POLY]))
        assert off == on and off

    def test_tstats(self):
        s = stream(seed=8, jitter_ms=500)
        off = list(PointTStatsQuery(conf(False), GRID).run(iter(s)))
        on = list(PointTStatsQuery(conf(True), GRID).run(iter(s)))
        _assert_tstats_equal(off, on)

    @pytest.mark.parametrize("agg", ["SUM", "AVG", "MIN", "MAX", "COUNT",
                                     "ALL"])
    def test_taggregate(self, agg):
        s = stream(seed=9, jitter_ms=400)
        off = list(PointTAggregateQuery(conf(False), GRID).run(iter(s), agg))
        on = list(PointTAggregateQuery(conf(True), GRID).run(iter(s), agg))
        _assert_taggregate_equal(off, on, agg)

    def test_run_multi_range_and_knn(self):
        s = stream(seed=10)
        qs = [QUERY, Point.create(116.0, 40.0, GRID, obj_id="q2")]
        for cls, args, canon in (
                (PointPointRangeQuery, (qs, 0.4), O.canon_point),
                (PointPointKNNQuery, (qs, 0.5, 5), O.canon_knn_pair)):
            def canon_multi(results):
                return [(r.window_start,
                         [sorted(canon(x) for x in per_q)
                          for per_q in r.records]) for r in results]
            off = canon_multi(cls(conf(False), GRID).run_multi(iter(s), *args))
            on = canon_multi(cls(conf(True), GRID).run_multi(iter(s), *args))
            assert off == on and off

    def test_bulk_range_and_knn(self):
        from spatialflink_tpu.streams.bulk import bulk_parse_csv

        r = np.random.default_rng(11)
        n = 3000
        ts = 1_700_000_000_000 + np.sort(r.integers(0, 60_000, n))
        lines = "".join(
            f"v{int(o)},{t},{x:.6f},{y:.6f}\n"
            for o, t, x, y in zip(r.integers(0, 50, n), ts,
                                  r.uniform(115.6, 117.5, n),
                                  r.uniform(39.7, 41.0, n)))
        parsed = bulk_parse_csv(lines.encode(), date_format=None)
        for cls, run in (
                (PointPointRangeQuery,
                 lambda op: op.run_bulk(parsed, QUERY, 0.4)),
                (PointPointKNNQuery,
                 lambda op: op.run_bulk(parsed, QUERY, 0.5, 7))):
            off = [(r2.window_start, sorted(map(_canon_any, r2.records)))
                   for r2 in run(cls(conf(False), GRID))]
            on = [(r2.window_start, sorted(map(_canon_any, r2.records)))
                  for r2 in run(cls(conf(True), GRID))]
            assert off == on and off

    def test_tumbling_bypasses_cache(self):
        s = stream(seed=12)
        with scoped_registry() as reg:
            off = canon_range(
                PointPointRangeQuery(conf(False, 10_000, 10_000), GRID)
                .run(iter(s), QUERY, 0.4))
            on = canon_range(
                PointPointRangeQuery(conf(True, 10_000, 10_000), GRID)
                .run(iter(s), QUERY, 0.4))
            assert off == on
            assert reg.counter("pane-cache-hits").count == 0
            assert reg.counter("pane-cache-misses").count == 0


def _canon_any(rec):
    if isinstance(rec, tuple):
        return (rec[0], round(float(rec[1]), 6))
    return rec


def _assert_tstats_equal(off, on, tol_spatial=1e-3, tol_temporal=1):
    assert [(r.window_start, r.window_end) for r in off] == \
           [(r.window_start, r.window_end) for r in on]
    for a, b in zip(off, on):
        da = {t[0]: t[1:] for t in a.records}
        db = {t[0]: t[1:] for t in b.records}
        assert set(da) == set(db), a.window_start
        for oid in da:
            assert abs(da[oid][0] - db[oid][0]) < tol_spatial
            assert abs(da[oid][1] - db[oid][1]) <= tol_temporal


def _assert_taggregate_equal(off, on, agg):
    assert len(off) == len(on)
    for a, b in zip(off, on):
        assert (a.window_start, a.window_end) == (b.window_start,
                                                  b.window_end)
        if agg == "ALL":
            assert sorted(a.records) == sorted(b.records)
        else:
            np.testing.assert_allclose(a.extras["heatmap"],
                                       b.extras["heatmap"],
                                       rtol=1e-5, atol=1e-3)


# --------------------------------------------------------------------- #
# pane-cache smoke: counters + telemetry (default marker set)


class TestPaneCacheSmoke:
    def test_hit_miss_counters_and_merge_spans(self):
        """At overlap o over P panes, the kernel runs once per pane
        (misses == P) and every other pane slot is a cache hit
        (hits == total slots - P); the telemetry snapshot carries the
        pane-merge span and the counters."""
        s = stream(n=300, seed=13)  # in-order, spans [0, 40s)
        overlap, slide = 4, 5_000
        with scoped_registry() as reg, telemetry_session() as tel:
            results = list(PointPointRangeQuery(
                conf(True, overlap * slide, slide), GRID)
                .run(iter(s), QUERY, 0.4))
            snap = tel.snapshot()
        panes = {p.timestamp - p.timestamp % slide for p in s}
        misses = reg.counter("pane-cache-misses").count
        hits = reg.counter("pane-cache-hits").count
        assert misses == len(panes)
        total_slots = sum(
            1 for r in results
            for p in range(r.window_start,
                           r.window_start + overlap * slide, slide)
            if p in panes)
        assert hits + misses == total_slots
        assert hits > 0
        assert "range.pane-merge" in snap["spans"]
        assert snap["spans"]["range.pane-merge"]["count"] == len(results)
        assert snap["counters"]["pane-cache-hits"] == hits
        assert snap["counters"]["pane-cache-misses"] == misses

    def test_kernel_work_drops_with_overlap(self):
        """batches-evaluated counts kernel dispatches: panes-off runs one
        per window; panes-on one per window too (the merge Deferred), but
        records-evaluated stays the same while actual pane kernels =
        misses << windows * overlap panes."""
        s = stream(n=400, seed=14)
        with scoped_registry() as reg:
            list(PointPointRangeQuery(conf(True, 40_000, 5_000), GRID)
                 .run(iter(s), QUERY, 0.4))
            misses = reg.counter("pane-cache-misses").count
            hits = reg.counter("pane-cache-hits").count
        # overlap 8: >= 7/8 of pane evaluations served from cache at
        # steady state (edges lower the ratio slightly)
        assert hits >= 2 * misses


# --------------------------------------------------------------------- #
# broad fuzz + chaos replay (slow)


@pytest.mark.slow
class TestPaneFuzz:
    @pytest.mark.parametrize("seed", range(8))
    def test_fuzz_all_families(self, seed):
        r = np.random.default_rng(seed)
        overlap = int(r.choice([2, 3, 4, 8]))
        slide = int(r.choice([2_000, 5_000]))
        lateness = int(r.choice([0, 1_000, 3_000]))
        jitter = int(r.choice([0, 500, 2_500]))
        s = stream(n=int(r.integers(50, 500)), seed=seed + 100,
                   jitter_ms=jitter, span_ms=overlap * slide * 5)
        c_off = conf(False, overlap * slide, slide,
                     allowed_lateness_ms=lateness)
        c_on = conf(True, overlap * slide, slide,
                    allowed_lateness_ms=lateness)

        assert canon_range(PointPointRangeQuery(c_off, GRID)
                           .run(iter(s), QUERY, 0.4)) == \
            canon_range(PointPointRangeQuery(c_on, GRID)
                        .run(iter(s), QUERY, 0.4))
        assert canon_knn(PointPointKNNQuery(c_off, GRID)
                         .run(iter(s), QUERY, 0.5, 6)) == \
            canon_knn(PointPointKNNQuery(c_on, GRID)
                      .run(iter(s), QUERY, 0.5, 6))
        b = stream(n=60, seed=seed + 200, jitter_ms=jitter,
                   span_ms=overlap * slide * 5)
        assert canon_join(PointPointJoinQuery(c_off, GRID, GRID)
                          .run(iter(s), iter(b), 0.3)) == \
            canon_join(PointPointJoinQuery(c_on, GRID, GRID)
                       .run(iter(s), iter(b), 0.3))
        _assert_tstats_equal(
            list(PointTStatsQuery(c_off, GRID).run(iter(s))),
            list(PointTStatsQuery(c_on, GRID).run(iter(s))))


@pytest.mark.slow
class TestPaneChaosReplay:
    """--panes under --chaos: the recovered window table of a chaos-injected
    panes-on run is identical to the fault-free panes-off oracle (the PR 1
    invariant, now with the pane engine in the loop)."""

    def test_chaos_replay_identity(self, tmp_path):
        from spatialflink_tpu.driver import main
        from spatialflink_tpu.streams import (KafkaWindowSink,
                                              reset_memory_brokers,
                                              resolve_broker,
                                              serialize_spatial)
        from spatialflink_tpu.streams.sources import SyntheticPointSource

        reset_memory_brokers()
        try:
            with open("conf/spatialflink-conf.yml") as f:
                d = yaml.safe_load(f)
            d["window"].update(interval=20, step=5)
            lines = [serialize_spatial(p, "GeoJSON")
                     for p in SyntheticPointSource(
                         GRID, num_trajectories=8, steps=6, seed=3)]

            def run(name, extra):
                d["kafkaBootStrapServers"] = f"memory://{name}"
                cfg = tmp_path / f"{name}.yml"
                cfg.write_text(yaml.safe_dump(d))
                broker = resolve_broker(f"memory://{name}")
                for ln in lines:
                    broker.produce("points.geojson", ln)
                assert main(["--config", str(cfg), "--kafka",
                             "--option", "1"] + extra) == 0
                table = {}
                for r in broker.fetch("output", 0, 1_000_000):
                    if isinstance(r.key, str) and r.key.startswith(
                            KafkaWindowSink.MARKER):
                        table[r.key[len(KafkaWindowSink.MARKER):]] = \
                            int(r.value)
                return table

            oracle = run("pane-oracle", [])
            chaotic = run("pane-chaos", [
                "--panes",
                "--chaos", "seed=7,fetch_fail=0.2,duplicate=0.3,"
                           "reorder=0.5,latency=0.1,latency_ms=1",
                "--retry", "attempts=12,base_ms=1,max_ms=20"])
            assert oracle and chaotic == oracle
        finally:
            reset_memory_brokers()
