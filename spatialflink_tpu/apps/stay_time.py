"""StayTime app: per-cell accumulated stay time of moving objects, normalized
by sensor coverage (reference: ``apps/StayTime.java:32-485``).

Pipeline parity:

- :meth:`StayTime.cell_stay_time_tuples` ≙ ``CellStayTime`` stage 1
  (``CellStayTimeWinFunction``, ``StayTime.java:227-396``): per trajectory,
  per window, time-sorted consecutive point pairs split their time delta
  across the grid cells traversed.
- :meth:`StayTime.cell_stay_time` ≙ stage 2 per-cell window sum
  (``CellStayTimeAggregateWinFunction``, ``StayTime.java:432-448``).
- :meth:`StayTime.cell_sensor_range_intersection` ≙
  ``CellSensorRangeIntersection`` (``StayTime.java:397-430``): per cell,
  count of distinct timestamps whose sensor polygon intersects the cell
  rectangle.
- :meth:`StayTime.normalized_cell_stay_time` ≙ the windowed join
  (``normalizedCellStayTimeWinFunction``, ``StayTime.java:113-212``):
  ``((stay_ms/1000) / intersections) * window_size_s`` per cell.

Cell-splitting rules for one consecutive pair (last → current), mirroring
``StayTime.java:270-371``:

- same cell: the whole delta goes to that cell;
- same x-index: delta split equally across the inclusive y-range of cells;
- same y-index: split equally across the inclusive x-range;
- both differ: split equally across {last cell, current cell} ∪ cells of the
  segment's bbox whose rectangle the segment geometrically intersects.

The per-pair work is vectorized with numpy per window; this is app-layer
aggregation over already-small per-trajectory groups, not a device kernel.
"""

from __future__ import annotations

from collections import defaultdict
from typing import Dict, Iterable, Iterator, List, Optional, Set, Tuple

import numpy as np

from spatialflink_tpu.index import UniformGrid
from spatialflink_tpu.models import Point, Polygon
from spatialflink_tpu.operators.base import (
    QueryConfiguration,
    SpatialOperator,
    WindowResult,
)


def _segment_intersects_rect(x0, y0, x1, y1, rect) -> bool:
    """Liang–Barsky clip test: does segment (x0,y0)-(x1,y1) hit the rect."""
    rx0, ry0, rx1, ry1 = rect
    dx, dy = x1 - x0, y1 - y0
    t0, t1 = 0.0, 1.0
    for p, q in ((-dx, x0 - rx0), (dx, rx1 - x0),
                 (-dy, y0 - ry0), (dy, ry1 - y0)):
        if p == 0:
            if q < 0:
                return False
            continue
        r = q / p
        if p < 0:
            if r > t1:
                return False
            t0 = max(t0, r)
        else:
            if r < t0:
                return False
            t1 = min(t1, r)
    return t0 <= t1


class StayTime(SpatialOperator):
    """Windowed stay-time pipeline over a :class:`UniformGrid`."""

    # ------------------------------------------------------------------ #
    # stage 1: per-(objID, pair) stay-time shares

    def _pair_shares(self, pts: List[Point]) -> Iterator[Tuple[int, int, int, float]]:
        """-> (t0, t1, cell, share_ms) per traversed cell, for one
        trajectory's time-sorted window points."""
        g = self.grid
        n = g.n
        for prev, cur in zip(pts[:-1], pts[1:]):
            dt = float(cur.timestamp - prev.timestamp)
            c0, c1 = prev.cell, cur.cell
            if c0 < 0 or c1 < 0:
                continue
            cx0, cy0 = divmod(c0, n)
            cx1, cy1 = divmod(c1, n)
            if c0 == c1:
                cells = [c0]
            elif cx0 == cx1:
                lo, hi = min(cy0, cy1), max(cy0, cy1)
                cells = [g.cell_id(cx0, i) for i in range(lo, hi + 1)]
            elif cy0 == cy1:
                lo, hi = min(cx0, cx1), max(cx0, cx1)
                cells = [g.cell_id(i, cy0) for i in range(lo, hi + 1)]
            else:
                cand = g.bbox_cells(min(prev.x, cur.x), min(prev.y, cur.y),
                                    max(prev.x, cur.x), max(prev.y, cur.y))
                hit: Set[int] = {c0, c1}
                for c in cand:
                    if c in hit:
                        continue
                    if _segment_intersects_rect(prev.x, prev.y, cur.x, cur.y,
                                                g.cell_bounds(c)):
                        hit.add(c)
                cells = sorted(hit)
            share = dt / len(cells)
            for c in cells:
                yield (prev.timestamp, cur.timestamp, c, share)

    def cell_stay_time_tuples(self, stream: Iterable[Point],
                              traj_ids: Optional[Set[str]] = None
                              ) -> Iterator[WindowResult]:
        """Per window: (objID, t0, t1, cell, stay_share_ms) tuples
        (``Tuple5``, ``StayTime.java:383-391``)."""
        allowed = set(traj_ids or ())
        for start, end, records in self._windows(stream):
            by_obj: Dict[str, List[Point]] = defaultdict(list)
            for p in records:
                if not allowed or p.obj_id in allowed:
                    by_obj[p.obj_id].append(p)
            out = []
            for oid, pts in by_obj.items():
                pts.sort(key=lambda p: p.timestamp)
                out.extend((oid, t0, t1, c, s)
                           for t0, t1, c, s in self._pair_shares(pts))
            yield WindowResult(start, end, out)

    def cell_stay_time(self, stream: Iterable[Point],
                       traj_ids: Optional[Set[str]] = None
                       ) -> Iterator[WindowResult]:
        """Per window: (cell, summed stay time ms) per touched cell."""
        for res in self.cell_stay_time_tuples(stream, traj_ids):
            sums: Dict[int, float] = defaultdict(float)
            for _oid, _t0, _t1, cell, share in res.records:
                sums[cell] += share
            yield WindowResult(res.window_start, res.window_end,
                               sorted(sums.items()))

    # ------------------------------------------------------------------ #
    # sensor coverage

    def _polygon_intersects_rect(self, poly: Polygon, rect) -> bool:
        """Rect–polygon intersection honoring interior rings: a rect lying
        strictly inside a hole does NOT intersect (JTS semantics in the
        reference's ``cellPoly.intersects(p.polygon)``)."""
        rx0, ry0, rx1, ry1 = rect
        bx0, by0, bx1, by1 = poly.bbox
        if bx1 < rx0 or bx0 > rx1 or by1 < ry0 or by0 > ry1:
            return False
        rings = [np.asarray(r, np.float64) for r in poly.rings]
        for ring in rings:
            # any ring vertex inside the rect → boundary overlaps the rect
            if ((ring[:, 0] >= rx0) & (ring[:, 0] <= rx1)
                    & (ring[:, 1] >= ry0) & (ring[:, 1] <= ry1)).any():
                return True
            # any ring edge (shell OR hole boundary) crossing the rect
            for (x0, y0), (x1, y1) in zip(ring[:-1], ring[1:]):
                if _segment_intersects_rect(x0, y0, x1, y1, rect):
                    return True
        # no boundary contact: the rect is entirely inside polygon material,
        # inside a hole, or outside. Even-odd ray cast over ALL rings
        # classifies one corner (holes flip parity back to outside).
        x, y = rx0, ry0
        crossings = 0
        for ring in rings:
            xs0, ys0 = ring[:-1, 0], ring[:-1, 1]
            xs1, ys1 = ring[1:, 0], ring[1:, 1]
            cond = (ys0 > y) != (ys1 > y)
            with np.errstate(divide="ignore", invalid="ignore"):
                xint = xs0 + (y - ys0) / (ys1 - ys0) * (xs1 - xs0)
            crossings += int((cond & (x < xint)).sum())
        return bool(crossings % 2)

    def cell_sensor_range_intersection(self, polygon_stream: Iterable[Polygon],
                                       traj_ids: Optional[Set[str]] = None
                                       ) -> Iterator[WindowResult]:
        """Per window: (cell, number of distinct timestamps whose polygon
        intersects the cell rectangle) (``StayTime.java:397-430``)."""
        allowed = set(traj_ids or ())
        for start, end, records in self._windows(polygon_stream):
            ts_per_cell: Dict[int, Set[int]] = defaultdict(set)
            for poly in records:
                if allowed and poly.obj_id not in allowed:
                    continue
                for c in sorted(poly.cells):
                    if self._polygon_intersects_rect(
                            poly, self.grid.cell_bounds(c)):
                        ts_per_cell[c].add(poly.timestamp)
            yield WindowResult(
                start, end,
                sorted((c, len(ts)) for c, ts in ts_per_cell.items()))

    # ------------------------------------------------------------------ #
    # normalized join

    def normalized_cell_stay_time(self, point_stream: Iterable[Point],
                                  polygon_stream: Iterable[Polygon],
                                  traj_ids_points: Optional[Set[str]] = None,
                                  traj_ids_sensors: Optional[Set[str]] = None
                                  ) -> Iterator[WindowResult]:
        """Windowed cell join of stay time and sensor coverage:
        ``((stay_ms/1000) / intersections) * window_size_s`` per cell
        (``normalizedCellStayTimeWinFunction``, ``StayTime.java:195-212``).
        Result records: (cell, win_start, win_end, normalized_stay_s)."""
        window_size_s = self.conf.window_size_ms / 1000.0
        # streaming two-pointer merge on window_start: both sides emit
        # windows in nondecreasing start order, so state stays bounded and
        # results flow as soon as both sides have sealed a window (the
        # reference's windowed join, no full materialization)
        sit = iter(self.cell_stay_time(point_stream, traj_ids_points))
        cit = iter(self.cell_sensor_range_intersection(polygon_stream,
                                                       traj_ids_sensors))
        s = next(sit, None)
        c = next(cit, None)
        while s is not None and c is not None:
            if s.window_start == c.window_start:
                start = s.window_start
                end = start + self.conf.window_size_ms
                stay, cover = dict(s.records), dict(c.records)
                out = [
                    (cell, start, end,
                     (stay[cell] / 1000.0) / cover[cell] * window_size_s)
                    for cell in sorted(set(stay) & set(cover))
                    if cover[cell] > 0
                ]
                yield WindowResult(start, end, out)
                s, c = next(sit, None), next(cit, None)
            elif s.window_start < c.window_start:
                s = next(sit, None)
            else:
                c = next(cit, None)
