"""Spatial object model.

Two representations:

- :mod:`spatialflink_tpu.models.objects` — host-side Python objects (one per
  stream record / query geometry), the analogue of the reference's
  ``spatialObjects/`` POJOs.
- :mod:`spatialflink_tpu.models.batches` — padded, fixed-shape
  structure-of-arrays device batches; the unit handed to TPU kernels.
"""

from spatialflink_tpu.models.objects import (
    SpatialObject,
    Point,
    Polygon,
    LineString,
    MultiPoint,
    MultiPolygon,
    MultiLineString,
    GeometryCollection,
)
from spatialflink_tpu.models.batches import PointBatch, EdgeGeomBatch

__all__ = [
    "SpatialObject",
    "Point",
    "Polygon",
    "LineString",
    "MultiPoint",
    "MultiPolygon",
    "MultiLineString",
    "GeometryCollection",
    "PointBatch",
    "EdgeGeomBatch",
]
