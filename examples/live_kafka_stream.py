"""LIVE streaming demo: a producer thread feeds wall-clock-stamped GeoJSON
points into the broker while a realtime range pipeline consumes them —
micro-batches evaluate mid-feed, per-record now-ingestionTime latencies ship
to a latency topic through :class:`KafkaLatencySink`, and the control tuple
stops the job remotely.

This is the reference's continuous operating mode (Kafka consumer feeding
``range/PointPointRangeQuery.java:43-83``, latency sinks at
``utils/HelperClass.java:455-529``) — replay answers "what were the
results", this answers "how far behind live is the pipeline".

Run: python examples/live_kafka_stream.py
"""

import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from examples._common import ensure_backend

ensure_backend()  # fall back to CPU if the accelerator tunnel is wedged

import numpy as np

from spatialflink_tpu.config import StreamConfig
from spatialflink_tpu.driver import decode_stream
from spatialflink_tpu.index import UniformGrid
from spatialflink_tpu.models import Point
from spatialflink_tpu.operators import (
    PointPointRangeQuery,
    QueryConfiguration,
    QueryType,
)
from spatialflink_tpu.streams import (
    InMemoryBroker,
    KafkaLatencySink,
    KafkaSource,
    serialize_spatial,
)
from spatialflink_tpu.utils.metrics import ControlTupleExit

N_RECORDS = 1500
RATE_HZ = 600


def main() -> int:
    grid = UniformGrid(115.50, 117.60, 39.60, 41.10, num_grid_partitions=100)
    broker = InMemoryBroker()
    done = {}

    def producer():
        rng = np.random.default_rng(7)
        for i in range(N_RECORDS):
            p = Point.create(float(rng.uniform(116.2, 117.0)),
                             float(rng.uniform(40.2, 40.9)), grid,
                             obj_id=f"veh{i % 61}",
                             timestamp=int(time.time() * 1000))
            broker.produce("points", serialize_spatial(p, "GeoJSON"))
            time.sleep(1.0 / RATE_HZ)
        done["at_ms"] = int(time.time() * 1000)
        broker.produce("points", json.dumps(
            {"geometry": {"type": "control", "coordinates": []}}))

    t = threading.Thread(target=producer, daemon=True)
    t.start()

    # consumer: follow the topic PAST its current end (live mode) until the
    # control tuple arrives; realtime micro-batches of 256 records
    source = KafkaSource(broker, "points", group="live-demo",
                         stop_at_end=False)
    stream = decode_stream(source, StreamConfig(format="GeoJSON"), grid)
    conf = QueryConfiguration(QueryType.RealTime, 10_000, 5_000,
                              realtime_batch_size=256)
    op = PointPointRangeQuery(conf, grid)
    lat_sink = KafkaLatencySink(broker, "latency")

    live_results = 0
    matched = 0
    try:
        for res in op.run(stream, Point.create(116.6, 40.55, grid), 0.25):
            matched += len(res.records)
            for rec in res.records:
                lat_sink.emit(rec)
            if "at_ms" not in done:
                live_results += 1
    except ControlTupleExit:
        pass
    t.join(timeout=30)

    lats = np.asarray(broker.topic_values("latency"), dtype=np.float64)
    assert lats.size > 0, "no latency records shipped"
    assert live_results >= 1, \
        "no result emitted while the producer was still feeding"
    p50, p95 = np.percentile(lats, [50, 95])
    print(f"{matched} matches in {live_results} live micro-batches "
          "(emitted while the producer was mid-feed)")
    print(f"live latency p50={p50:.0f}ms p95={p95:.0f}ms "
          f"over {lats.size} records")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
