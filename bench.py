"""Headline benchmark: windowed kNN (k=50) over a 1M-point sliding window.

North star (BASELINE.json): >= 10x per-window throughput vs CPU for kNN k=50
on 1M-point windows, single chip. Metric: points/sec/chip.

Prints exactly ONE JSON line:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

The CPU baseline is a vectorized NumPy implementation of the same semantics
(masked distances -> per-object min dedup -> top-k), i.e. an *optimized* CPU
scan — a stronger baseline than the reference's per-tuple JVM loop.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

N_POINTS = 1_000_000
K = 50
RADIUS = 0.5
# slope window: the high count must put MANY windows of device time between
# the two timings — over the axon tunnel a single dispatch→readback RTT is
# tens of ms with multi-ms jitter, which drowned the round-3 10-window gap.
# The high count now ESCALATES (×5) until the measured gap clears
# SLOPE_MIN_GAP_S: the approx_verified path runs a 1M window in ~46us, so a
# fixed 40-window gap (1.8ms) would sit inside the RTT jitter again.
SLOPE_LO = 2
SLOPE_HI = max(SLOPE_LO + 1,
               int(os.environ.get("SPATIALFLINK_BENCH_ITERS", "42")))
SLOPE_MIN_GAP_S = 0.2
SLOPE_MAX_HI = 40_000
# candidate strategies the bench times briefly and picks from when no
# explicit SPATIALFLINK_BENCH_STRATEGY is set: the TPU-optimal choice has
# never been measured interactively (the tunnel wedges for hours), so the
# bench tunes itself at run time instead of trusting CPU-derived constants
TPU_CANDIDATES = ("grouped", "prefilter", "approx_verified")


def _probe_default_backend_ok(attempts: int = 5) -> bool:
    """The axon TPU tunnel can wedge at backend init; probe it in a
    subprocess so a hang downgrades to CPU instead of stalling the bench.

    Probes with bounded retries + backoff (the tunnel sometimes recovers
    within minutes — round 4 saw multi-hour wedges, so the end-of-round
    bench spends up to ~12 min trying before surrendering to CPU)
    instead of a single long attempt.
    """
    timeouts = (60, 90, 120)
    for i in range(attempts):
        try:
            r = subprocess.run(
                [sys.executable, "-c", "import jax; jax.devices()"],
                timeout=timeouts[min(i, len(timeouts) - 1)],
                capture_output=True,
            )
            if r.returncode == 0:
                return True
            print(f"warning: backend probe attempt {i + 1} failed "
                  f"(rc={r.returncode})", file=sys.stderr)
        except subprocess.TimeoutExpired:
            print(f"warning: backend probe attempt {i + 1} timed out",
                  file=sys.stderr)
        if i + 1 < attempts:
            time.sleep(15 * (i + 1))
    return False


def _force_cpu():
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")


def build_inputs():
    import numpy as np

    from spatialflink_tpu.index import UniformGrid
    from spatialflink_tpu.models import PointBatch

    grid = UniformGrid(115.50, 117.60, 39.60, 41.10, num_grid_partitions=100)
    rng = np.random.default_rng(0)
    xs = rng.uniform(grid.min_x, grid.max_x, N_POINTS)
    ys = rng.uniform(grid.min_y, grid.max_y, N_POINTS)
    oid = rng.integers(0, N_POINTS // 4, N_POINTS).astype(np.int32)
    batch = PointBatch.from_arrays(xs, ys, grid=grid, obj_id=oid)
    return grid, batch, xs, ys, oid


def bench_device(grid, batch):
    """-> (points/sec/chip, p50_ms, strategy, pick_info) on the default device.

    Windows are processed in an on-device ``fori_loop`` whose body depends on
    the loop index (so XLA cannot hoist it); timing the loop at two iteration
    counts and taking the slope isolates per-window device time from the
    fixed per-dispatch overhead — the regime a streaming pipeline runs in,
    where window batches are queued back-to-back ahead of completion.

    Strategy selection: an explicit ``SPATIALFLINK_BENCH_STRATEGY`` wins;
    otherwise on TPU the bench briefly times each exact candidate and runs
    the full slope measurement on the winner (self-tuning — the constants in
    ops.knn's "auto" were derived on CPU and round 3 showed they don't
    transfer). CPU keeps "auto" (measured: prefilter).
    """
    from functools import partial

    import jax
    import jax.numpy as jnp

    from spatialflink_tpu.ops.knn import knn_point

    qx, qy = 116.5, 40.5
    q_cell, _ = grid.assign_cell(qx, qy)
    nb_layers = grid.candidate_layers(RADIUS)
    batch = jax.device_put(batch)
    qc = jnp.int32(q_cell)

    # iters is a DYNAMIC argument (fori_loop lowers to a while loop), so one
    # compile per strategy covers every loop count the escalation below needs
    @partial(jax.jit, static_argnames=("strategy",))
    def run_n(b, iters, *, strategy):
        def body(i, acc):
            r = knn_point(b, qx + i * 1e-7, qy, qc, RADIUS, nb_layers,
                          n=grid.n, k=K, strategy=strategy)
            return acc + r.dist[0]
        return jax.lax.fori_loop(0, iters, body, jnp.float32(0))

    warmed = set()

    def timed(strategy, iters, reps=3) -> float:
        it = jnp.int32(iters)
        if strategy not in warmed:  # one compile+warm covers every count
            jax.block_until_ready(run_n(batch, it, strategy=strategy))
            warmed.add(strategy)
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            jax.block_until_ready(run_n(batch, it, strategy=strategy))
            best = min(best, time.perf_counter() - t0)
        return best

    env_strat = os.environ.get("SPATIALFLINK_BENCH_STRATEGY", "")
    pick_info = {}
    if env_strat and env_strat != "auto-pick":
        strategy = env_strat
    elif jax.default_backend() != "tpu":
        strategy = "auto"
    else:
        # probe by slope GAP (not one absolute loop time: the tunnel's fixed
        # ~60ms dispatch RTT would swamp the difference between a 46us/window
        # and a 1.2ms/window strategy), ESCALATING the count until the gap
        # clears a 50ms floor — a fixed 100-window gap is ~4.6ms for the fast
        # path, inside the RTT jitter, and a jitter-negative gap must rank
        # the strategy as unmeasured-worst, never as best
        def probe_per_window(s):
            p_lo, p_hi = 2, 102
            t_lo = timed(s, p_lo, reps=2)
            while True:
                gap = timed(s, p_hi, reps=2) - t_lo
                if gap >= 0.05 or p_hi >= 20_000:
                    break
                p_hi = min(p_hi * 5, 20_000)
            if gap < 0.05:
                # never cleared the noise floor, even at the cap — a tiny
                # positive jitter gap (or a tunnel acking without executing)
                # must rank as unmeasured-WORST, not as the winner
                print(f"warning: strategy {s} probe gap {gap * 1e3:.1f}ms "
                      "below floor at cap; ranking it unmeasured",
                      file=sys.stderr)
                return float("inf")
            return gap / (p_hi - p_lo)

        for s in TPU_CANDIDATES:
            try:
                pick_info[s] = probe_per_window(s)
            except Exception as e:  # a strategy failing must not kill the run
                print(f"warning: strategy {s} failed quick probe: {e}",
                      file=sys.stderr)
        if pick_info and min(pick_info.values()) < float("inf"):
            strategy = min(pick_info, key=pick_info.get)
        else:  # every probe failed; don't let the pick kill the run
            strategy = "grouped"
            print("warning: all strategy probes failed; using 'grouped'",
                  file=sys.stderr)
        print("# strategy pick (probed s/window): "
              + ", ".join(f"{s}={t:.6f}" for s, t in pick_info.items())
              + f" -> {strategy}", file=sys.stderr)

    lo, hi = SLOPE_LO, SLOPE_HI
    t_lo = timed(strategy, lo)
    while True:
        t_hi = timed(strategy, hi)
        gap = t_hi - t_lo
        if gap >= SLOPE_MIN_GAP_S or hi >= SLOPE_MAX_HI:
            break
        hi = min(hi * 5, SLOPE_MAX_HI)
    per_window = gap / (hi - lo)
    if per_window <= 0:
        # timing noise swamped the slope even at SLOPE_MAX_HI; fall back to
        # the conservative whole-loop average (includes fixed dispatch
        # overhead) and say so.
        print("warning: non-positive slope; reporting whole-loop average",
              file=sys.stderr)
        per_window = t_hi / hi
    elif gap < SLOPE_MIN_GAP_S:
        # positive but sub-threshold at the cap: still jitter-sized — a
        # number this produces is NOT a clean measurement, say so loudly
        print(f"warning: slope gap {gap * 1e3:.1f}ms at the {hi}-window cap "
              f"is below the {SLOPE_MIN_GAP_S * 1e3:.0f}ms floor; headline "
              "may be noise-dominated", file=sys.stderr)
    else:
        print(f"# slope window: {lo}->{hi}, gap {gap * 1e3:.1f}ms "
              f"({per_window * 1e6:.1f}us/window)", file=sys.stderr)

    # measured single-window dispatch -> readback distributions (VERDICT
    # #6: a real per-window latency DISTRIBUTION, not slope arithmetic) at
    # pipeline depth 1 vs 2: depth 1 blocks on each window before
    # dispatching the next (what a realtime caller sees); depth 2 keeps one
    # window in flight while the next dispatches — the operator driver's
    # double-buffering — so its per-window latency includes queueing behind
    # the in-flight window, exactly what _drive_batched's readback pays
    win = jax.jit(lambda b, i: knn_point(b, qx + i * 1e-7, qy, qc, RADIUS,
                                         nb_layers, n=grid.n, k=K,
                                         strategy=strategy))
    jax.block_until_ready(win(batch, jnp.float32(0)))
    dist = window_latency_distribution(win, batch, depths=(1, 2))
    return (N_POINTS / per_window, dist["depth1"]["p50_ms"],
            strategy, pick_info, dist)


def window_latency_distribution(win, batch, depths=(1, 2), iters: int = 31):
    """Per-window dispatch->readback wall-clock distribution at each
    pipeline depth: dispatch window i, and block on the OLDEST in-flight
    window once ``depth`` are pending — the same drain rule as
    ``operators.base._drive_batched``. Returns {"depthN": {p50_ms, p99_ms,
    max_ms}} from the measured per-window latencies."""
    from collections import deque

    import jax
    import jax.numpy as jnp
    import numpy as _np

    out = {}
    for depth in depths:
        pending: deque = deque()
        lats = []

        def drain(n):
            while len(pending) > n:
                t0, res = pending.popleft()
                jax.block_until_ready(res)
                lats.append((time.perf_counter() - t0) * 1000)

        for i in range(iters):
            t0 = time.perf_counter()
            pending.append((t0, win(batch, jnp.float32(i))))
            drain(depth - 1)
        drain(0)
        out[f"depth{depth}"] = {
            "p50_ms": round(float(_np.percentile(lats, 50)), 3),
            "p99_ms": round(float(_np.percentile(lats, 99)), 3),
            "max_ms": round(float(_np.max(lats)), 3),
        }
    return out


def bench_cpu_numpy(grid, xs, ys, oid) -> float:
    """Vectorized NumPy baseline with identical semantics."""
    import numpy as np

    qx, qy = 116.5, 40.5
    q_cell, _ = grid.assign_cell(qx, qy)
    L = grid.candidate_layers(RADIUS)
    qcx, qcy = int(q_cell) // grid.n, int(q_cell) % grid.n

    cell, valid = grid.assign_cell(xs, ys)
    cx, cy = cell // grid.n, cell % grid.n

    def run():
        eligible = valid & (np.maximum(np.abs(cx - qcx), np.abs(cy - qcy)) <= L)
        d = np.hypot(xs - qx, ys - qy)
        d = np.where(eligible, d, np.inf)
        # per-object min dedup
        mins = np.full(int(oid.max()) + 1, np.inf)
        np.minimum.at(mins, oid, d)
        finite = np.isfinite(mins)
        idx = np.nonzero(finite)[0]
        if len(idx) > K:
            part = np.argpartition(mins[idx], K)[:K]
            idx = idx[part]
        order = np.argsort(mins[idx])
        return idx[order], mins[idx][order]

    run()  # warm caches
    t0 = time.perf_counter()
    iters = 3
    for _ in range(iters):
        run()
    dt = time.perf_counter() - t0
    return N_POINTS * iters / dt


def main(argv=None):
    import argparse

    ap = argparse.ArgumentParser(
        description="headline kNN bench; prints exactly ONE JSON line")
    ap.add_argument("--require-backend", choices=("cpu", "tpu", "gpu"),
                    default=os.environ.get("SPATIALFLINK_REQUIRE_BACKEND")
                    or None,
                    help="fail fast (exit 2, no JSON row) when the process "
                         "would run on any other backend — a silent CPU "
                         "fallback must refuse, not bank an invalid row")
    args = ap.parse_args(argv)
    if os.environ.get("SPATIALFLINK_BENCH_PLATFORM") == "cpu":
        _force_cpu()
    elif not _probe_default_backend_ok():
        print("warning: default backend probe failed after retries; "
              "falling back to CPU — result NOT valid for the TPU target",
              file=sys.stderr)
        _force_cpu()

    import jax

    from spatialflink_tpu.utils.telemetry import telemetry_session

    backend = jax.default_backend()
    if args.require_backend and backend != args.require_backend:
        print(f"bench: --require-backend {args.require_backend} but the "
              f"process landed on '{backend}'; refusing to measure (run "
              "python -m spatialflink_tpu.doctor --preflight)",
              file=sys.stderr)
        return 2
    # in-memory telemetry session (no reporter): per-stage spans + grid
    # occupancy ride the result row, so BENCH_* files carry a breakdown of
    # where the wall clock went, not just the headline number
    with telemetry_session() as tel:
        with tel.span("inputs", query="bench"):
            grid, batch, xs, ys, oid = build_inputs()
        with tel.span("device", query="bench"):
            (device_tput, p50_ms, strategy, _pick,
             win_lat) = bench_device(grid, batch)
        with tel.span("cpu-baseline", query="bench"):
            cpu_tput = bench_cpu_numpy(grid, xs, ys, oid)
        telemetry = tel.snapshot()

    row = {
        "metric": "knn_k50_1M_window_points_per_sec_per_chip",
        "value": round(device_tput),
        "unit": "points/s",
        "vs_baseline": round(device_tput / cpu_tput, 2),
        # The north-star target (BASELINE.md) is a TPU number; a CPU
        # fallback is reported, but flagged invalid for that target.
        "backend": backend,
        "device_kind": jax.devices()[0].device_kind,
        "device_count": jax.device_count(),
        "valid_for_target": backend == "tpu",
        "p50_window_latency_ms": round(p50_ms, 3),
        # measured dispatch->readback distribution per pipeline depth
        # (VERDICT #6): depth1 = block-per-window, depth2 = one window in
        # flight behind the dispatch (the driver's double-buffering)
        "window_latency_ms": win_lat,
        "strategy": strategy,
        # final telemetry snapshot: bench.* stage spans, grid occupancy/skew
        "telemetry": telemetry,
    }
    if backend != "tpu":
        # the tunnel wedges for hours; if a real-TPU measurement was banked
        # earlier (committed with full provenance), attach it — clearly
        # labeled — so a CPU-fallback run doesn't erase the valid number
        banked = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              "benchmarks", "BENCH_tpu_r04_interactive.json")
        try:
            with open(banked) as f:
                row["banked_tpu_run"] = json.load(f)
        except (OSError, ValueError):  # missing or corrupted artifact must
            pass                       # not cost the one-JSON-line contract
    print(json.dumps(row))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
