"""Rule 7 — recompile-surface: shape-determining kernel arguments come
from the bucketing seams, proven statically.

The engine's zero-recompile contract (padded window batches since the
seed, the PR 9 power-of-two fleet buckets, the PR 10 sentinel that
aborts on post-warmup compiles) hinges on one discipline: every value a
jitted kernel treats as *static* — sizes like ``n``/``k``/``m``/
``num_cells``/``tile`` that select an XLA program — must move through a
finite set of shape classes. The runtime sentinel observes violations
only on executed paths; this rule proves the discipline at every call
site in the tree.

Mechanics (the project call graph + shape-churn taint of
:mod:`spatialflink_tpu.analysis.dataflow`):

- every call that resolves — locally or across modules through the
  import map — to an ``instrumented_jit``-decorated kernel is a checked
  site; the kernel's ``static_argnames``/``static_argnums`` name the
  static parameters, and the *shape-determining* subset is selected by
  name (:data:`SHAPE_STATIC_PAT` — integer sizes, not mode flags like
  ``approximate``/``strategy``/``interpret``);
- the argument expression feeding each such static is classified:
  constants, plain attribute chains (``self.grid.n`` — run-constant
  geometry/config by convention), and caller parameters (the contract
  hoists to the caller, which is itself a checked or padded site) are
  churn-safe; ``bucket_size(...)`` sanitizes everything beneath it;
- anything that reaches the static from a data-dependent source —
  ``len(records)``, ``batch.xs.shape[0]``-style reads, arithmetic over
  them, a local bound from one — WITHOUT passing through the bucketing
  seam is a finding: that call site recompiles per distinct size, i.e.
  per churn event, exactly what the padded-fleet helpers exist to
  prevent.

Blind spots (documented): values laundered through instance attributes
(``self._n = len(...)`` then ``n=self._n``), kernels invoked through
dynamic dispatch tables, and `*args` forwarding — the runtime sentinel
remains the backstop for those.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator

from spatialflink_tpu.analysis import dataflow
from spatialflink_tpu.analysis.core import (Finding, ModuleSource, Rule,
                                            register)

#: static parameter names that determine compiled shapes. Mode flags
#: (approximate/strategy/enforce_radius/interpret/agg) take a few fixed
#: values and are deliberately not matched.
SHAPE_STATIC_PAT = re.compile(
    r"^(n|m|k|b|q|tile|pad|npad|cap|capacity|size|length"
    r"|num_\w+|\w+_size|\w+_len|min_bucket)$")


@register
class RecompileSurfaceRule(Rule):
    id = "recompile-surface"
    contract = ("every shape-determining static argument at an "
                "instrumented_jit call site derives from the bucketing "
                "seams (bucket_size / run-constant geometry / caller "
                "params), never raw data-dependent sizes")
    runtime_twin = ("recompile sentinel + --strict-recompile abort; "
                    "fleet-churn jit cache-counter assertions "
                    "(tests/test_queryplane.py)")
    severity = "error"
    depth = "interprocedural (cross-module call graph)"
    interprocedural = True
    scope = ("spatialflink_tpu/**",)

    def check(self, mod: ModuleSource,
              project=None) -> Iterator[Finding]:
        if project is None:
            from spatialflink_tpu.analysis.callgraph import Project

            project = Project.of_module(mod)
        graph = project.graph(mod)
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            info = project.resolve_call(mod, node)
            if info is None or not info.statics:
                continue
            argmap = dataflow.map_call_args(info.params, node)
            for sname in sorted(info.statics):
                if sname not in argmap \
                        or not SHAPE_STATIC_PAT.match(sname):
                    continue
                src = dataflow.shape_churn_source(graph, argmap[sname],
                                                  node)
                if src is None:
                    continue
                yield self.finding(
                    mod, node,
                    f"static arg {sname!r} of kernel {info.name} is "
                    f"data-dependent ({src}) and not bucketed — every "
                    "distinct value compiles a fresh XLA program under "
                    "churn; route it through bucket_size / the padded "
                    "fleet so it repads instead of recompiling")
