"""StayTime app: per-cell accumulated stay time of moving objects, normalized
by sensor coverage (reference: ``apps/StayTime.java:32-485``).

Pipeline parity:

- :meth:`StayTime.cell_stay_time_tuples` ≙ ``CellStayTime`` stage 1
  (``CellStayTimeWinFunction``, ``StayTime.java:227-396``): per trajectory,
  per window, time-sorted consecutive point pairs split their time delta
  across the grid cells traversed.
- :meth:`StayTime.cell_stay_time` ≙ stage 2 per-cell window sum
  (``CellStayTimeAggregateWinFunction``, ``StayTime.java:432-448``).
- :meth:`StayTime.cell_sensor_range_intersection` ≙
  ``CellSensorRangeIntersection`` (``StayTime.java:397-430``): per cell,
  count of distinct timestamps whose sensor polygon intersects the cell
  rectangle.
- :meth:`StayTime.normalized_cell_stay_time` ≙ the windowed join
  (``normalizedCellStayTimeWinFunction``, ``StayTime.java:113-212``):
  ``((stay_ms/1000) / intersections) * window_size_s`` per cell.

Cell-splitting rules for one consecutive pair (last → current), mirroring
``StayTime.java:270-371``:

- same cell: the whole delta goes to that cell;
- same x-index: delta split equally across the inclusive y-range of cells;
- same y-index: split equally across the inclusive x-range;
- both differ: split equally across {last cell, current cell} ∪ cells of the
  segment's bbox whose rectangle the segment geometrically intersects.

The per-pair work is vectorized with numpy per window; this is app-layer
aggregation over already-small per-trajectory groups, not a device kernel.
"""

from __future__ import annotations

from collections import defaultdict
from typing import Dict, Iterable, Iterator, List, Optional, Set, Tuple

import numpy as np

from spatialflink_tpu.models import Point, Polygon
from spatialflink_tpu.operators.base import (
    SpatialOperator,
    WindowResult,
)


def _segment_intersects_rect(x0, y0, x1, y1, rect) -> bool:
    """Liang–Barsky clip test: does segment (x0,y0)-(x1,y1) hit the rect."""
    rx0, ry0, rx1, ry1 = rect
    dx, dy = x1 - x0, y1 - y0
    t0, t1 = 0.0, 1.0
    for p, q in ((-dx, x0 - rx0), (dx, rx1 - x0),
                 (-dy, y0 - ry0), (dy, ry1 - y0)):
        if p == 0:
            if q < 0:
                return False
            continue
        r = q / p
        if p < 0:
            if r > t1:
                return False
            t0 = max(t0, r)
        else:
            if r < t0:
                return False
            t1 = min(t1, r)
    return t0 <= t1


def _segments_intersect_rects(x0, y0, x1, y1, rx0, ry0, rx1, ry1) -> np.ndarray:
    """Vectorized Liang–Barsky over parallel (segment, rect) arrays.

    The scalar version's early exits are equivalent to the final
    ``t0 <= t1`` test (t0 only grows, t1 only shrinks), so the vector form
    just clamps through all four edges and compares once.
    """
    dx, dy = x1 - x0, y1 - y0
    t0 = np.zeros_like(dx)
    t1 = np.ones_like(dx)
    ok = np.ones(dx.shape, bool)
    for p, q in ((-dx, x0 - rx0), (dx, rx1 - x0),
                 (-dy, y0 - ry0), (dy, ry1 - y0)):
        para = p == 0
        ok &= ~(para & (q < 0))
        with np.errstate(divide="ignore", invalid="ignore"):
            r = q / np.where(para, 1.0, p)
        neg = ~para & (p < 0)
        pos = ~para & (p > 0)
        t0 = np.where(neg, np.maximum(t0, r), t0)
        t1 = np.where(pos, np.minimum(t1, r), t1)
    return ok & (t0 <= t1)


class StayTime(SpatialOperator):
    """Windowed stay-time pipeline over a :class:`UniformGrid`."""
    # interner-keyed cross-window state: windows must carry
    # materialized records in the OPERATOR's id space (the
    # chunked decode still batches the parse)
    columnar_windows = False

    # the normalized join pairs the point and sensor streams BY WINDOW
    # START; count windows' starts are data timestamps that would never
    # align across streams, so the app keeps time windows only
    supports_count_windows = False

    # ------------------------------------------------------------------ #
    # stage 1: per-(objID, pair) stay-time shares

    def _pair_shares(self, pts: List[Point]) -> List[Tuple[int, int, int, float]]:
        """-> (t0, t1, cell, share_ms) per traversed cell, for one
        trajectory's time-sorted window points.

        Fully vectorized over the window's consecutive pairs (round-3 VERDICT
        weak #9 flagged the per-pair Python loops): pairs are classified
        (same cell / straight row-or-column move / diagonal), straight moves
        expand their inclusive index range with repeat+cumsum arithmetic, and
        diagonal moves run one vectorized Liang–Barsky pass over every
        (pair, bbox-cell) candidate. Output order matches the scalar
        semantics: pairs in stream order, cells ascending within a pair.
        """
        g = self.grid
        n = g.n
        if len(pts) < 2:
            return []
        ts = np.array([p.timestamp for p in pts], np.int64)
        xs = np.array([p.x for p in pts], np.float64)
        ys = np.array([p.y for p in pts], np.float64)
        cs = np.array([p.cell for p in pts], np.int64)
        c0, c1 = cs[:-1], cs[1:]
        t0a, t1a = ts[:-1], ts[1:]
        x0, x1 = xs[:-1], xs[1:]
        y0, y1 = ys[:-1], ys[1:]
        ok = (c0 >= 0) & (c1 >= 0)
        dt = (t1a - t0a).astype(np.float64)
        cx0, cy0 = c0 // n, c0 % n
        cx1, cy1 = c1 // n, c1 % n

        same = ok & (c0 == c1)
        col = ok & ~same & (cx0 == cx1)
        row = ok & ~same & (cy0 == cy1)
        diag = ok & ~same & ~col & ~row

        reps: List[np.ndarray] = []
        cells_out: List[np.ndarray] = []
        counts_out: List[np.ndarray] = []

        def expand(i, lo, hi):
            """(pair_reps, positions 0..count-1, counts per element)."""
            counts = (hi - lo + 1).astype(np.int64)
            total = int(counts.sum())
            rep = np.repeat(i, counts)
            cum = np.concatenate([[0], np.cumsum(counts)])
            pos = np.arange(total) - np.repeat(cum[:-1], counts)
            return rep, np.repeat(lo, counts) + pos, np.repeat(counts, counts)

        i = np.nonzero(same)[0]
        if i.size:
            reps.append(i)
            cells_out.append(c0[i])
            counts_out.append(np.ones(i.size, np.int64))

        i = np.nonzero(col)[0]
        if i.size:
            rep, vary, cnts = expand(
                i, np.minimum(cy0[i], cy1[i]), np.maximum(cy0[i], cy1[i]))
            reps.append(rep)
            cells_out.append(cx0[rep] * n + vary)
            counts_out.append(cnts)

        i = np.nonzero(row)[0]
        if i.size:
            rep, vary, cnts = expand(
                i, np.minimum(cx0[i], cx1[i]), np.maximum(cx0[i], cx1[i]))
            reps.append(rep)
            cells_out.append(vary * n + cy0[rep])
            counts_out.append(cnts)

        i = np.nonzero(diag)[0]
        if i.size:
            gx_lo = np.minimum(cx0[i], cx1[i])
            gx_hi = np.maximum(cx0[i], cx1[i])
            gy_lo = np.minimum(cy0[i], cy1[i])
            gy_hi = np.maximum(cy0[i], cy1[i])
            ny = gy_hi - gy_lo + 1
            counts = (gx_hi - gx_lo + 1) * ny
            total = int(counts.sum())
            rep = np.repeat(i, counts)
            cum = np.concatenate([[0], np.cumsum(counts)])
            pos = np.arange(total) - np.repeat(cum[:-1], counts)
            ny_r = np.repeat(ny, counts)
            cxs = np.repeat(gx_lo, counts) + pos // ny_r
            cys = np.repeat(gy_lo, counts) + pos % ny_r
            cand = cxs * n + cys
            rx0 = g.min_x + cxs * g.cell_length
            ry0 = g.min_y + cys * g.cell_length
            hit = _segments_intersect_rects(
                x0[rep], y0[rep], x1[rep], y1[rep],
                rx0, ry0, rx0 + g.cell_length, ry0 + g.cell_length)
            # endpoint cells always belong to the split set, like the
            # scalar rule's {last, current} seeding
            hit |= (cand == c0[rep]) | (cand == c1[rep])
            rep, cand = rep[hit], cand[hit]
            cnts = np.bincount(rep, minlength=c0.shape[0])[rep]
            reps.append(rep)
            cells_out.append(cand)
            counts_out.append(cnts)

        if not reps:
            return []
        rep = np.concatenate(reps)
        cells = np.concatenate(cells_out)
        counts = np.concatenate(counts_out)
        order = np.lexsort((cells, rep))  # pair order, cells asc within pair
        rep, cells, counts = rep[order], cells[order], counts[order]
        shares = dt[rep] / counts
        return list(zip(t0a[rep].tolist(), t1a[rep].tolist(),
                        cells.tolist(), shares.tolist()))

    def cell_stay_time_tuples(self, stream: Iterable[Point],
                              traj_ids: Optional[Set[str]] = None
                              ) -> Iterator[WindowResult]:
        """Per window: (objID, t0, t1, cell, stay_share_ms) tuples
        (``Tuple5``, ``StayTime.java:383-391``)."""
        allowed = set(traj_ids or ())
        for start, end, records in self._windows(stream):
            by_obj: Dict[str, List[Point]] = defaultdict(list)
            for p in records:
                if not allowed or p.obj_id in allowed:
                    by_obj[p.obj_id].append(p)
            out = []
            for oid, pts in by_obj.items():
                pts.sort(key=lambda p: p.timestamp)
                out.extend((oid, t0, t1, c, s)
                           for t0, t1, c, s in self._pair_shares(pts))
            yield WindowResult(start, end, out)

    def cell_stay_time(self, stream: Iterable[Point],
                       traj_ids: Optional[Set[str]] = None
                       ) -> Iterator[WindowResult]:
        """Per window: (cell, summed stay time ms) per touched cell."""
        for res in self.cell_stay_time_tuples(stream, traj_ids):
            sums: Dict[int, float] = defaultdict(float)
            for _oid, _t0, _t1, cell, share in res.records:
                sums[cell] += share
            yield WindowResult(res.window_start, res.window_end,
                               sorted(sums.items()))

    # ------------------------------------------------------------------ #
    # sensor coverage

    def _polygon_intersects_rect(self, poly: Polygon, rect) -> bool:
        """Rect–polygon intersection honoring interior rings: a rect lying
        strictly inside a hole does NOT intersect (JTS semantics in the
        reference's ``cellPoly.intersects(p.polygon)``)."""
        rx0, ry0, rx1, ry1 = rect
        bx0, by0, bx1, by1 = poly.bbox
        if bx1 < rx0 or bx0 > rx1 or by1 < ry0 or by0 > ry1:
            return False
        rings = [np.asarray(r, np.float64) for r in poly.rings]
        for ring in rings:
            # any ring vertex inside the rect → boundary overlaps the rect
            if ((ring[:, 0] >= rx0) & (ring[:, 0] <= rx1)
                    & (ring[:, 1] >= ry0) & (ring[:, 1] <= ry1)).any():
                return True
            # any ring edge (shell OR hole boundary) crossing the rect
            for (x0, y0), (x1, y1) in zip(ring[:-1], ring[1:]):
                if _segment_intersects_rect(x0, y0, x1, y1, rect):
                    return True
        # no boundary contact: the rect is entirely inside polygon material,
        # inside a hole, or outside. Even-odd ray cast over ALL rings
        # classifies one corner (holes flip parity back to outside).
        x, y = rx0, ry0
        crossings = 0
        for ring in rings:
            xs0, ys0 = ring[:-1, 0], ring[:-1, 1]
            xs1, ys1 = ring[1:, 0], ring[1:, 1]
            cond = (ys0 > y) != (ys1 > y)
            with np.errstate(divide="ignore", invalid="ignore"):
                xint = xs0 + (y - ys0) / (ys1 - ys0) * (xs1 - xs0)
            crossings += int((cond & (x < xint)).sum())
        return bool(crossings % 2)

    def cell_sensor_range_intersection(self, polygon_stream: Iterable[Polygon],
                                       traj_ids: Optional[Set[str]] = None
                                       ) -> Iterator[WindowResult]:
        """Per window: (cell, number of distinct timestamps whose polygon
        intersects the cell rectangle) (``StayTime.java:397-430``)."""
        allowed = set(traj_ids or ())
        for start, end, records in self._windows(polygon_stream):
            ts_per_cell: Dict[int, Set[int]] = defaultdict(set)
            for poly in records:
                if allowed and poly.obj_id not in allowed:
                    continue
                for c in sorted(poly.cells):
                    if self._polygon_intersects_rect(
                            poly, self.grid.cell_bounds(c)):
                        ts_per_cell[c].add(poly.timestamp)
            yield WindowResult(
                start, end,
                sorted((c, len(ts)) for c, ts in ts_per_cell.items()))

    # ------------------------------------------------------------------ #
    # normalized join

    def normalized_cell_stay_time(self, point_stream: Iterable[Point],
                                  polygon_stream: Iterable[Polygon],
                                  traj_ids_points: Optional[Set[str]] = None,
                                  traj_ids_sensors: Optional[Set[str]] = None
                                  ) -> Iterator[WindowResult]:
        """Windowed cell join of stay time and sensor coverage:
        ``((stay_ms/1000) / intersections) * window_size_s`` per cell
        (``normalizedCellStayTimeWinFunction``, ``StayTime.java:195-212``).
        Result records: (cell, win_start, win_end, normalized_stay_s)."""
        window_size_s = self.conf.window_size_ms / 1000.0
        # streaming two-pointer merge on window_start: both sides emit
        # windows in nondecreasing start order, so state stays bounded and
        # results flow as soon as both sides have sealed a window (the
        # reference's windowed join, no full materialization)
        sit = iter(self.cell_stay_time(point_stream, traj_ids_points))
        cit = iter(self.cell_sensor_range_intersection(polygon_stream,
                                                       traj_ids_sensors))
        s = next(sit, None)
        c = next(cit, None)
        while s is not None and c is not None:
            if s.window_start == c.window_start:
                start = s.window_start
                end = start + self.conf.window_size_ms
                stay, cover = dict(s.records), dict(c.records)
                out = [
                    (cell, start, end,
                     (stay[cell] / 1000.0) / cover[cell] * window_size_s)
                    for cell in sorted(set(stay) & set(cover))
                    if cover[cell] > 0
                ]
                yield WindowResult(start, end, out)
                s, c = next(sit, None), next(cit, None)
            elif s.window_start < c.window_start:
                s = next(sit, None)
            else:
                c = next(cit, None)
