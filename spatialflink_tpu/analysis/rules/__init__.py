"""Rule implementations. Importing this package registers every rule
with :data:`spatialflink_tpu.analysis.core.RULES` (the modules
self-register via the ``@register`` decorator)."""

from spatialflink_tpu.analysis.rules import (  # noqa: F401
    buglint,
    checkpoint_coverage,
    host_sync,
    jit_coverage,
    recompile_surface,
    telemetry_gating,
    thread_shared,
    trace_safety,
)
