"""Bulk (vectorized) point ingestion: text blocks -> structure-of-arrays.

The per-tuple path (``streams.formats.parse_spatial``) mirrors the
reference's per-record deserializer; this module is the high-throughput
twin used when a whole file/window of records is available at once — the
common replay/benchmark case, and what a Kafka poll returns. The parse runs
in native C++ (:mod:`spatialflink_tpu.native`), obj-id interning is
vectorized over unique hashes, and only rejected lines (ISO dates,
non-point GeoJSON, malformed rows) fall back to the Python parser.

Output is a :class:`ParsedPoints` SoA — exactly what
:meth:`PointBatch.from_arrays` wants — plus the per-record Python
:class:`Point` view for code that needs objects.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

import numpy as np

from spatialflink_tpu import native
from spatialflink_tpu.index import UniformGrid
from spatialflink_tpu.models import Point, PointBatch
from spatialflink_tpu.streams import formats
from spatialflink_tpu.utils import IdInterner

import ctypes


@dataclass
class ParsedPoints:
    """Structure-of-arrays result of a bulk parse (record order preserved)."""

    x: np.ndarray       # (N,) f64
    y: np.ndarray       # (N,) f64
    ts: np.ndarray      # (N,) i64 epoch millis
    obj_id: np.ndarray  # (N,) i32 interned ids
    interner: IdInterner

    def __len__(self) -> int:
        return self.x.shape[0]

    def to_batch(self, grid: Optional[UniformGrid] = None, *,
                 ts_base: Optional[int] = None,
                 pad: Optional[int] = None) -> PointBatch:
        base = int(self.ts[0]) if ts_base is None and len(self) else (ts_base or 0)
        return PointBatch.from_arrays(
            self.x, self.y, grid=grid, obj_id=self.obj_id, ts=self.ts,
            ts_base=base, pad=pad,
        )

    def to_points(self, grid: Optional[UniformGrid] = None) -> List[Point]:
        return [
            Point.create(float(self.x[i]), float(self.y[i]), grid,
                         self.interner.lookup(int(self.obj_id[i])),
                         int(self.ts[i]))
            for i in range(len(self))
        ]


def _ptr(a: np.ndarray, ctype):
    return a.ctypes.data_as(ctypes.POINTER(ctype))


def _intern_hashes(data: bytes, oid_hash, oid_start, oid_len,
                   interner: IdInterner, normalize) -> np.ndarray:
    """Vectorized obj-id interning: one string materialization per UNIQUE
    hash, everything else is numpy. ``normalize`` applies the same id
    normalization the native hash used (format-specific)."""
    uniq, first, inv = np.unique(oid_hash, return_index=True, return_inverse=True)
    ids = np.empty(uniq.shape[0], np.int32)
    for u, j in enumerate(first):
        s = data[oid_start[j]: oid_start[j] + oid_len[j]].decode("utf-8", "replace")
        ids[u] = interner.intern(normalize(s))
    return ids[inv]


# CSV ids: parse_csv removes every '"' then field-trims whitespace; GeoJSON
# ids: the native span is already the exact decoded value
_NORM_CSV = lambda s: s.replace('"', "").strip()  # noqa: E731
_NORM_RAW = lambda s: s  # noqa: E731


def _nonblank_lines(data: bytes):
    """The C parser's blank-line rule exactly: a line is blank iff it contains
    only ' ', '\t', '\r' — NOT the wider bytes.strip() whitespace set, so
    reject indices stay aligned."""
    return [ln for ln in data.split(b"\n") if ln.strip(b" \t\r")]


def _merge_rejects(n: int, accepted: dict, reparsed: List[Tuple[int, Point]],
                   interner: IdInterner) -> ParsedPoints:
    """Stitch native-accepted arrays and Python-reparsed records back into
    original line order."""
    if not reparsed:  # fast path: nothing rejected, arrays are already ordered
        return ParsedPoints(
            x=np.ascontiguousarray(accepted["x"]),
            y=np.ascontiguousarray(accepted["y"]),
            ts=np.ascontiguousarray(accepted["ts"]),
            obj_id=accepted["oid"], interner=interner,
        )
    total = n + len(reparsed)
    x = np.empty(total, np.float64)
    y = np.empty(total, np.float64)
    ts = np.empty(total, np.int64)
    oid = np.empty(total, np.int32)
    reject_lines = {line for line, _ in reparsed}
    # accepted records occupy the non-rejected line slots in order
    order = [i for i in range(total) if i not in reject_lines]
    x[order] = accepted["x"]
    y[order] = accepted["y"]
    ts[order] = accepted["ts"]
    oid[order] = accepted["oid"]
    for line, p in reparsed:
        x[line], y[line], ts[line] = p.x, p.y, p.timestamp
        oid[line] = interner.intern(p.obj_id)
    return ParsedPoints(x=x, y=y, ts=ts, obj_id=oid, interner=interner)


def _require_point(obj, line: str) -> Point:
    if not isinstance(obj, Point):
        raise ValueError(
            "bulk point ingestion got a non-Point record "
            f"({type(obj).__name__}); use streams.formats.parse_spatial for "
            f"mixed-geometry streams: {line[:120]!r}"
        )
    return obj


def _python_fallback(data: bytes, fmt: str, interner: IdInterner,
                     **kw) -> ParsedPoints:
    pts = []
    for ln in data.decode("utf-8", "replace").split("\n"):
        if not ln.strip():
            continue
        pts.append(_require_point(formats.parse_spatial(ln, fmt, None, **kw), ln))
    return ParsedPoints(
        x=np.array([p.x for p in pts], np.float64),
        y=np.array([p.y for p in pts], np.float64),
        ts=np.array([p.timestamp for p in pts], np.int64),
        obj_id=np.array([interner.intern(p.obj_id) for p in pts], np.int32),
        interner=interner,
    )


def bulk_parse_csv(
    data: bytes,
    *,
    delimiter: str = ",",
    schema: Sequence[Optional[int]] = (0, 1, 2, 3),
    date_format: Optional[str] = formats.DEFAULT_DATE_FORMAT,
    interner: Optional[IdInterner] = None,
) -> ParsedPoints:
    """Parse a newline-separated CSV/TSV block of points.

    ``schema`` = column indices of [oID, timestamp, x, y] (None = absent),
    matching :func:`formats.parse_csv` / ``Deserialization.java:288-330``.
    """
    interner = interner if interner is not None else IdInterner()
    nlib = native.lib()
    if nlib is None:
        return _python_fallback(data, "csv", interner, delimiter=delimiter,
                                schema=schema, date_format=date_format)
    cap = data.count(b"\n") + 1
    buf = data if data.endswith(b"\0") else data + b"\0"
    xs = np.empty(cap, np.float64)
    ys = np.empty(cap, np.float64)
    ts = np.empty(cap, np.int64)
    oh = np.empty(cap, np.uint64)
    os_ = np.empty(cap, np.int64)
    ol = np.empty(cap, np.int32)
    rej = np.empty(cap, np.int64)
    nrej = ctypes.c_long(0)
    oi = -1 if schema[0] is None else int(schema[0])
    ti = -1 if schema[1] is None else int(schema[1])
    n = nlib.sf_parse_points_csv(
        buf, len(data), delimiter.encode()[:1] or b",",
        oi, ti, int(schema[2]), int(schema[3]),
        _ptr(xs, ctypes.c_double), _ptr(ys, ctypes.c_double),
        _ptr(ts, ctypes.c_int64),
        _ptr(oh, ctypes.c_uint64), _ptr(os_, ctypes.c_int64),
        _ptr(ol, ctypes.c_int32),
        _ptr(rej, ctypes.c_int64), ctypes.byref(nrej),
    )
    oid = _intern_hashes(data, oh[:n], os_[:n], ol[:n], interner, _NORM_CSV)
    accepted = {"x": xs[:n], "y": ys[:n], "ts": ts[:n], "oid": oid}
    reparsed = []
    if nrej.value:  # line-splitting is only paid when something was rejected
        lines = _nonblank_lines(data)
        for i in rej[: nrej.value]:
            ln = lines[int(i)].decode("utf-8", "replace")
            p = formats.parse_csv(ln, None, delimiter=delimiter, schema=schema,
                                  date_format=date_format)
            reparsed.append((int(i), _require_point(p, ln)))
    return _merge_rejects(n, accepted, reparsed, interner)


def bulk_parse_geojson(
    data: bytes,
    *,
    property_obj_id: str = "oID",
    property_timestamp: str = "timestamp",
    date_format: Optional[str] = None,
    interner: Optional[IdInterner] = None,
) -> ParsedPoints:
    """Parse a newline-separated block of GeoJSON Point features.

    Non-point features and date-formatted timestamps are re-parsed by the
    Python parser (full fidelity), so this accepts exactly what
    :func:`formats.parse_geojson` accepts.
    """
    interner = interner if interner is not None else IdInterner()
    nlib = native.lib()
    kw = dict(property_obj_id=property_obj_id,
              property_timestamp=property_timestamp,
              date_format=date_format)
    if nlib is None:
        return _python_fallback(data, "geojson", interner, **kw)
    cap = data.count(b"\n") + 1
    buf = data if data.endswith(b"\0") else data + b"\0"
    xs = np.empty(cap, np.float64)
    ys = np.empty(cap, np.float64)
    ts = np.empty(cap, np.int64)
    oh = np.empty(cap, np.uint64)
    os_ = np.empty(cap, np.int64)
    ol = np.empty(cap, np.int32)
    rej = np.empty(cap, np.int64)
    nrej = ctypes.c_long(0)
    n = nlib.sf_parse_points_geojson(
        buf, len(data),
        property_obj_id.encode(), property_timestamp.encode(),
        _ptr(xs, ctypes.c_double), _ptr(ys, ctypes.c_double),
        _ptr(ts, ctypes.c_int64),
        _ptr(oh, ctypes.c_uint64), _ptr(os_, ctypes.c_int64),
        _ptr(ol, ctypes.c_int32),
        _ptr(rej, ctypes.c_int64), ctypes.byref(nrej),
    )
    oid = _intern_hashes(data, oh[:n], os_[:n], ol[:n], interner, _NORM_RAW)
    accepted = {"x": xs[:n], "y": ys[:n], "ts": ts[:n], "oid": oid}
    reparsed = []
    if nrej.value:
        lines = _nonblank_lines(data)
        for i in rej[: nrej.value]:
            ln = lines[int(i)].decode("utf-8", "replace")
            p = formats.parse_geojson(ln, None, **kw)
            reparsed.append((int(i), _require_point(p, ln)))
    return _merge_rejects(n, accepted, reparsed, interner)


def bulk_window_batches(parsed: ParsedPoints, spec, grid=None, *,
                        pad: Optional[int] = None):
    """Vectorized window assembly: ParsedPoints -> per-window device batches.

    Yields ``(start, end, idx, PointBatch)`` in window order, where ``idx``
    is the original-record index array for the window. The whole assignment
    is numpy (``WindowSpec.assign_bulk``); batches are built straight from
    the SoA slices, so no per-record Python objects exist anywhere on this
    path — the high-throughput twin of ``WindowAssembler`` for bounded
    replays, mirroring how ``bulk_parse_*`` twins ``formats.parse_spatial``.
    """
    if not len(parsed):
        return
    win, rec = spec.assign_bulk(parsed.ts)
    if not len(win):  # sampling specs (slide > size) can assign nothing
        return
    # cells once per record, not once per window membership (sliding windows
    # revisit each record size/slide times)
    if grid is not None:
        cells, _ = grid.assign_cell(parsed.x, parsed.y)
        cells = np.asarray(cells, np.int32)
    else:
        cells = np.full(len(parsed), -1, np.int32)
    bounds = np.flatnonzero(np.r_[True, win[1:] != win[:-1], True])
    for i in range(len(bounds) - 1):
        lo, hi = int(bounds[i]), int(bounds[i + 1])
        start = int(win[lo])
        idx = rec[lo:hi]
        batch = PointBatch.from_arrays(
            parsed.x[idx], parsed.y[idx], grid=grid,
            obj_id=parsed.obj_id[idx], ts=parsed.ts[idx],
            ts_base=start, pad=pad, cell=cells[idx],
        )
        yield start, start + spec.size_ms, idx, batch


def bulk_parse_file(path: str, fmt: str, **kw) -> ParsedPoints:
    """Bulk-parse a whole replay file of points."""
    with open(path, "rb") as f:
        data = f.read()
    if fmt.lower() in ("csv", "tsv"):
        if fmt.lower() == "tsv":
            kw.setdefault("delimiter", "\t")
        return bulk_parse_csv(data, **kw)
    if fmt.lower() == "geojson":
        return bulk_parse_geojson(data, **kw)
    raise ValueError(f"bulk ingestion supports csv/tsv/geojson, not {fmt!r}")
