"""Fleet worker plumbing: the pieces a supervised multi-worker run adds
INSIDE each worker process, plus the global-merge machinery both sides
share.

The reference runs GeoFlink at Flink parallelism 30: a JobManager places
keyed subtasks on TaskManagers and restarts the ones that die. The
rebuild's equivalent (``runtime/fleetsup.py``) spawns N full pipelines —
each worker is the EXISTING single-process driver with its own PaneCache,
checkpoint manifest, and opserver on an ephemeral port — and partitions
the stream by grid leaf (PR 8's leaf layout as the placement unit). This
module is the worker half and the shared contracts:

- :class:`TailingReplaySource` — a file-replay source that FOLLOWS its
  partition file while the supervisor is still routing records into it,
  and treats the durable ``partition.done`` marker as EOF. Resume-aware
  exactly like ``FileReplaySource`` (``skip``/``limit`` compose with
  ``CheckpointTap``), and shutdown-aware: a SIGTERM that lands while the
  source is idle raises :class:`~spatialflink_tpu.utils.metrics
  .GracefulShutdown` so the drain path runs instead of a hang.
- :class:`HeartbeatWriter` — a daemon thread touching a heartbeat file
  every interval; the supervisor's liveness probe that works even when
  the worker's pipeline thread is busy inside a kernel dispatch.
- :class:`OutboxWriter` / :func:`read_outbox` — the worker's durable
  per-window emission log for the global merge stage: one canonical JSON
  line per emitted window (fingerprinted, flushed before the journal
  records the window), so the supervisor can merge windowAll results
  without re-parsing worker stdout. Appended only for windows the
  emitted-window journal has NOT seen — a crash between the outbox
  append and the journal record re-appends a canonically identical line
  on resume (identity = window key + records fingerprint; the
  observability plane's ``lat`` sidecar may differ across incarnations
  and is excluded from both), which the merge dedups by window key (and
  cross-checks by fingerprint): exactly-once output identity across a
  kill.
- :class:`FleetManifest` — the supervisor's durable state (leaf→worker
  assignment, repartition epoch, restart counts, fence tokens, rescale
  and quarantine history) with the ``snapshot``/``restore`` pair the
  checkpoint-coverage linter rule proves field-by-field.
- :class:`WorkerContext` — the driver's one handle on all of the above
  when it runs under ``--fleet-role worker``.

**Fencing epochs.** Heartbeat-kill-respawn alone cannot contain a
*zombie*: a stalled-but-alive worker that resumes writing after the
supervisor presumed it dead and spawned a successor. The fence layer
makes that impossible by construction: the manifest carries a monotonic
fence token per worker slot, every outbox line and heartbeat is stamped
with the writer's fence, and a respawn's FIRST act is bumping the token
while recording the predecessor's durable outbox/journal byte sizes
(``fleet_fence_log``). A row stamped with fence *f* is a zombie row iff
its byte offset is at-or-past the cutoff recorded when fence *f*+1 was
issued — everything the predecessor durably wrote BEFORE it was
superseded stays valid, everything after is dropped at merge (counted
and evented, never a run-aborting :class:`FleetMergeError`). The
journal applies the same per-fence cutoff rule at load, so a successor
re-emits exactly the windows whose journal lines were zombie-written —
the outbox-before-journal write order guarantees those re-emissions
dedup against the predecessor's (still valid) pre-bump rows.

Merging reuses the per-family pane/shard merge twins through
:func:`~spatialflink_tpu.operators.base.merge_window_records` — see
:func:`merge_outboxes`.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time
from typing import Dict, Iterator, List, Optional, Tuple

from spatialflink_tpu.runtime.checkpoint import (atomic_write_json,
                                                 read_json)
from spatialflink_tpu.utils import telemetry as _telemetry
from spatialflink_tpu.utils.metrics import (REGISTRY, GracefulShutdown,
                                            shutdown_requested)

#: files inside one worker's fleet directory (``<fleet-dir>/worker<i>/``)
PARTITION_FILE = "partition.ndjson"
DONE_MARKER = "partition.done"
OUTBOX_FILE = "outbox.jsonl"
HEARTBEAT_FILE = "heartbeat"
URL_FILE = "opserver.url"
RUNS_FILE = "runs.jsonl"
#: supervisor-owned files at the fleet root
MANIFEST_FILE = "fleet.json"
MERGED_FILE = "merged.jsonl"
RESULT_FILE = "fleet_result.json"
#: observability-plane files (absent under ``--fleet-plane off``)
EVENTS_FILE = "fleet_events.jsonl"
LATENCY_FILE = "fleet_latency.json"
#: the supervisor's fleet-view snapshot dropped next to a dead worker's
#: flight-recorder bundles (``worker<i>/postmortem/``)
FLEET_VIEW_FILE = "fleet_view.json"


def worker_dir(fleet_dir: str, worker_id: int) -> str:
    return os.path.join(fleet_dir, f"worker{int(worker_id)}")


class FleetMergeError(RuntimeError):
    """Two outbox lines for the SAME window key disagree on content — the
    exactly-once identity the journal + canonical outbox guarantee was
    violated (or two different jobs shared a fleet dir)."""


# --------------------------------------------------------------------- #
# tailing partition source


class TailingReplaySource:
    """Replay a partition file the supervisor is still appending to.

    Yields complete stripped lines; a partial tail line (the supervisor
    flushes whole lines, but the OS may expose a torn read mid-write) is
    held back until its newline arrives. EOF is the durable
    ``partition.done`` marker: once observed, one final read drains
    anything appended before the marker, then iteration ends — so a
    bounded fleet run terminates exactly like a file replay.

    ``skip``/``limit`` mirror :class:`~spatialflink_tpu.streams.sources
    .FileReplaySource` so ``CheckpointTap`` resume semantics carry over
    unchanged. A writer stall is handled in two stages so a temporarily
    paused supervisor route (a quarantine drain, a rescale barrier)
    doesn't kill an innocent worker: every ``stall_timeout_s`` of
    silence emits a ``partition-stall`` event (and bumps the
    ``partition-stall`` counter) but keeps polling; only
    ``stall_deadline_s`` (default 4× the timeout) with no new data and
    no marker raises — a dead supervisor must not leave orphan workers
    spinning forever."""

    def __init__(self, path: str, done_path: str, *,
                 limit: Optional[int] = None, skip: int = 0,
                 poll_s: float = 0.05, stall_timeout_s: float = 300.0,
                 stall_deadline_s: Optional[float] = None):
        self._path = path
        self._done_path = done_path
        self._limit = limit
        self._skip = max(0, int(skip))
        self._poll_s = poll_s
        self._stall_timeout_s = float(stall_timeout_s)
        self._stall_deadline_s = (float(stall_deadline_s)
                                  if stall_deadline_s is not None
                                  else 4.0 * float(stall_timeout_s))
        self._warn_at = 0.0
        self.stall_events = 0

    def __iter__(self) -> Iterator[str]:
        if self._limit is not None and self._limit <= 0:
            return
        f = None
        tail = ""
        skipped = 0
        yielded = 0
        last_data = time.monotonic()
        try:
            while True:
                if f is None:
                    if os.path.exists(self._path):
                        f = open(self._path)
                    elif os.path.exists(self._done_path):
                        return  # empty partition, already final
                    else:
                        self._wait(last_data)
                        continue
                chunk = f.read(1 << 16)
                if chunk:
                    last_data = time.monotonic()
                    tail += chunk
                    lines = tail.split("\n")
                    tail = lines.pop()
                    for line in lines:
                        if not line:
                            continue
                        if skipped < self._skip:
                            skipped += 1
                            continue
                        yield line
                        yielded += 1
                        if (self._limit is not None
                                and yielded >= self._limit):
                            return
                    continue
                # at EOF: the marker is written AFTER the final flush, so
                # observing it means one more read drains everything
                if os.path.exists(self._done_path):
                    chunk = f.read(1 << 16)
                    if chunk:
                        last_data = time.monotonic()
                        tail += chunk
                        continue
                    if tail.strip() and skipped >= self._skip:
                        yield tail.strip()  # defensively drain a torn tail
                    return
                self._wait(last_data)
        finally:
            if f is not None:
                f.close()

    def _wait(self, last_data: float) -> None:
        if shutdown_requested():
            raise GracefulShutdown(
                "shutdown requested while tailing the partition file")
        stalled = time.monotonic() - last_data
        if stalled > self._stall_deadline_s:
            raise RuntimeError(
                f"partition file {self._path} stalled for "
                f"{stalled:.1f}s (deadline {self._stall_deadline_s:g}s) "
                "with no done marker — supervisor dead?")
        if (stalled >= self._stall_timeout_s
                and time.monotonic() >= self._warn_at):
            # bounded retry: complain periodically, keep polling — the
            # route may merely be paused (quarantine drain, rescale
            # barrier); only the hard deadline above gives up
            self._warn_at = time.monotonic() + self._stall_timeout_s
            self.stall_events += 1
            REGISTRY.counter("partition-stall").inc()
            _telemetry.emit_event("partition-stall", path=self._path,
                                  stalled_s=round(stalled, 2),
                                  deadline_s=self._stall_deadline_s)
        time.sleep(self._poll_s)


# --------------------------------------------------------------------- #
# heartbeat


class HeartbeatWriter:
    """Write ``path`` every ``interval_s`` from a daemon thread. The
    supervisor reads the file's mtime age as the liveness signal — a
    worker wedged hard enough to stop a daemon thread (or SIGKILLed) goes
    stale within one interval.

    Each beat atomically replaces the file with a fence-stamped JSON doc
    (``{fence, pid, ts_ms}``): a zombie predecessor and its successor
    share the path, so the supervisor must be able to tell whose beat it
    is reading — a beat carrying a superseded fence is not liveness. The
    write goes through a pid-suffixed temp file so concurrent writers
    never clobber each other's temp, and ``os.replace`` keeps the read
    side tear-free. ``gate`` is the fault layer's wedge hook
    (:class:`~spatialflink_tpu.runtime.faults.StallFault`): while it
    returns True, beats are skipped — the injectable gray failure."""

    def __init__(self, path: str, interval_s: float = 1.0, *,
                 fence: int = 0, gate=None):
        self._path = path
        self._interval_s = max(0.05, float(interval_s))
        self._fence = int(fence)
        self._gate = gate
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "HeartbeatWriter":
        self._touch()
        self._thread = threading.Thread(target=self._loop,
                                        name="fleet-heartbeat", daemon=True)
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self._interval_s):
            self._touch()

    def _touch(self) -> None:
        if self._gate is not None and self._gate():
            return  # injected gray failure: wedged, not dead
        tmp = f"{self._path}.tmp.{os.getpid()}"
        try:
            with open(tmp, "w") as f:
                f.write(json.dumps({"fence": self._fence,
                                    "pid": os.getpid(),
                                    "ts_ms": int(time.time() * 1000)},
                                   sort_keys=True))
            os.replace(tmp, self._path)
        except OSError:
            pass  # a missed beat is indistinguishable from a slow one

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None


def heartbeat_age_s(path: str, *,
                    fence: Optional[int] = None) -> Optional[float]:
    """Seconds since the worker last touched its heartbeat, or None when
    the file does not exist yet (worker still booting).

    With ``fence`` given, the beat's content is checked: a beat stamped
    with an OLDER fence than expected is a superseded incarnation's
    write, not liveness — it reads as None (successor still booting).
    Legacy/unparseable content falls back to plain mtime age."""
    try:
        age = max(0.0, time.time() - os.stat(path).st_mtime)
    except OSError:
        return None
    if fence is not None:
        try:
            with open(path) as f:
                beat = json.loads(f.read())
            if int(beat.get("fence", 0)) < int(fence):
                return None  # zombie beat: the expected fence never wrote
        except (OSError, ValueError, TypeError, AttributeError):
            pass  # legacy mtime-only heartbeat (or torn read): age stands
    return age


# --------------------------------------------------------------------- #
# canonical outbox


def _record_encoder():
    from spatialflink_tpu.streams.formats import serialize_spatial

    def encode(rec):
        try:
            return serialize_spatial(rec, "GeoJSON", date_format=None)
        except (AttributeError, TypeError, ValueError):
            return json.dumps(rec, sort_keys=True, default=str)

    return encode


def window_key(result) -> str:
    """The journal's idempotent window-sink key (``start:end:cell``) —
    the outbox keys windows identically so the two logs cross-check."""
    from spatialflink_tpu.runtime.checkpoint import EmittedWindowJournal

    return EmittedWindowJournal.key(result)


def canonical_window_doc(result, family: str,
                         lat: Optional[dict] = None,
                         fence: int = 0) -> dict:
    """One outbox line: the window's identity plus its records in a
    canonical, order-independent serialization (selection families sort
    encoded records; kNN keeps its (distance, id) top-k order, which IS
    canonical). The fingerprint seals the content so duplicate appends
    across a crash are provably identical.

    ``lat`` is the observability plane's lineage SIDECAR (the worker's
    stage budget + emit wall stamp, :func:`lat_sidecar`). It rides the
    line but is excluded from the fingerprint — the fp is computed over
    the records alone, BEFORE the sidecar is attached — and
    :func:`merged_table_digest` never reads it, so exactly-once identity
    and the merged digest are plane-independent: a resumed incarnation
    re-emitting a window with a different budget still dedups cleanly,
    and ``--fleet-plane off`` produces a byte-identical merged table.

    ``fence`` stamps the line with the writer incarnation's fence token
    (also outside the fingerprint — the same window re-emitted by a
    successor incarnation must still dedup against the predecessor's
    valid rows). Fence 0 (single-process runs, pre-fence outboxes) is
    not stamped, keeping those lines byte-identical to before."""
    if family == "knn":
        records = [[str(oid), float(d)] for oid, d in result.records]
    else:
        enc = _record_encoder()
        records = sorted(enc(r) for r in result.flat_records())
    payload = json.dumps(records, sort_keys=True)
    doc = {
        "key": window_key(result),
        "window": [int(result.window_start), int(result.window_end)],
        "cell": result.extras.get("cell"),
        "count": len(records),
        "records": records,
        "fp": hashlib.sha256(payload.encode()).hexdigest()[:16],
    }
    if lat is not None:
        doc["lat"] = lat
    if fence:
        doc["fence"] = int(fence)
    return doc


#: the sidecar's allowed stage keys: the worker's sum-to-total chain
#: (downstream sink stages run after emit and would break the fleet
#: chain's consecutive-interval construction)
_SIDECAR_STAGES = ("buffer", "queue", "dispatch", "inflight", "merge",
                   "emit")


def lat_sidecar(budget_row: Optional[dict]) -> Optional[dict]:
    """Filter one :meth:`~spatialflink_tpu.utils.latencyplane
    .LatencyPlane.budget_row` into the outbox lineage sidecar: the
    ingest/emit wall stamps plus the CHAIN stages only, so the
    supervisor can extend the chain with ``outbox-visible -> merge ->
    merged-emit`` and keep the sums-to-total invariant end to end.
    Returns None for windows without an ingest stamp (bulk batches) —
    they cannot anchor a record→merged-emit measurement."""
    if not budget_row or budget_row.get("first_ingest_ms") is None:
        return None
    stages = budget_row.get("stages") or {}
    return {
        "first_ingest_ms": budget_row["first_ingest_ms"],
        "emitted_ms": budget_row.get("emitted_ms"),
        "record_emit_ms": budget_row.get("record_emit_ms"),
        "stages": {s: stages[s] for s in _SIDECAR_STAGES if s in stages},
    }


class OutboxWriter:
    """Append-only canonical window log, one flushed JSON line per emitted
    window. Flushed BEFORE the emitted-window journal records the window:
    a ``kill -9`` between the two re-appends a canonically identical line
    on resume (the journal did not suppress it; only the diagnostic
    ``lat`` sidecar — outside the fingerprint — may differ), and
    :func:`read_outbox` dedups by key — never a lost window, never a
    divergent one."""

    def __init__(self, path: str):
        self.path = path
        self._f = open(path, "a")
        self.appended = 0

    def append(self, doc: dict) -> None:
        self._f.write(json.dumps(doc, sort_keys=True) + "\n")
        self._f.flush()
        self.appended += 1

    def close(self) -> None:
        self._f.close()


def read_outbox(path: str, *,
                fence_cutoffs: Optional[Dict[int, int]] = None,
                stats: Optional[dict] = None) -> Dict[str, dict]:
    """Parse one worker's outbox into ``key -> doc``, deduplicating the
    crash-replay duplicates (first occurrence wins) and raising
    :class:`FleetMergeError` if a same-fence duplicate DISAGREES — that
    would mean a resumed worker emitted different window contents than
    its pre-crash incarnation, exactly the bug the exactly-once
    machinery exists to make impossible.

    ``fence_cutoffs`` maps a superseded fence token to the byte size the
    outbox had when that fence was bumped away (the manifest's
    ``fleet_fence_log``): a row stamped with fence *f* that STARTS
    at-or-past ``fence_cutoffs[f]`` was written by a zombie — an
    incarnation still running after the supervisor superseded it — and
    is dropped, never merged, never an error. Rows without a fence field
    are fence 0 (pre-fence outboxes stay readable). Cross-fence
    disagreement on a window's content keeps the NEWEST fence's doc and
    counts a conflict instead of aborting — the superseded side is by
    definition the less trusted writer. ``stats``, when given, receives
    ``stale_fence_rows`` / ``fence_conflicts`` counts (added to any
    existing values, so one dict can accumulate across workers)."""
    out: Dict[str, dict] = {}
    fences: Dict[str, int] = {}
    stale = 0
    conflicts = 0
    cutoffs = fence_cutoffs or {}
    if not os.path.exists(path):
        if stats is not None:
            stats["stale_fence_rows"] = stats.get("stale_fence_rows", 0)
            stats["fence_conflicts"] = stats.get("fence_conflicts", 0)
        return out
    with open(path, "rb") as f:
        pos = 0
        for raw in f:
            start = pos
            pos += len(raw)
            line = raw.decode("utf-8", "replace").strip()
            if not line:
                continue
            try:
                doc = json.loads(line)
            except ValueError:
                continue  # torn tail from a kill mid-write: replayed later
            key = doc.get("key")
            if key is None:
                continue
            fence = int(doc.get("fence") or 0)
            cut = cutoffs.get(fence)
            if cut is not None and start >= int(cut):
                stale += 1  # zombie write: fence superseded before this row
                continue
            prev = out.get(key)
            if prev is None:
                out[key] = doc
                fences[key] = fence
            elif prev.get("fp") != doc.get("fp"):
                kept = fences.get(key, 0)
                if kept == fence:
                    raise FleetMergeError(
                        f"outbox {path}: window {key} re-emitted with "
                        f"different content (fp {prev.get('fp')} vs "
                        f"{doc.get('fp')}) — exactly-once identity "
                        "violated")
                conflicts += 1
                if fence > kept:
                    out[key] = doc
                    fences[key] = fence
    if stats is not None:
        stats["stale_fence_rows"] = (
            stats.get("stale_fence_rows", 0) + stale)
        stats["fence_conflicts"] = (
            stats.get("fence_conflicts", 0) + conflicts)
    return out


# --------------------------------------------------------------------- #
# global merge


def merge_outboxes(per_worker: Dict[int, Dict[str, dict]], family: str,
                   *, k: Optional[int] = None) -> List[dict]:
    """The fleet's global merge stage: combine every worker's deduped
    outbox into the windowAll table a single unpartitioned run would have
    produced, through the per-family merge seam
    (:func:`~spatialflink_tpu.operators.base.merge_window_records`).
    Workers merge in worker-id order and selection-family unions re-sort,
    so the result is independent of BOTH the leaf assignment and emission
    timing — the property the identity tests pin."""
    from spatialflink_tpu.operators.base import merge_window_records

    by_key: Dict[str, List[Tuple[int, dict]]] = {}
    for wid in sorted(per_worker):
        for key, doc in per_worker[wid].items():
            by_key.setdefault(key, []).append((wid, doc))
    merged: List[dict] = []
    for key, docs in by_key.items():
        parts = [d["records"] for _, d in docs]
        if family == "knn":
            records = [[str(oid), float(d)] for oid, d in
                       merge_window_records(
                           family, [[(r[0], r[1]) for r in p]
                                    for p in parts], k=k, tie_key=str)]
        else:
            records = sorted(merge_window_records(family, parts))
        first = docs[0][1]
        merged.append({
            "key": key,
            "window": first["window"],
            "cell": first.get("cell"),
            "count": len(records),
            "records": records,
            "workers": [wid for wid, _ in docs],
        })
    merged.sort(key=lambda d: (d["window"][0], d["window"][1],
                               str(d.get("cell"))))
    return merged


def merged_table_digest(merged: List[dict]) -> str:
    """Stable content digest of the merged window table (identity column
    excludes which workers contributed — two fleets with different leaf
    assignments must digest identically)."""
    canon = [{"key": d["key"], "records": d["records"]} for d in merged]
    return hashlib.sha256(
        json.dumps(canon, sort_keys=True).encode()).hexdigest()


# --------------------------------------------------------------------- #
# fleet manifest (supervisor durable state)


def fence_cutoffs_from(state: Optional[dict], worker: int) -> Dict[int, dict]:
    """Project a manifest snapshot's ``fence_log`` into one worker's
    superseded-fence byte cutoffs: ``{old_fence: {"outbox": bytes,
    "journal": bytes}}``. An entry issuing fence *f* records the durable
    sizes at the instant fence *f*−1 was superseded — anything a fence
    *f*−1 writer appends past those offsets is a zombie write. Shared by
    the supervisor's merge, the worker's journal load, and the doctor
    (which reads the raw ``fleet.json``)."""
    out: Dict[int, dict] = {}
    for e in (state or {}).get("fence_log") or []:
        try:
            if int(e.get("worker", -1)) != int(worker):
                continue
            f = int(e.get("fence", 0))
        except (TypeError, ValueError):
            continue
        if f > 0:
            out[f - 1] = {"outbox": int(e.get("outbox_bytes", 0)),
                          "journal": int(e.get("journal_bytes", 0))}
    return out


class FleetManifest:
    """The supervisor's durable state: leaf→worker assignment, the
    repartition epoch, per-worker restart counts, per-slot fence tokens
    (with the byte-offset log that defines zombie-row validity), and the
    rescale/quarantine history, written atomically to
    ``<fleet-dir>/fleet.json`` after every mutation that must survive
    a supervisor crash. The ``snapshot``/``restore`` pair is the same
    contract the checkpoint coordinator registers — and the
    checkpoint-coverage linter rule proves every ``fleet_*`` field is
    carried by both, so a field added later cannot silently stop being
    durable."""

    def __init__(self, path: str):
        self.path = path
        self.fleet_assignment: Dict[int, int] = {}
        self.fleet_epoch = 0
        self.fleet_restarts: Dict[int, int] = {}
        self.fleet_fences: Dict[int, int] = {}
        self.fleet_fence_log: List[dict] = []
        self.fleet_rescale_log: List[dict] = []
        self.fleet_quarantine_log: List[dict] = []
        loaded = read_json(path)
        if loaded:
            self.restore(loaded)

    def assign(self, leaf: int, worker: int) -> None:
        self.fleet_assignment[int(leaf)] = int(worker)

    def assign_all(self, assignment: Dict[int, int]) -> None:
        for leaf, worker in assignment.items():
            self.fleet_assignment[int(leaf)] = int(worker)

    def advance_epoch(self) -> int:
        self.fleet_epoch += 1
        return self.fleet_epoch

    def note_restart(self, worker: int) -> int:
        w = int(worker)
        self.fleet_restarts[w] = self.fleet_restarts.get(w, 0) + 1
        return self.fleet_restarts[w]

    def fence_of(self, worker: int) -> int:
        return self.fleet_fences.get(int(worker), 0)

    def bump_fence(self, worker: int, *, outbox_bytes: int = 0,
                   journal_bytes: int = 0,
                   reason: str = "respawn") -> int:
        """Supersede worker ``worker``'s current incarnation: issue the
        next fence token and record the predecessor's durable outbox and
        journal byte sizes — the cutoffs past which any write stamped
        with the OLD fence is provably a zombie's. Called by the
        supervisor BEFORE spawning the successor, so the containment
        holds from the successor's first instant."""
        w = int(worker)
        nf = self.fleet_fences.get(w, 0) + 1
        self.fleet_fences[w] = nf
        self.fleet_fence_log.append({
            "ts_ms": int(time.time() * 1000), "worker": w, "fence": nf,
            "outbox_bytes": int(outbox_bytes),
            "journal_bytes": int(journal_bytes), "reason": reason})
        return nf

    def fence_cutoffs(self, worker: int) -> Dict[int, dict]:
        """This worker's superseded-fence byte cutoffs (see
        :func:`fence_cutoffs_from`)."""
        return fence_cutoffs_from({"fence_log": self.fleet_fence_log},
                                  worker)

    def note_rescale(self, *, n_from: int, n_to: int, at_records: int,
                     epoch: int) -> None:
        self.fleet_rescale_log.append({
            "ts_ms": int(time.time() * 1000), "n_from": int(n_from),
            "n_to": int(n_to), "at_records": int(at_records),
            "epoch": int(epoch)})

    def note_quarantine(self, worker: int, action: str,
                        **fields) -> None:
        doc = {"ts_ms": int(time.time() * 1000), "worker": int(worker),
               "action": action}
        doc.update(fields)
        self.fleet_quarantine_log.append(doc)

    def snapshot(self) -> dict:
        return {
            "assignment": {str(k): v
                           for k, v in self.fleet_assignment.items()},
            "epoch": self.fleet_epoch,
            "restarts": {str(k): v
                         for k, v in self.fleet_restarts.items()},
            "fences": {str(k): v
                       for k, v in self.fleet_fences.items()},
            "fence_log": list(self.fleet_fence_log),
            "rescale_log": list(self.fleet_rescale_log),
            "quarantine_log": list(self.fleet_quarantine_log),
        }

    def restore(self, state: dict) -> None:
        self.fleet_assignment = {int(k): int(v) for k, v in
                                 (state.get("assignment") or {}).items()}
        self.fleet_epoch = int(state.get("epoch", 0))
        self.fleet_restarts = {int(k): int(v) for k, v in
                               (state.get("restarts") or {}).items()}
        self.fleet_fences = {int(k): int(v) for k, v in
                             (state.get("fences") or {}).items()}
        self.fleet_fence_log = list(state.get("fence_log") or [])
        self.fleet_rescale_log = list(state.get("rescale_log") or [])
        self.fleet_quarantine_log = list(
            state.get("quarantine_log") or [])

    def save(self) -> None:
        atomic_write_json(self.path, self.snapshot())


# --------------------------------------------------------------------- #
# worker context (driver glue)


class WorkerContext:
    """Everything ``--fleet-role worker`` adds to a driver run: the
    worker's fleet directory layout, the heartbeat, the canonical outbox,
    the opserver-URL drop file, and the per-incarnation run summary the
    supervisor and ``doctor fleet`` read."""

    def __init__(self, fleet_dir: str, worker_id: int, *,
                 family: str, k: Optional[int] = None,
                 heartbeat_s: float = 1.0, fence: int = 0,
                 stall=None):
        self.worker_id = int(worker_id)
        self.fleet_dir = fleet_dir
        self.dir = worker_dir(fleet_dir, worker_id)
        os.makedirs(self.dir, exist_ok=True)
        self.family = family
        self.k = k
        self.fence = int(fence)
        self.stall = stall  # injected gray failure (faults.StallFault)
        self._t0 = time.time()
        self._heartbeat = HeartbeatWriter(
            os.path.join(self.dir, HEARTBEAT_FILE), heartbeat_s,
            fence=self.fence,
            gate=(stall.wedged if stall is not None else None))
        self.outbox = OutboxWriter(os.path.join(self.dir, OUTBOX_FILE))

    @staticmethod
    def from_args(args, spec) -> Optional["WorkerContext"]:
        """The driver's constructor: a context iff this run is a fleet
        worker (validated in ``main``). The fence token is supervisor-
        assigned via ``--fleet-fence``; ``--fleet-stall-s`` arms the
        fault layer's injectable gray failure for chaos runs."""
        if getattr(args, "fleet_role", None) != "worker":
            return None
        stall = None
        stall_s = float(getattr(args, "fleet_stall_s", 0) or 0)
        if stall_s > 0:
            from spatialflink_tpu.runtime.faults import (StallFault,
                                                         install_stall)
            stall = install_stall(StallFault(stall_s))
        return WorkerContext(args.fleet_dir, args.fleet_worker_id,
                             family=spec.family,
                             heartbeat_s=args.fleet_heartbeat,
                             fence=int(getattr(args, "fleet_fence", 0)
                                       or 0),
                             stall=stall)

    @property
    def partition_path(self) -> str:
        return os.path.join(self.dir, PARTITION_FILE)

    @property
    def done_path(self) -> str:
        return os.path.join(self.dir, DONE_MARKER)

    def start(self) -> "WorkerContext":
        self._heartbeat.start()
        return self

    def tailing_source(self, *, limit: Optional[int] = None,
                       skip: int = 0) -> TailingReplaySource:
        return TailingReplaySource(self.partition_path, self.done_path,
                                   limit=limit, skip=skip)

    def write_url(self, url: str) -> None:
        atomic_write_json(os.path.join(self.dir, URL_FILE), {"url": url})

    def note_window(self, result, budget: Optional[dict] = None) -> None:
        """Outbox-append one emitted window (called only for windows the
        journal has NOT suppressed; flushed before the journal records
        it — see :class:`OutboxWriter` for the crash ordering).
        ``budget`` is the latency plane's budget row for this window;
        when present it rides the line as the fingerprint-excluded
        lineage sidecar (:func:`lat_sidecar`)."""
        if self.stall is not None:
            # arms the injected gray failure on the first emitted window
            # (and throttles emission while wedged — slow, not dead)
            self.stall.on_window()
        self.outbox.append(canonical_window_doc(
            result, self.family, lat=lat_sidecar(budget),
            fence=self.fence))

    def journal_fence_cutoffs(self) -> Dict[int, int]:
        """This worker's superseded-fence JOURNAL byte cutoffs, read
        from the supervisor's manifest (read-only — the worker never
        writes ``fleet.json``). The emitted-window journal skips lines
        past these offsets at load: a zombie predecessor may have
        journaled windows whose emissions are fence-dropped at merge,
        and trusting those lines would suppress the re-emission that
        makes the merged table whole."""
        state = read_json(os.path.join(self.fleet_dir, MANIFEST_FILE))
        return {f: c["journal"] for f, c in
                fence_cutoffs_from(state, self.worker_id).items()}

    def write_run_summary(self, **fields) -> None:
        """Append this incarnation's exit record to ``runs.jsonl``."""
        doc = {"ts_ms": int(time.time() * 1000),
               "wall_s": round(time.time() - self._t0, 3),
               "worker": self.worker_id,
               "fence": self.fence,
               "windows_appended": self.outbox.appended}
        doc.update(fields)
        with open(os.path.join(self.dir, RUNS_FILE), "a") as f:
            f.write(json.dumps(doc, sort_keys=True) + "\n")
            f.flush()
            os.fsync(f.fileno())

    def close(self) -> None:
        self._heartbeat.close()
        self.outbox.close()


def read_runs(workdir: str) -> List[dict]:
    """All incarnation summaries for one worker dir, oldest first."""
    path = os.path.join(workdir, RUNS_FILE)
    out: List[dict] = []
    if not os.path.exists(path):
        return out
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                try:
                    out.append(json.loads(line))
                except ValueError:
                    continue
    return out
