"""Shared synthetic skew/clustered stream generators.

One definition of the Zipfian/clustered traffic shape used everywhere the
skew machinery is exercised — the cost-attribution tests
(``tests/test_cost_attribution.py``), the adaptive-grid suites
(``tests/test_repartition.py``), and the skew sweep benchmark
(``benchmarks/bench_skew.py``) all import from here instead of each keeping
a private copy (the generator previously lived inline in the
cost-attribution tests).

Two shapes:

- :func:`zipf_cells` — raw CELL-ID streams for accumulator-level tests
  (occupancy / cost-profile units that never touch coordinates);
- :func:`clustered_points` / :func:`clustered_lines` — COORDINATE streams:
  a tight hot cluster holding ``hot_share`` of the records (the Zipf head a
  vehicle/checkin feed parks on a downtown cell) over a uniform background
  (the tail). ``hot_share=0`` degenerates to pure uniform traffic — the
  no-skew control row of the sweep.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

#: the hot cell of the :func:`zipf_cells` streams (kept at the historical
#: value the cost-attribution tests pinned)
ZIPF_HOT = 17


def zipf_cells(n: int = 4000, seed: int = 7, hot: int = ZIPF_HOT,
               hot_share: float = 0.6) -> np.ndarray:
    """A clustered cell-id stream: ``hot_share`` of records land in ``hot``,
    the rest spread Zipf-ish over higher cells — the skew shape a uniform
    grid sees under real (vehicle/checkin) traffic."""
    rng = np.random.default_rng(seed)
    tail = 20 + (rng.zipf(1.5, n) % 60)
    cells = np.where(rng.uniform(size=n) < hot_share, hot, tail)
    return cells.astype(np.int64)


def clustered_xy(grid, n: int, hot_share: float, seed: int = 7,
                 hot_center: Optional[Tuple[float, float]] = None,
                 cluster_span_cells: float = 2.0
                 ) -> Tuple[np.ndarray, np.ndarray]:
    """(x, y) arrays: ``hot_share`` of the points uniform inside a tight
    cluster box spanning ``cluster_span_cells`` grid cells around
    ``hot_center`` (default: the bbox middle, snapped off cell boundaries),
    the rest uniform over the whole bbox. Deterministic per seed."""
    rng = np.random.default_rng(seed)
    span = cluster_span_cells * grid.cell_length
    if hot_center is None:
        # mid-bbox, nudged a third of a cell so the cluster box never sits
        # exactly on a cell boundary (stable cell membership per seed)
        hot_center = ((grid.min_x + grid.max_x) / 2 + grid.cell_length / 3,
                      (grid.min_y + grid.max_y) / 2 + grid.cell_length / 3)
    hx, hy = hot_center
    hot = rng.uniform(size=n) < hot_share
    x = rng.uniform(grid.min_x, grid.max_x, n)
    y = rng.uniform(grid.min_y, grid.max_y, n)
    x[hot] = hx + rng.uniform(-span / 2, span / 2, int(hot.sum()))
    y[hot] = hy + rng.uniform(-span / 2, span / 2, int(hot.sum()))
    # the cluster must stay inside the bbox whatever the center
    x = np.clip(x, grid.min_x, np.nextafter(grid.max_x, -np.inf))
    y = np.clip(y, grid.min_y, np.nextafter(grid.max_y, -np.inf))
    return x, y


def clustered_points(grid, n: int, hot_share: float, seed: int = 7,
                     t0: int = 1_700_000_000_000, dt_ms: int = 100,
                     hot_center: Optional[Tuple[float, float]] = None,
                     cluster_span_cells: float = 2.0,
                     id_pool: int = 4093) -> List:
    """``n`` :class:`~spatialflink_tpu.models.Point` records on the
    clustered distribution, timestamps ``t0 + i * dt_ms`` (in order — the
    watermark-friendly shape every generator here emits). Object ids cycle
    through a bounded pool of ``id_pool`` ids (real feeds track a finite
    fleet; per-record-unique ids would make the decode interner the
    bottleneck and measure string hashing instead of the pipeline)."""
    from spatialflink_tpu.models import Point

    x, y = clustered_xy(grid, n, hot_share, seed, hot_center,
                        cluster_span_cells)
    return [Point.create(float(x[i]), float(y[i]), grid,
                         obj_id=f"o{i % id_pool}",
                         timestamp=t0 + i * dt_ms)
            for i in range(n)]


def clustered_lines(grid, n: int, hot_share: float, seed: int = 7,
                    fmt: str = "csv", t0: int = 1_700_000_000_000,
                    dt_ms: int = 100,
                    hot_center: Optional[Tuple[float, float]] = None,
                    cluster_span_cells: float = 2.0,
                    id_pool: int = 4093) -> List[str]:
    """The same stream as serialized ingest lines (``csv`` rows matching
    schema [oID, ts, x, y], or ``geojson`` features) — what the driver-level
    suites and the bench feed through the real decode path."""
    x, y = clustered_xy(grid, n, hot_share, seed, hot_center,
                        cluster_span_cells)
    ts = t0 + np.arange(n, dtype=np.int64) * dt_ms
    if fmt.lower() == "csv":
        return [f"o{i % id_pool},{int(ts[i])},{x[i]:.7f},{y[i]:.7f}"
                for i in range(n)]
    if fmt.lower() == "geojson":
        from spatialflink_tpu.models import Point
        from spatialflink_tpu.streams.formats import serialize_spatial

        return [serialize_spatial(
            Point.create(float(x[i]), float(y[i]), grid,
                         obj_id=f"o{i % id_pool}",
                         timestamp=int(ts[i])), "GeoJSON")
                for i in range(n)]
    raise ValueError(f"clustered_lines supports csv/geojson, not {fmt!r}")
