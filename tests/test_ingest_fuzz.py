"""Deterministic mutation fuzz over the native ingest parsers.

The C++ parsers walk raw bytes with hand-managed bounds; a mutated or
truncated line must either parse, reject to Python (whose error story is
tested elsewhere), or raise a Python-level exception — NEVER corrupt memory
or crash the interpreter. The suite mutates valid corpora (truncate, splice,
duplicate brackets/quotes/delimiters, flip bytes) and simply requires every
call to return or raise cleanly; an out-of-bounds write would crash the
test process itself, which is the signal.
"""

import numpy as np
import pytest

from spatialflink_tpu import native
from spatialflink_tpu.streams import bulk

pytestmark = pytest.mark.skipif(native.lib() is None,
                                reason="native library unavailable")

_WKT = [
    b"p1, 1700000000000, POLYGON ((1 1, 3 1, 3 3, 1 3, 1 1))",
    b"l1, 1700000000001, LINESTRING (0 0, 1 1, 2 0)",
    b"m1, 1700000000002, MULTIPOLYGON (((0 0, 1 0, 1 1, 0 0)))",
]
_GJ = [
    b'{"type": "Feature", "geometry": {"type": "Polygon", "coordinates": '
    b'[[[1, 1], [3, 1], [3, 3], [1, 1]]]}, "properties": {"oID": "p1", '
    b'"timestamp": 1700000000000}}',
    b'{"type": "Feature", "geometry": {"type": "LineString", "coordinates": '
    b'[[0, 0], [1, 1]]}, "properties": {"oID": "l1"}}',
    b'{"value": {"type": "Feature", "geometry": {"type": "Point", '
    b'"coordinates": [1, 2]}, "properties": {"oID": "x"}}}',
]
_CSV = [b"o1,1700000000000,116.5,40.5", b"o2,1700000000001,116.6,40.6"]

_NOISE = [b"[", b"]", b"(", b")", b'"', b",", b"\\", b"\n", b"\x00", b"{",
          b"}", b"POLYGON", b"coordinates", b"-", b"1e308", b" "]


def _mutations(corpus, rng, n):
    lines = list(corpus)
    for _ in range(n):
        base = bytearray(lines[rng.integers(len(lines))])
        op = rng.integers(5)
        if op == 0 and len(base) > 1:  # truncate
            base = base[: rng.integers(1, len(base))]
        elif op == 1:  # splice noise
            tok = _NOISE[rng.integers(len(_NOISE))]
            pos = rng.integers(len(base) + 1)
            base = base[:pos] + tok + base[pos:]
        elif op == 2 and base:  # flip a byte
            base[rng.integers(len(base))] = rng.integers(32, 127)
        elif op == 3:  # duplicate a slice
            a, b = sorted(rng.integers(0, len(base) + 1, 2))
            base = base[:a] + base[a:b] * 2 + base[b:]
        else:  # concatenate two lines on one row
            base = base + b" " + bytes(lines[rng.integers(len(lines))])
        yield bytes(base)


def _survives(fn, data, **kw):
    try:
        fn(data, **kw)
    except Exception:
        pass  # clean Python-level failure is fine; a crash is not


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_wkt_geoms_fuzz(seed):
    rng = np.random.default_rng(seed)
    for mut in _mutations(_WKT, rng, 400):
        _survives(bulk.bulk_parse_wkt, mut, date_format=None)


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_geojson_geoms_fuzz(seed):
    rng = np.random.default_rng(seed)
    for mut in _mutations(_GJ, rng, 400):
        _survives(bulk.bulk_parse_geojson_geoms, mut)


@pytest.mark.parametrize("seed", [0, 1])
def test_point_parsers_fuzz(seed):
    rng = np.random.default_rng(seed)
    for mut in _mutations(_CSV, rng, 300):
        _survives(bulk.bulk_parse_csv, mut, date_format=None)
    for mut in _mutations(_GJ, rng, 300):
        _survives(bulk.bulk_parse_geojson, mut)


def test_multi_line_blocks_fuzz():
    # whole blocks: shuffled valid+mutated lines joined with \n, plus
    # pathological all-bracket blocks that stress the capacity bounds
    rng = np.random.default_rng(9)
    pool = _WKT + [next(_mutations(_WKT, rng, 1)) for _ in range(20)]
    for _ in range(50):
        k = rng.integers(1, 8)
        block = b"\n".join(pool[int(i)] for i in rng.integers(0, len(pool), k))
        _survives(bulk.bulk_parse_wkt, block, date_format=None)
    _survives(bulk.bulk_parse_wkt, b"(" * 10_000, date_format=None)
    _survives(bulk.bulk_parse_geojson_geoms, b"[" * 10_000)
    _survives(bulk.bulk_parse_geojson_geoms,
              b'{"type": "Feature", "geometry": {"type": "Polygon", '
              b'"coordinates": ' + b"[" * 5_000 + b"]" * 5_000 + b"}}")
