"""Metrics/observability tests (reference: Flink metric wrappers in
Point.java:220-253, control tuple in HelperClass.java:441-453)."""

import json

import pytest

from spatialflink_tpu.utils.metrics import (
    REGISTRY,
    ControlTupleExit,
    Counter,
    Meter,
    MetricsRegistry,
    check_exit_control_tuple,
    metered,
    trace,
)


class TestCounterMeter:
    def test_counter(self):
        c = Counter("x")
        c.inc()
        c.inc(5)
        assert c.count == 6

    def test_meter_rate(self):
        m = Meter("tput", window_s=10.0)
        for i in range(100):
            m.mark(now=float(i) * 0.01)  # 100 events over 1s
        assert m.count == 100
        assert m.rate(now=1.0) == pytest.approx(100.0, rel=0.1)

    def test_meter_window_eviction(self):
        m = Meter("tput", window_s=1.0)
        m.mark(now=0.0)
        m.mark(now=5.0)
        # the t=0 bucket fell out of the 1s window
        assert m.rate(now=5.0) > 0
        assert len(m._buckets) == 1

    def test_meter_eviction_with_injected_clock(self):
        # drive the bucket eviction purely off injected monotonic nows:
        # marks land in per-second buckets; advancing the clock past the
        # window drops exactly the stale buckets and the rate reflects only
        # the surviving ones
        m = Meter("tput", window_s=5.0)
        for sec in range(10):                 # one mark at t=0..9
            m.mark(now=float(sec))
        assert m.count == 10
        # at t=9 the 5s window covers t in [3, 9] (horizon = now-window-1)
        m.rate(now=9.0)
        assert [b[0] for b in m._buckets] == [3, 4, 5, 6, 7, 8, 9]
        # far future: everything evicts, rate decays to 0
        assert m.rate(now=100.0) == 0.0
        assert len(m._buckets) == 0
        # count is cumulative and survives eviction (Dropwizard semantics)
        assert m.count == 10

    def test_meter_memory_is_bounded(self):
        # one bucket per second regardless of event count (hot-path safety)
        m = Meter("tput", window_s=60.0)
        for i in range(10_000):
            m.mark(now=100.0 + i * 0.0003)  # 10k events over 3s
        assert m.count == 10_000
        assert len(m._buckets) <= 4

    def test_non_dict_value_key_passes(self):
        check_exit_control_tuple({"value": "raw-bytes",
                                  "geometry": {"type": "Point"}})

    def test_registry_snapshot(self):
        r = MetricsRegistry()
        r.counter("a").inc(3)
        r.meter("b").mark()
        snap = r.snapshot()
        assert snap["a"] == 3
        assert snap["b.count"] == 1


class TestControlTuple:
    def test_geojson_string_control(self):
        rec = json.dumps({"geometry": {"type": "control"}})
        with pytest.raises(ControlTupleExit):
            check_exit_control_tuple(rec)

    def test_kafka_envelope_control(self):
        rec = {"value": {"geometry": {"type": "control"}}}
        with pytest.raises(ControlTupleExit):
            check_exit_control_tuple(rec)

    def test_normal_records_pass(self):
        check_exit_control_tuple('{"geometry": {"type": "Point"}}')
        check_exit_control_tuple({"geometry": {"type": "Point"}})
        check_exit_control_tuple("not json at all")

    def test_metered_stream(self):
        m = Meter("s")
        out = list(metered(iter([1, 2, 3]), m))
        assert out == [1, 2, 3] and m.count == 3

    def test_control_tuple_stops_driver_stream(self):
        from spatialflink_tpu.config import Params
        from spatialflink_tpu.driver import run_option

        params = Params.from_yaml("conf/spatialflink-conf.yml")
        params.query.option = 1
        lines = [json.dumps({
            "geometry": {"type": "Point", "coordinates": [116.5, 40.5]},
            "properties": {"oID": "a", "timestamp": 1700000000000},
        }), json.dumps({"geometry": {"type": "control"}})]
        with pytest.raises(ControlTupleExit):
            list(run_option(params, lines))


class TestOperatorMetrics:
    def test_drive_counts_batches_and_records(self):
        from spatialflink_tpu.index import UniformGrid
        from spatialflink_tpu.models import Point
        from spatialflink_tpu.operators import (
            PointPointRangeQuery,
            QueryConfiguration,
            QueryType,
        )

        grid = UniformGrid(0.0, 10.0, 0.0, 10.0, num_grid_partitions=10)
        pts = [Point.create(5.0, 5.0, grid, obj_id=f"o{i}",
                            timestamp=1_700_000_000_000 + i * 1000)
               for i in range(8)]
        before = REGISTRY.counter("records-evaluated").count
        conf = QueryConfiguration(QueryType.WindowBased, 10_000, 5_000)
        q = Point.create(5.0, 5.0, grid)
        list(PointPointRangeQuery(conf, grid).run(iter(pts), q, 1.0))
        assert REGISTRY.counter("records-evaluated").count > before


class TestOffTypeDropping:
    """decode_stream dead-letters records whose parsed type can't ride the
    declared stream's operator pipeline, counting them (off-type-dropped)
    instead of crashing the batcher."""

    def _decode(self, lines, geometry):
        from spatialflink_tpu.config import StreamConfig
        from spatialflink_tpu.driver import decode_stream
        from spatialflink_tpu.index import UniformGrid

        grid = UniformGrid(0.0, 10.0, 0.0, 10.0, num_grid_partitions=10)
        cfg = StreamConfig(format="WKT")
        return list(decode_stream(iter(lines), cfg, grid, geometry))

    def test_point_in_polygon_stream_dropped_and_counted(self):
        before = REGISTRY.counter("off-type-dropped").count
        out = self._decode(
            ["POLYGON ((1 1, 2 1, 2 2, 1 2, 1 1))", "POINT (5 5)"],
            "Polygon")
        assert len(out) == 1 and hasattr(out[0], "edge_array")
        assert REGISTRY.counter("off-type-dropped").count == before + 1

    def test_polygon_in_point_stream_dropped_and_counted(self):
        before = REGISTRY.counter("off-type-dropped").count
        out = self._decode(
            ["POINT (5 5)", "POLYGON ((1 1, 2 1, 2 2, 1 2, 1 1))"],
            "Point")
        assert len(out) == 1 and hasattr(out[0], "x")
        assert REGISTRY.counter("off-type-dropped").count == before + 1


class TestPruningCounters:
    """Distance-computation / GN-bypass counters (pruning effectiveness,
    ``spatialObjects/Point.java:220-235``)."""

    def _grid_pts(self):
        from spatialflink_tpu.index import UniformGrid
        from spatialflink_tpu.models import Point

        grid = UniformGrid(0.0, 10.0, 0.0, 10.0, num_grid_partitions=10)
        pts = [Point.create(5.0 + 0.01 * i, 5.0, grid, obj_id=f"o{i}",
                            timestamp=1_700_000_000_000 + i)
               for i in range(16)]
        return grid, pts

    def test_gn_window_reports_zero_distance_evals(self):
        # radius big enough that every cell is a guaranteed neighbor of the
        # query's cell: all points ride the GN bypass, no distances consulted
        from spatialflink_tpu.models import Point
        from spatialflink_tpu.operators import (
            PointPointRangeQuery, QueryConfiguration, QueryType)

        grid, pts = self._grid_pts()
        q = Point.create(5.0, 5.0, grid)
        radius = 50.0  # guaranteed_layers covers the whole 10x10 grid
        assert grid.guaranteed_layers(radius) >= grid.n
        d0 = REGISTRY.counter("distance-computations").count
        g0 = REGISTRY.counter("gn-bypassed").count
        conf = QueryConfiguration(QueryType.WindowBased, 10_000, 10_000)
        out = list(PointPointRangeQuery(conf, grid).run(iter(pts), q, radius))
        assert sum(len(w.records) for w in out) == len(pts)
        assert REGISTRY.counter("distance-computations").count == d0
        assert REGISTRY.counter("gn-bypassed").count - g0 == len(pts)

    def test_cn_window_counts_distance_evals(self):
        from spatialflink_tpu.models import Point
        from spatialflink_tpu.operators import (
            PointPointRangeQuery, QueryConfiguration, QueryType)

        grid, pts = self._grid_pts()
        q = Point.create(5.0, 5.0, grid)
        radius = 0.5  # no guaranteed layer at this radius/grid (gn = -1)
        assert grid.guaranteed_layers(radius) < 0
        d0 = REGISTRY.counter("distance-computations").count
        conf = QueryConfiguration(QueryType.WindowBased, 10_000, 10_000)
        list(PointPointRangeQuery(conf, grid).run(iter(pts), q, radius))
        assert REGISTRY.counter("distance-computations").count - d0 == len(pts)

    def test_distributed_paths_report_counters_too(self):
        # parallelism>1 must not silently zero the pruning metrics (the
        # per-shard scalars psum-merge); counts equal the 1-device run
        from spatialflink_tpu.models import Point
        from spatialflink_tpu.operators import (
            PointPointKNNQuery, PointPointRangeQuery,
            QueryConfiguration, QueryType)

        grid, pts = self._grid_pts()
        q = Point.create(5.0, 5.0, grid)
        d0 = REGISTRY.counter("distance-computations").count
        g0 = REGISTRY.counter("gn-bypassed").count
        conf = QueryConfiguration(QueryType.WindowBased, 10_000, 10_000,
                                  devices=8, k=4)
        list(PointPointRangeQuery(conf, grid).run(iter(pts), q, 0.5))
        assert REGISTRY.counter("distance-computations").count - d0 == len(pts)
        d1 = REGISTRY.counter("distance-computations").count
        list(PointPointRangeQuery(conf, grid).run(iter(pts), q, 50.0))
        assert REGISTRY.counter("distance-computations").count == d1  # all GN
        assert REGISTRY.counter("gn-bypassed").count - g0 == len(pts)
        d2 = REGISTRY.counter("distance-computations").count
        list(PointPointKNNQuery(conf, grid).run(iter(pts), q, 0.0))
        assert REGISTRY.counter("distance-computations").count - d2 == len(pts)

    def test_knn_counts_eligible_distance_evals(self):
        from spatialflink_tpu.models import Point
        from spatialflink_tpu.operators import (
            PointPointKNNQuery, QueryConfiguration, QueryType)

        grid, pts = self._grid_pts()
        q = Point.create(5.0, 5.0, grid)
        d0 = REGISTRY.counter("distance-computations").count
        conf = QueryConfiguration(QueryType.WindowBased, 10_000, 10_000, k=4)
        out = list(PointPointKNNQuery(conf, grid).run(iter(pts), q, 0.0))
        assert out  # sanity: windows emitted
        # radius 0 disables pruning -> every valid point is a candidate
        assert REGISTRY.counter("distance-computations").count - d0 == len(pts)


def test_trace_is_safe_noop_without_profiler():
    with trace("stage-x"):
        pass
