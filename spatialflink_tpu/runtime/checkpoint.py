"""Coordinated pipeline checkpointing with exactly-once crash recovery.

The reference inherits coordinated snapshots from Flink for free (GeoFlink
never configures them — SURVEY §5); the rebuild's ``--checkpoint`` covered
only the stateful realtime trajectory queries. This module generalizes that
into ONE coordinator that periodically snapshots the whole pipeline — source
positions, watermarks, open ``WindowAssembler``/``PaneBuffer`` windows,
``PaneCache`` partials, trajectory state, and supervision (circuit-breaker)
state — into a single atomic, checksummed, versioned manifest under a
checkpoint DIRECTORY, retaining the last K manifests with automatic
fallback to the previous one on corruption.

Consistency model (the "barrier"): participants are snapshotted only at
points where

1. every result yielded so far has been fully consumed downstream (sinks
   produced, window markers written, offsets committed) — guaranteed by
   generator semantics: code after a ``yield`` runs only once the consumer
   pulled the next item; and
2. no sealed-window payload is in flight — the pipelined drivers drain
   their deferred windows to zero before committing a checkpoint.

At such a point every record the source taps have reported is either (a)
buffered in a snapshotted structure (assembler/pane buffer/trajectory
state), (b) reflected in an already-produced result, or (c) dropped as
late/off-type — so ``restore + seek sources to the checkpointed positions``
reproduces the uninterrupted run exactly. Windows emitted between the last
checkpoint and a crash are re-emitted on resume with identical contents and
suppressed by the marker-seeded :class:`~spatialflink_tpu.streams.kafka
.KafkaWindowSink`, which is what upgrades bounded at-least-once replay to
exactly-once output.
"""

from __future__ import annotations

import base64
import json
import os
import re
import sys
import threading
import time
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

import numpy as np

from spatialflink_tpu.runtime.state import (CheckpointableState,
                                            CheckpointCorrupt)

#: manifest layout version (independent of the npz envelope version in
#: runtime.state): bump on incompatible changes to the component layout
MANIFEST_SCHEMA_VERSION = 1

_CKPT_RE = re.compile(r"^ckpt-(\d{8})\.npz$")


class CheckpointMismatch(RuntimeError):
    """A checkpoint was written by a DIFFERENT job configuration (query /
    window / group fingerprint). Restoring it would silently produce wrong
    state, so the resume refuses instead."""


def check_job_fingerprint(saved: Optional[str], current: Optional[str],
                          path: str) -> None:
    """The ONE job-fingerprint guard every resume path shares (the legacy
    single-file checkpoint, the driver's pre-flight check, and the
    coordinator's manifest load): raises :class:`CheckpointMismatch` when
    both fingerprints are known and differ."""
    if current and saved and saved != current:
        raise CheckpointMismatch(
            f"{path} was written by job fingerprint {saved!r} but this run "
            f"is {current!r} (different query/window config or consumer "
            "group); restoring it would produce wrong state. Use a fresh "
            "checkpoint location, or rerun with the original "
            "configuration.")


def atomic_write_json(path: str, doc: dict) -> None:
    """Durable small-JSON write (fsync + rename): the fleet manifest,
    worker run summaries, and partition-done markers ride the same
    atomicity discipline as the checkpoint manifests, without the npz
    envelope — a reader never observes a torn document."""
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(doc, f, sort_keys=True)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def read_json(path: str, default=None):
    """Best-effort JSON read: ``default`` on a missing/torn file (the
    atomic-write discipline makes torn mean mid-rename crash debris)."""
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return default


# --------------------------------------------------------------------- #
# codecs


def record_codec(grid):
    """(encode, decode) for stream records (SpatialObjects): GeoJSON with
    raw epoch-ms timestamps (``date_format=None`` serializes the timestamp
    as an integer, which the parser passes through unchanged — a lossless
    round trip; ``ingestion_time`` is transport metadata and is not
    carried)."""
    from spatialflink_tpu.streams.formats import (parse_spatial,
                                                  serialize_spatial)

    def encode(obj) -> str:
        return serialize_spatial(obj, "GeoJSON")

    def decode(s: str):
        return parse_spatial(s, "GeoJSON", grid)

    return encode, decode


def value_codec(grid):
    """(encode, decode) for pane-partial values: a tagged JSON projection
    covering every partial shape the operator families cache — record
    lists (range/tRange), (objID, distance) tuples (kNN), matched-ID sets
    (tRange), per-trajectory numpy summaries (tStats/tAggregate), and
    pane-pair blocks of (record, record, distance) tuples (join). Raises
    ``TypeError`` for anything else so an unencodable partial is skipped
    loudly at snapshot time (it simply recomputes on resume) rather than
    silently mangled."""
    enc_rec, dec_rec = record_codec(grid)

    def enc(v):
        if v is None or isinstance(v, (bool, int, str)):
            return v
        if isinstance(v, float):
            return v
        if isinstance(v, np.generic):
            return v.item()
        if isinstance(v, np.ndarray):
            return {"__nd__": [str(v.dtype), list(v.shape),
                               base64.b64encode(
                                   np.ascontiguousarray(v).tobytes()
                               ).decode("ascii")]}
        if isinstance(v, tuple):
            return {"__t__": [enc(x) for x in v]}
        if isinstance(v, list):
            return {"__l__": [enc(x) for x in v]}
        if isinstance(v, (set, frozenset)):
            return {"__s__": sorted(enc(x) for x in v)}
        if isinstance(v, dict):
            if not all(isinstance(k, str) for k in v):
                raise TypeError("only str-keyed dicts are encodable")
            return {"__d__": {k: enc(x) for k, x in v.items()}}
        if hasattr(v, "obj_id") and hasattr(v, "timestamp"):
            return {"__geo__": enc_rec(v)}
        raise TypeError(f"unencodable partial value {type(v).__name__}")

    def dec(v):
        if not isinstance(v, dict):
            return v
        if "__nd__" in v:
            dtype, shape, data = v["__nd__"]
            return np.frombuffer(
                base64.b64decode(data), dtype=np.dtype(dtype)
            ).reshape(shape).copy()
        if "__t__" in v:
            return tuple(dec(x) for x in v["__t__"])
        if "__l__" in v:
            return [dec(x) for x in v["__l__"]]
        if "__s__" in v:
            return {dec(x) for x in v["__s__"]}
        if "__d__" in v:
            return {k: dec(x) for k, x in v["__d__"].items()}
        if "__geo__" in v:
            return dec_rec(v["__geo__"])
        return v

    return enc, dec


# --------------------------------------------------------------------- #
# source taps


class CheckpointTap:
    """Pass-through source wrapper that reports the pipeline's live source
    position to the coordinator as records are handed downstream.

    ``position_fn`` (e.g. ``lambda: kafka_source.position``) reports the
    source's own next-offset; without one the tap counts records from
    ``base`` (the file-replay case). Positions are noted BEFORE the yield:
    at any coordinator barrier the wrapping generator is suspended at its
    ``yield`` and the yielded record has been fully processed, so the last
    noted position is exactly "everything before this is reflected
    downstream"."""

    def __init__(self, source, coordinator: "CheckpointCoordinator",
                 key: str, base: int = 0,
                 position_fn: Optional[Callable[[], int]] = None):
        self.source = source
        self.coordinator = coordinator
        self.key = key
        self.base = int(base)
        self.position_fn = position_fn

    def __iter__(self) -> Iterator:
        note = self.coordinator.note_position
        n = self.base
        for rec in self.source:
            if self.position_fn is not None:
                note(self.key, self.position_fn())
            else:
                n += 1
                note(self.key, n)
            yield rec


class EmittedWindowJournal:
    """Durable append-only log of emitted window keys for sinks without
    recovery state of their own (the driver's stdout/``--output`` file
    path): on resume, windows already journaled are suppressed instead of
    re-emitted, upgrading the file path to exactly-once output across a
    process crash — the role the commit markers in the output topic play
    for the Kafka sink.

    Keys are ``start:end:cell`` (the idempotent window-sink key). Lines are
    flushed per window: a ``kill -9`` cannot lose them (the OS owns the
    buffer once written); only a machine crash can drop the un-fsynced
    tail, in which case the affected windows re-emit with identical
    contents (at-least-once, never wrong).

    Under a fenced fleet, lines are stamped ``<fence>\\t<key>`` (fence 0
    — single-process runs — keeps the bare-key format, so existing
    journals stay readable and non-fleet runs are byte-identical).
    ``fence_cutoffs`` maps a superseded fence to the journal byte size
    recorded when that fence was bumped away: a line stamped with fence
    *f* that starts at-or-past ``fence_cutoffs[f]`` was written by a
    zombie incarnation whose corresponding outbox rows are fence-dropped
    at merge — trusting it would suppress the re-emission that makes the
    merged table whole, so it is skipped at load."""

    FILENAME = "emitted.log"

    def __init__(self, directory: str, fresh: bool = False, *,
                 fence: int = 0,
                 fence_cutoffs: Optional[Dict[int, int]] = None):
        self.path = os.path.join(directory, self.FILENAME)
        self.fence = int(fence)
        if fresh and os.path.exists(self.path):
            os.unlink(self.path)  # a non-resume run starts a new history
        self._seen = set()
        if os.path.exists(self.path):
            cuts = fence_cutoffs or {}
            with open(self.path, "rb") as f:
                pos = 0
                for raw in f:
                    start = pos
                    pos += len(raw)
                    ln = raw.decode("utf-8", "replace").rstrip("\n")
                    if not ln.strip():
                        continue
                    head, sep, rest = ln.partition("\t")
                    lfence, key = 0, ln
                    if sep:
                        try:
                            lfence, key = int(head), rest
                        except ValueError:
                            pass  # a tab inside a bare legacy key
                    cut = cuts.get(lfence)
                    if cut is not None and start >= int(cut):
                        continue  # zombie-journaled: window must re-emit
                    self._seen.add(key)
        self._f = open(self.path, "a")
        self.suppressed = 0

    @staticmethod
    def key(result) -> str:
        cell = (getattr(result, "extras", {}).get("cell")
                if hasattr(result, "extras") else None)
        return (f"{getattr(result, 'window_start', None)}:"
                f"{getattr(result, 'window_end', None)}:{cell}")

    def seen(self, result) -> bool:
        if self.key(result) in self._seen:
            self.suppressed += 1
            return True
        return False

    def record(self, result) -> None:
        k = self.key(result)
        if k not in self._seen:
            self._seen.add(k)
            self._f.write((f"{self.fence}\t{k}" if self.fence else k)
                          + "\n")
            self._f.flush()

    def close(self) -> None:
        self._f.close()


# --------------------------------------------------------------------- #
# coordinator


class CheckpointCoordinator:
    """Periodic whole-pipeline snapshots into one atomic manifest per
    checkpoint, written off the drive loop's critical path (a cheap
    counter/clock check per batch; the write itself happens at a drained
    barrier every ``every_batches`` processing units or ``every_seconds``
    wall seconds, whichever fires first).

    Participants register ``(snapshot_fn, restore_fn)`` under a stable
    name; ``snapshot_fn() -> (arrays, meta)`` returns numpy arrays plus
    JSON-able metadata, and ``restore_fn(arrays, meta)`` applies a loaded
    component. Registration auto-restores when a loaded manifest holds
    state for that name, so participants created lazily (assemblers built
    when the pipeline first iterates) pick up their state the moment they
    exist.

    Manifests are ``ckpt-<seq>.npz`` files riding
    :class:`~spatialflink_tpu.runtime.state.CheckpointableState`'s
    fsync+rename+checksum discipline; the newest ``retain`` are kept and
    :meth:`load` falls back to the previous manifest when the newest is
    truncated/corrupt (counter ``checkpoint-fallbacks``)."""

    def __init__(self, directory: str, *, every_batches: int = 16,
                 every_seconds: Optional[float] = None, retain: int = 3,
                 job: Optional[str] = None, layout: Optional[str] = None):
        os.makedirs(directory, exist_ok=True)
        self.dir = directory
        self.every_batches = max(1, int(every_batches))
        self.every_seconds = every_seconds
        self.retain = max(1, int(retain))
        self.job = job
        #: execution-layout tag (family:mode:panes:multi). The job
        #: fingerprint deliberately EXCLUDES execution knobs like --panes
        #: (a panes-on re-run must dedup against a panes-off run's sink
        #: markers), but the checkpoint's component layout depends on them:
        #: restoring a panes-on manifest into a panes-off run would leave
        #: the pane components unclaimed and lose their buffered records.
        #: Layout mismatch therefore refuses at load.
        self.layout = layout
        self.restored = False
        self.written = 0
        # the coordinator is driven by the pipeline thread at barriers,
        # but the opserver/reporter threads read seq/age through the
        # gauges and the doctor may probe concurrently — writes to the
        # cadence/sequence state hold this lock (RLock: commit() spans
        # participant snapshot callbacks)
        self._lock = threading.RLock()
        self._snapshots: Dict[str, Callable[[], Tuple[dict, Any]]] = {}
        self._pending: Dict[str, Tuple[dict, Any]] = {}
        self._positions: Dict[str, int] = {}
        self._batches = 0
        self._last_batches = 0
        self._last_time = time.monotonic()
        self._age_gauge_installed = False
        # continue numbering past any existing manifests: a fresh run (no
        # --resume) into a non-empty directory must sort NEWER than the
        # stale files so retention prunes them, not the new checkpoints
        existing = self._manifests()
        self.seq = existing[-1][0] if existing else 0

    # ------------------------------ participants ---------------------- #

    def register(self, name: str,
                 snapshot_fn: Callable[[], Tuple[dict, Any]],
                 restore_fn: Optional[Callable[[dict, Any], None]] = None
                 ) -> bool:
        """Register a participant; returns True when pending loaded state
        was applied through ``restore_fn``."""
        self._snapshots[name] = snapshot_fn
        if restore_fn is not None and name in self._pending:
            arrays, meta = self._pending.pop(name)
            restore_fn(arrays, meta)
            return True
        return False

    def note_position(self, key: str, next_pos: int) -> None:
        self._positions[key] = int(next_pos)

    def position(self, key: str, default: int = 0) -> int:
        return int(self._positions.get(key, default))

    def positions(self) -> Dict[str, int]:
        return dict(self._positions)

    # ------------------------------ cadence --------------------------- #

    def note_batch(self) -> None:
        with self._lock:
            self._batches += 1

    def due(self) -> bool:
        from spatialflink_tpu.runtime.faults import active_stall
        st = active_stall()
        if st is not None and st.wedged():
            # injected gray failure: the checkpoint surface wedges with
            # the heartbeat — a zombie must not commit manifests its
            # fenced successor would then resume from
            return False
        if self._batches - self._last_batches >= self.every_batches:
            return True
        return (self.every_seconds is not None
                and time.monotonic() - self._last_time >= self.every_seconds)

    def barrier(self) -> bool:
        """One processing unit completed at a consistent point (all yielded
        results consumed, nothing in flight): count it and checkpoint if
        due. The per-call cost when not due is one int compare."""
        self.note_batch()
        if self.due():
            self.commit()
            return True
        return False

    # ------------------------------ write ----------------------------- #

    def _path(self, seq: int) -> str:
        return os.path.join(self.dir, f"ckpt-{seq:08d}.npz")

    def commit(self) -> str:
        """Snapshot every participant + the live source positions into one
        atomic manifest; prune retained files. Must only be called at a
        barrier (see the module docstring)."""
        from spatialflink_tpu.utils import telemetry as _telemetry
        from spatialflink_tpu.utils.metrics import REGISTRY

        t0 = time.perf_counter()
        cp = CheckpointableState()
        components: Dict[str, Any] = {}
        for name, fn in self._snapshots.items():
            arrays, meta = fn()
            for k, a in (arrays or {}).items():
                cp.arrays[f"{name}/{k}"] = np.asarray(a)
            components[name] = meta
        from spatialflink_tpu.utils import deviceplane as _deviceplane

        with self._lock:
            self.seq += 1
        cp.meta = {
            "manifest_schema": MANIFEST_SCHEMA_VERSION,
            "job": self.job,
            "layout": self.layout,
            "seq": self.seq,
            "wall_ms": int(time.time() * 1000),
            "positions": dict(self._positions),
            "components": components,
            # backend provenance: which device truth wrote this state —
            # a CPU-written manifest resumed on the TPU (or vice versa)
            # is legal (host-layout state restores anywhere) but worth a
            # loud note, and the doctor reads it out of bundles
            "device": _deviceplane.backend_provenance(),
        }
        path = self._path(self.seq)
        cp.save(path)
        self._prune()
        with self._lock:
            self.written += 1
            self._last_batches = self._batches
            self._last_time = time.monotonic()
        REGISTRY.counter("checkpoints-written").inc()
        tel = _telemetry.active()
        if tel is not None:
            write_ms = (time.perf_counter() - t0) * 1e3
            size = os.path.getsize(path)
            tel.histogram("checkpoint-write-ms").record(write_ms)
            tel.histogram("checkpoint-size-bytes").record(size)
            tel.event("checkpoint-committed", seq=self.seq,
                      write_ms=round(write_ms, 3), size_bytes=size)
            if not self._age_gauge_installed:
                # callable gauge: snapshots always report the CURRENT age
                tel.gauge("checkpoint.age-s",
                          lambda: time.monotonic() - self._last_time)
                tel.gauge("checkpoint.seq", lambda: float(self.seq))
                with self._lock:
                    self._age_gauge_installed = True
        return path

    def _manifests(self) -> List[Tuple[int, str]]:
        out = []
        try:
            names = os.listdir(self.dir)
        except OSError:
            return out
        for n in names:
            m = _CKPT_RE.match(n)
            if m:
                out.append((int(m.group(1)), os.path.join(self.dir, n)))
        out.sort()
        return out

    def _prune(self) -> None:
        manifests = self._manifests()
        for _seq, path in manifests[:-self.retain]:
            try:
                os.unlink(path)
            except OSError:
                pass
        # a crash mid-save leaves a ckpt-*.npz.tmp behind; we are the only
        # writer, so any tmp present outside an in-progress save is dead
        for n in os.listdir(self.dir):
            if n.endswith(".npz.tmp") and not os.path.exists(
                    os.path.join(self.dir, n[:-4])):
                try:
                    os.unlink(os.path.join(self.dir, n))
                except OSError:
                    pass

    # ------------------------------ load ------------------------------ #

    def load(self) -> bool:
        """Restore from the newest VALID retained manifest: corrupt or
        truncated manifests (including a torn mid-write tmp that was never
        renamed — those are invisible by construction) fall back to the
        previous one with a warning. Returns False when no valid manifest
        exists. Raises :class:`CheckpointMismatch` when the manifest was
        written by a different job fingerprint."""
        from spatialflink_tpu.utils.metrics import REGISTRY

        for seq, path in reversed(self._manifests()):
            try:
                cp = CheckpointableState.load(path)
                meta = cp.meta
                schema = meta.get("manifest_schema")
                if schema != MANIFEST_SCHEMA_VERSION:
                    raise CheckpointCorrupt(
                        f"{path}: manifest schema {schema!r} != "
                        f"{MANIFEST_SCHEMA_VERSION}")
            except CheckpointCorrupt as e:
                from spatialflink_tpu.utils.telemetry import emit_event

                REGISTRY.counter("checkpoint-fallbacks").inc()
                emit_event("checkpoint-fallback", path=path, error=str(e))
                print(f"warning: {e}; falling back to the previous "
                      "retained checkpoint", file=sys.stderr)
                continue
            check_job_fingerprint(meta.get("job"), self.job, path)
            layout = meta.get("layout")
            if self.layout and layout and layout != self.layout:
                raise CheckpointMismatch(
                    f"{path} was written under execution layout {layout!r} "
                    f"but this run is {self.layout!r} (e.g. --panes, the "
                    "query mode, or the input source/topics changed); its "
                    "components and source positions would not restore "
                    "into this pipeline and records would be lost. Resume "
                    "with the original flags and sources, or use a fresh "
                    "--checkpoint-dir.")
            grouped: Dict[str, dict] = {}
            for k, arr in cp.arrays.items():
                name, _, sub = k.partition("/")
                grouped.setdefault(name, {})[sub] = arr
            with self._lock:
                self._pending = {
                    name: (grouped.get(name, {}), comp_meta)
                    for name, comp_meta in
                    meta.get("components", {}).items()
                }
                self._positions = {k: int(v) for k, v in
                                   meta.get("positions", {}).items()}
                self.seq = int(meta.get("seq", seq))
            written_on = (meta.get("device") or {}).get("platform")
            if written_on:
                from spatialflink_tpu.utils import deviceplane as _dp

                here = _dp.backend_provenance()["platform"]
                if here != written_on:
                    print(f"# note: resuming a checkpoint written on "
                          f"'{written_on}' onto '{here}' (host-layout "
                          "state restores anywhere; device-resident pane "
                          "values were read back at snapshot time)",
                          file=sys.stderr)
            with self._lock:
                self.restored = True
            REGISTRY.counter("checkpoint-restores").inc()
            from spatialflink_tpu.utils.telemetry import emit_event

            emit_event("checkpoint-restored", seq=self.seq,
                       positions=dict(self._positions))
            return True
        return False

    def pending_components(self) -> List[str]:
        """Names of loaded components not yet claimed by a registration —
        non-empty after the run means some state was never restored."""
        return sorted(self._pending)
