"""Device-truth observability plane: compile registry, recompile sentinel,
device-resource telemetry, and the post-mortem flight recorder.

The host side of the pipeline is well lit (spans, live opserver, cost
attribution — PRs 2/5/6) but the device/XLA layer was dark: the
zero-recompile contracts of the adaptive grid (PR 8) and the query plane
(PR 9) existed only as test-time asserts, and a silent CPU fallback
(BENCH r05) was discovered only by reading a ledger tail. This module makes
the device layer first-class:

- :func:`instrumented_jit` — a drop-in ``jax.jit`` replacement every kernel
  entry point in ``ops/*`` uses. It registers the function in the process's
  :class:`CompileRegistry` and hooks the TRACE: jax only executes the
  wrapped Python body on a cache miss (a fresh compile), so steady-state
  dispatch goes through the exact ``jax.jit`` fast path — zero per-call
  overhead, the instrumentation costs only when XLA is already spending
  hundreds of milliseconds compiling. Each trace records the trigger
  signature (abstract shapes/dtypes + static argument values), the trace
  wall-time, and (via a ``jax.monitoring`` listener) the backend compile
  wall-time; ``cost_analysis()`` FLOPs/bytes are computed lazily per entry
  on first request (an AOT lower+compile — one-time, never on a hot path).

- the **recompile sentinel** — ``registry().begin_run(strict)`` +
  ``mark_warm(reason)``: after the declared warmup, ANY fresh compile
  becomes a ``recompile`` lifecycle event (when a telemetry session is
  active), bumps the always-on ``device-recompiles`` counter, and — under
  ``--strict-recompile`` — raises :class:`RecompileError`, aborting the
  run. This promotes the PR 8/9 test-only zero-recompile contracts into an
  always-on production invariant, visible at ``GET /compile``.

- device-resource telemetry — :func:`backend_provenance` (platform, device
  kind, chip count, ``valid_for_target``), :func:`device_memory` (per-device
  live/peak HBM via ``Device.memory_stats()``; explicitly unavailable on
  CPU), and :func:`status_block`, the compact ``device`` stanza stamped
  into every status snapshot, stderr digest, and bench row. Host↔device
  transfer unifies with the existing accounting: the d2h side reads the
  always-on pane-readback byte counters, the h2d side the per-family
  ``CostProfiles.bytes_moved`` estimates.

- :class:`FlightRecorder` — a bounded always-on ring of run lifecycle notes
  that, on crash, SLO breach, strict-recompile abort, or SIGUSR1, dumps a
  post-mortem bundle directory (status snapshot, event ring, compile
  registry, recent window traces, device memory profile, config
  fingerprint) readable by ``python -m spatialflink_tpu.doctor``.

Gating contract: the registry's trace hook fires ONLY at compile time
(never on a cache-hit dispatch), memory probes run only on demand
(snapshot/request/dump — never per record), and the flight recorder exists
only under ``--postmortem-dir`` (which activates a telemetry session) — the
observability-off hot path stays byte-identical, extended-spy-tested in
``tests/test_deviceplane.py``.
"""

from __future__ import annotations

import functools
import json
import os
import signal
import threading
import time
from collections import deque
from typing import Dict, List, Optional

from spatialflink_tpu.utils import metrics as _metrics

#: bundle layout version (doctor refuses bundles it cannot read).
#: 2: + latency.json — the stage-residency decomposition, record→emit
#: histograms (global + per query) and the backpressure time series, so a
#: breach bundle answers "which stage blew the budget" offline
#: 3: + tenants.json — the per-tenant cost ledger (attributed kernel-ms/
#: bytes, fairness summary, quota counters), so a breach bundle answers
#: "who was paying for the pipeline when it died"
BUNDLE_SCHEMA = 3


class RecompileError(Exception):
    """A post-warmup XLA compile under ``--strict-recompile``.

    Deliberately NOT a RuntimeError: the elastic mesh degradation path
    (``operators.base._eval_degradable``) absorbs RuntimeErrors as device
    failures, and a contract violation must abort, not degrade."""


# --------------------------------------------------------------------- #
# signature capture (at trace time the dynamic args are tracers — their
# avals are exactly the compile-cache trigger; statics are concrete)


def _sig_leaf(x) -> str:
    aval = getattr(x, "aval", None)
    if aval is not None and hasattr(aval, "shape"):
        return f"{aval.dtype}[{'x'.join(str(d) for d in aval.shape)}]"
    shape = getattr(x, "shape", None)
    dtype = getattr(x, "dtype", None)
    if shape is not None and dtype is not None:
        return f"{dtype}[{'x'.join(str(d) for d in shape)}]"
    r = repr(x)
    return r if len(r) <= 48 else r[:45] + "..."


def _lower_leaf(x):
    """Tracer -> ShapeDtypeStruct (the lazy cost-analysis lowering re-feeds
    these to ``jitted.lower``); everything else passes through concrete."""
    import jax

    aval = getattr(x, "aval", None)
    if aval is not None and hasattr(aval, "shape") and hasattr(aval, "dtype"):
        return jax.ShapeDtypeStruct(aval.shape, aval.dtype)
    return x


def _signature(args, kwargs) -> str:
    import jax

    parts = [_sig_leaf(leaf) for leaf in
             jax.tree_util.tree_leaves(args)]
    for k in sorted(kwargs):
        for leaf in jax.tree_util.tree_leaves(kwargs[k]):
            parts.append(f"{k}={_sig_leaf(leaf)}")
    return "(" + ", ".join(parts) + ")"


class CompileEntry:
    """One instrumented jit entry point's compile history."""

    __slots__ = ("name", "module", "jit_kwargs", "compiles", "recompiles",
                 "trace_ms", "backend_compile_ms", "signatures",
                 "first_compile_ms", "last_compile_ms", "_jitted",
                 "_lower_call", "_cost", "_cost_error")

    def __init__(self, name: str, module: str, jit_kwargs: dict):
        self.name = name
        self.module = module
        self.jit_kwargs = {k: repr(v) for k, v in sorted(jit_kwargs.items())}
        self.compiles = 0
        self.recompiles = 0          # post-warmup compiles, cumulative
        self.trace_ms = 0.0          # Python trace time (body execution)
        self.backend_compile_ms = 0.0  # attributed XLA backend compile time
        self.signatures: deque = deque(maxlen=8)
        self.first_compile_ms: Optional[int] = None
        self.last_compile_ms: Optional[int] = None
        self._jitted = None
        self._lower_call = None      # (args, kwargs) with ShapeDtypeStructs
        self._cost: Optional[dict] = None
        self._cost_error: Optional[str] = None

    @property
    def qualname(self) -> str:
        return f"{self.module}.{self.name}"

    def cache_size(self) -> Optional[int]:
        try:
            return int(self._jitted._cache_size())
        except Exception:
            return None

    def cost_analysis(self) -> Optional[dict]:
        """Lazy one-time ``cost_analysis()`` for the LAST-compiled
        signature: an AOT ``lower(...).compile()`` from the captured
        abstract shapes — a real (cached-per-entry) compile, so this runs
        only on explicit request (``/compile?cost=1``, doctor, bundle
        dump), never on a hot path."""
        if self._cost is not None or self._cost_error is not None:
            return self._cost
        if self._jitted is None or self._lower_call is None:
            self._cost_error = "never compiled"
            return None
        try:
            import warnings

            largs, lkwargs = self._lower_call
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                compiled = self._jitted.lower(*largs, **lkwargs).compile()
            ca = compiled.cost_analysis()
            if isinstance(ca, (list, tuple)):
                ca = ca[0] if ca else {}
            keep = {k: float(v) for k, v in (ca or {}).items()
                    if k in ("flops", "bytes accessed", "transcendentals",
                             "optimal_seconds")
                    and isinstance(v, (int, float))}
            self._cost = {"flops": keep.get("flops"),
                          "bytes_accessed": keep.get("bytes accessed"),
                          "transcendentals": keep.get("transcendentals")}
        except Exception as e:  # never let analysis kill an observer
            self._cost_error = f"{type(e).__name__}: {e}"
            return None
        return self._cost

    def to_dict(self, cost: bool = False) -> dict:
        out = {
            "name": self.name,
            "module": self.module,
            "jit_kwargs": dict(self.jit_kwargs),
            "compiles": self.compiles,
            "recompiles": self.recompiles,
            "trace_ms": round(self.trace_ms, 3),
            "backend_compile_ms": round(self.backend_compile_ms, 3),
            "cache_size": self.cache_size(),
            "first_compile_ms": self.first_compile_ms,
            "last_compile_ms": self.last_compile_ms,
            "signatures": list(self.signatures),
        }
        if cost:
            out["cost_analysis"] = self.cost_analysis()
            if self._cost_error is not None:
                out["cost_analysis_error"] = self._cost_error
        return out


class _TLS(threading.local):
    pending_entry: Optional[CompileEntry] = None


_tls = _TLS()
_MONITOR_INSTALLED = False


def _install_monitor() -> None:
    """One process-wide ``jax.monitoring`` duration listener attributing
    backend-compile wall time to the entry whose trace most recently ran on
    this thread (the events fire inside the same dispatch, after the traced
    body returns). Attribution, not measurement — an uninstrumented jit
    compiling between an instrumented trace and its backend compile would
    mis-attribute; in this codebase every kernel entry point is
    instrumented, so the window is negligible."""
    global _MONITOR_INSTALLED
    if _MONITOR_INSTALLED:
        return
    _MONITOR_INSTALLED = True
    try:
        import jax.monitoring as _mon

        def listener(name, dur, **_kw):
            if name.endswith("backend_compile_duration"):
                entry = _tls.pending_entry
                if entry is not None:
                    entry.backend_compile_ms += dur * 1e3
                    _tls.pending_entry = None

        _mon.register_event_duration_secs_listener(listener)
    except Exception:
        pass  # monitoring API absent: backend_compile_ms stays 0


class CompileRegistry:
    """Process-global ledger of every instrumented jit entry point plus the
    recompile-sentinel state. Hot-path contract: the only mutation path is
    :meth:`_on_traced`, which jax invokes exclusively at trace (= compile)
    time — a warmed pipeline never enters it."""

    def __init__(self):
        self.entries: Dict[str, CompileEntry] = {}
        self._lock = threading.Lock()
        #: sentinel state (driver begin_run/mark_warm/end_run; tests and
        #: bench harnesses drive the same API)
        self.warm = False
        self.warm_reason: Optional[str] = None
        self.warm_at_ms: Optional[int] = None
        self.strict = False
        #: compiles since begin_run() / since begin_run's mark_warm()
        self.run_compiles = 0
        self.run_recompiles = 0
        self.total_compiles = 0

    # ------------------------------ feeding --------------------------- #

    def register(self, fun, jit_kwargs: dict) -> CompileEntry:
        name = getattr(fun, "__qualname__", getattr(fun, "__name__", "?"))
        module = getattr(fun, "__module__", "?")
        entry = CompileEntry(name, module, jit_kwargs)
        with self._lock:
            self.entries[f"{module}.{name}"] = entry
        _install_monitor()
        return entry

    def _on_traced(self, entry: CompileEntry, args, kwargs,
                   dt_s: float) -> None:
        """One fresh trace (= one XLA compile) of ``entry``. Runs only at
        compile time; the sentinel turns it into a recompile event after
        warmup and aborts under strict mode."""
        now_ms = int(time.time() * 1000)
        sig = _signature(args, kwargs)
        import jax

        with self._lock:
            entry.compiles += 1
            entry.trace_ms += dt_s * 1e3
            entry.signatures.append({"ts_ms": now_ms, "signature": sig,
                                     "post_warmup": self.warm})
            if entry.first_compile_ms is None:
                entry.first_compile_ms = now_ms
            entry.last_compile_ms = now_ms
            entry._lower_call = jax.tree_util.tree_map(
                _lower_leaf, (args, kwargs))
            entry._cost = None  # fresh signature: re-analyze on demand
            entry._cost_error = None
            self.total_compiles += 1
            self.run_compiles += 1
            warm, strict = self.warm, self.strict
            if warm:
                entry.recompiles += 1
                self.run_recompiles += 1
        _metrics.REGISTRY.counter("device-compiles").inc()
        _tls.pending_entry = entry
        if warm:
            _metrics.REGISTRY.counter("device-recompiles").inc()
            from spatialflink_tpu.utils.telemetry import emit_event

            emit_event("recompile", fn=entry.qualname, signature=sig,
                       warm_reason=self.warm_reason, strict=strict)
            if strict:
                raise RecompileError(
                    f"fresh XLA compile of {entry.qualname}{sig} after "
                    f"declared warmup ({self.warm_reason!r}) under "
                    "--strict-recompile; the zero-recompile contract is "
                    "violated — see GET /compile for the trigger signature")

    # ------------------------------ sentinel -------------------------- #

    def begin_run(self, strict: bool = False) -> None:
        """Start a sentinel run: warmup re-opens, run counters reset."""
        with self._lock:
            self.warm = False
            self.warm_reason = None
            self.warm_at_ms = None
            self.strict = bool(strict)
            self.run_compiles = 0
            self.run_recompiles = 0

    def mark_warm(self, reason: str) -> None:
        """Declare warmup done: from here every fresh compile is a
        ``recompile`` event (and an abort under strict mode)."""
        with self._lock:
            if not self.warm:
                self.warm = True
                self.warm_reason = reason
                self.warm_at_ms = int(time.time() * 1000)
        from spatialflink_tpu.utils.telemetry import emit_event

        emit_event("sentinel-warm", reason=reason)

    def end_run(self) -> None:
        """Close the sentinel run (driver exit stack): warm/strict reset so
        a later in-process run (tests, notebooks) starts cold."""
        with self._lock:
            self.warm = False
            self.warm_reason = None
            self.strict = False

    # ------------------------------ reading --------------------------- #

    def snapshot(self, cost: bool = False) -> dict:
        """The full ``GET /compile`` document."""
        with self._lock:
            entries = list(self.entries.values())
            head = {
                "ts_ms": int(time.time() * 1000),
                "functions": len(entries),
                "total_compiles": self.total_compiles,
                "run_compiles": self.run_compiles,
                "post_warmup_compiles": self.run_recompiles,
                "warm": self.warm,
                "warm_reason": self.warm_reason,
                "warm_at_ms": self.warm_at_ms,
                "strict": self.strict,
            }
        head["entries"] = sorted((e.to_dict(cost=cost) for e in entries),
                                 key=lambda d: (-d["compiles"], d["name"]))
        return head


_REGISTRY = CompileRegistry()


def registry() -> CompileRegistry:
    """The process's compile registry (module-global, like
    ``metrics.REGISTRY``)."""
    return _REGISTRY


def instrumented_jit(fun=None, **jit_kwargs):
    """Drop-in ``jax.jit`` replacement that registers the function in the
    compile registry and meters every fresh compile.

    Usable exactly like ``jax.jit``: bare decorator, or with kwargs via
    ``partial(instrumented_jit, static_argnames=(...))`` /
    ``instrumented_jit(fn, donate_argnums=(0,))``. Returns the real
    ``jax.jit`` object (``.lower``/``._cache_size`` intact): on a cache hit
    the dispatch is the unmodified C++ fast path; the registry hook lives
    inside the traced body, which jax executes only when compiling."""
    if fun is None:
        return lambda f: instrumented_jit(f, **jit_kwargs)
    import jax

    entry = _REGISTRY.register(fun, jit_kwargs)

    @functools.wraps(fun)
    def traced(*args, **kwargs):
        t0 = time.perf_counter()
        out = fun(*args, **kwargs)
        # hook AFTER the body so a strict-mode abort cannot leave a
        # half-traced cache entry blamed on the wrong signature; dt covers
        # the Python trace (backend compile time arrives via monitoring)
        _REGISTRY._on_traced(entry, args, kwargs, time.perf_counter() - t0)
        return out

    jitted = jax.jit(traced, **jit_kwargs)
    entry._jitted = jitted
    return jitted


# --------------------------------------------------------------------- #
# device-resource telemetry


_PROVENANCE: Optional[dict] = None


def backend_provenance(target: str = "tpu") -> dict:
    """Backend identity stamped into snapshots, bench rows, and checkpoint
    manifests: platform, device kind, chip count, and the
    ``valid_for_target`` verdict (the BENCH r05 failure mode — a silent CPU
    fallback — becomes a first-class field instead of ledger archaeology).
    Cached after the first probe: ``jax.devices()`` can block for seconds
    on a wedged accelerator tunnel."""
    global _PROVENANCE
    if _PROVENANCE is None:
        import jax

        devs = jax.devices()
        _PROVENANCE = {
            "platform": jax.default_backend(),
            "device_kind": devs[0].device_kind if devs else None,
            "device_count": jax.device_count(),
            "local_device_count": jax.local_device_count(),
            "process_index": jax.process_index(),
            "jax_version": jax.__version__,
        }
    out = dict(_PROVENANCE)
    out["target"] = target
    out["valid_for_target"] = out["platform"] == target
    return out


def device_memory() -> List[dict]:
    """Per-device live/peak memory rows from ``Device.memory_stats()``.
    CPU devices report no stats — the row says so explicitly
    (``available: False``) instead of faking zeros."""
    import jax

    rows = []
    for d in jax.local_devices():
        stats = None
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        if not stats:
            rows.append({"id": d.id, "kind": d.device_kind,
                         "available": False})
            continue
        rows.append({
            "id": d.id, "kind": d.device_kind, "available": True,
            "bytes_in_use": int(stats.get("bytes_in_use", 0)),
            "peak_bytes_in_use": int(stats.get("peak_bytes_in_use", 0)),
            "bytes_limit": int(stats.get("bytes_limit", 0)) or None,
        })
    return rows


def memory_gauges(rows: Optional[List[dict]] = None) -> dict:
    """Compact live/peak gauges over :func:`device_memory` rows: max
    bytes-in-use across devices (the health check's ``device_mem_bytes``
    value) and summed peak. None values when the backend exposes no
    stats."""
    rows = device_memory() if rows is None else rows
    avail = [r for r in rows if r.get("available")]
    if not avail:
        return {"available": False, "bytes_in_use": None,
                "peak_bytes_in_use": None}
    return {
        "available": True,
        "bytes_in_use": max(r["bytes_in_use"] for r in avail),
        "peak_bytes_in_use": sum(r["peak_bytes_in_use"] for r in avail),
    }


def _d2h_bytes(reg: Optional[_metrics.MetricsRegistry] = None) -> int:
    """Device→host bytes actually read back on the pane path — the
    always-on counters :class:`~spatialflink_tpu.operators.base.PanePartial`
    and the device pane merge maintain (the same numbers
    ``CostProfiles.bytes_moved`` folds in when a session is active)."""
    reg = reg if reg is not None else _metrics.REGISTRY
    return (reg.counter("pane-partial-readback-bytes").count
            + reg.counter("pane-merged-readback-bytes").count)


def status_block(tel=None, registry_=None) -> dict:
    """The compact ``device`` stanza every status snapshot carries (and the
    digest/bench rows read): backend provenance, sentinel counters, live
    memory gauges, and the d2h transfer bytes. Built on demand only —
    per snapshot/request, never per record."""
    reg = _REGISTRY
    mem = memory_gauges()
    return {
        "backend": backend_provenance(),
        "compiles": reg.total_compiles,
        "run_compiles": reg.run_compiles,
        "recompiles": reg.run_recompiles,
        "warm": reg.warm,
        "strict": reg.strict,
        "mem_available": mem["available"],
        "mem_bytes_in_use": mem["bytes_in_use"],
        "mem_peak_bytes": mem["peak_bytes_in_use"],
        "d2h_bytes": _d2h_bytes(registry_),
    }


def device_payload(tel=None) -> dict:
    """The full ``GET /device`` document: provenance, per-device memory,
    transfer accounting (d2h counters + per-family ``bytes_moved`` when a
    session is active), the dispatch-overlap distribution, the compile
    summary, and the flight-recorder state."""
    mem_rows = device_memory()
    reg = _REGISTRY
    out = {
        "ts_ms": int(time.time() * 1000),
        "backend": backend_provenance(),
        "memory": {"devices": mem_rows, **memory_gauges(mem_rows)},
        "transfer": {"d2h_bytes": _d2h_bytes()},
        "compile": {
            "functions": len(reg.entries),
            "total_compiles": reg.total_compiles,
            "post_warmup_compiles": reg.run_recompiles,
            "warm": reg.warm,
            "warm_reason": reg.warm_reason,
            "strict": reg.strict,
        },
    }
    if tel is not None:
        out["transfer"]["bytes_moved_by_family"] = {
            label: f.get("bytes_moved", 0)
            for label, f in tel.costs._families_dict().items()}
        h = tel.histograms.get("dispatch-overlap-ratio")
        out["dispatch_overlap"] = h.to_dict() if h is not None else {
            "count": 0}
    else:
        out["dispatch_overlap"] = {"count": 0}
    rec = active_recorder()
    out["recorder"] = ({"active": False} if rec is None else
                       {"active": True, "dir": rec.out_dir,
                        "dumps": rec.dumps, "notes": rec.total_notes})
    return out


# --------------------------------------------------------------------- #
# flight recorder


_ACTIVE_RECORDER: Optional["FlightRecorder"] = None


def active_recorder() -> Optional["FlightRecorder"]:
    return _ACTIVE_RECORDER


class FlightRecorder:
    """Bounded ring of run-lifecycle notes plus the post-mortem bundle
    dumper. The driver creates one under ``--postmortem-dir`` (which
    activates a telemetry session, so everything the bundle wants is being
    recorded); notes are appended at run/window/event granularity — never
    per record — and a dump renders one bundle directory:

    ========== ========================================================
    file        contents
    ========== ========================================================
    manifest    schema version, dump reason, timestamps, error, files
    status      the shared status snapshot (+ health verdict if --slo)
    compile     the full compile-registry snapshot (sentinel state)
    device      backend provenance + per-device memory + transfer
    events      the telemetry lifecycle event ring
    traces      recent window-trace summaries (+ full lineage, bounded)
    flight      this recorder's own note ring
    config      the run's config fingerprint (job id, argv, params)
    ========== ========================================================

    Triggers: pipeline crash (driver), SLO breach transition (health
    hook — one dump per run), strict-recompile abort, SIGUSR1, or an
    explicit :meth:`dump`. Bounded: at most ``max_dumps`` bundles per run
    so a crash loop cannot fill a disk."""

    def __init__(self, out_dir: str, config: Optional[dict] = None,
                 capacity: int = 512, max_dumps: int = 8):
        os.makedirs(out_dir, exist_ok=True)
        self.out_dir = out_dir
        self.config = config or {}
        self._ring: deque = deque(maxlen=max(1, int(capacity)))
        self._lock = threading.Lock()
        self.total_notes = 0
        self.dumps = 0
        self.max_dumps = int(max_dumps)
        self._dumped_reasons: set = set()
        self._old_handler = None
        self._signum = signal.SIGUSR1
        self._signal_installed = False
        global _ACTIVE_RECORDER
        _ACTIVE_RECORDER = self

    # ------------------------------ notes ----------------------------- #

    def note(self, kind: str, **fields) -> None:
        ev = {"ts_ms": int(time.time() * 1000), "kind": kind}
        ev.update(fields)
        with self._lock:
            self.total_notes += 1
            self._ring.append(ev)

    # ------------------------------ triggers -------------------------- #

    def install_signal(self, signum: int = signal.SIGUSR1) -> None:
        """SIGUSR1 → dump("signal") without exiting (kubectl-exec-able
        "what is it doing" capture). Main-thread only; silently skipped
        elsewhere (threaded test harnesses)."""
        try:
            old = signal.signal(signum, lambda s, f: self.dump("signal"))
            with self._lock:
                self._old_handler = old
                self._signum = signum
                self._signal_installed = True
        except ValueError:
            with self._lock:
                self._signal_installed = False

    def attach_health(self, health) -> None:
        """Hook the SLO evaluator's breach transitions: the FIRST breach of
        the run dumps a bundle (state at the moment the run went unhealthy
        — the timeline an operator wants after the fact)."""
        hooks = getattr(health, "hooks", None)
        if hooks is not None:
            hooks.append(self._on_breach)

    def _on_breach(self, check: str, value, threshold) -> None:
        self.note("slo-breach", check=check, value=value,
                  threshold=threshold)
        self.dump_once("slo-breach", "slo-breach",
                       detail={"check": check, "value": value,
                               "threshold": threshold})

    def dump_once(self, key: str, reason: str,
                  detail: Optional[dict] = None) -> Optional[str]:
        """:meth:`dump` at most once per ``key`` per run — the trigger
        discipline every breach-transition hook shares (global health,
        per-query SLO): an hour of flapping is one bundle, not a disk
        full. Returns the bundle directory on the first firing."""
        with self._lock:
            if key in self._dumped_reasons:
                return None
            self._dumped_reasons.add(key)
        return self.dump(reason, detail=detail)

    def close(self) -> None:
        global _ACTIVE_RECORDER
        with self._lock:
            restore = (self._old_handler
                       if self._signal_installed else None)
            signum = self._signum
            self._signal_installed = False
        if restore is not None:
            try:
                signal.signal(signum, restore)
            except ValueError:
                pass
        if _ACTIVE_RECORDER is self:
            _ACTIVE_RECORDER = None

    # ------------------------------ dumping --------------------------- #

    def dump(self, reason: str, error: Optional[BaseException] = None,
             detail: Optional[dict] = None) -> Optional[str]:
        """Write one post-mortem bundle; returns its directory (None when
        the per-run dump budget is exhausted). Best-effort per file — a
        torn telemetry read must not lose the rest of the bundle."""
        with self._lock:
            if self.dumps >= self.max_dumps:
                return None
            self.dumps += 1
            seq = self.dumps
        ts = time.strftime("%Y%m%dT%H%M%S")
        bundle = os.path.join(self.out_dir, f"bundle-{ts}-{seq:02d}-{reason}")
        os.makedirs(bundle, exist_ok=True)
        self.note("dump", reason=reason, bundle=bundle)

        from spatialflink_tpu.utils import telemetry as _telemetry

        tel = _telemetry.active()
        files: List[str] = []

        def write(name: str, build) -> None:
            try:
                payload = build()
            except Exception as e:
                payload = {"error": f"{type(e).__name__}: {e}"}
            path = os.path.join(bundle, name + ".json")
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(payload, f, sort_keys=True, default=repr)
            os.replace(tmp, path)
            files.append(name + ".json")

        write("status", lambda: _telemetry.status_snapshot())
        write("compile", lambda: _REGISTRY.snapshot())
        write("device", lambda: device_payload(tel))
        write("events", lambda: {
            "events": tel.events.list() if tel is not None else [],
            "total": tel.events.total if tel is not None else 0})
        write("traces", lambda: {
            "recent": (tel.traces.recent(32)
                       if tel is not None and tel.traces is not None else []),
            "enabled": tel is not None and tel.traces is not None})
        write("latency", lambda: (
            tel.latency.payload(tel=tel) if tel is not None
            else {"stages": {}, "recent": [],
                  "note": "no telemetry session at dump time"}))
        write("tenants", lambda: (
            tel.tenants.payload() if tel is not None
            else {"tenants": {}, "n": 0,
                  "note": "no telemetry session at dump time"}))
        with self._lock:
            ring = list(self._ring)
        write("flight", lambda: {"notes": ring, "total": self.total_notes})
        write("config", lambda: self.config)
        manifest = {
            "schema": BUNDLE_SCHEMA,
            "reason": reason,
            "ts_ms": int(time.time() * 1000),
            "error": (f"{type(error).__name__}: {error}"
                      if error is not None else None),
            "detail": detail,
            "files": sorted(files),
        }
        tmp = os.path.join(bundle, "manifest.json.tmp")
        with open(tmp, "w") as f:
            json.dump(manifest, f, sort_keys=True)
        os.replace(tmp, os.path.join(bundle, "manifest.json"))
        _telemetry.emit_event("postmortem-dump", reason=reason,
                              bundle=bundle)
        return bundle
