"""Micro-bench regression GATE for the batched hot path.

The batched-everywhere rebuild (chunk-vectorized decode + ``assign_bulk``
window assignment + columnar window payloads) is a performance claim with
no flag guarding it — a regression back to per-record cost would be
silent. This harness measures the hot-path speedup RATIOS (batched vs the
seed scalar loop, same machine, same run — ratios are robust to machine
speed in a way absolute rec/s is not) and ``--check`` diffs them against
the checked-in conservative floors in ``GUARD_baseline.json`` via
``bench_diff`` (metric ``speedup``, >25% below a floor fails). Wired into
tier-1 by ``tests/test_bench_guard.py``, so this PR's wins can't rot
unnoticed.

Rows (identity field ``path``):

- ``window_assign``     chunked ``WindowAssembler.assemble`` vs per-record
                        ``add`` (assignment + seal sweep only)
- ``decode_columnar``   ``driver.decode_chunks`` native columnar CSV parse
                        vs the seed per-record ``parse_spatial`` loop
- ``windowed_pipeline`` windowed range end-to-end (decode -> windows ->
                        kernel -> selection) on the batched path vs the
                        same operator fed the scalar-decoded record stream
- ``skew_adaptive``     multi-query windowed range over a high-skew
                        clustered stream: skew-adaptive grid (hot-cell
                        split prefilter, repartition controller live) vs
                        the uniform grid — the ISSUE 9 win, gated so
                        skew-adaptivity regressions fail tier-1 like the
                        batched-path ratios (window-table identity
                        asserted in-run)
- ``query_plane``       a Q=8 standing-query fleet served through the
                        DYNAMIC registry path (one padded Q-axis dispatch
                        per window) vs Q dedicated single-query pipelines
                        re-reading the stream — the ISSUE 10 contract:
                        the control plane must preserve run_multi's
                        amortization (per-query identity asserted)
- ``controller_pareto`` the chunk governor's closed loop vs a fixed-chunk
                        sweep: the gated ratio is the Pareto composite
                        (min over fixed chunks of the better of the
                        throughput ratio and the p99 ratio) — >= 1 means
                        no fixed chunk dominates the governed run on both
                        axes (window-table identity asserted; the full
                        per-class frontier lives in bench_control.py)
- ``realtime_vectorized``  the rebuilt realtime mode (columnar
                        MicroBatcher through the batched drive loop) vs
                        the pre-rebuild scalar ``_micro_batches`` branch,
                        fire-table identity asserted

plus one LOWER-IS-BETTER row gated by a second ``bench_diff`` pass
(``--metric p99_ms --lower-is-better`` against the ``latency_rows``
ceilings in the same baseline file):

and one fleet overhead row gated by a third lower-is-better pass
(``--metric overhead_x`` against the ``fleet_rows`` ceiling):

- ``fleet_scaling``     absolute wall clock of a single-worker supervised
                        fleet (supervisor routing -> worker subprocess ->
                        exactly-once merge) at a PINNED record count —
                        the supervision machinery's cost ceiling (metric
                        ``wall_fleet1_s``); the overhead-vs-single-process
                        ratio and the N=2 scaling ratio ride along
- ``fleet_rescale``     absolute wall clock of an N=2 fleet that scales
                        out to N=4 mid-run at an epoch boundary
                        (``--fleet-rescale``), pinned record count,
                        merged digest asserted identical to a fixed-N=2
                        oracle in the same run — the fenced exactly-once
                        rescale's cost ceiling (carried under the shared
                        fleet metric key ``wall_fleet1_s``)
                        ungated (a one-host CPU box is spawn/routing-
                        dominated — BASELINE.md carries the honest
                        numbers) and merged-digest identity across
                        N=1/N=2 is asserted in-run

- ``latency_record_emit``  record→emit p99 (the latency plane's budget
                        chain) of a windowed range run at the DEFAULT
                        decode chunk, at a PINNED record count so the
                        workload is fixed; window-table identity vs the
                        uninstrumented run is asserted, and the ceiling
                        carries a 3x margin (absolute ms is machine-
                        sensitive in a way the speedup ratios are not)

and one tenant-ledger overhead row gated by a fourth lower-is-better
pass (``--metric overhead_vs_off_x`` against the ``tenant_rows``
ceiling):

- ``tenant_plane``      the per-dispatch tenant cost ledger on vs off
                        over the same two-tenant dynamic fleet replay
                        (pinned record count): the gated ratio is the
                        on/off wall, window-table identity AND the
                        ledger's conservation invariants asserted in-run

Usage:
    python benchmarks/bench_guard.py [--n N] [--out PATH]
    python benchmarks/bench_guard.py --check          # exit 1 on regression
    python benchmarks/bench_guard.py --write-baseline # refresh the floors
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

BASELINE_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "GUARD_baseline.json")
#: floors are written at measured/MARGIN so box-to-box variance does not
#: flap the gate; the 25% diff threshold sits on top
MARGIN = 2.0
#: per-row margin overrides: the skew-adaptive ratio sits closer to 1 than
#: the batched-vs-scalar ratios, so the default /2 floor would degenerate
#: to 1.0 and the gate could never catch a silently-broken prefilter
#: (ratio ~1.0); a tighter margin keeps the floor meaningfully above it
MARGIN_BY_PATH = {"skew_adaptive": 1.3}
#: the latency row's CEILING margin (lower-is-better: ceiling = measured x
#: margin) — generous because absolute milliseconds vary box to box where
#: the speedup ratios cancel machine speed out
LATENCY_MARGIN = 3.0
#: the fleet row's CEILING margin on absolute single-worker-fleet wall
#: seconds (lower-is-better, like the latency row: ceiling = measured x
#: margin) — worker process spawn and the supervisor's per-line routing
#: are machine-sensitive absolute costs, so the margin is generous
FLEET_MARGIN = 3.0
#: the tenant row's CEILING margin on the ledger-on/ledger-off wall
#: ratio (lower-is-better): the measured overhead sits near 1.0, so the
#: ceiling multiplies a ratio, not an absolute, and stays tight enough
#: that a ledger regressed to per-record cost fails the gate
TENANT_MARGIN = 1.5


def _lines(n: int):
    rng = np.random.default_rng(0)
    t0 = 1_700_000_000_000
    ts = t0 + (np.arange(n) * 100_000 // max(n, 1))  # 100 s span
    return [f"v{int(i) % 97},{int(t)},"
            f"{115.5 + rng.random() * 2:.6f},{39.6 + rng.random() * 1.5:.6f}"
            for i, t in enumerate(ts)]


def _cfg():
    from spatialflink_tpu.config import StreamConfig

    return StreamConfig(format="CSV", date_format=None,
                        csv_tsv_schema=[0, 1, 2, 3])


def _grid():
    from spatialflink_tpu.index import UniformGrid

    return UniformGrid(115.5, 117.6, 39.6, 41.1, num_grid_partitions=100)


def _scalar_decode(lines, cfg, grid):
    """The SEED per-record decoder (parse_spatial per record) — kept here
    and in tests/oracles.py as the reference the ratios divide by."""
    from spatialflink_tpu.streams.formats import parse_spatial

    return [parse_spatial(ln, cfg.format, grid, delimiter=cfg.delimiter,
                          schema=cfg.csv_tsv_schema, geometry="Point")
            for ln in lines]


def bench_window_assign(n: int) -> dict:
    import types

    from spatialflink_tpu.runtime.windows import WindowAssembler, WindowSpec

    rng = np.random.default_rng(0)
    ts = (1_700_000_000_000 + np.sort(rng.integers(0, 100_000, n))).tolist()
    recs = [types.SimpleNamespace(timestamp=t) for t in ts]
    spec = WindowSpec.sliding(40_000, 5_000)  # overlap 8

    def per_record():
        wa = WindowAssembler(spec)
        out = []
        for r in recs:
            out += [(s, e, len(rr)) for s, e, rr in wa.add(r.timestamp, r)]
        out += [(s, e, len(rr)) for s, e, rr in wa.flush()]
        return out

    def chunked():
        wa = WindowAssembler(spec)
        return [(s, e, len(rr)) for s, e, rr in wa.assemble(iter(recs))]

    per_record(), chunked()  # warm
    t0 = time.perf_counter()
    ref = per_record()
    dt_rec = time.perf_counter() - t0
    t0 = time.perf_counter()
    fast = chunked()
    dt_chunk = time.perf_counter() - t0
    assert fast == ref, "chunked assignment diverged from per-record add"
    return dict(path="window_assign", records=n,
                speedup=round(dt_rec / dt_chunk, 2))


def bench_decode_columnar(n: int) -> dict:
    from spatialflink_tpu import driver

    lines = _lines(n)
    cfg, grid = _cfg(), _grid()

    def batched():
        return sum(len(c) for c in driver.decode_chunks(iter(lines), cfg,
                                                        grid))

    batched()
    _scalar_decode(lines[:2048], cfg, grid)  # warm both import paths
    t0 = time.perf_counter()
    total = batched()
    dt_b = time.perf_counter() - t0
    assert total == n
    t0 = time.perf_counter()
    objs = _scalar_decode(lines, cfg, grid)
    dt_s = time.perf_counter() - t0
    assert len(objs) == n
    return dict(path="decode_columnar", records=n,
                speedup=round(dt_s / dt_b, 2))


def bench_windowed_pipeline(n: int) -> dict:
    from spatialflink_tpu import driver
    from spatialflink_tpu.models import Point
    from spatialflink_tpu.operators import (PointPointRangeQuery,
                                            QueryConfiguration, QueryType)

    lines = _lines(n)
    cfg, grid = _cfg(), _grid()
    conf = QueryConfiguration(QueryType.WindowBased, 10_000, 5_000)
    qp = Point.create(116.5, 40.3, grid, obj_id="q")
    scalar_objs = _scalar_decode(lines, cfg, grid)

    def run_batched():
        op = PointPointRangeQuery(conf, grid)
        stream = driver.decode_stream(iter(lines), cfg, grid)
        return [(r.window_start, len(r.records))
                for r in op.run(stream, qp, 0.5)]

    def run_scalar():
        op = PointPointRangeQuery(conf, grid)
        return [(r.window_start, len(r.records))
                for r in op.run(iter(scalar_objs), qp, 0.5)]

    run_batched(), run_scalar()  # warm jit shapes both paths share
    t0 = time.perf_counter()
    tb = run_batched()
    dt_b = time.perf_counter() - t0
    t0 = time.perf_counter()
    ts_ = run_scalar()
    dt_s = time.perf_counter() - t0
    assert tb == ts_, "batched pipeline window table diverged"
    # the scalar side here ALREADY skips the per-record parse (pre-decoded
    # objects), so the ratio under-counts the full win — a conservative
    # guard by construction
    dt_s += 0.0
    return dict(path="windowed_pipeline", records=n,
                speedup=round((dt_s) / dt_b, 2))


def bench_skew_adaptive(n: int) -> dict:
    """Adaptive-vs-uniform grid ratio on the skewed clustered stream — the
    compact tier-1 form of ``bench_skew.py``'s high-skew row: a
    standing-query fleet (Q=128, eight hotspot monitors) over a 95%-hot
    clustered stream, repartition controller live, identity asserted."""
    import dataclasses

    import numpy as np

    from spatialflink_tpu import driver
    from spatialflink_tpu.index import AdaptiveGrid
    from spatialflink_tpu.models import Point
    from spatialflink_tpu.operators import (PointPointRangeQuery,
                                            QueryConfiguration, QueryType)
    from spatialflink_tpu.runtime.repartition import RepartitionController
    from spatialflink_tpu.streams.synthetic import clustered_lines

    # the ratio needs enough windows for the kernel share to dominate the
    # jit-warm/decode fraction: pin the row's own record count so a small
    # --n (the tier-1 run) cannot wash the gate out
    n = max(n, 120_000)
    cfg, grid = _cfg(), _grid()
    lines = clustered_lines(grid, n, 0.95, seed=7, fmt="csv", dt_ms=1)
    rng = np.random.default_rng(101)
    q = 128  # the Q-axis serving fleet bench_skew.py sweeps; at small Q the
    # kernel no longer dominates and the ratio loses its gating power
    xs = rng.uniform(grid.min_x, grid.max_x, q)
    ys = rng.uniform(grid.min_y, grid.max_y, q)
    hx = (grid.min_x + grid.max_x) / 2 + grid.cell_length / 3
    hy = (grid.min_y + grid.max_y) / 2 + grid.cell_length / 3
    span = 2.0 * grid.cell_length
    xs[:8] = hx + rng.uniform(-span / 2, span / 2, 8)
    ys[:8] = hy + rng.uniform(-span / 2, span / 2, 8)
    qpts = [Point.create(float(x), float(y), grid)
            for x, y in zip(xs, ys)]
    conf = QueryConfiguration(QueryType.WindowBased, 40_000, 5_000)

    def run(adaptive: bool):
        c, ctl = conf, None
        if adaptive:
            ag = AdaptiveGrid(grid, refine=8)
            c = dataclasses.replace(conf, adaptive_grid=ag)
            ctl = RepartitionController(ag,
                                        interval_records=max(1000, n // 8))
        op = PointPointRangeQuery(c, grid)
        stream = driver.decode_stream(iter(lines), cfg, grid)
        if ctl is not None:
            ctl.install()
        try:
            t0 = time.perf_counter()
            out = [(w.window_start, tuple(len(r) for r in w.records))
                   for w in op.run_multi(stream, qpts, 0.002)]
            dt = time.perf_counter() - t0
        finally:
            if ctl is not None:
                ctl.uninstall()
        return out, dt

    run(False), run(True)  # warm jit shapes + the adapted layouts
    ref, dt_u = run(False)
    got, dt_a = run(True)
    assert got == ref, "adaptive window table diverged from uniform"
    return dict(path="skew_adaptive", records=n,
                speedup=round(dt_u / dt_a, 2))


def bench_query_plane(n: int) -> dict:
    """Standing-query control plane ratio: a Q=8 DYNAMIC fleet served
    through the registry path (one padded Q-axis dispatch per window,
    admissions applied at window boundaries) vs Q dedicated single-query
    pipelines re-reading the stream — the reference's one-Flink-job-per-
    query shape. The registry path must preserve run_multi's amortization
    ON TOP of its lifecycle machinery; per-query window-table identity is
    asserted so a silently-wrong demux can never pass the gate."""
    from spatialflink_tpu import driver
    from spatialflink_tpu.models import Point
    from spatialflink_tpu.operators import (PointPointRangeQuery,
                                            QueryConfiguration, QueryType)
    from spatialflink_tpu.runtime.queryplane import QueryRegistry

    lines = _lines(n)
    cfg, grid = _cfg(), _grid()
    conf = QueryConfiguration(QueryType.WindowBased, 10_000, 5_000)
    rng = np.random.default_rng(3)
    q = 8
    pts = [(115.5 + rng.random() * 2, 39.6 + rng.random() * 1.5)
           for _ in range(q)]

    def registry():
        reg = QueryRegistry("range", radius=0.5)
        for i, (x, y) in enumerate(pts):
            reg.admit({"id": f"q{i}", "x": x, "y": y})
        reg.apply()
        return reg

    def run_dynamic():
        op = PointPointRangeQuery(conf, grid)
        stream = driver.decode_stream(iter(lines), cfg, grid)
        return [(w.window_start, tuple(len(r) for r in w.records))
                for w in op.run_dynamic(stream, registry(), 0.5)]

    def run_dedicated():
        out = []
        for x, y in pts:
            op = PointPointRangeQuery(conf, grid)
            stream = driver.decode_stream(iter(lines), cfg, grid)
            out.append([(w.window_start, len(w.records))
                        for w in op.run(stream,
                                        Point.create(x, y, grid), 0.5)])
        return out

    run_dynamic(), run_dedicated()  # warm jit shapes on both sides
    t0 = time.perf_counter()
    dyn = run_dynamic()
    dt_d = time.perf_counter() - t0
    t0 = time.perf_counter()
    ded = run_dedicated()
    dt_s = time.perf_counter() - t0
    for i in range(q):
        assert [(ws, c[i]) for ws, c in dyn] == ded[i], \
            f"dynamic fleet query {i} diverged from its dedicated run"

    # recompile-sentinel gate over the PR 9 churn acceptance shape (ISSUE
    # 12): a Q=32 fleet with ONE admit + ONE retire per emitted window —
    # every change repads within the same power-of-two bucket, so after
    # the warmup pass the sentinel must record 0 post-warmup XLA compiles
    from spatialflink_tpu.utils import deviceplane

    q32 = 32
    pts32 = [(115.5 + rng.random() * 2, 39.6 + rng.random() * 1.5)
             for _ in range(q32)]

    def run_churn():
        reg = QueryRegistry("range", radius=0.5)
        for i, (x, y) in enumerate(pts32):
            reg.admit({"id": f"q{i}", "x": x, "y": y})
        reg.apply()
        op = PointPointRangeQuery(conf, grid)
        stream = driver.decode_stream(iter(lines), cfg, grid)
        i = 0
        for _w in op.run_dynamic(stream, reg, 0.5):
            reg.admit({"id": f"churn{i}", "x": 115.5 + (i % 10) * 0.1,
                       "y": 39.6 + (i % 10) * 0.1})
            reg.retire([e.id for e in reg.active_entries()][0])
            i += 1

    run_churn()  # warm the Q=32 bucket's shapes
    dp = deviceplane.registry()
    dp.begin_run()
    dp.mark_warm("bench_guard query-plane churn (shapes pre-warmed)")
    try:
        run_churn()
        post_warm = dp.run_recompiles
    finally:
        dp.end_run()
    assert post_warm == 0, (
        f"recompile sentinel fired {post_warm}x across the Q={q32} "
        "admit/retire-per-window churn run — in-bucket repadding must "
        "never recompile (the PR 9 contract, now device-truth-asserted)")
    return dict(path="query_plane", records=n, queries=q,
                speedup=round(dt_s / dt_d, 2),
                churn_post_warmup_compiles=post_warm)


def bench_controller_pareto(n: int) -> dict:
    """Closed-loop governor Pareto gate (ISSUE 18): the GOVERNED windowed
    range run (decode chunk driven live by the ChunkGovernor off the
    latency plane's buckets) against a FIXED-chunk sweep of the same
    pipeline. The gated ``speedup`` is the Pareto composite

        min over fixed chunks c of max(gov_rps / rps_c, p99_c / gov_p99)

    — >= 1 means no fixed chunk dominates the governor on BOTH axes
    (throughput and record→emit p99), the bench bar's "meet or beat every
    fixed size on the frontier" stated as one machine-robust ratio (each
    axis covers the other's noise; per-axis p99 over ~21 windows flaps).
    Window-table identity across every fixed chunk AND the governed run
    is asserted, so a governor that bought its numbers by changing
    results can never pass. ``benchmarks/bench_control.py`` carries the
    full per-latency-class frontier incl. --chaos; this row is its
    tier-1 sentinel."""
    from spatialflink_tpu import driver
    from spatialflink_tpu.models import Point
    from spatialflink_tpu.operators import (PointPointRangeQuery,
                                            QueryConfiguration, QueryType)
    from spatialflink_tpu.runtime.control import ChunkGovernor
    from spatialflink_tpu.utils.telemetry import telemetry_session

    lines = _lines(n)
    cfg, grid = _cfg(), _grid()
    conf = QueryConfiguration(QueryType.WindowBased, 10_000, 5_000)
    qp = Point.create(116.5, 40.3, grid, obj_id="q")

    def ticking(tel):
        # the reporter thread normally closes buckets; a replay bench
        # drives the same maybe_tick from the feed (time-gated, so the
        # cadence is the plane's tick_interval_s, not the loop count)
        for i in range(0, len(lines), 256):
            yield from lines[i:i + 256]
            tel.latency.maybe_tick(tel)

    def run(chunk, gov=None):
        with telemetry_session() as tel:
            tel.latency.tick_interval_s = 0.05
            if gov is not None:
                gov.install()
            try:
                op = PointPointRangeQuery(conf, grid)
                s = driver.decode_stream(ticking(tel), cfg, grid,
                                         chunk=chunk)
                t0 = time.perf_counter()
                table = [(r.window_start, len(r.records))
                         for r in op.run(s, qp, 0.5)]
                wall = time.perf_counter() - t0
                p99 = tel.latency.record_emit.percentile(99)
            finally:
                if gov is not None:
                    gov.uninstall()
        return table, n / wall, p99

    run(4096)  # warm
    ref = None
    fixed = {}
    for c in (512, 2048, 8192):
        table, rps, p99 = run(c)
        if ref is None:
            ref = table
        assert table == ref, f"fixed chunk {c} changed the window table"
        fixed[c] = (rps, p99)
    gov = ChunkGovernor()
    table, gov_rps, gov_p99 = run(gov.chunk_callback(), gov)
    assert table == ref, "governed run changed the window table"
    st = gov.status()
    score = min(max(gov_rps / rps, p99 / gov_p99)
                for rps, p99 in fixed.values())
    return dict(path="controller_pareto", records=n,
                speedup=round(score, 2),
                gov_rps=int(gov_rps), gov_p99_ms=round(gov_p99, 3),
                gov_final_chunk=st["chunk"], gov_ticks=st["ticks"],
                gov_steps=st["grows"] + st["shrinks"],
                fixed={str(c): dict(rps=int(r), p99_ms=round(p, 3))
                       for c, (r, p) in fixed.items()})


def bench_realtime_vectorized(n: int) -> dict:
    """Realtime-on-the-vectorized-path gate (ISSUE 18): throughput of the
    rebuilt realtime mode (tumbling count micro-windows cut by the
    columnar MicroBatcher, driven through the batched pipeline) vs the
    pre-rebuild scalar branch — per-record flatten into ``_micro_batches``
    feeding the same drive loop (kept in-tree as the trajectory-family
    helper, so the oracle is the actual old code, not a reconstruction).
    Fire-table identity is asserted: same bounds, same selections."""
    from spatialflink_tpu import driver
    from spatialflink_tpu.models import Point
    from spatialflink_tpu.operators import (PointPointRangeQuery,
                                            QueryConfiguration, QueryType)

    lines = _lines(n)
    cfg, grid = _cfg(), _grid()
    conf = QueryConfiguration(QueryType.RealTime, realtime_batch_size=512)
    qp = Point.create(116.5, 40.3, grid, obj_id="q")

    def run_new():
        op = PointPointRangeQuery(conf, grid)
        s = driver.decode_stream(iter(lines), cfg, grid)
        return [(r.window_start, r.window_end, len(r.records))
                for r in op.run(s, qp, 0.5)]

    def run_scalar():
        op = PointPointRangeQuery(conf, grid)
        stream = iter(driver.decode_stream(iter(lines), cfg, grid))
        batched = ((r[0].timestamp, r[-1].timestamp, r)
                   for r in op._micro_batches(stream) if r)
        mask_cache = op._leaf_mask_cache(
            lambda: op.conf.adaptive_grid.neighboring_leaf_mask(
                0.5, qp.cell, point=(qp.x, qp.y)))
        return [(r.window_start, r.window_end, len(r.records))
                for r in op._drive_batched(
                    batched,
                    lambda recs, tsb: op._eval(recs, qp, 0.5, tsb,
                                               mask_cache),
                    realtime=True)]

    run_new(), run_scalar()  # warm both paths' jit shapes
    t0 = time.perf_counter()
    new = run_new()
    dt_new = time.perf_counter() - t0
    t0 = time.perf_counter()
    old = run_scalar()
    dt_old = time.perf_counter() - t0
    assert new == old, "vectorized realtime diverged from the scalar oracle"
    return dict(path="realtime_vectorized", records=n, fires=len(new),
                speedup=round(dt_old / dt_new, 2))


def bench_latency_record_emit(n: int) -> dict:
    """Record→emit p99 (ms) through the latency-decomposition plane on a
    windowed range replay at the DEFAULT decode chunk — the tier-1 gate on
    the record-to-emission hot path (a regression here is a latency-tier
    regression even when throughput holds). The record count is PINNED so
    the absolute-ms ceiling compares a fixed workload; the sum invariant
    and window-table identity vs the uninstrumented run are asserted so a
    silently-miswired budget chain can never pass."""
    from spatialflink_tpu import driver
    from spatialflink_tpu.models import Point
    from spatialflink_tpu.operators import (PointPointRangeQuery,
                                            QueryConfiguration, QueryType)
    from spatialflink_tpu.utils.telemetry import telemetry_session

    n = 60_000  # pinned: an absolute-ms ceiling needs a fixed workload
    lines = _lines(n)
    cfg, grid = _cfg(), _grid()
    conf = QueryConfiguration(QueryType.WindowBased, 10_000, 5_000)
    qp = Point.create(116.5, 40.3, grid, obj_id="q")

    def run():
        op = PointPointRangeQuery(conf, grid)
        stream = driver.decode_stream(iter(lines), cfg, grid)
        return [(r.window_start, len(r.records))
                for r in op.run(stream, qp, 0.5)]

    run()  # warm
    ref = run()  # uninstrumented reference (identity)
    with telemetry_session() as tel:
        got = run()
        plane = tel.latency
        p99 = plane.record_emit.percentile(99)
        assert plane.record_emit.count == len(got) > 0
        assert plane.max_residual_ms < 1.0, (
            "stage budget no longer sums to record→emit "
            f"(max residual {plane.max_residual_ms} ms)")
    assert got == ref, "instrumented run diverged from uninstrumented"
    return dict(path="latency_record_emit", records=n,
                p99_ms=round(p99, 3))


def bench_fleet_scaling(n: int) -> dict:
    """Supervised-fleet overhead gate (lower-is-better): wall clock of a
    single-worker fleet (supervisor routing -> worker subprocess ->
    exactly-once global merge) over the SAME replay run single-process —
    the price of the supervision machinery, which must stay bounded. The
    N=2 scaling ratio rides along informationally: spatial partitioning
    on a one-host CPU box is spawn/routing-dominated at this scale, so it
    is NOT gated (BASELINE.md carries the honest numbers). Merged-digest
    identity across N=1 and N=2 — the exactly-once contract — is asserted
    in the same run.

    The GATED metric is the absolute single-worker-fleet wall
    (``wall_fleet1_s``) at the pinned record count, against a generous
    x3 ceiling: the overhead-vs-single-process ratio divides by a
    sub-second batched run and would flap on denominator noise."""
    import contextlib
    import io
    import shutil

    from spatialflink_tpu.driver import main as driver_main
    from spatialflink_tpu.runtime import fleet as fleet_mod
    from spatialflink_tpu.streams.synthetic import clustered_lines

    n = 30_000  # pinned: the overhead ratio mixes fixed (spawn) and
    # per-record (routing) cost, so the ceiling needs a fixed workload
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    conf = os.path.join(root, "conf", "spatialflink-conf.yml")
    lines = clustered_lines(_grid(), n, 0.95, seed=7, fmt="geojson",
                            dt_ms=1)
    td = tempfile.mkdtemp(prefix="bench-fleet-")
    # workers are fresh processes: without a persistent compile cache the
    # warm runs below could not actually warm the measured ones
    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                          os.path.join(td, "xla-cache"))
    os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0")
    try:
        path1 = os.path.join(td, "in.geojson")
        with open(path1, "w") as f:
            f.write("\n".join(lines) + "\n")

        def solo():
            t0 = time.perf_counter()
            with contextlib.redirect_stdout(io.StringIO()):
                rc = driver_main(["--config", conf, "--option", "1",
                                  "--input1", path1])
            dt = time.perf_counter() - t0
            assert rc == 0
            return dt

        def fleet(workers, tag):
            fdir = os.path.join(td, f"fleet-{tag}")
            t0 = time.perf_counter()
            with contextlib.redirect_stdout(sys.stderr):
                rc = driver_main([
                    "--config", conf, "--option", "1", "--input1", path1,
                    "--fleet", str(workers), "--fleet-dir", fdir,
                    # no mid-run rebalance: a shape change would compile
                    # inside the timed region
                    "--fleet-epoch-records", str(10**9)])
            dt = time.perf_counter() - t0
            assert rc == 0
            res = fleet_mod.read_json(os.path.join(fdir,
                                                   fleet_mod.RESULT_FILE))
            return res, dt

        solo()          # warm the in-process jit shapes
        fleet(1, "w1")  # warm the workers' persistent cache: full-window
        fleet(2, "w2")  # and split-window padding buckets compile here
        dt_solo = solo()
        r1, dt_f1 = fleet(1, "n1")
        r2, dt_f2 = fleet(2, "n2")
        assert r1["digest"] == r2["digest"], \
            "fleet merged digest diverged between N=1 and N=2 workers"
        assert r1["merged_windows"] > 0
        return dict(path="fleet_scaling", records=n, workers=2,
                    merged_windows=r1["merged_windows"],
                    wall_solo_s=round(dt_solo, 3),
                    wall_fleet1_s=round(dt_f1, 3),
                    wall_fleet2_s=round(dt_f2, 3),
                    scaling_n2=round(dt_f1 / dt_f2, 2),
                    overhead_x=round(dt_f1 / dt_solo, 2))
    finally:
        shutil.rmtree(td, ignore_errors=True)


def bench_fleet_rescale(n: int) -> dict:
    """Live-rescale cost gate (lower-is-better): wall clock of an N=2
    fleet that scales OUT to N=4 mid-run at an epoch boundary
    (``--fleet-rescale``), at a pinned record count. Merged-digest
    identity against a fixed-N=2 oracle run of the same replay is
    asserted in the same run — the fenced exactly-once rescale contract:
    a live worker-set change must be invisible to the merged output.

    The GATED metric is the rescaling run's absolute wall, carried under
    ``wall_fleet1_s`` so the shared fleet diff pass (lower-is-better,
    ``--require-all``) pairs every fleet row on one metric key; the
    rescale-vs-fixed ratio rides along informationally."""
    import contextlib
    import shutil

    from spatialflink_tpu.driver import main as driver_main
    from spatialflink_tpu.runtime import fleet as fleet_mod
    from spatialflink_tpu.streams.synthetic import clustered_lines

    n = 12_000  # pinned: spawn cost (two extra workers mid-run) is fixed,
    # routing cost is per-record — the ceiling needs a fixed workload
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    conf = os.path.join(root, "conf", "spatialflink-conf.yml")
    lines = clustered_lines(_grid(), n, 0.95, seed=7, fmt="geojson",
                            dt_ms=1)
    td = tempfile.mkdtemp(prefix="bench-rescale-")
    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                          os.path.join(td, "xla-cache"))
    os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0")
    try:
        path1 = os.path.join(td, "in.geojson")
        with open(path1, "w") as f:
            f.write("\n".join(lines) + "\n")

        def fleet(tag, *extra):
            fdir = os.path.join(td, f"fleet-{tag}")
            t0 = time.perf_counter()
            with contextlib.redirect_stdout(sys.stderr):
                rc = driver_main([
                    "--config", conf, "--option", "1", "--input1", path1,
                    "--fleet", "2", "--fleet-dir", fdir,
                    "--fleet-epoch-records", str(10**9)] + list(extra))
            dt = time.perf_counter() - t0
            assert rc == 0
            res = fleet_mod.read_json(os.path.join(fdir,
                                                   fleet_mod.RESULT_FILE))
            return res, dt

        rescale_argv = ["--fleet-rescale", f"{n // 3}:4",
                        "--fleet-epoch-records", str(n // 6)]
        fleet("warm", *rescale_argv)  # fills the persistent compile
        # cache for BOTH worker-set shapes (N=2 and the post-rescale N=4)
        r_fix, dt_fix = fleet("n2")
        r_rs, dt_rs = fleet("rs", *rescale_argv)
        assert r_rs["digest"] == r_fix["digest"], \
            "fleet merged digest diverged across a live N=2->4 rescale"
        assert r_rs.get("workers_final") == 4, r_rs.get("workers_final")
        assert [(r["n_from"], r["n_to"])
                for r in r_rs.get("rescales", [])] == [(2, 4)]
        assert r_fix["merged_windows"] > 0
        return dict(path="fleet_rescale", records=n, workers=2,
                    workers_final=4,
                    merged_windows=r_rs["merged_windows"],
                    wall_fleet2_fixed_s=round(dt_fix, 3),
                    wall_fleet1_s=round(dt_rs, 3),
                    rescale_x=round(dt_rs / dt_fix, 2),
                    post_warmup_compiles=r_rs["post_warmup_compiles"])
    finally:
        shutil.rmtree(td, ignore_errors=True)


def bench_tenant_plane(n: int) -> dict:
    """Tenant-ledger overhead gate (ISSUE 20, lower-is-better): the same
    two-tenant Q=8 dynamic registry fleet over the same replay with the
    per-dispatch cost ledger OFF (no telemetry session — the gated hot
    path) vs ON (telemetry session: ``note_dispatch`` + the proportional
    ``resolve`` split, host-side arithmetic on already-materialized
    masks). The GATED metric is the on/off wall ratio
    (``overhead_vs_off_x``) at a PINNED record count against a generous
    ceiling — attribution must stay bookkeeping-priced. Window-table
    identity and the ledger's own conservation invariants (every
    dispatch resolved, zero residual from the exact-split fold) are
    asserted in-run, so a ledger that got cheap by dropping spans or
    changing results can never pass."""
    from spatialflink_tpu import driver
    from spatialflink_tpu.operators import (PointPointRangeQuery,
                                            QueryConfiguration, QueryType)
    from spatialflink_tpu.runtime.queryplane import QueryRegistry
    from spatialflink_tpu.utils import telemetry as _telemetry
    from spatialflink_tpu.utils.telemetry import telemetry_session

    n = 60_000  # pinned: the overhead ratio mixes per-dispatch ledger
    # cost into a fixed windowed workload
    lines = _lines(n)
    cfg, grid = _cfg(), _grid()
    conf = QueryConfiguration(QueryType.WindowBased, 10_000, 5_000)
    rng = np.random.default_rng(11)
    q = 8
    pts = [(115.5 + rng.random() * 2, 39.6 + rng.random() * 1.5)
           for _ in range(q)]

    def run():
        reg = QueryRegistry("range", radius=0.5)
        for i, (x, y) in enumerate(pts):
            reg.admit({"id": f"q{i}", "x": x, "y": y,
                       "tenant": "acme" if i % 2 == 0 else "free"})
        reg.apply()
        op = PointPointRangeQuery(conf, grid)
        stream = driver.decode_stream(iter(lines), cfg, grid)
        t0 = time.perf_counter()
        table = [(w.window_start, tuple(len(r) for r in w.records))
                 for w in op.run_dynamic(stream, reg, 0.5)]
        return table, time.perf_counter() - t0

    run()  # warm the Q-bucket's jit shapes both configurations share
    assert _telemetry.active() is None
    table_off, dt_off = run()
    with telemetry_session() as tel:
        table_on, dt_on = run()
        ledger = tel.tenants.to_dict()
    assert table_on == table_off, (
        "tenant ledger changed the window table — attribution must be "
        "bookkeeping, not semantics")
    assert ledger["resolved"] > 0 and ledger["pending"] == 0
    assert ledger["late_resolves"] == 0
    assert ledger["max_residual_ms"] < 1e-6, ledger["max_residual_ms"]
    assert set(ledger["tenants"]) == {"acme", "free"}
    return dict(path="tenant_plane", records=n, queries=q,
                overhead_vs_off_x=round(dt_on / dt_off, 2),
                dispatches_resolved=ledger["resolved"],
                max_residual_ms=ledger["max_residual_ms"])


def measure(n: int) -> list:
    return [bench_window_assign(n), bench_decode_columnar(n),
            bench_windowed_pipeline(n), bench_skew_adaptive(n),
            bench_query_plane(n), bench_controller_pareto(n),
            bench_realtime_vectorized(n), bench_latency_record_emit(n),
            bench_fleet_scaling(n), bench_fleet_rescale(n),
            bench_tenant_plane(n)]


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=120_000)
    ap.add_argument("--out", default=None)
    ap.add_argument("--check", action="store_true",
                    help="diff the fresh ratios against GUARD_baseline.json "
                         "(bench_diff, metric=speedup, threshold 0.25); "
                         "exit 1 on regression")
    ap.add_argument("--write-baseline", action="store_true",
                    help="write measured/%.1f floors to GUARD_baseline.json"
                         % MARGIN)
    args = ap.parse_args()

    from benchmarks._common import settle_backend

    settle_backend()
    import jax

    backend = jax.default_backend()
    rows = measure(args.n)
    for r in rows:
        r["backend"] = backend
        print(json.dumps(r), flush=True)

    speed_rows = [r for r in rows if "speedup" in r]
    lat_rows = [r for r in rows if "p99_ms" in r]
    fleet_rows = [r for r in rows if "wall_fleet1_s" in r]
    tenant_rows = [r for r in rows if "overhead_vs_off_x" in r]

    if args.write_baseline:
        floors = [dict(path=r["path"],
                       speedup=round(max(
                           r["speedup"] / MARGIN_BY_PATH.get(r["path"],
                                                             MARGIN),
                           1.0), 2))
                  for r in speed_rows]
        ceilings = [dict(path=r["path"],
                         p99_ms=round(r["p99_ms"] * LATENCY_MARGIN, 1))
                    for r in lat_rows]
        fleet_ceilings = [dict(path=r["path"],
                               wall_fleet1_s=round(
                                   r["wall_fleet1_s"] * FLEET_MARGIN, 1))
                          for r in fleet_rows]
        tenant_ceilings = [dict(path=r["path"],
                                overhead_vs_off_x=round(
                                    max(r["overhead_vs_off_x"], 1.0)
                                    * TENANT_MARGIN, 2))
                           for r in tenant_rows]
        with open(BASELINE_PATH, "w") as f:
            json.dump({"metric": "speedup",
                       "note": "conservative floors = measured/%.1f "
                               "(skew_adaptive: /%.1f); bench_guard "
                               "--check trips >25%% below. latency_rows "
                               "are lower-is-better CEILINGS = measured x "
                               "%.1f (metric p99_ms); fleet_rows are "
                               "lower-is-better CEILINGS = measured x "
                               "%.1f (metric wall_fleet1_s: absolute "
                               "single-worker supervised-fleet wall at "
                               "the pinned record count); tenant_rows is "
                               "a lower-is-better CEILING = max(measured, "
                               "1.0) x %.1f (metric overhead_vs_off_x: "
                               "the tenant ledger's on/off wall ratio at "
                               "the pinned record count, identity + "
                               "conservation asserted in-run)"
                               % (MARGIN, MARGIN_BY_PATH["skew_adaptive"],
                                  LATENCY_MARGIN, FLEET_MARGIN,
                                  TENANT_MARGIN),
                       "rows": floors, "latency_rows": ceilings,
                       "fleet_rows": fleet_ceilings,
                       "tenant_rows": tenant_ceilings},
                      f, indent=1)
        print(f"# wrote {BASELINE_PATH}", file=sys.stderr)
        return 0

    if args.out:
        with open(args.out, "w") as f:
            json.dump({"backend": backend, "rows": rows}, f, indent=1)

    if args.check:
        from benchmarks.bench_diff import main as diff_main

        def run_diff(base_rows, fresh_rows, metric, extra):
            base_f = tempfile.NamedTemporaryFile("w", suffix=".json",
                                                 delete=False)
            fresh_f = tempfile.NamedTemporaryFile("w", suffix=".json",
                                                  delete=False)
            try:
                # identity = path only (the floors are scale/backend-
                # agnostic; keeping records/backend in the key would
                # unpair rows)
                json.dump({"rows": base_rows}, base_f)
                base_f.close()
                json.dump({"rows": [dict(path=r["path"],
                                         **{metric: r[metric]})
                                    for r in fresh_rows]}, fresh_f)
                fresh_f.close()
                return diff_main([base_f.name, fresh_f.name,
                                  "--metric", metric,
                                  "--threshold", "0.25",
                                  "--require-all"] + extra)
            finally:
                os.unlink(base_f.name)
                os.unlink(fresh_f.name)

        base = json.load(open(BASELINE_PATH))
        rc = run_diff(base.get("rows", []), speed_rows, "speedup", [])
        # second pass: the latency ceiling, lower-is-better (the worked
        # example in bench_diff's docs)
        rc_lat = run_diff(base.get("latency_rows", []), lat_rows,
                          "p99_ms", ["--lower-is-better"])
        # third pass: the fleet supervision-cost ceiling, also
        # lower-is-better (metric wall_fleet1_s)
        rc_fleet = run_diff(base.get("fleet_rows", []), fleet_rows,
                            "wall_fleet1_s", ["--lower-is-better"])
        # fourth pass: the tenant-ledger overhead ceiling (lower-is-
        # better ratio — the accounting plane must stay bookkeeping-
        # priced on the dispatch hot path)
        rc_tenant = run_diff(base.get("tenant_rows", []), tenant_rows,
                             "overhead_vs_off_x", ["--lower-is-better"])
        return rc or rc_lat or rc_fleet or rc_tenant
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
