"""CLI for the invariant linter.

``python -m spatialflink_tpu.analysis [--rule ID]...
[--format text|json|sarif] [--check] [--root DIR] [--allowlist FILE]
[--no-cache] [--list-rules]``

Exit codes: 0 clean (or report-only mode), 1 non-allowlisted findings or
stale allowlist entries / stale pragmas under ``--check``, 2
usage/configuration errors (unknown rule, malformed allowlist).

``--format sarif`` emits SARIF 2.1.0 so CI viewers render findings as
code annotations; suppressed findings ride along with their
``suppressions`` field filled (``inSource`` for pragmas, ``external``
for allowlist entries).
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional

from spatialflink_tpu.analysis.core import (ALLOWLIST_PATH, REPO_ROOT,
                                            AllowlistError, Report,
                                            all_rules, run_analysis)

_SARIF_SCHEMA = ("https://raw.githubusercontent.com/oasis-tcs/sarif-spec/"
                 "master/Schemata/sarif-schema-2.1.0.json")


def _render_text(report, check: bool, out) -> None:
    for f in report.findings:
        print(f.render(), file=out)
    for f, entry in report.suppressed:
        print(f"{f.render()}  [allowlisted: {entry.reason}]", file=out)
    for f, pragma in report.pragma_suppressed:
        print(f"{f.render()}  [pragma: {pragma.reason}]", file=out)
    for e in report.stale:
        print(f"stale allowlist entry — remove stale entry: {e.render()}",
              file=out)
    for p in report.stale_pragmas:
        print(f"stale pragma — remove stale pragma: {p.render()}",
              file=out)
    n_active = len(report.findings)
    n_supp = len(report.suppressed) + len(report.pragma_suppressed)
    n_stale = len(report.stale) + len(report.stale_pragmas)
    print(f"{n_active} finding(s), {n_supp} allowlisted, "
          f"{n_stale} stale suppression"
          f"{'' if n_stale == 1 else 's'} across "
          f"{report.files} file(s) [{', '.join(report.rules)}]", file=out)
    if check:
        print("check: " + ("PASS" if report.ok else "FAIL"), file=out)


def _sarif_result(f, suppression: Optional[dict] = None) -> dict:
    level = "error" if f.severity == "error" else "warning"
    result = {
        "ruleId": f.rule,
        "level": level,
        "message": {"text": f.message +
                    (f" [{f.symbol}]" if f.symbol else "")},
        "locations": [{
            "physicalLocation": {
                "artifactLocation": {"uri": f.path},
                "region": {"startLine": max(1, f.line),
                           "startColumn": max(1, f.col + 1)},
            },
        }],
    }
    if suppression is not None:
        result["suppressions"] = [suppression]
    return result


def render_sarif(report: Report) -> dict:
    """The findings as a SARIF 2.1.0 document (one run, one driver)."""
    known = {r.id: r for r in all_rules()}
    rules_meta = []
    for rid in report.rules:
        rule = known.get(rid)
        meta = {"id": rid}
        if rule is not None:
            meta["shortDescription"] = {"text": rule.contract}
            meta["defaultConfiguration"] = {
                "level": "error" if rule.severity == "error"
                else "warning"}
        rules_meta.append(meta)
    results = [_sarif_result(f) for f in report.findings]
    results += [_sarif_result(f, {"kind": "external",
                                  "justification": e.reason})
                for f, e in report.suppressed]
    results += [_sarif_result(f, {"kind": "inSource",
                                  "justification": p.reason})
                for f, p in report.pragma_suppressed]
    return {
        "$schema": _SARIF_SCHEMA,
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "spatialflink-analysis",
                "informationUri":
                    "https://example.invalid/spatialflink-tpu/analysis",
                "rules": rules_meta,
            }},
            "results": results,
        }],
    }


def main(argv: Optional[List[str]] = None,
         out=sys.stdout) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m spatialflink_tpu.analysis",
        description="invariant linter: prove the engine's contracts at "
                    "the AST level")
    ap.add_argument("--rule", action="append", default=None,
                    metavar="ID", help="run only this rule (repeatable)")
    ap.add_argument("--format", choices=("text", "json", "sarif"),
                    default="text")
    ap.add_argument("--check", action="store_true",
                    help="exit 1 on non-allowlisted findings, stale "
                         "allowlist entries, or stale pragmas (the "
                         "tier-1 gate mode)")
    ap.add_argument("--root", default=REPO_ROOT,
                    help="tree to scan (default: this repo)")
    ap.add_argument("--allowlist", default=ALLOWLIST_PATH,
                    help="allowlist TOML (default: the committed "
                         "analysis/ALLOWLIST.toml); 'none' disables")
    ap.add_argument("--no-cache", action="store_true",
                    help="disable the per-module findings cache")
    ap.add_argument("--list-rules", action="store_true",
                    help="print rule ids + contracts and exit")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rule in all_rules():
            print(f"{rule.id:<22} {rule.contract}", file=out)
        return 0
    allowlist = None if args.allowlist == "none" else args.allowlist
    try:
        report = run_analysis(root=args.root, rule_ids=args.rule,
                              allowlist=allowlist,
                              cache=None if args.no_cache else "auto")
    except AllowlistError as e:
        print(f"analysis: {e}", file=sys.stderr)
        return 2
    if args.format == "json":
        print(json.dumps(report.to_dict(), sort_keys=True), file=out)
    elif args.format == "sarif":
        print(json.dumps(render_sarif(report), sort_keys=True), file=out)
    else:
        _render_text(report, args.check, out)
    if args.check and not report.ok:
        return 1
    return 0
